"""Test configuration: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's ``foo.mpirun=4.input`` trick (SURVEY.md §4): the
reference exercises its MPI paths with oversubscribed local ranks; we
exercise our sharding paths with ``xla_force_host_platform_device_count``
virtual CPU devices. Real-TPU execution is covered by bench.py and the
driver's compile checks, not by this suite.

Must set env vars BEFORE jax is imported anywhere.
"""

import os

# The container pins JAX_PLATFORMS=axon (single real TPU chip behind a
# loopback relay) and a sitecustomize hook that registers that backend in
# every interpreter and would force-initialize it on first jax compute —
# even under JAX_PLATFORMS=cpu. Tests must run on the virtual CPU mesh
# (eager ops over the tunnel are ~1000x slower and hang forever if the
# relay is down), so below we drop the axon backend factory before any
# compute happens.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import pytest  # noqa: E402

try:  # private jax API; harmless to skip if it moves between releases
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
except Exception:
    pass
jax.config.update("jax_platforms", "cpu")

# Allow float64 in tests: production state is f32 (TPU), but convergence
# tests validate the SAME operators at f64 on CPU so truncation error is
# measured above the roundoff floor (SURVEY.md §7.3 hard-part #2).
jax.config.update("jax_enable_x64", True)


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture(scope="session")
def mesh8():
    """A 1-D 8-device mesh for sharding tests."""
    import numpy as np
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:8])
    return Mesh(devs, axis_names=("x",))


@pytest.fixture(scope="session")
def mesh2x4():
    import numpy as np
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    return Mesh(devs, axis_names=("x", "y"))


@pytest.fixture(autouse=True, scope="module")
def _bound_xla_state_per_module():
    """Clear jax's compilation caches after every test module.

    The monolithic full-gate run (650+ tests, one process) accumulates
    hundreds of compiled CPU executables; at ~45% of the round-5 suite
    XLA's CPU compiler segfaulted inside backend_compile_and_load —
    reproducibly, while every file passes in isolation (the split-gate
    receipt). Dropping the executables between modules bounds the
    in-process compiler/runtime state the monolithic run carries; each
    module re-compiles only its own shapes, so the wall-clock cost is
    minor."""
    yield
    jax.clear_caches()


# ---------------------------------------------------------------------------
# Fast/slow test tiers (VERDICT round 2, item 8): the full suite is the
# pre-commit gate (~60 min on the virtual 8-device CPU mesh); the
# developer loop is `pytest -m "not slow"`. The tier is defined HERE
# (names measured >= ~12 s by `--durations`) so the policy lives in one
# place instead of scattered decorators.
# ---------------------------------------------------------------------------

SLOW_FILES = {
    "test_lagrangian_sharded.py",   # ~29 min total: sharded-marker suites
    "test_pallas_interaction.py",   # Pallas interpret mode: ~4 min on CPU
    "test_pallas_packed.py",        # Pallas interpret mode: ~3 min on CPU
}

SLOW_TESTS = {
    # PR 5 replay drills: end-to-end record -> escalate -> replay loops
    # (multiple jitted-run compiles each; the kill-and-replay drill
    # spawns a subprocess victim). Covered in CI by dryrun path 18.
    "test_precision_escalation_end_to_end_drill",
    "test_engine_override_verdict",
    "test_cross_mesh_kill_and_replay",
    "test_window_tracks_advected_membrane",
    "test_window_regrid_3d_smoke",
    "test_oldroyd_b_steady_shear_analytic",
    "test_elastic_disc_relaxes",
    "test_ib_shell3d_sharded_matches_single",
    "test_sharded_multilevel_matches_single_device",
    "test_pallas_spread_overflow_fallback",
    "test_membrane_in_refined_box_tracks_uniform_fine",
    "test_shell_step_fast_matches_scatter",
    "test_wall_bounded_ins_sharded_matches_single",
    "test_ib_membrane_sharded_matches_single",
    "test_two_level_ib_sharded_matches_single",
    "test_vc_poisson_3d",
    "test_straight_rod_zero_strain",
    "test_pallas_spread_matches_scatter",
    "test_falling_drop_volume_and_symmetry",
    "test_fac_3d_smoke",
    "test_total_force_and_torque_balance",
    "test_intrinsic_curvature_equilibrium",
    "test_vortex_matches_uniform_fine",
    "test_profile_trace_writes_trace",
    # PR 10: real jax.profiler capture + attribute round trip (~30 s:
    # one jit compile, a 40-step captured run, and trace parsing)
    "test_real_capture_attributes_driver_chunk",
    # PR 19 gradient drills: end-to-end FD checks roll the coupled
    # solver out twice per direction at f64 (~5-7 s each). The fast
    # tier keeps the cheap spectral/interp FD checks and the census,
    # donation-guard, remat and design-loop pins; these two heavies
    # are covered in CI by dryrun path 23 (--design-smoke).
    "test_eel_objective_grad_matches_fd",
    "test_packed_spread_vjp_matches_fd",
    "test_gib_twisted_rod_relaxes",
    "test_project_vc_divergence_free",
    "test_pallas_total_force_conserved",
    "test_3d_channel_smoke",
    "test_matches_scatter_path",
    "test_f32_convergence_regression",
    "test_adjointness",
    "test_two_level_matches_uniform_fine",
    "test_3d_channel_integrator_smoke",
    "test_imp_step_jits",
    "test_vc_projection_mg_preconditioner_ratio_robust",
    "test_lid_driven_cavity_re100_ghia",
    "test_preconditioner_iterations_bounded",
    "test_drop_buoyancy_relative_motion",
    "test_dirichlet_exact_inverse",
    "test_variable_coefficient_poisson",
    "test_exact_inverse_channel_unsteady",
    "test_implicit_midpoint_3x_matches_reference",
    "test_implicit_backward_euler_14x_matches_reference",
    "test_constant_field_interp_and_moment",
    "test_overflow_fallback_exact",
    "test_periodic_transverse_axis",
    "test_channel_develops_to_poiseuille",
    "test_constant_field_interpolates_exactly",
    "test_grid_independent_convergence",
    "test_hydrostatic_balance_no_spurious_currents",
    "test_three_level_tracks_uniform_fine_and_converges",
    "test_early_time_added_mass_free_fall",
    "test_vortex_3level_matches_uniform_finest",
    "test_membrane_ib_3level",
    "test_single_box_matches_two_level",
    "test_fac_multilevel_preconditioner",
    "test_cib_terminal_velocity_matches_constraint_ib",
    "test_preconditioner_cuts_iterations",
    "test_wave_generated_then_damped",
    "test_porous_obstacle_drag_balances_driving_force",
    "test_multilevel_ins_sharded_matches_single",
    "test_multilevel_regrid_tracks_drifting_structure",
    "test_channel_develops_to_poiseuille_stabilized_ppm",
    "test_two_level_ib_3d_shell",
    "test_two_level_ib_3d_sharded_matches_single",
    # round-3 re-tier (fast tier had grown to 27 min; --durations=50):
    "test_shell_silhouette_packing_efficiency",
    "test_chunk_capacity_overflow_exact",
    "test_free_body_two_bodies_interact",
    "test_two_level_conservation",
    "test_momentum_conservation_beats_nonconservative",
    "test_free_body_matches_direct_resistance_path",
    "test_ppm_reduces_to_centered_on_linear_field",
    "test_stabilized_ppm_free_stream_preservation",
    "test_hot_tile_takes_many_chunks_no_overflow",
    "test_vc_beta_folds_into_coefficient",
    "test_stokes_box_energy_decay",
    "test_free_body_step_advances",
    "test_conservative_3d_smoke",
    "test_multilevel_ib_3d_shell",
    "test_bf16_compute_matches_f32_within_tolerance",
    "test_hydrodynamic_force_measures_body_drag",
    "test_multilevel_ib_sharded_matches_single",
    # round-4 additions (measured >= ~12 s)
    "test_two_level_ib_sharded_window_matches_single",
    "test_two_level_ib_3d_sharded_window_matches_single",
    "test_multilevel_ib_sharded_boxes_matches_single",
    "test_nwt_physical_walls_match_brinkman",
    "test_free_body_trajectory_matches_constraint_ib",
    "test_explicit_composite_unstable_beyond_limit",
    "test_implicit_composite_stable_at_10x",
    "test_implicit_composite_matches_explicit_at_small_dt",
    "test_falling_drop_walled_tank_stable_and_conserves",
    "test_channel_viscous_mode_decay_rate",
    "test_conservative_walled_mass_exact",
    "test_komega_channel_law_of_the_wall",
    "test_vc_ins_sharded_matches_single",
    "test_smagorinsky_walled_channel_decays_bounded",
    "test_falling_drop_3d_walled_smoke",
    "test_hydrostatic_quiescence_3d_walled_tank",
    "test_komega_walled_transport_sane",
    "test_komega_ins_walled_channel_smoke",
    "test_ibfe_on_two_level_hierarchy_relaxes",
    "test_ibfe_two_level_matches_uniform_fine",
    "test_cylinder_wake_drag_re20",
    "test_ib_open_free_structure_advects",
    "test_implicit_regridding_window_tracks_structure",
    "test_two_level_ib_sharded_window_s2_markers_matches_single",
    "test_membrane_capsule_sediments_in_two_phase_tank",
    "test_open_ins_sharded_matches_single",
    "test_ib_open_sharded_matches_single",
    "test_fe_capsule_in_two_phase_fluid",
    "test_ib_open_3d_sphere_smoke",
    # round-5 additions
    "test_shedding_cylinder_adaptive_dt",
    "test_open_outlet_passes_throughflow",
    "test_open_outlet_wave_train_finite_and_bounded",
    "test_les_refined_window_matches_uniform_fine",
    "test_walled_cib_mobility_symmetric_and_confined",
    "test_walled_cib_wall_approach_monotonicity",
    "test_walled_cib_prescribed_kinematics_and_free_step",
    "test_vc_open_outlet_sharded_matches_single",
    "test_les_two_level_sharded_matches_single",
    "test_cib_walled_sharded_matches_single",
    "test_cross_mesh_restart_flagship_1_to_8_and_back",
    "test_filament_example_short",
    "test_oscillating_cylinder_example",
    "test_filament_length_conservation",
    "test_dam_break_example_short",
    "test_eel_example_swims_against_wave",
    "test_ibfe_beam_example_bends_downstream",
    "test_dam_break_restart_continuation",
    # PR 2 (resilience): subprocess SIGKILL drill spawns 4 interpreters
    "test_kill_mid_write_loses_at_most_one_interval",
    # PR 3 (silent failures): real-sleep stall drill — wall-clock
    # timing-sensitive, so it rides the slow tier, not the dev loop
    "test_watchdog_flags_stalled_supervised_run",
    # PR 6 (sharded checkpoints): subprocess kill drills — each spawns
    # multiple interpreters; covered in CI by dryrun path 19
    "test_sharded_kill_one_writer_loses_at_most_one_interval",
    "test_sharded_smoke_drill_end_to_end",
    # PR 6 re-tier (measured >= ~12 s by --durations on the
    # single-core tier-1 box; the fast tier had crept to within ~30 s
    # of the 870 s gate budget, so borderline runs timed out at ~93%
    # — the "environment-specific" tier-1 flake)
    # PR 7 (fleet): the subprocess drill spawns an interpreter for the
    # B=8 shell fleet (covered in CI by dryrun path 20); the capsule
    # test compiles two shell fleet chunks plus an unbatched replay
    "test_fleet_smoke_drill_end_to_end",
    "test_sliced_capsule_replays_bitwise",
    "test_open_outlet_hydrostatic_quiescence",
    "test_shell_engine_knob_and_step",
    "test_walled_momentum_wall_shear_sign",
    "test_hybrid_in_flagship_model",
    "test_failed_engine_degrades_and_matches_fallback",
    "test_hybrid_bf16_registry_name",
    # PR 17 (traffic): multi-minute sustained soaks (real-time open
    # loop; the bounded variants run in tier-1 via `slo.py check
    # --soak` and dryrun path 21)
    "test_soak_long_sustained_open_loop",
    "test_soak_long_chaos_smoke",
    # PR 18 (robustness): elastic-pool drills against a LIVE router
    # (real compiles, real-time open loop; the stub-router fast tier
    # covers the same policy logic in milliseconds, and CI exercises
    # the full drill via `slo.py check --elastic` and dryrun path 22)
    "test_grow_never_blocks_serving",
    "test_restart_drill_zero_fresh_compiles",
    "test_run_elastic_smoke_end_to_end",
    # PR 20 (assimilation): the collapse->rollback->escalation loop
    # compiles two fleet chunks + analysis executables; the subprocess
    # chaos drill spawns an interpreter (covered in CI by dryrun path
    # 24 and `slo.py check --assim`)
    "test_spread_collapse_rolls_back_and_escalates_inflation",
    "test_assim_smoke_drill_end_to_end",
}


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: heavy integrator/sharding tests; excluded from "
        "the developer fast tier (-m 'not slow')")


def pytest_collection_modifyitems(config, items):
    for item in items:
        base = item.name.split("[")[0]
        if item.fspath.basename in SLOW_FILES or base in SLOW_TESTS:
            item.add_marker(pytest.mark.slow)
