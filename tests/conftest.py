"""Test configuration: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's ``foo.mpirun=4.input`` trick (SURVEY.md §4): the
reference exercises its MPI paths with oversubscribed local ranks; we
exercise our sharding paths with ``xla_force_host_platform_device_count``
virtual CPU devices. Real-TPU execution is covered by bench.py and the
driver's compile checks, not by this suite.

Must set env vars BEFORE jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture(scope="session")
def mesh8():
    """A 1-D 8-device mesh for sharding tests."""
    import numpy as np
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:8])
    return Mesh(devs, axis_names=("x",))


@pytest.fixture(scope="session")
def mesh2x4():
    import numpy as np
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    return Mesh(devs, axis_names=("x", "y"))
