"""CIB mobility-solver menu (P15, SURVEY.md §2.2): Direct / Krylov /
KrylovFreeBody solvers.

Oracles: the dense approximate tensors (RPY 3D, regularized blob 2D) are
SPD for overlapping and separated configurations; Direct solve is an
exact inverse of its own matrix; the dense preconditioner strictly cuts
exact-mobility CG iterations; the matrix-free free-body solve agrees
with the dense resistance-column path; and the Krylov free-body terminal
velocity of a heavy disc agrees with the inertial ConstraintIB
sedimentation dynamics in the overlapping (quasi-steady, back-flow
frame) regime.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators import cib
from ibamr_tpu.solvers import mobility


def _grid2d(n=64):
    return StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))


def _disc(n_markers=40, radius=0.12, center=(0.5, 0.5)):
    X = cib.make_disc(center, radius, n_markers)
    bodies = cib.RigidBodies(
        body_id=jnp.zeros(n_markers, dtype=jnp.int32), n_bodies=1)
    return X, bodies


# -- dense approximate tensors ---------------------------------------------

def test_blob_mobility_spd_2d():
    rng = np.random.default_rng(0)
    # random cloud including overlapping pairs
    X = jnp.asarray(rng.uniform(0.3, 0.7, size=(25, 2)))
    M = mobility.blob_mobility_matrix(X, radius=0.02, mu=0.7)
    assert np.allclose(np.asarray(M), np.asarray(M).T, atol=1e-12)
    w = np.linalg.eigvalsh(np.asarray(M))
    assert w.min() > 0.0, f"blob mobility not PD: min eig {w.min()}"


def test_rpy_mobility_spd_3d_overlapping():
    rng = np.random.default_rng(1)
    X = jnp.asarray(rng.uniform(0.4, 0.6, size=(18, 3)))  # dense, overlaps
    M = mobility.rpy_mobility_matrix(X, radius=0.05, mu=1.3)
    assert np.allclose(np.asarray(M), np.asarray(M).T, atol=1e-12)
    w = np.linalg.eigvalsh(np.asarray(M))
    assert w.min() > 0.0, f"RPY not PD with overlaps: min eig {w.min()}"


def test_rpy_isolated_particle_stokes_drag():
    """A lone RPY particle has exactly the Stokes mobility 1/(6 pi mu a)."""
    X = jnp.asarray([[0.5, 0.5, 0.5]])
    a, mu = 0.03, 2.0
    M = mobility.rpy_mobility_matrix(X, radius=a, mu=mu)
    expect = 1.0 / (6.0 * np.pi * mu * a)
    assert np.allclose(np.asarray(M), expect * np.eye(3), rtol=1e-12)


def test_direct_solver_exact_inverse():
    X, _ = _disc()
    ds = mobility.DirectMobilitySolver(X, radius=0.01, mu=1.0)
    rng = np.random.default_rng(2)
    rhs = jnp.asarray(rng.standard_normal(X.shape))
    lam = ds.solve(rhs)
    assert np.allclose(np.asarray(ds.apply(lam)), np.asarray(rhs),
                       atol=1e-8)


# -- Krylov mobility solver -------------------------------------------------

def test_preconditioner_cuts_iterations():
    """The dense blob preconditioner must strictly reduce CG iterations
    on the exact grid mobility (the reference's reason for nesting
    DirectMobilitySolver inside KrylovMobilitySolver)."""
    g = _grid2d(64)
    X, bodies = _disc(n_markers=60)
    m = cib.CIBMethod(g, bodies, mu=1.0)
    rng = np.random.default_rng(3)
    apply_m = lambda lam: m.mobility_apply(X, lam)
    # in-range RHS (a marker velocity the kernel-regularized mobility can
    # actually produce): random forces pushed through M
    rhs = apply_m(jnp.asarray(rng.standard_normal(X.shape)))
    plain = mobility.KrylovMobilitySolver(apply_m, precond=None,
                                          tol=1e-5,
                                          maxiter=2000).solve(rhs)
    # hydrodynamic radius ~ marker spacing
    ds = mobility.DirectMobilitySolver(X, radius=float(g.dx[0]), mu=1.0)
    pcg = mobility.KrylovMobilitySolver(apply_m, precond=ds,
                                        tol=1e-5,
                                        maxiter=2000).solve(rhs)
    assert bool(plain.converged) and bool(pcg.converged)
    assert int(pcg.iters) < int(plain.iters), \
        f"precond {int(pcg.iters)} !< plain {int(plain.iters)}"
    # both realize the requested marker velocities (lambda itself is
    # non-unique in the kernel-regularized near-nullspace)
    rn = float(jnp.linalg.norm(rhs))
    for sol in (plain, pcg):
        resid = float(jnp.linalg.norm(apply_m(sol.x) - rhs))
        assert resid < 2e-5 * max(rn, 1.0), resid


# -- Krylov free-body solver ------------------------------------------------

def test_free_body_matches_direct_resistance_path():
    """KrylovFreeBodyMobilitySolver and the dense resistance-column
    path (CIBMethod.solve_mobility) are two routes to the same U."""
    g = _grid2d(64)
    X, bodies = _disc(n_markers=48)
    m = cib.CIBMethod(g, bodies, mu=1.0, cg_tol=1e-10)
    FT = jnp.asarray([[0.3, -1.0, 0.05]])  # force + torque
    U_direct, _, info = m.solve_mobility(X, FT)
    assert bool(info.converged)

    solver = m.free_body_solver(X, radius=float(g.dx[0]))
    res = solver.solve(FT)
    assert bool(res.converged)
    assert np.allclose(np.asarray(res.U), np.asarray(U_direct),
                       rtol=1e-5, atol=1e-8), (res.U, U_direct)


def test_free_body_two_bodies_interact():
    """Two side-by-side discs driven by equal forces in a periodic box:
    mirror symmetry forces equal settling speeds and opposite spins
    (each disc rotates in the other's shear field), and settling is
    HINDERED relative to an isolated disc — the doubled net force
    doubles the periodic back-flow (classic hindered settling of a
    periodic suspension; the zero-mean frame the mobility solve uses)."""
    g = _grid2d(64)
    n_mk = 32
    X1 = cib.make_disc((0.35, 0.5), 0.08, n_mk)
    X2 = cib.make_disc((0.65, 0.5), 0.08, n_mk)
    X = jnp.concatenate([X1, X2])
    bodies = cib.RigidBodies(
        body_id=jnp.concatenate([jnp.zeros(n_mk, dtype=jnp.int32),
                                 jnp.ones(n_mk, dtype=jnp.int32)]),
        n_bodies=2)
    m = cib.CIBMethod(g, bodies, mu=1.0)
    FT = jnp.asarray([[0.0, -1.0, 0.0], [0.0, -1.0, 0.0]])
    res = m.free_body_solver(X, radius=float(g.dx[0])).solve(FT)
    assert bool(res.converged)

    Xs, bs = _disc(n_markers=n_mk, radius=0.08)
    ms = cib.CIBMethod(g, bs, mu=1.0)
    res_single = ms.free_body_solver(Xs, radius=float(g.dx[0])).solve(
        jnp.asarray([[0.0, -1.0, 0.0]]))
    v1, v2 = float(res.U[0, 1]), float(res.U[1, 1])
    w1, w2 = float(res.U[0, 2]), float(res.U[1, 2])
    v_single = float(res_single.U[0, 1])
    assert np.isclose(v1, v2, rtol=1e-6), (v1, v2)       # mirror symmetry
    assert np.isclose(w1, -w2, rtol=1e-6), (w1, w2)      # counter-spin
    assert abs(w1) > 1e-3                                 # real rotation
    assert v_single < v1 < 0.0, (v1, v_single)            # hindered


def test_free_body_step_advances():
    g = _grid2d(32)
    X, bodies = _disc(n_markers=24, radius=0.1)
    m = cib.CIBMethod(g, bodies, mu=1.0)
    FT = jnp.asarray([[0.0, -1.0, 0.0]])
    Xn, U, res = m.step_krylov(X, FT, dt=1e-2, radius=float(g.dx[0]))
    assert bool(res.converged)
    assert float(jnp.mean(Xn[:, 1] - X[:, 1])) < 0.0  # moved down


# -- overlap with ConstraintIB dynamics ------------------------------------

def _terminal_ratio(n):
    """ConstraintIB long-time sedimentation velocity (back-flow frame)
    over the quasi-static CIB free-body velocity for the same disc."""
    from ibamr_tpu.integrators.constraint_ib import (ConstraintIBMethod,
                                                     advance_constraint_ib,
                                                     fill_disc)
    from ibamr_tpu.integrators.ins import INSStaggeredIntegrator

    mu, rho, r_disc, s = 0.5, 1.0, 0.08, 4.0
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))

    # inertial run to the viscous steady state (mu=0.5: box viscous
    # time L^2/nu = 2 s; t = 1.2 s with the wake scale ~0.1 s)
    ins = INSStaggeredIntegrator(g, mu=mu, rho=rho)
    X0 = fill_disc((0.5, 0.6), r_disc, 1.0 / n / 2, dtype=ins.dtype)
    bodies = cib.RigidBodies(
        body_id=jnp.zeros(X0.shape[0], dtype=jnp.int32), n_bodies=1)
    method = ConstraintIBMethod(ins, bodies, density_ratio=[s],
                                gravity=[0.0, -1.0])
    st = method.initialize(X0)
    st = advance_constraint_ib(method, st, 1e-3, 1000)
    v_a = float(st.U_body[0, 1]) - float(jnp.mean(st.ins.u[1]))
    st = advance_constraint_ib(method, st, 1e-3, 200)
    v_b = float(st.U_body[0, 1]) - float(jnp.mean(st.ins.u[1]))
    assert v_b < 0.0
    # settled: drift over the last 0.2 s is small
    assert abs(v_b - v_a) < 0.1 * abs(v_b), (v_a, v_b)

    # quasi-static CIB: rigid boundary ring at the settled centroid,
    # excess weight F = (s-1) rho A g
    cent = np.asarray(st.X).mean(axis=0)
    n_ring = max(12, int(2 * np.pi * r_disc * n))
    Xr = cib.make_disc(tuple(cent), r_disc, n_ring)
    ring = cib.RigidBodies(
        body_id=jnp.zeros(n_ring, dtype=jnp.int32), n_bodies=1)
    m = cib.CIBMethod(g, ring, mu=mu, cg_tol=1e-8)
    F_excess = (s - 1.0) * rho * np.pi * r_disc ** 2
    res = m.free_body_solver(Xr, radius=float(g.dx[0])).solve(
        jnp.asarray([[0.0, -F_excess, 0.0]]))
    assert bool(res.converged)
    v_cib = float(res.U[0, 1])
    assert v_cib < 0.0
    return v_b / v_cib


def test_cib_terminal_velocity_matches_constraint_ib():
    """A heavy disc's quasi-static CIB velocity under its excess weight
    agrees with the long-time ConstraintIB sedimentation velocity
    (measured in the back-flow frame: body velocity relative to the mean
    fluid velocity — the zero-mean convention of the periodic Stokes
    mobility solve), and the residual gap SHRINKS under refinement: the
    momentum-projection constraint under-resolves drag at coarse dx
    (calibrated: ratio 1.64 at 32^2 -> 1.22 at 64^2). The two
    formulations share only the spread/interp kernels — this pins the
    mobility menu against the independently-tested inertial integrator
    (VERDICT round 2, item 6)."""
    r32 = _terminal_ratio(32)
    r64 = _terminal_ratio(64)
    assert 0.9 < r64 < 1.45, (r32, r64)
    assert abs(r64 - 1.0) < 0.75 * abs(r32 - 1.0), (r32, r64)


def test_rpy_coincident_markers_finite():
    """Two DISTINCT markers at the same position (touching body
    discretizations) must take the near-field limit c0*I, not NaN
    (round-3 review finding: the far branch divided by r2=0)."""
    X = jnp.asarray([[0.5, 0.5, 0.5], [0.5, 0.5, 0.5],
                     [0.7, 0.5, 0.5]])
    a, mu = 0.05, 1.0
    M = mobility.rpy_mobility_matrix(X, radius=a, mu=mu)
    assert np.isfinite(np.asarray(M)).all()
    c0 = 1.0 / (6.0 * np.pi * mu * a)
    assert np.allclose(np.asarray(M[0:3, 3:6]), c0 * np.eye(3),
                       rtol=1e-12)
    # coincident blobs are indistinguishable -> exactly PSD (one zero
    # eigenvalue), never negative; the Direct solver's jitter covers it
    w = np.linalg.eigvalsh(np.asarray(M))
    assert w.min() > -1e-12 * w.max()
    ds = mobility.DirectMobilitySolver(X, radius=a, mu=mu, jitter=1e-8)
    assert np.isfinite(np.asarray(ds.solve(jnp.ones_like(X)))).all()


def test_free_body_trajectory_matches_constraint_ib():
    """TIME-DEPENDENT CIB (VERDICT round 3, missing #5): a heavy disc's
    centroid TRAJECTORY under the mobility formulation — positions
    integrated with per-step KrylovFreeBodyMobilitySolver velocities —
    against the ConstraintIB sedimentation path at matched parameters.
    Quasi-static Stokes flow is memoryless, so the CIB path is straight
    at the terminal velocity; the inertial ConstraintIB path approaches
    the same line after its short wake transient. Agreement is pinned
    via the settled-velocity window with the same refinement-limited
    calibration band as the terminal-velocity cross-check; exact marker
    rigidity over the whole trajectory is pinned alongside."""
    from ibamr_tpu.integrators.constraint_ib import (ConstraintIBMethod,
                                                     advance_constraint_ib,
                                                     fill_disc)
    from ibamr_tpu.integrators.ins import INSStaggeredIntegrator

    mu, rho, r_disc, s = 0.5, 1.0, 0.08, 4.0
    n = 32
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))

    # -- inertial reference trajectory (ConstraintIB) -------------------
    ins = INSStaggeredIntegrator(g, mu=mu, rho=rho)
    X0 = fill_disc((0.5, 0.6), r_disc, 1.0 / n / 2, dtype=ins.dtype)
    bodies0 = cib.RigidBodies(
        body_id=jnp.zeros(X0.shape[0], dtype=jnp.int32), n_bodies=1)
    method = ConstraintIBMethod(ins, bodies0, density_ratio=[s],
                                gravity=[0.0, -1.0])
    st = method.initialize(X0)
    st = advance_constraint_ib(method, st, 1e-3, 1000)  # settle
    y_a = float(np.asarray(st.X).mean(axis=0)[1])
    ubg_a = float(jnp.mean(st.ins.u[1]))
    st = advance_constraint_ib(method, st, 1e-3, 200)
    y_b = float(np.asarray(st.X).mean(axis=0)[1])
    ubg_b = float(jnp.mean(st.ins.u[1]))
    T = 0.2
    # displacement in the back-flow frame (periodic mobility convention)
    disp_con = (y_b - y_a) - 0.5 * (ubg_a + ubg_b) * T
    assert disp_con < 0.0

    # -- time-dependent CIB trajectory over the same window -------------
    n_ring = max(12, int(2 * np.pi * r_disc * n))
    Xr = cib.make_disc((0.5, 0.55), r_disc, n_ring)
    ring = cib.RigidBodies(
        body_id=jnp.zeros(n_ring, dtype=jnp.int32), n_bodies=1)
    m = cib.CIBMethod(g, ring, mu=mu, cg_tol=1e-8)
    F_excess = (s - 1.0) * rho * np.pi * r_disc ** 2
    FT = jnp.asarray([[0.0, -F_excess, 0.0]])
    traj = cib.advance_free_bodies(
        m, Xr, lambda t, c: FT, dt=1e-2, num_steps=20,
        radius=float(g.dx[0]))
    cents = np.asarray(traj.centroids)
    disp_cib = cents[-1, 0, 1] - 0.55
    assert disp_cib < 0.0

    # straight vertical fall: x frozen, per-step velocity near-constant
    # (quasi-static flow is memoryless; the residual ~0.1% variation is
    # the marker-grid discretization shifting as the body crosses cells)
    assert float(np.max(np.abs(cents[:, 0, 0] - 0.5))) < 1e-10
    U = np.asarray(traj.U)[:, 0, 1]
    assert abs(U[-1] - U[0]) < 0.01 * abs(U[0])

    # marker rigidity exact over the trajectory: the ring's radius
    # is preserved to roundoff
    rads = np.linalg.norm(np.asarray(traj.X) - cents[-1, 0], axis=1)
    assert float(np.max(np.abs(rads - r_disc))) < 1e-12

    # trajectory agreement within the 32^2 calibration band of the
    # terminal-velocity cross-check (constraint drag under-resolved at
    # coarse dx -> ratio ~1.6; see test_cib_terminal_velocity_...)
    ratio = disp_con / disp_cib
    assert 0.8 < ratio < 2.0, (disp_con, disp_cib, ratio)


# ---------------------------------------------------------------------------
# Walled-domain CIB (round 5, VERDICT item 3c: composition closure)
# ---------------------------------------------------------------------------

def _one_disc(center, n_markers=24, radius=0.12):
    X = cib.make_disc(center, radius, n_markers, dtype=jnp.float64)
    bodies = cib.RigidBodies(
        body_id=jnp.zeros(n_markers, dtype=jnp.int32), n_bodies=1)
    return X, bodies


def test_walled_cib_mobility_symmetric_and_confined():
    """CIB on a no-slip enclosure (the CIBStaggeredStokesSolver-over-
    wall-BCs configuration [U]): the walled mobility stays symmetric
    (the saddle solve is self-adjoint on the div-free subspace, so the
    constraint CG remains valid), and confinement INCREASES the
    resistance relative to the periodic frame at the same box size."""
    n = 48
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    X, bodies = _one_disc((0.5, 0.5))
    per = cib.CIBMethod(g, bodies, mu=1.0, cg_tol=1e-8, cg_maxiter=200)
    wal = cib.CIBMethod(g, bodies, mu=1.0, cg_tol=1e-8, cg_maxiter=200,
                        domain="walled")

    rng = np.random.default_rng(0)
    l1 = jnp.asarray(rng.standard_normal(X.shape))
    l2 = jnp.asarray(rng.standard_normal(X.shape))
    a = float(jnp.sum(l2 * wal.mobility_apply(X, l1)))
    b = float(jnp.sum(l1 * wal.mobility_apply(X, l2)))
    assert abs(a - b) < 1e-7 * abs(a)

    Rp, _, ip = per.resistance_matrix(X)
    Rw, _, iw = wal.resistance_matrix(X)
    assert bool(ip.converged) and bool(iw.converged)
    # SPD resistance
    ew = np.linalg.eigvalsh(np.asarray(Rw))
    assert ew.min() > 0.0
    # confinement: no-slip enclosure drags harder than the periodic
    # zero-mean frame at the same box size (measured ~1.5x here)
    assert float(Rw[0, 0]) > 1.2 * float(Rp[0, 0])
    assert float(Rw[1, 1]) > 1.2 * float(Rp[1, 1])


def test_walled_cib_wall_approach_monotonicity():
    """Lubrication trend: translating a body toward a wall raises its
    resistance monotonically — impossible to observe in the periodic
    frame (no wall), so it pins that the walls are physically there."""
    n = 48
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    rxx = []
    for cy in (0.5, 0.36, 0.27):
        X, bodies = _one_disc((0.5, cy))
        wal = cib.CIBMethod(g, bodies, mu=1.0, cg_tol=1e-8,
                            cg_maxiter=300, domain="walled")
        Rw, _, info = wal.resistance_matrix(X)
        assert bool(info.converged)
        rxx.append(float(Rw[0, 0]))     # drag parallel to the wall
    assert rxx[0] < rxx[1] < rxx[2], rxx


def test_walled_cib_prescribed_kinematics_and_free_step():
    """The constraint (prescribed-motion) and free-body paths run on
    the walled domain: prescribed translation yields a net force
    opposing the motion; a forced free body moves in the force
    direction with finite state."""
    n = 48
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    X, bodies = _one_disc((0.5, 0.5))
    wal = cib.CIBMethod(g, bodies, mu=1.0, cg_tol=1e-8, cg_maxiter=300,
                        domain="walled")
    U = jnp.asarray([[1.0, 0.0, 0.0]])          # translate +x
    lam, FT, info = wal.solve_constraint(X, U)
    assert bool(info.converged)
    assert float(FT[0, 0]) > 0.0                # force needed along +x
    assert abs(float(FT[0, 1])) < 0.05 * float(FT[0, 0])  # symmetry

    FT_ext = jnp.asarray([[0.0, -1.0, 0.0]])    # push down
    X2, U2, info2 = wal.step(X, FT_ext, 1e-3)
    assert bool(info2.converged)
    assert float(U2[0, 1]) < 0.0                # moves down
    assert bool(jnp.all(jnp.isfinite(X2)))
