"""HierarchyDriver run-loop skeleton + divergence guard (T13, §5.2 —
VERDICT round 1 item 8).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
from ibamr_tpu.utils.hierarchy_driver import (HierarchyDriver, RunConfig,
                                              SimulationDiverged)


def _ins(n=16, mu=0.01, **kw):
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    return INSStaggeredIntegrator(g, rho=1.0, mu=mu, dtype=jnp.float64,
                                  **kw)


def _tg_state(integ):
    import math
    g = integ.grid
    xf, yc = g.face_centers(0, jnp.float64)
    xc, yf = g.face_centers(1, jnp.float64)
    u = jnp.sin(2 * math.pi * xf) * jnp.cos(2 * math.pi * yc) + 0 * yc
    v = -jnp.cos(2 * math.pi * xc) * jnp.sin(2 * math.pi * yf) + 0 * xc
    return integ.initialize(u0_arrays=(u, v))


def test_run_matches_manual_stepping():
    integ = _ins()
    st0 = _tg_state(integ)
    cfg = RunConfig(dt=1e-3, num_steps=23, health_interval=7)
    drv = HierarchyDriver(integ, cfg)
    out = drv.run(st0)
    ref = st0
    for _ in range(23):
        ref = integ.step(ref, 1e-3)
    np.testing.assert_allclose(np.asarray(out.u[0]),
                               np.asarray(ref.u[0]), atol=1e-13)
    assert int(out.k) == 23


def test_callback_cadences_land_exactly():
    integ = _ins()
    st = _tg_state(integ)
    seen = {"viz": [], "ckpt": [], "metrics": []}
    cfg = RunConfig(dt=1e-3, num_steps=30, viz_dump_interval=6,
                    restart_interval=10, health_interval=7)
    drv = HierarchyDriver(
        integ, cfg,
        viz_fn=lambda s, k: seen["viz"].append(k),
        checkpoint_fn=lambda s, k: seen["ckpt"].append(k),
        metrics_fn=lambda s, k: seen["metrics"].append(k) or {})
    drv.run(st)
    assert seen["viz"] == [6, 12, 18, 24, 30]
    assert seen["ckpt"] == [10, 20, 30]
    assert seen["metrics"][-1] == 30


def test_divergence_halts_with_diagnostic():
    """A deliberately unstable config (convective CFL >> 1) must raise
    SimulationDiverged naming the bad leaves, and no checkpoint of the
    broken state may be written."""
    integ = _ins(n=32, mu=1e-4)
    st = _tg_state(integ)
    ckpts = []
    cfg = RunConfig(dt=0.5, num_steps=200, restart_interval=100,
                    health_interval=10)
    drv = HierarchyDriver(integ, cfg,
                          checkpoint_fn=lambda s, k: ckpts.append(k))
    with pytest.raises(SimulationDiverged) as ei:
        drv.run(st)
    assert ei.value.bad_leaves            # names the offending leaves
    assert any(".u" in n or "u[" in n or "u" in n
               for n in ei.value.bad_leaves)
    assert ckpts == []                    # nothing poisoned the chain


def test_cfl_dt_recompute_no_retrace():
    """dt is traced: changing it between chunks must not retrigger
    compilation (counted via the driver's trace counter)."""
    integ = _ins()
    st = _tg_state(integ)
    cfg = RunConfig(dt=2e-3, num_steps=40, health_interval=10, cfl=0.3)
    drv = HierarchyDriver(integ, cfg)
    out = drv.run(st)
    assert bool(jnp.all(jnp.isfinite(out.u[0])))
    assert len(drv._chunks) == 1                  # one chunk length
    # dt traced: no retrace. Counted by the driver's trace counter, not
    # jit._cache_size() — the process-global pjit LRU can evict a live
    # entry in a long test session (observed in the round-5 full gate:
    # _cache_size() == 0 after ~280 in-process tests) and the count
    # must survive that.
    assert drv.trace_counts[10] == 1
