"""Dynamic AMR tests (stage 11): tagging, single-box fitting, traced-
origin regrid conservation, overlap preservation, and the moving-window
integrator tracking an advected pulse (regrid-invariance acceptance,
SURVEY.md §7.2 stage 11).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ibamr_tpu.amr_dynamic import (AMRState, DynamicTwoLevelAdvDiff,
                                   copy_overlap, fit_box_origin,
                                   prolong_cc_conservative, regrid,
                                   restrict_into_coarse, tag_gradient,
                                   tag_markers, tag_value)
from ibamr_tpu.grid import StaggeredGrid

F64 = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


def gauss2d(x0, y0, w):
    def fn(coords):
        x, y = coords
        return jnp.exp(-((x - x0) ** 2 + (y - y0) ** 2) / w ** 2)
    return fn


# -- tagging + fitting -------------------------------------------------------

def test_tag_value_and_fit():
    grid = StaggeredGrid(n=(32, 32), x_lo=(0, 0), x_up=(1, 1))
    Q = jnp.zeros((32, 32)).at[10:14, 20:22].set(1.0)
    tags = tag_value(Q, 0.5)
    lo = np.asarray(fit_box_origin(tags, (8, 8), clearance=2))
    # window [lo, lo+8) must cover cells [10,14) x [20,22)
    assert lo[0] <= 10 and lo[0] + 8 >= 14
    assert lo[1] <= 20 and lo[1] + 8 >= 22


def test_fit_clips_to_clearance():
    tags = jnp.zeros((32, 32), dtype=bool).at[0:3, 29:32].set(True)
    lo = np.asarray(fit_box_origin(tags, (8, 8), clearance=2))
    assert lo[0] == 2 and lo[1] == 32 - 8 - 2


def test_fit_no_tags_centers():
    tags = jnp.zeros((32, 32), dtype=bool)
    lo = np.asarray(fit_box_origin(tags, (8, 8), clearance=2))
    assert tuple(lo) == (12, 12)


def test_tag_markers_buffer():
    grid = StaggeredGrid(n=(16, 16), x_lo=(0, 0), x_up=(1, 1))
    X = jnp.array([[0.53, 0.53]])  # cell (8, 8)
    tags = np.asarray(tag_markers(X, grid, buffer=1))
    assert tags[8, 8] and tags[7, 8] and tags[8, 9] and tags[9, 8]
    assert not tags[8, 11]


# -- transfer operators ------------------------------------------------------

def test_prolong_conservative_block_means():
    rng = np.random.RandomState(0)
    Qc = jnp.asarray(rng.randn(16, 16), dtype=F64)
    lo = jnp.array([3, 5], dtype=jnp.int32)
    Qf = prolong_cc_conservative(Qc, lo, (6, 4))
    # each 2x2 fine block averages exactly to its parent coarse value
    blk = np.asarray(Qf).reshape(6, 2, 4, 2).mean(axis=(1, 3))
    assert np.allclose(blk, np.asarray(Qc)[3:9, 5:9], atol=1e-6)


def test_prolong_conservative_linear_exact():
    # linear fields are reproduced exactly by central-slope reconstruction
    x = np.arange(16)[:, None] + 0.5
    y = np.arange(16)[None, :] + 0.5
    Qc = jnp.asarray(2.0 * x + 3.0 * y, dtype=F64)
    lo = jnp.array([4, 4], dtype=jnp.int32)
    Qf = np.asarray(prolong_cc_conservative(Qc, lo, (4, 4)))
    xf = 4 + (np.arange(8)[:, None] + 0.5) / 2
    yf = 4 + (np.arange(8)[None, :] + 0.5) / 2
    assert np.allclose(Qf, 2.0 * xf + 3.0 * yf, atol=1e-5)


def test_regrid_conserves_total():
    grid = StaggeredGrid(n=(32, 32), x_lo=(0, 0), x_up=(1, 1))
    sim = DynamicTwoLevelAdvDiff(grid, (8, 8), dtype=F64)
    state = sim.initialize(gauss2d(0.4, 0.4, 0.1))
    T0 = float(sim.total(state))
    lo_new = jnp.array([14, 16], dtype=jnp.int32)
    Qc2, Qf2 = regrid(state.Qc, state.Qf, state.lo, lo_new)
    s2 = AMRState(Qc=Qc2, Qf=Qf2, lo=lo_new)
    T1 = float(sim.total(s2))
    assert abs(T1 - T0) <= 1e-10 * max(1.0, abs(T0)) + 1e-12


def test_copy_overlap_preserves_fine_data():
    rng = np.random.RandomState(1)
    Qf_old = jnp.asarray(rng.randn(8, 8), dtype=F64)
    lo_old = jnp.array([4, 4], dtype=jnp.int32)
    lo_new = jnp.array([5, 6], dtype=jnp.int32)   # shift (1,2) coarse cells
    Qf_new = jnp.zeros((8, 8), dtype=F64)
    out = np.asarray(copy_overlap(Qf_new, Qf_old, lo_new, lo_old))
    # overlap in new-window fine coords: rows 0..5, cols 0..3 come from
    # old rows 2.., cols 4..
    assert np.allclose(out[0:6, 0:4], np.asarray(Qf_old)[2:8, 4:8])
    assert np.allclose(out[6:, :], 0.0) and np.allclose(out[:, 4:], 0.0)


def test_restrict_into_coarse_roundtrip():
    Qc = jnp.zeros((16, 16), dtype=F64)
    Qf = jnp.ones((8, 8), dtype=F64) * 3.0
    lo = jnp.array([5, 7], dtype=jnp.int32)
    out = np.asarray(restrict_into_coarse(Qc, Qf, lo))
    assert np.allclose(out[5:9, 7:11], 3.0)
    assert out.sum() == pytest.approx(4 * 4 * 3.0)


# -- moving-window integrator ------------------------------------------------

def test_jitted_advance_mass_conservation_and_tracking():
    grid = StaggeredGrid(n=(48, 48), x_lo=(0, 0), x_up=(1, 1))

    def u_fn(coords, d):
        return jnp.full_like(coords[0], 0.7 if d == 0 else 0.0)

    sim = DynamicTwoLevelAdvDiff(grid, (12, 12), kappa=1e-4,
                                 scheme="upwind", u_fn=u_fn,
                                 tag_threshold=0.03, dtype=F64)
    state = sim.initialize(gauss2d(0.3, 0.5, 0.07))
    lo0 = np.asarray(state.lo).copy()
    T0 = float(sim.total(state))

    dt = 0.25 * grid.dx[0] / 0.7 / 2   # fine CFL-safe
    adv = jax.jit(lambda s: sim.advance(s, dt, 64, regrid_interval=4))
    state = jax.block_until_ready(adv(state))
    T1 = float(sim.total(state))
    # flux-form + reflux + conservative regrid => conservation
    assert abs(T1 - T0) < 1e-8 * max(1.0, abs(T0)) + 1e-10
    # the window moved with the pulse (advected right by 0.7*t)
    lo1 = np.asarray(state.lo)
    assert lo1[0] > lo0[0]
    # pulse peak near expected position on the composite solution
    t_end = 64 * dt
    x_peak = 0.3 + 0.7 * t_end
    Qc = np.asarray(restrict_into_coarse(state.Qc, state.Qf, state.lo))
    i_pk = np.unravel_index(np.argmax(Qc), Qc.shape)
    x_pk = (i_pk[0] + 0.5) * grid.dx[0]
    assert abs(x_pk - x_peak) < 0.08
    assert abs((i_pk[1] + 0.5) * grid.dx[1] - 0.5) < 0.08


def test_regrid_invariance_of_smooth_solution():
    # advancing with frequent regrids vs a static window that already
    # covers the pulse path must agree closely where both are fine
    grid = StaggeredGrid(n=(48, 48), x_lo=(0, 0), x_up=(1, 1))
    sim = DynamicTwoLevelAdvDiff(grid, (16, 16), kappa=2e-3,
                                 tag_threshold=0.02, dtype=F64)
    ic = gauss2d(0.5, 0.5, 0.08)
    s_dyn = sim.initialize(ic)
    s_static = sim.initialize(ic)
    dt = 2e-4
    adv_regrid = jax.jit(lambda s: sim.advance(s, dt, 40,
                                               regrid_interval=5))
    adv_static = jax.jit(lambda s: sim.advance(s, dt, 40,
                                               regrid_interval=10 ** 6))
    out_d = jax.block_until_ready(adv_regrid(s_dyn))
    out_s = jax.block_until_ready(adv_static(s_static))
    # same composite solution on the coarse level
    Qd = np.asarray(restrict_into_coarse(out_d.Qc, out_d.Qf, out_d.lo))
    Qs = np.asarray(restrict_into_coarse(out_s.Qc, out_s.Qf, out_s.lo))
    assert np.max(np.abs(Qd - Qs)) < 5e-4 * np.max(np.abs(Qs))
