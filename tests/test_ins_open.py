"""Open-boundary NS integrator (P2/P3 completion): channel flow with
inflow + traction outflow, full convection active through the transient.

Physics oracles: starting from REST, the channel must develop to the
discrete Poiseuille equilibrium (convection is nonzero during the
transient and vanishes at steady state — so the test exercises the
advection path AND the coupled solve), conserving station flux exactly
once developed, with div u at solver tolerance every step."""

import jax
import jax.numpy as jnp
import numpy as np

from ibamr_tpu.integrators.ins_open import INSOpenIntegrator, advance
from ibamr_tpu.solvers.stokes import channel_bc


def test_channel_develops_to_poiseuille():
    nx, ny = 32, 16
    L, H, U, mu = 2.0, 1.0, 1.0, 0.2
    dx, dy = L / nx, H / ny
    dt = 0.02
    y = (np.arange(ny) + 0.5) * dy
    profile = 4.0 * U * y * (H - y) / H ** 2
    bdry = {(0, 0, 0): jnp.asarray(profile)[None, :],
            (1, 0, 0): 0.0}
    integ = INSOpenIntegrator((nx, ny), (dx, dy), channel_bc(2),
                              mu=mu, dt=dt, bdry=bdry, tol=1e-10)
    st = integ.initialize()
    # develop: ~2 flow-through + viscous times
    st = advance(integ, st, 160)
    un = np.asarray(st.u[0])

    # divergence-free to solver tolerance
    assert float(integ.max_divergence(st)) < 1e-7

    # developed: downstream profile matches the parabola to O(h^2)
    err = np.max(np.abs(un[3 * nx // 4, :] - profile))
    assert err < 20.0 * dy ** 2

    # station flux == inflow flux (mass conservation, exact)
    fluxes = un.sum(axis=1) * dy
    assert np.max(np.abs(fluxes - fluxes[0])) < 1e-7


def test_step_is_jittable_and_stable():
    nx, ny = 16, 8
    integ = INSOpenIntegrator((nx, ny), (1.0 / nx, 1.0 / ny),
                              channel_bc(2), mu=0.1, dt=0.01,
                              bdry={(0, 0, 0): 0.5}, tol=1e-8)
    st = integ.initialize()
    step = jax.jit(lambda s: integ.step(s))
    for _ in range(5):
        st = step(st)
    assert np.all(np.isfinite(np.asarray(st.u[0])))
    assert float(jnp.max(jnp.abs(st.u[0]))) < 10.0


def test_3d_channel_integrator_smoke():
    """The open-boundary NS integrator is dimension-generic: a short 3D
    channel run conserves station flux and stays finite."""
    n = (12, 8, 8)
    dx = (2.0 / 12, 1.0 / 8, 1.0 / 8)
    y = (np.arange(8) + 0.5) / 8
    z = (np.arange(8) + 0.5) / 8
    prof = (4.0 * y * (1.0 - y))[:, None] * (4.0 * z * (1.0 - z))[None, :]
    integ = INSOpenIntegrator(n, dx, channel_bc(3), mu=0.1, dt=0.01,
                              bdry={(0, 0, 0): jnp.asarray(prof)[None],
                                    (1, 0, 0): 0.0, (2, 0, 0): 0.0},
                              tol=1e-6)
    st = integ.initialize()
    st = advance(integ, st, 10)
    un = np.asarray(st.u[0])
    assert np.all(np.isfinite(un))
    flux = un.sum(axis=(1, 2)) * dx[1] * dx[2]
    assert np.max(np.abs(flux - flux[0])) < 1e-5
    assert float(integ.max_divergence(st)) < 1e-4


def test_stabilized_ppm_free_stream_preservation():
    """Stabilized-PPM convection (the reference's
    INSStaggeredStabilizedPPMConvectiveOperator analog): a uniform
    stream through inflow->outflow is an exact solution every term
    must preserve — PPM reconstruction of constants is constant, the
    upwind band adds nothing, and the saddle solve keeps the plug."""
    nx, ny = 24, 12
    U0 = 0.8
    integ = INSOpenIntegrator((nx, ny), (1.0 / nx, 1.0 / ny),
                              channel_bc(2), mu=1e-12, dt=0.01,
                              bdry={(0, 0, 0): U0},
                              convective_op_type="stabilized_ppm",
                              tol=1e-11)
    # no-slip walls would shear the plug; use a y-uniform inflow and
    # inspect the CENTER row only after a short run
    st = integ.initialize(u=(jnp.full((nx + 1, ny), U0),
                             jnp.zeros((nx, ny + 1))))
    st = advance(integ, st, 10)
    un = np.asarray(st.u[0])
    assert np.all(np.isfinite(un))
    # interior center row stays at the plug value (walls only diffuse
    # with mu ~ 0)
    np.testing.assert_allclose(un[5:-5, ny // 2], U0, rtol=5e-6)
    assert float(integ.max_divergence(st)) < 1e-7


def test_channel_develops_to_poiseuille_stabilized_ppm():
    """The Poiseuille development oracle under stabilized-PPM
    convection: same equilibrium, same flux conservation."""
    nx, ny = 32, 16
    L, H, U, mu = 2.0, 1.0, 1.0, 0.2
    dx, dy = L / nx, H / ny
    y = (np.arange(ny) + 0.5) * dy
    profile = 4.0 * U * y * (H - y) / H ** 2
    bdry = {(0, 0, 0): jnp.asarray(profile)[None, :],
            (1, 0, 0): 0.0}
    integ = INSOpenIntegrator((nx, ny), (dx, dy), channel_bc(2),
                              mu=mu, dt=0.02, bdry=bdry, tol=1e-10,
                              convective_op_type="stabilized_ppm")
    st = integ.initialize()
    st = advance(integ, st, 160)
    un = np.asarray(st.u[0])
    assert float(integ.max_divergence(st)) < 1e-7
    err = np.max(np.abs(un[3 * nx // 4, :] - profile))
    assert err < 20.0 * dy ** 2
    fluxes = un.sum(axis=1) * dy
    assert np.max(np.abs(fluxes - fluxes[0])) < 1e-7


def test_dynamic_dt_matches_fixed_and_recompiles_nothing():
    """Adaptive-dt support (VERDICT round 4 item 6): alpha = rho/dt is
    threaded through the saddle solve as a traced value. Pins (a) the
    dynamic path reproduces the construction-dt step to roundoff, and
    (b) ONE compiled step serves different dt values (dt changes do
    not retrigger compilation)."""
    nx, ny = 32, 16
    bdry = {(0, 0, 0): 1.0}
    integ = INSOpenIntegrator((nx, ny), (2.0 / nx, 1.0 / ny),
                              channel_bc(2), mu=0.02, dt=2e-3,
                              bdry=bdry, tol=1e-10)
    st = integ.initialize()
    st_fixed = integ.step(st)

    calls = {"n": 0}

    def counted(s, dt):
        calls["n"] += 1
        return integ.step(s, dt=dt)

    f = jax.jit(counted)
    st_dyn = f(st, jnp.asarray(2e-3, st.u[0].dtype))
    du = max(float(jnp.max(jnp.abs(a - b)))
             for a, b in zip(st_fixed.u, st_dyn.u))
    # eager vs jitted FGMRES reassociates reductions; couple the bound
    # to the solve tolerance, not roundoff
    assert du < 1e-8

    st2 = f(st_dyn, jnp.asarray(1e-3, st.u[0].dtype))
    st2 = f(st2, jnp.asarray(3.3e-3, st.u[0].dtype))
    assert calls["n"] == 1          # traced once; dt is data, not shape
    assert bool(jnp.all(jnp.isfinite(st2.u[0])))
    assert float(integ.max_divergence(st2)) < 1e-7
    np.testing.assert_allclose(float(st2.t), 2e-3 + 1e-3 + 3.3e-3,
                               rtol=1e-12)


def test_open_channel_under_cfl_driver():
    """The CFL-adaptive hierarchy_driver loop drives the open-boundary
    integrator end to end — the composition the baked-alpha design
    made impossible (VERDICT round 4 weak #5)."""
    from ibamr_tpu.utils.hierarchy_driver import HierarchyDriver, RunConfig

    nx, ny = 32, 16
    integ = INSOpenIntegrator((nx, ny), (2.0 / nx, 1.0 / ny),
                              channel_bc(2), mu=0.05, dt=0.05,
                              bdry={(0, 0, 0): 1.0}, tol=1e-10)
    st = integ.initialize()
    dts = []
    drv = HierarchyDriver(
        integ,
        RunConfig(dt=0.05, num_steps=30, health_interval=5, cfl=0.4),
        metrics_fn=lambda s, k: dts.append(float(s.t)) or {})
    out = drv.run(st)
    assert bool(jnp.all(jnp.isfinite(out.u[0])))
    # the CFL bound must actually bite: from rest the first chunk rides
    # cfg.dt, later chunks shrink dt below it as the inflow fills in
    steps_t = np.diff([0.0] + dts)
    assert steps_t.min() < 0.05 * 5 - 1e-9
    assert float(integ.max_divergence(out)) < 1e-7
