"""Force-assembly lowering guard (ops.forces).

The round-5 on-chip profile charged 13.1 ms/step to the force
scatter-add at the flagship size; ``compute_lagrangian_force`` now
assembles bounded-degree topologies through a static (N, K) gather
table + axis sum. This file pins the guarantee at the HLO level: the
compiled flagship force path contains ZERO scatter ops (the op census
comes from tools.hlo_cost_audit). Hub topologies whose K would blow
the table up keep the sorted segment_sum, and traced indices keep the
scatter-add fallback — both tiers must agree numerically with the
gather tier.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ibamr_tpu.ops import forces as force_mod
from tools.hlo_cost_audit import hlo_op_counts

F64 = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


def _ring_specs(N=64, dtype=F64):
    """Bounded-degree topology shaped like the real structures: ring
    springs + bending beams + a few tethers (max degree ~7)."""
    idx = np.arange(N)
    springs = force_mod.make_springs(
        idx, np.roll(idx, -1), 1.0 + 0.1 * np.cos(idx),
        0.5 / N, dtype=dtype)
    beams = force_mod.make_beams(
        np.roll(idx, 1), idx, np.roll(idx, -1), 0.01, dim=2,
        dtype=dtype)
    rng = np.random.default_rng(0)
    tid = rng.choice(N, size=8, replace=False)
    targets = force_mod.make_targets(
        tid, 2.0, rng.random((8, 2)), dtype=dtype)
    return force_mod.ForceSpecs(springs=springs, beams=beams,
                                targets=targets)


def _scatter_oracle(X, U, specs):
    """Direct .at[].add assembly, independent of the plan machinery."""
    F = jnp.zeros_like(X)
    s = specs.springs
    d = X[s.idx1] - X[s.idx0]
    length = jnp.sqrt(jnp.sum(d * d, axis=-1))
    safe = jnp.where(length > 0, length, 1.0)
    fvec = ((s.enabled * s.stiffness * (length - s.rest_length))
            / safe)[:, None] * d
    F = F.at[s.idx0].add(fvec).at[s.idx1].add(-fvec)
    b = specs.beams
    cD = (b.enabled * b.rigidity)[:, None] * (
        X[b.prev] - 2.0 * X[b.mid] + X[b.nxt] - b.rest_curvature)
    F = F.at[b.prev].add(-cD).at[b.mid].add(2.0 * cD).at[b.nxt].add(-cD)
    t = specs.targets
    fvec = (t.enabled * t.stiffness)[:, None] * (t.X_target - X[t.idx]) \
        - (t.enabled * t.damping)[:, None] * U[t.idx]
    return F.at[t.idx].add(fvec)


def test_flagship_force_hlo_has_zero_scatter():
    # the REAL flagship force path: shell topology (ring + meridian
    # springs), jitted exactly as the coupled step consumes it
    from ibamr_tpu.models.shell3d import build_shell_example

    integ, state = build_shell_example(
        n_cells=16, n_lat=24, n_lon=24, radius=0.25,
        use_fast_interaction="packed")
    ib = integ.ib
    compiled = jax.jit(
        lambda X, U: ib.compute_force(X, U, 0.0)).lower(
            state.X, state.U).compile()
    ops = hlo_op_counts(compiled.as_text())
    scatters = {k: v for k, v in ops.items() if k.startswith("scatter")}
    assert not scatters, f"force path lowered scatter ops: {scatters}"
    # sanity on the census itself: a real module was walked
    assert sum(ops.values()) > 0


def test_ring_force_hlo_has_zero_scatter():
    specs = _ring_specs()
    X = jnp.asarray(np.random.default_rng(1).random((64, 2)), dtype=F64)
    U = jnp.zeros_like(X)
    compiled = jax.jit(
        lambda X, U: force_mod.compute_lagrangian_force(
            X, U, specs)).lower(X, U).compile()
    ops = hlo_op_counts(compiled.as_text())
    scatters = {k: v for k, v in ops.items() if k.startswith("scatter")}
    assert not scatters, f"force path lowered scatter ops: {scatters}"


def test_gather_tier_matches_scatter_oracle_and_traced_fallback():
    specs = _ring_specs()
    rng = np.random.default_rng(2)
    X = jnp.asarray(rng.random((64, 2)), dtype=F64)
    U = jnp.asarray(rng.standard_normal((64, 2)), dtype=F64)

    F_gather = force_mod.compute_lagrangian_force(X, U, specs)
    F_oracle = _scatter_oracle(X, U, specs)
    np.testing.assert_allclose(np.asarray(F_gather),
                               np.asarray(F_oracle), rtol=0, atol=1e-12)

    # jitting the SPECS as an argument makes the index arrays tracers:
    # the plan raises and the scatter-add fallback must agree
    F_traced = jax.jit(force_mod.compute_lagrangian_force)(X, U, specs)
    np.testing.assert_allclose(np.asarray(F_traced),
                               np.asarray(F_oracle), rtol=0, atol=1e-12)


def test_hub_topology_takes_segment_sum_tier():
    # a hub: every spring touches marker 0, so K ~ M and the (N, K)
    # gather table would cost ~N*M — the tier check must route this
    # through the sorted segment_sum, and the numbers must still match
    N, M = 64, 600
    rng = np.random.default_rng(3)
    idx0 = np.zeros(M, dtype=np.int32)
    idx1 = (rng.integers(1, N, size=M)).astype(np.int32)
    specs = force_mod.ForceSpecs(springs=force_mod.make_springs(
        idx0, idx1, 1.0, 0.01, dtype=F64))
    X = jnp.asarray(rng.random((N, 2)), dtype=F64)
    U = jnp.zeros_like(X)

    # validate the premise: this topology really is above the gather
    # tier's cutoff (else the test silently stops covering segsum)
    perm, sorted_ids, gather = force_mod._scatter_plan(
        (specs.springs.idx0, specs.springs.idx1), N)
    K = gather.shape[1]
    assert N * K > 4 * (2 * M + N)

    F_seg = force_mod.compute_lagrangian_force(X, U, specs)
    F_ref = jnp.zeros_like(X)
    s = specs.springs
    d = X[s.idx1] - X[s.idx0]
    length = jnp.sqrt(jnp.sum(d * d, axis=-1))
    fvec = ((s.stiffness * (length - s.rest_length))
            / jnp.where(length > 0, length, 1.0))[:, None] * d
    F_ref = F_ref.at[s.idx0].add(fvec).at[s.idx1].add(-fvec)
    np.testing.assert_allclose(np.asarray(F_seg), np.asarray(F_ref),
                               rtol=0, atol=1e-11)
