"""IB structures in MULTIPHASE (VC) flow — the capsule/biofilm-style
configuration the reference runs by pairing IBMethod with its VC
hierarchy integrators (SURVEY.md P8 over P22): the explicit IB coupling
composes with ``INSVCStaggeredIntegrator`` through the same
``step(state, dt, f=...)`` seam as the single-phase integrator.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ib import (IBExplicitIntegrator, IBMethod,
                                      advance_ib)
from ibamr_tpu.integrators.ins_vc import INSVCStaggeredIntegrator
from ibamr_tpu.models.membrane2d import make_circle_membrane

F64 = jnp.float64


def test_membrane_capsule_sediments_in_two_phase_tank():
    """An elastic membrane enclosing a HEAVY drop (level set and
    markers initialized on the same circle) sediments in a closed
    walled tank: the membrane centroid falls WITH the drop's level-set
    centroid (the two interface representations stay together), the
    heavy volume is conserved, everything stays finite and
    divergence-free, and the wall faces stay pinned."""
    n = 48
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    r0, c0 = 0.12, (0.5, 0.62)
    vc = INSVCStaggeredIntegrator(
        g, rho0=1.0, rho1=5.0, mu0=0.02, mu1=0.05,
        gravity=(0.0, -3.0), convective_op_type="upwind",
        reinit_interval=10, cg_tol=1e-10, wall_axes=(True, True),
        dtype=F64)
    xx = (np.arange(n) + 0.5) / n
    X, Y = np.meshgrid(xx, xx, indexing="ij")
    phi0 = jnp.asarray(r0 - np.sqrt((X - c0[0]) ** 2
                                    + (Y - c0[1]) ** 2))
    s = make_circle_membrane(64, r0, c0, stiffness=1.0)
    ib = IBMethod(s.force_specs(dtype=F64), kernel="IB_4")
    integ = IBExplicitIntegrator(vc, ib)
    st = integ.initialize(jnp.asarray(s.vertices, F64),
                          ins_state=vc.initialize(phi0))
    vol0 = float(vc.heavy_phase_volume(st.ins))

    def ls_centroid_y(phi):
        from ibamr_tpu.physics.level_set import heaviside
        H = heaviside(phi, vc.eps)
        return float(jnp.sum(H * jnp.asarray(Y)) / jnp.sum(H))

    y_ls0 = ls_centroid_y(st.ins.phi)
    y_mb0 = float(jnp.mean(st.X[:, 1]))

    st = advance_ib(integ, st, 5e-4, 300)

    assert bool(jnp.all(jnp.isfinite(st.X)))
    assert bool(jnp.all(jnp.isfinite(st.ins.u[0])))
    assert float(vc.max_divergence(st.ins)) < 1e-7
    # wall faces pinned
    for d in (0, 1):
        idx = [slice(None)] * 2
        idx[d] = slice(0, 1)
        assert float(jnp.max(jnp.abs(st.ins.u[d][tuple(idx)]))) == 0.0

    y_ls1 = ls_centroid_y(st.ins.phi)
    y_mb1 = float(jnp.mean(st.X[:, 1]))
    # both representations fell ...
    assert y_ls1 < y_ls0 - 0.01, (y_ls0, y_ls1)
    assert y_mb1 < y_mb0 - 0.01, (y_mb0, y_mb1)
    # ... and fell TOGETHER (the membrane is advected by the same
    # velocity field that transports the level set)
    assert abs((y_ls1 - y_ls0) - (y_mb1 - y_mb0)) < 0.012, \
        (y_ls1 - y_ls0, y_mb1 - y_mb0)

    vol1 = float(vc.heavy_phase_volume(st.ins))
    assert abs(vol1 - vol0) / vol0 < 0.05, (vol0, vol1)


def test_membrane_tension_drives_flow_in_two_phase_fluid():
    """A pre-stretched membrane in a quiescent two-phase box (no
    gravity): its elastic relaxation must inject momentum into the VC
    fluid — pins the f-argument coupling path through the variable-
    density predictor."""
    n = 32
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    vc = INSVCStaggeredIntegrator(
        g, rho0=1.0, rho1=2.0, mu0=0.05, mu1=0.1,
        convective_op_type="none", reinit_interval=0, cg_tol=1e-10,
        dtype=F64)
    y = (np.arange(n) + 0.5) / n
    phi0 = jnp.asarray(np.broadcast_to((0.5 - y)[None, :], (n, n)))
    # everywhere-taut loop (rest length < chord): net inward tension
    # drives the ellipse toward a circle, injecting momentum
    s = make_circle_membrane(48, 0.12, (0.5, 0.5), stiffness=5.0,
                             aspect=1.3, rest_length_factor=0.7)
    ib = IBMethod(s.force_specs(dtype=F64), kernel="IB_4")
    integ = IBExplicitIntegrator(vc, ib)
    st = integ.initialize(jnp.asarray(s.vertices, F64),
                          ins_state=vc.initialize(phi0))
    st = advance_ib(integ, st, 5e-4, 60)
    umax = max(float(jnp.max(jnp.abs(c))) for c in st.ins.u)
    assert umax > 1e-4, umax                      # flow developed
    assert bool(jnp.all(jnp.isfinite(st.X)))
    # the stretched ellipse is relaxing toward the circle
    X1 = np.asarray(st.X)
    r = np.linalg.norm(X1 - X1.mean(axis=0), axis=1)
    X0 = np.asarray(s.vertices)
    r0 = np.linalg.norm(X0 - X0.mean(axis=0), axis=1)
    assert (r.max() - r.min()) < (r0.max() - r0.min()), \
        ((r0.max() - r0.min()), (r.max() - r.min()))


def test_fe_capsule_in_two_phase_fluid():
    """FINITE-ELEMENT capsule in two-phase flow: IBFEMethod composes
    with the VC integrator through the same seam (quadrature-cloud
    transfers against the variable-density fluid) — a pre-stretched FE
    disc relaxes, drives flow, and stays finite."""
    from ibamr_tpu.fe.fem import neo_hookean
    from ibamr_tpu.fe.mesh import disc_mesh
    from ibamr_tpu.integrators.ibfe import IBFEMethod

    n = 32
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    vc = INSVCStaggeredIntegrator(
        g, rho0=1.0, rho1=2.0, mu0=0.05, mu1=0.1,
        convective_op_type="none", reinit_interval=0, cg_tol=1e-10,
        dtype=F64)
    y = (np.arange(n) + 0.5) / n
    phi0 = jnp.asarray(np.broadcast_to((0.5 - y)[None, :], (n, n)))
    m = disc_mesh(radius=0.1, center=(0.5, 0.5), n_rings=3)
    S = np.diag([1.1, 1.0 / 1.1])
    X0 = jnp.asarray((m.nodes - 0.5) @ S.T + 0.5, F64)
    fe = IBFEMethod(m, neo_hookean(1.0, 4.0), kernel="IB_4", dtype=F64)
    integ = IBExplicitIntegrator(vc, fe)
    st = integ.initialize(X0, ins_state=vc.initialize(phi0))
    st = advance_ib(integ, st, 1e-3, 50)
    assert bool(jnp.all(jnp.isfinite(st.X)))
    umax = max(float(jnp.max(jnp.abs(c))) for c in st.ins.u)
    assert umax > 1e-5, umax
    # relaxing toward the reference shape
    d0 = float(jnp.max(jnp.abs(X0 - jnp.asarray(m.nodes))))
    d1 = float(jnp.max(jnp.abs(st.X - jnp.asarray(m.nodes))))
    assert d1 < d0, (d0, d1)
