"""Implicit IB coupling + FGMRES/Newton-Krylov solvers.

Reference parity: ``IBImplicitStaggeredHierarchyIntegrator`` (P8) and
the T6 solver-framework completion (FGMRES, SNES-style Newton-Krylov)
— VERDICT round 1 item 6.

The stiffness scenario: a gently perturbed circular membrane with very
stiff springs (k = 1e5). The explicit midpoint integrator is unstable
beyond dt ~ 7e-4 (the fast tension mode); the implicit integrators run
stably at 7x (midpoint) and 14-70x (backward Euler) that limit, and
their trajectories match an explicit small-dt reference.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.integrators.ib import advance_ib
from ibamr_tpu.integrators.ib_implicit import (IBImplicitIntegrator,
                                               advance_ib_implicit)
from ibamr_tpu.models.membrane2d import build_membrane_example
from ibamr_tpu.solvers.krylov import fgmres, newton_krylov


# --------------------------------------------------------------------------
# solver units
# --------------------------------------------------------------------------

def test_fgmres_solves_nonsymmetric():
    rng = np.random.default_rng(0)
    n = 40
    A = jnp.asarray(rng.standard_normal((n, n))) * 0.3 + 10.0 * jnp.eye(n)
    xs = jnp.asarray(rng.standard_normal(n))
    res = fgmres(lambda v: A @ v, A @ xs, m=20, tol=1e-12, restarts=10)
    assert bool(res.converged)
    np.testing.assert_allclose(np.asarray(res.x), np.asarray(xs),
                               atol=1e-10)


def test_fgmres_pytree_and_jit():
    rng = np.random.default_rng(1)
    n = 24
    A = jnp.asarray(rng.standard_normal((n, n))) * 0.2 + 5.0 * jnp.eye(n)
    xs = jnp.asarray(rng.standard_normal(n))
    b = {"a": A @ xs}

    @jax.jit
    def solve(bb):
        return fgmres(lambda v: {"a": A @ v["a"]}, bb, m=24,
                      tol=1e-12, restarts=5).x

    np.testing.assert_allclose(np.asarray(solve(b)["a"]), np.asarray(xs),
                               atol=1e-9)


def test_fgmres_flexible_preconditioner():
    """A nonlinear (iteration-varying) preconditioner is legal in
    FGMRES; convergence must still hold."""
    rng = np.random.default_rng(2)
    n = 30
    A = jnp.asarray(rng.standard_normal((n, n))) * 0.2 + 4.0 * jnp.eye(n)
    xs = jnp.asarray(rng.standard_normal(n))
    Dinv = 1.0 / jnp.diag(A)

    def M(v):  # Jacobi with a data-dependent (nonlinear) tweak
        return Dinv * v * (1.0 + 0.01 * jnp.tanh(v))

    res = fgmres(lambda v: A @ v, A @ xs, M=M, m=20, tol=1e-11,
                 restarts=10)
    assert bool(res.converged)
    np.testing.assert_allclose(np.asarray(res.x), np.asarray(xs),
                               atol=1e-8)


def test_newton_krylov_coupled_cubic():
    rng = np.random.default_rng(3)
    n = 30
    A = jnp.asarray(rng.standard_normal((n, n))) * 0.3 + 8.0 * jnp.eye(n)
    b = jnp.asarray(rng.standard_normal(n))

    def F(x):
        return A @ x + x ** 3 - b

    res = newton_krylov(F, jnp.zeros(n), tol=1e-12, maxiter=30,
                        inner_m=20, inner_restarts=3, inner_tol=1e-8)
    assert bool(res.converged), float(res.resnorm)
    np.testing.assert_allclose(np.asarray(F(res.x)), 0.0, atol=1e-9)


def test_newton_krylov_inside_jit():
    def F(x):
        return jnp.stack([x[0] ** 2 + x[1] - 3.0, x[0] - x[1] + 1.0])

    sol = jax.jit(lambda x0: newton_krylov(F, x0, tol=1e-12,
                                           maxiter=20).x)(jnp.ones(2))
    np.testing.assert_allclose(np.asarray(F(sol)), 0.0, atol=1e-10)


# --------------------------------------------------------------------------
# implicit IB
# --------------------------------------------------------------------------

_K = 1e5


def _build():
    return build_membrane_example(
        n_cells=32, num_markers=64, stiffness=_K, aspect=1.05,
        rest_length_factor=1.0, mu=0.05, dtype=jnp.float64,
        convective_op_type="none")


@pytest.fixture(scope="module")
def explicit_reference():
    integ, st = _build()
    return advance_ib(integ, st, 5e-5, 2000)      # T = 0.1


def test_explicit_unstable_beyond_limit():
    integ, st = _build()
    out = advance_ib(integ, st, 2e-3, 50)
    blew_up = (not bool(jnp.all(jnp.isfinite(out.X)))
               or float(jnp.max(jnp.abs(out.X))) > 10.0)
    assert blew_up


def _implicit_run(scheme, dt, **kw):
    integ, st = _build()
    args = dict(newton_tol=1e-9, newton_maxiter=15,
                inner_m=24, inner_restarts=2, inner_tol=1e-4)
    args.update(kw)
    imp = IBImplicitIntegrator(integ.ins, integ.ib, scheme=scheme, **args)
    return advance_ib_implicit(imp, st, dt, int(round(0.1 / dt)))


def test_implicit_midpoint_3x_matches_reference(explicit_reference):
    """Midpoint (trapezoidal) is 2nd order but only marginally A-stable
    — robust a little past the explicit limit (3x here); backward Euler
    below carries the large-ratio claims."""
    out = _implicit_run("midpoint", 2e-3, inner_tol=1e-5)
    assert bool(jnp.all(jnp.isfinite(out.X)))
    err = float(jnp.max(jnp.abs(out.X - explicit_reference.X)))
    assert err < 2e-2, err


def test_implicit_backward_euler_14x_matches_reference(explicit_reference):
    out = _implicit_run("backward_euler", 1e-2)
    assert bool(jnp.all(jnp.isfinite(out.X)))
    err = float(jnp.max(jnp.abs(out.X - explicit_reference.X)))
    assert err < 3e-2, err


def test_implicit_backward_euler_70x_stable():
    out = _implicit_run("backward_euler", 5e-2)
    assert bool(jnp.all(jnp.isfinite(out.X)))
    assert float(jnp.max(jnp.abs(out.X))) < 2.0
