"""Tests for the auxiliary IB subsystems (P13/P14): internal fluid
sources/sinks, penalty (massive) IB, and instrument panels."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.instruments import InstrumentPanel, make_meters
from ibamr_tpu.integrators.ib import IBMethod
from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
from ibamr_tpu.integrators.penalty_ib import (PenaltyIBIntegrator,
                                              advance_penalty_ib)
from ibamr_tpu.models.membrane2d import make_circle_membrane
from ibamr_tpu.ops import interaction, sources, stencils

F64 = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


# -- internal sources (P14) --------------------------------------------------

def test_eulerian_source_integrates_to_strengths():
    grid = StaggeredGrid(n=(32, 32), x_lo=(0, 0), x_up=(1, 1))
    X = jnp.array([[0.3, 0.5], [0.7, 0.5]], dtype=F64)
    specs = sources.make_sources([0, 1], [1.0, -1.0], dtype=F64)
    q = sources.eulerian_source(specs, grid, X)
    # delta integrates to 1: cell sum * h^2 == sum of strengths (0 here)
    h2 = float(np.prod(grid.dx))
    assert abs(float(jnp.sum(q)) * h2) < 1e-6
    # positive near the source, negative near the sink
    assert float(q[9, 16]) > 0.0 and float(q[22, 16]) < 0.0


def test_ins_step_with_divergence_source():
    grid = StaggeredGrid(n=(32, 32), x_lo=(0, 0), x_up=(1, 1))
    ins = INSStaggeredIntegrator(grid, rho=1.0, mu=0.05,
                                 convective_op_type="none", dtype=F64)
    X = jnp.array([[0.3, 0.5], [0.7, 0.5]], dtype=F64)
    specs = sources.make_sources([0, 1], [0.5, -0.5], dtype=F64)
    q = sources.eulerian_source(specs, grid, X)
    state = ins.initialize()
    state = ins.step(state, 1e-2, q=q)
    # projection imposed div u == q exactly (periodic FFT path)
    div = stencils.divergence(state.u, grid.dx)
    assert float(jnp.max(jnp.abs(div - q))) < 1e-8
    # flow emanates from the source toward the sink (u_x > 0 between)
    assert float(state.u[0][16, 16]) > 0.0


# -- penalty IB (P14) --------------------------------------------------------

def _membrane_ib(grid, num=48, dtype=F64):
    s = make_circle_membrane(num, 0.12, (0.5, 0.6), stiffness=2.0,
                             rest_length_factor=1.0)
    return s, IBMethod(s.force_specs(dtype=dtype))


def test_massive_membrane_sinks():
    grid = StaggeredGrid(n=(48, 48), x_lo=(0, 0), x_up=(1, 1))
    ins = INSStaggeredIntegrator(grid, rho=1.0, mu=0.1,
                                 convective_op_type="none", dtype=F64)
    s, ib = _membrane_ib(grid)
    n = s.vertices.shape[0]
    integ = PenaltyIBIntegrator(ins, ib, mass=np.full(n, 0.05),
                                stiffness=200.0, gravity=(0.0, -1.0))
    state = integ.initialize(s.vertices)
    y0 = float(jnp.mean(state.ib.X[:, 1]))
    state = jax.block_until_ready(advance_penalty_ib(integ, state, 1e-3, 80))
    y1 = float(jnp.mean(state.ib.X[:, 1]))
    assert np.isfinite(y1) and y1 < y0 - 1e-3   # it sinks
    # shadow points track the markers (stiff spring)
    gap = float(jnp.max(jnp.abs(state.Y - state.ib.X)))
    assert gap < 0.02


def test_massless_markers_ignore_gravity():
    grid = StaggeredGrid(n=(32, 32), x_lo=(0, 0), x_up=(1, 1))
    ins = INSStaggeredIntegrator(grid, rho=1.0, mu=0.1,
                                 convective_op_type="none", dtype=F64)
    s, ib = _membrane_ib(grid, num=32)
    n = s.vertices.shape[0]
    integ = PenaltyIBIntegrator(ins, ib, mass=np.zeros(n),
                                stiffness=200.0, gravity=(0.0, -5.0))
    state = integ.initialize(s.vertices)
    state = jax.block_until_ready(advance_penalty_ib(integ, state, 1e-3, 20))
    drift = float(jnp.max(jnp.abs(state.ib.X - jnp.asarray(
        s.vertices, dtype=F64))))
    assert drift < 1e-5                        # nothing moves


# -- instrument panel (P13) --------------------------------------------------

def test_2d_meter_flux_uniform_flow():
    grid = StaggeredGrid(n=(32, 32), x_lo=(0, 0), x_up=(1, 1))
    # vertical segment x=0.5, y in [0.3, 0.7]: 9 markers
    ys = np.linspace(0.3, 0.7, 9)
    X = jnp.asarray(np.stack([np.full(9, 0.5), ys], axis=1), dtype=F64)
    panel = InstrumentPanel(grid, make_meters([list(range(9))], closed=False, dtype=F64))
    U0 = 0.8
    u = (jnp.full(grid.n, U0, dtype=F64), jnp.zeros(grid.n, dtype=F64))
    p = jnp.zeros(grid.n, dtype=F64)
    out = panel.readings(u, p, X)
    # flux through the segment = U0 * length (left normal of +y tangent
    # is +x)
    assert abs(float(out["flux"][0]) - U0 * 0.4) < 1e-5


def test_3d_meter_flux_and_pressure():
    grid = StaggeredGrid(n=(16, 16, 16), x_lo=(0, 0, 0), x_up=(1, 1, 1))
    # circular loop of radius r in the plane x=0.5
    r, m = 0.2, 24
    th = 2 * np.pi * np.arange(m) / m
    X = jnp.asarray(np.stack([np.full(m, 0.5),
                              0.5 + r * np.cos(th),
                              0.5 + r * np.sin(th)], axis=1), dtype=F64)
    panel = InstrumentPanel(grid, make_meters([list(range(m))], closed=True, dtype=F64))
    U0 = 0.6
    u = (jnp.full(grid.n, U0, dtype=F64),
         jnp.zeros(grid.n, dtype=F64), jnp.zeros(grid.n, dtype=F64))
    # linear pressure p = x (cell centers)
    xc = grid.cell_centers(F64)[0]
    p = jnp.broadcast_to(xc, grid.n).astype(F64)
    out = panel.readings(u, p, X)
    # flux ~ U0 * area of the polygonal disc; polygon area < pi r^2
    area_poly = 0.5 * m * r * r * np.sin(2 * np.pi / m)
    assert abs(abs(float(out["flux"][0])) - U0 * area_poly) < 2e-3
    assert abs(float(out["mean_pressure"][0]) - 0.5) < 0.02


def test_two_meters_padded():
    grid = StaggeredGrid(n=(32, 32), x_lo=(0, 0), x_up=(1, 1))
    ys1 = np.linspace(0.2, 0.8, 13)
    ys2 = np.linspace(0.4, 0.6, 5)
    X = jnp.asarray(np.concatenate([
        np.stack([np.full(13, 0.3), ys1], axis=1),
        np.stack([np.full(5, 0.7), ys2], axis=1)]), dtype=F64)
    meters = make_meters([list(range(13)), list(range(13, 18))], closed=False, dtype=F64)
    panel = InstrumentPanel(grid, meters)
    u = (jnp.full(grid.n, 1.0, dtype=F64), jnp.zeros(grid.n, dtype=F64))
    out = panel.readings(u, jnp.zeros(grid.n, dtype=F64), X)
    assert abs(float(out["flux"][0]) - 0.6) < 1e-5
    assert abs(float(out["flux"][1]) - 0.2) < 1e-5


# --------------------------------------------------------------------------
# control-volume hydrodynamic force (IBHydrodynamicForceEvaluator analog)
# --------------------------------------------------------------------------

def _tg_mac(n, t, nu, rho=1.0):
    """Analytic Taylor-Green (u, p) on the periodic MAC layout."""
    import math

    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    two_pi = 2.0 * np.pi
    decay = math.exp(-2.0 * two_pi ** 2 * nu * t)
    xf, yc = g.face_centers(0, jnp.float64)
    xc, yf = g.face_centers(1, jnp.float64)
    u = jnp.sin(two_pi * xf) * jnp.cos(two_pi * yc) * decay + 0 * yc
    v = -jnp.cos(two_pi * xc) * jnp.sin(two_pi * yf) * decay + 0 * xc
    xcc, ycc = g.cell_centers(jnp.float64)
    # for u = +sin*cos the nonlinear term is balanced by +rho/4(...)
    p = rho / 4.0 * (jnp.cos(2 * two_pi * xcc)
                     + jnp.cos(2 * two_pi * ycc)) * decay ** 2
    return g, (u, v), p


def test_hydrodynamic_force_momentum_budget_tg():
    """Empty CV in a decaying Taylor-Green vortex: the surface integral
    must equal the CV momentum rate (F_body = 0), and the discrete
    surface quadrature converges at 2nd order to that identity."""
    from ibamr_tpu.instruments import HydrodynamicForceEvaluator

    nu = 0.02
    errs = {}
    for n in (32, 64):
        g, u, p = _tg_mac(n, 0.0, nu)
        lo = (3 * n // 32, 5 * n // 32)
        hi = (13 * n // 32, 14 * n // 32)
        ev = HydrodynamicForceEvaluator(g, lo, hi, rho=1.0, mu=nu)
        S = np.asarray(ev.surface_force(u, p))
        M = np.asarray(ev.momentum(u))
        dMdt = -2.0 * (2.0 * np.pi) ** 2 * nu * M     # analytic decay
        scale = max(np.abs(dMdt).max(), 1e-12)
        errs[n] = float(np.abs(S - dMdt).max() / scale)
    assert errs[64] < 0.02, errs
    order = np.log2(errs[32] / errs[64])
    assert order > 1.6, (errs, order)


def test_hydrodynamic_force_measures_body_drag():
    """CV momentum budget around an immersed target-held membrane in a
    stream: body_force (surface integral minus momentum rate) matches
    minus the total Lagrangian force the structure exerts on the fluid
    inside the CV."""
    from ibamr_tpu.instruments import HydrodynamicForceEvaluator

    n = 64
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    struct = make_circle_membrane(64, 0.08, (0.5, 0.5), stiffness=1.0)
    specs = struct.force_specs(dtype=jnp.float64)
    from ibamr_tpu.ops.forces import make_targets
    specs = specs._replace(targets=make_targets(
        np.arange(struct.vertices.shape[0]), 50.0, struct.vertices,
        dtype=jnp.float64))
    ib = IBMethod(specs, kernel="IB_4")
    from ibamr_tpu.integrators.ib import IBExplicitIntegrator

    ins = INSStaggeredIntegrator(g, mu=0.02, dtype=jnp.float64)
    integ = IBExplicitIntegrator(ins, ib, scheme="midpoint")
    st = integ.initialize(jnp.asarray(struct.vertices, jnp.float64))
    # background stream (div-free, survives the projection)
    st = st._replace(ins=st.ins._replace(
        u=(st.ins.u[0] + 0.4, st.ins.u[1])))

    ev = HydrodynamicForceEvaluator(g, (8, 8), (56, 56), rho=1.0,
                                    mu=0.02)
    dt = 2.5e-4
    for _ in range(40):                      # develop the wake a bit
        st = integ.step(st, dt)
    m0 = ev.momentum(st.ins.u)
    st1 = integ.step(st, dt)
    m1 = ev.momentum(st1.ins.u)
    # surface terms near the midpoint of the step window
    S_mid = 0.5 * (ev.surface_force(st.ins.u, st1.ins.p)
                   + ev.surface_force(st1.ins.u, st1.ins.p))
    F_cv = np.asarray(S_mid - (m1 - m0) / dt)

    # the structure's reaction on the fluid, midpoint convention of the
    # integrator's force spreading
    U = ib.interpolate_velocity(st.ins.u, g, st.X, st.mask)
    X_half = st.X + 0.5 * dt * U
    F_lag = np.asarray(
        jnp.sum(ib.compute_force(X_half, U, float(st.ins.t))
                * st.mask[:, None], axis=0))
    scale = max(np.abs(F_lag).max(), 1e-10)
    assert np.abs(F_cv + F_lag).max() / scale < 0.08, (F_cv, F_lag)
