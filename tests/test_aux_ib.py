"""Tests for the auxiliary IB subsystems (P13/P14): internal fluid
sources/sinks, penalty (massive) IB, and instrument panels."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.instruments import InstrumentPanel, make_meters
from ibamr_tpu.integrators.ib import IBMethod
from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
from ibamr_tpu.integrators.penalty_ib import (PenaltyIBIntegrator,
                                              advance_penalty_ib)
from ibamr_tpu.models.membrane2d import make_circle_membrane
from ibamr_tpu.ops import interaction, sources, stencils

F64 = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


# -- internal sources (P14) --------------------------------------------------

def test_eulerian_source_integrates_to_strengths():
    grid = StaggeredGrid(n=(32, 32), x_lo=(0, 0), x_up=(1, 1))
    X = jnp.array([[0.3, 0.5], [0.7, 0.5]], dtype=F64)
    specs = sources.make_sources([0, 1], [1.0, -1.0], dtype=F64)
    q = sources.eulerian_source(specs, grid, X)
    # delta integrates to 1: cell sum * h^2 == sum of strengths (0 here)
    h2 = float(np.prod(grid.dx))
    assert abs(float(jnp.sum(q)) * h2) < 1e-6
    # positive near the source, negative near the sink
    assert float(q[9, 16]) > 0.0 and float(q[22, 16]) < 0.0


def test_ins_step_with_divergence_source():
    grid = StaggeredGrid(n=(32, 32), x_lo=(0, 0), x_up=(1, 1))
    ins = INSStaggeredIntegrator(grid, rho=1.0, mu=0.05,
                                 convective_op_type="none", dtype=F64)
    X = jnp.array([[0.3, 0.5], [0.7, 0.5]], dtype=F64)
    specs = sources.make_sources([0, 1], [0.5, -0.5], dtype=F64)
    q = sources.eulerian_source(specs, grid, X)
    state = ins.initialize()
    state = ins.step(state, 1e-2, q=q)
    # projection imposed div u == q exactly (periodic FFT path)
    div = stencils.divergence(state.u, grid.dx)
    assert float(jnp.max(jnp.abs(div - q))) < 1e-8
    # flow emanates from the source toward the sink (u_x > 0 between)
    assert float(state.u[0][16, 16]) > 0.0


# -- penalty IB (P14) --------------------------------------------------------

def _membrane_ib(grid, num=48, dtype=F64):
    s = make_circle_membrane(num, 0.12, (0.5, 0.6), stiffness=2.0,
                             rest_length_factor=1.0)
    return s, IBMethod(s.force_specs(dtype=dtype))


def test_massive_membrane_sinks():
    grid = StaggeredGrid(n=(48, 48), x_lo=(0, 0), x_up=(1, 1))
    ins = INSStaggeredIntegrator(grid, rho=1.0, mu=0.1,
                                 convective_op_type="none", dtype=F64)
    s, ib = _membrane_ib(grid)
    n = s.vertices.shape[0]
    integ = PenaltyIBIntegrator(ins, ib, mass=np.full(n, 0.05),
                                stiffness=200.0, gravity=(0.0, -1.0))
    state = integ.initialize(s.vertices)
    y0 = float(jnp.mean(state.ib.X[:, 1]))
    state = jax.block_until_ready(advance_penalty_ib(integ, state, 1e-3, 80))
    y1 = float(jnp.mean(state.ib.X[:, 1]))
    assert np.isfinite(y1) and y1 < y0 - 1e-3   # it sinks
    # shadow points track the markers (stiff spring)
    gap = float(jnp.max(jnp.abs(state.Y - state.ib.X)))
    assert gap < 0.02


def test_massless_markers_ignore_gravity():
    grid = StaggeredGrid(n=(32, 32), x_lo=(0, 0), x_up=(1, 1))
    ins = INSStaggeredIntegrator(grid, rho=1.0, mu=0.1,
                                 convective_op_type="none", dtype=F64)
    s, ib = _membrane_ib(grid, num=32)
    n = s.vertices.shape[0]
    integ = PenaltyIBIntegrator(ins, ib, mass=np.zeros(n),
                                stiffness=200.0, gravity=(0.0, -5.0))
    state = integ.initialize(s.vertices)
    state = jax.block_until_ready(advance_penalty_ib(integ, state, 1e-3, 20))
    drift = float(jnp.max(jnp.abs(state.ib.X - jnp.asarray(
        s.vertices, dtype=F64))))
    assert drift < 1e-5                        # nothing moves


# -- instrument panel (P13) --------------------------------------------------

def test_2d_meter_flux_uniform_flow():
    grid = StaggeredGrid(n=(32, 32), x_lo=(0, 0), x_up=(1, 1))
    # vertical segment x=0.5, y in [0.3, 0.7]: 9 markers
    ys = np.linspace(0.3, 0.7, 9)
    X = jnp.asarray(np.stack([np.full(9, 0.5), ys], axis=1), dtype=F64)
    panel = InstrumentPanel(grid, make_meters([list(range(9))], closed=False, dtype=F64))
    U0 = 0.8
    u = (jnp.full(grid.n, U0, dtype=F64), jnp.zeros(grid.n, dtype=F64))
    p = jnp.zeros(grid.n, dtype=F64)
    out = panel.readings(u, p, X)
    # flux through the segment = U0 * length (left normal of +y tangent
    # is +x)
    assert abs(float(out["flux"][0]) - U0 * 0.4) < 1e-5


def test_3d_meter_flux_and_pressure():
    grid = StaggeredGrid(n=(16, 16, 16), x_lo=(0, 0, 0), x_up=(1, 1, 1))
    # circular loop of radius r in the plane x=0.5
    r, m = 0.2, 24
    th = 2 * np.pi * np.arange(m) / m
    X = jnp.asarray(np.stack([np.full(m, 0.5),
                              0.5 + r * np.cos(th),
                              0.5 + r * np.sin(th)], axis=1), dtype=F64)
    panel = InstrumentPanel(grid, make_meters([list(range(m))], closed=True, dtype=F64))
    U0 = 0.6
    u = (jnp.full(grid.n, U0, dtype=F64),
         jnp.zeros(grid.n, dtype=F64), jnp.zeros(grid.n, dtype=F64))
    # linear pressure p = x (cell centers)
    xc = grid.cell_centers(F64)[0]
    p = jnp.broadcast_to(xc, grid.n).astype(F64)
    out = panel.readings(u, p, X)
    # flux ~ U0 * area of the polygonal disc; polygon area < pi r^2
    area_poly = 0.5 * m * r * r * np.sin(2 * np.pi / m)
    assert abs(abs(float(out["flux"][0])) - U0 * area_poly) < 2e-3
    assert abs(float(out["mean_pressure"][0]) - 0.5) < 0.02


def test_two_meters_padded():
    grid = StaggeredGrid(n=(32, 32), x_lo=(0, 0), x_up=(1, 1))
    ys1 = np.linspace(0.2, 0.8, 13)
    ys2 = np.linspace(0.4, 0.6, 5)
    X = jnp.asarray(np.concatenate([
        np.stack([np.full(13, 0.3), ys1], axis=1),
        np.stack([np.full(5, 0.7), ys2], axis=1)]), dtype=F64)
    meters = make_meters([list(range(13)), list(range(13, 18))], closed=False, dtype=F64)
    panel = InstrumentPanel(grid, meters)
    u = (jnp.full(grid.n, 1.0, dtype=F64), jnp.zeros(grid.n, dtype=F64))
    out = panel.readings(u, jnp.zeros(grid.n, dtype=F64), X)
    assert abs(float(out["flux"][0]) - 0.6) < 1e-5
    assert abs(float(out["flux"][1]) - 0.2) < 1e-5
