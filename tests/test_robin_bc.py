"""General Robin BCs + spatially-varying boundary data (T9 upgrade,
SURVEY.md §2.1 T9 — RobinBcCoefStrategy / muParserRobinBcCoefs).
"""

import math

import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu import bc as bc_mod
from ibamr_tpu.bc import (AxisBC, DomainBC, SideBC, dirichlet_axis,
                          neumann_axis, robin_axis)
from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.adv_diff import (AdvDiffSemiImplicitIntegrator,
                                            TransportedQuantity,
                                            advance_adv_diff)
from ibamr_tpu.solvers.fastdiag import FastDiagSolver


def test_robin_reduces_to_dirichlet_neumann():
    """robin(1,0) == dirichlet and robin(0,1) == neumann ghosts."""
    rng = np.random.default_rng(0)
    Q = jnp.asarray(rng.standard_normal((8, 8)))
    dx = (0.1, 0.1)
    for named, a, b in ((dirichlet_axis(0.7, -0.3), 1.0, 0.0),
                        (neumann_axis(0.7, -0.3), 0.0, 1.0)):
        rob = robin_axis(a, b, lo=0.7, hi=-0.3)
        g_named = bc_mod.fill_ghosts_cc(
            Q, DomainBC(axes=(named, AxisBC())), dx)
        g_rob = bc_mod.fill_ghosts_cc(
            Q, DomainBC(axes=(rob, AxisBC())), dx)
        np.testing.assert_allclose(np.asarray(g_rob), np.asarray(g_named),
                                   atol=1e-13)


def test_robin_ghost_satisfies_condition():
    """The filled ghost reproduces a*Q_face + b*dQ/dn = g discretely."""
    rng = np.random.default_rng(1)
    Q = jnp.asarray(rng.standard_normal((8, 6)))
    h = 0.125
    a, b, g = 2.0, 0.5, 1.3
    dom = DomainBC(axes=(robin_axis(a, b, lo=g, hi=g), AxisBC()))
    G = bc_mod.fill_ghosts_cc(Q, dom, (h, h))
    ghost_lo = np.asarray(G[0, 1:-1])
    int_lo = np.asarray(Q[0, :])
    q_face = 0.5 * (ghost_lo + int_lo)
    dqdn = (ghost_lo - int_lo) / h      # outward normal on the lo side
    np.testing.assert_allclose(a * q_face + b * dqdn, g, atol=1e-12)


def test_fastdiag_robin_solve_consistent():
    """(alpha + beta lap_robin) solve(rhs) == rhs through the
    BC-aware Laplacian (the homogeneous-operator contract)."""
    rng = np.random.default_rng(2)
    g = StaggeredGrid(n=(16, 12), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    dom = DomainBC(axes=(robin_axis(1.5, 0.25), AxisBC()))
    solver = FastDiagSolver(g, dom, ("cc", "cc"))
    rhs = jnp.asarray(rng.standard_normal(g.n))
    alpha, beta = 3.0, -0.7
    Q = solver.solve(rhs, alpha, beta)
    resid = alpha * Q + beta * bc_mod.laplacian_cc(Q, dom, g.dx)
    np.testing.assert_allclose(np.asarray(resid), np.asarray(rhs),
                               atol=1e-10)


def _steady_robin_error(n):
    """Steady diffusion with Robin walls on x: exact Q = x(1-x) + 1
    satisfies 2*Q + 1*dQ/dn = 1 on both walls with source 2*kappa."""
    g = StaggeredGrid(n=(n, 8), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    kappa = 1.0
    dom = DomainBC(axes=(robin_axis(2.0, 1.0, lo=1.0, hi=1.0), AxisBC()))
    q = TransportedQuantity(name="Q", kappa=kappa,
                            source=lambda c, t, Q: 2.0 * kappa,
                            convective_op_type="none", bc=dom)
    integ = AdvDiffSemiImplicitIntegrator(g, [q], dtype=jnp.float64)
    st = integ.initialize([jnp.ones(g.n, dtype=jnp.float64)])
    st = advance_adv_diff(integ, st, 0.05, 400)      # t = 20: steady
    xc = (np.arange(n) + 0.5) / n
    exact = xc * (1.0 - xc) + 1.0
    return float(np.max(np.abs(np.asarray(st.Q[0][:, 0]) - exact)))


def test_robin_steady_state_convergence():
    e16 = _steady_robin_error(16)
    e32 = _steady_robin_error(32)
    assert e32 < 2e-3, (e16, e32)
    assert e16 / e32 > 3.0, (e16, e32)        # ~2nd order


def _laplace_dirichlet_strip_error(n):
    """Laplace equation on [0,1]^2 with spatially-varying Dirichlet
    data g(x) = sin(pi x) on the y=0 wall (zero on the others):
    exact Q = sin(pi x) sinh(pi (1-y)) / sinh(pi)."""
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    dom = DomainBC(axes=(dirichlet_axis(), dirichlet_axis()))
    xc = (jnp.arange(n, dtype=jnp.float64) + 0.5) / n
    gdata = {(1, 0): jnp.sin(math.pi * xc)[:, None]}
    solver = FastDiagSolver(g, dom, ("cc", "cc"))
    # lap Q = 0 with inhomogeneous data: A Q = -b, b = lap_bc(0)
    b_vec = bc_mod.laplacian_cc(jnp.zeros(g.n, dtype=jnp.float64), dom,
                                g.dx, bdry_data=gdata)
    Q = solver.solve(-b_vec, 0.0, 1.0)
    X, Y = np.meshgrid((np.arange(n) + 0.5) / n,
                       (np.arange(n) + 0.5) / n, indexing="ij")
    exact = np.sin(np.pi * X) * np.sinh(np.pi * (1 - Y)) / np.sinh(np.pi)
    return float(np.max(np.abs(np.asarray(Q) - exact)))


def test_spatially_varying_dirichlet_laplace():
    e32 = _laplace_dirichlet_strip_error(32)
    e64 = _laplace_dirichlet_strip_error(64)
    assert e64 < 1.5e-3, (e32, e64)
    assert e32 / e64 > 3.0, (e32, e64)        # 2nd order


def test_time_varying_data_through_integrator():
    """bdry_data threads through the CN lifting: a heated strip drives
    the interior above the initial value only near the strip."""
    n = 32
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    dom = DomainBC(axes=(AxisBC(), dirichlet_axis()))
    xc = (jnp.arange(n, dtype=jnp.float64) + 0.5) / n
    strip = jnp.where(jnp.abs(xc - 0.5) < 0.1, 1.0, 0.0)[:, None]
    q = TransportedQuantity(name="T", kappa=0.05,
                            convective_op_type="none", bc=dom,
                            bdry_data={(1, 0): strip})
    integ = AdvDiffSemiImplicitIntegrator(g, [q], dtype=jnp.float64)
    st = integ.initialize([jnp.zeros(g.n, dtype=jnp.float64)])
    st = advance_adv_diff(integ, st, 0.01, 200)
    Q = np.asarray(st.Q[0])
    assert Q[n // 2, 0] > 0.5          # hot under the strip
    assert abs(Q[2, 0]) < 0.05         # cold away from it
    assert Q[n // 2, 0] > Q[n // 2, n // 2] > Q[n // 2, -1] >= -1e-6


def test_robin_requires_nonzero_coeffs():
    with pytest.raises(ValueError, match="robin"):
        SideBC("robin", 0.0, a=0.0, b=0.0)
