"""Determinism + profiling hooks (SURVEY.md §5.1/5.2).

The reference's sanitizer story (race detection, deterministic MPI
reductions) maps to: jitted steps must be BITWISE deterministic across
runs (same compiled program, same inputs), including the scatter-add
transfer paths (atomics-free XLA scatters) and the stochastic-forcing
path under a fixed key. The profiler hook must produce a trace dir."""

import jax
import jax.numpy as jnp
import numpy as np

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.models.membrane2d import build_membrane_example
from ibamr_tpu.utils.timers import profile_trace


def _run_membrane(steps=5):
    integ, state = build_membrane_example(n_cells=32, num_markers=96)
    step = jax.jit(lambda s, d: integ.step(s, d))
    for _ in range(steps):
        state = step(state, 1e-3)
    jax.block_until_ready(state)
    return state


def test_coupled_ib_step_bitwise_deterministic():
    """Two fresh runs of the jitted coupled IB step (scatter-add spread
    inside) must agree BITWISE — the determinism contract the reference
    needs sanitizers to approximate."""
    a = _run_membrane()
    b = _run_membrane()
    assert np.array_equal(np.asarray(a.X), np.asarray(b.X))
    for ua, ub in zip(a.ins.u, b.ins.u):
        assert np.array_equal(np.asarray(ua), np.asarray(ub))


def test_stochastic_forcing_deterministic_under_key():
    from ibamr_tpu.ops.stochastic import StochasticStressForcing

    g = StaggeredGrid(n=(16, 16), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    key = jax.random.PRNGKey(7)
    forcing = StochasticStressForcing(g, mu=0.1, kT=1.0)
    f1 = forcing.sample(key, 1e-3)
    f2 = forcing.sample(key, 1e-3)
    for a, b in zip(f1, f2):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_profile_trace_writes_trace(tmp_path):
    d = str(tmp_path / "prof")
    with profile_trace(d):
        x = jnp.ones((64, 64))
        jax.block_until_ready(jnp.dot(x, x))
    import os

    found = []
    for root, _, files in os.walk(d):
        found += files
    assert found, "profiler produced no trace files"


def test_profile_trace_noop_without_dir():
    with profile_trace(""):
        pass
    with profile_trace(None):
        pass


def test_packed_engine_bitwise_deterministic():
    """The occupancy-packed engine's sort/segment pipeline is bitwise
    repeatable: two independent bucket+spread+interp evaluations of the
    same inputs are identical (sorted segment reductions, no atomics —
    the determinism the reference's MPI reductions cannot promise)."""
    import jax.numpy as jnp

    from ibamr_tpu.models.shell3d import make_spherical_shell
    from ibamr_tpu.ops.interaction_packed import PackedInteraction

    g = StaggeredGrid(n=(32, 32, 32), x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    s = make_spherical_shell(16, 16, 0.12, (0.5,) * 3, 1.0)
    X = jnp.asarray(s.vertices, jnp.float32)
    rng = np.random.default_rng(7)
    F = jnp.asarray(rng.standard_normal((X.shape[0], 3)), jnp.float32)
    u = tuple(jnp.asarray(rng.standard_normal(g.n), jnp.float32)
              for _ in range(3))
    eng = PackedInteraction(g, tile=8, chunk=128, nchunks=64)

    f1 = eng.spread_vel(F, X)
    f2 = eng.spread_vel(F, X)
    for a, b in zip(f1, f2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    U1 = eng.interpolate_vel(u, X)
    U2 = eng.interpolate_vel(u, X)
    np.testing.assert_array_equal(np.asarray(U1), np.asarray(U2))
