"""Pencil-decomposed distributed FFT vs the local spectral solver."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.parallel import make_mesh
from ibamr_tpu.parallel.fftpar import PencilFFT
from ibamr_tpu.parallel.mesh import grid_pspec
from ibamr_tpu.solvers import fft as local_fft


def _random_field(shape, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal(shape))


CASES = [
    ((32, 16), 1),     # 2D grid, 1D mesh
    ((32, 16), 2),     # 2D grid, 2D mesh (flattened transpose group)
    ((16, 16, 8), 1),  # 3D grid, 1D mesh
    ((16, 16, 8), 2),  # 3D grid, 2D mesh (true pencils)
]


@pytest.mark.parametrize("shape,mesh_axes", CASES)
def test_poisson_matches_local(shape, mesh_axes):
    grid = StaggeredGrid(n=shape, x_lo=(0.0,) * len(shape),
                         x_up=(1.0,) * len(shape))
    mesh = make_mesh(8, max_axes=mesh_axes)
    pencil = PencilFFT(grid, mesh)
    rhs = _random_field(shape)
    rhs = rhs - jnp.mean(rhs)

    got = jax.jit(pencil.poisson)(rhs)
    want = local_fft.solve_poisson_periodic(rhs, grid.dx)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-10, atol=1e-10)


@pytest.mark.parametrize("shape,mesh_axes", CASES)
def test_helmholtz_matches_local(shape, mesh_axes):
    grid = StaggeredGrid(n=shape, x_lo=(0.0,) * len(shape),
                         x_up=(1.0,) * len(shape))
    mesh = make_mesh(8, max_axes=mesh_axes)
    pencil = PencilFFT(grid, mesh)
    rhs = _random_field(shape, seed=1)
    alpha, beta = 10.0, -0.05

    got = jax.jit(lambda r, a, b: pencil.helmholtz(r, a, b))(
        rhs, alpha, beta)
    want = local_fft.solve_helmholtz_periodic(rhs, grid.dx, alpha, beta)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-10, atol=1e-10)


def test_helmholtz_traced_coefficients():
    """alpha/beta may be traced (dt-dependent) without recompiling."""
    grid = StaggeredGrid(n=(16, 16), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    mesh = make_mesh(8, max_axes=1)
    pencil = PencilFFT(grid, mesh)
    rhs = _random_field((16, 16), seed=2)

    fn = jax.jit(lambda r, a: pencil.helmholtz(r, a, -0.1))
    for a in (1.0, 5.0):
        got = fn(rhs, a)
        want = local_fft.solve_helmholtz_periodic(rhs, grid.dx, a, -0.1)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-10, atol=1e-10)


def test_poisson_solves_discrete_laplacian():
    """Residual check: lap(p) == rhs through the actual stencils."""
    from ibamr_tpu.ops import stencils

    grid = StaggeredGrid(n=(16, 16, 8), x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    mesh = make_mesh(8, max_axes=2)
    pencil = PencilFFT(grid, mesh)
    rhs = _random_field((16, 16, 8), seed=3)
    rhs = rhs - jnp.mean(rhs)

    p = jax.jit(pencil.poisson)(rhs)
    res = stencils.laplacian(p, grid.dx) - rhs
    assert float(jnp.max(jnp.abs(res))) < 1e-9


def test_projection_divergence_free():
    grid = StaggeredGrid(n=(16, 8, 8), x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    mesh = make_mesh(8, max_axes=2)
    pencil = PencilFFT(grid, mesh)
    u = tuple(_random_field((16, 8, 8), seed=10 + d) for d in range(3))

    from ibamr_tpu.ops import stencils

    u_proj, _ = jax.jit(lambda v: pencil.project_divergence_free(v, grid.dx))(u)
    div = stencils.divergence(u_proj, grid.dx)
    assert float(jnp.max(jnp.abs(div))) < 1e-9


def test_divisibility_errors():
    mesh = make_mesh(8, max_axes=1)
    with pytest.raises(ValueError):
        PencilFFT(StaggeredGrid(n=(12, 16), x_lo=(0, 0), x_up=(1, 1)), mesh)
    with pytest.raises(ValueError):
        # axis 1 not divisible by P=8 (transpose plan)
        PencilFFT(StaggeredGrid(n=(16, 12), x_lo=(0, 0), x_up=(1, 1)), mesh)


@pytest.mark.parametrize("mesh_axes", [1, 2])
@pytest.mark.parametrize("tiles", [2, 4])
def test_pipelined_tiles_bitwise_equal_unpipelined(mesh_axes, tiles):
    """The PR-16 double-buffered transpose pipeline is a pure
    reordering: tiling only slices the batch axes of batched 1-D FFTs
    and elementwise symbol algebra, so each element's expression tree
    is unchanged and tiles>1 must match tiles=1 BITWISE in f64 — for
    both kernel flavors (Helmholtz and Poisson) on both mesh shapes."""
    shape = (16, 16, 8)
    grid = StaggeredGrid(n=shape, x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    mesh = make_mesh(8, max_axes=mesh_axes)
    ref = PencilFFT(grid, mesh, tiles=1)
    pipe = PencilFFT(grid, mesh, tiles=tiles)
    rhs = _random_field(shape, seed=4)

    for solve in ("helmholtz", "poisson"):
        if solve == "helmholtz":
            a = jax.jit(lambda r: ref.helmholtz(r, 10.0, -0.05))(rhs)
            b = jax.jit(lambda r: pipe.helmholtz(r, 10.0, -0.05))(rhs)
        else:
            a = jax.jit(ref.poisson)(rhs)
            b = jax.jit(pipe.poisson)(rhs)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=f"{solve} mesh_axes="
                                              f"{mesh_axes} tiles={tiles}")


def test_pipeline_hides_the_transposes():
    """The structural pin at the unit level: on the 2-D mesh the tiled
    Helmholtz kernel leaves at most ONE unhidden data-moving collective
    (stage C's first return transpose — no independent work exists
    there), where the unpipelined chain leaves all four."""
    from ibamr_tpu.analysis.graph_census import structural_overlap_census

    shape = (16, 16, 8)
    grid = StaggeredGrid(n=shape, x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    mesh = make_mesh(8, max_axes=2)
    rhs = _random_field(shape, seed=5)

    def census(pencil):
        jx = jax.make_jaxpr(
            lambda r: pencil.helmholtz(r, 10.0, -0.05))(rhs)
        return structural_overlap_census(jx.jaxpr)

    chain = census(PencilFFT(grid, mesh, tiles=1))
    pipe = census(PencilFFT(grid, mesh, tiles=2))
    assert pipe["unhidden_collectives"] <= 1
    assert pipe["unhidden_collectives"] < chain["unhidden_collectives"]
    assert pipe["hidden_fraction"] > chain["hidden_fraction"]


def test_sharded_input_stays_sharded():
    """Solver accepts an already-sharded operand and returns the same
    sharding (no silent gather to one device)."""
    from jax.sharding import NamedSharding

    grid = StaggeredGrid(n=(32, 16), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    mesh = make_mesh(8, max_axes=1)
    pencil = PencilFFT(grid, mesh)
    sharding = NamedSharding(mesh, grid_pspec(mesh, 2))
    rhs = jax.device_put(_random_field((32, 16)), sharding)
    out = jax.jit(pencil.poisson)(rhs)
    assert out.sharding.is_equivalent_to(sharding, out.ndim)
