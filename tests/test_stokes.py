"""General staggered-Stokes saddle solver (P3): coupled Krylov solve
with inflow / no-slip / open boundaries.

Oracles: exact-inverse manufactured solutions (rhs built by applying the
discrete operator to known fields — the solver must return them to
Krylov tolerance), the discrete Poiseuille channel (analytic profile to
O(h^2), EXACT station-wise flux conservation), and preconditioner
quality (Krylov restarts stay small and roughly grid-independent — the
reference's projection-preconditioner promise, SURVEY.md §6)."""

import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.solvers.stokes import (StaggeredStokesSolver, StokesBC,
                                      VelocitySide, WALL, INFLOW, OPEN,
                                      channel_bc, cavity_bc)


def _random_state(solver, seed=0):
    rng = np.random.default_rng(seed)
    u = tuple(jnp.asarray(rng.standard_normal(s)) for s in solver.shapes)
    p = jnp.asarray(rng.standard_normal(solver.n))
    return u, p


def test_exact_inverse_channel_unsteady():
    n = (24, 16)
    solver = StaggeredStokesSolver(n, (1.0 / n[0], 1.0 / n[1]),
                                   channel_bc(2), alpha=1.0, mu=0.01,
                                   tol=1e-11)
    u, p = _random_state(solver)
    rhs = solver.operator((u, p))
    sol = solver.solve(rhs)
    assert bool(sol.converged)
    for a, b in zip(sol.u, u):
        assert np.max(np.abs(np.asarray(a - b))) < 1e-7
    assert np.max(np.abs(np.asarray(sol.p - p))) < 1e-6


def test_exact_inverse_cavity_steady():
    """All-wall (cavity) steady Stokes: pressure determined up to a
    constant; compare mean-zero fields."""
    n = (16, 16)
    solver = StaggeredStokesSolver(n, (1.0 / 16, 1.0 / 16),
                                   cavity_bc(2), alpha=0.0, mu=1.0,
                                   tol=1e-11)
    u, p = _random_state(solver, seed=4)
    p = p - jnp.mean(p)
    rhs = solver.operator((u, p))
    sol = solver.solve(rhs)
    assert bool(sol.converged)
    for a, b in zip(sol.u, u):
        assert np.max(np.abs(np.asarray(a - b))) < 1e-7
    assert np.max(np.abs(np.asarray(
        sol.p - (p - jnp.mean(p))))) < 1e-6


def test_poiseuille_channel():
    """Parabolic inflow -> parabolic everywhere, linear pressure,
    EXACT flux conservation at every station, div u ~ 0."""
    nx, ny = 48, 32
    L, H, U, mu = 1.5, 1.0, 1.0, 0.7
    dx, dy = L / nx, H / ny
    solver = StaggeredStokesSolver((nx, ny), (dx, dy), channel_bc(2),
                                   alpha=0.0, mu=mu, tol=1e-11,
                                   m=60, restarts=20)
    y = (np.arange(ny) + 0.5) * dy
    profile = 4.0 * U * y * (H - y) / H ** 2
    bdry = {(0, 0, 0): jnp.asarray(profile)[None, :],  # u inflow
            (1, 0, 0): 0.0}                             # v = 0 at inflow
    rhs = solver.make_rhs(bdry=bdry)
    sol = solver.solve(rhs)
    assert bool(sol.converged)

    un = np.asarray(sol.u[0])          # (nx+1, ny)
    vn = np.asarray(sol.u[1])          # (nx, ny)
    pn = np.asarray(sol.p)

    # flux through every x-station equals the inflow flux exactly
    fluxes = un.sum(axis=1) * dy
    assert np.max(np.abs(fluxes - fluxes[0])) < 1e-8

    # profile stays parabolic to the O(h^2) ghost-reflection error
    err = np.max(np.abs(un - profile[None, :]))
    assert err < 10.0 * dy ** 2

    # transverse velocity: O(h^2) entrance effect at the inlet row
    # (prescribed cell-center parabola vs ghost-reflected wall corner),
    # decaying to solver tolerance downstream
    assert np.max(np.abs(vn)) < 10.0 * dy ** 2
    assert np.max(np.abs(vn[3 * nx // 4:, :])) \
        < 0.05 * np.max(np.abs(vn[:nx // 4, :]))
    # developed region (past the O(h^2) entrance layer): linear p with
    # the analytic gradient to discretization error
    dpdx = (pn[1:, :] - pn[:-1, :]) / dx
    dpdx_exact = -8.0 * U * mu / H ** 2
    assert np.max(np.abs(dpdx[nx // 2:] - dpdx_exact)) < 20.0 * dy ** 2

    # divergence to solver tolerance
    div = np.asarray(solver.divergence(sol.u))
    assert np.max(np.abs(div)) < 1e-8


@pytest.mark.parametrize("n", [16, 32])
def test_preconditioner_iterations_bounded(n):
    """Projection-preconditioned FGMRES restarts stay small and do not
    blow up with refinement (time-dependent regime)."""
    solver = StaggeredStokesSolver((n, n), (1.0 / n, 1.0 / n),
                                   channel_bc(2), alpha=100.0, mu=1.0,
                                   tol=1e-9)
    u, p = _random_state(solver, seed=1)
    rhs = solver.operator((u, p))
    sol = solver.solve(rhs)
    assert bool(sol.converged)
    assert int(sol.iters) <= 6       # outer restarts (m=40 each)


def test_3d_channel_smoke():
    n = (12, 8, 8)
    solver = StaggeredStokesSolver(n, tuple(1.0 / v for v in n),
                                   channel_bc(3), alpha=1.0, mu=0.05,
                                   tol=1e-9)
    u, p = _random_state(solver, seed=9)
    rhs = solver.operator((u, p))
    sol = solver.solve(rhs)
    assert bool(sol.converged)
    for a, b in zip(sol.u, u):
        assert np.max(np.abs(np.asarray(a - b))) < 1e-5


def test_lid_driven_cavity_corner_rows():
    """Moving-lid tangential data whose lift slab crosses prescribed
    x-wall boundary faces: those identity rows must keep u = 0 (corner
    regression — the lift must not leak onto prescribed faces)."""
    n = 16
    solver = StaggeredStokesSolver((n, n), (1.0 / n, 1.0 / n),
                                   cavity_bc(2), alpha=0.0, mu=1.0,
                                   tol=1e-10)
    rhs = solver.make_rhs(bdry={(0, 1, 1): 1.0})   # u = 1 on the top lid
    # prescribed u-faces (x walls) carry exactly 0, not the ghost lift
    ru = np.asarray(rhs[0][0])
    assert np.all(ru[0, :] == 0.0) and np.all(ru[-1, :] == 0.0)
    sol = solver.solve(rhs)
    assert bool(sol.converged)
    un, vn = np.asarray(sol.u[0]), np.asarray(sol.u[1])
    assert np.max(np.abs(un[0, :])) < 1e-12       # no-slip wall faces
    assert np.max(np.abs(un[-1, :])) < 1e-12
    # the lid drives a recirculating flow
    assert np.max(np.abs(un)) > 0.05
    assert np.max(np.abs(vn)) > 0.01
    assert np.max(np.abs(np.asarray(solver.divergence(sol.u)))) < 1e-8


def test_f32_convergence_regression():
    """The production (f32) solve must actually converge: regression
    for jnp.linalg.lstsq's default rcond truncating the essential
    singular direction of the Hessenberg under a strongly-scaled
    preconditioner (observed: FGMRES made ZERO progress in f32)."""
    nx, ny = 32, 16
    y = (np.arange(ny) + 0.5) / ny
    profile = 4.0 * y * (1.0 - y)
    solver = StaggeredStokesSolver((nx, ny), (2.0 / nx, 1.0 / ny),
                                   channel_bc(2), alpha=200.0, mu=0.05,
                                   tol=1e-5, dtype=jnp.float32)
    assert solver.dtype == jnp.float32
    rhs = solver.make_rhs(bdry={(0, 0, 0): jnp.asarray(
        profile, jnp.float32)[None, :], (1, 0, 0): 0.0})
    sol = solver.solve(rhs)
    # f32 residual floors near 1e-3 absolute from a zero start (tol
    # 1e-5 relative is below the floor), but the solve must make REAL
    # progress: the stuck solver gave res ~ |b| = 2.9 and u ~ 1e-6
    assert float(sol.resnorm) < 1e-2
    assert float(jnp.max(jnp.abs(sol.u[0]))) > 0.5


def test_periodic_transverse_axis():
    """Channel with a periodic spanwise axis mixes periodic + wall +
    open handling in one solve."""
    bc = StokesBC(axes=((VelocitySide(INFLOW), VelocitySide(OPEN)),
                        None))
    n = (16, 16)
    solver = StaggeredStokesSolver(n, (1.0 / 16, 1.0 / 16), bc,
                                   alpha=1.0, mu=0.1, tol=1e-10)
    u, p = _random_state(solver, seed=3)
    rhs = solver.operator((u, p))
    sol = solver.solve(rhs)
    assert bool(sol.converged)
    for a, b in zip(sol.u, u):
        assert np.max(np.abs(np.asarray(a - b))) < 1e-6
