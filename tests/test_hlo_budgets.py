"""Fast-tier HLO/jaxpr budget regression (round 6).

Pins the two structural guarantees the fused spectral substep makes at
compile time, on a small grid so the tier runs in seconds:

- the flagship IB step's jaxpr contains at most TWO batched ``fft``
  primitives for the fluid substep (one forward rfftn, one inverse
  irfftn) plus none smuggled in elsewhere, and
- the optimized HLO of the full step contains ZERO scatter ops (the
  round-5 gather-based force assembly + the k-space-resident solve
  leave nothing to scatter).

These are jaxpr/HLO censuses, not timings — backend-independent and
safe for the CPU CI tier (CPU lowers lax.fft to a ducc custom-call, so
the FFT census MUST run at the jaxpr level; the scatter census runs on
the optimized HLO text).
"""

import jax
import jax.numpy as jnp

from ibamr_tpu.models.shell3d import build_shell_example


def _subjaxprs(params):
    for v in params.values():
        if isinstance(v, jax.core.ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, jax.core.Jaxpr):
            yield v
        elif isinstance(v, (tuple, list)):
            for w in v:
                if isinstance(w, jax.core.ClosedJaxpr):
                    yield w.jaxpr
                elif isinstance(w, jax.core.Jaxpr):
                    yield w


def count_fft(jaxpr) -> int:
    n = 0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "fft":
            n += 1
        for sub in _subjaxprs(eqn.params):
            n += count_fft(sub)
    return n


def _build(n=32):
    # explicit use_fast_interaction bypasses the auto-engine size
    # eligibility gate so the fast tier exercises the flagship path
    integ, st = build_shell_example(n_cells=n, n_lat=8, n_lon=16,
                                    use_fast_interaction="packed")
    return integ, st


def test_step_jaxpr_fft_budget():
    integ, st = _build()
    assert integ.ins.fused_stokes is not None   # flagship fused path on
    jaxpr = jax.make_jaxpr(lambda s: integ.step(s, 1e-3))(st)
    n_fft = count_fft(jaxpr.jaxpr)
    # one batched rfftn + one batched irfftn; anything more means the
    # substep fell off the k-space-resident path (e.g. back to the
    # chained per-field solves, which cost 8)
    assert 1 <= n_fft <= 2, f"fft primitive count {n_fft}, budget 2"


def test_step_jaxpr_fft_budget_chained_is_worse():
    # the guard itself: disabling fusion must blow the budget, so the
    # test above cannot pass vacuously
    integ, st = _build(n=16)
    integ.ins.fused_stokes = None
    jaxpr = jax.make_jaxpr(lambda s: integ.step(s, 1e-3))(st)
    assert count_fft(jaxpr.jaxpr) > 2


def test_step_hlo_zero_scatter():
    import sys
    import os
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    from hlo_cost_audit import hlo_op_counts

    integ, st = _build(n=16)
    compiled = jax.jit(lambda s: integ.step(s, 1e-3)).lower(st).compile()
    ops = hlo_op_counts(compiled.as_text())
    scatter = sum(v for k, v in ops.items() if k.startswith("scatter"))
    assert scatter == 0, f"scatter ops leaked into the step HLO: {ops}"


def test_bf16_step_same_fft_budget():
    integ, st = build_shell_example(n_cells=16, n_lat=8, n_lon=16,
                                    use_fast_interaction="packed",
                                    spectral_dtype="bf16")
    jaxpr = jax.make_jaxpr(lambda s: integ.step(s, 1e-3))(st)
    # mixed precision changes operand dtypes, never transform count
    assert 1 <= count_fft(jaxpr.jaxpr) <= 2
