"""Elastic warm pools: the traffic-driven autoscaler, brownout mode
ladder, bytes-aware executable cache, and crash-safe restart (PR 18).

Estimator, mode-ladder, and scaling-policy tests run against a stub
router over VIRTUAL time — no jax, no compiles, no sleeps — so the
hysteresis/dwell arithmetic is pinned deterministically. Router-level
tests (grow never blocks serving, cruise cap, shed_batch) share one
module-scoped warm pool, the same budget discipline as
tests/test_traffic.py. The multi-second restart drill and the full
elastic smoke are slow-tier; CI covers them via ``tools/slo.py check
--elastic`` and dryrun path 22.
"""

import os
import time

import pytest

from ibamr_tpu import obs
from ibamr_tpu.serve.autoscale import (MODES, ElasticPoolManager,
                                       MixEstimator, ScalePolicy,
                                       read_serving_manifest,
                                       restore_serving_manifest)
from ibamr_tpu.serve.router import BucketSpec, ScenarioRequest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_N, _N_LAT, _N_LON = 8, 6, 8


def _req(tag, **kw):
    kw.setdefault("steps", 2)
    return ScenarioRequest(tenant=tag, n_cells=_N, n_lat=_N_LAT,
                           n_lon=_N_LON, **kw)


# ---------------------------------------------------------------------------
# mix estimator (pure virtual time — no jax)
# ---------------------------------------------------------------------------

def test_mix_estimator_is_deterministic():
    """The mix is a pure function of the (family, t) stream: replaying
    the stream replays the estimate bit-for-bit."""
    stream = [("a", 0.1), ("a", 0.3), ("b", 0.6), ("a", 0.7),
              ("b", 1.2), ("b", 1.3), ("b", 1.9), ("a", 2.4)]

    def run():
        est = MixEstimator(window_s=0.5, alpha=0.5)
        for fam, t in stream:
            est.observe(fam, t)
        return est.mix()

    m1, m2 = run(), run()
    assert m1 == m2
    assert set(m1) == {"a", "b"}
    assert abs(sum(m1.values()) - 1.0) < 1e-9


def test_mix_estimator_tracks_a_shift():
    est = MixEstimator(window_s=0.5, alpha=0.5)
    for i in range(10):
        est.observe("old", i * 0.25)
    assert est.mix()["old"] == pytest.approx(1.0)
    for i in range(10):
        est.observe("new", 2.5 + i * 0.25)
    mix = est.mix()
    assert mix["new"] > 0.8
    assert mix.get("old", 0.0) < 0.2


def test_mix_estimator_idle_advance_decays_partial_window():
    """advance() without arrivals rolls empty windows into the EWMA so
    an idle stream ages the estimate the same way observe() would."""
    est = MixEstimator(window_s=0.5, alpha=0.5)
    est.observe("a", 0.1)
    est.observe("b", 0.2)
    before = dict(est.mix())
    est.advance(10.0)          # 19 empty windows
    after = est.mix()
    # proportions survive (normalized), but raw mass decayed: a new
    # arrival now dominates immediately
    assert set(after) <= set(before)
    est.observe("c", 10.1)
    est.advance(11.0)
    assert est.mix()["c"] > 0.9


def test_mix_estimator_arrival_totals():
    est = MixEstimator()
    for i in range(7):
        est.observe("x", i * 0.1)
    est.observe("y", 1.0)
    assert est.arrivals("x") == 7
    assert est.arrivals("y") == 1
    assert est.arrivals("z") == 0


# ---------------------------------------------------------------------------
# stub router: scaling policy over virtual time (no jax)
# ---------------------------------------------------------------------------

class _StubCache:
    max_bytes = None

    def __init__(self):
        self.released = []
        self.directory = None

    def bytes(self):
        return 0

    def release(self, keys):
        self.released.extend(keys)
        return len(list(keys))


class _StubRouter:
    """The manager-facing slice of WarmPoolRouter: pools are entries
    in a dict, builds publish through a gateable wait() callable."""

    def __init__(self, families=()):
        self.cache = _StubCache()
        self.default_lanes = 2
        self.manager = None
        self.inflight = {}
        self.backlog = 0
        self.build_gate = None      # Event: builds block until set
        self.admission = type("_A", (), {"_policies": {}})()
        self._pools = {}
        for fam in families:
            self._pools[fam] = self._spec(fam)

    def _spec(self, family):
        return BucketSpec(n_cells=family[0], n_lat=family[1],
                          n_lon=family[2], engine=family[3],
                          spectral_dtype=family[4], mu=family[5],
                          lanes=self.default_lanes)

    def live_families(self):
        return dict(self._pools)

    def live_specs(self):
        return list(self._pools.values())

    def family_inflight(self, family):
        return self.inflight.get(family, 0)

    def build_backlog(self):
        return self.backlog

    def _bucket_for(self, family, count):
        return self._spec(family)

    def _ensure_pool(self, spec, trace_ids=()):
        gate = self.build_gate

        def wait(timeout=None):
            if gate is not None:
                gate.wait(10.0)
            self._pools[spec.family()] = spec
            return spec

        return wait

    def release_pool(self, spec):
        self._pools.pop(spec.family(), None)
        return self.cache.release([str(spec.family())])

    def drain_builds(self, timeout_s=60.0):
        return 0


_FAM_A = (8, 6, 8, None, None, 0.05)
_FAM_B = (8, 6, 12, None, None, 0.05)


def _stub_manager(families=(_FAM_A,), **policy_kw):
    policy_kw.setdefault("window_s", 0.5)
    policy_kw.setdefault("grow_share", 0.10)
    policy_kw.setdefault("grow_min_arrivals", 2)
    policy_kw.setdefault("min_dwell_s", 2.0)
    policy_kw.setdefault("mode_min_dwell_s", 1.0)
    router = _StubRouter(families)
    mgr = ElasticPoolManager(router, policy=ScalePolicy(**policy_kw))
    return router, mgr


def _admit(mgr, family, t):
    req = ScenarioRequest(tenant="t", n_cells=family[0],
                          n_lat=family[1], n_lon=family[2],
                          engine=family[3], spectral_dtype=family[4],
                          mu=family[5])
    mgr.observe_admit(req, t=t)


def test_grow_triggers_on_hot_unseen_family():
    router, mgr = _stub_manager()
    for i in range(6):
        _admit(mgr, _FAM_B, 0.1 * i + 0.05)
    mgr.tick(t=1.5)
    mgr.drain(timeout_s=5.0)
    assert _FAM_B in router.live_families()
    actions = [e["action"] for e in mgr.scale_events]
    assert "grow" in actions and "warmed" in actions


def test_grow_needs_min_arrivals():
    router, mgr = _stub_manager(grow_min_arrivals=5)
    for i in range(3):
        _admit(mgr, _FAM_B, 0.2 * i)
    mgr.tick(t=2.0)
    assert _FAM_B not in router.live_families()
    assert not any(e["action"] == "grow" for e in mgr.scale_events)


def test_grow_respects_max_live_families():
    router, mgr = _stub_manager(max_live_families=1)
    for i in range(6):
        _admit(mgr, _FAM_B, 0.1 * i)
    mgr.tick(t=2.0)
    assert _FAM_B not in router.live_families()


def test_shrink_waits_out_min_dwell_then_fires():
    """Hysteresis: a family that just served is NOT shrunk inside
    min_dwell_s even at zero share; it is shrunk after."""
    router, mgr = _stub_manager(families=(_FAM_A, _FAM_B),
                                min_dwell_s=2.0, shrink_share=0.3)
    _admit(mgr, _FAM_A, 0.1)      # last activity on A at t=0.1
    for i in range(15):           # B owns the mix, t in [0.5, 1.9]
        _admit(mgr, _FAM_B, 0.5 + 0.1 * i)
    mgr.tick(t=2.0)               # A idle 1.9s < dwell: survives
    assert _FAM_A in router.live_families()
    mgr.tick(t=2.2)               # A idle 2.1s >= dwell: shrunk
    assert _FAM_A not in router.live_families()
    shrink = [e for e in mgr.scale_events if e["action"] == "shrink"]
    assert [e["family"] for e in shrink] == [str(_FAM_A)]
    assert router.cache.released  # executables were released


def test_shrink_never_evicts_family_currently_serving():
    router, mgr = _stub_manager(families=(_FAM_A, _FAM_B),
                                min_dwell_s=0.5)
    router.inflight[_FAM_A] = 1   # a batch is on A's pool right now
    for i in range(20):
        _admit(mgr, _FAM_B, 0.1 * i)
    mgr.tick(t=30.0)
    assert _FAM_A in router.live_families()
    router.inflight[_FAM_A] = 0
    mgr.tick(t=30.5)
    assert _FAM_A not in router.live_families()


def test_shrink_never_scales_to_zero():
    router, mgr = _stub_manager(families=(_FAM_A,), min_dwell_s=0.1,
                                idle_evict_s=1.0)
    _admit(mgr, _FAM_A, 0.0)
    mgr.tick(t=100.0)             # idle far past every horizon
    assert len(router.live_families()) == 1


def test_idle_evicted_family_is_not_regrown_on_stale_share():
    """The shrink->grow oscillation guard: after an idle eviction the
    family's normalized share is still high (nothing else arrived),
    but the grow loop must not re-grow it on that stale share."""
    router, mgr = _stub_manager(families=(_FAM_A, _FAM_B),
                                min_dwell_s=0.5, idle_evict_s=2.0)
    _admit(mgr, _FAM_A, 0.0)
    _admit(mgr, _FAM_A, 0.1)
    for i in range(4):
        _admit(mgr, _FAM_B, 0.2 + 0.1 * i)
    mgr.tick(t=5.0)               # both idle >= 2s: A evicted
    assert _FAM_A not in router.live_families()
    for t in (5.5, 6.0, 6.5):
        mgr.tick(t=t)
    assert _FAM_A not in router.live_families()
    grows = [e for e in mgr.scale_events
             if e["action"] == "grow" and e["family"] == str(_FAM_A)]
    assert not grows


def test_grow_decision_is_ledgered_with_mix_snapshot(tmp_path):
    lp = str(tmp_path / "ledger.jsonl")
    with obs.ledger(lp):
        router, mgr = _stub_manager()
        for i in range(6):
            _admit(mgr, _FAM_B, 0.1 * i)
        mgr.tick(t=1.5)
        mgr.drain(timeout_s=5.0)
    recs = [r for r in obs.read_ledger(lp)
            if r.get("kind") == "pool_scale"]
    grow = next(r for r in recs if r["action"] == "grow")
    assert grow["family"] == str(_FAM_B)
    assert grow["reason"]
    assert isinstance(grow["mix"], dict) and grow["mix"]
    warmed = next(r for r in recs if r["action"] == "warmed")
    assert warmed["warm_s"] >= 0.0


# ---------------------------------------------------------------------------
# brownout mode ladder (pressure_fn override — virtual time, no jax)
# ---------------------------------------------------------------------------

def _pressured_manager(**policy_kw):
    policy_kw.setdefault("mode_min_dwell_s", 1.0)
    router = _StubRouter((_FAM_A,))
    pressure = {"queue_p99_s": 0.0, "backlog": 0, "cache_frac": 0.0}
    mgr = ElasticPoolManager(router, policy=ScalePolicy(**policy_kw),
                             pressure_fn=lambda: dict(pressure))
    return router, mgr, pressure


def test_mode_ladder_escalates_immediately_and_exits_after_dwell():
    _, mgr, p = _pressured_manager()
    assert mgr.mode == "healthy"
    p["queue_p99_s"] = 2.0                 # over brownout threshold
    mgr.tick(t=0.1)
    assert mgr.mode == "brownout"          # escalation: immediate
    p["queue_p99_s"] = 0.0
    mgr.tick(t=0.5)                        # dwell 0.4s < 1.0s
    assert mgr.mode == "brownout"
    mgr.tick(t=1.2)                        # dwell satisfied
    assert mgr.mode == "healthy"


def test_mode_ladder_escalates_to_shed_batch_and_steps_down():
    _, mgr, p = _pressured_manager()
    p["queue_p99_s"] = 10.0                # over the shed threshold
    mgr.tick(t=0.1)
    mgr.tick(t=0.2)                        # one rung per tick, no dwell
    assert mgr.mode == "shed_batch"
    p["queue_p99_s"] = 0.5                 # below brownout entry...
    mgr.tick(t=2.0)
    assert mgr.mode == "brownout"          # ...one rung at a time
    p["queue_p99_s"] = 0.0
    mgr.tick(t=4.0)
    assert mgr.mode == "healthy"
    assert [(a, b) for _, a, b in mgr.transitions] == [
        ("healthy", "brownout"), ("brownout", "shed_batch"),
        ("shed_batch", "brownout"), ("brownout", "healthy")]


def test_mode_dead_band_holds_between_exit_and_entry():
    """Pressure between the exit and entry thresholds changes
    nothing in either direction — the anti-flap dead band."""
    _, mgr, p = _pressured_manager()
    p["queue_p99_s"] = 2.0
    mgr.tick(t=0.1)
    assert mgr.mode == "brownout"
    p["queue_p99_s"] = 0.5      # exit needs < 0.25, entry needs >= 1.0
    for t in (1.5, 3.0, 9.0):
        mgr.tick(t=t)
        assert mgr.mode == "brownout"
    assert len(mgr.transitions) == 1


def test_mode_oscillation_bounded_by_dwell():
    """Square-wave pressure faster than the dwell cannot produce more
    than one transition per dwell window."""
    _, mgr, p = _pressured_manager(mode_min_dwell_s=2.0)
    for i in range(40):
        t = 0.1 * (i + 1)
        p["queue_p99_s"] = 2.0 if i % 2 == 0 else 0.0
        mgr.tick(t=t)
    # 4s of virtual time, 2s de-escalation dwell: at most 1 entry +
    # 2 exits could ever fit; flapping would produce ~20
    assert len(mgr.transitions) <= 3


def test_backlog_and_cache_watermark_trip_brownout():
    _, mgr, p = _pressured_manager(brownout_backlog=2)
    p["backlog"] = 2
    mgr.tick(t=0.1)
    assert mgr.mode == "brownout"
    p["backlog"] = 0
    mgr.tick(t=2.0)
    assert mgr.mode == "healthy"
    p["cache_frac"] = 0.95
    mgr.tick(t=2.1)
    assert mgr.mode == "brownout"


def test_should_shed_and_cruise_cap_by_mode():
    _, mgr, p = _pressured_manager()
    assert not mgr.should_shed("batch")
    assert mgr.cruise_cap(["batch"]) is None
    p["queue_p99_s"] = 2.0
    mgr.tick(t=0.1)                        # brownout
    assert not mgr.should_shed("batch")    # brownout caps, not sheds
    assert mgr.cruise_cap(["batch", "batch"]) == 1
    assert mgr.cruise_cap(["batch", "interactive"]) is None
    p["queue_p99_s"] = 10.0
    mgr.tick(t=0.2)                        # shed_batch
    assert mgr.should_shed("batch")
    assert not mgr.should_shed("interactive")


def test_brownout_defers_non_urgent_grow_and_resumes_on_healthy():
    router, mgr, p = _pressured_manager(grow_share=0.05,
                                        urgent_share=0.9,
                                        grow_min_arrivals=1)
    p["queue_p99_s"] = 2.0
    mgr.tick(t=0.05)
    assert mgr.mode == "brownout"
    for i in range(3):                     # B hot but not urgent-hot:
        _admit(mgr, _FAM_A, 0.1 + 0.1 * i)   # A first keeps B's blended
        _admit(mgr, _FAM_B, 0.15 + 0.1 * i)  # share at 0.5 < urgent 0.9
    assert _FAM_B not in router.live_families()
    assert any(e["action"] == "deferred" for e in mgr.scale_events)
    p["queue_p99_s"] = 0.0
    mgr.tick(t=2.0)                        # healthy: deferred resumes
    assert mgr.mode == "healthy"
    mgr.drain(timeout_s=5.0)
    assert _FAM_B in router.live_families()
    resumed = [e for e in mgr.scale_events
               if e["action"] == "grow"
               and e["reason"] == "deferred_resume"]
    assert resumed


def test_serve_mode_gauge_tracks_ladder_index():
    _, mgr, p = _pressured_manager()
    p["queue_p99_s"] = 2.0
    mgr.tick(t=0.1)
    snap = obs.metrics_snapshot()["gauges"]
    assert snap["serve_mode"] == MODES.index("brownout")
    obs.reset_metrics()


# ---------------------------------------------------------------------------
# serving manifest (stub router — no jax)
# ---------------------------------------------------------------------------

def test_manifest_round_trip_and_digest_guard(tmp_path):
    router, mgr = _stub_manager(families=(_FAM_A, _FAM_B))
    mp = str(tmp_path / "serving_manifest.json")
    mgr.manifest_path = mp
    mgr.save_manifest()
    body = read_serving_manifest(mp)
    fams = {tuple(BucketSpec(**f).family()) for f in body["families"]}
    assert fams == {_FAM_A, _FAM_B}
    assert body["mode"] == "healthy"
    assert body["scale_digest"]
    # a flipped byte is refused, never restored wrong
    raw = open(mp).read().replace('"mode": "healthy"',
                                  '"mode": "healthy "')
    with open(mp, "w") as f:
        f.write(raw)
    with pytest.raises(ValueError):
        read_serving_manifest(mp)


def test_manifest_scale_digest_tracks_history(tmp_path):
    router, mgr = _stub_manager()
    d0 = mgr.manifest()["scale_digest"]
    for i in range(6):
        _admit(mgr, _FAM_B, 0.1 * i)
    mgr.tick(t=1.5)
    mgr.drain(timeout_s=5.0)
    assert mgr.manifest()["scale_digest"] != d0


# ---------------------------------------------------------------------------
# bytes-aware executable cache (PR 18 satellite — no compiles needed)
# ---------------------------------------------------------------------------

def test_cache_bytes_accounting_and_release(tmp_path):
    from ibamr_tpu.serve.aot_cache import CacheEntry, ExecutableCache
    cache = ExecutableCache(directory=str(tmp_path))
    # inject entries directly: bytes accounting is pure bookkeeping
    with cache._lock:
        for i, size in enumerate((100, 250)):
            cache._entries[f"k{i}"] = CacheEntry(
                key=f"k{i}", executable=object(),
                built_at=time.time(), size_bytes=size)
            cache._stats["bytes"] += size
            cache._set_bytes_gauge_locked()
    assert cache.bytes() == 350
    assert obs.metrics_snapshot()["gauges"]["aot_cache_bytes"] == 350
    dropped = cache.release(["k0", "missing"])
    assert dropped == 1
    assert cache.bytes() == 250
    assert cache.stats()["released"] == 1
    obs.reset_metrics()


def test_cache_max_bytes_evicts_lru_first(tmp_path):
    from ibamr_tpu.serve.aot_cache import CacheEntry, ExecutableCache
    cache = ExecutableCache(directory=str(tmp_path))
    with cache._lock:
        for i in range(4):
            cache._entries[f"k{i}"] = CacheEntry(
                key=f"k{i}", executable=object(),
                built_at=time.time(), size_bytes=100)
            cache._stats["bytes"] += 100
    evicted = cache.set_max_bytes(150)      # k3 is newest (insertion)
    assert evicted == 3
    assert list(cache.keys()) == ["k3"]
    assert cache.bytes() == 100
    # restoring a roomier ceiling evicts nothing further
    assert cache.set_max_bytes(None) == 0
    obs.reset_metrics()


def test_estimate_executable_bytes_falls_back_gracefully():
    from ibamr_tpu.serve.aot_cache import estimate_executable_bytes

    class _WithMem:
        def memory_analysis(self):
            class _M:
                generated_code_size_in_bytes = 1234
            return _M()

    class _WithText:
        def as_text(self):
            return "x" * 77

    assert estimate_executable_bytes(_WithMem()) == 1234
    assert estimate_executable_bytes(_WithText()) == 77
    assert estimate_executable_bytes(object()) == 0


# ---------------------------------------------------------------------------
# loadgen: family overrides + piecewise mix schedule (PR 18 satellite)
# ---------------------------------------------------------------------------

def test_mix_schedule_default_replays_pre_pr18_schedule():
    from ibamr_tpu.serve.loadgen import poisson_burst_schedule
    a = poisson_burst_schedule(seed=3, duration_s=4.0, rate_rps=6.0)
    b = poisson_burst_schedule(seed=3, duration_s=4.0, rate_rps=6.0,
                               mix_schedule=None)
    assert [(x.t, x.request) for x in a] == [(x.t, x.request)
                                            for x in b]


def test_mix_schedule_rotates_families_at_the_boundary():
    import dataclasses as dc

    from ibamr_tpu.serve.loadgen import (SCENARIO_MIX,
                                         poisson_burst_schedule)
    shifted = tuple(dc.replace(s, family=(("n_lon", 12),))
                    for s in SCENARIO_MIX)
    arrivals = poisson_burst_schedule(
        seed=0, duration_s=4.0, rate_rps=8.0,
        mix_schedule=[(0.0, SCENARIO_MIX), (0.5, shifted)])
    pre = [a for a in arrivals if a.t < 2.0]
    post = [a for a in arrivals if a.t >= 2.0]
    assert pre and post
    assert all(a.request.n_lon == 8 for a in pre)
    assert all(a.request.n_lon == 12 for a in post)
    # same seed, same split, bit-for-bit
    again = poisson_burst_schedule(
        seed=0, duration_s=4.0, rate_rps=8.0,
        mix_schedule=[(0.0, SCENARIO_MIX), (0.5, shifted)])
    assert [(x.t, x.request) for x in arrivals] == [
        (x.t, x.request) for x in again]


def test_mix_shift_injector_is_deterministic():
    from tools.fault_injection import mix_shift_injector
    a1, fam1 = mix_shift_injector(seed=1, duration_s=3.0,
                                  rate_rps=6.0, shift_frac=0.5)
    a2, fam2 = mix_shift_injector(seed=1, duration_s=3.0,
                                  rate_rps=6.0, shift_frac=0.5)
    assert fam1 == fam2
    assert [(x.t, x.request) for x in a1] == [(x.t, x.request)
                                             for x in a2]
    assert any(str(x.request.family()) == fam1 for x in a1)


def test_memory_pressure_injector_restores_ceiling(tmp_path):
    from ibamr_tpu.serve.aot_cache import ExecutableCache
    from tools.fault_injection import memory_pressure_injector
    cache = ExecutableCache(directory=str(tmp_path), max_bytes=1000)
    with memory_pressure_injector(cache, 10) as evicted:
        assert cache.max_bytes == 10
        assert evicted == [0]              # nothing cached yet
    assert cache.max_bytes == 1000


# ---------------------------------------------------------------------------
# real router: grow never blocks serving + restart drill (compiles)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def live_router(tmp_path_factory):
    from ibamr_tpu.serve.aot_cache import ExecutableCache
    from ibamr_tpu.serve.loadgen import SOAK_POLICIES
    from ibamr_tpu.serve.router import WarmPoolRouter
    cache = ExecutableCache(directory=str(
        tmp_path_factory.mktemp("autoscale_cache")))
    spec = BucketSpec(n_cells=_N, n_lat=_N_LAT, n_lon=_N_LON,
                      lanes=2, chunk_steps=2)
    router = WarmPoolRouter([spec], cache=cache, allow_dynamic=True,
                            policies=dict(SOAK_POLICIES))
    router.warm(spec)
    return router, spec


def test_grow_never_blocks_serving(live_router, tmp_path):
    """While a grow build for an unseen family is in flight (slowed by
    the compile-storm injector), requests to the live family must keep
    completing — proven from ledger seq ordering: warm serves land
    BETWEEN the grow decision and its warm confirmation."""
    from tools.fault_injection import compile_storm_injector
    router, spec = live_router
    mgr = ElasticPoolManager(
        router, policy=ScalePolicy(grow_share=0.05,
                                   grow_min_arrivals=1,
                                   urgent_share=0.0,
                                   min_dwell_s=1e9))
    lp = str(tmp_path / "ledger.jsonl")
    try:
        with obs.ledger(lp), compile_storm_injector(extra_s=1.0):
            for i in range(4):
                _admit(mgr, _FAM_B, 0.05 + 0.1 * i)   # triggers grow
            for i in range(3):
                res = router.serve([_req("live", steps=2)])[0]
                assert res.ok and not res.cold
            mgr.drain(timeout_s=60.0)
            obs.chunk_boundary()
    finally:
        router.manager = None
        obs.reset_metrics()
    recs = list(obs.read_ledger(lp))
    grow_seq = next(r["seq"] for r in recs
                    if r.get("kind") == "pool_scale"
                    and r.get("action") == "grow")
    warm_seq = next(r["seq"] for r in recs
                    if r.get("kind") == "pool_scale"
                    and r.get("action") == "warmed")
    served = [r["seq"] for r in recs if r.get("kind") == "request"
              and not r.get("cold") and r.get("ok")]
    assert any(grow_seq < s < warm_seq for s in served), \
        "no warm serve landed while the grow build was in flight"


def test_restart_drill_zero_fresh_compiles(tmp_path):
    """save_manifest -> fresh router restore: every re-warmed family
    must load from the persistent compile layer (cold_source
    attribution), and the first post-restart serve is warm."""
    from ibamr_tpu.serve import aot_cache
    from ibamr_tpu.serve.loadgen import SOAK_POLICIES
    from ibamr_tpu.serve.router import WarmPoolRouter
    aot_cache.enable_persistent_cache(min_compile_secs=0.0)
    cache = aot_cache.ExecutableCache(
        directory=str(tmp_path / "cache"))
    spec = BucketSpec(n_cells=_N, n_lat=_N_LAT, n_lon=_N_LON,
                      lanes=2, chunk_steps=2)
    router = WarmPoolRouter([spec], cache=cache, allow_dynamic=True,
                            policies=dict(SOAK_POLICIES))
    router.warm(spec)
    mp = str(tmp_path / "serving_manifest.json")
    mgr = ElasticPoolManager(router, manifest_path=mp)
    _admit(mgr, _FAM_A, 0.1)
    mgr.save_manifest()
    mgr.drain(timeout_s=60.0)
    router.manager = None

    router2, mgr2, stats = restore_serving_manifest(mp)
    try:
        assert stats["fresh_compiles"] == 0
        assert stats["persistent_loads"] >= 2    # lengths {1, chunk}
        assert stats["warmed"] == 1 and not stats["errors"]
        res = router2.serve([_req("after", steps=2)])[0]
        assert res.ok and not res.cold and not res.shed
    finally:
        router2.manager = None
        obs.reset_metrics()


def test_run_elastic_smoke_end_to_end(tmp_path):
    """The full dryrun-path-22 drill: mix shift + memory pressure +
    restart, every pinned invariant raised inside."""
    from tools.fault_injection import run_elastic_smoke
    out = run_elastic_smoke(str(tmp_path))
    assert out["elastic_smoke"] == "ok"
    assert out["lost"] == 0
    assert out["restart_fresh_compiles"] == 0
    assert out["grows"] >= 1 and out["shrinks"] >= 1
    assert out["mode_transitions"] <= 6
    assert out["predicted_rps"] is not None
    obs.reset_metrics()
