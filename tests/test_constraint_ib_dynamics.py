"""Inertial (free, density-mismatched) rigid-body dynamics in
ConstraintIB — the time-dependent Newton-Euler completion of P15/P16.

Physics oracles: a heavy disc under gravity sediments (accelerates
downward, approaching drag-limited growth); a light disc rises; the
neutrally-buoyant limit (ratio=1) reproduces the pure momentum
projection bitwise; with no gravity, an impulsively started heavy disc
decelerates monotonically under drag."""

import jax.numpy as jnp
import numpy as np

from ibamr_tpu.integrators.cib import RigidBodies
from ibamr_tpu.integrators.constraint_ib import (ConstraintIBMethod,
                                                 ConstraintIBState,
                                                 advance_constraint_ib,
                                                 fill_disc)
from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
from ibamr_tpu.grid import StaggeredGrid


def _setup(density_ratio=None, gravity=None, n=32, mu=0.05,
           virtual_mass=1.0):
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    ins = INSStaggeredIntegrator(g, mu=mu, rho=1.0)
    X0 = fill_disc((0.5, 0.6), 0.08, 1.0 / n / 2, dtype=ins.dtype)
    bodies = RigidBodies(body_id=jnp.zeros(X0.shape[0], dtype=jnp.int32),
                         n_bodies=1)
    method = ConstraintIBMethod(ins, bodies,
                                density_ratio=density_ratio,
                                gravity=gravity,
                                virtual_mass=virtual_mass)
    return method, method.initialize(X0)


def test_heavy_disc_sediments():
    method, st = _setup(density_ratio=[4.0], gravity=[0.0, -1.0])
    dt = 1e-3
    st = advance_constraint_ib(method, st, dt, 30)
    v30 = float(st.U_body[0, 1])
    st = advance_constraint_ib(method, st, dt, 30)
    v60 = float(st.U_body[0, 1])
    assert v30 < 0.0                  # falls
    assert v60 < v30                  # still accelerating
    # slower than free fall of the excess mass (drag is active):
    # free-fall bound for the blended update: |v| < g*t
    assert abs(v60) < 1.0 * 60 * dt


def test_light_disc_rises():
    method, st = _setup(density_ratio=[0.3], gravity=[0.0, -1.0])
    st = advance_constraint_ib(method, st, 1e-3, 40)
    assert float(st.U_body[0, 1]) > 0.0
    # markers actually moved up
    assert float(jnp.mean(st.X[:, 1])) > 0.6


def test_neutral_ratio_matches_pure_projection():
    m_plain, st0 = _setup()
    m_one, _ = _setup(density_ratio=[1.0], gravity=[0.0, -1.0])
    # give the fluid an initial swirl so the projection is nontrivial
    g = m_plain.ins.grid
    x = np.arange(g.n[0]) / g.n[0]
    u0 = jnp.asarray(0.1 * np.sin(2 * np.pi * x)[:, None]
                     * np.ones(g.n[1])[None, :],
                     dtype=m_plain.ins.dtype)
    ins0 = m_plain.ins.initialize()
    ins0 = ins0._replace(u=(u0, jnp.zeros_like(u0)))
    st = ConstraintIBState(ins=ins0, X=st0.X, U_body=st0.U_body)
    a = advance_constraint_ib(m_plain, st, 1e-3, 5)
    b = advance_constraint_ib(m_one, st, 1e-3, 5)
    # ratio-1 blend: (U + 0*(U_prev + dt g))/1 == U exactly
    assert np.allclose(np.asarray(a.U_body), np.asarray(b.U_body),
                       atol=0.0)
    assert np.allclose(np.asarray(a.X), np.asarray(b.X), atol=0.0)


def test_early_time_added_mass_free_fall():
    """Quantitative pin on the inertial forcing (ADVICE round 2): a
    dense disc released from rest follows the classical added-mass
    early-time solution V(t) = -(s-1)/(s+vm) g t before the wake
    develops (for a 2D cylinder the physical added mass equals the
    displaced mass, vm=1 — the integrator's default). Viscous drag only
    REDUCES |V|, so the analytic slope brackets from above and the
    tolerance band below catches any mis-weighted gravity kick (e.g. a
    (1+vm) inflation or 1/s deflation would leave the band)."""
    s, vm, g, dt = 4.0, 1.0, 1.0, 5e-4
    method, st = _setup(density_ratio=[s], gravity=[0.0, -g],
                        virtual_mass=vm)
    # step 1 from rest: fluid and body both quiescent, so the update is
    # EXACTLY V_1 = -a dt g with a = (s-1)/(s+vm) — any mis-weighted
    # gravity kick (the (1+vm)-inflated or 1/s-deflated variants) fails
    # this to machine precision
    st1 = advance_constraint_ib(method, st, dt, 1)
    a = (s - 1.0) / (s + vm)
    np.testing.assert_allclose(float(st1.U_body[0, 1]), -a * dt * g,
                               rtol=1e-5)
    # short trajectory: bracketed by the inviscid added-mass fall from
    # above and a 35% drag allowance below (Basset + potential-flow
    # reaction through the projection act from the first steps)
    steps = 16
    st16 = advance_constraint_ib(method, st, dt, steps)
    v = float(st16.U_body[0, 1])
    v_exact = -a * g * (steps * dt)
    assert v < 0.0
    assert v >= v_exact * 1.02          # never faster than inviscid fall
    assert v <= v_exact * 0.65          # within 35% of it this early


def test_impulsive_heavy_disc_decelerates_under_drag():
    method, st = _setup(density_ratio=[5.0], gravity=None, mu=0.1)
    st = ConstraintIBState(ins=st.ins, X=st.X,
                           U_body=st.U_body.at[0, 0].set(0.2))
    speeds = []
    for _ in range(4):
        st = advance_constraint_ib(method, st, 1e-3, 10)
        speeds.append(float(jnp.abs(st.U_body[0, 0])))
    assert all(b < a for a, b in zip(speeds, speeds[1:]))
    assert speeds[0] < 0.2            # drag from the start
