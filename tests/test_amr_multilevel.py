"""L-level nested AMR (T4/S4 beyond two levels) + T4/T5 diagnostics.

Oracles: exact composite conservation over a 3-level hierarchy with
advection + diffusion (the reflux correctness proof), accuracy against
a uniform-fine reference on a smooth profile, strain-rate analytic
checks, and multi-width Robin ghost fills reproducing exact linear
profiles layer by layer."""

import jax.numpy as jnp
import numpy as np

from ibamr_tpu.amr import FineBox
from ibamr_tpu.amr_multilevel import MultiLevelAdvDiff, build_hierarchy
from ibamr_tpu.bc import (DomainBC, dirichlet_axis, fill_ghosts_cc,
                          neumann_axis, robin_axis)
from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops.stencils import (strain_rate_cc,
                                    strain_rate_magnitude_cc)


def _gauss(c):
    X, Y = c
    return jnp.exp(-((X - 0.4) ** 2 + (Y - 0.5) ** 2) / 0.02)


def _vel(mesh):
    # constant advection velocity (u, v)
    return (0.7 + 0.0 * mesh[0], 0.3 + 0.0 * mesh[1])


def _three_level(n=32, kappa=0.002, scheme="centered"):
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    boxes = [FineBox(lo=(n // 4, n // 4), shape=(n // 2, n // 2)),
             FineBox(lo=(n // 4, n // 4), shape=(n // 2, n // 2))]
    return MultiLevelAdvDiff(g, boxes, kappa=kappa, scheme=scheme,
                             vel_fn=_vel)


def test_hierarchy_validates_nesting():
    g = StaggeredGrid(n=(32, 32), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    levels = build_hierarchy(
        g, [FineBox(lo=(8, 8), shape=(16, 16)),
            FineBox(lo=(8, 8), shape=(16, 16))])
    assert len(levels) == 3
    assert levels[1].grid.n == (32, 32)
    assert levels[2].grid.n == (32, 32)
    # level-2 spacing is 4x finer than the root
    assert np.isclose(levels[2].grid.dx[0], g.dx[0] / 4)


def test_three_level_conservation():
    """Composite integral conserved to roundoff across an L=3 subcycled
    advance with BOTH advection and diffusion active — requires correct
    refluxing at both CF interfaces."""
    ml = _three_level()
    Qs = ml.initialize(_gauss)
    t0 = float(ml.total(Qs))
    dt = 0.2 / 32   # CFL ~ 0.2 on the root
    for _ in range(20):
        Qs = ml.step(Qs, dt)
    t1 = float(ml.total(Qs))
    assert abs(t1 - t0) < 1e-12 * max(1.0, abs(t0))


def _composite_error_vs_uniform(n, steps):
    """Max error of the 3-level composite level-2 solution against a
    uniform 4x-resolution periodic reference, after ``steps`` root
    steps at fixed physical dt*steps."""
    ml = _three_level(n=n, kappa=0.001)
    Qs = ml.initialize(_gauss)
    gf = StaggeredGrid(n=(4 * n, 4 * n), x_lo=(0.0, 0.0),
                       x_up=(1.0, 1.0))
    ref = MultiLevelAdvDiff(gf, [], kappa=0.001, vel_fn=_vel)
    Qr = ref.initialize(_gauss)

    dt = 0.1 / n          # fixed CFL across resolutions
    for _ in range(steps):
        Qs = ml.step(Qs, dt)
    for _ in range(4 * steps):
        Qr = ref.step(Qr, dt / 4)

    # level-2 covers root cells [3n/8, 5n/8): level-1 lo n/4 plus half
    # of level-2's lo (n/4 level-1 cells = n/8 root cells)
    lo_root = n // 4 + n // 8
    ext = n // 4
    sl = np.s_[4 * lo_root:4 * (lo_root + ext)]
    ref_region = np.asarray(Qr[0])[sl, sl]
    return np.max(np.abs(np.asarray(Qs[2]) - ref_region))


def test_three_level_tracks_uniform_fine_and_converges():
    """The composite solution tracks a uniform 4x reference closely and
    improves under refinement. (The gaussian's tails extend beyond the
    level-2 box, so the comparison includes coarse-level error advected
    through the CF interface — clean 2nd-order ratios are not
    measurable at these sizes; the absolute-accuracy bound plus
    monotone improvement is the meaningful check, with conservation
    tested to roundoff separately.)"""
    e16 = _composite_error_vs_uniform(16, 8)
    e32 = _composite_error_vs_uniform(32, 16)
    assert e32 < 3e-3
    assert e16 > e32


def test_strain_rate_analytic():
    """Linear shear u = (gamma*y, 0): E_xy = gamma/2 exact, diagonal 0,
    |E| = sqrt(2*(2*(gamma/2)^2)) = gamma*sqrt... check both."""
    n = 16
    h = 1.0 / n
    gamma = 0.8
    y_cc = (np.arange(n) + 0.5) * h
    u = jnp.asarray(np.broadcast_to(gamma * y_cc[None, :], (n, n)))
    v = jnp.zeros((n, n))
    E = strain_rate_cc((u, v), (h, h))
    assert np.max(np.abs(np.asarray(E[0][0]))) < 1e-12
    assert np.max(np.abs(np.asarray(E[1][1]))) < 1e-12
    interior = np.s_[:, 1:-1]   # periodic wrap pollutes the y edges
    assert np.max(np.abs(np.asarray(E[0][1])[interior]
                         - gamma / 2)) < 1e-12
    mag = np.asarray(strain_rate_magnitude_cc((u, v), (h, h)))
    assert np.max(np.abs(mag[interior] - gamma)) < 1e-10


def test_multiwidth_ghost_fill_linear_exact():
    """Width-3 fills must extend an affine field exactly for Dirichlet
    and Neumann data consistent with it (each ghost pair straddles the
    face symmetrically, so affine profiles are represented exactly)."""
    n = 8
    h = 1.0 / n
    x = (np.arange(n) + 0.5) * h
    Q = jnp.asarray(np.broadcast_to((2.0 * x)[:, None], (n, n)))
    # axis 0: dirichlet with the exact face values (0 at lo, 2 at hi);
    # axis 1: neumann 0 (field constant along y)
    bc = DomainBC((dirichlet_axis(0.0, 2.0), neumann_axis()))
    for w in (1, 2, 3):
        G = np.asarray(fill_ghosts_cc(Q, bc, (h, h), width=w))
        xg = (np.arange(-w, n + w) + 0.5) * h
        expect = np.broadcast_to((2.0 * xg)[:, None], (n + 2 * w, n + 2 * w))
        assert np.max(np.abs(G - expect)) < 1e-12, w


def test_multiwidth_rejects_oversized_width_and_bad_data():
    """width > field extent raises (no silent truncation), and
    wrongly-sized boundary data raises instead of silently padding."""
    import pytest

    n = 4
    h = 1.0 / n
    Q = jnp.zeros((n, n))
    bc = DomainBC((dirichlet_axis(), dirichlet_axis()))
    with pytest.raises(ValueError):
        fill_ghosts_cc(Q, bc, (h, h), width=n + 1)
    with pytest.raises(ValueError):
        # data sized n-2 along the already-grown axis: misaligned
        fill_ghosts_cc(Q, bc, (h, h),
                       bdry_data={(1, 0): jnp.zeros((n - 2, 1))})


def test_open_channel_varying_lid_profile():
    """Spatially-varying tangential wall data must flow through the
    advection ghosts (regression: broadcast failure on grown slabs)."""
    import jax

    from ibamr_tpu.integrators.ins_open import INSOpenIntegrator
    from ibamr_tpu.solvers.stokes import channel_bc

    nx, ny = 12, 8
    lid = jnp.asarray(0.1 * np.sin(np.pi * np.arange(nx + 1) / nx))
    integ = INSOpenIntegrator((nx, ny), (1.0 / nx, 1.0 / ny),
                              channel_bc(2), mu=0.1, dt=0.01,
                              bdry={(0, 0, 0): 0.3,
                                    (0, 1, 1): lid[:, None]},
                              tol=1e-7)
    st = integ.initialize()
    st = jax.jit(integ.step)(st)
    assert np.all(np.isfinite(np.asarray(st.u[0])))


def test_multiwidth_robin_consistency():
    """Width-2 Robin fill: each ghost pair satisfies the Robin relation
    at the face with its own pair spacing."""
    n = 8
    h = 1.0 / n
    rng = np.random.default_rng(0)
    Q = jnp.asarray(rng.standard_normal((n, n)))
    a, b, g = 2.0, 0.7, 0.3
    bc = DomainBC((robin_axis(a, b, lo=g, hi=g), neumann_axis()))
    G = np.asarray(fill_ghosts_cc(Q, bc, (h, h), width=2))
    Qn = np.asarray(Q)
    for k in (1, 2):
        ghost = G[2 - k, 2:-2]          # k-th lo ghost layer
        interior = Qn[k - 1, :]
        heff = (2 * k - 1) * h
        resid = a * (ghost + interior) / 2 + b * (ghost - interior) / heff
        assert np.max(np.abs(resid - g)) < 1e-12
