"""Fast-diagonalization solver vs dense oracles and the ghost-fill
stencil (consistency between bc.laplacian_cc and the 1D matrices)."""

import math

import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu import bc as bc_mod
from ibamr_tpu.bc import (AxisBC, DomainBC, SideBC, dirichlet_axis,
                          neumann_axis, periodic_axis)
from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.solvers.fastdiag import (FastDiagSolver, laplacian_1d_cc,
                                        laplacian_1d_fc_pinned)


def _grid(n=(16, 12)):
    return StaggeredGrid(n=n, x_lo=(0.0,) * len(n), x_up=(1.0,) * len(n))


def _rand(shape, seed=0):
    return jnp.asarray(np.random.default_rng(seed).standard_normal(shape))


def test_cc_dirichlet_residual():
    """(alpha + beta lap) Q == rhs through the ghost-fill stencil."""
    grid = _grid()
    bc = DomainBC(axes=(dirichlet_axis(), dirichlet_axis()))
    solver = FastDiagSolver(grid, bc, ("cc", "cc"))
    rhs = _rand(grid.n)
    alpha, beta = 3.0, -0.7
    Q = solver.solve(rhs, alpha, beta)
    res = alpha * Q + beta * bc_mod.laplacian_cc(Q, bc, grid.dx) - rhs
    assert float(jnp.max(jnp.abs(res))) < 1e-10


def test_cc_neumann_poisson_residual():
    grid = _grid()
    bc = DomainBC(axes=(neumann_axis(), neumann_axis()))
    solver = FastDiagSolver(grid, bc, ("cc", "cc"))
    rhs = _rand(grid.n, seed=1)
    rhs = rhs - jnp.mean(rhs)          # compatibility
    Q = solver.solve(rhs, 0.0, 1.0, zero_nullspace=True)
    res = bc_mod.laplacian_cc(Q, bc, grid.dx) - rhs
    assert float(jnp.max(jnp.abs(res))) < 1e-10


def test_cc_mixed_periodic_wall_residual():
    """Channel pattern: periodic x, Dirichlet walls y."""
    grid = _grid((16, 16))
    bc = DomainBC(axes=(periodic_axis(), dirichlet_axis()))
    solver = FastDiagSolver(grid, bc, ("cc", "cc"))
    rhs = _rand(grid.n, seed=2)
    alpha, beta = 5.0, -0.2
    Q = solver.solve(rhs, alpha, beta)
    res = alpha * Q + beta * bc_mod.laplacian_cc(Q, bc, grid.dx) - rhs
    assert float(jnp.max(jnp.abs(res))) < 1e-10


def test_cc_mixed_dirichlet_neumann_axis():
    """Different kinds on the two sides of one axis."""
    grid = _grid((8, 10))
    ax1 = AxisBC(SideBC("dirichlet"), SideBC("neumann"))
    bc = DomainBC(axes=(dirichlet_axis(), ax1))
    solver = FastDiagSolver(grid, bc, ("cc", "cc"))
    rhs = _rand(grid.n, seed=3)
    Q = solver.solve(rhs, 2.0, -1.0)
    res = 2.0 * Q - bc_mod.laplacian_cc(Q, bc, grid.dx) - rhs
    assert float(jnp.max(jnp.abs(res))) < 1e-10


def test_fc_pinned_dense_oracle():
    """Normal-velocity centering: interior faces solved, boundary face
    pinned to zero; dense solve of the (n-1) tridiagonal as oracle."""
    n, h = 12, 1.0 / 12
    grid = StaggeredGrid(n=(n,), x_lo=(0.0,), x_up=(1.0,))
    bc = DomainBC(axes=(dirichlet_axis(),))
    solver = FastDiagSolver(grid, bc, ("fc_pinned",))
    rhs = _rand((n,), seed=4)
    alpha, beta = 1.5, -0.3
    Q = solver.solve(rhs, alpha, beta)

    A = laplacian_1d_fc_pinned(n, h)
    dense = np.linalg.solve(alpha * np.eye(n - 1) + beta * A,
                            np.asarray(rhs)[1:])
    assert Q[0] == 0.0
    np.testing.assert_allclose(np.asarray(Q)[1:], dense, rtol=1e-10,
                               atol=1e-12)


def test_cc_dense_oracle_2d():
    """Full 2D dense-kron oracle for the Dirichlet box."""
    n0, n1 = 6, 5
    grid = _grid((n0, n1))
    bc = DomainBC(axes=(dirichlet_axis(), dirichlet_axis()))
    solver = FastDiagSolver(grid, bc, ("cc", "cc"))
    rhs = _rand((n0, n1), seed=5)
    alpha, beta = 0.7, -1.1
    Q = solver.solve(rhs, alpha, beta)

    A0 = laplacian_1d_cc(n0, grid.dx[0], bc.axes[0])
    A1 = laplacian_1d_cc(n1, grid.dx[1], bc.axes[1])
    L = np.kron(A0, np.eye(n1)) + np.kron(np.eye(n0), A1)
    dense = np.linalg.solve(alpha * np.eye(n0 * n1) + beta * L,
                            np.asarray(rhs).ravel()).reshape(n0, n1)
    np.testing.assert_allclose(np.asarray(Q), dense, rtol=1e-9, atol=1e-11)


def test_ghost_fill_values():
    """Dirichlet/Neumann ghost extrapolation formulas."""
    grid = _grid((4, 4))
    Q = jnp.arange(16.0).reshape(4, 4)
    bc = DomainBC(axes=(
        AxisBC(SideBC("dirichlet", 2.0), SideBC("neumann", 3.0)),
        periodic_axis()))
    G = bc_mod.fill_ghosts_cc(Q, bc, grid.dx)
    assert G.shape == (6, 6)
    h = grid.dx[0]
    # lo dirichlet: ghost = 2*g - Q[0]; (interior cols offset by 1)
    np.testing.assert_allclose(np.asarray(G[0, 1:-1]),
                               np.asarray(2.0 * 2.0 - Q[0]))
    # hi neumann (outward normal +): (ghost - Q[-1])/h = g
    np.testing.assert_allclose(np.asarray(G[-1, 1:-1]),
                               np.asarray(Q[-1] + h * 3.0))
    # periodic wrap on axis 1
    np.testing.assert_allclose(np.asarray(G[1:-1, 0]), np.asarray(Q[:, -1]))


def test_analytic_dirichlet_mode():
    """lap of sin(pi x) on a Dirichlet box matches the discrete
    eigenvalue; the solver recovers the mode from its image."""
    n = 32
    grid = StaggeredGrid(n=(n,), x_lo=(0.0,), x_up=(1.0,))
    bc = DomainBC(axes=(dirichlet_axis(),))
    solver = FastDiagSolver(grid, bc, ("cc",))
    x = grid.cell_coords_1d(0, jnp.float64)
    Q = jnp.sin(math.pi * x)
    h = grid.dx[0]
    lam = (2.0 * math.cos(math.pi / n) - 2.0) / h ** 2
    rhs = lam * Q
    got = solver.solve(rhs, 0.0, 1.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(Q), rtol=1e-9,
                               atol=1e-11)
