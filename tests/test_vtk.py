"""VTK writer tests (T15/VisIt replacement): well-formed XML, value
round-trip through the ASCII payload, polyline connectivity, and the
time-series collection index."""

import os
import xml.etree.ElementTree as ET

import numpy as np

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.io.vtk import VizWriter, write_vti, write_vtp


def _data_array(root, name):
    for da in root.iter("DataArray"):
        if da.get("Name") == name:
            return np.array(da.text.split(), dtype=np.float64)
    raise KeyError(name)


def test_vti_scalar_and_vector_roundtrip(tmp_path):
    grid = StaggeredGrid(n=(4, 3), x_lo=(0, 0), x_up=(1, 0.75))
    rng = np.random.RandomState(0)
    p = rng.randn(4, 3)
    u = (rng.randn(4, 3), rng.randn(4, 3))
    path = write_vti(str(tmp_path / "out.vti"), grid,
                     {"p": p, "u": u})
    root = ET.parse(path).getroot()
    img = root.find("ImageData")
    assert img.get("WholeExtent") == "0 4 0 3 0 1"
    assert img.get("Spacing").startswith("0.25 0.25")
    vals = _data_array(root, "p")
    assert np.allclose(vals, p.ravel(order="F"), atol=1e-5)
    vec = _data_array(root, "u").reshape(-1, 3)
    assert np.allclose(vec[:, 0], u[0].ravel(order="F"), atol=1e-5)
    assert np.allclose(vec[:, 2], 0.0)


def test_vtp_markers_and_fibers(tmp_path):
    X = np.array([[0.1, 0.2], [0.3, 0.4], [0.5, 0.6], [0.7, 0.8]])
    F = np.array([[1.0, 0.0], [0.0, 1.0], [1.0, 1.0], [0.5, 0.5]])
    path = write_vtp(str(tmp_path / "m.vtp"), X,
                     point_data={"force": F},
                     lines=[[0, 1, 2], [2, 3]])
    root = ET.parse(path).getroot()
    piece = root.find("PolyData/Piece")
    assert piece.get("NumberOfPoints") == "4"
    assert piece.get("NumberOfLines") == "2"
    conn = _data_array(root, "connectivity")
    offs = _data_array(root, "offsets")
    assert conn.astype(int).tolist() == [0, 1, 2, 2, 3]
    assert offs.astype(int).tolist() == [3, 5]
    frc = _data_array(root, "force").reshape(-1, 3)
    assert np.allclose(frc[:, :2], F, atol=1e-6)


def test_viz_writer_series(tmp_path):
    grid = StaggeredGrid(n=(4, 4), x_lo=(0, 0), x_up=(1, 1))
    w = VizWriter(str(tmp_path / "viz"), grid)
    X = np.random.RandomState(1).rand(5, 2)
    for k, t in ((0, 0.0), (10, 0.1)):
        w.dump(k, t, cell_fields={"p": np.ones((4, 4)) * t},
               markers=X + t, fibers=[[0, 1, 2, 3, 4, 0]])
    names = sorted(os.listdir(tmp_path / "viz"))
    assert "eulerian.pvd" in names and "lagrangian.pvd" in names
    assert "eul_000000.vti" in names and "lag_000010.vtp" in names
    pvd = ET.parse(str(tmp_path / "viz" / "eulerian.pvd")).getroot()
    ds = list(pvd.iter("DataSet"))
    assert len(ds) == 2
    assert ds[1].get("timestep") == "0.1"
    assert ds[1].get("file") == "eul_000010.vti"
