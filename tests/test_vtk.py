"""VTK writer tests (T15/VisIt replacement): well-formed XML, value
round-trip through the ASCII payload, polyline connectivity, and the
time-series collection index."""

import os
import xml.etree.ElementTree as ET

import numpy as np

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.io.vtk import VizWriter, write_vti, write_vtp


def _data_array(root, name):
    for da in root.iter("DataArray"):
        if da.get("Name") == name:
            return np.array(da.text.split(), dtype=np.float64)
    raise KeyError(name)


def test_vti_scalar_and_vector_roundtrip(tmp_path):
    grid = StaggeredGrid(n=(4, 3), x_lo=(0, 0), x_up=(1, 0.75))
    rng = np.random.RandomState(0)
    p = rng.randn(4, 3)
    u = (rng.randn(4, 3), rng.randn(4, 3))
    path = write_vti(str(tmp_path / "out.vti"), grid,
                     {"p": p, "u": u})
    root = ET.parse(path).getroot()
    img = root.find("ImageData")
    assert img.get("WholeExtent") == "0 4 0 3 0 1"
    assert img.get("Spacing").startswith("0.25 0.25")
    vals = _data_array(root, "p")
    assert np.allclose(vals, p.ravel(order="F"), atol=1e-5)
    vec = _data_array(root, "u").reshape(-1, 3)
    assert np.allclose(vec[:, 0], u[0].ravel(order="F"), atol=1e-5)
    assert np.allclose(vec[:, 2], 0.0)


def test_vtp_markers_and_fibers(tmp_path):
    X = np.array([[0.1, 0.2], [0.3, 0.4], [0.5, 0.6], [0.7, 0.8]])
    F = np.array([[1.0, 0.0], [0.0, 1.0], [1.0, 1.0], [0.5, 0.5]])
    path = write_vtp(str(tmp_path / "m.vtp"), X,
                     point_data={"force": F},
                     lines=[[0, 1, 2], [2, 3]])
    root = ET.parse(path).getroot()
    piece = root.find("PolyData/Piece")
    assert piece.get("NumberOfPoints") == "4"
    assert piece.get("NumberOfLines") == "2"
    conn = _data_array(root, "connectivity")
    offs = _data_array(root, "offsets")
    assert conn.astype(int).tolist() == [0, 1, 2, 2, 3]
    assert offs.astype(int).tolist() == [3, 5]
    frc = _data_array(root, "force").reshape(-1, 3)
    assert np.allclose(frc[:, :2], F, atol=1e-6)


def test_viz_writer_series(tmp_path):
    grid = StaggeredGrid(n=(4, 4), x_lo=(0, 0), x_up=(1, 1))
    w = VizWriter(str(tmp_path / "viz"), grid)
    X = np.random.RandomState(1).rand(5, 2)
    for k, t in ((0, 0.0), (10, 0.1)):
        w.dump(k, t, cell_fields={"p": np.ones((4, 4)) * t},
               markers=X + t, fibers=[[0, 1, 2, 3, 4, 0]])
    names = sorted(os.listdir(tmp_path / "viz"))
    assert "eulerian.pvd" in names and "lagrangian.pvd" in names
    assert "eul_000000.vti" in names and "lag_000010.vtp" in names
    pvd = ET.parse(str(tmp_path / "viz" / "eulerian.pvd")).getroot()
    ds = list(pvd.iter("DataSet"))
    assert len(ds) == 2
    assert ds[1].get("timestep") == "0.1"
    assert ds[1].get("file") == "eul_000010.vti"


def test_vtm_hierarchy_roundtrip(tmp_path):
    """AMR multiblock dump: per-level .vti files with each level's own
    origin/spacing, indexed by a .vtm that references them; values
    round-trip through the ascii payload."""
    import xml.etree.ElementTree as ET

    from ibamr_tpu.amr import FineBox
    from ibamr_tpu.io.vtk import write_vtm_hierarchy

    g0 = StaggeredGrid(n=(8, 8), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    box = FineBox(lo=(2, 2), shape=(4, 4))
    g1 = box.fine_grid(g0)
    Q0 = np.arange(64, dtype=np.float32).reshape(8, 8)
    Q1 = np.arange(64, dtype=np.float32).reshape(8, 8) * 2.0
    path = str(tmp_path / "amr.vtm")
    write_vtm_hierarchy(path, [g0, g1], [{"Q": Q0}, {"Q": Q1}])

    root = ET.parse(path).getroot()
    assert root.get("type") == "vtkMultiBlockDataSet"
    refs = [ds.get("file") for ds in root.iter("DataSet")]
    assert refs == ["amr_L0.vti", "amr_L1.vti"]

    l1 = ET.parse(str(tmp_path / "amr_L1.vti")).getroot()
    img = l1.find("ImageData")
    # level-1 geometry: origin at the box corner, spacing halved
    assert img.get("Origin").split()[0] == "0.25"
    assert float(img.get("Spacing").split()[0]) == g1.dx[0]
    arr = img.find("Piece/CellData/DataArray")
    vals = np.asarray([float(v) for v in arr.text.split()])
    np.testing.assert_allclose(vals, Q1.ravel(order="F"))


def test_vizwriter_hierarchy_series(tmp_path):
    """VizWriter.dump_hierarchy maintains a hierarchy.pvd collection."""
    from ibamr_tpu.amr import FineBox
    from ibamr_tpu.io.vtk import VizWriter

    g0 = StaggeredGrid(n=(8, 8), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    g1 = FineBox(lo=(2, 2), shape=(4, 4)).fine_grid(g0)
    w = VizWriter(str(tmp_path), g0)
    for k in (0, 10):
        w.dump_hierarchy(k, 0.1 * k, [g0, g1],
                         [{"Q": np.zeros((8, 8), np.float32)},
                          {"Q": np.ones((8, 8), np.float32)}])
    pvd = (tmp_path / "hierarchy.pvd").read_text()
    assert "amr_000000.vtm" in pvd and "amr_000010.vtm" in pvd


def test_vtu_unstructured_fe_mesh(tmp_path):
    """write_vtu round-trips the FE element menu as UnstructuredGrid:
    counts, connectivity, cell types, and point data parse back."""
    import xml.etree.ElementTree as ET

    import numpy as np

    from ibamr_tpu.fe.mesh import (box_hex_mesh, disc_mesh,
                                   rect_quad_mesh, to_quadratic)
    from ibamr_tpu.io.vtk import _VTK_CELL_TYPES, write_vtu

    meshes = [disc_mesh(n_rings=3), to_quadratic(disc_mesh(n_rings=2)),
              rect_quad_mesh(3, 2), box_hex_mesh(2, 2, 2)]
    for m in meshes:
        p = write_vtu(str(tmp_path / f"vtu_{m.elem_type}.vtu"), m.nodes, m.elems,
                      m.elem_type,
                      point_data={"disp": np.zeros_like(m.nodes),
                                  "id": np.arange(m.n_nodes)})
        root = ET.parse(p).getroot()
        piece = root.find(".//Piece")
        assert int(piece.get("NumberOfPoints")) == m.n_nodes
        assert int(piece.get("NumberOfCells")) == m.n_elems
        conn = [int(v) for v in root.find(
            ".//DataArray[@Name='connectivity']").text.split()]
        assert conn == [int(v) for v in m.elems.reshape(-1)]
        types = {int(v) for v in root.find(
            ".//DataArray[@Name='types']").text.split()}
        assert types == {_VTK_CELL_TYPES[m.elem_type]}
    import pytest

    with pytest.raises(ValueError, match="unsupported element"):
        write_vtu(str(tmp_path / "bad.vtu"), meshes[0].nodes, meshes[0].elems,
                  "PYRAMID5")
