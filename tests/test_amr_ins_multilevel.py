"""L-level composite INS + IB (VERDICT round 2 item 3): the two-level
composite fluid machinery generalized to arbitrary-depth hierarchies.

Oracles:
- the L-level composite projection drives the composite divergence to
  solver tolerance on random data (3 levels);
- with a single box the L-level integrator reproduces the two-level
  integrator (same scheme, independent implementations);
- a compact vortex doubly refined at the center: the finest region
  tracks a uniform run at the finest resolution far better than the
  coarse run does;
- FGMRES iteration counts stay level-count independent (2 vs 3 levels);
- a membrane inside the FINEST box of a 3-level hierarchy conserves
  area and keeps the composite field div-free.
"""


import jax
import jax.numpy as jnp
import numpy as np

from ibamr_tpu.amr import FineBox
from ibamr_tpu.amr_ins import TwoLevelINS, advance_two_level
from ibamr_tpu.amr_ins_multilevel import (MultiLevelCompositeProjection,
                                          MultiLevelIBINS, MultiLevelINS,
                                          advance_multilevel,
                                          advance_multilevel_ib,
                                          build_hierarchy)
from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ib import IBMethod, polygon_area
from ibamr_tpu.models.membrane2d import make_circle_membrane
from ibamr_tpu.ops import stencils
from ibamr_tpu.ops.convection import convective_rate
from ibamr_tpu.solvers import fft


def _grid(n):
    return StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))


# analytic compact vortex: psi = A exp(-((x-.5)^2+(y-.5)^2)/s^2)
_A, _S = 0.05, 0.08


def _psi(x, y):
    return _A * np.exp(-((x - 0.5) ** 2 + (y - 0.5) ** 2) / _S ** 2)


def _vel(d, mesh):
    x, y = mesh
    if d == 0:     # u = dpsi/dy
        return _psi(x, y) * (-2.0 * (y - 0.5) / _S ** 2)
    return _psi(x, y) * (2.0 * (x - 0.5) / _S ** 2)   # v = -dpsi/dx


def _uniform_run(n, T, steps, mu):
    """Uniform-grid run with the same explicit scheme, analytic init."""
    g = _grid(n)
    comps = []
    for d in range(2):
        coords = []
        for e in range(2):
            if e == d:
                c = np.arange(g.n[e]) * g.dx[e]
            else:
                c = (np.arange(g.n[e]) + 0.5) * g.dx[e]
            coords.append(c)
        mesh = np.meshgrid(*coords, indexing="ij")
        comps.append(jnp.asarray(_vel(d, mesh)))
    u, _ = fft.project_divergence_free(tuple(comps), g.dx)
    dt = T / steps

    def step(u, _):
        lap = stencils.laplacian_vel(u, g.dx)
        nc = convective_rate(u, g.dx, "centered")
        us = tuple(c + dt * (-a + mu * l)
                   for c, a, l in zip(u, nc, lap))
        un, _ = fft.project_divergence_free(us, g.dx)
        return un, None

    u, _ = jax.lax.scan(step, u, None, length=steps)
    return u


_BOXES3 = [FineBox(lo=(8, 8), shape=(16, 16)),
           FineBox(lo=(8, 8), shape=(16, 16))]


def _random_slaved_field(levels, seed=0):
    """Random per-level MAC field with covered parent faces slaved to
    the child restriction — the projection's input contract (matches
    the two-level exact test; the predictor slaves bottom-up too)."""
    from ibamr_tpu.amr import restrict_mac
    from ibamr_tpu.amr_ins import scatter_box_mac_to_coarse

    rng = np.random.default_rng(seed)
    us = []
    for l, spec in enumerate(levels):
        g = spec.grid
        comps = []
        for d in range(2):
            shape = tuple(g.n[e] + (1 if (l > 0 and e == d) else 0)
                          for e in range(2))
            comps.append(jnp.asarray(rng.standard_normal(shape)) * 0.1)
        us.append(tuple(comps))
    for l in range(len(levels) - 2, -1, -1):
        us[l] = scatter_box_mac_to_coarse(us[l], restrict_mac(us[l + 1]),
                                          levels[l + 1].box)
    return us


def test_multilevel_projection_exact():
    levels = build_hierarchy(_grid(32), _BOXES3)
    proj = MultiLevelCompositeProjection(levels, tol=1e-12, m=30,
                                         restarts=20)
    us = _random_slaved_field(levels)
    out, iters = proj.project(us)
    assert float(proj.max_divergence(out)) < 1e-9
    assert int(iters) < 30 * 20


def test_single_box_matches_two_level():
    """L=2 instance vs TwoLevelINS: same scheme, two implementations —
    fields must agree to solver tolerance."""
    mu, dt, steps = 0.002, 6.25e-4, 40
    g = _grid(32)
    box = FineBox(lo=(8, 8), shape=(16, 16))

    ml = MultiLevelINS(g, [box], mu=mu, proj_tol=1e-11)
    st0_ml = ml.initialize(_vel)
    st_ml = advance_multilevel(ml, st0_ml, dt, steps)

    # start TwoLevelINS from the multilevel's own projected initial
    # state so the comparison isolates the step implementations
    tl = TwoLevelINS(g, box, mu=mu, proj_tol=1e-11)
    from ibamr_tpu.amr_ins import TwoLevelINSState
    st_tl = TwoLevelINSState(uc=st0_ml.us[0], uf=st0_ml.us[1],
                             t=jnp.zeros(()), k=jnp.zeros((), jnp.int32))
    st_tl = advance_two_level(tl, st_tl, dt, steps)

    for a, b in zip(st_ml.us[0] + st_ml.us[1], st_tl.uc + st_tl.uf):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-8


def test_vortex_3level_matches_uniform_finest():
    """Doubly-refined center: the finest region must be far closer to
    uniform-128 than uniform-32 is."""
    T, steps, mu = 0.125, 200, 0.002
    u128 = _uniform_run(128, T, steps, mu)
    u32 = _uniform_run(32, T, steps, mu)

    ml = MultiLevelINS(_grid(32), _BOXES3, mu=mu, proj_tol=1e-11)
    st = ml.initialize(_vel)
    st = advance_multilevel(ml, st, T / steps, steps)
    assert float(ml.max_divergence(st)) < 1e-9

    # finest level covers coarse cells [12, 20) = fine-128 cells
    # [48, 80); u-faces of that region on the uniform-128 grid
    uf = st.us[2][0]
    err_3lev = float(jnp.max(jnp.abs(uf - u128[0][48:81, 48:80])))

    # coarse u-face value ~ mean of the 4 coincident fine faces
    # (faces at 4k along x, cell pairs 4k..4k+3 along y)
    sub = u128[0][48:81:4, 48:80]
    u_ref_avg = 0.25 * (sub[:, 0::4] + sub[:, 1::4] + sub[:, 2::4]
                        + sub[:, 3::4])
    err_c32 = float(jnp.max(jnp.abs(u32[0][12:21, 12:20] - u_ref_avg)))
    umax = float(jnp.max(jnp.abs(u128[0])))
    assert err_3lev < 0.35 * err_c32, (err_3lev, err_c32)
    assert err_3lev < 0.03 * umax, (err_3lev, umax)


def test_fgmres_iterations_level_count_independent():
    """The per-level exact-inverse preconditioner must keep FGMRES
    iteration counts flat as depth grows (T8's grid-independence
    property, hierarchy-wide)."""

    def iters_for(boxes):
        levels = build_hierarchy(_grid(32), boxes)
        proj = MultiLevelCompositeProjection(levels, tol=1e-10, m=40,
                                             restarts=10)
        us = _random_slaved_field(levels, seed=1)
        _, iters = proj.project(us)
        return int(iters)

    i2 = iters_for(_BOXES3[:1])
    i3 = iters_for(_BOXES3)
    assert i3 <= max(int(1.6 * i2), i2 + 8), (i2, i3)


def test_membrane_ib_3level():
    """Membrane inside the FINEST box of a 3-level hierarchy: area
    conserved, composite field div-free, markers finite."""
    struct = make_circle_membrane(64, 0.08, (0.5, 0.5), stiffness=2.0,
                                  aspect=1.2, rest_length_factor=0.7)
    ib = IBMethod(struct.force_specs(dtype=jnp.float64), kernel="IB_4")
    integ = MultiLevelIBINS(_grid(32), _BOXES3, ib, rho=1.0, mu=0.02,
                            proj_tol=1e-10)
    st = integ.initialize(jnp.asarray(struct.vertices, jnp.float64))
    a0 = float(polygon_area(st.X))
    st = advance_multilevel_ib(integ, st, 2.5e-4, 200)
    assert float(integ.core.max_divergence(st.fluid)) < 1e-8
    assert abs(float(polygon_area(st.X)) - a0) / a0 < 5e-4
    assert np.all(np.isfinite(np.asarray(st.X)))


def test_fac_multilevel_preconditioner():
    """The L-level FAC V-cycle (solvers.fac.FACMultilevelPoisson) as
    the external preconditioner for the 3-level composite projection:
    converges to the same answer as the exact-inverse default within a
    bounded iteration budget."""
    from ibamr_tpu.solvers.fac import FACMultilevelPoisson

    levels = build_hierarchy(_grid(32), _BOXES3)
    us = _random_slaved_field(levels, seed=2)

    proj_ref = MultiLevelCompositeProjection(levels, tol=1e-10, m=40,
                                             restarts=10)
    out_ref, _ = proj_ref.project(us)

    fac = FACMultilevelPoisson(levels, nu=2)
    proj_fac = MultiLevelCompositeProjection(
        levels, tol=1e-10, m=40, restarts=10,
        preconditioner=fac.precondition)
    out_fac, iters = proj_fac.project(us)

    assert float(proj_fac.max_divergence(out_fac)) < 1e-8
    assert int(iters) < 120, int(iters)
    for a, b in zip(out_ref, out_fac):
        for ca, cb in zip(a, b):
            assert float(jnp.max(jnp.abs(ca - cb))) < 1e-7


def test_multilevel_regrid_tracks_drifting_structure():
    """Moving-window regrid at depth 3 (SURVEY.md §3.4 for L levels):
    a membrane advected by a uniform background flow is tracked by the
    WHOLE window chain; the composite field stays div-free across
    window moves and the membrane area is conserved."""
    from ibamr_tpu.amr_ins_multilevel import (
        advance_multilevel_ib_regridding, regrid_multilevel_ib)

    struct = make_circle_membrane(64, 0.06, (0.4, 0.5), stiffness=1.0)
    ib = IBMethod(struct.force_specs(dtype=jnp.float64), kernel="IB_4")
    # boxes centered on the structure (center x=0.4 -> root cell 12.8,
    # level-1 cell 15.6) so the t=0 regrid is a no-move
    boxes = [FineBox(lo=(5, 8), shape=(16, 16)),
             FineBox(lo=(8, 8), shape=(16, 16))]
    integ = MultiLevelIBINS(_grid(32), boxes, ib, rho=1.0, mu=0.02,
                            proj_tol=1e-10)

    def vel(d, mesh):
        return 0.6 + 0.0 * mesh[0] if d == 0 else 0.0 * mesh[0]

    st = integ.initialize(jnp.asarray(struct.vertices, jnp.float64),
                          vel_fn=vel)
    a0 = float(polygon_area(st.X))

    # no-move fast path: an immediate regrid must return the SAME objects
    integ_same, st_same = regrid_multilevel_ib(integ, st)
    assert integ_same is integ and st_same is st

    integ2, st = advance_multilevel_ib_regridding(
        integ, st, 2.5e-4, 400, regrid_interval=25)
    # the structure drifted ~0.06 of the domain: the chain MUST have moved
    assert integ2 is not integ
    assert integ2.levels[1].box.lo != integ.levels[1].box.lo
    x_center = float(jnp.mean(st.X[:, 0]))
    assert x_center > 0.43, x_center
    assert float(integ2.core.max_divergence(st.fluid)) < 1e-8
    assert abs(float(polygon_area(st.X)) - a0) / a0 < 5e-3
    assert np.all(np.isfinite(np.asarray(st.X)))


def test_multilevel_ib_3d_shell():
    """3-level composite INS/IB in 3D (arbitrary-depth production
    shape): a small shell inside the finest box of a 24^3 root
    hierarchy — composite divergence at solver tolerance, markers
    finite and inside the finest region."""
    from ibamr_tpu.models.shell3d import make_spherical_shell

    g = StaggeredGrid(n=(24,) * 3, x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    s = make_spherical_shell(10, 10, 0.07, (0.5,) * 3, 1.0,
                             rest_length_factor=0.8)
    ib = IBMethod(s.force_specs(dtype=jnp.float64), kernel="IB_4")
    boxes = [FineBox(lo=(6, 6, 6), shape=(12, 12, 12)),
             FineBox(lo=(6, 6, 6), shape=(12, 12, 12))]
    integ = MultiLevelIBINS(g, boxes, ib, mu=0.05, proj_tol=1e-9)
    st = integ.initialize(jnp.asarray(s.vertices, jnp.float64))
    st = advance_multilevel_ib(integ, st, 5e-4, 20)
    assert float(integ.core.max_divergence(st.fluid)) < 1e-7
    X = np.asarray(st.X)
    assert np.isfinite(X).all()
    fg = integ.finest_grid
    assert X.min() > fg.x_lo[0] and X.max() < fg.x_up[0]
