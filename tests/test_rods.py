"""Kirchhoff rod + generalized IB tests (P12): strain measures, energy
invariances, force/torque consistency, and coupled rod relaxation."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.gib import (GeneralizedIBMethod, advance_gib,
                                       couple_force_mac)
from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
from ibamr_tpu.ops.rods import (make_rods, rod_energy, rod_force_torque,
                                rod_strains, rodrigues, rotate_frames,
                                straight_rod)

F64 = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


def _chain_specs(n, ds, b=1.0, kappa=0.0, s=10.0, dtype=F64):
    idx = np.arange(n - 1)
    return make_rods(idx, idx + 1, b, kappa, s, ds, dtype=dtype)


# -- strain measures ---------------------------------------------------------

def test_straight_rod_zero_strain():
    X, D = straight_rod(8, 0.7, dtype=F64)
    specs = _chain_specs(8, 0.1)
    Om, Gam = rod_strains(X, D, specs)
    assert np.allclose(np.asarray(Om), 0.0, atol=1e-6)
    assert np.allclose(np.asarray(Gam), 0.0, atol=1e-6)
    assert float(rod_energy(X, D, specs)) < 1e-10
    F, N = rod_force_torque(X, D, specs)
    assert np.allclose(np.asarray(F), 0.0, atol=1e-5)
    assert np.allclose(np.asarray(N), 0.0, atol=1e-5)


def test_twist_strain_measured():
    n, ds = 9, 0.1
    X, D = straight_rod(n, (n - 1) * ds, dtype=F64)
    rate = 0.8   # rad per unit length about the axis
    w = jnp.stack([jnp.zeros(n), jnp.zeros(n),
                   rate * jnp.arange(n) * ds], axis=-1).astype(F64)
    D_tw = rotate_frames(D, w)
    specs = _chain_specs(n, ds)
    Om, Gam = rod_strains(X, D_tw, specs)
    # twist component Omega_3 ~ rate, bending ~ 0
    assert np.allclose(np.asarray(Om)[:, 2], rate, rtol=2e-2)
    assert np.allclose(np.asarray(Om)[:, :2], 0.0, atol=1e-5)
    assert np.allclose(np.asarray(Gam), 0.0, atol=1e-6)


def test_bend_strain_circle_matches_curvature():
    # rod bent into a circular arc of radius R with frames following the
    # tangent: curvature about D1 (or D2) = 1/R
    n, R = 24, 0.5
    ds_arc = 2 * np.pi * R / 48
    th = np.arange(n) * ds_arc / R
    X = np.stack([np.zeros(n), R * np.cos(th), R * np.sin(th)], axis=1)
    # D3 = tangent, D1 = x-axis, D2 = D3 x D1
    D3 = np.stack([np.zeros(n), -np.sin(th), np.cos(th)], axis=1)
    D1 = np.tile(np.array([1.0, 0.0, 0.0]), (n, 1))
    D2 = np.cross(D3, D1)
    D = np.stack([D1, D2, D3], axis=1)
    specs = _chain_specs(n, ds_arc)
    Om, _ = rod_strains(jnp.asarray(X, dtype=F64),
                        jnp.asarray(D, dtype=F64), specs)
    Om = np.asarray(Om)
    # Omega_1 = dD2/ds . D3 = -1/R for this parametrization (sign conv)
    assert np.allclose(np.abs(Om[:, 0]), 1.0 / R, rtol=2e-2)
    assert np.allclose(Om[:, 1:], 0.0, atol=1e-3)


def test_intrinsic_curvature_equilibrium():
    # with kappa matching the arc's actual curvature, forces vanish
    n, R = 16, 0.5
    ds_arc = 0.05
    th = np.arange(n) * ds_arc / R
    X = np.stack([np.zeros(n), R * np.cos(th), R * np.sin(th)], axis=1)
    D3 = np.stack([np.zeros(n), -np.sin(th), np.cos(th)], axis=1)
    D1 = np.tile(np.array([1.0, 0.0, 0.0]), (n, 1))
    D2 = np.cross(D3, D1)
    D = np.stack([D1, D2, D3], axis=1)
    Xj = jnp.asarray(X, dtype=F64)
    Dj = jnp.asarray(D, dtype=F64)
    # pure bending rod (s=0): the chord-vs-arc length defect would
    # otherwise leave a tiny O(ds^2) stretch energy
    specs0 = _chain_specs(n, ds_arc, s=0.0)
    Om, _ = rod_strains(Xj, Dj, specs0)
    specs = specs0._replace(kappa=Om)   # intrinsic = current
    assert float(rod_energy(Xj, Dj, specs)) < 1e-12
    F, N = rod_force_torque(Xj, Dj, specs)
    assert np.allclose(np.asarray(F), 0.0, atol=1e-6)
    assert np.allclose(np.asarray(N), 0.0, atol=1e-6)


# -- invariances -------------------------------------------------------------

def test_energy_rotation_translation_invariant():
    rng = np.random.RandomState(0)
    n = 10
    X, D = straight_rod(n, 0.9, dtype=F64)
    X = X + 0.02 * jnp.asarray(rng.randn(n, 3), dtype=F64)
    D = rotate_frames(D, 0.1 * jnp.asarray(rng.randn(n, 3), dtype=F64))
    specs = _chain_specs(n, 0.1, kappa=0.2)
    E0 = float(rod_energy(X, D, specs))
    w = jnp.asarray([0.3, -0.2, 0.5], dtype=F64)
    R = rodrigues(w)
    Xr = X @ R.T + jnp.asarray([1.0, -2.0, 0.3], dtype=F64)
    Dr = jnp.einsum("ij,nkj->nki", R, D)
    E1 = float(rod_energy(Xr, Dr, specs))
    assert abs(E1 - E0) < 1e-6 * max(1.0, abs(E0))


def test_total_force_and_torque_balance():
    rng = np.random.RandomState(1)
    n = 12
    X, D = straight_rod(n, 1.1, dtype=F64)
    X = X + 0.05 * jnp.asarray(rng.randn(n, 3), dtype=F64)
    D = rotate_frames(D, 0.2 * jnp.asarray(rng.randn(n, 3), dtype=F64))
    specs = _chain_specs(n, 0.1, kappa=0.3)
    F, N = rod_force_torque(X, D, specs)
    # free rod: net force zero; net torque about origin zero
    # (consequences of translation / rotation invariance of the energy)
    assert np.allclose(np.asarray(jnp.sum(F, axis=0)), 0.0, atol=1e-5)
    tot_torque = jnp.sum(N, axis=0) + jnp.sum(jnp.cross(X, F), axis=0)
    assert np.allclose(np.asarray(tot_torque), 0.0, atol=1e-5)


def test_rodrigues_small_angle_and_orthogonality():
    w = jnp.asarray(np.random.RandomState(2).randn(5, 3) * 0.5, dtype=F64)
    R = rodrigues(w)
    I = jnp.einsum("...ij,...kj->...ik", R, R)
    assert np.allclose(np.asarray(I),
                       np.broadcast_to(np.eye(3), I.shape), atol=1e-6)
    R0 = rodrigues(jnp.zeros(3, dtype=F64))
    assert np.allclose(np.asarray(R0), np.eye(3), atol=1e-8)


# -- torque couple on the grid ----------------------------------------------

def test_couple_force_is_divergence_free_and_zero_mean():
    grid = StaggeredGrid(n=(16, 16, 16), x_lo=(0, 0, 0), x_up=(1, 1, 1))
    rng = np.random.RandomState(3)
    n_cc = tuple(jnp.asarray(rng.randn(16, 16, 16), dtype=F64)
                 for _ in range(3))
    f = couple_force_mac(n_cc, grid)
    from ibamr_tpu.ops import stencils
    div = stencils.divergence(f, grid.dx)
    # curl fields are divergence-free (discretely, by commuting rolls)
    assert float(jnp.max(jnp.abs(div))) < 1e-8
    for comp in f:
        assert abs(float(jnp.sum(comp))) < 1e-8


# -- coupled dynamics --------------------------------------------------------

def test_gib_twisted_rod_relaxes():
    grid = StaggeredGrid(n=(24, 24, 24), x_lo=(0, 0, 0), x_up=(1, 1, 1))
    ins = INSStaggeredIntegrator(grid, rho=1.0, mu=0.1,
                                 convective_op_type="none", dtype=F64)
    n, L = 12, 0.4
    X, D = straight_rod(n, L, origin=(0.5, 0.5, 0.3), dtype=F64)
    # impose an initial twist; intrinsic twist zero -> rod untwists
    rate = 3.0
    w = jnp.stack([jnp.zeros(n), jnp.zeros(n),
                   rate * jnp.arange(n) * L / (n - 1)], axis=-1).astype(F64)
    D = rotate_frames(D, w)
    ds = L / (n - 1)
    specs = _chain_specs(n, ds, b=0.05, s=5.0)
    gib = GeneralizedIBMethod(ins, specs)
    state = gib.initialize(X, D)
    E0 = float(gib.energy(state))
    state = jax.block_until_ready(advance_gib(gib, state, 5e-4, 40))
    E1 = float(gib.energy(state))
    assert np.isfinite(E1) and E1 < E0
    # rod stays intact (no blow-up): node spacing near ds
    seg = np.linalg.norm(np.diff(np.asarray(state.X), axis=0), axis=1)
    assert np.all(seg < 2 * ds) and np.all(seg > 0.3 * ds)
