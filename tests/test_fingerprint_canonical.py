"""Canonical flight-recorder fingerprints (PR 12 satellite).

``run_id`` is the serving cache's key source, so it must be a pure
function of fingerprint CONTENT: dict insertion order cannot move it,
and the compile-identity fields (engine, spectral dtype, mesh, x64)
must each move it.
"""

import jax

from ibamr_tpu.obs import run_id_from_fingerprint
from ibamr_tpu.utils.flight_recorder import FlightRecorder, canonicalize


def _small_integ():
    from ibamr_tpu.models.shell3d import build_shell_example

    integ, _ = build_shell_example(n_cells=8, n_lat=6, n_lon=8,
                                   radius=0.25, aspect=1.2,
                                   stiffness=1.0,
                                   rest_length_factor=0.75, mu=0.05)
    return integ


def test_canonicalize_sorts_keys_recursively():
    a = {"b": 2, "a": {"y": [1, {"q": 0, "p": 1}], "x": 0}}
    b = {"a": {"x": 0, "y": [1, {"p": 1, "q": 0}]}, "b": 2}
    import json
    assert json.dumps(canonicalize(a)) == json.dumps(canonicalize(b))
    # lists keep their order — only mapping keys are canonical
    assert canonicalize({"k": [2, 1]})["k"] == [2, 1]


def test_run_id_insertion_order_invariant():
    integ = _small_integ()
    rec_ab = FlightRecorder(capacity=1,
                            extra_fingerprint={"alpha": 1, "beta": 2})
    rec_ba = FlightRecorder(capacity=1,
                            extra_fingerprint={"beta": 2, "alpha": 1})
    rec_ab.observe(integ=integ)
    rec_ba.observe(integ=integ)
    assert rec_ab.run_id() == rec_ba.run_id()
    # different CONTENT still separates
    rec_c = FlightRecorder(capacity=1,
                           extra_fingerprint={"alpha": 1, "beta": 3})
    rec_c.observe(integ=integ)
    assert rec_c.run_id() != rec_ab.run_id()


def test_run_id_sensitive_to_compile_identity_fields():
    rec = FlightRecorder(capacity=1)
    rec.observe(integ=_small_integ())
    fp = rec.fingerprint()
    base = run_id_from_fingerprint(fp)
    for mutation in ({"engine": "mutated"},
                     {"spectral_dtype": "bf16-mutated"},
                     {"mesh_shape": [4, 2]},
                     {"x64": not fp.get("x64")}):
        assert run_id_from_fingerprint(dict(fp, **mutation)) != base, \
            f"run_id ignored {list(mutation)[0]}"


def test_fingerprint_reports_x64_mode():
    rec = FlightRecorder(capacity=1)
    rec.observe(integ=_small_integ())
    assert rec.fingerprint()["x64"] == jax.config.jax_enable_x64
