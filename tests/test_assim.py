"""Fault-tolerant ensemble data assimilation (PR 20).

Correctness of the masked ESRF against an independent NumPy Kalman
oracle, the masked==dense-on-alive identity that lets quarantine ride
through mask VALUES (one trace), the QC rejection matrix, the
collapse -> rollback -> inflation-escalation loop through the
supervisor (which also pins the exactly-once resume-regrid fix: the
retried cycle's analysis must re-fire after a rollback), the
ensemble-size skill argument, the HealthProbe.rebaseline contract,
and the end-to-end chaos drill as a subprocess.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.assim import (AssimConfig, AssimilationCycle,
                             ObservationOperator, QCConfig,
                             esrf_analysis, screen, state_packer,
                             synthesize_batches)
from ibamr_tpu.assim.observe import ObservationBatch

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _random_problem(rng, B=5, n=7, m=4):
    """A dense random linear-obs ensemble problem (f64)."""
    ens = rng.standard_normal((B, n))
    H = rng.standard_normal((m, n))
    obs_ens = ens @ H.T
    y = rng.standard_normal(m)
    r = 10.0 ** rng.uniform(-2.0, 0.0, m)
    return ens, obs_ens, y, r


def test_esrf_matches_numpy_kalman_oracle():
    """Ensemble-space square-root update == covariance-space Kalman
    formulas, computed independently in NumPy: the analysis mean is
    xbar + K d and the analysis covariance is (I - KH) P (the defining
    property of a square-root filter — no stochastic obs perturbation
    noise)."""
    rng = np.random.default_rng(0)
    ens, obs_ens, y, r = _random_problem(rng)
    B, _n = ens.shape
    ana, diag = esrf_analysis(
        jnp.asarray(ens), jnp.asarray(obs_ens), jnp.asarray(y),
        jnp.asarray(r), jnp.ones((B,), bool),
        jnp.ones((y.size,), bool), jnp.asarray(1.0))
    ana = np.asarray(ana)

    xbar, ybar = ens.mean(0), obs_ens.mean(0)
    Zx, Zy = ens - xbar, obs_ens - ybar
    PHt = Zx.T @ Zy / (B - 1)                      # (n, m)
    HPHt = Zy.T @ Zy / (B - 1)                     # (m, m)
    K = PHt @ np.linalg.inv(HPHt + np.diag(r))
    np.testing.assert_allclose(ana.mean(0), xbar + K @ (y - ybar),
                               atol=1e-10)

    Za = ana - ana.mean(0)
    Pa_ens = Za.T @ Za / (B - 1)
    Pa = Zx.T @ Zx / (B - 1) - K @ (Zy.T @ Zx / (B - 1))
    np.testing.assert_allclose(Pa_ens, Pa, atol=1e-10)

    np.testing.assert_allclose(
        float(diag.innov_rms),
        float(np.sqrt(np.mean((y - ybar) ** 2))), atol=1e-10)


def test_masked_equals_dense_on_alive_and_freezes_dead():
    """Dead lanes contribute NOTHING: the masked update on the full
    fleet equals the dense update on the alive subset exactly (block
    structure of the masked gain), and dead rows ride through
    bitwise-frozen."""
    rng = np.random.default_rng(1)
    ens, obs_ens, y, r = _random_problem(rng, B=6)
    alive = np.array([True, True, False, True, False, True])
    om = jnp.ones((y.size,), bool)
    ana_m, diag_m = esrf_analysis(
        jnp.asarray(ens), jnp.asarray(obs_ens), jnp.asarray(y),
        jnp.asarray(r), jnp.asarray(alive), om, jnp.asarray(1.0))
    sub = np.flatnonzero(alive)
    ana_d, _ = esrf_analysis(
        jnp.asarray(ens[sub]), jnp.asarray(obs_ens[sub]),
        jnp.asarray(y), jnp.asarray(r),
        jnp.ones((sub.size,), bool), om, jnp.asarray(1.0))
    np.testing.assert_allclose(np.asarray(ana_m)[sub],
                               np.asarray(ana_d), atol=1e-10)
    assert np.array_equal(np.asarray(ana_m)[~alive], ens[~alive])
    assert int(diag_m.n_alive) == sub.size


def test_posterior_inflation_scales_spread_exactly():
    """Posterior multiplicative inflation acts on the analysis
    anomalies alone, so spread_a is EXACTLY linear in the factor —
    the property that makes collapse -> escalate -> cure
    deterministic."""
    rng = np.random.default_rng(4)
    ens, obs_ens, y, r = _random_problem(rng)
    B = ens.shape[0]
    args = (jnp.asarray(ens), jnp.asarray(obs_ens), jnp.asarray(y),
            jnp.asarray(r), jnp.ones((B,), bool),
            jnp.ones((y.size,), bool))
    _, d1 = esrf_analysis(*args, jnp.asarray(1.0))
    _, d2 = esrf_analysis(*args, jnp.asarray(1.4))
    np.testing.assert_allclose(float(d2.spread_a),
                               1.4 * float(d1.spread_a), rtol=1e-12)


def test_qc_rejection_matrix():
    """Each failure mode hits its own channel; the gate rejects
    exactly those with the right reason, in the documented precedence
    dropout > stale > outlier (a NaN can't be an outlier; a stale
    value's innovation is not trusted enough to call it one)."""
    values = np.array([np.nan, 5.0, 0.01, 0.0, 100.0])
    age = np.array([0.0, 0.0, 1e4, 0.0, 1e4])
    batch = ObservationBatch(values=values, r=np.full(5, 1e-2),
                             age_s=age, cycle=0,
                             names=("a", "b", "c", "d", "e"))
    accept, report = screen(batch, ybar=np.zeros(5),
                            hph=np.full(5, 1e-2),
                            cfg=QCConfig(k_sigma=4.0, max_age_s=60.0),
                            step=0, cycle=0)
    assert accept.tolist() == [False, False, False, True, False]
    assert report["accepted"] == 1 and report["rejected"] == 4
    assert report["by_reason"] == {"dropout": 1, "stale": 2,
                                   "outlier": 1}


def test_analysis_skill_improves_with_ensemble_size():
    """With identity observations of a zero truth and tiny R, the
    analysis can only correct within the span of the ensemble
    anomalies: B=4 in a 12-dim state leaves most of the error
    untouched, B=32 spans the space and pulls the mean to the truth.
    Both beat their own forecast."""
    n = 12
    y = np.zeros(n)
    r = np.full(n, 1e-4)
    rng = np.random.default_rng(3)
    errs = {}
    for B in (4, 32):
        ens = rng.standard_normal((B, n))
        ana, _ = esrf_analysis(
            jnp.asarray(ens), jnp.asarray(ens), jnp.asarray(y),
            jnp.asarray(r), jnp.ones((B,), bool),
            jnp.ones((n,), bool), jnp.asarray(1.0))
        errs[B] = float(np.sqrt(np.mean(
            np.asarray(ana).mean(0) ** 2)))
        forecast = float(np.sqrt(np.mean(ens.mean(0) ** 2)))
        assert errs[B] < forecast
    assert errs[32] < 0.2 * errs[4]


def test_one_trace_through_qc_and_quarantine():
    """QC rejections (obs_mask), quarantine (alive), and inflation
    escalation all arrive as ARRAY VALUES, not shapes: the jitted
    analysis retains one trace across every combination."""
    rng = np.random.default_rng(2)
    ens, obs_ens, y, r = _random_problem(rng, B=4)
    m = y.size
    traces = {"n": 0}

    def f(ens, obs_ens, y, r, alive, om, infl):
        traces["n"] += 1
        return esrf_analysis(ens, obs_ens, y, r, alive, om, infl)

    jf = jax.jit(f)
    base = (jnp.asarray(ens), jnp.asarray(obs_ens), jnp.asarray(y),
            jnp.asarray(r))
    cases = [
        (np.ones(4, bool), np.ones(m, bool), 1.0),
        (np.array([True, True, True, False]), np.ones(m, bool), 1.0),
        (np.ones(4, bool),
         np.array([True, False, True, True]), 1.05),
        (np.array([False, True, True, True]),
         np.array([False, False, True, True]), 1.4),
    ]
    for alive, om, infl in cases:
        jax.block_until_ready(jf(*base, jnp.asarray(alive),
                                 jnp.asarray(om),
                                 jnp.asarray(infl)))
    assert traces["n"] == 1


def test_state_packer_roundtrip_bitwise():
    from ibamr_tpu.models.shell3d import build_shell_example

    _integ, st = build_shell_example(n_cells=8, n_lat=6, n_lon=8,
                                     dtype="float64")
    pack, unpack, n = state_packer(st)
    vec = pack(st)
    assert vec.shape == (n,)
    st2 = unpack(st, vec)
    for a, b in zip(jax.tree_util.tree_leaves(st),
                    jax.tree_util.tree_leaves(st2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_rebaseline_drops_anchors_keeps_streaks():
    """The analysis legitimately moves every lane, so the cycle calls
    ``probe.rebaseline()``: drift anchors re-seed from the NEXT chunk
    (no false WARN against a pre-analysis baseline) but warn streaks
    survive (a lane already trending bad keeps its strikes)."""
    from ibamr_tpu.utils.health import OK, HealthProbe

    def vitals(func):
        # (finite, max_u, cfl, div, func, vol, budget)
        return np.array([1.0, 0.1, 0.1, 0.0, func, 1.0, 1.0])

    probe = HealthProbe(func_growth_warn=3.0, sustain=10)
    level, _, _ = probe.classify(vitals(1.0), step=1, dt=1e-3)
    assert level == OK and probe._baseline_func == 1.0

    # without rebaseline the post-analysis functional reads as drift
    ctrl = HealthProbe(func_growth_warn=3.0, sustain=10)
    ctrl.classify(vitals(1.0), step=1, dt=1e-3)
    level_ctrl, reasons_ctrl, _ = ctrl.classify(vitals(10.0), step=2,
                                                dt=1e-3)
    assert level_ctrl != OK and reasons_ctrl

    probe._warn_streak = 2
    probe.rebaseline()
    assert probe._baseline_func is None
    assert probe._warn_streak == 2
    level, reasons, _ = probe.classify(vitals(10.0), step=2, dt=1e-3)
    assert level == OK and not reasons
    assert probe._baseline_func == 10.0
    assert probe._warn_streak == 0  # OK chunk legitimately clears it


def _shell_assim_setup(B, n_cyc, spc=2, dt0=1e-3, seed=11):
    from ibamr_tpu.instruments import InstrumentPanel, make_meters
    from ibamr_tpu.models.shell3d import build_shell_example
    from ibamr_tpu.utils.lanes import stack_lanes

    n_lon = 16
    integ, st0 = build_shell_example(n_cells=16, n_lat=8, n_lon=n_lon,
                                     mu=0.05, dtype="float64")
    loops = [[2 * n_lon + j for j in range(n_lon)],
             [5 * n_lon + j for j in range(n_lon)]]
    panel = InstrumentPanel(integ.ins.grid,
                            make_meters(loops, closed=True,
                                        dtype=jnp.float64))
    op = ObservationOperator(panel)
    st, truth = st0, []
    for _ in range(n_cyc):
        for _ in range(spc):
            st = integ.step(st, dt0)
        truth.append(st)
    batches = synthesize_batches(op, truth, sigma=1e-5, seed=seed)
    fleet0 = stack_lanes([st0._replace(ins=st0.ins._replace(
        u=tuple(c + 2e-3 * (i + 1) for c in st0.ins.u)))
        for i in range(B)])
    return integ, op, fleet0, batches


def test_spread_collapse_rolls_back_and_escalates_inflation(tmp_path):
    """The filter-health loop end-to-end: a spread floor set just
    above the filter's natural analysis spread trips FilterDegraded,
    the supervisor rolls back to the verified checkpoint and escalates
    inflation one rung per retry (1.0 -> 1.05 -> 1.1 cures a 7%
    deficit), and — the exactly-once resume-regrid pin — the retried
    cycle's analysis RE-FIRES after the rollback, so no cycle is
    lost and the escalated inflation actually applies."""
    from ibamr_tpu import obs as _obs
    from ibamr_tpu.serve.aot_cache import ExecutableCache
    from ibamr_tpu.utils.health import HealthProbe

    B, n_cyc, spc = 4, 3, 2
    integ, op, fleet0, batches = _shell_assim_setup(B, n_cyc, spc=spc)

    # clean pass to learn the natural first-cycle analysis spread
    base_dir = tmp_path / "base"
    base_dir.mkdir()
    base_ledger = str(base_dir / "ledger.jsonl")
    cyc0 = AssimilationCycle(
        integ, op, B, AssimConfig(steps_per_cycle=spc, dt=1e-3),
        probe=HealthProbe.for_integrator(integ),
        cache=ExecutableCache())
    with _obs.ledger(base_ledger):
        cyc0.run(fleet0, batches, directory=str(base_dir),
                 max_retries=1)
    recs = list(_obs.read_ledger(base_ledger))
    s_base = next(r["spread_a"] for r in recs
                  if r.get("kind") == "assim_cycle"
                  and not r.get("skipped"))
    assert cyc0.escalations == [] and cyc0.inflation == 1.0

    run_dir = tmp_path / "run"
    run_dir.mkdir()
    ledger = str(run_dir / "ledger.jsonl")
    cyc = AssimilationCycle(
        integ, op, B,
        AssimConfig(steps_per_cycle=spc, dt=1e-3,
                    spread_floor=1.07 * s_base),
        probe=HealthProbe.for_integrator(integ),
        cache=ExecutableCache())
    # the floor is ABSOLUTE and the filter's natural spread keeps
    # contracting cycle over cycle, so later cycles legitimately need
    # more rungs — give the ladder room to climb
    with _obs.ledger(ledger):
        cyc.run(fleet0, batches, directory=str(run_dir),
                max_retries=8)

    # two rungs: 1.05 * s still under the 1.07 floor, 1.1 * s clears
    assert cyc.escalations[:2] == [(1.0, 1.05), (1.05, 1.1)]
    assert cyc.inflation >= 1.1

    incidents = [json.loads(ln) for ln in
                 open(os.path.join(str(run_dir), "incidents.jsonl"))]
    esc = [r for r in incidents
           if r.get("event") == "inflation_escalation"]
    assert [(r["inflation_before"], r["inflation_after"])
            for r in esc[:2]] == [(1.0, 1.05), (1.05, 1.1)]

    # zero lost cycles THROUGH the rollbacks (the resume-regrid pin)
    recs = list(_obs.read_ledger(ledger))
    done = {r["cycle"] for r in recs
            if r.get("kind") == "assim_cycle"}
    assert done == set(range(n_cyc))


def test_assim_smoke_drill_end_to_end(tmp_path):
    """The committed chaos drill as CI runs it (dryrun path 24): all
    four injectors armed at once, subprocess-isolated."""
    r = subprocess.run(
        [sys.executable, "-m", "tools.fault_injection",
         "--assim-smoke", "--dir", str(tmp_path)],
        capture_output=True, text=True, cwd=REPO, timeout=900)
    assert r.returncode == 0, (r.stdout or "") + (r.stderr or "")[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["assim_smoke"] == "ok"
    assert out["lost_cycles"] == 0
    assert out["analysis_compiles"] == 2
    assert {tuple(t) for t in out["qc_rejections"]} == {
        (1, "flux[0]", "dropout"), (2, "flux[1]", "outlier"),
        (3, "mean_pressure[0]", "stale")}
    assert out["forecast_error"] < out["open_loop_error"]
