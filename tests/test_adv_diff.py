"""Adv-diff integrator tests: analytic decay, translation, conservation,
spatial convergence, and sharded-vs-single agreement."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.adv_diff import (AdvDiffSemiImplicitIntegrator,
                                            TransportedQuantity,
                                            advance_adv_diff)

TWO_PI = 2.0 * math.pi


def _grid(n, dim=2):
    return StaggeredGrid(n=(n,) * dim, x_lo=(0.0,) * dim, x_up=(1.0,) * dim)


def test_pure_diffusion_decay():
    """A single Fourier mode under CN diffusion decays at the discrete
    rate (1 + dt k l/2)/(1 - dt k l/2) per step with l the discrete
    Laplacian eigenvalue — checked exactly."""
    n, kappa, dt = 32, 0.01, 1e-3
    grid = _grid(n)
    integ = AdvDiffSemiImplicitIntegrator(
        grid, [TransportedQuantity("Q", kappa=kappa,
                                   convective_op_type="none")],
        dtype=jnp.float64)
    x, y = grid.cell_centers(jnp.float64)
    Q0 = jnp.sin(TWO_PI * x) * jnp.sin(TWO_PI * y)
    state = integ.initialize([Q0])

    steps = 50
    state = advance_adv_diff(integ, state, dt, steps)

    h = grid.dx[0]
    lam = (2.0 * math.cos(TWO_PI / n) - 2.0) / h ** 2   # per-axis eigenvalue
    lam_total = 2.0 * lam
    amp = ((1.0 + 0.5 * dt * kappa * lam_total)
           / (1.0 - 0.5 * dt * kappa * lam_total)) ** steps
    np.testing.assert_allclose(np.asarray(state.Q[0]),
                               np.asarray(amp * Q0), rtol=1e-10, atol=1e-12)


def test_advection_translates_blob():
    """Centered advection in a uniform velocity translates the profile;
    compare against the exactly-shifted initial condition after a whole
    number of cells of travel."""
    n = 64
    grid = _grid(n)
    integ = AdvDiffSemiImplicitIntegrator(
        grid, [TransportedQuantity("Q", kappa=0.0,
                                   convective_op_type="centered")],
        dtype=jnp.float64)
    x, y = grid.cell_centers(jnp.float64)
    Q0 = jnp.exp(-((x - 0.5) ** 2 + (y - 0.5) ** 2) / (2 * 0.08 ** 2))
    state = integ.initialize([Q0])
    u = (jnp.ones(grid.n, dtype=jnp.float64),
         jnp.zeros(grid.n, dtype=jnp.float64))

    # travel exactly 8 cells: T = 8*h at u=1
    h = grid.dx[0]
    steps = 256
    dt = 8 * h / steps
    state = advance_adv_diff(integ, state, dt, steps, u=u)

    expected = jnp.roll(Q0, 8, axis=0)
    # ~1% peak error is the expected 2nd-order dispersion for a 5-cell
    # Gaussian; the rigorous order check is the convergence test below.
    err = float(jnp.max(jnp.abs(state.Q[0] - expected)))
    assert err < 2e-2, err


def test_conservation_under_advection():
    """Conservative flux form: sum(Q) is machine-exact under periodic
    advection (any scheme, any velocity)."""
    n = 32
    grid = _grid(n)
    for scheme in ("centered", "upwind"):
        integ = AdvDiffSemiImplicitIntegrator(
            grid, [TransportedQuantity("Q", kappa=0.0,
                                       convective_op_type=scheme)],
            dtype=jnp.float64)
        x, y = grid.cell_centers(jnp.float64)
        Q0 = jnp.exp(-((x - 0.3) ** 2 + (y - 0.6) ** 2) / 0.01)
        state = integ.initialize([Q0])
        rng = np.random.default_rng(0)
        u = tuple(jnp.asarray(rng.standard_normal(grid.n))
                  for _ in range(2))
        total0 = float(integ.total(state))
        state = advance_adv_diff(integ, state, 1e-3, 20, u=u)
        total1 = float(integ.total(state))
        np.testing.assert_allclose(total1, total0, rtol=1e-12)


def test_advection_spatial_convergence():
    """Centered face interpolation is 2nd-order: halving h reduces the
    translation error by ~4 (time step scaled with h)."""
    errs = {}
    for n in (32, 64):
        grid = _grid(n)
        integ = AdvDiffSemiImplicitIntegrator(
            grid, [TransportedQuantity("Q", kappa=0.0,
                                       convective_op_type="centered")],
            dtype=jnp.float64)
        x, y = grid.cell_centers(jnp.float64)
        Q0 = jnp.sin(TWO_PI * x)
        state = integ.initialize([Q0])
        u = (jnp.ones(grid.n, dtype=jnp.float64),
             jnp.zeros(grid.n, dtype=jnp.float64))
        T = 0.25
        steps = 8 * n          # dt ~ h/8: time error negligible
        state = advance_adv_diff(integ, state, T / steps, steps, u=u)
        exact = jnp.sin(TWO_PI * (x - T))
        errs[n] = float(jnp.max(jnp.abs(state.Q[0] - exact)))
    order = math.log2(errs[32] / errs[64])
    assert order > 1.8, (errs, order)


def test_source_term():
    """Constant source with no transport integrates linearly in time."""
    grid = _grid(16)
    integ = AdvDiffSemiImplicitIntegrator(
        grid, [TransportedQuantity("Q", kappa=0.0,
                                   convective_op_type="none",
                                   source=lambda c, t, Q: 2.0 + 0 * Q)],
        dtype=jnp.float64)
    state = integ.initialize()
    state = advance_adv_diff(integ, state, 1e-2, 10)
    np.testing.assert_allclose(np.asarray(state.Q[0]), 0.2, rtol=1e-12)


def test_multiple_quantities_independent():
    grid = _grid(16)
    integ = AdvDiffSemiImplicitIntegrator(
        grid,
        [TransportedQuantity("A", kappa=0.05, convective_op_type="none"),
         TransportedQuantity("B", kappa=0.0, convective_op_type="none",
                             source=lambda c, t, Q: 1.0 + 0 * Q)],
        dtype=jnp.float64)
    x, y = grid.cell_centers(jnp.float64)
    state = integ.initialize([jnp.sin(TWO_PI * x) + 0 * y, None])
    state = advance_adv_diff(integ, state, 1e-3, 5)
    # A decays, B grows linearly
    assert float(jnp.max(jnp.abs(state.Q[0]))) < 1.0
    np.testing.assert_allclose(np.asarray(state.Q[1]), 5e-3, rtol=1e-12)


def test_sharded_matches_single():
    from ibamr_tpu.parallel import make_mesh
    from ibamr_tpu.parallel.mesh import make_sharded_adv_diff_step

    grid = _grid(32)
    integ = AdvDiffSemiImplicitIntegrator(
        grid, [TransportedQuantity("Q", kappa=0.02,
                                   convective_op_type="upwind")],
        dtype=jnp.float64)
    x, y = grid.cell_centers(jnp.float64)
    Q0 = jnp.exp(-((x - 0.5) ** 2 + (y - 0.5) ** 2) / 0.02)
    state0 = integ.initialize([Q0])
    rng = np.random.default_rng(1)
    u = tuple(jnp.asarray(rng.standard_normal(grid.n)) for _ in range(2))

    ref = state0
    step1 = jax.jit(lambda s, d: integ.step(s, d, u=u))
    for _ in range(5):
        ref = step1(ref, 1e-3)

    mesh = make_mesh(8, max_axes=2)
    stepN = make_sharded_adv_diff_step(integ, mesh)
    out = state0
    for _ in range(5):
        out = stepN(out, 1e-3, u=u)

    for a, b in zip(jax.tree_util.tree_leaves(ref),
                    jax.tree_util.tree_leaves(out)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-12, atol=1e-13)


def test_cui_conservation_and_boundedness():
    """CUI (CBC-limited cubic upwind, the reference's newer convective
    menu entry, SURVEY.md P4/P19): conservative flux form is
    machine-exact, and the CBC limiter keeps a step profile inside its
    initial bounds (no new extrema), unlike centered differencing."""
    n = 64
    grid = _grid(n)
    integ = AdvDiffSemiImplicitIntegrator(
        grid, [TransportedQuantity("Q", kappa=0.0,
                                   convective_op_type="cui")],
        dtype=jnp.float64)
    x, y = grid.cell_centers(jnp.float64)
    Q0 = jnp.where((x > 0.25) & (x < 0.5), 1.0, 0.0).astype(jnp.float64)
    state = integ.initialize([Q0])
    u = (jnp.ones(grid.n, dtype=jnp.float64),
         jnp.zeros(grid.n, dtype=jnp.float64))
    total0 = float(integ.total(state))
    state = advance_adv_diff(integ, state, 0.25 / n, 4 * n, u=u)
    np.testing.assert_allclose(float(integ.total(state)), total0,
                               rtol=1e-12)
    Q = np.asarray(state.Q[0])
    assert Q.min() > -1e-8 and Q.max() < 1.0 + 1e-8, (Q.min(), Q.max())


def test_cui_accuracy_beats_upwind():
    """Smooth translation: CUI's error is far below donor-cell upwind
    at the same resolution (the point of the cubic segment)."""
    n = 64
    grid = _grid(n)
    errs = {}
    for scheme in ("cui", "upwind"):
        integ = AdvDiffSemiImplicitIntegrator(
            grid, [TransportedQuantity("Q", kappa=0.0,
                                       convective_op_type=scheme)],
            dtype=jnp.float64)
        x, y = grid.cell_centers(jnp.float64)
        Q0 = jnp.sin(TWO_PI * x)
        state = integ.initialize([Q0])
        u = (jnp.ones(grid.n, dtype=jnp.float64),
             jnp.zeros(grid.n, dtype=jnp.float64))
        T = 0.25
        steps = 8 * n
        state = advance_adv_diff(integ, state, T / steps, steps, u=u)
        exact = jnp.sin(TWO_PI * (x - T))
        errs[scheme] = float(jnp.max(jnp.abs(state.Q[0] - exact)))
    assert errs["cui"] < 0.25 * errs["upwind"], errs
