"""Implicit IB on the composite two-level hierarchy (VERDICT round 3,
missing #6 / next-round item 7): Newton-Krylov coupling with
spread/interp at FINE resolution inside a refined window — the
``IBImplicitStaggeredHierarchyIntegrator``-on-AMR case the reference
runs for stiff structures (SURVEY.md P8 [U]).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.amr import FineBox
from ibamr_tpu.amr_ins import TwoLevelIBINS, advance_two_level_ib
from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ib import IBMethod
from ibamr_tpu.integrators.ib_implicit import (
    TwoLevelIBImplicit, advance_two_level_ib_implicit)
from ibamr_tpu.models.membrane2d import make_circle_membrane

_K = 1e5          # spring stiffness (same stiff regime as the uniform
#                   implicit tests: explicit limit ~1e-4)


def _pieces(mu=0.02):
    g = StaggeredGrid(n=(32, 32), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    box = FineBox(lo=(8, 8), shape=(16, 16))
    s = make_circle_membrane(48, 0.08, (0.5, 0.5), stiffness=_K,
                             aspect=1.05, rest_length_factor=1.0)
    ib = IBMethod(s.force_specs(dtype=jnp.float64), kernel="IB_4")
    return g, box, ib, s


def test_explicit_composite_unstable_beyond_limit():
    """The stiff membrane blows up the EXPLICIT composite coupling at
    dt = 5e-4 — establishing the 10x margin the implicit test claims."""
    g, box, ib, s = _pieces()
    integ = TwoLevelIBINS(g, box, ib, mu=0.02, proj_tol=1e-8)
    st = integ.initialize(jnp.asarray(s.vertices, jnp.float64))
    out = advance_two_level_ib(integ, st, 5e-4, 40)
    blew_up = (not bool(jnp.all(jnp.isfinite(out.X)))
               or float(jnp.max(jnp.abs(out.X))) > 10.0)
    assert blew_up


def test_implicit_composite_stable_at_10x():
    """Backward-Euler Newton-Krylov composite coupling at dt = 5e-4
    (>= 10x the explicit spring limit, inside the fine level's viscous
    bound): stable, finite, membrane stays the same scale, and the
    stiff ellipse actually relaxes toward the circle."""
    g, box, ib, s = _pieces()
    imp = TwoLevelIBImplicit(g, box, ib, mu=0.02, proj_tol=1e-8,
                             scheme="backward_euler",
                             newton_tol=1e-8, newton_maxiter=12,
                             inner_m=16, inner_restarts=2,
                             inner_tol=1e-3)
    st = imp.initialize(jnp.asarray(s.vertices, jnp.float64))
    X0 = np.asarray(st.X)
    r0 = np.linalg.norm(X0 - X0.mean(axis=0), axis=1)
    ecc0 = r0.max() - r0.min()
    out = advance_two_level_ib_implicit(imp, st, 5e-4, 40)
    assert bool(jnp.all(jnp.isfinite(out.X)))
    X1 = np.asarray(out.X)
    assert float(np.max(np.abs(X1 - 0.5))) < 0.2      # stayed in window
    r1 = np.linalg.norm(X1 - X1.mean(axis=0), axis=1)
    ecc1 = r1.max() - r1.min()
    assert ecc1 < 0.7 * ecc0, (ecc0, ecc1)            # relaxing


def test_implicit_composite_matches_explicit_at_small_dt():
    """At a SMALL dt both couplings converge to the same trajectory:
    the implicit composite step at dt=5e-5 tracks the explicit
    composite reference (same spatial operators, different coupling
    solve — agreement pins the residual formulation)."""
    g, box, ib, s = _pieces()
    X0 = jnp.asarray(s.vertices, jnp.float64)
    expl = TwoLevelIBINS(g, box, ib, mu=0.02, proj_tol=1e-9)
    ref = advance_two_level_ib(expl, expl.initialize(X0), 5e-5, 40)
    imp = TwoLevelIBImplicit(g, box, ib, mu=0.02, proj_tol=1e-9,
                             scheme="midpoint", newton_tol=1e-10,
                             newton_maxiter=12, inner_m=20,
                             inner_restarts=2, inner_tol=1e-5)
    out = advance_two_level_ib_implicit(imp, imp.initialize(X0),
                                        5e-5, 40)
    err = float(jnp.max(jnp.abs(out.X - ref.X)))
    assert err < 2e-4, err


def test_implicit_regridding_window_tracks_structure():
    """Implicit composite + moving-window regrid: a stiff membrane
    advected by a background stream keeps its refined window centered
    on it across regrids, at 10x the explicit dt."""
    g, box, ib, s = _pieces()
    imp = TwoLevelIBImplicit(g, box, ib, mu=0.02, proj_tol=1e-7,
                             scheme="backward_euler", newton_tol=1e-7,
                             newton_maxiter=10, inner_m=12,
                             inner_restarts=2, inner_tol=1e-3)
    # seed a rightward stream on the coarse level so the membrane
    # drifts (fine seeded by initialize's prolongation)
    uc = tuple(jnp.full(g.n, 1.0, jnp.float64) if d == 0
               else jnp.zeros(g.n, jnp.float64) for d in range(2))
    st = imp.initialize(jnp.asarray(s.vertices, jnp.float64), uc=uc)

    from ibamr_tpu.integrators.ib_implicit import (
        advance_two_level_ib_implicit_regridding,
        regrid_two_level_ib_implicit)

    lo0 = imp.box.lo
    imp2, st2 = advance_two_level_ib_implicit_regridding(
        imp, st, 5e-4, 200, regrid_interval=25)
    assert bool(jnp.all(jnp.isfinite(st2.X)))
    # the membrane drifted right and the window moved with it
    drift = float(jnp.mean(st2.X[:, 0]) - jnp.mean(st.X[:, 0]))
    assert drift > 0.01, drift
    assert imp2.box.lo[0] > lo0[0], (lo0, imp2.box.lo)
    # the structure is still inside the (moved) window with clearance
    c = (np.asarray(st2.X)[:, 0] - 0.0) / (1.0 / 32)
    assert c.min() > imp2.box.lo[0] + 1
    assert c.max() < imp2.box.lo[0] + imp2.box.shape[0] - 1
