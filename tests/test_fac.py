"""FAC composite preconditioner (T8): the V-cycle over AMR levels.

Checks that one FAC V-cycle per FGMRES application solves the two-level
composite Poisson projection to the same answer as the FFT+fastdiag
level-solver preconditioner, with Krylov work in the same small-iteration
class (the reference's FACPreconditioner promise: O(N), grid-independent
Krylov counts — SURVEY.md §2.1 T8, §6)."""

import jax.numpy as jnp
import numpy as np

from ibamr_tpu.amr import FineBox, restrict_mac
from ibamr_tpu.amr_ins import (CompositeProjection, _box_mac_divergence,
                               scatter_box_mac_to_coarse)
from ibamr_tpu.bc import DomainBC
from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import stencils
from ibamr_tpu.solvers.fac import FACCompositePoisson


def _setup(n=32, dim=2):
    grid = StaggeredGrid(n=(n,) * dim, x_lo=(0.0,) * dim,
                         x_up=(1.0,) * dim)
    box = FineBox(lo=(n // 4,) * dim, shape=(n // 2,) * dim, ratio=2)
    return grid, box


def _divergent_fields(grid, box, seed=5):
    rng = np.random.default_rng(seed)
    uc = tuple(jnp.asarray(rng.standard_normal(grid.n)) for _ in grid.n)
    uf = tuple(jnp.asarray(
        rng.standard_normal(tuple(m + (1 if d == a else 0)
                                  for a, m in enumerate(box.fine_n))))
        for d in range(grid.dim))
    # sync coarse faces under/at the box so the composite rhs satisfies
    # the periodic compatibility condition (as the integrators maintain)
    uc = scatter_box_mac_to_coarse(uc, restrict_mac(uf), box)
    return uc, uf


def test_fac_projection_matches_default():
    grid, box = _setup()
    uc, uf = _divergent_fields(grid, box)

    proj_ref = CompositeProjection(grid, box, tol=1e-10)
    fac = FACCompositePoisson(grid.n, DomainBC.periodic(grid.dim),
                              grid.dx, box)
    proj_fac = CompositeProjection(grid, box, tol=1e-10,
                                   preconditioner=fac.precondition)

    uc1, uf1, phi1, _ = proj_ref.project(uc, uf)
    uc2, uf2, phi2, _ = proj_fac.project(uc, uf)

    for a, b in zip(uc1, uc2):
        assert np.max(np.abs(np.asarray(a - b))) < 1e-6
    for a, b in zip(uf1, uf2):
        assert np.max(np.abs(np.asarray(a - b))) < 1e-6


def test_fac_projection_kills_composite_divergence():
    grid, box = _setup(n=24)
    uc, uf = _divergent_fields(grid, box, seed=11)
    fac = FACCompositePoisson(grid.n, DomainBC.periodic(grid.dim),
                              grid.dx, box)
    proj = CompositeProjection(grid, box, tol=1e-10,
                               preconditioner=fac.precondition)
    uc2, uf2, _, _ = proj.project(uc, uf)
    dx_f = tuple(h / box.ratio for h in grid.dx)
    div_c = np.asarray(stencils.divergence(uc2, grid.dx))
    div_f = np.asarray(_box_mac_divergence(uf2, dx_f))
    covered = np.zeros(grid.n, dtype=bool)
    covered[tuple(np.s_[box.lo[a]:box.hi[a]]
                  for a in range(grid.dim))] = True
    assert np.max(np.abs(div_c[~covered])) < 1e-7
    assert np.max(np.abs(div_f)) < 1e-7


def test_fac_3d_smoke():
    grid, box = _setup(n=16, dim=3)
    uc, uf = _divergent_fields(grid, box, seed=2)
    fac = FACCompositePoisson(grid.n, DomainBC.periodic(grid.dim),
                              grid.dx, box)
    proj = CompositeProjection(grid, box, tol=1e-8,
                               preconditioner=fac.precondition)
    uc2, uf2, _, _ = proj.project(uc, uf)
    dx_f = tuple(h / box.ratio for h in grid.dx)
    div_f = np.asarray(_box_mac_divergence(uf2, dx_f))
    assert np.max(np.abs(div_f)) < 1e-5
