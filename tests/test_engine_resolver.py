"""Pluggable auto-engine resolution (PR 12 satellite).

``use_fast_interaction=None`` ("auto") no longer hard-codes the packed
promotion inline: resolution routes through
``ibamr_tpu/models/engine_resolver.py`` (env override -> tuning DB ->
built-in heuristic), and the RESOLVED name — never "auto" — is stamped
on the integrator and lands in the flight-recorder fingerprint, so the
serving cache key always reflects what actually runs.
"""

import json

import pytest

from ibamr_tpu.models.engine_resolver import (ENV_ENGINE, ENV_TUNING_DB,
                                              RESOLVED_ENGINES,
                                              default_rule,
                                              load_tuning_db,
                                              resolve_engine)

_SUPPORT = 2                          # ib4 half-width


def test_default_rule_promotion_band():
    # large tile-divisible grid with enough markers -> packed
    assert default_rule((128, 128, 128), 100_000, _SUPPORT) == "packed"
    # too few markers -> scatter
    assert default_rule((128, 128, 128), 100, _SUPPORT) == "scatter"
    # not tile-divisible -> scatter
    assert default_rule((12, 12, 12), 100_000, _SUPPORT) == "scatter"
    # tile-divisible but below the make_geometry minimum extent
    assert default_rule((8, 8, 8), 100_000, _SUPPORT) == "scatter"


def test_env_override_wins_and_validates():
    env = {ENV_ENGINE: "packed3"}
    assert resolve_engine((8, 8, 8), 10, _SUPPORT, env=env) == "packed3"
    # "auto"/empty defer to the rest of the chain
    assert resolve_engine((8, 8, 8), 10, _SUPPORT,
                          env={ENV_ENGINE: "auto"}) == "scatter"
    assert resolve_engine((8, 8, 8), 10, _SUPPORT,
                          env={ENV_ENGINE: ""}) == "scatter"
    # a typo'd engine dies at build time, never poisons a cache key
    with pytest.raises(ValueError, match="unknown transfer engine"):
        resolve_engine((8, 8, 8), 10, _SUPPORT,
                       env={ENV_ENGINE: "packedd"})
    assert "auto" not in RESOLVED_ENGINES


def test_tuning_db_most_specific_wins(tmp_path):
    db = tmp_path / "tuning.json"
    db.write_text(json.dumps({"schema": 1, "entries": [
        {"engine": "packed3", "n_cells": 256},
        # generic marker-band entry FIRST...
        {"engine": "mxu", "markers_min": 50, "markers_max": 500},
        # ...but the later, MORE SPECIFIC entry wins the overlap:
        # file order is not load-bearing for differently-specific
        # entries (the PR-12 first-match order-dependence is gone)
        {"engine": "packed3_bf16", "n_cells": 64,
         "markers_min": 50, "markers_max": 500},
    ]}))
    env = {ENV_TUNING_DB: str(db)}
    assert resolve_engine((256, 256, 256), 10_000, _SUPPORT,
                          env=env) == "packed3"
    # overlap: both the mxu band and the n_cells=64 entry match;
    # higher specificity (n_cells + band > band alone) wins
    assert resolve_engine((64, 64, 64), 100, _SUPPORT,
                          env=env) == "packed3_bf16"
    # off the pinned n_cells, the generic band entry still serves
    assert resolve_engine((32, 32, 32), 100, _SUPPORT,
                          env=env) == "mxu"
    # no entry matches -> heuristic
    assert resolve_engine((64, 64, 64), 10, _SUPPORT,
                          env=env) == "scatter"
    # env override outranks the DB
    assert resolve_engine((256, 256, 256), 10_000, _SUPPORT,
                          env={ENV_TUNING_DB: str(db),
                               ENV_ENGINE: "pallas"}) == "pallas"


def test_tuning_db_equal_specificity_keeps_file_order(tmp_path):
    db = tmp_path / "tuning.json"
    db.write_text(json.dumps({"schema": 1, "entries": [
        {"engine": "mxu", "markers_min": 50, "markers_max": 500},
        {"engine": "packed3", "markers_min": 40, "markers_max": 600},
    ]}))
    # both match at score 2 -> the deterministic tiebreak is file
    # order (earlier wins), never dict-iteration accident
    assert resolve_engine((64, 64, 64), 100, _SUPPORT,
                          env={ENV_TUNING_DB: str(db)}) == "mxu"


def test_tuning_db_platform_and_provenance_gates(tmp_path):
    db = tmp_path / "tuning.json"
    db.write_text(json.dumps({"schema": 1, "entries": [
        # platform match-field pin: only serves tpu queries
        {"engine": "packed3", "platform": "tpu"},
        # provenance pin: measured on tpu, must not steer cpu runs
        {"engine": "mxu", "markers_min": 50, "markers_max": 500,
         "provenance": {"platform": "tpu", "timestamp": "2026-08-06"}},
    ]}))
    env = {ENV_TUNING_DB: str(db)}
    # under the forced-cpu test backend both entries are skipped
    assert resolve_engine((64, 64, 64), 100, _SUPPORT,
                          env=env) == "scatter"
    # an explicit tpu query reaches them (10 markers: outside the mxu
    # band, so the platform-pinned entry serves)
    assert resolve_engine((64, 64, 64), 10, _SUPPORT, env=env,
                          platform="tpu") == "packed3"
    # cpu provenance serves cpu queries
    db.write_text(json.dumps({"schema": 1, "entries": [
        {"engine": "mxu", "markers_min": 50, "markers_max": 500,
         "provenance": {"platform": "cpu",
                        "timestamp": "2026-08-06"}}]}))
    assert resolve_engine((64, 64, 64), 100, _SUPPORT,
                          env=env) == "mxu"


def test_tuning_db_disable_and_spectral_dtype_match(tmp_path):
    db = tmp_path / "tuning.json"
    db.write_text(json.dumps({"schema": 1, "entries": [
        {"engine": "mxu", "markers_min": 50, "markers_max": 500,
         "spectral_dtype": "bf16"}]}))
    env = {ENV_TUNING_DB: str(db)}
    # a bf16-pinned entry does not serve the default-f32 query...
    assert resolve_engine((64, 64, 64), 100, _SUPPORT,
                          env=env) == "scatter"
    # ...but serves the bf16 one
    assert resolve_engine((64, 64, 64), 100, _SUPPORT, env=env,
                          spectral_dtype="bf16") == "mxu"
    # IBAMR_TUNING_DB=none opts out of the committed default DB
    assert resolve_engine((64, 64, 64), 100, _SUPPORT,
                          env={ENV_TUNING_DB: "none"}) == "scatter"


def test_tuning_db_unknown_schema_rejected(tmp_path):
    db = tmp_path / "tuning.json"
    db.write_text(json.dumps({"schema": 99, "entries": []}))
    with pytest.raises(ValueError, match="schema"):
        load_tuning_db(str(db))


def test_malformed_tuning_db_raises(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"winners": []}))
    with pytest.raises(ValueError, match="entries"):
        load_tuning_db(str(bad))
    # a configured-but-broken DB is an error, not a silent fallback
    with pytest.raises(ValueError):
        resolve_engine((64, 64, 64), 10, _SUPPORT,
                       env={ENV_TUNING_DB: str(bad)})
    with pytest.raises(ValueError, match="unknown transfer engine"):
        ok_shape = tmp_path / "typo.json"
        ok_shape.write_text(json.dumps(
            {"entries": [{"engine": "warp9"}]}))
        resolve_engine((64, 64, 64), 10, _SUPPORT,
                       env={ENV_TUNING_DB: str(ok_shape)})


def test_resolved_engine_stamped_on_integrator_and_fingerprint():
    from ibamr_tpu.models.shell3d import build_shell_example
    from ibamr_tpu.serve.aot_cache import step_fingerprint

    integ, _ = build_shell_example(n_cells=8, n_lat=6, n_lon=8,
                                   radius=0.25, aspect=1.2,
                                   stiffness=1.0,
                                   rest_length_factor=0.75, mu=0.05,
                                   use_fast_interaction=None)
    # tiny grid: the heuristic resolves auto -> scatter, and the
    # RESOLVED name (not "auto") is what the fingerprint carries
    assert integ.ib.engine_name == "scatter"
    fp = step_fingerprint(integ)
    assert fp["engine"] == "scatter"


def test_explicit_engine_stamped_too():
    from ibamr_tpu.models.shell3d import build_shell_example

    integ, _ = build_shell_example(n_cells=8, n_lat=6, n_lon=8,
                                   radius=0.25, aspect=1.2,
                                   stiffness=1.0,
                                   rest_length_factor=0.75, mu=0.05,
                                   use_fast_interaction=False)
    assert integ.ib.engine_name == "scatter"
