"""Collocated INS (P5) + stochastic forcing (P6) tests: Taylor-Green
accuracy of the cell-centered scheme, approximate-projection divergence
behavior, exact momentum neutrality of the fluctuating stress, and the
fluctuation-dissipation balance (equipartition scaling with kT)."""

import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
from ibamr_tpu.integrators.ins_collocated import (INSCollocatedIntegrator,
                                                  advance_collocated)
from ibamr_tpu.ops.stochastic import (StochasticFluxForcing,
                                      StochasticStressForcing)

F64 = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
TWO_PI = 2.0 * math.pi


def _tg_cc(g, t, nu, dtype):
    decay = math.exp(-2.0 * TWO_PI ** 2 * nu * t)
    xc, yc = g.cell_centers(dtype)
    u = jnp.sin(TWO_PI * xc) * jnp.cos(TWO_PI * yc) * decay + 0 * yc
    v = -jnp.cos(TWO_PI * xc) * jnp.sin(TWO_PI * yc) * decay + 0 * xc
    return jnp.broadcast_to(u, g.n), jnp.broadcast_to(v, g.n)


def _run_tg_cc(n, steps, T, nu):
    g = StaggeredGrid(n=(n, n), x_lo=(0, 0), x_up=(1, 1))
    integ = INSCollocatedIntegrator(g, rho=1.0, mu=nu, dtype=F64)
    st = integ.initialize(u0_arrays=_tg_cc(g, 0.0, nu, F64))
    st = advance_collocated(integ, st, T / steps, steps)
    ue, ve = _tg_cc(g, T, nu, F64)
    err = max(float(jnp.max(jnp.abs(st.u[0] - ue))),
              float(jnp.max(jnp.abs(st.u[1] - ve))))
    return st, err, integ


# -- collocated INS ----------------------------------------------------------

def test_collocated_taylor_green_convergence():
    nu, T = 0.01, 0.25
    _, e16, _ = _run_tg_cc(16, 32, T, nu)
    _, e32, _ = _run_tg_cc(32, 64, T, nu)
    order = math.log2(e16 / e32)
    assert e32 < 4e-3
    assert order > 1.6, (e16, e32, order)


def test_collocated_divergence_small_not_exact():
    st, _, integ = _run_tg_cc(32, 20, 0.1, 0.02)
    div = float(integ.max_divergence(st))
    # approximate projection: small (O(h^2) of the solution scale)
    assert div < 5e-2


def test_collocated_momentum_conserved_linear_terms():
    # diffusion + pressure correction conserve momentum exactly
    # (telescoping rolls); the ADVECTIVE-form convective term does not
    # telescope (unlike the staggered flux form), so it is off here
    g = StaggeredGrid(n=(32, 32), x_lo=(0, 0), x_up=(1, 1))
    integ = INSCollocatedIntegrator(g, rho=1.0, mu=0.02,
                                    convective_op_type="none", dtype=F64)
    rng = np.random.RandomState(0)
    u0 = tuple(jnp.asarray(rng.randn(32, 32), dtype=F64)
               for _ in range(2))
    st = integ.initialize(u0_arrays=u0)
    mom0 = [float(jnp.sum(c)) for c in st.u]
    st = advance_collocated(integ, st, 1e-3, 20)
    mom1 = [float(jnp.sum(c)) for c in st.u]
    assert np.allclose(mom0, mom1, atol=1e-8)


def test_collocated_matches_staggered_taylor_green():
    # both discretizations approximate the same flow to comparable error
    from ibamr_tpu.integrators.ins import advance as advance_staggered
    nu, T, n, steps = 0.02, 0.2, 32, 40
    _, e_cc, _ = _run_tg_cc(n, steps, T, nu)
    g = StaggeredGrid(n=(n, n), x_lo=(0, 0), x_up=(1, 1))
    sintg = INSStaggeredIntegrator(g, rho=1.0, mu=nu, dtype=F64)
    decay0 = 1.0
    xf, yc = g.face_centers(0, F64)
    xc, yf = g.face_centers(1, F64)
    u0 = jnp.broadcast_to(
        jnp.sin(TWO_PI * xf) * jnp.cos(TWO_PI * yc) * decay0, g.n)
    v0 = jnp.broadcast_to(
        -jnp.cos(TWO_PI * xc) * jnp.sin(TWO_PI * yf) * decay0, g.n)
    st = advance_staggered(sintg, sintg.initialize(u0_arrays=(u0, v0)),
                           T / steps, steps)
    decay = math.exp(-2.0 * TWO_PI ** 2 * nu * T)
    ue = jnp.broadcast_to(
        jnp.sin(TWO_PI * xf) * jnp.cos(TWO_PI * yc) * decay, g.n)
    e_st = float(jnp.max(jnp.abs(st.u[0] - ue)))
    assert e_cc < 5e-3 and e_st < 5e-3
    # the collocated (approximate-projection) error is the same order
    assert e_cc < 10.0 * max(e_st, 1e-6)


# -- stochastic forcing ------------------------------------------------------

def test_stochastic_stress_zero_net_momentum():
    for n in ((32, 32), (12, 12, 12)):
        grid = StaggeredGrid(n=n, x_lo=(0,) * len(n), x_up=(1,) * len(n))
        forcing = StochasticStressForcing(grid, mu=0.1, kT=2.0, dtype=F64)
        f = forcing.sample(jax.random.PRNGKey(0), dt=1e-3)
        for comp in f:
            assert abs(float(jnp.sum(comp))) < 1e-8


def test_stochastic_stress_variance_scaling():
    grid = StaggeredGrid(n=(64, 64), x_lo=(0, 0), x_up=(1, 1))
    forcing = StochasticStressForcing(grid, mu=0.1, kT=1.0, dtype=F64)
    f1 = forcing.sample(jax.random.PRNGKey(1), dt=1e-3)
    f2 = forcing.sample(jax.random.PRNGKey(1), dt=4e-3)
    v1 = float(jnp.var(f1[0]))
    v2 = float(jnp.var(f2[0]))
    # same key: identical normals, scale ~ 1/sqrt(dt) -> var ratio 4
    assert abs(v1 / v2 - 4.0) < 1e-6


def test_fluctuation_dissipation_equipartition_scaling():
    # thermal steady-state KE must scale linearly with kT
    grid = StaggeredGrid(n=(32, 32), x_lo=(0, 0), x_up=(1, 1))
    ins = INSStaggeredIntegrator(grid, rho=1.0, mu=0.1,
                                 convective_op_type="none", dtype=F64)
    dt, steps = 2e-3, 300

    def run(kT, seed):
        forcing = StochasticStressForcing(grid, mu=ins.mu, kT=kT,
                                          dtype=F64)

        def body(carry, k):
            st, key = carry
            key, sub = jax.random.split(key)
            f = forcing.sample(sub, dt)
            st = ins.step(st, dt, f=f)
            return (st, key), ins.kinetic_energy(st)

        (st, _), kes = jax.lax.scan(
            body, (ins.initialize(), jax.random.PRNGKey(seed)),
            jnp.arange(steps))
        return float(jnp.mean(kes[steps // 2:]))

    ke1 = run(1.0, 0)
    ke4 = run(4.0, 1)
    assert ke1 > 0.0
    ratio = ke4 / ke1
    assert 2.5 < ratio < 6.0   # ~4 expected; loose for sampling noise


def test_stochastic_flux_conserves_scalar():
    grid = StaggeredGrid(n=(32, 32), x_lo=(0, 0), x_up=(1, 1))
    forcing = StochasticFluxForcing(grid, kappa=0.01, dtype=F64)
    dq = forcing.sample(jax.random.PRNGKey(2), dt=1e-3)
    assert abs(float(jnp.sum(dq))) < 1e-8
    assert float(jnp.std(dq)) > 0.0


# ---------------------------------------------------------------------------
# Wall-bounded collocated INS (round 5: P5 closure — the collocated
# family beyond periodic-FFT)
# ---------------------------------------------------------------------------

def test_collocated_walled_channel_decay_rate():
    """No-slip channel decay of the u_x = sin(pi y) mode: the measured
    rate must match mu * (discrete Dirichlet-cc eigenvalue) — the
    SAME 1D operator the fast-diagonalization solve transforms with,
    so the implicit and explicit halves share one discretization.
    Measured agreement: 1.3e-8 relative (CN time error at this dt)."""
    import numpy as np

    from ibamr_tpu.bc import dirichlet_axis
    from ibamr_tpu.integrators.ins_collocated import (
        INSCollocatedIntegrator, advance_collocated)
    from ibamr_tpu.solvers.fastdiag import laplacian_1d_cc

    n = 32
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    mu = 0.02
    col = INSCollocatedIntegrator(g, mu=mu, wall_axes=(False, True),
                                  convective_op_type="none",
                                  dtype=jnp.float64)
    y = (np.arange(n) + 0.5) / n
    u0 = np.broadcast_to(np.sin(np.pi * y)[None, :], (n, n)).copy()
    st = col.initialize(u0_arrays=(jnp.asarray(u0),
                                   jnp.zeros((n, n))))
    dt, steps = 2e-3, 100
    st = advance_collocated(col, st, dt, steps)
    rate = -float(jnp.log(jnp.max(st.u[0]) / np.max(u0))) / (dt * steps)
    lam = np.linalg.eigvalsh(laplacian_1d_cc(n, 1.0 / n,
                                             dirichlet_axis()))
    rate_disc = mu * (-lam[-1])
    assert abs(rate - rate_disc) / rate_disc < 1e-6, (rate, rate_disc)


def test_collocated_walled_quiescence_and_convection_stable():
    """Exact quiescence at rest; a convecting vortex between walls
    stays finite with O(h^2)-small cell divergence (the approximate
    projection's documented residual)."""
    import numpy as np

    from ibamr_tpu.integrators.ins_collocated import (
        INSCollocatedIntegrator, advance_collocated)

    n = 32
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    col = INSCollocatedIntegrator(g, mu=5e-3,
                                  wall_axes=(True, True),
                                  convective_op_type="upwind",
                                  dtype=jnp.float64)
    st0 = col.initialize()
    st0 = advance_collocated(col, st0, 1e-3, 5)
    assert max(float(jnp.max(jnp.abs(c))) for c in st0.u) == 0.0

    c = (np.arange(n) + 0.5) / n
    X, Y = np.meshgrid(c, c, indexing="ij")
    sig = 0.12
    psi_amp = 0.05
    u0 = psi_amp * -(Y - 0.5) / sig ** 2 * np.exp(
        -((X - 0.5) ** 2 + (Y - 0.5) ** 2) / (2 * sig ** 2))
    v0 = psi_amp * (X - 0.5) / sig ** 2 * np.exp(
        -((X - 0.5) ** 2 + (Y - 0.5) ** 2) / (2 * sig ** 2))
    st = col.initialize(u0_arrays=(jnp.asarray(u0), jnp.asarray(v0)))
    st_mid = advance_collocated(col, st, 1e-3, 20)
    st_end = advance_collocated(col, st_mid, 1e-3, 80)
    assert bool(jnp.all(jnp.isfinite(st_end.u[0])))
    # approximate projection: central divergence small, not roundoff
    assert float(col.max_divergence(st_end)) < 0.05
    # energy decays monotonically (no-slip walls + viscosity, no
    # forcing) — compare through the integrator's own functional
    ke_mid = float(col.kinetic_energy(st_mid))
    ke_end = float(col.kinetic_energy(st_end))
    assert ke_end < ke_mid
