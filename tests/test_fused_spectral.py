"""Fused spectral Stokes substep: must reproduce the unfused
Helmholtz -> project -> pressure-update pipeline to roundoff (same
discrete operators, one spectral pass), stay divergence-free, and keep
the Taylor-Green trajectory unchanged."""

import jax.numpy as jnp
import numpy as np

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ins import INSStaggeredIntegrator, advance
from ibamr_tpu.ops import stencils
from ibamr_tpu.solvers import fft


def _taylor_green_u(g):
    n = g.n[0]
    x_f = np.arange(n) / n
    y_c = (np.arange(n) + 0.5) / n
    X, Y = np.meshgrid(x_f, y_c, indexing="ij")
    u = np.sin(2 * np.pi * X) * np.cos(2 * np.pi * Y)
    Xc, Yc = np.meshgrid(y_c, x_f, indexing="ij")
    v = -np.cos(2 * np.pi * Xc) * np.sin(2 * np.pi * Yc)
    return jnp.asarray(u), jnp.asarray(v)


def test_fused_equals_unfused_single_substep():
    g = StaggeredGrid(n=(32, 32), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    rng = np.random.default_rng(0)
    rhs = tuple(jnp.asarray(rng.standard_normal(g.n)) for _ in range(2))
    alpha, beta = 50.0, -0.05
    u_f, pinc = fft.helmholtz_project_periodic(
        rhs, g.dx, alpha, beta, pinc_coeffs=(alpha, beta))
    u_star = fft.solve_helmholtz_periodic_vel(rhs, g.dx, alpha, beta)
    u_ref, phi0 = fft.project_divergence_free(u_star, g.dx)
    pinc_ref = alpha * phi0 + beta * stencils.laplacian(phi0, g.dx)
    for a, b in zip(u_f, u_ref):
        assert np.max(np.abs(np.asarray(a - b))) < 1e-12
    assert np.max(np.abs(np.asarray(pinc - pinc_ref))) < 1e-10
    div = stencils.divergence(u_f, g.dx)
    assert float(jnp.max(jnp.abs(div))) < 1e-12


def test_fused_step_matches_unfused_trajectory():
    g = StaggeredGrid(n=(32, 32), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    integ = INSStaggeredIntegrator(g, mu=0.01, rho=1.0,
                                   dtype=jnp.float64)
    assert integ.fused_stokes is not None
    u0 = _taylor_green_u(g)
    st0 = integ.initialize(u0_arrays=u0)
    st_f = advance(integ, st0, 1e-3, 20)

    integ.fused_stokes = None
    st_u = advance(integ, st0, 1e-3, 20)

    for a, b in zip(st_f.u, st_u.u):
        assert np.max(np.abs(np.asarray(a - b))) < 1e-11
    assert np.max(np.abs(np.asarray(st_f.p - st_u.p))) < 1e-10


def test_fused_3d_divergence_free():
    g = StaggeredGrid(n=(16, 16, 16), x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    rng = np.random.default_rng(1)
    rhs = tuple(jnp.asarray(rng.standard_normal(g.n)) for _ in range(3))
    u_f, _ = fft.helmholtz_project_periodic(
        rhs, g.dx, 100.0, -0.01, pinc_coeffs=(100.0, -0.01))
    div = stencils.divergence(u_f, g.dx)
    assert float(jnp.max(jnp.abs(div))) < 1e-11
