"""Godunov advector tests (P20): convergence on smooth profiles, strict
monotonicity on discontinuous ones, exact conservation, and the
predictor-corrector adv-diff integrator against an exact solution."""

import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops.godunov import (AdvDiffPredictorCorrector, advect,
                                   godunov_face_values, mc_limited_slope)

F64 = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
TWO_PI = 2.0 * math.pi


def _uniform_u(grid, vel, dtype=F64):
    return tuple(jnp.full(grid.n, v, dtype=dtype) for v in vel)


def _advect_error(n, steps, vel=(0.7, 0.3)):
    grid = StaggeredGrid(n=(n, n), x_lo=(0, 0), x_up=(1, 1))
    xc, yc = grid.cell_centers(F64)
    Q0 = jnp.broadcast_to(
        jnp.sin(TWO_PI * xc) * jnp.sin(TWO_PI * yc), grid.n).astype(F64)
    u = _uniform_u(grid, vel)
    T = 0.5
    dt = T / steps

    def body(Q, _):
        return advect(Q, u, grid.dx, dt), None

    Q, _ = jax.lax.scan(body, Q0, None, length=steps)
    xe = xc - vel[0] * T
    ye = yc - vel[1] * T
    Qe = jnp.broadcast_to(jnp.sin(TWO_PI * xe) * jnp.sin(TWO_PI * ye),
                          grid.n)
    # L1 norm: the MC limiter clips smooth extrema, degrading the MAX
    # norm locally (expected for limited schemes); L1 shows the design
    # order
    return float(jnp.mean(jnp.abs(Q - Qe)))


def test_smooth_advection_second_order():
    e32 = _advect_error(32, 64)
    e64 = _advect_error(64, 128)
    order = math.log2(e32 / e64)
    assert e64 < 2e-3
    assert order > 1.6, (e32, e64, order)


def test_square_pulse_monotone_and_conservative():
    grid = StaggeredGrid(n=(64, 64), x_lo=(0, 0), x_up=(1, 1))
    xc, yc = grid.cell_centers(F64)
    Q0 = jnp.broadcast_to(
        ((jnp.abs(xc - 0.3) < 0.1) & (jnp.abs(yc - 0.5) < 0.1))
        .astype(F64), grid.n)
    u = _uniform_u(grid, (0.9, 0.45))
    dt = 0.4 * grid.dx[0] / 0.9

    def body(Q, _):
        return advect(Q, u, grid.dx, dt), None

    Q, _ = jax.lax.scan(body, Q0, None, length=80)
    # unsplit CTU: essentially non-oscillatory (sub-percent corner
    # over/undershoots are inherent to unsplit predictors)
    assert float(jnp.min(Q)) > -1e-2
    assert float(jnp.max(Q)) < 1.0 + 1e-2
    # flux form: exact conservation
    assert abs(float(jnp.sum(Q) - jnp.sum(Q0))) < 1e-9 * float(
        jnp.sum(Q0))


def test_split_advection_strictly_monotone():
    from ibamr_tpu.ops.godunov import advect_split
    grid = StaggeredGrid(n=(64, 64), x_lo=(0, 0), x_up=(1, 1))
    xc, yc = grid.cell_centers(F64)
    Q0 = jnp.broadcast_to(
        ((jnp.abs(xc - 0.3) < 0.1) & (jnp.abs(yc - 0.5) < 0.1))
        .astype(F64), grid.n)
    u = _uniform_u(grid, (0.9, 0.45))
    dt = 0.4 * grid.dx[0] / 0.9

    def body(Q, _):
        Q = advect_split(Q, u, grid.dx, dt, parity=0)
        Q = advect_split(Q, u, grid.dx, dt, parity=1)
        return Q, None

    Q, _ = jax.lax.scan(body, Q0, None, length=40)
    assert float(jnp.min(Q)) > -1e-12
    assert float(jnp.max(Q)) < 1.0 + 1e-12
    assert abs(float(jnp.sum(Q) - jnp.sum(Q0))) < 1e-9 * float(jnp.sum(Q0))


def test_variable_velocity_solid_body_rotation():
    # rotating velocity field: a blob returns near its start after one
    # revolution; mass conserved exactly
    grid = StaggeredGrid(n=(64, 64), x_lo=(0, 0), x_up=(1, 1))
    xf, yc = grid.face_centers(0, F64)
    xc, yf = grid.face_centers(1, F64)
    om = TWO_PI
    u = (jnp.broadcast_to(-om * (yc - 0.5), grid.n).astype(F64),
         jnp.broadcast_to(om * (xc - 0.5), grid.n).astype(F64))
    cc = grid.cell_centers(F64)
    r2 = (cc[0] - 0.5) ** 2 + (cc[1] - 0.7) ** 2
    Q0 = jnp.broadcast_to(jnp.exp(-r2 / 0.01), grid.n).astype(F64)
    steps = 400
    dt = 1.0 / steps

    def body(Q, _):
        return advect(Q, u, grid.dx, dt), None

    Q, _ = jax.lax.scan(body, Q0, None, length=steps)
    assert abs(float(jnp.sum(Q) - jnp.sum(Q0))) < 1e-9 * float(jnp.sum(Q0))
    # peak region overlaps the initial blob after a full revolution
    i_pk = np.unravel_index(int(jnp.argmax(Q)), grid.n)
    x_pk = (i_pk[0] + 0.5) * grid.dx[0]
    y_pk = (i_pk[1] + 0.5) * grid.dx[1]
    assert abs(x_pk - 0.5) < 0.06 and abs(y_pk - 0.7) < 0.06


def test_mc_slope_zero_at_extrema():
    Q = jnp.asarray([0.0, 1.0, 0.0, -1.0, 0.0, 1.0], dtype=F64)
    s = np.asarray(mc_limited_slope(Q, 0))
    assert s[1] == 0.0 and s[3] == 0.0   # local max / min


def test_predictor_corrector_adv_diff_exact_decay():
    # traveling decaying sine: dQ/dt + u dQ/dx = kappa lap Q
    n, steps = 64, 128
    grid = StaggeredGrid(n=(n, n), x_lo=(0, 0), x_up=(1, 1))
    kappa, vel, T = 5e-3, (0.8, 0.0), 0.25
    integ = AdvDiffPredictorCorrector(grid, kappa=kappa)
    xc, yc = grid.cell_centers(F64)
    Q = jnp.broadcast_to(jnp.sin(TWO_PI * xc) + 0 * yc, grid.n).astype(F64)
    u = _uniform_u(grid, vel)
    dt = T / steps

    def body(Q, _):
        return integ.step(Q, u, dt), None

    Q, _ = jax.lax.scan(body, Q, None, length=steps)
    decay = math.exp(-TWO_PI ** 2 * kappa * T)
    Qe = jnp.broadcast_to(jnp.sin(TWO_PI * (xc - vel[0] * T)) * decay,
                          grid.n)
    assert float(jnp.max(jnp.abs(Q - Qe))) < 4e-3
