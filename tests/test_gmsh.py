"""Gmsh MSH v2 ASCII mesh import for the IBFE path (VERDICT round 3,
missing #4 / next-round item 5): external user geometries enter
``fe/mesh.py`` from a file — the rebuild's analog of the reference's
libMesh readers (``FEDataManager`` via ``GmshIO``, SURVEY.md T16 [U]).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from ibamr_tpu.fe.mesh import (FEMesh, block_mesh_tet, block_mesh_tri,
                               box_hex_mesh, disc_mesh, read_gmsh,
                               rect_quad_mesh, to_quadratic, write_gmsh)

F64 = jnp.float64


ALL_MESHES = [
    ("TRI3", lambda: block_mesh_tri(3, 2)),
    ("TRI6", lambda: to_quadratic(block_mesh_tri(2, 2))),
    ("QUAD4", lambda: rect_quad_mesh(3, 2)),
    ("TET4", lambda: block_mesh_tet(2, 2, 2)),
    ("TET10", lambda: to_quadratic(block_mesh_tet(2, 1, 1))),
    ("HEX8", lambda: box_hex_mesh(2, 2, 2)),
]


@pytest.mark.parametrize("etype,maker", ALL_MESHES,
                         ids=[m[0] for m in ALL_MESHES])
def test_gmsh_roundtrip_full_menu(etype, maker, tmp_path):
    """write_gmsh -> read_gmsh is the identity (nodes, connectivity,
    type) for EVERY element family of the menu — including the TET10
    midside reorder between Gmsh and libMesh conventions."""
    m = maker()
    p = str(tmp_path / f"{etype}.msh")
    write_gmsh(m, p)
    m2 = read_gmsh(p)
    assert m2.elem_type == etype
    np.testing.assert_allclose(m2.nodes, m.nodes, rtol=0, atol=0)
    np.testing.assert_array_equal(m2.elems, m.elems)
    # the quadrature measure agrees (catches any ordering slip that
    # preserves the node set but scrambles the element maps)
    assert abs(m2.volume() - m.volume()) < 1e-14


def test_gmsh_noncontiguous_ids_and_mixed_types(tmp_path):
    """A hand-written file with gappy node ids and a mixed element
    block (boundary lines + triangles): the reader keeps the
    highest-dimension type and densely remaps the ids."""
    p = str(tmp_path / "mixed.msh")
    with open(p, "w") as f:
        f.write("""$MeshFormat
2.2 0 8
$EndMeshFormat
$Nodes
5
10 0 0 0
20 1 0 0
30 1 1 0
41 0 1 0
99 5 5 0
$EndNodes
$Elements
4
1 1 2 0 1 10 20
2 1 2 0 1 20 30
3 2 2 0 1 10 20 30
4 2 2 0 1 10 30 41
$EndElements
""")
    m = read_gmsh(p)
    assert m.elem_type == "TRI3"
    assert m.n_elems == 2
    # node 99 is unreferenced by the triangles -> dropped
    assert m.n_nodes == 4
    assert m.dim == 2
    assert abs(m.volume() - 1.0) < 1e-14   # unit square from 2 tris


def test_gmsh_explicit_type_selection(tmp_path):
    """elem_type picks a lower-dimension block when requested."""
    p = str(tmp_path / "two.msh")
    with open(p, "w") as f:
        f.write("""$MeshFormat
2.2 0 8
$EndMeshFormat
$Nodes
8
1 0 0 0
2 1 0 0
3 1 1 0
4 0 1 0
5 0 0 1
6 1 0 1
7 1 1 1
8 0 1 1
$EndNodes
$Elements
3
1 5 2 0 1 1 2 3 4 5 6 7 8
2 3 2 0 1 1 2 3 4
3 3 2 0 1 5 6 7 8
$EndElements
""")
    m = read_gmsh(p)                       # default: highest dim wins
    assert m.elem_type == "HEX8"
    m2 = read_gmsh(p, elem_type="QUAD4")
    assert m2.elem_type == "QUAD4"
    assert m2.n_elems == 2


def test_gmsh_version_guard(tmp_path):
    p = str(tmp_path / "v4.msh")
    with open(p, "w") as f:
        f.write("$MeshFormat\n4.1 0 8\n$EndMeshFormat\n")
    with pytest.raises(ValueError, match="v2 ASCII"):
        read_gmsh(p)


def test_ibfe_runs_from_file_loaded_mesh(tmp_path):
    """The IBFE-ex0 variant driven by a FILE-LOADED mesh: write the
    disc to .msh, read it back, build the FE assembly and run coupled
    IB/FE steps — the end-to-end external-geometry path."""
    from ibamr_tpu.fe.fem import neo_hookean
    from ibamr_tpu.grid import StaggeredGrid
    from ibamr_tpu.integrators.ibfe import IBFEMethod
    from ibamr_tpu.integrators.ib import IBExplicitIntegrator
    from ibamr_tpu.integrators.ins import INSStaggeredIntegrator

    disc = disc_mesh(radius=0.2, center=(0.5, 0.5), n_rings=3)
    p = str(tmp_path / "disc.msh")
    write_gmsh(disc, p)
    loaded = read_gmsh(p)
    assert loaded.elem_type == "TRI3"
    assert abs(loaded.volume() - disc.volume()) < 1e-14

    grid = StaggeredGrid(n=(32, 32), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    ins = INSStaggeredIntegrator(grid, mu=0.05, rho=1.0,
                                 convective_op_type="centered",
                                 dtype=F64)

    fe = IBFEMethod(loaded, neo_hookean(1.0, 4.0), kernel="IB_4",
                    dtype=F64)
    integ = IBExplicitIntegrator(ins, fe)
    st = integ.initialize(jnp.asarray(loaded.nodes, F64))
    for _ in range(3):
        st = integ.step(st, 1e-3)
    assert bool(jnp.all(jnp.isfinite(st.X)))
    # undeformed disc at rest: forces stay near zero, mesh stays put
    assert float(jnp.max(jnp.abs(st.X - jnp.asarray(loaded.nodes)))) \
        < 1e-3


def test_gmsh_surface_embedded_in_3d_keeps_z(tmp_path):
    """A TRI3 shell embedded in 3D (curved codim-1 IBFE configuration,
    ADVICE round 4): the reader keeps all three coordinate columns
    instead of silently flattening, and the surface bridge makes the
    result consumable by the codim-1 machinery."""
    from ibamr_tpu.fe.surface import (build_surface_assembly,
                                      sphere_surface_mesh,
                                      surface_mesh_from_fe)

    sph = sphere_surface_mesh(radius=0.3, n_subdiv=1)
    fem_like = FEMesh(nodes=sph.nodes, elems=sph.elems,
                      elem_type="TRI3")
    p = str(tmp_path / "shell.msh")
    write_gmsh(fem_like, p)
    loaded = read_gmsh(p)
    assert loaded.dim == 3                 # z preserved
    assert loaded.elem_type == "TRI3"
    np.testing.assert_allclose(loaded.nodes, sph.nodes, atol=1e-12)

    surf = surface_mesh_from_fe(loaded)
    asm = build_surface_assembly(surf)
    # octahedron-subdivision sphere area converges to 4 pi r^2 from
    # below; at n_subdiv=1 it is ~83% of the limit
    area = float(np.sum(np.asarray(asm.wdA)))
    assert 0.80 * 4 * np.pi * 0.3 ** 2 < area < 4 * np.pi * 0.3 ** 2


def test_gmsh_planar_sheet_not_promoted_by_other_blocks(tmp_path):
    """A mixed-dimension file (planar TRI3 sheet at z=0 + a TET4 block
    with z>0): selecting the TRI3 block must NOT inherit dim=3 from
    the unreferenced tet nodes (code-review round 5)."""
    p = str(tmp_path / "mixed3d.msh")
    with open(p, "w") as f:
        f.write("""$MeshFormat
2.2 0 8
$EndMeshFormat
$Nodes
7
1 0 0 0
2 1 0 0
3 0 1 0
4 2 0 0.5
5 3 0 0.5
6 2 1 0.5
7 2 0 1.5
$EndNodes
$Elements
2
1 2 2 0 1 1 2 3
2 4 2 0 1 4 5 6 7
$EndElements
""")
    tri = read_gmsh(p, elem_type="TRI3")
    assert tri.dim == 2                    # planar sheet stays 2D
    assert abs(tri.volume() - 0.5) < 1e-14
    tet = read_gmsh(p, elem_type="TET4")
    assert tet.dim == 3
