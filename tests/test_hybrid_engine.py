"""Hybrid transfer engine: Pallas-packed spread + XLA packed interp
over one shared PackedBuckets context (round-5 composition, motivated
by the on-chip phases table: spread is cheapest in Pallas, interp in
XLA-with-bf16). Oracle: the XLA scatter path. The load-bearing claim
is that ONE context built by ``buckets`` serves both backends'
transfer directions without re-packing."""

import jax
import jax.numpy as jnp
import numpy as np

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import interaction
from ibamr_tpu.ops.interaction_packed import suggest_chunks
from ibamr_tpu.ops.pallas_interaction import HybridPackedInteraction


def _engine(g, X, chunk=64, **kw):
    Q = suggest_chunks(g, X, tile=8, chunk=chunk, slack=1.3)
    return HybridPackedInteraction(g, kernel="IB_4", tile=8,
                                   chunk=chunk, nchunks=Q,
                                   interpret=True, **kw)


def test_hybrid_matches_scatter_shared_ctx():
    rng = np.random.default_rng(0)
    g = StaggeredGrid(n=(16, 16, 32), x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    X = jnp.asarray(rng.uniform(0, 1, (300, 3)), dtype=jnp.float32)
    F = jnp.asarray(rng.standard_normal((300, 3)), dtype=jnp.float32)
    eng = _engine(g, X)
    b = eng.buckets(X)          # ONE context for both directions
    f_hy = eng.spread_vel(F, X, b=b)
    f_ref = interaction.spread_vel(F, g, X, kernel="IB_4")
    for a, c in zip(f_ref, f_hy):
        scale = float(jnp.max(jnp.abs(a)))
        np.testing.assert_allclose(np.asarray(c), np.asarray(a),
                                   atol=2e-6 * scale)

    u = tuple(jnp.asarray(rng.standard_normal(g.n), dtype=jnp.float32)
              for _ in range(3))
    U_hy = eng.interpolate_vel(u, X, b=b)
    U_ref = interaction.interpolate_vel(u, g, X, kernel="IB_4")
    scale = float(jnp.max(jnp.abs(U_ref)))
    np.testing.assert_allclose(np.asarray(U_hy), np.asarray(U_ref),
                               atol=2e-6 * scale)


def test_hybrid_bf16_interp_tolerance():
    # bf16 compresses only the interp contraction operands; spread
    # stays f32 through the Pallas program — both within engine
    # tolerances of the scatter oracle
    rng = np.random.default_rng(2)
    g = StaggeredGrid(n=(16, 16, 16), x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    X = jnp.asarray(rng.uniform(0, 1, (200, 3)), dtype=jnp.float32)
    eng = _engine(g, X, compute_dtype=jnp.bfloat16)
    b = eng.buckets(X)
    u = tuple(jnp.asarray(rng.standard_normal(g.n), dtype=jnp.float32)
              for _ in range(3))
    U_hy = eng.interpolate_vel(u, X, b=b)
    U_ref = interaction.interpolate_vel(u, g, X, kernel="IB_4")
    scale = float(jnp.max(jnp.abs(U_ref)))
    np.testing.assert_allclose(np.asarray(U_hy), np.asarray(U_ref),
                               atol=2e-2 * scale)

    F = jnp.asarray(rng.standard_normal((200, 3)), dtype=jnp.float32)
    f_hy = eng.spread_vel(F, X, b=b)
    f_ref = interaction.spread_vel(F, g, X, kernel="IB_4")
    for a, c in zip(f_ref, f_hy):
        scale = float(jnp.max(jnp.abs(a)))
        np.testing.assert_allclose(np.asarray(c), np.asarray(a),
                                   atol=2e-6 * scale)


def test_hybrid_in_flagship_model():
    from ibamr_tpu.models.shell3d import build_shell_example

    integ, state = build_shell_example(
        n_cells=16, n_lat=16, n_lon=16, radius=0.25,
        use_fast_interaction="hybrid_packed_bf16")
    step = jax.jit(lambda s, d: integ.step(s, d))
    s1 = step(state, 1e-4)
    assert bool(jnp.isfinite(s1.X).all())

    # oracle: the scatter-path model advanced one step
    integ0, state0 = build_shell_example(
        n_cells=16, n_lat=16, n_lon=16, radius=0.25,
        use_fast_interaction=False)
    s0 = jax.jit(lambda s, d: integ0.step(s, d))(state0, 1e-4)
    np.testing.assert_allclose(np.asarray(s1.X), np.asarray(s0.X),
                               rtol=0, atol=5e-5)


def test_hybrid_bf16_registry_name():
    """``hybrid_bf16`` is the canonical registry/knob name of the
    pallas-spread + bf16-interp engine (``hybrid_packed_bf16`` stays
    as an alias); both the python arg and the reference-style input
    knob must build the same configuration."""
    from ibamr_tpu.models.shell3d import build_shell_example
    from ibamr_tpu.utils.input_db import parse_input_string

    integ, _ = build_shell_example(
        n_cells=16, n_lat=16, n_lon=16,
        use_fast_interaction="hybrid_bf16")
    eng = integ.ib.fast
    assert type(eng).__name__ == "HybridPackedInteraction"
    assert eng._xla.compute_dtype == jnp.bfloat16

    db = parse_input_string('''
CartesianGeometry { n_cells = 16, 16, 16 }
Shell { n_lat = 16 n_lon = 16 }
IBMethod { transfer_engine = "hybrid_bf16" }
''')
    integ2, _ = build_shell_example(input_db=db)
    assert type(integ2.ib.fast).__name__ == "HybridPackedInteraction"
    assert integ2.ib.fast._xla.compute_dtype == jnp.bfloat16


def test_hybrid_refresh_shares_one_context():
    # the hybrid engine's refresh delegates to the XLA twin: ONE
    # refreshed PackedBuckets must serve the pallas spread AND the
    # bf16 interp at the drifted position
    rng = np.random.default_rng(5)
    g = StaggeredGrid(n=(16, 16, 16), x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    X = jnp.asarray(rng.uniform(0, 1, (180, 3)), dtype=jnp.float32)
    eng = _engine(g, X, compute_dtype=jnp.bfloat16)
    b = eng.buckets(X)
    Xd = X - jnp.float32(0.4 * float(g.dx[0]))
    b2, hit = eng.refresh(b, Xd)
    assert bool(hit)
    F = jnp.asarray(rng.standard_normal((180, 3)), dtype=jnp.float32)
    f_hy = eng.spread_vel(F, Xd, b=b2)
    f_ref = interaction.spread_vel(F, g, Xd, kernel="IB_4")
    for a, c in zip(f_ref, f_hy):
        scale = float(jnp.max(jnp.abs(a)))
        np.testing.assert_allclose(np.asarray(c), np.asarray(a),
                                   atol=2e-6 * scale)
    u = tuple(jnp.asarray(rng.standard_normal(g.n), dtype=jnp.float32)
              for _ in range(3))
    U_hy = eng.interpolate_vel(u, Xd, b=b2)
    U_ref = interaction.interpolate_vel(u, g, Xd, kernel="IB_4")
    np.testing.assert_allclose(
        np.asarray(U_hy), np.asarray(U_ref),
        atol=2e-2 * float(jnp.max(jnp.abs(U_ref))))
