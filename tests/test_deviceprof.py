"""Device-time attribution tests (PR 10): trace parsing, span
mapping, residual accounting, roofline join, and the drift gate.

Most of this file drives ``ibamr_tpu/obs/deviceprof.py`` with
HAND-BUILT trace-viewer JSON — the attribution math must be testable
on a machine with no profiler at all, and a synthetic trace pins the
exact event shapes the two backends emit (TPU: ``/device:*``
processes with ``XLA Ops`` lanes and scope paths in ``tf_op`` args;
CPU/TFRT: op events scattered across host pool threads, identified
only by their ``hlo_module``/``hlo_op`` args). The one real capture
(``test_real_capture_attributes_driver_chunk``) closes the acceptance
loop: a CPU-backend ``jax.profiler`` capture of the solo driver chunk
must attribute >= 90% of device-lane time to the ``driver/chunk``
span, with the residual reported explicitly.
"""

import gzip
import json
import os

import pytest

from ibamr_tpu.obs import deviceprof
from ibamr_tpu.obs.roofline import census_sidecar, roofline_join

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# synthetic trace-viewer fixtures
# ---------------------------------------------------------------------------

def _meta(pid, pname, threads):
    out = [{"ph": "M", "pid": pid, "name": "process_name",
            "args": {"name": pname}}]
    for tid, tname in threads.items():
        out.append({"ph": "M", "pid": pid, "tid": tid,
                    "name": "thread_name", "args": {"name": tname}})
    return out


def _x(name, dur_us, pid=1, tid=1, ts=0, args=None):
    return {"ph": "X", "pid": pid, "tid": tid, "ts": ts,
            "dur": dur_us, "name": name, "args": args}


def _cpu_style_trace():
    """The TFRT-CPU shape: one host process, python-tracer events
    (args=None) interleaved with hlo-tagged op events on pool
    threads. 1000us of device-op time total: 900 in jit_chunk, 60 in
    an eager jit_exp, 40 carrying no identity at all."""
    events = _meta(1, "python", {1: "MainThread", 2: "pool-0"})
    events += [
        # python tracer noise — must NOT count as device time
        _x("FuncGraph", 5000, tid=1),
        _x("backend_compile", 2000, tid=1),
        # the chunk's ops, spread across two pool threads
        _x("fusion.1", 500, tid=1,
           args={"hlo_module": "jit_chunk", "hlo_op": "fusion.1"}),
        _x("fft.2", 300, tid=2,
           args={"hlo_module": "jit_chunk", "hlo_op": "fft.2"}),
        _x("dot_general.3", 100, tid=2,
           args={"hlo_module": "jit_chunk", "hlo_op": "dot.3"}),
        # eager constant-folding module (the real residual shape)
        _x("exp.4", 60, tid=2,
           args={"hlo_module": "jit_exp", "hlo_op": "exp.4"}),
        # an op event with NO module identity -> unattributed bucket
        _x("mystery_op", 40, tid=2, args={"hlo_op": "mystery_op"}),
    ]
    return {"displayTimeUnit": "ns", "traceEvents": events}


def _tpu_style_trace():
    """The TPU shape: a /device: process whose ``XLA Ops`` lane
    carries scope paths in ``tf_op``; the ``Steps`` lane overlaps the
    op lane and must be EXCLUDED (else every second double-counts)."""
    events = _meta(7, "/device:TPU:0 (chip 0)",
                   {1: "Steps", 2: "XLA Ops"})
    events += _meta(3, "python", {1: "MainThread"})
    events += [
        _x("step 0", 1000, pid=7, tid=1),          # Steps row: skip
        _x("fusion.9", 700, pid=7, tid=2,
           args={"tf_op": "jit(chunk)/driver/chunk/interp/fusion.9"}),
        _x("fft.1", 200, pid=7, tid=2,
           args={"tf_op": "jit(chunk)/driver/chunk/fft.1"}),
        _x("copy.2", 100, pid=7, tid=2, args={}),  # lane event, no scope
        _x("host python", 4000, pid=3, tid=1),     # host: skip
    ]
    return {"displayTimeUnit": "ns", "traceEvents": events}


def _write_capture(tmp_path, trace, name="host"):
    d = tmp_path / "cap" / "plugins" / "profile" / "2026_08_06"
    d.mkdir(parents=True, exist_ok=True)
    with gzip.open(d / f"{name}.trace.json.gz", "wb") as f:
        f.write(json.dumps(trace).encode())
    return str(tmp_path / "cap")


# ---------------------------------------------------------------------------
# event selection
# ---------------------------------------------------------------------------

def test_cpu_event_selection_ignores_python_tracer():
    events, lanes = deviceprof.device_op_events(_cpu_style_trace())
    # 5 hlo-tagged events; the 7s of python tracer noise excluded
    assert len(events) == 5
    assert sum(e["dur"] for e in events) == 1000
    assert {ln["thread"] for ln in lanes} == {"MainThread", "pool-0"}


def test_tpu_lane_selection_excludes_step_rows():
    events, lanes = deviceprof.device_op_events(_tpu_style_trace())
    # the Steps row (1000us) and host python (4000us) are excluded;
    # the unscoped copy on the op lane IS device time
    assert sum(e["dur"] for e in events) == 1000
    assert len(lanes) == 1 and lanes[0]["thread"] == "XLA Ops"


# ---------------------------------------------------------------------------
# attribution
# ---------------------------------------------------------------------------

def test_module_attribution_maps_jit_chunk_to_span():
    events, _ = deviceprof.device_op_events(_cpu_style_trace())
    s = deviceprof.attribute_events(events, ["driver", "driver/chunk"])
    # jit_chunk -> chunk -> driver/chunk leaf
    assert s["spans"]["driver/chunk"]["device_s"] == pytest.approx(
        900e-6)
    assert s["spans"]["driver/chunk"]["via"] == {"module": 3}
    # jit_exp has no span; grouped under its module name, explicitly
    assert s["spans"]["exp"]["device_s"] == pytest.approx(60e-6)
    assert s["spans"]["exp"]["via"] == {"module-name": 1}
    # the identity-free op is the residual, never dropped
    assert s["unattributed"] == {"mystery_op": pytest.approx(40e-6)}
    assert s["total_device_s"] == pytest.approx(1000e-6)
    assert s["attributed_s"] + s["unattributed_s"] == pytest.approx(
        s["total_device_s"])


def test_scope_prefix_attribution_beats_module():
    events, _ = deviceprof.device_op_events(_tpu_style_trace())
    s = deviceprof.attribute_events(events, ["driver/chunk",
                                             "driver/chunk/interp"])
    # deepest matching scope component wins: interp claims fusion.9
    assert s["spans"]["driver/chunk/interp"]["device_s"] == \
        pytest.approx(700e-6)
    assert s["spans"]["driver/chunk"]["device_s"] == pytest.approx(
        200e-6)
    assert s["unattributed"] == {"copy.2": pytest.approx(100e-6)}


def test_explicit_module_map_override():
    events, _ = deviceprof.device_op_events(_cpu_style_trace())
    s = deviceprof.attribute_events(
        events, [], module_map={"jit_exp": "driver/warmup"})
    assert s["spans"]["driver/warmup"]["device_s"] == pytest.approx(
        60e-6)


def test_span_leaf_map_prefers_shallowest_on_ambiguity():
    m = deviceprof.span_leaf_map(["a/chunk", "chunk", "b/c/chunk"])
    assert m["chunk"] == "chunk"


def test_attribute_capture_roundtrip(tmp_path):
    cap = _write_capture(tmp_path, _cpu_style_trace())
    s = deviceprof.attribute_capture(cap, span_paths=["driver/chunk"])
    assert deviceprof.validate_summary(s) == []
    assert s["trace_files"] == 1
    path = deviceprof.write_summary(cap, s)
    assert deviceprof.read_summary(cap) == json.load(open(path))
    compact = deviceprof.compact_summary(s)
    assert compact["spans"]["driver/chunk"]["device_s"] == \
        s["spans"]["driver/chunk"]["device_s"]
    assert "lanes" not in compact


# ---------------------------------------------------------------------------
# schema validation: malformation is loud
# ---------------------------------------------------------------------------

def test_validate_summary_catches_dropped_time(tmp_path):
    cap = _write_capture(tmp_path, _cpu_style_trace())
    s = deviceprof.attribute_capture(cap)
    assert deviceprof.validate_summary(s) == []
    bad = dict(s, attributed_s=0.0)       # time silently dropped
    assert any("time dropped" in p
               for p in deviceprof.validate_summary(bad))
    assert deviceprof.validate_summary({"schema": 99}) != []
    assert deviceprof.validate_summary("not a dict") != []
    bad2 = dict(s, fraction_attributed=1.5)
    assert any("fraction" in p for p in deviceprof.validate_summary(bad2))


# ---------------------------------------------------------------------------
# roofline join
# ---------------------------------------------------------------------------

def test_roofline_join_math():
    summary = {"total_device_s": 2.0,
               "op_classes": {"fft_s": 1.0, "dot_s": 0.5,
                              "other_s": 0.5}}
    census = {"executions": 10, "fft_bytes": 4_000_000_000,
              "fft_ops": 6, "dot_lhs_bytes": 1_000_000,
              "dot_rhs_bytes": 1_000_000, "dot_out_bytes": 2_000_000,
              "dot_flops": 1_000_000_000, "dot_count": 2}
    r = roofline_join(summary, census)
    # 4 GB per execution over 0.1 s of FFT time -> 40 GB/s achieved
    assert r["fft"]["achieved_gb_per_s"] == pytest.approx(40.0)
    # 1 GFLOP over 0.05 s -> 20 GFLOP/s
    assert r["dot"]["achieved_gflop_per_s"] == pytest.approx(20.0)
    assert r["fraction_of_step_accounted"] == pytest.approx(0.75)
    assert r["device_s_per_execution"] == pytest.approx(0.2)
    # no executions -> no join (never a divide-by-zero)
    assert roofline_join(summary, dict(census, executions=0)) is None


def test_census_sidecar_counts_ffts():
    import jax
    import jax.numpy as jnp

    def f(x):
        return jnp.fft.irfftn(jnp.fft.rfftn(x), s=x.shape)

    x = jnp.zeros((8, 8), jnp.float32)
    census = census_sidecar(jax.jit(f), (x,), label="t", executions=3)
    assert census["executions"] == 3
    assert census["fft_ops"] == 2
    assert census["fft_bytes"] > 0
    assert census["label"] == "t"


def test_capture_census_joins_into_summary(tmp_path):
    cap = _write_capture(tmp_path, _cpu_style_trace())
    with open(os.path.join(cap, deviceprof.CENSUS_NAME), "w") as f:
        json.dump({"schema": 1, "label": "n16", "executions": 5,
                   "fft_ops": 1, "fft_bytes": 3_000_000,
                   "dot_lhs_bytes": 0, "dot_rhs_bytes": 0,
                   "dot_out_bytes": 0, "dot_flops": 2_000_000,
                   "dot_count": 1}, f)
    s = deviceprof.attribute_capture(cap)
    assert s["roofline"]["executions"] == 5
    # fft.2 carried 300us -> 60us/exec over 3 MB -> 50 GB/s
    assert s["roofline"]["fft"]["achieved_gb_per_s"] == pytest.approx(
        50.0)


# ---------------------------------------------------------------------------
# the drift gate (tools/prof.py)
# ---------------------------------------------------------------------------

def _summarize(tmp_path, name, scale=1.0):
    trace = _cpu_style_trace()
    for e in trace["traceEvents"]:
        if e.get("ph") == "X" and (e.get("args") or {}).get(
                "hlo_module") == "jit_chunk":
            e["dur"] = e["dur"] * scale
    cap = _write_capture(tmp_path / name, trace)
    s = deviceprof.attribute_capture(cap, span_paths=["driver/chunk"])
    deviceprof.write_summary(cap, s)
    return cap


def test_diff_self_is_clean_inflation_regresses(tmp_path):
    from tools.prof import main as prof_main

    a = _summarize(tmp_path, "a")
    assert prof_main(["diff", a, a]) == 0
    b = _summarize(tmp_path, "b", scale=10.0)   # inflated chunk span
    assert prof_main(["diff", a, b]) == 2
    # the reverse direction is an improvement, not a regression
    assert prof_main(["diff", b, a]) == 1


def test_diff_band_tolerates_noise(tmp_path):
    from tools.prof import main as prof_main

    a = _summarize(tmp_path, "a")
    b = _summarize(tmp_path, "b", scale=1.10)   # 10% < 25% band
    assert prof_main(["diff", a, b]) == 0
    # tightening the band makes the same delta a regression... but
    # only past the absolute floor, which 90us of drift is not
    assert prof_main(["diff", a, b, "--tol-pct", "5"]) == 0
    assert prof_main(["diff", a, b, "--tol-pct", "5",
                      "--abs-floor", "10e-6"]) == 2


def test_diff_of_bench_jsons_with_embedded_summaries(tmp_path):
    from tools.prof import main as prof_main

    a = _summarize(tmp_path, "a")
    b = _summarize(tmp_path, "b", scale=10.0)

    def bench_json(cap, path):
        s = deviceprof.read_summary(cap)
        payload = {"stages": [], "profiles": [
            {"dir": cap, "stage": "n16", "rev": "abc", "bytes": 1,
             "attributed": True,
             "summary": deviceprof.compact_summary(s)}]}
        with open(path, "w") as f:
            json.dump(payload, f)
        return str(path)

    ja = bench_json(a, tmp_path / "A.json")
    jb = bench_json(b, tmp_path / "B.json")
    assert prof_main(["diff", ja, ja]) == 0
    assert prof_main(["diff", ja, jb]) == 2


def test_check_and_archive_refuse_malformed(tmp_path):
    from tools.prof import main as prof_main

    cap = _summarize(tmp_path, "a")
    assert prof_main(["check", cap]) == 0
    raw = deviceprof.find_trace_files(cap)
    assert raw
    # corrupt the summary: archive must exit 2 and keep the raw trace
    s = deviceprof.read_summary(cap)
    s["attributed_s"] = -1.0
    with open(os.path.join(cap, deviceprof.SUMMARY_NAME), "w") as f:
        json.dump(s, f)
    assert prof_main(["check", cap]) == 2
    assert prof_main(["archive", cap]) == 2
    assert deviceprof.find_trace_files(cap) == raw
    # restore a valid summary: archive prunes the raw trace, keeps it
    s["attributed_s"] = s["total_device_s"] - s["unattributed_s"]
    deviceprof.write_summary(cap, s)
    assert prof_main(["archive", cap]) == 0
    assert deviceprof.find_trace_files(cap) == []
    assert deviceprof.validate_summary(deviceprof.read_summary(cap)) \
        == []


# ---------------------------------------------------------------------------
# manifest compat + collision fix
# ---------------------------------------------------------------------------

def test_obs_compare_reads_old_and_new_profile_manifests():
    from tools.obs import _profile_entries

    old = _profile_entries({"profiles": ["/tmp/p/n256_ab12cd3"]})
    assert old["n256"]["dir"] == "/tmp/p/n256_ab12cd3"
    assert old["n256"]["attributed"] is False
    new = _profile_entries({"profiles": [
        {"dir": "/tmp/p/n256_ab12cd3", "stage": "n256", "rev": "ab1",
         "bytes": 123, "attributed": True,
         "summary": {"total_device_s": 1.0}}]})
    assert new["n256"]["summary"]["total_device_s"] == 1.0


def test_stage_profile_dir_decollides_repeated_labels():
    import argparse

    from bench import stage_profile_dir

    args = argparse.Namespace(profile="/tmp/prof",
                              profile_stages="n256,packed*")
    used = {}
    d1 = stage_profile_dir(args, "n256", "abc", used=used)
    d2 = stage_profile_dir(args, "n256", "abc", used=used)
    d3 = stage_profile_dir(args, "n256", "abc", used=used)
    assert d1 == "/tmp/prof/n256_abc"
    assert d2 == "/tmp/prof/n256_abc_2"
    assert d3 == "/tmp/prof/n256_abc_3"
    assert stage_profile_dir(args, "nomatch", "abc", used=used) == ""
    # without a tracking dict the legacy single-call behavior holds
    assert stage_profile_dir(args, "n256", "abc") == d1


# ---------------------------------------------------------------------------
# the real thing: a CPU-backend capture of the solo driver chunk
# ---------------------------------------------------------------------------

def test_real_capture_attributes_driver_chunk(tmp_path):
    """Acceptance: capture the driver chunk with jax.profiler on the
    CPU backend, attribute the trace against the run's ledger, and
    account for >= 90% of device-lane time — residual explicit."""
    import jax
    import jax.numpy as jnp

    from ibamr_tpu import obs
    from ibamr_tpu.utils.timers import profile_trace

    cap = str(tmp_path / "cap")
    led = str(tmp_path / "led")

    @jax.jit
    def chunk(x):
        for _ in range(8):
            x = jnp.fft.irfftn(jnp.fft.rfftn(
                jnp.sin(x) * 1.0001), s=x.shape)
        return x

    x = jnp.ones((64, 64), jnp.float32)
    chunk(x).block_until_ready()          # compile outside the capture
    with obs.ledger(os.path.join(led, "ledger.jsonl")):
        with profile_trace(cap, stage="solo"):
            for step in range(40):
                with obs.span("driver/chunk", step=step, block_on=x):
                    x = chunk(x)
            jax.block_until_ready(x)

    # satellite: profile_trace rode the bus — the ledger shows the
    # capture landing as a span plus a `profile` record naming the dir
    records = obs.read_ledger(os.path.join(led, "ledger.jsonl"))
    prof_recs = [r for r in records if r.get("kind") == "profile"]
    assert prof_recs and prof_recs[0]["capture_dir"] == cap
    assert prof_recs[0]["stage"] == "solo"
    assert any(r.get("kind") == "span"
               and r.get("path") == "profile_trace"
               for r in records)

    assert deviceprof.find_trace_files(cap), "profiler wrote no trace"
    summary = deviceprof.attribute_capture(cap, ledger=led)
    assert deviceprof.validate_summary(summary) == []
    total = summary["total_device_s"]
    assert total > 0
    # the chunk span nests under profile_trace's own span (PR 10
    # satellite), so its ledger path is profile_trace/driver/chunk
    chunk_s = sum(v["device_s"] for p, v in summary["spans"].items()
                  if p.endswith("driver/chunk"))
    # the acceptance bar: the solo chunk claims >= 90% of device time
    assert chunk_s >= 0.90 * total, (
        f"driver/chunk={chunk_s} of {total}: "
        f"{json.dumps(deviceprof.compact_summary(summary))[:800]}")
    # and the residual is explicit: every unclaimed second is named
    assert summary["attributed_s"] + summary["unattributed_s"] == \
        pytest.approx(total, rel=1e-6)
