"""Observability bus (PR 9): spans, counters, the run ledger, and the
tools that read them.

Contracts under test:

- the ledger is SIGKILL-safe: one ``os.write`` per line on an
  ``O_APPEND`` fd means a kill mid-run leaves only parseable records
  (plus at most one torn tail the reader must skip), with contiguous
  ``seq`` — and a reopened ledger resumes the sequence;
- counters are cumulative and readers take the LAST per-chunk snapshot,
  so a supervised rollback (re-running steps) cannot double-count
  supervisor events, and every incident record cross-references its
  ledger ``seq``;
- span nesting produces slash paths whose percent-of-parent math is
  exact, and ``TimerManager.scope`` emits spans without breaking its
  own report;
- the Prometheus snapshot lints against the text exposition format;
- device-memory watermark sampling is a clean no-op where the backend
  reports nothing (CPU) and survives a device whose ``memory_stats``
  raises;
- the ledger's self-accounted overhead stays under the 2% warm-chunk
  budget;
- a supervised fleet run produces a ledger ``tools/obs.py summary``
  renders (phase tree + counters + incidents) — the PR's acceptance
  path.
"""

import json
import math
import os
import re
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu import obs
from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
from ibamr_tpu.utils.hierarchy_driver import HierarchyDriver, RunConfig
from ibamr_tpu.utils.lanes import stack_lanes
from ibamr_tpu.utils.supervisor import ResilientDriver
from tools.fault_injection import lane_nan_injector

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ins(n=16, mu=0.01):
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    return INSStaggeredIntegrator(g, rho=1.0, mu=mu, dtype=jnp.float64)


def _tg_state(integ, amp=1.0):
    g = integ.grid
    xf, yc = g.face_centers(0, jnp.float64)
    xc, yf = g.face_centers(1, jnp.float64)
    u = amp * jnp.sin(2 * math.pi * xf) * jnp.cos(2 * math.pi * yc) \
        + 0 * yc
    v = -amp * jnp.cos(2 * math.pi * xc) * jnp.sin(2 * math.pi * yf) \
        + 0 * xc
    return integ.initialize(u0_arrays=(u, v))


# ---------------------------------------------------------------------------
# ledger durability
# ---------------------------------------------------------------------------

_KILL_CHILD = r"""
import os, sys, time
sys.path.insert(0, {repo!r})
from ibamr_tpu.obs import RunLedger

led = RunLedger({path!r})
print("ready", flush=True)
i = 0
while True:
    led.append("span", {{"name": "work", "path": "work", "depth": 0,
                         "dur_s": 0.001, "i": i}})
    i += 1
    time.sleep(0.002)
"""


def test_ledger_sigkill_round_trip(tmp_path):
    """SIGKILL mid-append stream: every surviving line parses, seq is
    contiguous from 0, and a reopened ledger RESUMES the sequence."""
    path = str(tmp_path / "ledger.jsonl")
    proc = subprocess.Popen(
        [sys.executable, "-c",
         _KILL_CHILD.format(repo=REPO_ROOT, path=path)],
        stdout=subprocess.PIPE, text=True)
    try:
        assert proc.stdout.readline().strip() == "ready"
        # let it stream records, then kill WITHOUT warning
        deadline = time.time() + 30.0
        while time.time() < deadline:
            recs = obs.read_ledger(path)
            if len(recs) > 20:
                break
            time.sleep(0.05)
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    recs = obs.read_ledger(path)
    assert len(recs) > 20
    seqs = [r["seq"] for r in recs]
    assert seqs == list(range(len(seqs))), "seq gap after SIGKILL"
    assert recs[0]["kind"] == "run_start"
    run_id = recs[0]["run_id"]
    assert all(r["run_id"] == run_id for r in recs)

    # resume: a fresh ledger on the same file continues the sequence
    led = obs.RunLedger(path)
    try:
        nxt = led.append("note", {"resumed": True})
    finally:
        led.close()
    assert nxt > seqs[-1]
    recs2 = obs.read_ledger(path)
    assert recs2[-1]["kind"] == "note" and recs2[-1]["seq"] == nxt


def test_ledger_skips_torn_tail(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    with obs.RunLedger(path) as led:
        led.append("span", {"name": "a"})
    with open(path, "ab") as f:
        f.write(b'{"seq": 99, "kind": "span", "tru')   # torn tail
    recs = obs.read_ledger(path)
    assert [r["seq"] for r in recs] == [0, 1]
    # and a reader also rejects a parseable line WITHOUT a seq
    with open(path, "ab") as f:
        f.write(b'\n{"kind": "noise"}\n')
    assert [r["seq"] for r in obs.read_ledger(path)] == [0, 1]


def test_ledger_jsonable_nonfinite(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    with obs.RunLedger(path) as led:
        led.append("vitals", {"max_u": float("nan"),
                              "arr": np.float32(2.0)})
    rec = obs.read_ledger(path)[-1]
    assert rec["max_u"] is None
    assert rec["arr"] == 2.0


def test_run_id_is_fingerprint_digest():
    fp = {"config_digest": "abc", "engine": "packed"}
    a = obs.run_id_from_fingerprint(fp)
    b = obs.run_id_from_fingerprint(dict(fp))
    assert a == b and re.fullmatch(r"[0-9a-f]{16}", a)
    # no fingerprint: still AN identity, just not a reproducible one
    assert obs.run_id_from_fingerprint(None) != \
        obs.run_id_from_fingerprint(None)


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

def test_span_nesting_paths_and_error_tag(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    with obs.ledger(path):
        with obs.span("outer", attempt=1):
            with obs.span("inner"):
                pass
        with pytest.raises(RuntimeError):
            with obs.span("bad"):
                raise RuntimeError("boom")
    spans = [r for r in obs.read_ledger(path) if r["kind"] == "span"]
    by_name = {s["name"]: s for s in spans}
    assert by_name["inner"]["path"] == "outer/inner"
    assert by_name["inner"]["depth"] == 1
    assert by_name["outer"]["path"] == "outer"
    assert by_name["outer"]["attrs"] == {"attempt": 1}
    assert by_name["bad"]["error"] == "RuntimeError"
    # inner closes BEFORE outer (children precede parents in the file)
    assert spans.index(by_name["inner"]) < spans.index(by_name["outer"])


def test_span_block_on_orders_clock_after_dispatch(tmp_path):
    """block_on: the span must not close before the async work it
    timed — its duration covers block_until_ready."""
    path = str(tmp_path / "ledger.jsonl")
    x = jnp.ones((256, 256))
    f = jax.jit(lambda a: a @ a)
    _ = f(x).block_until_ready()     # compile outside the span
    with obs.ledger(path):
        with obs.span("mm", block_on=f(x)):
            pass
    rec = [r for r in obs.read_ledger(path) if r["kind"] == "span"][0]
    assert rec["dur_s"] >= 0.0


def test_percent_of_parent_math():
    from tools.obs import percent_of_parent, span_tree
    recs = [
        {"seq": 0, "kind": "span", "path": "run", "dur_s": 4.0},
        {"seq": 1, "kind": "span", "path": "run/a", "dur_s": 1.0},
        {"seq": 2, "kind": "span", "path": "run/a", "dur_s": 1.0},
        {"seq": 3, "kind": "span", "path": "run/b", "dur_s": 1.0},
        {"seq": 4, "kind": "span", "path": "run/a/x", "dur_s": 0.5},
    ]
    tree = span_tree(recs)
    assert tree["run/a"]["count"] == 2
    assert percent_of_parent(tree, "run/a") == pytest.approx(50.0)
    assert percent_of_parent(tree, "run/b") == pytest.approx(25.0)
    assert percent_of_parent(tree, "run/a/x") == pytest.approx(25.0)
    # root charged against the root total when no wall is given
    assert percent_of_parent(tree, "run") == pytest.approx(100.0)
    # a slash inside one span NAME must not invent a phantom parent
    tree2 = span_tree([{"seq": 0, "kind": "span",
                        "path": "driver/chunk", "dur_s": 2.0}])
    assert percent_of_parent(tree2, "driver/chunk",
                             wall_s=4.0) == pytest.approx(50.0)


def test_timer_scope_emits_span(tmp_path):
    from ibamr_tpu.utils.timers import TimerManager
    tm = TimerManager()
    path = str(tmp_path / "ledger.jsonl")
    with obs.ledger(path):
        with tm.scope("advance"):
            time.sleep(0.01)
    spans = [r for r in obs.read_ledger(path) if r["kind"] == "span"]
    assert [s["name"] for s in spans] == ["advance"]
    # the legacy timer table still accumulated (one path, two readers)
    assert tm.get("advance").total >= 0.01
    assert spans[0]["dur_s"] >= 0.01


# ---------------------------------------------------------------------------
# counters / gauges / exporter
# ---------------------------------------------------------------------------

def test_counter_identity_and_labels():
    obs.reset_metrics()
    c1 = obs.counter("test_events_total", stage="a")
    c2 = obs.counter("test_events_total", stage="a")
    c3 = obs.counter("test_events_total", stage="b")
    assert c1 is c2 and c1 is not c3
    c1.inc()
    c1.inc(2)
    c3.inc()
    snap = obs.metrics_snapshot()["counters"]
    assert snap['test_events_total{stage="a"}'] == 3
    assert snap['test_events_total{stage="b"}'] == 1
    # reset zeroes values but keeps the cached handles LIVE
    obs.reset_metrics()
    c1.inc()
    assert obs.metrics_snapshot()["counters"][
        'test_events_total{stage="a"}'] == 1


def test_prometheus_export_lints(tmp_path):
    obs.reset_metrics()
    obs.counter("lint_events_total", kind='we"ird', k2="b").inc(7)
    obs.gauge("lint_depth").set(2.5)
    obs.describe("lint_events_total", "Lint fixture counter.")
    h = obs.histogram("lint_latency_seconds", stage="a")
    for v in (0.001, 0.02, 3.0):
        h.observe(v)
    text = obs.prometheus_text()
    name = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
    label = r'[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
    sample = re.compile(
        rf"^{name}(?:\{{{label}(?:,{label})*\}})? -?[0-9.e+-]+$")
    type_line = re.compile(
        rf"^# TYPE {name} (counter|gauge|histogram)$")
    help_line = re.compile(rf"^# HELP {name} \S.*$")
    seen_types, seen_helps = set(), set()
    for line in text.strip().splitlines():
        if line.startswith("# TYPE"):
            m = type_line.match(line)
            assert m, f"bad TYPE line: {line!r}"
            seen_types.add(line.split()[2])
            continue
        if line.startswith("#"):
            m = help_line.match(line)
            assert m, f"bad HELP line: {line!r}"
            seen_helps.add(line.split()[2])
            continue
        assert sample.match(line), f"bad sample line: {line!r}"
    assert "lint_events_total" in seen_types
    assert "lint_depth" in seen_types
    assert "lint_latency_seconds" in seen_types
    assert "lint_events_total" in seen_helps
    # histogram family: full cumulative series with le labels
    buckets = [ln for ln in text.splitlines()
               if ln.startswith("lint_latency_seconds_bucket{")]
    assert buckets and 'le="+Inf"' in buckets[-1]
    assert all('stage="a"' in ln for ln in buckets)
    cums = [int(ln.rsplit(" ", 1)[1]) for ln in buckets]
    assert cums == sorted(cums) and cums[-1] == 3   # cumulative
    assert any(ln.startswith("lint_latency_seconds_sum{")
               for ln in text.splitlines())
    assert any(ln.startswith("lint_latency_seconds_count{")
               and ln.rstrip().endswith(" 3")
               for ln in text.splitlines())
    # ledger-snapshot rendering takes the same path (and an
    # undescribed family gets no HELP line — exposition unchanged)
    out = tmp_path / "metrics.prom"
    obs.write_prometheus(str(out),
                         counters={"from_ledger_total": 3}, gauges={})
    assert out.read_text() == "# TYPE from_ledger_total counter\n" \
                              "from_ledger_total 3\n"
    # a ledger histogram snapshot rendered standalone
    snap = obs.metrics_snapshot()["histograms"]
    text2 = obs.prometheus_text(histograms=snap)
    assert "# TYPE lint_latency_seconds histogram" in text2
    assert 'le="+Inf",stage="a"} 3' in text2


def test_memory_watermarks_cpu_noop(monkeypatch):
    # CPU backend: no memory_stats -> zero samples, zero errors
    assert obs.sample_memory_watermarks() >= 0

    class _Raising:
        id = 0

        def memory_stats(self):
            raise NotImplementedError

    class _Reporting:
        id = 1

        def memory_stats(self):
            return {"bytes_in_use": 123, "peak_bytes_in_use": 456}

    monkeypatch.setattr(jax, "local_devices",
                        lambda: [_Raising(), _Reporting()])
    obs.reset_metrics()
    assert obs.sample_memory_watermarks() == 2
    g = obs.metrics_snapshot()["gauges"]
    assert g['device_bytes_in_use{device="1"}'] == 123
    assert g['device_peak_bytes_in_use{device="1"}'] == 456


def test_chunk_boundary_noop_without_ledger():
    assert obs.chunk_boundary(step=1, chunk_wall_s=0.1) is None


# ---------------------------------------------------------------------------
# supervised-rollback counter consistency
# ---------------------------------------------------------------------------

def test_rollback_counters_do_not_double_count(tmp_path):
    """A lane fault that costs one rollback re-RUNS steps, but the
    last counters snapshot reports exactly one lane_rollback — and the
    ledger's incident records cross-reference by seq."""
    obs.reset_metrics()
    integ = _ins()
    B, BAD, steps, dt = 3, 1, 8, 1e-3
    states = [_tg_state(integ, amp=1.0 + 0.05 * i) for i in range(B)]
    inj = dict(at_step=4, lane=BAD, fleet_size=B, leaf_path="u[0]",
               step_attr="k", dt_gate=dt)
    drv = HierarchyDriver(
        integ, RunConfig(dt=dt, num_steps=steps, health_interval=2,
                         restart_interval=2),
        lanes=B, fleet_step_wrap=lambda s: lane_nan_injector(s, **inj))
    sup = ResilientDriver(drv, str(tmp_path), max_retries=1,
                          dt_backoff=0.5, handle_signals=False)
    path = str(tmp_path / "ledger.jsonl")
    with obs.ledger(path):
        sup.run(stack_lanes(states))

    recs = obs.read_ledger(path)
    rolls = [r for r in sup.incidents
             if r.get("event") == "lane_rollback"]
    assert len(rolls) == 1

    snaps = [r for r in recs if r["kind"] == "counters"]
    assert snaps, "driver never flushed a chunk boundary"
    last = snaps[-1]["counters"]
    # the reader contract: the LAST cumulative snapshot equals the true
    # event count — even though the fault chunk ran twice
    assert last["supervisor_lane_rollbacks_total"] == 1
    # naive summing across snapshots WOULD overcount; pin that the
    # cumulative value appears in more than one snapshot so the
    # last-not-sum discipline is actually load-bearing
    tallies = [s["counters"].get("supervisor_lane_rollbacks_total", 0)
               for s in snaps]
    assert sum(tallies) >= 1
    # steps counter: monotonic across snapshots (cumulative)
    steps_seen = [s["counters"]["driver_steps_total"] for s in snaps]
    assert steps_seen == sorted(steps_seen)

    # every supervisor incident got a ledger twin with matching seq
    inc_recs = {r["seq"]: r for r in recs if r["kind"] == "incident"}
    for rec in sup.incidents:
        seq = rec.get("ledger_seq")
        assert seq in inc_recs
        assert inc_recs[seq]["event"] == rec["event"]


# ---------------------------------------------------------------------------
# overhead pin
# ---------------------------------------------------------------------------

def test_ledger_overhead_under_two_percent_warm(tmp_path):
    """The observability bill, self-accounted: on WARM chunks (trace
    cached by the first run) the ledger's own overhead must stay under
    2% of chunk wall. Production-shaped chunks (tens of steps on a
    real grid — the same shape the flight-recorder overhead pin uses):
    per-chunk telemetry is a handful of host appends, so the budget
    only means anything against a chunk that does real work."""
    integ = _ins(n=128)
    cfg = RunConfig(dt=1e-4, num_steps=192, health_interval=96)
    drv = HierarchyDriver(integ, cfg)
    st = _tg_state(integ)
    drv.run(st)                       # compile; telemetry off
    path = str(tmp_path / "ledger.jsonl")
    with obs.ledger(path) as led:
        t0 = time.perf_counter()
        drv.run(st)
        wall = time.perf_counter() - t0
        overhead = led.overhead_s
    assert overhead < 0.02 * wall, \
        f"obs overhead {overhead:.6f}s is >=2% of warm wall {wall:.3f}s"
    # and the run_end record published the same accounting
    end = [r for r in obs.read_ledger(path) if r["kind"] == "run_end"]
    assert end and end[0]["overhead_s"] >= 0.0


# ---------------------------------------------------------------------------
# heartbeat / fsck cross-references
# ---------------------------------------------------------------------------

def test_heartbeat_carries_ledger_pointer(tmp_path):
    from ibamr_tpu.utils.watchdog import RunWatchdog, read_heartbeat
    hb = str(tmp_path / "heartbeat.json")
    wd = RunWatchdog(heartbeat_path=hb)
    wd.beat(step=4)
    assert "ledger_path" not in read_heartbeat(hb)   # solo schema kept
    wd.beat(step=8, ledger_path=str(tmp_path / "ledger.jsonl"),
            ledger_seq=17)
    payload = read_heartbeat(hb)
    assert payload["ledger_path"].endswith("ledger.jsonl")
    assert payload["ledger_seq"] == 17


def test_ckpt_fsck_reports_run_id(tmp_path):
    from tools.ckpt_fsck import audit
    with obs.RunLedger(str(tmp_path / "ledger.jsonl"),
                       fingerprint={"config_digest": "x"}) as led:
        rid = led.run_id
    report = audit(str(tmp_path))
    assert report["run_id"] == rid
    # a pre-ledger tree audits as before
    os.makedirs(str(tmp_path / "empty"))
    assert audit(str(tmp_path / "empty"))["run_id"] is None


# ---------------------------------------------------------------------------
# MetricsLogger satellite
# ---------------------------------------------------------------------------

def test_metrics_logger_nonfinite_to_null(tmp_path):
    from ibamr_tpu.utils.metrics import MetricsLogger
    path = str(tmp_path / "metrics.jsonl")
    with MetricsLogger(path) as m:
        m.log({"t": 0.5, "cfl": float("nan"),
               "dt": float("-inf"), "k": 3})
    line = open(path).read().strip()
    assert "NaN" not in line and "Infinity" not in line
    rec = json.loads(line)               # strict parse must succeed
    assert rec["cfl"] is None and rec["cfl_nonfinite"] == "nan"
    assert rec["dt"] is None and rec["dt_nonfinite"] == "-inf"
    assert rec["k"] == 3


# ---------------------------------------------------------------------------
# acceptance: supervised fleet run -> ledger -> tools/obs.py summary
# ---------------------------------------------------------------------------

def test_fleet_run_ledger_renders_summary(tmp_path, capsys):
    from tools.fleet import run_fleet
    from tools.obs import main as obs_main
    obs.reset_metrics()
    integ = _ins()
    B, steps, dt = 2, 8, 1e-3
    states = [_tg_state(integ, amp=1.0 + 0.05 * i) for i in range(B)]
    cfg = RunConfig(dt=dt, num_steps=steps, health_interval=2,
                    restart_interval=4)
    summary, _final = run_fleet(integ, stack_lanes(states), cfg, B,
                                directory=str(tmp_path))
    assert summary["ledger_path"] == str(tmp_path / "ledger.jsonl")
    assert summary["ledger_records"] >= 4
    recs = obs.read_ledger(summary["ledger_path"])
    kinds = {r["kind"] for r in recs}
    assert {"run_start", "span", "counters", "run_end"} <= kinds

    rc = obs_main(["summary", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "run_id:" in out
    assert "chunk" in out                    # the phase tree
    assert "driver_steps_total" in out       # the counter table
    assert "incidents:" in out               # the timeline section

    # the same ledger snapshot exports as Prometheus text
    snap = [r for r in recs if r["kind"] == "counters"][-1]
    text = obs.prometheus_text(counters=snap["counters"],
                               gauges=snap["gauges"])
    assert "# TYPE driver_steps_total counter" in text


# ---------------------------------------------------------------------------
# latency histograms
# ---------------------------------------------------------------------------

def test_histogram_identity_and_reset_liveness():
    obs.reset_metrics()
    h1 = obs.histogram("hist_demo_seconds", path="warm")
    h2 = obs.histogram("hist_demo_seconds", path="warm")
    assert h1 is h2                       # registry identity, like counters
    h3 = obs.histogram("hist_demo_seconds", path="cold")
    assert h3 is not h1
    h1.observe(0.5)
    h1.observe(2.0)
    assert h1.count == 2 and h1.sum == 2.5
    snap = obs.metrics_snapshot()["histograms"]
    assert snap['hist_demo_seconds{path="warm"}']["count"] == 2
    # reset zeroes in place: the held handle stays live
    obs.reset_metrics()
    assert h1.count == 0 and h1.sum == 0.0
    h1.observe(1.0)
    assert obs.metrics_snapshot()["histograms"][
        'hist_demo_seconds{path="warm"}']["count"] == 1


def test_histogram_concurrent_observes_lose_no_counts():
    """observe() must be GIL-atomic: threaded observers may not lose
    increments (the same pin counters carry)."""
    import threading
    obs.reset_metrics()
    h = obs.histogram("hist_race_seconds")
    n_threads, n_obs = 4, 20_000
    vals = [1e-5, 1e-3, 0.1, 10.0]

    def worker(v):
        for _ in range(n_obs):
            h.observe(v)

    ts = [threading.Thread(target=worker, args=(vals[i],))
          for i in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert h.count == n_threads * n_obs
    assert math.isclose(h.sum, n_obs * sum(vals), rel_tol=1e-9)


def test_histogram_percentiles_vs_numpy_oracle():
    """Bucketed estimates must land within one bucket ratio
    (10**(1/6) per decade-sixth bounds) of the exact percentile."""
    rng = np.random.default_rng(7)
    samples = rng.lognormal(mean=-3.0, sigma=1.2, size=5000)
    h = obs.Histogram("oracle_seconds", ())
    for v in samples:
        h.observe(float(v))
    ratio = 10.0 ** (1.0 / 6.0)
    for q in (0.5, 0.95, 0.99):
        est = h.quantile(q)
        exact = float(np.percentile(samples, 100.0 * q))
        assert exact / ratio <= est <= exact * ratio, \
            f"q={q}: est {est:.6g} vs exact {exact:.6g}"
    # degenerate cases
    empty = obs.Histogram("empty_seconds", ())
    assert empty.quantile(0.5) is None
    over = obs.Histogram("over_seconds", ())
    over.observe(1e9)                     # lands in the +Inf bucket
    assert over.quantile(0.99) == obs.HISTOGRAM_BOUNDS[-1]


def test_quantiles_from_counts_matches_handle():
    h = obs.Histogram("qfc_seconds", ())
    for v in (0.001, 0.002, 0.02, 0.5, 0.5, 3.0):
        h.observe(v)
    counts = h.snapshot()["counts"]
    qs = obs.quantiles_from_counts(counts, [0.5, 0.95, 0.99])
    assert qs == [h.quantile(0.5), h.quantile(0.95), h.quantile(0.99)]


def test_chunk_boundary_snapshots_histograms(tmp_path):
    obs.reset_metrics()
    path = str(tmp_path / "ledger.jsonl")
    with obs.ledger(path):
        obs.histogram("hist_led_seconds").observe(0.25)
        obs.chunk_boundary()
    recs = [r for r in obs.read_ledger(path) if r["kind"] == "counters"]
    assert recs and recs[-1]["histograms"][
        "hist_led_seconds"]["count"] == 1


# ---------------------------------------------------------------------------
# request-scoped trace identity
# ---------------------------------------------------------------------------

def test_trace_scope_stamps_emit_and_span(tmp_path):
    obs.reset_metrics()
    path = str(tmp_path / "ledger.jsonl")
    tid = obs.new_trace_id()
    assert re.fullmatch(r"[0-9a-f]{16}", tid)
    with obs.ledger(path):
        with obs.trace_scope(tid):
            assert obs.current_trace() == (tid,)
            obs.emit("demo_event", detail=1)
            with obs.span("demo/phase"):
                pass
        obs.emit("outside_event")         # after scope: unstamped
    recs = obs.read_ledger(path)
    by_kind = {}
    for r in recs:
        by_kind.setdefault(r["kind"], []).append(r)
    assert by_kind["demo_event"][0]["trace_id"] == tid
    assert by_kind["span"][0]["trace_id"] == tid
    assert "trace_id" not in by_kind["outside_event"][0]
    assert obs.record_trace_ids(by_kind["demo_event"][0]) == (tid,)
    assert obs.record_trace_ids(by_kind["outside_event"][0]) == ()


def test_trace_scope_batch_stamps_id_list(tmp_path):
    obs.reset_metrics()
    path = str(tmp_path / "ledger.jsonl")
    t1, t2 = obs.new_trace_id(), obs.new_trace_id()
    with obs.ledger(path):
        with obs.trace_scope(t1, t2):     # a batch serving two requests
            obs.emit("batch_event")
        with obs.trace_scope(t1, None):   # Nones are dropped
            obs.emit("solo_event")
    recs = {r["kind"]: r for r in obs.read_ledger(path)}
    assert recs["batch_event"]["trace_ids"] == [t1, t2]
    assert "trace_id" not in recs["batch_event"]
    assert recs["solo_event"]["trace_id"] == t1
    assert obs.record_trace_ids(recs["batch_event"]) == (t1, t2)


def test_heartbeat_serving_fields(tmp_path):
    from ibamr_tpu.utils.watchdog import RunWatchdog, read_heartbeat
    hb = str(tmp_path / "heartbeat.json")
    wd = RunWatchdog(heartbeat_path=hb)
    if obs.peek_gauge("serve_requests_inflight") is None:
        wd.beat(step=1)                   # solo schema: fields absent
        assert "requests_inflight" not in read_heartbeat(hb)
    # once the router's gauges exist the beat carries them
    obs.gauge("serve_requests_inflight").set(2)
    obs.gauge("serve_requests_completed").set(5)
    wd.beat(step=2)
    payload = read_heartbeat(hb)
    assert payload["requests_inflight"] == 2
    assert payload["requests_completed"] == 5
    assert isinstance(payload["requests_completed"], int)
