"""Pallas bucketed-spread kernel (SURVEY.md §7.3 hard-part #1, P23).

Runs in Pallas interpret mode on the CPU suite; the compiled-TPU path
is exercised by ``bench.py`` (spread_paths comparison). Oracle: the
XLA scatter path (ops.interaction.spread) at f32 tolerances.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import interaction
from ibamr_tpu.ops.interaction_fast import FastInteraction
from ibamr_tpu.ops.pallas_interaction import PallasSpread3D


def _setup(n=(16, 16, 32), N=300, cap=64, seed=0):
    rng = np.random.default_rng(seed)
    g = StaggeredGrid(n=n, x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    X = jnp.asarray(rng.uniform(0, 1, (N, 3)), dtype=jnp.float32)
    F = jnp.asarray(rng.standard_normal((N, 3)), dtype=jnp.float32)
    fast = FastInteraction(g, kernel="IB_4", tile=8, cap=cap)
    ps = PallasSpread3D(g, kernel="IB_4", tile=8, cap=cap,
                        interpret=True)
    return g, X, F, fast, ps


def test_pallas_spread_matches_scatter():
    g, X, F, fast, ps = _setup()
    b = fast.buckets(X)
    f_pl = ps.spread_vel(F, X, b)
    f_ref = interaction.spread_vel(F, g, X, kernel="IB_4")
    for a, c in zip(f_ref, f_pl):
        scale = float(jnp.max(jnp.abs(a)))
        np.testing.assert_allclose(np.asarray(c), np.asarray(a),
                                   atol=2e-6 * scale)


def test_pallas_spread_cell_centering():
    g, X, F, fast, ps = _setup(seed=1)
    b = fast.buckets(X)
    f_pl = ps.spread(F[:, 0], X, "cell", b)
    f_ref = interaction.spread(F[:, 0], g, X, centering="cell",
                               kernel="IB_4")
    scale = float(jnp.max(jnp.abs(f_ref)))
    np.testing.assert_allclose(np.asarray(f_pl), np.asarray(f_ref),
                               atol=2e-6 * scale)


def test_pallas_spread_overflow_fallback():
    """Tile overflow flows through the compact scatter fallback."""
    rng = np.random.default_rng(2)
    g = StaggeredGrid(n=(16, 16, 16), x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    N = 200
    # cluster into one tile
    X = jnp.asarray(np.stack([rng.uniform(0.0, 0.05, N),
                              rng.uniform(0.0, 0.05, N),
                              rng.uniform(0, 1, N)], axis=1),
                    dtype=jnp.float32)
    F = jnp.asarray(rng.standard_normal((N, 3)), dtype=jnp.float32)
    fast = FastInteraction(g, kernel="IB_4", tile=8, cap=16)
    ps = PallasSpread3D(g, kernel="IB_4", tile=8, cap=16, interpret=True)
    b = fast.buckets(X)
    assert bool(b.any_overflow)
    f_pl = ps.spread_vel(F, X, b)
    f_ref = interaction.spread_vel(F, g, X, kernel="IB_4")
    for a, c in zip(f_ref, f_pl):
        scale = float(jnp.max(jnp.abs(a)))
        np.testing.assert_allclose(np.asarray(c), np.asarray(a),
                                   atol=2e-6 * scale)


def test_pallas_total_force_conserved():
    """Spreading conserves the total force integral exactly (zeroth
    moment of the kernel), including across tile seams."""
    g, X, F, fast, ps = _setup(seed=3)
    b = fast.buckets(X)
    f_pl = ps.spread_vel(F, X, b)
    for d in range(3):
        np.testing.assert_allclose(
            float(jnp.sum(f_pl[d])) * g.cell_volume,
            float(jnp.sum(F[:, d])), rtol=1e-5)


def test_pallas_rejects_2d():
    g = StaggeredGrid(n=(16, 16), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    with pytest.raises(ValueError, match="3D"):
        PallasSpread3D(g)


def test_pallas_interp_matches_gather():
    """The interp twin (VERDICT round 2 item 5): PallasInteraction
    gathers grid velocity at markers identically to the XLA gather."""
    from ibamr_tpu.ops.pallas_interaction import PallasInteraction

    rng = np.random.default_rng(3)
    g = StaggeredGrid(n=(16, 16, 32), x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    X = jnp.asarray(rng.uniform(0, 1, (300, 3)), dtype=jnp.float32)
    u = tuple(jnp.asarray(rng.standard_normal(g.n), dtype=jnp.float32)
              for _ in range(3))
    eng = PallasInteraction(g, kernel="IB_4", tile=8, cap=64,
                            interpret=True)
    U_pl = eng.interpolate_vel(u, X)
    U_ref = interaction.interpolate_vel(u, g, X, kernel="IB_4")
    scale = float(jnp.max(jnp.abs(U_ref)))
    np.testing.assert_allclose(np.asarray(U_pl), np.asarray(U_ref),
                               atol=2e-6 * scale)


def test_pallas_interp_overflow_and_mask():
    """Undersized capacity: overflow markers flow through the compact
    gather fallback; masked markers contribute zero."""
    from ibamr_tpu.ops.pallas_interaction import PallasInteraction

    rng = np.random.default_rng(4)
    g = StaggeredGrid(n=(16, 16, 16), x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
    # cluster markers into one tile so cap=4 overflows
    X = jnp.asarray(0.2 + 0.05 * rng.uniform(0, 1, (64, 3)),
                    dtype=jnp.float32)
    u = tuple(jnp.asarray(rng.standard_normal(g.n), dtype=jnp.float32)
              for _ in range(3))
    w = jnp.asarray((np.arange(64) % 2), dtype=jnp.float32)
    eng = PallasInteraction(g, kernel="IB_4", tile=8, cap=4,
                            overflow_cap=64, interpret=True)
    U_pl = eng.interpolate_vel(u, X, weights=w)
    U_ref = interaction.interpolate_vel(u, g, X, kernel="IB_4",
                                        weights=w)
    scale = float(jnp.max(jnp.abs(U_ref)))
    np.testing.assert_allclose(np.asarray(U_pl), np.asarray(U_ref),
                               atol=2e-6 * scale)


def test_pallas_engine_coupled_step_matches_scatter():
    """Flagship selection path: build_shell_example(use_fast_interaction
    ="pallas") steps identically to the scatter engine."""
    from ibamr_tpu.models.shell3d import build_shell_example

    integ_pl, st_pl = build_shell_example(
        n_cells=16, n_lat=12, n_lon=12, mu=0.05,
        use_fast_interaction="pallas")
    integ_sc, st_sc = build_shell_example(
        n_cells=16, n_lat=12, n_lon=12, mu=0.05,
        use_fast_interaction=False)
    for _ in range(3):
        st_pl = integ_pl.step(st_pl, 1e-3)
        st_sc = integ_sc.step(st_sc, 1e-3)
    np.testing.assert_allclose(np.asarray(st_pl.X), np.asarray(st_sc.X),
                               atol=5e-6)
