"""Resilience layer (PR 2): atomic verified checkpoints, supervised
rollback-and-retry, graceful engine degradation, fault injection.

Every failure path the recovery machinery claims to handle is
EXERCISED here with a deterministic injected fault
(tools.fault_injection): torn/corrupt/uncommitted checkpoints, flaky
writes under the async writer, NaN divergence under the supervisor,
preemption signals, a monkeypatch-killed transfer engine, and a
SIGKILL-mid-write subprocess drill proving no crash sequence loses
more than one checkpoint interval.
"""

import json
import os
import signal
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
from ibamr_tpu.utils import checkpoint as ckpt
from ibamr_tpu.utils.checkpoint import (AsyncCheckpointWriter,
                                        CheckpointCorruptError,
                                        latest_step, restore_checkpoint,
                                        save_checkpoint,
                                        verify_checkpoint)
from ibamr_tpu.utils.hierarchy_driver import (HierarchyDriver, RunConfig,
                                              SimulationDiverged)
from ibamr_tpu.utils.supervisor import ResilientDriver
from tools.fault_injection import (corrupt_checkpoint, crash_state,
                                   drop_sidecar,
                                   failing_checkpoint_writes, inject_nan,
                                   nan_injector_step, truncate_checkpoint)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ins(n=16, mu=0.01, **kw):
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    return INSStaggeredIntegrator(g, rho=1.0, mu=mu, dtype=jnp.float64,
                                  **kw)


def _tg_state(integ):
    import math
    g = integ.grid
    xf, yc = g.face_centers(0, jnp.float64)
    xc, yf = g.face_centers(1, jnp.float64)
    u = jnp.sin(2 * math.pi * xf) * jnp.cos(2 * math.pi * yc) + 0 * yc
    v = -jnp.cos(2 * math.pi * xc) * jnp.sin(2 * math.pi * yf) + 0 * xc
    return integ.initialize(u0_arrays=(u, v))


# ---------------------------------------------------------------------------
# checkpoint integrity: every damage mode a crash/bad disk can inflict
# ---------------------------------------------------------------------------

def test_truncated_checkpoint_skipped(tmp_path):
    d = str(tmp_path)
    for s in (5, 10):
        save_checkpoint(d, crash_state(s), s)
    truncate_checkpoint(d, 10)
    assert not verify_checkpoint(d, 10)
    assert verify_checkpoint(d, 5)
    assert latest_step(d) == 5                      # newest VERIFIED
    assert latest_step(d, verified_only=False) == 10
    with pytest.warns(UserWarning, match="unverified"):
        st, k, _ = restore_checkpoint(d, crash_state(5))
    assert k == 5
    assert np.array_equal(np.asarray(st["u"]), crash_state(5)["u"])


def test_byte_flip_caught_by_whole_file_crc(tmp_path):
    d = str(tmp_path)
    save_checkpoint(d, crash_state(7), 7)
    assert verify_checkpoint(d, 7)
    corrupt_checkpoint(d, 7)                        # same size, one bit
    assert not verify_checkpoint(d, 7)
    with pytest.raises(CheckpointCorruptError):
        restore_checkpoint(d, crash_state(7), step=7)
    with pytest.warns(UserWarning), pytest.raises(FileNotFoundError,
                                                  match="all corrupt"):
        restore_checkpoint(d, crash_state(7))       # nothing to fall to
    with pytest.raises(FileNotFoundError):
        restore_checkpoint(d, crash_state(7), step=99)


def test_missing_sidecar_means_uncommitted(tmp_path):
    d = str(tmp_path)
    for s in (5, 10):
        save_checkpoint(d, crash_state(s), s)
    drop_sidecar(d, 10)
    assert not verify_checkpoint(d, 10)
    assert latest_step(d) == 5


def test_leaf_crc_mismatch_detected_and_fallback(tmp_path):
    """A tampered sidecar whose file-level digest still matches must be
    caught by the per-leaf CRC at load time, and step=None restore must
    fall back to the previous verified checkpoint."""
    d = str(tmp_path)
    for s in (5, 10):
        save_checkpoint(d, crash_state(s), s)
    side = os.path.join(d, "restore.00000010.json")
    with open(side) as f:
        meta = json.load(f)
    meta["integrity"]["leaves"]["u"] ^= 1
    with open(side, "w") as f:
        json.dump(meta, f)
    assert verify_checkpoint(d, 10)     # whole-file digest still OK...
    with pytest.raises(CheckpointCorruptError, match="CRC32"):
        restore_checkpoint(d, crash_state(10), step=10)
    with pytest.warns(UserWarning, match="skipping checkpoint step 10"):
        st, k, _ = restore_checkpoint(d, crash_state(5))
    assert k == 5


def test_prune_never_deletes_last_verified(tmp_path):
    d = str(tmp_path)
    for s in (1, 2, 3):
        save_checkpoint(d, crash_state(s), s, keep=0)    # keep=0: no prune
    corrupt_checkpoint(d, 2)
    corrupt_checkpoint(d, 3)
    ckpt._prune(d, keep=1)
    # doomed = {1, 2}; 1 is the newest verified so it is spared
    assert verify_checkpoint(d, 1)
    assert not os.path.exists(os.path.join(d, "restore.00000002.npz"))
    assert latest_step(d) == 1
    st, k, _ = restore_checkpoint(d, crash_state(1))
    assert k == 1


def test_async_writer_retries_flaky_write(tmp_path):
    d = str(tmp_path)
    w = AsyncCheckpointWriter(d, keep=3)
    try:
        with failing_checkpoint_writes({0}) as ctr:
            w.save(crash_state(4), 4)
            w.wait()
        assert ctr["calls"] == 2                    # attempt + retry
        assert verify_checkpoint(d, 4)
    finally:
        w.close()


def test_async_writer_double_failure_surfaces_once(tmp_path):
    d = str(tmp_path)
    w = AsyncCheckpointWriter(d, keep=3)
    try:
        with failing_checkpoint_writes({0, 1}):
            w.save(crash_state(4), 4)
            with pytest.raises(OSError, match="injected"):
                w.wait()
        # the failure must not poison later saves
        w.save(crash_state(8), 8)
        w.wait()
        assert latest_step(d) == 8
        assert not verify_checkpoint(d, 4)
    finally:
        w.close()


def test_inject_nan_matches_only_floating_leaves():
    st = inject_nan(crash_state(3), "u")
    assert np.all(np.isnan(np.asarray(st["u"])))
    assert int(st["k"]) == 3
    with pytest.raises(KeyError):
        inject_nan(crash_state(3), "nope")
    with pytest.raises(KeyError):
        inject_nan(crash_state(3), "k")             # int leaf: no match


# ---------------------------------------------------------------------------
# supervised rollback-and-retry
# ---------------------------------------------------------------------------

def _nan_driver(integ, dt0, *, gated=True, num_steps=12):
    cfg = RunConfig(dt=dt0, num_steps=num_steps, restart_interval=4,
                    health_interval=2)
    return HierarchyDriver(
        integ, cfg,
        step_fn=nan_injector_step(
            integ.step, at_step=6, leaf_path="u[0]",
            dt_gate=dt0 * 0.99 if gated else None))


def test_supervisor_recovers_from_divergence(tmp_path):
    """The acceptance drill: NaN at step 6 -> rollback to the step-4
    checkpoint, dt backoff (which disarms the dt-gated fault), run to
    completion, one structured JSONL incident — and the recovered run
    is BITWISE the clean run restarted from that checkpoint at the
    backed-off dt."""
    integ = _ins()
    st0 = _tg_state(integ)
    dt0 = 1e-3
    d = str(tmp_path)
    drv = _nan_driver(integ, dt0)
    sup = ResilientDriver(drv, d, max_retries=2, dt_backoff=0.5,
                          handle_signals=False)
    out = sup.run(st0)
    assert int(out.k) == 12
    assert bool(jnp.all(jnp.isfinite(out.u[0])))
    assert not sup.preempted
    assert drv.cfg.dt == pytest.approx(dt0 * 0.5)

    [rec] = [r for r in sup.incidents if r["event"] == "divergence"]
    assert rec["step"] == 6
    assert rec["bad_leaves"]
    assert rec["retry"] == 1 and rec["max_retries"] == 2
    assert rec["rollback_step"] == 4 and rec["from_checkpoint"]
    assert rec["dt_before"] == pytest.approx(dt0)
    assert rec["dt_after"] == pytest.approx(dt0 * 0.5)
    with open(os.path.join(d, "incidents.jsonl")) as f:
        lines = [json.loads(l) for l in f]
    assert [l["event"] for l in lines] == ["divergence"]
    assert all("time" in l for l in lines)

    # checkpoints landed at the cadence of the RECOVERED run
    assert latest_step(d) == 12

    # recovered == clean-restart-from-checkpoint, bitwise
    st4, k4, _ = restore_checkpoint(d, out, step=4)
    assert k4 == 4
    drv2 = _nan_driver(integ, dt0)
    drv2.cfg.dt = dt0 * 0.5
    ref = drv2.run(st4, start_step=4)
    for a, b in zip(jax.tree_util.tree_leaves(out),
                    jax.tree_util.tree_leaves(ref)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_supervisor_gives_up_after_max_retries(tmp_path):
    """An UNGATED fault re-fires on every retry: the supervisor must
    stop at max_retries, record a give_up incident, and re-raise."""
    integ = _ins()
    st0 = _tg_state(integ)
    drv = _nan_driver(integ, 1e-3, gated=False)
    sup = ResilientDriver(drv, str(tmp_path), max_retries=1,
                          dt_backoff=0.5, handle_signals=False)
    with pytest.raises(SimulationDiverged):
        sup.run(st0)
    assert [r["event"] for r in sup.incidents] == ["divergence",
                                                   "give_up"]
    assert sup.incidents[-1]["retries"] == 1


def test_supervisor_preemption_writes_final_checkpoint(tmp_path):
    """SIGTERM mid-run: the installed handler raises through the step
    loop; the supervisor drains the writer, writes a final synchronous
    checkpoint of the last healthy state, records the incident, and
    returns instead of dying."""
    integ = _ins()
    st0 = _tg_state(integ)
    d = str(tmp_path)
    cfg = RunConfig(dt=1e-3, num_steps=40, restart_interval=10,
                    health_interval=2)
    fired = []

    def metrics_fn(s, k):
        if k >= 6 and not fired:
            fired.append(k)
            os.kill(os.getpid(), signal.SIGTERM)
        return None

    drv = HierarchyDriver(integ, cfg, metrics_fn=metrics_fn)
    sup = ResilientDriver(drv, d, handle_signals=True)
    before = signal.getsignal(signal.SIGTERM)
    out = sup.run(st0)
    assert sup.preempted and sup.preempt_signum == signal.SIGTERM
    assert signal.getsignal(signal.SIGTERM) == before   # handler restored
    k_final = int(out.k)
    assert k_final >= 6
    assert latest_step(d) == k_final
    st, k, meta = restore_checkpoint(d, out)
    assert k == k_final and meta.get("preempted") is True
    [rec] = [r for r in sup.incidents if r["event"] == "preemption"]
    assert rec["signal"] == "SIGTERM"
    assert rec["checkpoint_step"] == k_final


# ---------------------------------------------------------------------------
# graceful engine degradation
# ---------------------------------------------------------------------------

def test_engine_fallback_vocabulary():
    from ibamr_tpu.ops.interaction_packed import (ENGINE_FALLBACKS,
                                                  fallback_chain,
                                                  normalize_engine_name)

    assert normalize_engine_name(True) == "mxu"
    assert normalize_engine_name(False) == "scatter"
    assert normalize_engine_name(None) == "scatter"
    assert fallback_chain("hybrid_bf16") == [
        "hybrid_bf16", "packed_bf16", "packed", "scatter"]
    assert fallback_chain("pallas_packed") == [
        "pallas_packed", "packed", "scatter"]
    assert fallback_chain("scatter") == ["scatter"]
    for name in ENGINE_FALLBACKS:
        chain = fallback_chain(name)
        assert chain[-1] == "scatter"
        assert len(chain) == len(set(chain))        # no cycles
    with pytest.raises(KeyError):
        fallback_chain("no_such_engine")


def test_failed_engine_degrades_and_matches_fallback(monkeypatch):
    """A transfer engine whose build/compile probe fails must degrade
    down the registry chain with a warning — and the degraded model's
    step must be BITWISE the step of a model built directly on the
    fallback engine."""
    from ibamr_tpu.models.shell3d import build_shell_example
    from ibamr_tpu.ops import pallas_interaction

    def boom(self, *a, **kw):
        raise RuntimeError("injected engine failure")

    monkeypatch.setattr(pallas_interaction.HybridPackedInteraction,
                        "spread_vel", boom)
    with pytest.warns(RuntimeWarning, match="degrading to 'packed_bf16'"):
        integ, state = build_shell_example(
            n_cells=16, n_lat=8, n_lon=8,
            use_fast_interaction="hybrid_bf16")
    assert type(integ.ib.fast).__name__ == "PackedInteraction"
    assert integ.ib.fast.compute_dtype == jnp.bfloat16

    integ2, state2 = build_shell_example(
        n_cells=16, n_lat=8, n_lon=8,
        use_fast_interaction="packed_bf16", engine_fallback=False)
    s1 = jax.jit(integ.step)(state, 1e-4)
    s2 = jax.jit(integ2.step)(state2, 1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(s1),
                    jax.tree_util.tree_leaves(s2)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_engine_fallback_off_raises(monkeypatch):
    """With the fallback disabled a broken engine fails the build loudly
    (construction failure here: without the compile probe, a broken
    METHOD would only surface at first step)."""
    from ibamr_tpu.models.shell3d import build_shell_example
    from ibamr_tpu.ops import pallas_interaction

    def boom(self, *a, **kw):
        raise RuntimeError("injected engine failure")

    monkeypatch.setattr(pallas_interaction.HybridPackedInteraction,
                        "__init__", boom)
    with pytest.raises(RuntimeError, match="injected"):
        build_shell_example(n_cells=16, n_lat=8, n_lon=8,
                            use_fast_interaction="hybrid_bf16",
                            engine_fallback=False)


# ---------------------------------------------------------------------------
# satellites: retrace observable + overflow-pad debug check
# ---------------------------------------------------------------------------

def test_trace_counts_distinct_signatures():
    """trace_counts counts DISTINCT input signatures: a benign re-trace
    of a known signature (cache cleared) must not read as a retrace; a
    genuinely new signature must."""
    integ = _ins()
    st = _tg_state(integ)
    cfg = RunConfig(dt=1e-3, num_steps=20, health_interval=10)
    drv = HierarchyDriver(integ, cfg)
    out = drv.run(st)
    assert drv.trace_counts[10] == 1
    jax.clear_caches()                  # forces a re-trace next call
    drv._chunk(10)(out, 1e-3)
    assert drv.trace_counts[10] == 1    # same signature: no retrace
    drv._chunk(10)(out, jnp.asarray(1e-3, dtype=jnp.float32))
    assert drv.trace_counts[10] == 2    # new dt dtype: real retrace


def test_overflow_pad_debug_check_clean():
    """Debug mode asserts (in-jit, via host callback) that o_w == 0
    overflow pad entries contribute nothing; the clean path must pass
    and still match the scatter oracle."""
    from ibamr_tpu.ops import interaction
    from ibamr_tpu.ops import interaction_fast as ifast

    grid = StaggeredGrid(n=(32, 32), x_lo=(0, 0), x_up=(1, 1))
    rng = np.random.RandomState(2)
    X = jnp.asarray(0.1 + 0.05 * rng.rand(200, 2), dtype=jnp.float64)
    F = jnp.asarray(rng.randn(200, 2), dtype=jnp.float64)
    prev = ifast.debug_overflow_pad(True)
    try:
        assert prev is False
        fast = ifast.FastInteraction(grid, tile=8, cap=8)
        b = fast.buckets(X)
        assert bool(b.any_overflow)     # pads actually in play
        f_new = fast.spread_vel(F, X)
        jax.block_until_ready(f_new)    # host check ran, no violation
        f_ref = interaction.spread_vel(F, grid, X)
        for a, c in zip(f_ref, f_new):
            scale = float(jnp.max(jnp.abs(a))) + 1e-12
            assert float(jnp.max(jnp.abs(a - c))) < 1e-5 * scale
        u = tuple(jnp.asarray(rng.randn(32, 32), dtype=jnp.float64)
                  for _ in range(2))
        U = fast.interpolate_vel(u, X)
        jax.block_until_ready(U)
        U_ref = interaction.interpolate_vel(u, grid, X)
        assert float(jnp.max(jnp.abs(U_ref - U))) < 1e-5
    finally:
        ifast.debug_overflow_pad(prev)


# ---------------------------------------------------------------------------
# cross-mesh restore of a RECOVERED run
# ---------------------------------------------------------------------------

def test_cross_mesh_restore_of_recovered_run(tmp_path):
    """A supervised run that rolled back on one device resumes onto the
    virtual 8-device mesh: restored leaves are bitwise the single-device
    final state, the same-mesh continuation is bitwise, and the sharded
    continuation matches the single-device one to spectral-solver
    tolerance (the test_parallel cross-mesh bound)."""
    from jax.sharding import NamedSharding, PartitionSpec as PSpec

    from ibamr_tpu.parallel import make_mesh
    from ibamr_tpu.parallel.mesh import grid_pspec, make_sharded_ins_step

    integ = _ins()
    st0 = _tg_state(integ)
    dt0 = 1e-3
    d = str(tmp_path)
    drv = _nan_driver(integ, dt0)
    sup = ResilientDriver(drv, d, max_retries=2, dt_backoff=0.5,
                          handle_signals=False)
    out = sup.run(st0)
    assert [r["event"] for r in sup.incidents] == ["divergence"]
    assert latest_step(d) == 12
    dt2 = drv.cfg.dt                    # the backed-off dt resumes

    # same-mesh restore: bitwise state, bitwise continuation
    st1, k1, _ = restore_checkpoint(d, out)
    assert k1 == 12
    for a, b in zip(jax.tree_util.tree_leaves(st1),
                    jax.tree_util.tree_leaves(out)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    step1 = jax.jit(integ.step)
    one_a, one_b = step1(st1, dt2), step1(out, dt2)
    for a, b in zip(jax.tree_util.tree_leaves(one_a),
                    jax.tree_util.tree_leaves(one_b)):
        assert np.array_equal(np.asarray(a), np.asarray(b))

    # cross-mesh restore: grid-shaped leaves spatially sharded over 8
    # devices, scalars replicated (test_parallel resharder idiom)
    mesh = make_mesh(8, max_axes=2)
    spatial = NamedSharding(mesh, grid_pspec(mesh, 2))
    repl = NamedSharding(mesh, PSpec())

    def resharder(key, arr):
        sh = spatial if np.ndim(arr) == 2 else repl
        return jax.device_put(jnp.asarray(arr), sh)

    sh_st, k8, _ = restore_checkpoint(d, out, sharding_fn=resharder)
    assert k8 == 12
    assert len(sh_st.u[0].sharding.device_set) == 8
    for a, b in zip(jax.tree_util.tree_leaves(sh_st),
                    jax.tree_util.tree_leaves(out)):
        assert np.array_equal(np.asarray(a), np.asarray(b))

    stepN = make_sharded_ins_step(integ, mesh)
    sh, one = sh_st, st1
    for _ in range(3):
        sh = stepN(sh, dt2)
        one = step1(one, dt2)
    np.testing.assert_allclose(np.asarray(sh.u[0]), np.asarray(one.u[0]),
                               rtol=1e-10, atol=1e-11)
    np.testing.assert_allclose(np.asarray(sh.p), np.asarray(one.p),
                               rtol=1e-10, atol=1e-11)


# ---------------------------------------------------------------------------
# SIGKILL-mid-write subprocess drill (slow tier)
# ---------------------------------------------------------------------------

def _spawn_crash_child(d, steps=60, interval=5):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, "-m", "tools.fault_injection",
         "--crash-child", str(d), "--steps", str(steps),
         "--interval", str(interval)],
        cwd=REPO_ROOT, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, bufsize=1)


def test_kill_mid_write_loses_at_most_one_interval(tmp_path):
    """SIGKILL the checkpoint-writer child the instant a save lands,
    three crash cycles in a row: after every kill the newest VERIFIED
    checkpoint is no older than the last acknowledged save (at most
    the in-flight interval is lost) and restores bitwise against the
    closed-form trajectory. A deliberate corruption on top of the last
    crash costs exactly one more interval. Then the child runs to
    completion from the wreckage."""
    d = str(tmp_path)
    last_acked = 0
    for cycle in range(3):
        p = _spawn_crash_child(d)
        acked = None
        try:
            for line in p.stdout:
                if line.startswith("SAVED"):
                    acked = int(line.split()[1])
                    if acked > last_acked:
                        break           # kill mid-run, write just landed
                elif line.startswith("DONE"):
                    break
        finally:
            p.kill()
            p.wait()
        assert acked is not None and acked > last_acked, \
            f"cycle {cycle}: child made no progress"
        last_acked = acked
        ls = latest_step(d)
        assert ls is not None and ls >= acked       # <= 1 interval lost
        st, k, _ = restore_checkpoint(d, template=crash_state(ls),
                                      step=ls)
        assert k == ls
        assert np.array_equal(np.asarray(st["u"]), crash_state(ls)["u"])

    # compound the crash with bitrot on the newest checkpoint: the
    # fallback costs one more interval, never the whole chain
    newest = latest_step(d)
    corrupt_checkpoint(d, newest)
    ls2 = latest_step(d)
    assert ls2 is not None and ls2 >= newest - 5
    with pytest.warns(UserWarning):
        st, k, _ = restore_checkpoint(d, template=crash_state(ls2))
    assert k == ls2
    assert np.array_equal(np.asarray(st["u"]), crash_state(ls2)["u"])

    p = _spawn_crash_child(d)
    out, _ = p.communicate(timeout=300)
    assert p.returncode == 0, out
    assert "DONE" in out
    assert latest_step(d) == 60
    st, k, _ = restore_checkpoint(d, template=crash_state(60))
    assert k == 60
    assert np.array_equal(np.asarray(st["u"]), crash_state(60)["u"])
