"""Resilience layer (PR 2): atomic verified checkpoints, supervised
rollback-and-retry, graceful engine degradation, fault injection.

Every failure path the recovery machinery claims to handle is
EXERCISED here with a deterministic injected fault
(tools.fault_injection): torn/corrupt/uncommitted checkpoints, flaky
writes under the async writer, NaN divergence under the supervisor,
preemption signals, a monkeypatch-killed transfer engine, and a
SIGKILL-mid-write subprocess drill proving no crash sequence loses
more than one checkpoint interval.

PR 3 adds the SILENT failures: a finite exponential blow-up caught by
the fused health vitals BEFORE any NaN exists, a stagnating Krylov
solve escalated through its declared chain (and surfaced as a
structured ``SolverBreakdown`` when the chain exhausts), and a stalled
chunk flagged by the run watchdog's heartbeat.
"""

import json
import os
import signal
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
from ibamr_tpu.solvers.escalation import (ESCALATION_FALLBACKS,
                                          ESCALATION_LEVELS,
                                          SolverBreakdown, escalate_solve,
                                          escalation_chain,
                                          record_solve_stats)
from ibamr_tpu.solvers.krylov import SolveResult, bicgstab, fgmres
from ibamr_tpu.utils import checkpoint as ckpt
from ibamr_tpu.utils.checkpoint import (AsyncCheckpointWriter,
                                        CheckpointCorruptError,
                                        latest_step, restore_checkpoint,
                                        save_checkpoint,
                                        verify_checkpoint)
from ibamr_tpu.utils.health import (FATAL, OK, WARN, HealthDegraded,
                                    HealthProbe)
from ibamr_tpu.utils.hierarchy_driver import (HierarchyDriver, RunConfig,
                                              SimulationDiverged)
from ibamr_tpu.utils.supervisor import ResilientDriver
from ibamr_tpu.utils.watchdog import (RunWatchdog, heartbeat_age,
                                      read_heartbeat, write_heartbeat)
from tools.fault_injection import (corrupt_checkpoint, crash_state,
                                   drop_sidecar,
                                   failing_checkpoint_writes,
                                   growth_injector_step, inject_nan,
                                   nan_injector_step, slow_metrics,
                                   stagnating_operator,
                                   truncate_checkpoint)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ins(n=16, mu=0.01, **kw):
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    return INSStaggeredIntegrator(g, rho=1.0, mu=mu, dtype=jnp.float64,
                                  **kw)


def _tg_state(integ):
    import math
    g = integ.grid
    xf, yc = g.face_centers(0, jnp.float64)
    xc, yf = g.face_centers(1, jnp.float64)
    u = jnp.sin(2 * math.pi * xf) * jnp.cos(2 * math.pi * yc) + 0 * yc
    v = -jnp.cos(2 * math.pi * xc) * jnp.sin(2 * math.pi * yf) + 0 * xc
    return integ.initialize(u0_arrays=(u, v))


# ---------------------------------------------------------------------------
# checkpoint integrity: every damage mode a crash/bad disk can inflict
# ---------------------------------------------------------------------------

def test_truncated_checkpoint_skipped(tmp_path):
    d = str(tmp_path)
    for s in (5, 10):
        save_checkpoint(d, crash_state(s), s)
    truncate_checkpoint(d, 10)
    assert not verify_checkpoint(d, 10)
    assert verify_checkpoint(d, 5)
    assert latest_step(d) == 5                      # newest VERIFIED
    assert latest_step(d, verified_only=False) == 10
    with pytest.warns(UserWarning, match="unverified"):
        st, k, _ = restore_checkpoint(d, crash_state(5))
    assert k == 5
    assert np.array_equal(np.asarray(st["u"]), crash_state(5)["u"])


def test_byte_flip_caught_by_whole_file_crc(tmp_path):
    d = str(tmp_path)
    save_checkpoint(d, crash_state(7), 7)
    assert verify_checkpoint(d, 7)
    corrupt_checkpoint(d, 7)                        # same size, one bit
    assert not verify_checkpoint(d, 7)
    with pytest.raises(CheckpointCorruptError):
        restore_checkpoint(d, crash_state(7), step=7)
    with pytest.warns(UserWarning), pytest.raises(FileNotFoundError,
                                                  match="all corrupt"):
        restore_checkpoint(d, crash_state(7))       # nothing to fall to
    with pytest.raises(FileNotFoundError):
        restore_checkpoint(d, crash_state(7), step=99)


def test_missing_sidecar_means_uncommitted(tmp_path):
    d = str(tmp_path)
    for s in (5, 10):
        save_checkpoint(d, crash_state(s), s)
    drop_sidecar(d, 10)
    assert not verify_checkpoint(d, 10)
    assert latest_step(d) == 5


def test_leaf_crc_mismatch_detected_and_fallback(tmp_path):
    """A tampered sidecar whose file-level digest still matches must be
    caught by the per-leaf CRC at load time, and step=None restore must
    fall back to the previous verified checkpoint."""
    d = str(tmp_path)
    for s in (5, 10):
        save_checkpoint(d, crash_state(s), s)
    side = os.path.join(d, "restore.00000010.json")
    with open(side) as f:
        meta = json.load(f)
    meta["integrity"]["leaves"]["u"] ^= 1
    with open(side, "w") as f:
        json.dump(meta, f)
    assert verify_checkpoint(d, 10)     # whole-file digest still OK...
    with pytest.raises(CheckpointCorruptError, match="CRC32"):
        restore_checkpoint(d, crash_state(10), step=10)
    with pytest.warns(UserWarning, match="skipping checkpoint step 10"):
        st, k, _ = restore_checkpoint(d, crash_state(5))
    assert k == 5


def test_prune_never_deletes_last_verified(tmp_path):
    d = str(tmp_path)
    for s in (1, 2, 3):
        save_checkpoint(d, crash_state(s), s, keep=0)    # keep=0: no prune
    corrupt_checkpoint(d, 2)
    corrupt_checkpoint(d, 3)
    ckpt._prune(d, keep=1)
    # doomed = {1, 2}; 1 is the newest verified so it is spared
    assert verify_checkpoint(d, 1)
    assert not os.path.exists(os.path.join(d, "restore.00000002.npz"))
    assert latest_step(d) == 1
    st, k, _ = restore_checkpoint(d, crash_state(1))
    assert k == 1


def test_async_writer_retries_flaky_write(tmp_path):
    d = str(tmp_path)
    w = AsyncCheckpointWriter(d, keep=3)
    try:
        with failing_checkpoint_writes({0}) as ctr:
            w.save(crash_state(4), 4)
            w.wait()
        assert ctr["calls"] == 2                    # attempt + retry
        assert verify_checkpoint(d, 4)
    finally:
        w.close()


def test_async_writer_double_failure_surfaces_once(tmp_path):
    d = str(tmp_path)
    w = AsyncCheckpointWriter(d, keep=3)
    try:
        with failing_checkpoint_writes({0, 1}):
            w.save(crash_state(4), 4)
            with pytest.raises(OSError, match="injected"):
                w.wait()
        # the failure must not poison later saves
        w.save(crash_state(8), 8)
        w.wait()
        assert latest_step(d) == 8
        assert not verify_checkpoint(d, 4)
    finally:
        w.close()


def test_inject_nan_matches_only_floating_leaves():
    st = inject_nan(crash_state(3), "u")
    assert np.all(np.isnan(np.asarray(st["u"])))
    assert int(st["k"]) == 3
    with pytest.raises(KeyError):
        inject_nan(crash_state(3), "nope")
    with pytest.raises(KeyError):
        inject_nan(crash_state(3), "k")             # int leaf: no match


# ---------------------------------------------------------------------------
# supervised rollback-and-retry
# ---------------------------------------------------------------------------

def _nan_driver(integ, dt0, *, gated=True, num_steps=12):
    cfg = RunConfig(dt=dt0, num_steps=num_steps, restart_interval=4,
                    health_interval=2)
    return HierarchyDriver(
        integ, cfg,
        step_fn=nan_injector_step(
            integ.step, at_step=6, leaf_path="u[0]",
            dt_gate=dt0 * 0.99 if gated else None))


def test_supervisor_recovers_from_divergence(tmp_path):
    """The acceptance drill: NaN at step 6 -> rollback to the step-4
    checkpoint, dt backoff (which disarms the dt-gated fault), run to
    completion, one structured JSONL incident — and the recovered run
    is BITWISE the clean run restarted from that checkpoint at the
    backed-off dt."""
    integ = _ins()
    st0 = _tg_state(integ)
    dt0 = 1e-3
    d = str(tmp_path)
    drv = _nan_driver(integ, dt0)
    sup = ResilientDriver(drv, d, max_retries=2, dt_backoff=0.5,
                          handle_signals=False)
    out = sup.run(st0)
    assert int(out.k) == 12
    assert bool(jnp.all(jnp.isfinite(out.u[0])))
    assert not sup.preempted
    assert drv.cfg.dt == pytest.approx(dt0 * 0.5)

    [rec] = [r for r in sup.incidents if r["event"] == "divergence"]
    assert rec["step"] == 6
    assert rec["bad_leaves"]
    assert rec["retry"] == 1 and rec["max_retries"] == 2
    assert rec["rollback_step"] == 4 and rec["from_checkpoint"]
    assert rec["dt_before"] == pytest.approx(dt0)
    assert rec["dt_after"] == pytest.approx(dt0 * 0.5)
    with open(os.path.join(d, "incidents.jsonl")) as f:
        lines = [json.loads(l) for l in f]
    assert [l["event"] for l in lines] == ["divergence"]
    assert all("time" in l for l in lines)

    # checkpoints landed at the cadence of the RECOVERED run
    assert latest_step(d) == 12

    # recovered == clean-restart-from-checkpoint, bitwise
    st4, k4, _ = restore_checkpoint(d, out, step=4)
    assert k4 == 4
    drv2 = _nan_driver(integ, dt0)
    drv2.cfg.dt = dt0 * 0.5
    ref = drv2.run(st4, start_step=4)
    for a, b in zip(jax.tree_util.tree_leaves(out),
                    jax.tree_util.tree_leaves(ref)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_supervisor_gives_up_after_max_retries(tmp_path):
    """An UNGATED fault re-fires on every retry: the supervisor must
    stop at max_retries, record a give_up incident, and re-raise."""
    integ = _ins()
    st0 = _tg_state(integ)
    drv = _nan_driver(integ, 1e-3, gated=False)
    sup = ResilientDriver(drv, str(tmp_path), max_retries=1,
                          dt_backoff=0.5, handle_signals=False)
    with pytest.raises(SimulationDiverged):
        sup.run(st0)
    assert [r["event"] for r in sup.incidents] == ["divergence",
                                                   "give_up"]
    assert sup.incidents[-1]["retries"] == 1


def test_supervisor_preemption_writes_final_checkpoint(tmp_path):
    """SIGTERM mid-run: the installed handler raises through the step
    loop; the supervisor drains the writer, writes a final synchronous
    checkpoint of the last healthy state, records the incident, and
    returns instead of dying."""
    integ = _ins()
    st0 = _tg_state(integ)
    d = str(tmp_path)
    cfg = RunConfig(dt=1e-3, num_steps=40, restart_interval=10,
                    health_interval=2)
    fired = []

    def metrics_fn(s, k):
        if k >= 6 and not fired:
            fired.append(k)
            os.kill(os.getpid(), signal.SIGTERM)
        return None

    drv = HierarchyDriver(integ, cfg, metrics_fn=metrics_fn)
    sup = ResilientDriver(drv, d, handle_signals=True)
    before = signal.getsignal(signal.SIGTERM)
    out = sup.run(st0)
    assert sup.preempted and sup.preempt_signum == signal.SIGTERM
    assert signal.getsignal(signal.SIGTERM) == before   # handler restored
    k_final = int(out.k)
    assert k_final >= 6
    assert latest_step(d) == k_final
    st, k, meta = restore_checkpoint(d, out)
    assert k == k_final and meta.get("preempted") is True
    [rec] = [r for r in sup.incidents if r["event"] == "preemption"]
    assert rec["signal"] == "SIGTERM"
    assert rec["checkpoint_step"] == k_final


# ---------------------------------------------------------------------------
# graceful engine degradation
# ---------------------------------------------------------------------------

def test_engine_fallback_vocabulary():
    from ibamr_tpu.ops.interaction_packed import (ENGINE_FALLBACKS,
                                                  fallback_chain,
                                                  normalize_engine_name)

    assert normalize_engine_name(True) == "mxu"
    assert normalize_engine_name(False) == "scatter"
    assert normalize_engine_name(None) == "scatter"
    assert fallback_chain("hybrid_bf16") == [
        "hybrid_bf16", "packed_bf16", "packed", "scatter"]
    assert fallback_chain("pallas_packed") == [
        "pallas_packed", "packed", "scatter"]
    assert fallback_chain("scatter") == ["scatter"]
    for name in ENGINE_FALLBACKS:
        chain = fallback_chain(name)
        assert chain[-1] == "scatter"
        assert len(chain) == len(set(chain))        # no cycles
    with pytest.raises(KeyError):
        fallback_chain("no_such_engine")


def test_failed_engine_degrades_and_matches_fallback(monkeypatch):
    """A transfer engine whose build/compile probe fails must degrade
    down the registry chain with a warning — and the degraded model's
    step must be BITWISE the step of a model built directly on the
    fallback engine."""
    from ibamr_tpu.models.shell3d import build_shell_example
    from ibamr_tpu.ops import pallas_interaction

    def boom(self, *a, **kw):
        raise RuntimeError("injected engine failure")

    monkeypatch.setattr(pallas_interaction.HybridPackedInteraction,
                        "spread_vel", boom)
    with pytest.warns(RuntimeWarning, match="degrading to 'packed_bf16'"):
        integ, state = build_shell_example(
            n_cells=16, n_lat=8, n_lon=8,
            use_fast_interaction="hybrid_bf16")
    assert type(integ.ib.fast).__name__ == "PackedInteraction"
    assert integ.ib.fast.compute_dtype == jnp.bfloat16

    integ2, state2 = build_shell_example(
        n_cells=16, n_lat=8, n_lon=8,
        use_fast_interaction="packed_bf16", engine_fallback=False)
    s1 = jax.jit(integ.step)(state, 1e-4)
    s2 = jax.jit(integ2.step)(state2, 1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(s1),
                    jax.tree_util.tree_leaves(s2)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_engine_fallback_off_raises(monkeypatch):
    """With the fallback disabled a broken engine fails the build loudly
    (construction failure here: without the compile probe, a broken
    METHOD would only surface at first step)."""
    from ibamr_tpu.models.shell3d import build_shell_example
    from ibamr_tpu.ops import pallas_interaction

    def boom(self, *a, **kw):
        raise RuntimeError("injected engine failure")

    monkeypatch.setattr(pallas_interaction.HybridPackedInteraction,
                        "__init__", boom)
    with pytest.raises(RuntimeError, match="injected"):
        build_shell_example(n_cells=16, n_lat=8, n_lon=8,
                            use_fast_interaction="hybrid_bf16",
                            engine_fallback=False)


# ---------------------------------------------------------------------------
# satellites: retrace observable + overflow-pad debug check
# ---------------------------------------------------------------------------

def test_trace_counts_distinct_signatures():
    """trace_counts counts DISTINCT input signatures: a benign re-trace
    of a known signature (cache cleared) must not read as a retrace; a
    genuinely new signature must."""
    integ = _ins()
    st = _tg_state(integ)
    cfg = RunConfig(dt=1e-3, num_steps=20, health_interval=10)
    drv = HierarchyDriver(integ, cfg)
    out = drv.run(st)
    assert drv.trace_counts[10] == 1
    jax.clear_caches()                  # forces a re-trace next call
    drv._chunk(10)(out, 1e-3)
    assert drv.trace_counts[10] == 1    # same signature: no retrace
    drv._chunk(10)(out, jnp.asarray(1e-3, dtype=jnp.float32))
    assert drv.trace_counts[10] == 2    # new dt dtype: real retrace


def test_overflow_pad_debug_check_clean():
    """Debug mode asserts (in-jit, via host callback) that o_w == 0
    overflow pad entries contribute nothing; the clean path must pass
    and still match the scatter oracle."""
    from ibamr_tpu.ops import interaction
    from ibamr_tpu.ops import interaction_fast as ifast

    grid = StaggeredGrid(n=(32, 32), x_lo=(0, 0), x_up=(1, 1))
    rng = np.random.RandomState(2)
    X = jnp.asarray(0.1 + 0.05 * rng.rand(200, 2), dtype=jnp.float64)
    F = jnp.asarray(rng.randn(200, 2), dtype=jnp.float64)
    prev = ifast.debug_overflow_pad(True)
    try:
        assert prev is False
        fast = ifast.FastInteraction(grid, tile=8, cap=8)
        b = fast.buckets(X)
        assert bool(b.any_overflow)     # pads actually in play
        f_new = fast.spread_vel(F, X)
        jax.block_until_ready(f_new)    # host check ran, no violation
        f_ref = interaction.spread_vel(F, grid, X)
        for a, c in zip(f_ref, f_new):
            scale = float(jnp.max(jnp.abs(a))) + 1e-12
            assert float(jnp.max(jnp.abs(a - c))) < 1e-5 * scale
        u = tuple(jnp.asarray(rng.randn(32, 32), dtype=jnp.float64)
                  for _ in range(2))
        U = fast.interpolate_vel(u, X)
        jax.block_until_ready(U)
        U_ref = interaction.interpolate_vel(u, grid, X)
        assert float(jnp.max(jnp.abs(U_ref - U))) < 1e-5
    finally:
        ifast.debug_overflow_pad(prev)


# ---------------------------------------------------------------------------
# cross-mesh restore of a RECOVERED run
# ---------------------------------------------------------------------------

def test_cross_mesh_restore_of_recovered_run(tmp_path):
    """A supervised run that rolled back on one device resumes onto the
    virtual 8-device mesh: restored leaves are bitwise the single-device
    final state, the same-mesh continuation is bitwise, and the sharded
    continuation matches the single-device one to spectral-solver
    tolerance (the test_parallel cross-mesh bound)."""
    from jax.sharding import NamedSharding, PartitionSpec as PSpec

    from ibamr_tpu.parallel import make_mesh
    from ibamr_tpu.parallel.mesh import grid_pspec, make_sharded_ins_step

    integ = _ins()
    st0 = _tg_state(integ)
    dt0 = 1e-3
    d = str(tmp_path)
    drv = _nan_driver(integ, dt0)
    sup = ResilientDriver(drv, d, max_retries=2, dt_backoff=0.5,
                          handle_signals=False)
    out = sup.run(st0)
    assert [r["event"] for r in sup.incidents] == ["divergence"]
    assert latest_step(d) == 12
    dt2 = drv.cfg.dt                    # the backed-off dt resumes

    # same-mesh restore: bitwise state, bitwise continuation
    st1, k1, _ = restore_checkpoint(d, out)
    assert k1 == 12
    for a, b in zip(jax.tree_util.tree_leaves(st1),
                    jax.tree_util.tree_leaves(out)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    step1 = jax.jit(integ.step)
    one_a, one_b = step1(st1, dt2), step1(out, dt2)
    for a, b in zip(jax.tree_util.tree_leaves(one_a),
                    jax.tree_util.tree_leaves(one_b)):
        assert np.array_equal(np.asarray(a), np.asarray(b))

    # cross-mesh restore: grid-shaped leaves spatially sharded over 8
    # devices, scalars replicated (test_parallel resharder idiom)
    mesh = make_mesh(8, max_axes=2)
    spatial = NamedSharding(mesh, grid_pspec(mesh, 2))
    repl = NamedSharding(mesh, PSpec())

    def resharder(key, arr):
        sh = spatial if np.ndim(arr) == 2 else repl
        return jax.device_put(jnp.asarray(arr), sh)

    sh_st, k8, _ = restore_checkpoint(d, out, sharding_fn=resharder)
    assert k8 == 12
    assert len(sh_st.u[0].sharding.device_set) == 8
    for a, b in zip(jax.tree_util.tree_leaves(sh_st),
                    jax.tree_util.tree_leaves(out)):
        assert np.array_equal(np.asarray(a), np.asarray(b))

    stepN = make_sharded_ins_step(integ, mesh)
    sh, one = sh_st, st1
    for _ in range(3):
        sh = stepN(sh, dt2)
        one = step1(one, dt2)
    np.testing.assert_allclose(np.asarray(sh.u[0]), np.asarray(one.u[0]),
                               rtol=1e-10, atol=1e-11)
    np.testing.assert_allclose(np.asarray(sh.p), np.asarray(one.p),
                               rtol=1e-10, atol=1e-11)


# ---------------------------------------------------------------------------
# SIGKILL-mid-write subprocess drill (slow tier)
# ---------------------------------------------------------------------------

def _spawn_crash_child(d, steps=60, interval=5):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, "-m", "tools.fault_injection",
         "--crash-child", str(d), "--steps", str(steps),
         "--interval", str(interval)],
        cwd=REPO_ROOT, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, bufsize=1)


def test_kill_mid_write_loses_at_most_one_interval(tmp_path):
    """SIGKILL the checkpoint-writer child the instant a save lands,
    three crash cycles in a row: after every kill the newest VERIFIED
    checkpoint is no older than the last acknowledged save (at most
    the in-flight interval is lost) and restores bitwise against the
    closed-form trajectory. A deliberate corruption on top of the last
    crash costs exactly one more interval. Then the child runs to
    completion from the wreckage."""
    d = str(tmp_path)
    last_acked = 0
    for cycle in range(3):
        p = _spawn_crash_child(d)
        acked = None
        try:
            for line in p.stdout:
                if line.startswith("SAVED"):
                    acked = int(line.split()[1])
                    if acked > last_acked:
                        break           # kill mid-run, write just landed
                elif line.startswith("DONE"):
                    break
        finally:
            p.kill()
            p.wait()
        assert acked is not None and acked > last_acked, \
            f"cycle {cycle}: child made no progress"
        last_acked = acked
        ls = latest_step(d)
        assert ls is not None and ls >= acked       # <= 1 interval lost
        st, k, _ = restore_checkpoint(d, template=crash_state(ls),
                                      step=ls)
        assert k == ls
        assert np.array_equal(np.asarray(st["u"]), crash_state(ls)["u"])

    # compound the crash with bitrot on the newest checkpoint: the
    # fallback costs one more interval, never the whole chain
    newest = latest_step(d)
    corrupt_checkpoint(d, newest)
    ls2 = latest_step(d)
    assert ls2 is not None and ls2 >= newest - 5
    with pytest.warns(UserWarning):
        st, k, _ = restore_checkpoint(d, template=crash_state(ls2))
    assert k == ls2
    assert np.array_equal(np.asarray(st["u"]), crash_state(ls2)["u"])

    p = _spawn_crash_child(d)
    out, _ = p.communicate(timeout=300)
    assert p.returncode == 0, out
    assert "DONE" in out
    assert latest_step(d) == 60
    st, k, _ = restore_checkpoint(d, template=crash_state(60))
    assert k == 60
    assert np.array_equal(np.asarray(st["u"]), crash_state(60)["u"])


# ---------------------------------------------------------------------------
# PR 3: fail-fast input validation
# ---------------------------------------------------------------------------

def test_runconfig_rejects_bad_inputs():
    """A typo'd input file must die at construction with the offending
    field named — not produce a zero-length scan hours later."""
    with pytest.raises(ValueError, match="dt"):
        RunConfig(dt=0.0, num_steps=10)
    with pytest.raises(ValueError, match="dt"):
        RunConfig(dt=float("nan"), num_steps=10)
    with pytest.raises(ValueError, match="num_steps"):
        RunConfig(dt=1e-3, num_steps=-1)
    with pytest.raises(ValueError, match="restart_interval"):
        RunConfig(dt=1e-3, num_steps=10, restart_interval=-4)
    with pytest.raises(ValueError, match="health_interval"):
        RunConfig(dt=1e-3, num_steps=10, health_interval=0)
    with pytest.raises(ValueError, match="cfl"):
        RunConfig(dt=1e-3, num_steps=10, cfl=0.0)
    # the valid edge cases stay valid: zero steps, cadences off
    cfg = RunConfig(dt=1e-3, num_steps=0)
    assert cfg.restart_interval == 0


# ---------------------------------------------------------------------------
# PR 3: fused health vitals — jit side, host triage, end-to-end rollback
# ---------------------------------------------------------------------------

def test_health_probe_measure_matches_state():
    """The jit-side vitals vector must report the real physics numbers
    of the state it measured."""
    import math
    integ = _ins()
    st = _tg_state(integ)
    probe = HealthProbe.for_integrator(integ)
    dt = 1e-3
    v = np.asarray(jax.jit(probe.measure)(st, dt))
    assert v.shape == (len(HealthProbe.VITALS_FIELDS),) \
        and v.dtype == np.float32
    d = HealthProbe.unpack(v)
    assert d["finite"] == 1.0
    max_u = max(float(jnp.max(jnp.abs(c))) for c in st.u)
    assert d["max_u"] == pytest.approx(max_u, rel=1e-5)
    assert d["cfl"] == pytest.approx(max_u * dt / min(integ.grid.dx),
                                     rel=1e-5)
    assert d["div_norm"] >= 0.0
    assert math.isfinite(d["func"])     # default functional: KE
    assert d["func"] == pytest.approx(float(integ.kinetic_energy(st)),
                                      rel=1e-5)


def test_health_probe_triage_streaks_and_baseline():
    """Host-side triage: WARN streaks escalate only at ``sustain``,
    FATAL fires immediately, the functional baseline is the first
    observed value, and the streak resets after a raise so a supervised
    retry starts clean."""
    probe = HealthProbe(max_u_warn=1.0, max_u_fatal=10.0,
                        func_growth_warn=4.0, sustain=2)
    ok = np.array([1.0, 0.5, 0.0, 0.0, 1.0], np.float32)
    warn = np.array([1.0, 2.0, 0.0, 0.0, 1.0], np.float32)
    assert probe.check(ok, step=1, dt=1e-3)["level"] == OK
    rec = probe.check(warn, step=2, dt=1e-3)
    assert rec["level"] == WARN and rec["warn_streak"] == 1
    with pytest.raises(HealthDegraded) as ei:    # 2nd WARN = sustain
        probe.check(warn, step=3, dt=1e-3)
    e = ei.value
    assert isinstance(e, SimulationDiverged)     # supervisor catches it
    assert e.kind == "health_degraded"
    assert e.step == 3 and e.bad_leaves == []    # nothing non-finite
    assert e.reasons and "max_u" in e.reasons[0]
    assert set(e.incident_payload()) == {"reasons", "vitals"}
    # the raise reset the streak: one clean chunk, one WARN chunk, fine
    assert probe.check(ok, step=4, dt=1e-3)["level"] == OK
    grown = np.array([1.0, 0.5, 0.0, 0.0, 8.0], np.float32)
    rec = probe.check(grown, step=5, dt=1e-3)    # func baseline was 1.0
    assert rec["level"] == WARN
    assert rec["func_growth"] == pytest.approx(8.0)
    # FATAL needs no streak
    fatal = np.array([1.0, 50.0, 0.0, 0.0, 1.0], np.float32)
    with pytest.raises(HealthDegraded):
        probe.check(fatal, step=6, dt=1e-3)
    assert probe.history[-1]["level"] == FATAL
    with pytest.raises(ValueError, match="sustain"):
        HealthProbe(sustain=0)


def test_health_probe_adds_no_retrace():
    """The fused vitals vector rides the SAME one-transfer-per-chunk
    sync the plain finite bool paid: one trace per chunk length, every
    chunk classified, no extra signatures."""
    integ = _ins()
    st = _tg_state(integ)
    probe = HealthProbe.for_integrator(integ)
    cfg = RunConfig(dt=1e-3, num_steps=12, health_interval=4)
    drv = HierarchyDriver(integ, cfg, health_probe=probe)
    out = drv.run(st)
    assert int(out.k) == 12
    assert drv.trace_counts == {4: 1}           # 3 chunks, ONE signature
    assert len(probe.history) == 3
    assert [r["step"] for r in probe.history] == [4, 8, 12]
    assert all(r["finite"] >= 1.0 for r in probe.history)
    assert drv.last_vitals is probe.history[-1]


def test_health_rollback_before_any_nan(tmp_path):
    """The PR-3 acceptance drill: a FINITE exponential velocity growth
    (dt-gated) trips the functional-growth WARN streak; the supervisor
    rolls back to a checkpoint that predates the degradation and the dt
    backoff disarms the fault — with ZERO non-finite values ever
    observed anywhere, and at most one checkpoint interval lost."""
    integ = _ins(mu=0.05)
    st0 = _tg_state(integ)
    dt0 = 1e-3
    d = str(tmp_path)
    probe = HealthProbe.for_integrator(integ, func_growth_warn=8.0,
                                       sustain=2)
    cfg = RunConfig(dt=dt0, num_steps=12, restart_interval=4,
                    health_interval=2)
    drv = HierarchyDriver(
        integ, cfg,
        step_fn=growth_injector_step(integ.step, rate=1.5, leaf_path="u",
                                     dt_gate=dt0 * 0.99),
        health_probe=probe)
    sup = ResilientDriver(drv, d, max_retries=2, dt_backoff=0.5,
                          handle_signals=False)
    out = sup.run(st0)
    assert int(out.k) == 12
    assert all(bool(jnp.all(jnp.isfinite(l)))
               for l in jax.tree_util.tree_leaves(out)
               if hasattr(l, "dtype"))
    # the whole point: every chunk the probe ever classified — before,
    # during and after the blow-up — was still finite
    assert probe.history
    assert all(rec["finite"] >= 1.0 for rec in probe.history)

    [rec] = [r for r in sup.incidents if r["event"] == "divergence"]
    assert rec["kind"] == "health_degraded"
    assert rec["bad_leaves"] == []
    # WARN at step 6, fired at step 8 -> newest checkpoint is step 4:
    # at most one restart interval lost
    assert rec["step"] == 8
    assert rec["rollback_step"] == 4 and rec["from_checkpoint"]
    assert rec["reasons"] and "grew" in rec["reasons"][0]
    assert rec["vitals"]["func_growth"] > 8.0
    assert rec["dt_after"] == pytest.approx(dt0 * 0.5)

    # the JSONL mirror carries the v2 ``kind`` discriminator
    with open(os.path.join(d, "incidents.jsonl")) as f:
        lines = [json.loads(l) for l in f]
    assert [l["kind"] for l in lines] == ["health_degraded"]
    # the checkpoint chain finished clean and never held garbage
    assert latest_step(d) == 12
    st4, k4, _ = restore_checkpoint(d, out, step=4)
    assert k4 == 4
    assert all(bool(jnp.all(jnp.isfinite(l)))
               for l in jax.tree_util.tree_leaves(st4)
               if hasattr(l, "dtype"))


# ---------------------------------------------------------------------------
# PR 3: solver non-convergence surfacing + escalation
# ---------------------------------------------------------------------------

def test_escalation_chain_vocabulary():
    """The chain registry mirrors ENGINE_FALLBACKS: one flat name->next
    dict, chains derived by walking it, terminal level ends every walk,
    no cycles, unknown names raise."""
    assert [l.name for l in escalation_chain()] == [
        "base", "restarts_x4", "deep_x4_inner_x2"]
    assert [l.name for l in escalation_chain("restarts_x4")] == [
        "restarts_x4", "deep_x4_inner_x2"]
    assert set(ESCALATION_FALLBACKS) == set(ESCALATION_LEVELS)
    for name in ESCALATION_LEVELS:
        chain = [l.name for l in escalation_chain(name)]
        assert chain[-1] == "deep_x4_inner_x2"
        assert len(chain) == len(set(chain))    # no cycles
    base = ESCALATION_LEVELS["base"]
    assert (base.restarts_scale, base.m_scale, base.inner_scale) == (1, 1, 1)
    with pytest.raises(KeyError, match="no_such_level"):
        escalation_chain("no_such_level")


def test_escalation_walks_chain_and_recovers():
    """A restarted-GMRES-hostile diagonal system fails at base and at
    restarts_x4, converges at deep_x4_inner_x2 — the walk stops there
    and lands ONE recovered ``solver_escalation`` incident."""
    w = jnp.logspace(0.0, 2.0, 48)
    A = lambda x: w * x                                     # noqa: E731
    b = jnp.ones(48)

    def attempt(level, i):
        return fgmres(A, b, m=8 * level.m_scale, tol=1e-4,
                      restarts=1 * level.restarts_scale)

    incidents = []
    sol = escalate_solve(attempt, context="drill",
                         on_incident=incidents.append)
    assert bool(sol.converged)
    [rec] = incidents
    assert rec["event"] == "solver_escalation"
    assert rec["kind"] == "solver_breakdown"
    assert rec["recovered"] is True and rec["context"] == "drill"
    assert rec["level"] == "deep_x4_inner_x2"
    assert [a["converged"] for a in rec["attempts"]] == [False, False,
                                                         True]
    assert [a["level"] for a in rec["attempts"]] == [
        "base", "restarts_x4", "deep_x4_inner_x2"]
    assert rec["attempts"][0]["resnorm"] > rec["attempts"][-1]["resnorm"]


def test_escalation_level0_converging_is_bitwise_plain_solve():
    """When the base geometry converges the walk must add NOTHING: no
    incident, and a result bitwise-identical to the plain solve."""
    A = lambda x: 2.0 * x                                   # noqa: E731
    b = jnp.ones(48)
    ref = fgmres(A, b, m=8, tol=1e-4, restarts=1)
    assert bool(ref.converged)

    incidents = []
    sol = escalate_solve(
        lambda level, i: fgmres(A, b, m=8 * level.m_scale, tol=1e-4,
                                restarts=1 * level.restarts_scale),
        on_incident=incidents.append)
    assert incidents == []
    assert np.array_equal(np.asarray(sol.x), np.asarray(ref.x))
    assert int(sol.iters) == int(ref.iters)
    assert float(sol.resnorm) == float(ref.resnorm)


def test_stagnating_solver_exhausts_chain():
    """A singular operator (``stagnating_operator``) leaves a residual
    floor no level can pass: the chain exhausts, the breakdown incident
    is recorded, and ``SolverBreakdown`` carries the full attempts list
    plus the supervisor-compatible divergence interface."""
    w = jnp.logspace(0.0, 2.0, 48)
    As = stagnating_operator(lambda x: w * x)
    b = jnp.ones(48)
    incidents = []
    with pytest.raises(SolverBreakdown) as ei:
        escalate_solve(
            lambda level, i: fgmres(As, b, m=8 * level.m_scale, tol=1e-4,
                                    restarts=1 * level.restarts_scale),
            context="drill", on_incident=incidents.append, step=42)
    e = ei.value
    assert isinstance(e, SimulationDiverged)
    assert e.kind == "solver_breakdown"
    assert e.step == 42 and e.bad_leaves == []
    assert len(e.attempts) == 3
    assert not any(a["converged"] for a in e.attempts)
    assert e.incident_payload() == {"context": "drill",
                                    "attempts": e.attempts}
    rec = incidents[-1]
    assert rec["event"] == "solver_breakdown"
    assert rec["recovered"] is False and rec["level"] is None
    assert rec["attempts"] == e.attempts


def test_record_solve_stats_eager_jit_and_mirror():
    """Stats surfacing contract: eager solves record synchronously (and
    onto every mirror — the FAC-preconditioner sharing path); traced
    solves record NOTHING unless the owner opted into the callback."""
    class Sink:
        last_solve_stats = None

    sink, mirror = Sink(), Sink()
    sol = SolveResult(x=jnp.zeros(3), iters=jnp.asarray(5),
                      resnorm=jnp.asarray(1e-9),
                      converged=jnp.asarray(True))
    record_solve_stats(sink, sol, solver="fgmres",
                       mirrors=(mirror, None))
    assert sink.last_solve_stats == {"iters": 5, "resnorm": 1e-9,
                                     "converged": True,
                                     "solver": "fgmres"}
    assert mirror.last_solve_stats is sink.last_solve_stats

    # traced, no opt-in: jitted/SPMD paths pay nothing
    silent = Sink()

    @jax.jit
    def f(b):
        record_solve_stats(
            silent, SolveResult(b, jnp.asarray(1), jnp.sum(b),
                                jnp.asarray(True)), solver="x")
        return b

    jax.block_until_ready(f(jnp.ones(3)))
    assert silent.last_solve_stats is None

    # traced WITH opt-in: the debug callback lands host-side
    tapped = Sink()

    @jax.jit
    def g(b):
        record_solve_stats(
            tapped, SolveResult(b, jnp.asarray(7), jnp.sum(b),
                                jnp.asarray(False)),
            solver="cg", use_callback=True)
        return b

    jax.block_until_ready(g(jnp.ones(3)))
    jax.effects_barrier()
    assert tapped.last_solve_stats == {"iters": 7, "resnorm": 3.0,
                                       "converged": False, "solver": "cg"}


def test_stokes_solve_escalated_level0_bitwise():
    """The production wiring: a converging StaggeredStokesSolver base
    solve records ``last_solve_stats`` and ``solve_escalated`` returns
    BITWISE the plain solve with no incident."""
    from ibamr_tpu.solvers.stokes import StaggeredStokesSolver, channel_bc

    n = (12, 12)
    solver = StaggeredStokesSolver(n, (1.0 / 12, 1.0 / 12), channel_bc(2),
                                   alpha=1.0, mu=0.01, tol=1e-8)
    rng = np.random.default_rng(3)
    u = tuple(jnp.asarray(rng.standard_normal(s)) for s in solver.shapes)
    p = jnp.asarray(rng.standard_normal(solver.n))
    rhs = solver.operator((u, p))
    ref = solver.solve(rhs)
    assert bool(ref.converged)
    stats = solver.last_solve_stats
    assert stats["converged"] is True and stats["solver"] == "fgmres"
    assert stats["iters"] == int(ref.iters)
    assert stats["resnorm"] == float(ref.resnorm)

    incidents = []
    sol = solver.solve_escalated(rhs, on_incident=incidents.append)
    assert incidents == []
    for a, b in zip(jax.tree_util.tree_leaves((sol.u, sol.p)),
                    jax.tree_util.tree_leaves((ref.u, ref.p))):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert int(sol.iters) == int(ref.iters)


def test_supervisor_treats_solver_breakdown_like_divergence(tmp_path):
    """A ``SolverBreakdown`` raised at the driver level (the host-side
    escalation seat, between chunks) must ride the SAME rollback + dt
    backoff as a NaN divergence, with the attempts list in the
    incident."""
    integ = _ins()
    st0 = _tg_state(integ)
    dt0 = 1e-3
    d = str(tmp_path)
    cfg = RunConfig(dt=dt0, num_steps=12, restart_interval=4,
                    health_interval=2)
    attempts = [{"level": "base", "iters": 8, "resnorm": 0.5,
                 "converged": False},
                {"level": "restarts_x4", "iters": 32, "resnorm": 0.4,
                 "converged": False},
                {"level": "deep_x4_inner_x2", "iters": 64, "resnorm": 0.3,
                 "converged": False}]
    drv = HierarchyDriver(integ, cfg)

    def metrics_fn(s, k):
        # dt-gated like a real breakdown: the backed-off dt converges
        if k == 6 and drv.cfg.dt >= dt0 * 0.99:
            raise SolverBreakdown("StaggeredStokesSolver", attempts,
                                  step=k)
        return None

    drv.metrics_fn = metrics_fn
    sup = ResilientDriver(drv, d, max_retries=2, dt_backoff=0.5,
                          handle_signals=False)
    out = sup.run(st0)
    assert int(out.k) == 12
    [rec] = [r for r in sup.incidents if r["event"] == "divergence"]
    assert rec["kind"] == "solver_breakdown"
    assert rec["context"] == "StaggeredStokesSolver"
    assert rec["attempts"] == attempts
    assert rec["step"] == 6
    assert rec["rollback_step"] == 4 and rec["from_checkpoint"]
    assert rec["dt_after"] == pytest.approx(dt0 * 0.5)
    with open(os.path.join(d, "incidents.jsonl")) as f:
        [line] = [json.loads(l) for l in f]
    assert line["kind"] == "solver_breakdown"
    assert line["attempts"] == attempts


def test_bicgstab_guard_returns_best_iterate():
    """The cg round-4 divergence guard, ported: a converging solve is
    untouched, and a WANDERING solve (this matrix drives the BiCGStab
    residual from |b| = 7.1 up to ~27 and it never comes back) must
    return the best iterate seen — so the returned residual norm can
    never exceed |b|, the x0 = 0 starting residual. The pre-guard code
    returned the final wandered iterate here, ~3.7x worse than doing
    nothing."""
    rng = np.random.RandomState(0)
    n = 24
    Mb = np.eye(n) + 0.1 * rng.randn(n, n)      # nonsymmetric, benign
    A = lambda x: jnp.asarray(Mb) @ x           # noqa: E731
    b = jnp.asarray(rng.randn(n))
    res = bicgstab(A, b, tol=1e-10, maxiter=200)
    assert bool(res.converged)
    assert float(jnp.linalg.norm(b - A(res.x))) \
        <= 1e-8 * float(jnp.linalg.norm(b))

    rng = np.random.RandomState(3)
    Mw = np.eye(40) * 2.0 + rng.randn(40, 40)   # the wander case
    Aw = lambda x: jnp.asarray(Mw) @ x          # noqa: E731
    bw = jnp.asarray(rng.randn(40))
    bnorm = float(jnp.linalg.norm(bw))
    res2 = bicgstab(Aw, bw, tol=1e-14, maxiter=400)
    assert not bool(res2.converged)
    assert bool(jnp.all(jnp.isfinite(res2.x)))
    assert float(res2.resnorm) <= bnorm * (1 + 1e-12)
    # and the claim holds for the TRUE residual of the returned iterate,
    # not just the recurred norm
    assert float(jnp.linalg.norm(bw - Aw(res2.x))) <= bnorm * (1 + 1e-10)
    # the guard's resnorm is a running min: non-increasing in maxiter
    # (the final-iterate residual oscillates; the best-seen cannot)
    cuts = [float(bicgstab(Aw, bw, tol=1e-14, maxiter=mi).resnorm)
            for mi in (5, 25, 100, 400)]
    assert all(a >= c - 1e-12 for a, c in zip(cuts, cuts[1:]))


# ---------------------------------------------------------------------------
# PR 3: run watchdog — heartbeat semantics + stall detection
# ---------------------------------------------------------------------------

def test_watchdog_rejects_bad_config():
    for kw in ({"interval_s": 0.0}, {"stall_factor": 1.0},
               {"min_stall_s": -1.0}, {"ema_alpha": 0.0},
               {"ema_alpha": 1.5}):
        with pytest.raises(ValueError):
            RunWatchdog(**kw)


def test_watchdog_heartbeat_and_stall_detection(tmp_path):
    """Deterministic (clock-injected) detector contract: heartbeat age
    tracks the last BEAT (not the last file write — the daemon keeps
    rewriting during a hang), the stall fires once per silence at
    max(min_stall_s, factor x EMA), and a new beat re-arms it."""
    import time as _time
    recs = []
    wd = RunWatchdog(heartbeat_path=str(tmp_path), interval_s=0.5,
                     stall_factor=3.0, min_stall_s=1.0,
                     on_incident=recs.append)
    # a directory path (existing or not) gets the canonical file name
    assert wd.heartbeat_path == os.path.join(str(tmp_path),
                                             "heartbeat.json")
    # before the first beat the detector stays silent forever
    assert wd.check(now=_time.monotonic() + 1e6) is None

    wd.beat(step=10, last_chunk_wall_s=0.2)
    wd.beat(step=20, last_chunk_wall_s=0.2)
    hb = read_heartbeat(wd.heartbeat_path)
    assert hb["step"] == 20 and hb["pid"] == os.getpid()
    assert hb["last_chunk_wall_s"] == pytest.approx(0.2)
    assert hb["steps_per_s"] is not None and hb["steps_per_s"] > 0

    # heartbeat_age follows the beat: a later rewrite with a fresher
    # ``written`` stamp must NOT make the file look younger
    age0 = heartbeat_age(wd.heartbeat_path)
    assert age0 is not None and age0 < 5.0
    write_heartbeat(wd.heartbeat_path,
                    dict(hb, written=hb["written"] + 100.0))
    assert heartbeat_age(wd.heartbeat_path) == pytest.approx(age0,
                                                             abs=5.0)
    assert heartbeat_age(os.path.join(str(tmp_path), "nope.json")) is None

    # threshold floors at min_stall_s (EMA of 0.2 s chunks x 3 < 1 s)
    assert wd.stall_threshold_s() == pytest.approx(1.0)
    t0 = wd._last_beat
    assert wd.check(now=t0 + 0.5) is None       # within threshold
    rec = wd.check(now=t0 + 2.0)                # past it: fires ONCE
    assert rec is not None
    assert rec["event"] == "stall" and rec["kind"] == "stall"
    assert rec["step"] == 20
    assert rec["beat_age_s"] == pytest.approx(2.0)
    assert rec["threshold_s"] == pytest.approx(1.0)
    assert recs == [rec] and wd.stalls == [rec]
    assert wd.check(now=t0 + 3.0) is None       # once per silence
    wd.beat(step=30)                            # the run moved: re-arm
    assert wd.check(now=wd._last_beat + 2.0) is not None
    assert len(wd.stalls) == 2


def test_watchdog_flags_stalled_supervised_run(tmp_path):
    """End-to-end (slow tier): a supervised run whose host callback
    hangs 1.2 s — indistinguishable from a hung compile from outside —
    gets a ``stall`` incident in the SAME incidents.jsonl, and the
    heartbeat file ends on the final real beat."""
    integ = _ins()
    st0 = _tg_state(integ)
    d = str(tmp_path)
    cfg = RunConfig(dt=1e-3, num_steps=8, health_interval=2)
    drv = HierarchyDriver(integ, cfg)
    drv.run(st0, start_step=6)          # warm the 2-step chunk compile
    stalls = []
    wd = RunWatchdog(heartbeat_path=d, interval_s=0.05, stall_factor=3.0,
                     min_stall_s=0.4, on_stall=stalls.append)
    drv.metrics_fn = slow_metrics(1.2, at_steps={4})
    sup = ResilientDriver(drv, d, handle_signals=False, watchdog=wd)
    out = sup.run(st0)
    assert int(out.k) == 8
    recs = [r for r in sup.incidents if r["kind"] == "stall"]
    assert recs, "stall never detected"
    assert recs[0]["step"] == 4         # the beat that preceded the hang
    assert recs[0]["beat_age_s"] > recs[0]["threshold_s"]
    assert stalls and stalls[0]["step"] == 4    # policy hook fired too
    hb = read_heartbeat(os.path.join(d, "heartbeat.json"))
    assert hb is not None and hb["step"] == 8
    with open(os.path.join(d, "incidents.jsonl")) as f:
        kinds = [json.loads(l)["kind"] for l in f]
    assert "stall" in kinds
