"""IMP material-point method (P18): kernel-gradient transfers,
constitutive law, and end-to-end coupling.

Oracles: analytic velocity-gradient interpolation on a smooth periodic
field (2nd-order kernel accuracy), exact zero total spread force
(sum_g grad(delta) = 0 — discrete momentum conservation), neo-Hookean
stress identities (P(I) = 0, small-strain linear elasticity limit), and
a relaxing elastic disc that stays finite, conserves volume
approximately, and returns toward J = 1."""

import jax.numpy as jnp
import numpy as np

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.imp import (IMPExplicitIntegrator, IMPMethod,
                                       IMPState, NeoHookean,
                                       material_disc)
from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
from ibamr_tpu.ops import interaction


def _grid(n=32, dim=2):
    return StaggeredGrid(n=(n,) * dim, x_lo=(0.0,) * dim,
                         x_up=(1.0,) * dim)


def test_velocity_gradient_interpolation_accuracy():
    """du_i/dx_j at points matches the analytic gradient of a smooth
    periodic velocity field to kernel accuracy (O(h^2) for BSPLINE_3)."""
    errs = []
    for n in (32, 64):
        g = _grid(n)
        x_f = np.arange(n) / n                 # u faces
        y_c = (np.arange(n) + 0.5) / n
        X, Y = np.meshgrid(x_f, y_c, indexing="ij")
        u = jnp.asarray(np.sin(2 * np.pi * X) * np.cos(2 * np.pi * Y))
        Xc, Yc = np.meshgrid(y_c, x_f, indexing="ij")
        v = jnp.asarray(np.cos(2 * np.pi * Xc) * np.sin(2 * np.pi * Yc))
        rng = np.random.default_rng(0)
        pts = jnp.asarray(0.2 + 0.6 * rng.random((200, 2)))
        G = interaction.interpolate_gradient_vel((u, v), g, pts)
        p = np.asarray(pts)
        dudx = 2 * np.pi * np.cos(2 * np.pi * p[:, 0]) \
            * np.cos(2 * np.pi * p[:, 1])
        dudy = -2 * np.pi * np.sin(2 * np.pi * p[:, 0]) \
            * np.sin(2 * np.pi * p[:, 1])
        Gn = np.asarray(G)
        errs.append(max(np.max(np.abs(Gn[:, 0, 0] - dudx)),
                        np.max(np.abs(Gn[:, 0, 1] - dudy))))
    assert errs[0] < 0.5
    assert errs[0] / errs[1] > 3.0     # ~2nd order


def test_spread_stress_zero_total_force():
    """Total spread internal force is exactly zero (momentum
    conservation: the kernel gradient sums to zero over the grid)."""
    g = _grid(16)
    rng = np.random.default_rng(3)
    X = jnp.asarray(0.2 + 0.6 * rng.random((40, 2)))
    PFt = jnp.asarray(rng.standard_normal((40, 2, 2)))
    V = jnp.asarray(rng.random(40) + 0.5)
    f = interaction.spread_stress(PFt, V, g, X)
    for comp in f:
        assert abs(float(jnp.sum(comp))) < 1e-10


def test_neo_hookean_identities():
    model = NeoHookean(mu=1.0, lam=2.0)
    eye = jnp.eye(2)[None]
    assert np.max(np.abs(np.asarray(model.pk1(eye)))) < 1e-12
    # small-strain limit: P ~ mu*(grad u + grad u^T) + lam*tr(eps)*I
    eps = 1e-6
    H = jnp.asarray([[[0.3, 0.1], [0.2, -0.4]]]) * eps
    P = np.asarray(model.pk1(eye + H))[0]
    Hs = np.asarray(H)[0]
    P_lin = 1.0 * (Hs + Hs.T) + 2.0 * np.trace(Hs) * np.eye(2)
    assert np.max(np.abs(P - P_lin)) < 1e-10


def test_elastic_disc_relaxes():
    """A pre-stretched elastic disc in quiescent fluid develops flow,
    stays finite, and relaxes its deformation (mean |J - 1| decreases)."""
    n = 32
    g = _grid(n)
    ins = INSStaggeredIntegrator(g, mu=0.05, rho=1.0)
    X0, V0 = material_disc(g, (0.5, 0.5), 0.15, points_per_cell=2)
    imp = IMPMethod(V0, NeoHookean(mu=5.0, lam=5.0))
    integ = IMPExplicitIntegrator(ins, imp)
    st = integ.initialize(X0)
    # impose an initial uniform 10% x-stretch on the material
    stretch = jnp.asarray([[1.1, 0.0], [0.0, 1.0]], dtype=st.F.dtype)
    st = IMPState(ins=st.ins, X=st.X, F=st.F @ stretch, mask=st.mask)
    J0 = float(jnp.mean(jnp.abs(integ.jacobians(st) - 1.0)))
    dt = 2e-3
    for _ in range(40):
        st = integ.step(st, dt)
    assert np.all(np.isfinite(np.asarray(st.X)))
    assert np.all(np.isfinite(np.asarray(st.F)))
    J1 = float(jnp.mean(jnp.abs(integ.jacobians(st) - 1.0)))
    assert J1 < J0          # stress drives back toward J = 1
    # fluid picked up energy from the prestress
    assert float(jnp.max(jnp.abs(st.ins.u[0]))) > 1e-4


def test_imp_step_jits():
    import jax

    g = _grid(16)
    ins = INSStaggeredIntegrator(g, mu=0.1, rho=1.0)
    X0, V0 = material_disc(g, (0.5, 0.5), 0.12)
    integ = IMPExplicitIntegrator(ins, IMPMethod(V0, NeoHookean(1.0, 1.0)))
    st = integ.initialize(X0)
    step = jax.jit(lambda s: integ.step(s, 1e-3))
    st = step(step(st))
    assert np.all(np.isfinite(np.asarray(st.X)))


def test_nonsmooth_kernel_rejected_for_gradient_transfers():
    """ADVICE round 2: IMP accepts any Kernel, but kink-point (IB_4),
    table-interpolated (IB_6), and C^0 kernels must raise rather than
    silently degrade the kernel-gradient transfers."""
    import pytest

    grid = StaggeredGrid(n=(16, 16), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    u = tuple(jnp.zeros(grid.n) for _ in range(2))
    X = jnp.asarray([[0.5, 0.5]])
    for bad in ("IB_4", "IB_6", "PIECEWISE_LINEAR", "BSPLINE_2",
                "COMPOSITE_BSPLINE_32"):
        with pytest.raises(ValueError, match="C\\^1"):
            interaction.interpolate_vel_and_gradient(u, grid, X,
                                                     kernel=bad)
    # the C^1 families and user-defined pairs still work
    interaction.interpolate_vel_and_gradient(u, grid, X,
                                             kernel="BSPLINE_3")
    from ibamr_tpu.ops.delta import get_kernel
    interaction.interpolate_vel_and_gradient(
        u, grid, X, kernel=get_kernel("BSPLINE_3"))
