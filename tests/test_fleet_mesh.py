"""Fleet lanes × mesh sharding (PR 16): the B×D pod fleet.

The contract under test is that lane-mesh sharding is INVISIBLE to the
physics and to the resilience machinery: sharding the lane axis of a
B-lane fleet over D devices (``parallel.mesh.make_lane_mesh`` +
``lane_mesh=`` on the driver) must reproduce the replicated fleet
BITWISE in f64 — through per-lane quarantine and dt backoff — because
lanes are independent and each device owns whole lanes (no cross-lane
collective may ever be introduced). Elastic N→M restart rides the
PR-6 sharded-checkpoint manifests: a run saved on the 8-device lane
mesh restores bitwise onto a 4-device mesh (2 lanes/device).
"""

import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
from ibamr_tpu.parallel.mesh import (
    make_lane_mesh, place_lanes, shard_lanes)
from ibamr_tpu.utils.hierarchy_driver import HierarchyDriver, RunConfig
from ibamr_tpu.utils.lanes import lane_slice, stack_lanes
from ibamr_tpu.utils.supervisor import ResilientDriver
from tools.fault_injection import lane_nan_injector


def _ins(n=16, mu=0.01):
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    return INSStaggeredIntegrator(g, rho=1.0, mu=mu, dtype=jnp.float64)


def _tg_state(integ, amp=1.0):
    g = integ.grid
    xf, yc = g.face_centers(0, jnp.float64)
    xc, yf = g.face_centers(1, jnp.float64)
    u = amp * jnp.sin(2 * math.pi * xf) * jnp.cos(2 * math.pi * yc) \
        + 0 * yc
    v = -amp * jnp.cos(2 * math.pi * xc) * jnp.sin(2 * math.pi * yf) \
        + 0 * xc
    return integ.initialize(u0_arrays=(u, v))


def _lane_states(integ, B):
    return [_tg_state(integ, amp=1.0 + 0.05 * i) for i in range(B)]


def _bitwise_equal(a, b) -> bool:
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return all(np.asarray(x).tobytes() == np.asarray(y).tobytes()
               for x, y in zip(la, lb))


# ---------------------------------------------------------------------------
# sharded == replicated, bitwise
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n_dev", [8, 4])
def test_sharded_fleet_matches_replicated_bitwise(n_dev):
    """B=8 lanes over an 8- and a 4-device lane mesh (1 and 2 whole
    lanes per device) vs the replicated fleet: identical bits."""
    integ = _ins()
    B, steps, dt = 8, 4, 1e-3
    states = _lane_states(integ, B)
    cfg = RunConfig(dt=dt, num_steps=steps, health_interval=2)

    rep = HierarchyDriver(integ, cfg, lanes=B).run(stack_lanes(states))

    mesh = make_lane_mesh(n_dev)
    drv = HierarchyDriver(integ, cfg, lanes=B, lane_mesh=mesh)
    stacked = place_lanes(stack_lanes(states), mesh)
    sh = drv.run(stacked)

    assert _bitwise_equal(rep, sh)
    # and the output is STILL lane-sharded (no silent gather)
    for leaf in jax.tree_util.tree_leaves(sh):
        if hasattr(leaf, "sharding") and leaf.ndim >= 1:
            assert len(leaf.sharding.device_set) == n_dev
            break


def test_lane_mesh_rejects_indivisible_fleet():
    integ = _ins()
    mesh = make_lane_mesh(8)
    with pytest.raises(ValueError, match="divisible"):
        HierarchyDriver(integ, RunConfig(dt=1e-3, num_steps=2),
                        lanes=6, lane_mesh=mesh)
    with pytest.raises(ValueError, match="fleet mode"):
        HierarchyDriver(integ, RunConfig(dt=1e-3, num_steps=2),
                        lane_mesh=mesh)
    with pytest.raises(ValueError, match="divide"):
        place_lanes(stack_lanes(_lane_states(integ, 6)), mesh)


def test_shard_lanes_pins_lane_axis():
    integ = _ins()
    mesh = make_lane_mesh(8)
    stacked = stack_lanes(_lane_states(integ, 8))

    @jax.jit
    def pin(t):
        return shard_lanes(t, mesh)

    out = pin(stacked)
    leaf = jax.tree_util.tree_leaves(out)[0]
    assert len(leaf.sharding.device_set) == 8


# ---------------------------------------------------------------------------
# resilience machinery under sharding: quarantine + dt backoff
# ---------------------------------------------------------------------------

def _supervised(integ, cfg, B, states, tmp_path, tag, inj,
                max_retries, lane_mesh=None):
    drv = HierarchyDriver(
        integ, cfg, lanes=B, lane_mesh=lane_mesh,
        fleet_step_wrap=lambda s: lane_nan_injector(s, **inj))
    sup = ResilientDriver(drv, os.path.join(str(tmp_path), tag),
                          max_retries=max_retries, dt_backoff=0.5,
                          handle_signals=False,
                          sharded=lane_mesh is not None, mesh=lane_mesh)
    stacked = stack_lanes(states)
    if lane_mesh is not None:
        stacked = place_lanes(stacked, lane_mesh)
    final = sup.run(stacked)
    return drv, sup, final


def test_sharded_fleet_quarantine_and_backoff_match_replicated(tmp_path):
    """The full resilience episode — NaN fault, per-lane rollback with
    dt backoff, quarantine after retry exhaustion — plays out
    IDENTICALLY on the sharded and the replicated fleet. No
    checkpoints (restart_interval=0), so both modes roll the failing
    lane back to its initial slice and the final states must be
    bitwise equal lane for lane."""
    integ = _ins()
    B, BAD, steps, dt = 8, 3, 8, 1e-3
    states = _lane_states(integ, B)
    cfg = RunConfig(dt=dt, num_steps=steps, health_interval=2,
                    restart_interval=0)
    inj = dict(at_step=4, lane=BAD, fleet_size=B, leaf_path="u[0]",
               step_attr="k", dt_gate=dt)

    drv_r, sup_r, fin_r = _supervised(integ, cfg, B, states, tmp_path,
                                      "rep", inj, max_retries=1)
    drv_s, sup_s, fin_s = _supervised(integ, cfg, B, states, tmp_path,
                                      "sh", inj, max_retries=1,
                                      lane_mesh=make_lane_mesh(8))

    # same episode: one rollback (dt-gated fault cured by backoff),
    # no quarantine, same dt vectors and alive masks
    for sup in (sup_r, sup_s):
        assert [r.get("event") for r in sup.incidents].count(
            "lane_rollback") == 1
        assert not any(r.get("event") == "lane_quarantine"
                       for r in sup.incidents)
    np.testing.assert_array_equal(drv_r.lane_dt, drv_s.lane_dt)
    np.testing.assert_array_equal(drv_r.lane_alive, drv_s.lane_alive)
    assert drv_s.lane_dt[BAD] == pytest.approx(0.5 * dt)
    assert _bitwise_equal(fin_r, fin_s)


def test_sharded_fleet_quarantines_exhausted_lane(tmp_path):
    integ = _ins()
    B, BAD, steps, dt = 8, 5, 8, 1e-3
    states = _lane_states(integ, B)
    cfg = RunConfig(dt=dt, num_steps=steps, health_interval=2,
                    restart_interval=0)
    inj = dict(at_step=4, lane=BAD, fleet_size=B, leaf_path="u[0]",
               step_attr="k")

    drv_r, sup_r, fin_r = _supervised(integ, cfg, B, states, tmp_path,
                                      "rep", inj, max_retries=0)
    drv_s, sup_s, fin_s = _supervised(integ, cfg, B, states, tmp_path,
                                      "sh", inj, max_retries=0,
                                      lane_mesh=make_lane_mesh(8))

    for drv, sup in ((drv_r, sup_r), (drv_s, sup_s)):
        assert not drv.lane_alive[BAD]
        assert sum(drv.lane_alive) == B - 1
        quar = [r for r in sup.incidents
                if r.get("event") == "lane_quarantine"]
        assert len(quar) == 1 and quar[0]["lane"] == BAD
    assert _bitwise_equal(fin_r, fin_s)
    # one compiled trace per chunk length on the sharded side too
    assert all(v == 1 for v in drv_s.trace_counts.values()), \
        drv_s.trace_counts


# ---------------------------------------------------------------------------
# elastic N -> M restart via the sharded-checkpoint manifest
# ---------------------------------------------------------------------------

def test_elastic_8_to_4_restart_bitwise(tmp_path):
    """A fleet checkpoint saved on the 8-device lane mesh restores
    BITWISE onto a 4-device mesh (2 lanes/device) via the manifest,
    and resuming there matches the uninterrupted 8-device run."""
    from ibamr_tpu.utils.checkpoint_sharded import (
        restore_sharded, save_sharded_checkpoint)

    integ = _ins()
    B, dt = 8, 1e-3
    states = _lane_states(integ, B)
    mesh8 = make_lane_mesh(8)
    cfg_half = RunConfig(dt=dt, num_steps=4, health_interval=2)

    # run 4 steps on 8 devices, checkpoint
    drv8 = HierarchyDriver(integ, cfg_half, lanes=B, lane_mesh=mesh8)
    mid = drv8.run(place_lanes(stack_lanes(states), mesh8))
    save_sharded_checkpoint(str(tmp_path), mid, 4, mesh=mesh8)

    # the pod shrank: restore onto 4 devices via the manifest
    mesh4 = make_lane_mesh(4)
    template = place_lanes(stack_lanes(states), mesh4)
    restored, step, manifest = restore_sharded(str(tmp_path), template)
    assert step == 4
    assert _bitwise_equal(restored, mid)
    lead = jax.tree_util.tree_leaves(restored)[0]
    assert len(lead.sharding.device_set) == 4

    # resume 4 more steps on the smaller mesh == 8 uninterrupted steps
    drv4 = HierarchyDriver(integ, cfg_half, lanes=B, lane_mesh=mesh4)
    fin4 = drv4.run(restored)
    cfg_full = RunConfig(dt=dt, num_steps=8, health_interval=2)
    drv_full = HierarchyDriver(integ, cfg_full, lanes=B,
                               lane_mesh=mesh8)
    fin8 = drv_full.run(place_lanes(stack_lanes(states), mesh8))
    assert _bitwise_equal(fin4, fin8)
