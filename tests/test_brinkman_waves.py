"""P22 remainder (VERDICT round 2, item 9): Brinkman penalization and
wave generation/damping zones.

Oracles: the implicit penalty clamps interior velocity to the body
velocity and stays divergence-free; at steady state the porous-obstacle
drag balances the driving force (periodic momentum budget); a free heavy
cylinder sediments drag-limited; zero-amplitude wave zones preserve
still water; a generated wave reaches the working region at the target
amplitude scale and the damping beach kills it.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ins import INSStaggeredIntegrator
from ibamr_tpu.ops import stencils
from ibamr_tpu.physics import brinkman, waves


# ---------------------------------------------------------------------------
# Brinkman penalization
# ---------------------------------------------------------------------------

def _cyl_setup(n=48, eta=1e-3, mu=0.02):
    g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    ins = INSStaggeredIntegrator(g, mu=mu, rho=1.0)
    body = brinkman.BrinkmanBody(brinkman.make_cylinder_sdf(0.12),
                                 eta=eta)
    bp = brinkman.BrinkmanPenalization(ins, [body])
    bst = brinkman.RigidBodyState(
        center=jnp.asarray([0.5, 0.5], dtype=ins.dtype),
        U=jnp.zeros(2, dtype=ins.dtype),
        theta=jnp.zeros((), dtype=ins.dtype),
        omega=jnp.zeros((), dtype=ins.dtype))
    return g, ins, bp, [bst]


def test_brinkman_clamps_interior_and_divfree():
    """Driven periodic flow past a fixed cylinder: the velocity inside
    the body collapses to ~0 while the outside stream stays O(free
    stream); the re-projection keeps div u at roundoff."""
    g, ins, bp, bsts = _cyl_setup()
    st = ins.initialize()
    fdrive = (0.2 * jnp.ones(g.n, dtype=ins.dtype),
              jnp.zeros(g.n, dtype=ins.dtype))
    dt = 2e-3
    for _ in range(60):
        st, bsts, imp = bp.step(st, bsts, dt, f=fdrive)
    chi = bp.bodies[0].chi(g, 0, bsts[0])
    core = chi > 0.99
    u_in = float(jnp.max(jnp.abs(jnp.where(core, st.u[0], 0.0))))
    u_out = float(jnp.max(jnp.abs(st.u[0])))
    assert u_out > 20.0 * u_in, (u_in, u_out)
    div = stencils.divergence(st.u, g.dx)
    assert float(jnp.max(jnp.abs(div))) < 1e-3 * u_out / g.dx[0]


def test_porous_obstacle_drag_balances_driving_force():
    """Periodic momentum budget, two oracles: (a) EVERY step satisfies
    dP/dt = F_drive - F_drag exactly (convection/pressure/viscous all
    integrate to zero on the periodic box, so the penalty impulse is the
    only sink — discrete identity, not an approximation); (b) at steady
    state the obstacle drag balances the driving force to ~1%."""
    g = StaggeredGrid(n=(48, 48), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    ins = INSStaggeredIntegrator(g, mu=0.2, rho=1.0)
    body = brinkman.BrinkmanBody(brinkman.make_cylinder_sdf(0.2),
                                 eta=1e-3)
    bp = brinkman.BrinkmanPenalization(ins, [body])
    bsts = [brinkman.RigidBodyState(
        center=jnp.asarray([0.5, 0.5], dtype=ins.dtype),
        U=jnp.zeros(2, dtype=ins.dtype),
        theta=jnp.zeros((), dtype=ins.dtype),
        omega=jnp.zeros((), dtype=ins.dtype))]
    st = ins.initialize()
    fdrive = (0.2 * jnp.ones(g.n, dtype=ins.dtype),
              jnp.zeros(g.n, dtype=ins.dtype))
    dt = 5e-3
    vol = g.dx[0] * g.dx[1]
    drive = 0.2 * 1.0                        # integral f dV, unit box
    drag = 0.0
    for k in range(400):
        P0 = float(jnp.sum(st.u[0])) * vol
        st, bsts, imp = bp.step(st, bsts, dt, f=fdrive)
        P1 = float(jnp.sum(st.u[0])) * vol
        drag = float(imp[0][0][0]) / dt
        budget_err = abs((P1 - P0) / dt - (drive - drag))
        # bound = f32 cancellation floor of (P1-P0)/dt: P*eps/dt ~ 5e-6
        assert budget_err < 5e-5 * drive, (k, budget_err)
    assert abs(drag - drive) < 0.02 * drive, (drag, drive)


def test_brinkman_free_cylinder_sediments():
    """A heavy free cylinder under gravity falls, drag-limited, and the
    measured settling stays below free fall of the excess weight."""
    g = StaggeredGrid(n=(48, 48), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    ins = INSStaggeredIntegrator(g, mu=0.05, rho=1.0)
    r = 0.1
    vol = math.pi * r * r
    body = brinkman.BrinkmanBody(brinkman.make_cylinder_sdf(r),
                                 eta=1e-3, density=3.0, volume=vol)
    bp = brinkman.BrinkmanPenalization(ins, [body],
                                       gravity=[0.0, -1.0])
    bst = brinkman.RigidBodyState(
        center=jnp.asarray([0.5, 0.65], dtype=ins.dtype),
        U=jnp.zeros(2, dtype=ins.dtype),
        theta=jnp.zeros((), dtype=ins.dtype),
        omega=jnp.zeros((), dtype=ins.dtype))
    st = ins.initialize()
    dt = 2e-3
    v_hist = []
    bsts = [bst]
    for _ in range(150):
        st, bsts, _ = bp.step(st, bsts, dt)
        v_hist.append(float(bsts[0].U[1]))
    t_end = 150 * dt
    vy = v_hist[-1]
    assert vy < 0.0                                   # falls
    assert v_hist[-1] <= v_hist[10]                   # kept falling
    g_eff = (3.0 - 1.0) / 3.0 * 1.0                   # buoyant accel
    assert abs(vy) < g_eff * t_end, (vy, g_eff * t_end)  # drag active
    assert float(bsts[0].center[1]) < 0.65


def test_box_sdf_and_prescribed_motion():
    """A prescribed moving box advects its center, and the box SDF is
    negative inside / positive outside."""
    sdf = brinkman.make_box_sdf((0.1, 0.2))
    inside = float(sdf([jnp.asarray(0.05), jnp.asarray(0.1)]))
    outside = float(sdf([jnp.asarray(0.3), jnp.asarray(0.0)]))
    assert inside < 0.0 < outside
    g = StaggeredGrid(n=(32, 32), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    ins = INSStaggeredIntegrator(g, mu=0.05, rho=1.0)
    body = brinkman.BrinkmanBody(sdf, eta=1e-3)
    bp = brinkman.BrinkmanPenalization(ins, [body])
    bst = brinkman.RigidBodyState(
        center=jnp.asarray([0.4, 0.5], dtype=ins.dtype),
        U=jnp.asarray([0.25, 0.0], dtype=ins.dtype),
        theta=jnp.zeros((), dtype=ins.dtype),
        omega=jnp.zeros((), dtype=ins.dtype))
    st = ins.initialize()
    st, bsts, _ = bp.step(st, [bst], 0.02)
    assert np.isclose(float(bsts[0].center[0]), 0.405)
    # the dragged fluid moves with the box
    chi = body.chi(g, 0, bsts[0])
    u_core = st.u[0][chi > 0.99]
    assert float(jnp.mean(u_core)) > 0.08


# ---------------------------------------------------------------------------
# wave zones
# ---------------------------------------------------------------------------

def test_stokes_wave_theory_sanity():
    w = waves.StokesWave(amplitude=0.02, wavelength=1.0, depth=0.25,
                        still_level=0.25, gravity=1.0)
    # finite-depth dispersion
    assert np.isclose(w.omega,
                      math.sqrt(1.0 * w.k * math.tanh(w.k * 0.25)))
    x = jnp.linspace(0.0, 1.0, 201)[:-1]
    eta = w.elevation(x, 0.0)
    assert abs(float(jnp.mean(eta))) < 1e-6        # zero-mean (order 1)
    assert np.isclose(float(jnp.max(eta)), 0.02, rtol=1e-6)
    # deep-water velocity decays with depth
    u_top = float(w.velocity(jnp.asarray(0.0), jnp.asarray(0.25),
                             0.0, 0))
    u_bot = float(w.velocity(jnp.asarray(0.0), jnp.asarray(0.02),
                             0.0, 0))
    assert abs(u_top) > abs(u_bot) > 0.0
    # second order steepens crests, zero-mean stays approximately
    w2 = w._replace(order=2)
    eta2 = w2.elevation(x, 0.0)
    assert float(jnp.max(eta2)) > float(jnp.max(eta))


def test_relaxation_ramp_endpoints():
    g = StaggeredGrid(n=(64, 16), x_lo=(0.0, 0.0), x_up=(2.0, 0.5))
    z = waves.make_zone(g, 0.0, 0.5, "generation", outer="lo")
    # outer end (x=0) strongly constrained, inner end free, outside zero
    assert float(z.w_cc[0, 0]) > 0.8
    assert float(z.w_cc[10, 0]) < 0.05
    assert float(z.w_cc[40, 0]) == 0.0


def _tank(amp=0.015):
    """The calibrated NWT layout (round-3): wall-bounded in BOTH
    periodic directions via Brinkman slabs (an x-periodic tank is a
    resonator, and the bare z-wrap is a water-over-air RT instability
    at grid scale), soft-started generation, wave bed aligned with the
    floor top, beach before the end wall."""
    from ibamr_tpu.integrators.ins_vc import INSVCStaggeredIntegrator

    g = StaggeredGrid(n=(128, 32), x_lo=(0.0, 0.0), x_up=(2.56, 0.64))
    integ = INSVCStaggeredIntegrator(
        g, rho0=1.0, rho1=1e-2, mu0=1e-4, mu1=1e-4,
        gravity=[0.0, -1.0], convective_op_type="upwind",
        reinit_interval=0, precond="mg")
    wave = waves.StokesWave(amplitude=amp, wavelength=1.0, depth=0.25,
                            still_level=0.31, gravity=1.0)
    gen = waves.make_zone(g, 0.1, 0.6, "generation", outer="lo")
    damp = waves.make_zone(g, 1.6, 2.4, "damping", outer="hi")
    tank = waves.WaveTank(integ, wave, gen, damp, floor=0.06, lid=0.06,
                          end_wall=0.12, eta_solid=1e-3)
    zc = waves.cell_coords(g, integ.dtype)
    st = integ.initialize(zc[1] - 0.31)
    return g, tank, st


def test_still_water_preserved_by_zones():
    """amplitude=0: the tank machinery (zones + solid slabs + soft
    start) must not disturb hydrostatics."""
    g, tank, st = _tank(amp=0.0)
    step = jax.jit(lambda s: tank.step(s, 2e-3))
    for _ in range(100):
        st = step(st)
    assert float(jnp.max(jnp.abs(st.u[0]))) < 5e-3
    assert float(jnp.max(jnp.abs(st.u[1]))) < 5e-3
    probe = tank.elevation_probe(st, 55)
    assert abs(float(probe)) < 3e-3


def test_wave_generated_then_damped():
    """Waves reach the working region at the target amplitude scale
    (calibrated: amp_mid ~ 0.75 a at t = 6 ~ 2.3 periods) and the
    beach sits orders of magnitude quieter; water volume is conserved
    to a few percent with no reinitialization."""
    g, tank, st = _tank(amp=0.015)
    dt = 2e-3
    step = jax.jit(lambda s: tank.step(s, dt))
    ix_mid = int(1.1 / 2.56 * 128)
    ix_beach = int(2.3 / 2.56 * 128)
    vol0 = float(jnp.sum(st.phi < 0)) * g.dx[0] * g.dx[1]
    probes_mid, probes_beach = [], []
    n_steps = 3000
    for k in range(n_steps):
        st = step(st)
        if k > n_steps - 1600:                # >= one period window
            probes_mid.append(float(tank.elevation_probe(st, ix_mid)))
            probes_beach.append(
                float(tank.elevation_probe(st, ix_beach)))
    amp_mid = 0.5 * (max(probes_mid) - min(probes_mid))
    amp_beach = 0.5 * (max(probes_beach) - min(probes_beach))
    # margin note: the f32 projection's tolerance floor (krylov cg
    # divergence guard + dtype clamp, round 4) shifts the roundoff
    # path; measured amp sits at 0.40a +- a few 1e-4 across such
    # perturbations, so the arrival threshold is 0.35a, not 0.40a
    assert amp_mid > 0.35 * 0.015, (amp_mid,)      # wave arrived
    assert amp_mid < 2.0 * 0.015, (amp_mid,)       # same scale
    assert amp_beach < 0.1 * amp_mid, (amp_mid, amp_beach)
    vol1 = float(jnp.sum(st.phi < 0)) * g.dx[0] * g.dx[1]
    assert abs(vol1 - vol0) < 0.03 * vol0, (vol0, vol1)
    assert bool(jnp.isfinite(st.u[0]).all())


def test_brinkman_free_rotation_spins_down():
    """A free body spinning in quiescent fluid must be RETARDED by the
    penalty torque (round-3 review: a sign inversion anti-damped it)."""
    g = StaggeredGrid(n=(48, 48), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    ins = INSStaggeredIntegrator(g, mu=0.05, rho=1.0)
    r = 0.15
    vol = math.pi * r * r
    body = brinkman.BrinkmanBody(brinkman.make_cylinder_sdf(r),
                                 eta=1e-3, density=2.0, volume=vol,
                                 moment=0.5 * 2.0 * vol * r * r)
    bp = brinkman.BrinkmanPenalization(ins, [body])
    bsts = [brinkman.RigidBodyState(
        center=jnp.asarray([0.5, 0.5], dtype=ins.dtype),
        U=jnp.zeros(2, dtype=ins.dtype),
        theta=jnp.zeros((), dtype=ins.dtype),
        omega=jnp.asarray(2.0, dtype=ins.dtype))]
    st = ins.initialize()
    om_hist = []
    for _ in range(80):
        st, bsts, _ = bp.step(st, bsts, 2e-3)
        om_hist.append(float(bsts[0].omega))
    assert om_hist[-1] > 0.0                      # same direction
    assert om_hist[-1] < om_hist[0] < 2.0         # monotone spin-down
    assert om_hist[-1] < 0.95 * 2.0


def test_irregular_sea_vectorized_and_tank_compatible():
    """IrregularSea: the broadcast-sum evaluation matches a manual
    per-component superposition, and WaveTank accepts it (soft start
    via scaled(), ramp sized by the slowest component)."""
    from ibamr_tpu.integrators.ins_vc import INSVCStaggeredIntegrator

    sea = waves.IrregularSea(
        amplitudes=jnp.asarray([0.01, 0.005, 0.002]),
        wavelengths=jnp.asarray([1.0, 0.6, 0.4]),
        phases=jnp.asarray([0.0, 1.0, 2.5]),
        depth=0.25, still_level=0.31, gravity=1.0)
    x = jnp.linspace(0.0, 2.0, 41)
    eta = sea.elevation(x, 0.7)
    manual = sum(
        waves.StokesWave(amplitude=float(a), wavelength=float(w),
                         depth=0.25, still_level=0.31, gravity=1.0,
                         phase=float(p)).elevation(x, 0.7)
        for a, w, p in zip(sea.amplitudes, sea.wavelengths, sea.phases))
    assert np.allclose(np.asarray(eta), np.asarray(manual), atol=1e-7)
    u = sea.velocity(x, jnp.asarray(0.2), 0.7, 0)
    manual_u = sum(
        waves.StokesWave(amplitude=float(a), wavelength=float(w),
                         depth=0.25, still_level=0.31, gravity=1.0,
                         phase=float(p)).velocity(x, jnp.asarray(0.2),
                                                  0.7, 0)
        for a, w, p in zip(sea.amplitudes, sea.wavelengths, sea.phases))
    assert np.allclose(np.asarray(u), np.asarray(manual_u), atol=1e-6)

    g = StaggeredGrid(n=(64, 16), x_lo=(0.0, 0.0), x_up=(2.56, 0.64))
    integ = INSVCStaggeredIntegrator(
        g, rho0=1.0, rho1=1e-2, mu0=1e-4, mu1=1e-4,
        gravity=[0.0, -1.0], reinit_interval=0, precond="mg")
    gen = waves.make_zone(g, 0.1, 0.6, "generation", outer="lo")
    tank = waves.WaveTank(integ, sea, gen, floor=0.06, lid=0.06,
                          end_wall=0.12)
    zc = waves.cell_coords(g, integ.dtype)
    st = integ.initialize(zc[1] - 0.31)
    step = jax.jit(lambda s: tank.step(s, 2e-3))
    for _ in range(10):
        st = step(st)
    assert bool(jnp.isfinite(st.u[0]).all())


def test_nwt_physical_walls_match_brinkman():
    """The PHYSICALLY-walled NWT (floor + lid as no-slip wall BCs on
    the vertical axis, VERDICT round 3 missing #3) against the
    calibrated Brinkman-slab tank: same wave, same depth, same zones —
    the mid-tank amplitude must agree and the beach must stay quiet.
    This validates the wall-BC path against the penalization path the
    round-3 tank was built on."""
    from ibamr_tpu.integrators.ins_vc import INSVCStaggeredIntegrator

    amp = 0.015
    # physically-walled tank: floor at z=0 (still level = depth so the
    # water column matches the Brinkman tank's bed-to-surface depth)
    g = StaggeredGrid(n=(128, 32), x_lo=(0.0, 0.0), x_up=(2.56, 0.64))
    integ = INSVCStaggeredIntegrator(
        g, rho0=1.0, rho1=1e-2, mu0=1e-4, mu1=1e-4,
        gravity=[0.0, -1.0], convective_op_type="upwind",
        reinit_interval=0, precond="mg", wall_axes=(False, True))
    wave = waves.StokesWave(amplitude=amp, wavelength=1.0, depth=0.25,
                            still_level=0.25, gravity=1.0)
    gen = waves.make_zone(g, 0.1, 0.6, "generation", outer="lo")
    damp = waves.make_zone(g, 1.6, 2.4, "damping", outer="hi")
    tank = waves.WaveTank(integ, wave, gen, damp,
                          end_wall=0.12, eta_solid=1e-3)
    zc = waves.cell_coords(g, integ.dtype)
    st = integ.initialize(zc[1] - 0.25)

    dt = 2e-3
    step = jax.jit(lambda s: tank.step(s, dt))
    ix_mid = int(1.1 / 2.56 * 128)
    ix_beach = int(2.3 / 2.56 * 128)
    probes_mid, probes_beach = [], []
    n_steps = 3000
    for k in range(n_steps):
        st = step(st)
        if k > n_steps - 1600:
            probes_mid.append(float(tank.elevation_probe(st, ix_mid)))
            probes_beach.append(
                float(tank.elevation_probe(st, ix_beach)))
    amp_mid = 0.5 * (max(probes_mid) - min(probes_mid))
    amp_beach = 0.5 * (max(probes_beach) - min(probes_beach))

    # same acceptance envelope as the Brinkman tank's own test: the
    # wave arrives at the target scale and the beach is quiet
    assert amp_mid > 0.35 * amp, (amp_mid,)
    assert amp_mid < 2.0 * amp, (amp_mid,)
    assert amp_beach < 0.15 * amp_mid, (amp_mid, amp_beach)
    assert bool(jnp.isfinite(st.u[0]).all())
    # wall-normal faces exactly zero at floor and lid (the physical
    # wall really is the boundary — no Brinkman slab involved)
    assert float(jnp.max(jnp.abs(st.u[1][:, 0:1]))) == 0.0
