"""Stage-8 tests: two-level static refinement machinery (SURVEY.md §7.2).

Covers the T10 transfer-operator contracts and the composite subcycled
advance: restriction conservation, CF interpolation accuracy order,
divergence-preserving MAC prolongation (exactness), composite mass
conservation with refluxing, and matched-solution accuracy vs a uniform
fine run.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu import amr
from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import stencils
from ibamr_tpu.solvers import fft


def _grid2d(n=32):
    return StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))


def _grid3d(n=16):
    return StaggeredGrid(n=(n, n, n), x_lo=(0.0,) * 3, x_up=(1.0,) * 3)


# -- restriction ------------------------------------------------------------

def test_restrict_cc_conservation():
    rng = np.random.default_rng(0)
    f = jnp.asarray(rng.standard_normal((8, 8, 8)))
    c = amr.restrict_cc(f, 2)
    assert c.shape == (4, 4, 4)
    # block mean conserves the integral (fine cells are 1/8 volume)
    assert np.isclose(float(f.sum()) / 8.0, float(c.sum()))


def test_restrict_mac_preserves_coarse_fluxes():
    rng = np.random.default_rng(1)
    nf = (8, 6)
    uf = (jnp.asarray(rng.standard_normal((nf[0] + 1, nf[1]))),
          jnp.asarray(rng.standard_normal((nf[0], nf[1] + 1))))
    uc = amr.restrict_mac(uf, 2)
    assert uc[0].shape == (5, 3)
    assert uc[1].shape == (4, 4)
    # flux through a coarse x-face = sum of its 2 fine faces
    want = float(uf[0][2, 0] + uf[0][2, 1]) / 2.0
    assert np.isclose(float(uc[0][1, 0]), want)


# -- CF interpolation -------------------------------------------------------

@pytest.mark.parametrize("dim", [2, 3])
def test_cf_ghost_interp_order(dim):
    """Quadratic ghost fill from coarse is O(h^3) on smooth fields."""
    errs = []
    for n in (16, 32):
        g = _grid2d(n) if dim == 2 else StaggeredGrid(
            n=(n,) * 3, x_lo=(0.0,) * 3, x_up=(1.0,) * 3)
        box = amr.FineBox(lo=(n // 4,) * dim, shape=(n // 4,) * dim)

        def f(coords):
            out = 0.0
            for c in coords:
                out = out + jnp.sin(2 * jnp.pi * c)
            return jnp.broadcast_to(out, g.n if len(
                coords[0].shape) == dim else None)

        Qc = f(g.cell_centers(jnp.float64))
        fine = box.fine_grid(g)
        ghost = 2
        padded = amr.prolong_cc(Qc, box, ghost=ghost, order=2)
        # exact values at the padded points
        r = box.ratio
        axes = []
        for d in range(dim):
            i = np.arange(-ghost, box.fine_n[d] + ghost)
            axes.append(g.x_lo[d] + (box.lo[d] + (i + 0.5) / r) * g.dx[d])
        mesh = np.meshgrid(*axes, indexing="ij")
        exact = sum(np.sin(2 * np.pi * m) for m in mesh)
        errs.append(float(jnp.max(jnp.abs(padded - exact))))
    order = np.log2(errs[0] / errs[1])
    assert order > 2.5, f"CF interp order {order}, errs {errs}"


# -- div-preserving MAC prolongation ---------------------------------------

@pytest.mark.parametrize("dim", [2, 3])
def test_prolong_mac_div_preserving(dim):
    rng = np.random.default_rng(2)
    n = 16
    g = _grid2d(n) if dim == 2 else _grid3d(n)
    # random MAC field, projected discretely divergence-free
    u = tuple(jnp.asarray(rng.standard_normal(g.n)) for _ in range(dim))
    u, _ = fft.project_divergence_free(u, g.dx)
    assert float(jnp.max(jnp.abs(stencils.divergence(u, g.dx)))) < 1e-10

    box = amr.FineBox(lo=(4,) * dim, shape=(4,) * dim)
    uf = amr.prolong_mac_div_preserving(u, g, box)
    for d in range(dim):
        want = list(box.fine_n)
        want[d] += 1
        assert uf[d].shape == tuple(want)
    dx_f = tuple(h / 2 for h in g.dx)
    df = amr._box_mac_divergence(uf, dx_f)
    assert float(jnp.max(jnp.abs(df))) < 1e-10, "prolonged field not div-free"

    # fine divergence equals the parent coarse divergence for general
    # (non-solenoidal) fields too
    v = tuple(jnp.asarray(rng.standard_normal(g.n)) for _ in range(dim))
    vf = amr.prolong_mac_div_preserving(v, g, box)
    df = amr._box_mac_divergence(vf, dx_f)
    dc = stencils.divergence(v, g.dx)
    box_sl = tuple(slice(box.lo[a], box.hi[a]) for a in range(dim))
    parent = np.repeat(np.repeat(np.asarray(dc[box_sl]), 2, 0), 2, 1)
    if dim == 3:
        parent = np.repeat(parent, 2, 2)
    assert float(jnp.max(jnp.abs(df - parent))) < 1e-9


def test_prolong_mac_preserves_coarse_face_fluxes():
    """Restriction o prolongation = identity on the box MAC data."""
    rng = np.random.default_rng(3)
    g = _grid2d(16)
    u = tuple(jnp.asarray(rng.standard_normal(g.n)) for _ in range(2))
    box = amr.FineBox(lo=(4, 6), shape=(4, 3))
    uf = amr.prolong_mac_div_preserving(u, g, box)
    uc = amr.restrict_mac(uf, 2)
    # compare against the coarse faces of the box (+1 extent on own axis)
    want_x = u[0][4:9, 6:9]
    want_y = u[1][4:8, 6:10]
    np.testing.assert_allclose(np.asarray(uc[0]), np.asarray(want_x),
                               atol=1e-12)
    np.testing.assert_allclose(np.asarray(uc[1]), np.asarray(want_y),
                               atol=1e-12)


# -- composite advance ------------------------------------------------------

def _gauss(coords, x0, w):
    r2 = 0.0
    for c, x in zip(coords, x0):
        r2 = r2 + (c - x) ** 2
    return jnp.exp(-r2 / w ** 2)


def test_two_level_conservation():
    """Refluxed composite advance conserves total mass to roundoff."""
    n = 32
    g = _grid2d(n)
    box = amr.FineBox(lo=(8, 8), shape=(12, 12))
    fine = box.fine_grid(g)
    u_c = (0.7 * jnp.ones(g.n), -0.3 * jnp.ones(g.n))
    u_f = (0.7 * jnp.ones((box.fine_n[0] + 1, box.fine_n[1])),
           -0.3 * jnp.ones((box.fine_n[0], box.fine_n[1] + 1)))
    integ = amr.TwoLevelAdvDiff(g, box, kappa=2e-3, scheme="upwind",
                                u_coarse=u_c, u_fine=u_f)
    Qc, Qf = integ.initialize(lambda c: _gauss(c, (0.45, 0.45), 0.08))
    tot0 = float(integ.total(Qc, Qf))
    dt = 2e-3
    for _ in range(40):
        Qc, Qf = integ.step(Qc, Qf, dt)
    tot1 = float(integ.total(Qc, Qf))
    assert abs(tot1 - tot0) < 1e-12 * max(1.0, abs(tot0)), \
        f"mass drift {tot1 - tot0}"
    assert np.isfinite(float(jnp.max(jnp.abs(Qf))))


def test_two_level_matches_uniform_fine():
    """With the feature inside the fine box, the composite solution tracks
    a uniform-fine run far better than the coarse-only run (the stage-8
    acceptance criterion, SURVEY.md §7.2)."""
    n = 32
    kappa = 1.5e-3
    g = _grid2d(n)
    box = amr.FineBox(lo=(6, 6), shape=(16, 16))
    integ = amr.TwoLevelAdvDiff(g, box, kappa=kappa, scheme="centered")
    Qc, Qf = integ.initialize(lambda c: _gauss(c, (0.45, 0.45), 0.07))
    dt = 1.2e-3
    nsteps = 60
    for _ in range(nsteps):
        Qc, Qf = integ.step(Qc, Qf, dt)

    # uniform fine reference: pure-diffusion explicit Euler at dx/2, dt/2
    gf = _grid2d(2 * n)
    Qr = _gauss(gf.cell_centers(jnp.float64), (0.45, 0.45), 0.07)
    Qr = jnp.broadcast_to(Qr, gf.n)
    for _ in range(2 * nsteps):
        Qr = Qr + 0.5 * dt * kappa * stencils.laplacian(Qr, gf.dx)

    # coarse-only run (same scheme on the coarse grid)
    Qo = _gauss(g.cell_centers(jnp.float64), (0.45, 0.45), 0.07)
    Qo = jnp.broadcast_to(Qo, g.n)
    for _ in range(nsteps):
        Qo = Qo + dt * kappa * stencils.laplacian(Qo, g.dx)

    # compare inside the fine box (fine cells vs reference cells coincide)
    fsl = tuple(slice(2 * box.lo[a], 2 * box.hi[a]) for a in range(2))
    err_comp = float(jnp.max(jnp.abs(Qf - Qr[fsl])))
    # coarse-only error measured against block-averaged reference
    ref_c = amr.restrict_cc(Qr, 2)
    box_sl = tuple(slice(box.lo[a], box.hi[a]) for a in range(2))
    err_coarse = float(jnp.max(jnp.abs(Qo[box_sl] - ref_c[box_sl])))
    assert err_comp < 0.5 * err_coarse, (err_comp, err_coarse)
    assert err_comp < 5e-4, err_comp
