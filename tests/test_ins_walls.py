"""Wall-bounded (no-slip) INS: channel flow and Stokes-box checks."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.bc import dirichlet_axis
from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.integrators.ins import INSStaggeredIntegrator, advance
from ibamr_tpu.integrators.ins_walls import WallOps
from ibamr_tpu.ops import stencils
from ibamr_tpu.solvers.fastdiag import laplacian_1d_cc


def test_projection_wall_divergence_free():
    """Random u* projects to a discretely div-free field with pinned wall
    faces, in 2D and 3D."""
    rng = np.random.default_rng(0)
    for n, walls in (((16, 12), (False, True)),
                     ((8, 8, 8), (False, True, True))):
        grid = StaggeredGrid(n=n, x_lo=(0.0,) * len(n), x_up=(1.0,) * len(n))
        ops = WallOps(grid, walls)
        u = []
        for d in range(len(n)):
            c = jnp.asarray(rng.standard_normal(n))
            u.append(ops._pin_normal(c, d))
        u_new, _ = ops.project(tuple(u), grid.dx)
        div = stencils.divergence(u_new, grid.dx)
        assert float(jnp.max(jnp.abs(div))) < 1e-10
        # pinned faces stay zero
        for d, w in enumerate(walls):
            if w:
                idx = [slice(None)] * len(n)
                idx[d] = 0
                assert float(jnp.max(jnp.abs(u_new[d][tuple(idx)]))) == 0.0


def test_poiseuille_steady_state():
    """Constant body force in a channel (periodic x, no-slip y walls)
    relaxes to the DISCRETE Poiseuille profile: mu lap_h u = -G with
    Dirichlet-face walls — compared against the dense 1D solve, and
    against the parabolic analytic profile at O(h^2)."""
    nx, ny = 8, 32
    G, mu = 1.0, 0.1
    grid = StaggeredGrid(n=(nx, ny), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    integ = INSStaggeredIntegrator(grid, rho=1.0, mu=mu,
                                   convective_op_type="none",
                                   dtype=jnp.float64,
                                   wall_axes=(False, True))
    state = integ.initialize()
    f = (jnp.full(grid.n, G, dtype=jnp.float64),
         jnp.zeros(grid.n, dtype=jnp.float64))

    # viscous time H^2/nu = 10; run well past it
    state = advance(integ, state, dt=0.05, num_steps=600, f=f)

    profile = np.asarray(state.u[0][0, :])     # u(y), any x column
    # dense discrete steady state
    A = laplacian_1d_cc(ny, grid.dx[1], dirichlet_axis())
    dense = np.linalg.solve(mu * A, -G * np.ones(ny))
    np.testing.assert_allclose(profile, dense, rtol=1e-6)
    # analytic parabola at O(h^2)
    y = np.asarray(grid.cell_coords_1d(1, jnp.float64))
    exact = G / (2 * mu) * y * (1.0 - y)
    assert float(np.max(np.abs(profile - exact))) < 2e-3
    # v stays identically zero
    assert float(jnp.max(jnp.abs(state.u[1]))) < 1e-12


def test_stokes_box_energy_decay():
    """No-slip box, unforced: kinetic energy decays monotonically and the
    field stays div-free."""
    n = 24
    grid = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    integ = INSStaggeredIntegrator(grid, rho=1.0, mu=0.02,
                                   convective_op_type="none",
                                   dtype=jnp.float64,
                                   wall_axes=(True, True))
    # streamfunction psi = sin^2(pi x) sin^2(pi y): no-slip compatible
    pi = math.pi

    def u0(coords, t):
        x, y = coords
        return [2 * pi * jnp.sin(pi * x) ** 2 * jnp.sin(pi * y)
                * jnp.cos(pi * y),
                -2 * pi * jnp.sin(pi * x) * jnp.cos(pi * x)
                * jnp.sin(pi * y) ** 2]

    state = integ.initialize(u0=u0)
    # project the analytic field onto the discrete div-free space and pin
    ops = WallOps(grid, (True, True))
    u = tuple(ops._pin_normal(c, d) for d, c in enumerate(state.u))
    u, _ = ops.project(u, grid.dx)
    state = state._replace(u=u)

    energies = [float(integ.kinetic_energy(state))]
    for _ in range(5):
        state = advance(integ, state, dt=2e-3, num_steps=10)
        energies.append(float(integ.kinetic_energy(state)))
        div = float(jnp.max(jnp.abs(integ.max_divergence(state))))
        assert div < 1e-10
    assert all(b < a for a, b in zip(energies, energies[1:])), energies
    # t_end = 0.1, nu = 0.02: expect roughly exp(-2 nu (2 pi^2) t) ~ 0.8
    assert energies[-1] < 0.9 * energies[0], energies


def test_float32_pressure_stays_bounded():
    """Regression: the Neumann-Poisson nullspace eigenvalue from eigh is
    ~1e-13 (never exactly 0); without relative thresholding the constant
    mode amplifies f32 roundoff into O(1e6) pressures."""
    n = 16
    grid = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    integ = INSStaggeredIntegrator(grid, rho=1.0, mu=0.02,
                                   convective_op_type="none",
                                   dtype=jnp.float32,
                                   wall_axes=(True, True))
    state = integ.initialize()
    rng = np.random.default_rng(2)
    f = tuple(jnp.asarray(rng.standard_normal(grid.n), dtype=jnp.float32)
              for _ in range(2))
    state = advance(integ, state, dt=1e-2, num_steps=5, f=f)
    assert float(jnp.max(jnp.abs(state.p))) < 1e3
    assert float(jnp.max(jnp.abs(state.u[0]))) < 1e2


def test_wall_axes_length_validated():
    grid = StaggeredGrid(n=(8, 8), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    with pytest.raises(ValueError):
        INSStaggeredIntegrator(grid, wall_axes=(False, False, True),
                               convective_op_type="none")


def test_wall_convection_supported():
    """Round 1 hard-errored here; wall-aware convection is now a
    first-class path (tests/test_ins_ppm_walls.py has the physics)."""
    grid = StaggeredGrid(n=(8, 8), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    integ = INSStaggeredIntegrator(grid, wall_axes=(False, True),
                                   convective_op_type="centered")
    st = integ.initialize()
    st = integ.step(st, 1e-3)
    assert bool(jnp.all(jnp.isfinite(st.u[0])))


def test_helmholtz_vel_wall_residual():
    """(alpha + beta lap_wall) u == rhs through WallOps.laplacian_vel."""
    rng = np.random.default_rng(1)
    grid = StaggeredGrid(n=(12, 16), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    ops = WallOps(grid, (False, True))
    rhs = tuple(ops._pin_normal(jnp.asarray(rng.standard_normal(grid.n)), d)
                for d in range(2))
    alpha, beta = 4.0, -0.3
    u = ops.helmholtz_vel(rhs, grid.dx, alpha, beta)
    lap = ops.laplacian_vel(u, grid.dx)
    for d in range(2):
        res = alpha * u[d] + beta * lap[d] - rhs[d]
        # pinned slots excluded (rhs there is irrelevant)
        if ops.wall_axes[d]:
            idx = [slice(None)] * 2
            idx[d] = slice(1, None)
            res = res[tuple(idx)]
        assert float(jnp.max(jnp.abs(res))) < 1e-10, d
