"""Multi-box dynamic AMR (VERDICT round 2 item 4): tag clustering into
K fine windows — two separating structures each tracked by their own
refined box.

Oracles: clustering (components, separation, identity matching);
conservation of the composite integral through multi-window regrids;
the two-blob separation scenario with each blob inside its own window
at the end; regrid-invariance against a static two-window layout that
already covers both blob paths.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ibamr_tpu.amr_multibox import (MultiBoxDynamicAdvDiff,
                                    cluster_boxes, connected_components)
from ibamr_tpu.grid import StaggeredGrid

F64 = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


def two_gauss(x0a, y0a, x0b, y0b, w):
    def fn(coords):
        x, y = coords
        return (jnp.exp(-((x - x0a) ** 2 + (y - y0a) ** 2) / w ** 2)
                + jnp.exp(-((x - x0b) ** 2 + (y - y0b) ** 2) / w ** 2))
    return fn


def test_connected_components_and_cluster():
    tags = np.zeros((32, 32), dtype=bool)
    tags[4:8, 4:8] = True                 # blob A (16 cells)
    tags[20:26, 20:26] = True             # blob B (36 cells)
    comps = connected_components(tags)
    assert len(comps) == 2
    assert len(comps[0]) == 36 and len(comps[1]) == 16

    lo = cluster_boxes(tags, 2, (8, 8), clearance=2)
    assert lo.shape == (2, 2)
    # each blob inside one box
    boxes = [tuple(l) for l in lo]
    for (r0, r1), blob in ((((4, 8), (4, 8)), None),
                           (((20, 26), (20, 26)), None)):
        hit = any(l[0] <= r0[0] and r0[1] <= l[0] + 8
                  and l[1] <= r1[0] and r1[1] <= l[1] + 8
                  for l in boxes)
        assert hit, (boxes, r0, r1)


def test_cluster_separates_overlapping_boxes():
    tags = np.zeros((32, 32), dtype=bool)
    tags[10:12, 10:12] = True
    tags[14:16, 14:16] = True             # close pair: centered 8-boxes
    lo = cluster_boxes(tags, 2, (8, 8), clearance=2)  # would overlap
    ov = [min(lo[0][d] + 8, lo[1][d] + 8) - max(lo[0][d], lo[1][d])
          for d in range(2)]
    assert not all(o > 0 for o in ov), lo   # disjoint (may touch)


def test_cluster_identity_matching():
    tags = np.zeros((32, 32), dtype=bool)
    tags[4:7, 4:7] = True
    tags[22:25, 22:25] = True
    prev = np.asarray([[20, 20], [3, 3]])  # box 0 was at the FAR blob
    lo = cluster_boxes(tags, 2, (8, 8), clearance=2, prev=prev)
    # identity follows the feature: box 0 stays near (20,20)
    assert abs(lo[0][0] - 20) <= 4 and abs(lo[1][0] - 3) <= 4


def test_wrap_cluster_no_tags_uses_prev():
    tags = np.zeros((16, 16), dtype=bool)
    prev = np.asarray([[2, 2], [8, 8]])
    lo = cluster_boxes(tags, 2, (4, 4), clearance=2, prev=prev)
    assert np.array_equal(lo, prev)


@pytest.mark.slow
def test_two_blobs_tracked_and_conserved():
    """Two blobs advected apart by u = -A sin(2 pi x): each ends inside
    its own window; the composite integral is conserved through every
    multi-window regrid."""
    grid = StaggeredGrid(n=(48, 48), x_lo=(0, 0), x_up=(1, 1))

    def u_fn(coords, d):
        x = coords[0]
        if d == 0:
            return -0.4 * jnp.sin(2.0 * np.pi * x)
        return jnp.zeros_like(x)

    sim = MultiBoxDynamicAdvDiff(grid, (12, 12), K=2, kappa=1e-3,
                                 u_fn=u_fn, tag_threshold=0.03,
                                 dtype=F64)
    st = sim.initialize(two_gauss(0.36, 0.5, 0.64, 0.5, 0.06))
    m0 = float(sim.total(st))
    # the two windows start on different blobs
    assert abs(int(st.lo[0][0]) - int(st.lo[1][0])) > 4

    dt = 2.5e-4
    st = sim.advance_regridding(st, dt, 400, regrid_interval=10)
    m1 = float(sim.total(st))
    assert abs(m1 - m0) < 1e-10 * max(1.0, abs(m0))

    # blobs separated; each window tracked its blob (windows moved
    # apart and still bracket the solution mass)
    Qc = np.asarray(st.Qc)
    lo = np.asarray(st.lo)
    assert abs(lo[0][0] - lo[1][0]) > 8
    # locate blob peaks on the synchronized coarse level
    from ibamr_tpu.amr_dynamic import restrict_into_coarse
    Qs = st.Qc
    for k in range(2):
        Qs = restrict_into_coarse(Qs, st.Qf[k], st.lo[k], 2)
    Qs = np.asarray(Qs)
    left_peak = np.unravel_index(np.argmax(Qs[:24, :]), (24, 48))
    right_peak = np.unravel_index(np.argmax(Qs[24:, :]), (24, 48))
    right_peak = (right_peak[0] + 24, right_peak[1])
    for peak in (left_peak, right_peak):
        inside = any(lo[k][0] <= peak[0] < lo[k][0] + 12
                     and lo[k][1] <= peak[1] < lo[k][1] + 12
                     for k in range(2))
        assert inside, (peak, lo)


@pytest.mark.slow
def test_multibox_regrid_invariance():
    """Frequent multi-window regrids vs a static layout already covering
    both blob paths: fields agree closely on the coarse level."""
    grid = StaggeredGrid(n=(48, 48), x_lo=(0, 0), x_up=(1, 1))

    def u_fn(coords, d):
        x = coords[0]
        if d == 0:
            return -0.25 * jnp.sin(2.0 * np.pi * x)
        return jnp.zeros_like(x)

    sim = MultiBoxDynamicAdvDiff(grid, (14, 14), K=2, kappa=2e-3,
                                 u_fn=u_fn, tag_threshold=0.02,
                                 dtype=F64)
    ic = two_gauss(0.35, 0.5, 0.65, 0.5, 0.06)
    st_dyn = sim.initialize(ic)
    st_static = sim.initialize(ic)
    dt = 2.5e-4
    st_dyn = sim.advance_regridding(st_dyn, dt, 60, regrid_interval=6)
    st_static = jax.jit(lambda s: sim.advance(s, dt, 60))(st_static)

    # compare on the synchronized coarse level
    from ibamr_tpu.amr_dynamic import restrict_into_coarse
    out = []
    for st in (st_dyn, st_static):
        Q = st.Qc
        for k in range(2):
            Q = restrict_into_coarse(Q, st.Qf[k], st.lo[k], 2)
        out.append(np.asarray(Q))
    scale = np.max(np.abs(out[1]))
    assert np.max(np.abs(out[0] - out[1])) < 0.02 * scale


def test_cluster_enforces_gap_and_raises_when_impossible():
    """Windows must be separated by >= GAP (reflux cells uncovered);
    impossible layouts raise instead of silently overlapping."""
    tags = np.zeros((32, 32), dtype=bool)
    tags[10:12, 10:12] = True
    tags[13:15, 10:12] = True             # adjacent pair
    lo = cluster_boxes(tags, 2, (8, 8), clearance=2)
    from ibamr_tpu.amr_multibox import GAP
    gap = [max(lo[0][d], lo[1][d])
           - min(lo[0][d] + 8, lo[1][d] + 8) for d in range(2)]
    assert max(gap) >= GAP, lo

    with pytest.raises(ValueError, match="disjoint"):
        cluster_boxes(np.zeros((16, 16), dtype=bool), 2, (8, 8),
                      clearance=2)        # two 8-boxes cannot fit
