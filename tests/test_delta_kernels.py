"""IB_6 + composite B-spline delta kernels and the extended structure
file menu (VERDICT round 1 item 7; SURVEY.md T2/P10/Appendix B).
"""

import math

import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.io.structures import (StructureData, read_structure,
                                     write_structure)
from ibamr_tpu.ops import interaction
from ibamr_tpu.ops.delta import (available_kernels, get_kernel,
                                 get_kernel_axes, is_composite,
                                 stencil_size)

_K6 = 59.0 / 60.0 - math.sqrt(29.0) / 20.0


# --------------------------------------------------------------------------
# IB_6
# --------------------------------------------------------------------------

def _weights6(x):
    """Weights of the 6 stencil points around fractional position x."""
    support, phi = get_kernel("IB_6")
    j = np.arange(-2, 4)
    return np.asarray(phi(jnp.asarray(x - j, dtype=jnp.float64))), j


@pytest.mark.parametrize("x", [0.0, 0.13, 0.25, 0.5, 0.77, 0.999])
def test_ib6_moment_conditions(x):
    w, j = _weights6(x)
    r = x - j
    assert abs(w.sum() - 1.0) < 1e-6                       # m0
    assert abs((r * w).sum()) < 1e-6                       # m1
    assert abs((r * r * w).sum() - _K6) < 1e-6             # m2 == K
    assert abs((r ** 3 * w).sum()) < 1e-6                  # m3
    even = (j % 2 == 0)
    assert abs(w[even].sum() - 0.5) < 1e-6                 # even-odd


def test_ib6_shape_properties():
    support, phi = get_kernel("IB_6")
    assert support == 6
    r = jnp.linspace(-3.5, 3.5, 2001, dtype=jnp.float64)
    v = np.asarray(phi(r))
    assert v.min() > -1e-7                                  # positive
    np.testing.assert_allclose(v, v[::-1], atol=1e-6)       # even
    assert abs(float(phi(jnp.asarray(3.0)))) < 1e-7         # compact
    assert abs(float(phi(jnp.asarray(-3.0)))) < 1e-7
    # smooth: no jumps at integer r (window transitions)
    for ri in (-2.0, -1.0, 1.0, 2.0):
        a = float(phi(jnp.asarray(ri - 1e-6)))
        b = float(phi(jnp.asarray(ri + 1e-6)))
        assert abs(a - b) < 1e-4, ri


def test_ib6_interp_spread_adjoint():
    rng = np.random.default_rng(0)
    g = StaggeredGrid(n=(24, 24), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    X = jnp.asarray(rng.uniform(0, 1, (50, 2)))
    F = jnp.asarray(rng.standard_normal((50, 2)))
    u = tuple(jnp.asarray(rng.standard_normal(g.n)) for _ in range(2))
    f = interaction.spread_vel(F, g, X, kernel="IB_6")
    U = interaction.interpolate_vel(u, g, X, kernel="IB_6")
    lhs = sum(float(jnp.sum(a * b)) for a, b in zip(f, u)) * g.cell_volume
    rhs = float(jnp.sum(F * U))
    np.testing.assert_allclose(lhs, rhs, rtol=1e-10)


# --------------------------------------------------------------------------
# composite B-splines
# --------------------------------------------------------------------------

def test_composite_kernel_resolution():
    assert is_composite("COMPOSITE_BSPLINE_32")
    assert stencil_size("COMPOSITE_BSPLINE_32") == 3
    with pytest.raises(ValueError):
        get_kernel("COMPOSITE_BSPLINE_32")   # anisotropic: per-axis only
    specs = get_kernel_axes("COMPOSITE_BSPLINE_32", 0, 2)
    assert specs[0][0] == 3 and specs[1][0] == 2      # normal=3, tang=2
    specs_c = get_kernel_axes("COMPOSITE_BSPLINE_32", "cell", 2)
    assert all(s[0] == 3 for s in specs_c)
    assert "COMPOSITE_BSPLINE_32" in available_kernels()


def test_composite_partition_of_unity_and_adjoint():
    """B-splines are partitions of unity, so spreading unit density
    integrates exactly; adjointness holds per component."""
    rng = np.random.default_rng(1)
    g = StaggeredGrid(n=(24, 20), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    X = jnp.asarray(rng.uniform(0, 1, (40, 2)))
    ones = jnp.ones(40)
    for comp in range(2):
        f = interaction.spread(ones, g, X, centering=comp,
                               kernel="COMPOSITE_BSPLINE_32")
        np.testing.assert_allclose(float(jnp.sum(f)) * g.cell_volume,
                                   40.0, rtol=1e-12)
    F = jnp.asarray(rng.standard_normal((40, 2)))
    u = tuple(jnp.asarray(rng.standard_normal(g.n)) for _ in range(2))
    f = interaction.spread_vel(F, g, X, kernel="COMPOSITE_BSPLINE_32")
    U = interaction.interpolate_vel(u, g, X,
                                    kernel="COMPOSITE_BSPLINE_32")
    lhs = sum(float(jnp.sum(a * b)) for a, b in zip(f, u)) * g.cell_volume
    np.testing.assert_allclose(lhs, float(jnp.sum(F * U)), rtol=1e-10)


def test_composite_linear_reproduction():
    """BSPLINE_2/3 interpolation reproduces linear fields exactly
    (order >= 2), composite mixing included."""
    g = StaggeredGrid(n=(32, 32), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    xf, yc = g.face_centers(0, jnp.float64)
    lin = 0.3 + 0.5 * xf + 0.2 * yc + 0 * xf
    lin = jnp.broadcast_to(lin, g.n)
    rng = np.random.default_rng(2)
    X = jnp.asarray(rng.uniform(0.2, 0.8, (30, 2)))
    U = interaction.interpolate(lin, g, X, centering=0,
                                kernel="COMPOSITE_BSPLINE_32")
    exact = 0.3 + 0.5 * X[:, 0] + 0.2 * X[:, 1]
    np.testing.assert_allclose(np.asarray(U), np.asarray(exact),
                               atol=1e-12)


# --------------------------------------------------------------------------
# extended structure-file menu
# --------------------------------------------------------------------------

def _full_structure():
    rng = np.random.default_rng(3)
    N = 10
    verts = rng.uniform(0.2, 0.8, (N, 3))
    rods = np.zeros((N - 1, 12))
    rods[:, 0] = np.arange(N - 1)
    rods[:, 1] = np.arange(1, N)
    rods[:, 2] = 0.05                       # ds
    rods[:, 3:6] = [1.0, 1.0, 0.5]          # bend/twist moduli
    rods[:, 6:9] = [10.0, 10.0, 20.0]       # shear/stretch moduli
    rods[:, 9:12] = [0.0, 0.1, 0.02]        # kappa1 kappa2 tau
    anchors = np.array([[0.0], [9.0]])
    masses = np.array([[2.0, 0.5, 100.0], [3.0, 0.25, 50.0]])
    sources = np.array([[4.0, 1.5], [5.0, -1.5]])
    inst = np.array([[6.0, 0.0, 0.0], [7.0, 0.0, 1.0], [8.0, 0.0, 2.0],
                     [1.0, 1.0, 0.0], [2.0, 1.0, 1.0]])
    return StructureData(name="fullmenu", vertices=verts, rods=rods,
                         anchors=anchors, masses=masses, sources=sources,
                         inst=inst)


def test_extended_menu_round_trip(tmp_path):
    data = _full_structure()
    base = str(tmp_path / "fullmenu")
    write_structure(base, data)
    back = read_structure(base)
    np.testing.assert_allclose(back.vertices, data.vertices)
    np.testing.assert_allclose(back.rods, data.rods)
    np.testing.assert_allclose(back.anchors, data.anchors)
    np.testing.assert_allclose(back.masses, data.masses)
    np.testing.assert_allclose(back.sources, data.sources)
    np.testing.assert_allclose(back.inst, data.inst)


def test_extended_menu_feeds_modules(tmp_path):
    data = _full_structure()
    base = str(tmp_path / "fullmenu")
    write_structure(base, data)
    back = read_structure(base)

    rods = back.rod_specs(dtype=jnp.float64)
    assert rods.idx0.shape[0] == 9
    np.testing.assert_allclose(np.asarray(rods.kappa[0]),
                               [0.0, 0.1, 0.02])
    # the rod specs drive the force evaluation end to end
    from ibamr_tpu.ops.rods import rod_force_torque, straight_rod
    X = jnp.asarray(back.vertices)
    D = jnp.broadcast_to(jnp.eye(3), (10, 3, 3)).astype(jnp.float64)
    F, T = rod_force_torque(X, D, rods)
    assert bool(jnp.all(jnp.isfinite(F))) and bool(jnp.all(jnp.isfinite(T)))

    srcs = back.source_specs(dtype=jnp.float64)
    np.testing.assert_allclose(np.asarray(srcs.Q), [1.5, -1.5])

    meters = back.meter_specs(closed=False, dtype=jnp.float64)
    assert meters.idx.shape[0] == 2          # two meters
    np.testing.assert_allclose(np.asarray(meters.idx[0][:3]), [6, 7, 8])

    mass, kappa = back.mass_arrays()
    assert mass[2] == 0.5 and kappa[3] == 50.0 and mass[0] == 0.0

    back.anchors_to_targets(1e3)
    specs = back.force_specs(dtype=jnp.float64)
    assert specs.targets is not None
    np.testing.assert_allclose(np.asarray(specs.targets.idx), [0, 9])


def test_index_validation(tmp_path):
    data = _full_structure()
    data.sources = np.array([[99.0, 1.0]])    # out of range
    base = str(tmp_path / "bad")
    write_structure(base, data)
    with pytest.raises(ValueError, match="out of range"):
        read_structure(base)
