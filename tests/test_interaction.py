"""Stage-4 acceptance (SURVEY.md §7.2 stage 4): delta-kernel moment
conditions, spread/interp adjointness, interpolation accuracy, conservation.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import delta, interaction

ALL_KERNELS = delta.available_kernels()
# composite B-splines are anisotropic (per-axis kernels) — pointwise
# phi(r) checks use the isotropic menu; tests/test_delta_kernels.py
# covers the composite family
ISOTROPIC_KERNELS = tuple(k for k in ALL_KERNELS
                          if not delta.is_composite(k))
IB_KERNELS = ("IB_3", "IB_4")


@pytest.mark.parametrize("name", ISOTROPIC_KERNELS)
def test_partition_of_unity(name):
    """sum_j phi(r - j) == 1 for any shift r (zeroth moment)."""
    support, phi = delta.get_kernel(name)
    for r in np.linspace(-0.5, 0.5, 11):
        js = np.arange(-support - 1, support + 2)
        s = float(sum(phi(jnp.asarray(r - j, dtype=jnp.float64)) for j in js))
        assert s == pytest.approx(1.0, abs=1e-12), (name, r)


@pytest.mark.parametrize("name", ("IB_3", "IB_4", "PIECEWISE_LINEAR",
                                  "BSPLINE_3", "BSPLINE_4", "BSPLINE_6"))
def test_first_moment(name):
    """sum_j (r - j) phi(r - j) == 0 (first moment -> force and torque
    consistency of spread)."""
    support, phi = delta.get_kernel(name)
    for r in np.linspace(-0.5, 0.5, 7):
        js = np.arange(-support - 2, support + 3)
        m1 = float(sum((r - j) * phi(jnp.asarray(r - j, dtype=jnp.float64))
                       for j in js))
        assert m1 == pytest.approx(0.0, abs=1e-10), (name, r)


@pytest.mark.parametrize("name", ["IB_4"])
def test_even_odd_condition(name):
    """The classic 4-point Peskin kernel satisfies the even-odd sum
    condition sum_{j even} phi == sum_{j odd} phi == 1/2 (the 3-point Roma
    kernel trades it for a second-moment condition instead)."""
    support, phi = delta.get_kernel(name)
    for r in np.linspace(-0.5, 0.5, 7):
        js = np.arange(-support - 2, support + 3)
        even = float(sum(phi(jnp.asarray(r - j, dtype=jnp.float64))
                         for j in js if j % 2 == 0))
        assert even == pytest.approx(0.5, abs=1e-10), (name, r)


@pytest.mark.parametrize("name,expected", [("IB_3", 0.5), ("IB_4", 0.375)])
def test_sum_of_squares_condition(name, expected):
    """Peskin-family kernels: sum_j phi(r-j)^2 is independent of r
    (= 1/2 for the 3-point Roma kernel, 3/8 for the 4-point Peskin)."""
    support, phi = delta.get_kernel(name)
    for r in np.linspace(-0.5, 0.5, 9):
        js = np.arange(-support - 2, support + 3)
        s2 = float(sum(phi(jnp.asarray(r - j, dtype=jnp.float64)) ** 2
                       for j in js))
        assert s2 == pytest.approx(expected, abs=1e-10), (name, r)


def test_support_compact():
    for name in ISOTROPIC_KERNELS:
        support, phi = delta.get_kernel(name)
        edge = 0.5 * support
        assert float(phi(jnp.asarray(edge + 1e-3))) == 0.0
        assert float(phi(jnp.asarray(-edge - 1e-3))) == 0.0
        assert float(phi(jnp.asarray(0.0))) > 0.0


@pytest.mark.parametrize("dim", [2, 3])
@pytest.mark.parametrize("kernel", ["IB_4", "IB_3", "BSPLINE_4"])
def test_spread_interp_adjoint(dim, kernel):
    """<spread(F), u> h^dim == sum_m F_m interp(u)_m, exactly."""
    n = 16
    g = StaggeredGrid(n=(n,) * dim, x_lo=(0.0,) * dim, x_up=(1.0,) * dim)
    rng = np.random.default_rng(0)
    N = 37
    X = jnp.asarray(rng.uniform(0, 1, size=(N, dim)), dtype=jnp.float64)
    F = jnp.asarray(rng.standard_normal(N), dtype=jnp.float64)
    u = jnp.asarray(rng.standard_normal(g.n), dtype=jnp.float64)

    f_spread = interaction.spread(F, g, X, centering="cell", kernel=kernel)
    lhs = float(jnp.sum(f_spread * u)) * g.cell_volume
    Um = interaction.interpolate(u, g, X, centering="cell", kernel=kernel)
    rhs = float(jnp.sum(F * Um))
    assert lhs == pytest.approx(rhs, rel=1e-12)


def test_spread_conserves_total_force():
    """integral of spread force == sum of marker forces (zeroth moment +
    periodic wrap)."""
    g = StaggeredGrid(n=(24, 24), x_lo=(0.0, 0.0), x_up=(2.0, 2.0))
    rng = np.random.default_rng(1)
    N = 50
    X = jnp.asarray(rng.uniform(0, 2, size=(N, 2)), dtype=jnp.float64)
    F = jnp.asarray(rng.standard_normal((N, 2)), dtype=jnp.float64)
    f = interaction.spread_vel(F, g, X, kernel="IB_4")
    for d in range(2):
        total = float(jnp.sum(f[d])) * g.cell_volume
        assert total == pytest.approx(float(jnp.sum(F[:, d])), rel=1e-12)


def test_interpolate_smooth_field_accuracy():
    """Interpolating a smooth field converges (2nd order for IB_4)."""
    errs = []
    rng = np.random.default_rng(2)
    Xn = rng.uniform(0.2, 0.8, size=(200, 2))
    for n in (16, 32, 64):
        g = StaggeredGrid(n=(n, n), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
        cx, cy = g.cell_centers(jnp.float64)
        u = jnp.sin(2 * math.pi * cx) * jnp.cos(2 * math.pi * cy)
        X = jnp.asarray(Xn, dtype=jnp.float64)
        Um = interaction.interpolate(u, g, X, centering="cell", kernel="IB_4")
        exact = np.sin(2 * math.pi * Xn[:, 0]) * np.cos(2 * math.pi * Xn[:, 1])
        errs.append(float(jnp.max(jnp.abs(Um - exact))))
    order = math.log2(errs[0] / errs[1]) / 1 if errs[1] else 99
    order2 = math.log2(errs[1] / errs[2])
    assert 0.5 * (order + order2) > 1.8, errs


def test_constant_field_interpolates_exactly():
    """Partition of unity -> a constant field interpolates exactly,
    anywhere (including near the periodic wrap)."""
    g = StaggeredGrid(n=(8, 8), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    X = jnp.asarray([[0.01, 0.99], [0.5, 0.5], [0.999, 0.001]],
                    dtype=jnp.float64)
    for kernel in ALL_KERNELS:
        u = jnp.full(g.n, 2.5, dtype=jnp.float64)
        Um = interaction.interpolate(u, g, X, centering="cell", kernel=kernel)
        np.testing.assert_allclose(np.asarray(Um), 2.5, rtol=1e-12,
                                   err_msg=kernel)


def test_velocity_interp_linear_field_exact():
    """MAC staggering honored: interpolating u=(x at x-faces, y at y-faces)
    linear fields reproduces marker coordinates (first moment), away from
    the periodic wrap."""
    g = StaggeredGrid(n=(32, 32), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    xf, yc = g.face_centers(0, jnp.float64)
    xc, yf = g.face_centers(1, jnp.float64)
    u = jnp.broadcast_to(xf, g.n)
    v = jnp.broadcast_to(yf, g.n)
    rng = np.random.default_rng(3)
    X = jnp.asarray(rng.uniform(0.2, 0.8, size=(40, 2)), dtype=jnp.float64)
    U = interaction.interpolate_vel((u, v), g, X, kernel="IB_4")
    np.testing.assert_allclose(np.asarray(U), np.asarray(X), atol=1e-12)


def test_masked_markers_contribute_nothing():
    g = StaggeredGrid(n=(16, 16), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    rng = np.random.default_rng(4)
    X = jnp.asarray(rng.uniform(0, 1, size=(10, 2)), dtype=jnp.float64)
    F = jnp.asarray(rng.standard_normal((10, 2)), dtype=jnp.float64)
    mask = jnp.asarray([1.0] * 5 + [0.0] * 5, dtype=jnp.float64)
    f_all = interaction.spread_vel(F[:5], g, X[:5], kernel="IB_4")
    f_masked = interaction.spread_vel(F, g, X, kernel="IB_4", weights=mask)
    for a, b in zip(f_all, f_masked):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-12)


def test_spread_interp_inside_jit():
    g = StaggeredGrid(n=(16, 16), x_lo=(0.0, 0.0), x_up=(1.0, 1.0))
    X = jnp.asarray([[0.3, 0.7]], dtype=jnp.float32)
    F = jnp.asarray([[1.0, -2.0]], dtype=jnp.float32)

    @jax.jit
    def roundtrip(F, X):
        f = interaction.spread_vel(F, g, X, kernel="IB_4")
        return interaction.interpolate_vel(f, g, X, kernel="IB_4")

    out = roundtrip(F, X)
    assert out.shape == (1, 2)
    assert np.isfinite(np.asarray(out)).all()
