"""Traffic robustness: admission control, deadlines, retries, the
open-loop load generator, the soak SLO gate, and the serving chaos
injectors (PR 17).

Admission-controller and SLI tests are pure host code (no jax, no
compiles). Router-level overload tests use policies that shed BEFORE
any pool exists (queue_depth=0 / deadline already spent / all builds
injected to fail), so nothing in the fast tier waits on a compile
except the one module-scoped warm pool shared with the concurrency
test. The multi-minute sustained soaks are slow-tier
(``test_soak_long_*``); CI covers the bounded variant via
``tools/slo.py check --soak`` and dryrun path 21.
"""

import json
import os
import threading
import time

import pytest

from ibamr_tpu import obs
from ibamr_tpu.serve.aot_cache import ExecutableCache
from ibamr_tpu.serve.router import (AdmissionController, BucketSpec,
                                    ScenarioRequest, TenantClassPolicy,
                                    WarmPoolRouter)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_N, _N_LAT, _N_LON = 8, 6, 8


def _req(tag, **kw):
    kw.setdefault("steps", 2)
    return ScenarioRequest(tenant=tag, n_cells=_N, n_lat=_N_LAT,
                           n_lon=_N_LON, **kw)


# ---------------------------------------------------------------------------
# admission controller (pure threading — no jax)
# ---------------------------------------------------------------------------

def test_default_policy_admits_everything_immediately():
    ac = AdmissionController()
    for _ in range(100):
        ok, wait_s, reason = ac.admit("anything")
        assert ok and reason is None
        assert wait_s == 0.0
    for _ in range(100):
        ac.release("anything")


def test_admission_sheds_queue_full():
    ac = AdmissionController(
        {"tight": TenantClassPolicy(max_inflight=1, queue_depth=0)})
    ok, _, _ = ac.admit("tight")
    assert ok
    ok, _, reason = ac.admit("tight")      # slot held, queue closed
    assert not ok and reason == "queue_full"
    ac.release("tight")
    ok, _, _ = ac.admit("tight")           # released slot admits again
    assert ok
    ac.release("tight")


def test_admission_queue_timeout_is_bounded():
    ac = AdmissionController(
        {"q": TenantClassPolicy(max_inflight=1, queue_depth=4,
                                queue_timeout_s=0.2)})
    assert ac.admit("q")[0]
    t0 = time.perf_counter()
    ok, wait_s, reason = ac.admit("q")     # nobody releases: must time out
    waited = time.perf_counter() - t0
    assert not ok and reason == "queue_timeout"
    assert 0.15 <= waited < 5.0            # bounded, never a hang
    assert wait_s > 0.0
    ac.release("q")


def test_admission_deadline_beats_queue_timeout():
    ac = AdmissionController(
        {"d": TenantClassPolicy(max_inflight=1, queue_depth=4,
                                queue_timeout_s=30.0)})
    assert ac.admit("d")[0]
    ok, _, reason = ac.admit("d", deadline_left=0.1)
    assert not ok and reason == "deadline_exceeded"
    ok, _, reason = ac.admit("d", deadline_left=-1.0)
    assert not ok and reason == "deadline_exceeded"
    ac.release("d")


def test_queued_waiter_wakes_on_release():
    ac = AdmissionController(
        {"w": TenantClassPolicy(max_inflight=1, queue_depth=4,
                                queue_timeout_s=10.0)})
    assert ac.admit("w")[0]
    got = {}

    def waiter():
        got["res"] = ac.admit("w")

    th = threading.Thread(target=waiter)
    th.start()
    time.sleep(0.1)
    ac.release("w", reclaimed=True)        # a reclaimed slot must wake it
    th.join(10.0)
    assert not th.is_alive()
    ok, wait_s, _ = got["res"]
    assert ok and wait_s < 5.0
    ac.release("w")


def test_reclaimed_release_counts():
    obs.reset_metrics()
    ac = AdmissionController(
        {"r": TenantClassPolicy(max_inflight=2)})
    ac.admit("r")
    ac.admit("r")
    ac.release("r", reclaimed=True)
    ac.release("r")
    snap = obs.metrics_snapshot()
    assert snap["counters"].get("serve_slots_reclaimed_total") == 1


# ---------------------------------------------------------------------------
# router-level shed paths (no pool ever built — fast)
# ---------------------------------------------------------------------------

def test_router_sheds_queue_full_with_terminal_record(tmp_path):
    router = WarmPoolRouter(
        [BucketSpec(n_cells=_N, n_lat=_N_LAT, n_lon=_N_LON, lanes=2)],
        cache=ExecutableCache(), allow_dynamic=False,
        policies={"none": TenantClassPolicy(max_inflight=0,
                                            queue_depth=0)})
    lp = tmp_path / "ledger.jsonl"
    with obs.ledger(str(lp)):
        res = router.serve([_req("t0", tenant_class="none")])
    assert len(res) == 1 and res[0].shed
    assert res[0].shed_reason == "queue_full"
    assert not res[0].ok and res[0].lane == -1
    recs = list(obs.read_ledger(str(lp)))
    admits = [r for r in recs if r.get("kind") == "request_admit"]
    sheds = [r for r in recs if r.get("kind") == "request_shed"]
    assert len(admits) == 1 and len(sheds) == 1
    assert sheds[0]["trace_id"] == admits[0]["trace_id"]
    assert sheds[0]["reason"] == "queue_full"
    assert sheds[0]["tenant_class"] == "none"


def test_router_sheds_spent_deadline_before_any_wait(tmp_path):
    router = WarmPoolRouter(
        [BucketSpec(n_cells=_N, n_lat=_N_LAT, n_lon=_N_LON, lanes=2)],
        cache=ExecutableCache(), allow_dynamic=False)
    lp = tmp_path / "ledger.jsonl"
    with obs.ledger(str(lp)):
        res = router.serve([_req("t0", deadline_s=0.0)])
    assert res[0].shed and res[0].shed_reason == "deadline_exceeded"
    sheds = [r for r in obs.read_ledger(str(lp))
             if r.get("kind") == "request_shed"]
    assert sheds and sheds[0]["reason"] == "deadline_exceeded"


def test_failing_builds_exhaust_retry_budget_and_shed(tmp_path):
    from tools.fault_injection import failing_build_injector

    router = WarmPoolRouter(
        [BucketSpec(n_cells=_N, n_lat=_N_LAT, n_lon=_N_LON, lanes=2)],
        cache=ExecutableCache(), allow_dynamic=False,
        policies={"retry": TenantClassPolicy(retry_budget=2,
                                             backoff_base_s=0.01,
                                             backoff_cap_s=0.02)})
    lp = tmp_path / "ledger.jsonl"
    with obs.ledger(str(lp)), failing_build_injector(n_failures=99):
        res = router.serve([_req("t0", tenant_class="retry")])
    assert res[0].shed and res[0].shed_reason == "build_failed"
    assert res[0].retries == 2                 # the whole budget spent
    assert "injected build failure" in res[0].error
    recs = list(obs.read_ledger(str(lp)))
    retries = [r for r in recs if r.get("kind") == "request_retry"]
    assert [r["attempt"] for r in retries] == [1, 2]
    assert all(r["reason"] == "build_failed" for r in retries)
    assert all(r["backoff_s"] > 0 for r in retries)
    sheds = [r for r in recs if r.get("kind") == "request_shed"]
    assert len(sheds) == 1 and sheds[0]["retries"] == 2


def test_shed_slot_is_reclaimed_for_the_next_waiter():
    from tools.fault_injection import failing_build_injector

    obs.reset_metrics()
    router = WarmPoolRouter(
        [BucketSpec(n_cells=_N, n_lat=_N_LAT, n_lon=_N_LON, lanes=2)],
        cache=ExecutableCache(), allow_dynamic=False,
        policies={"one": TenantClassPolicy(max_inflight=1,
                                           queue_depth=4,
                                           queue_timeout_s=20.0)})
    results = []
    lock = threading.Lock()

    def submit():
        out = router.serve([_req("t", tenant_class="one")])
        with lock:
            results.extend(out)

    with failing_build_injector(n_failures=99):
        threads = [threading.Thread(target=submit) for _ in range(3)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(60.0)
    assert not any(th.is_alive() for th in threads)
    # all three were ADMITTED (never queue_full/queue_timeout): each
    # build_failed shed handed its slot to the next waiter
    assert len(results) == 3
    assert all(r.shed and r.shed_reason == "build_failed"
               for r in results)
    snap = obs.metrics_snapshot()
    assert snap["counters"].get("serve_slots_reclaimed_total", 0) >= 2


def test_backoff_is_deterministic_and_capped():
    pol = TenantClassPolicy(backoff_base_s=0.1, backoff_cap_s=0.4)
    tid = "deadbeef00000000"
    b1 = WarmPoolRouter._backoff_s(pol, 1, tid)
    assert b1 == WarmPoolRouter._backoff_s(pol, 1, tid)  # no RNG
    assert 0.05 <= b1 <= 0.1
    b9 = WarmPoolRouter._backoff_s(pol, 9, tid)
    assert b9 <= 0.4                                      # capped
    assert WarmPoolRouter._backoff_s(pol, 1, "00000000") \
        != WarmPoolRouter._backoff_s(pol, 1, "ffffffff0")


# ---------------------------------------------------------------------------
# load generator (schedule math only — no jax)
# ---------------------------------------------------------------------------

def test_poisson_burst_schedule_is_deterministic():
    from ibamr_tpu.serve.loadgen import poisson_burst_schedule

    a = poisson_burst_schedule(seed=3, duration_s=10.0, rate_rps=5.0)
    b = poisson_burst_schedule(seed=3, duration_s=10.0, rate_rps=5.0)
    assert [(x.t, x.scenario, x.request.tenant) for x in a] \
        == [(x.t, x.scenario, x.request.tenant) for x in b]
    c = poisson_burst_schedule(seed=4, duration_s=10.0, rate_rps=5.0)
    assert [x.t for x in a] != [x.t for x in c]
    assert all(0.0 <= x.t < 10.0 for x in a)
    assert [x.t for x in a] == sorted(x.t for x in a)


def test_burst_window_multiplies_the_rate():
    from ibamr_tpu.serve.loadgen import poisson_burst_schedule

    arr = poisson_burst_schedule(seed=0, duration_s=100.0,
                                 rate_rps=2.0, burst_factor=4.0,
                                 burst_start_frac=0.4,
                                 burst_len_frac=0.3)
    in_burst = [x for x in arr if 40.0 <= x.t < 70.0]
    outside = [x for x in arr if not (40.0 <= x.t < 70.0)]
    rate_in = len(in_burst) / 30.0
    rate_out = len(outside) / 70.0
    assert rate_in > 2.0 * rate_out        # 4x nominal, 2x with noise


def test_scenario_mix_is_heavy_tailed_with_both_classes():
    from ibamr_tpu.serve.loadgen import (SCENARIO_MIX,
                                         poisson_burst_schedule)

    assert abs(sum(s.weight for s in SCENARIO_MIX) - 1.0) < 1e-9
    classes = {s.tenant_class for s in SCENARIO_MIX}
    assert classes == {"interactive", "batch"}
    # heavy tail: the largest service demand dominates the smallest
    steps = sorted(s.steps for s in SCENARIO_MIX)
    assert steps[-1] >= 4 * steps[0]
    arr = poisson_burst_schedule(seed=1, duration_s=60.0, rate_rps=5.0)
    # one family only — a bounded soak pays exactly one bucket compile
    assert len({x.request.family() for x in arr}) == 1
    by_class = {}
    for x in arr:
        by_class[x.request.tenant_class] = \
            by_class.get(x.request.tenant_class, 0) + 1
    assert by_class["interactive"] > by_class["batch"] > 0


def test_open_loop_counts_results_and_errors():
    from ibamr_tpu.serve.loadgen import (Arrival, run_open_loop,
                                         traffic_summary)

    class FakeResult:
        def __init__(self, tenant, shed=False):
            self.tenant = tenant
            self.shed = shed
            self.shed_reason = "queue_full" if shed else None
            self.ok = not shed
            self.quarantined = False
            self.retries = 0
            self.queue_wait_s = 0.01
            self.cold = False
            self.first_step_s = 0.02

    class FakeRouter:
        def __init__(self):
            self.n = 0
            self.lock = threading.Lock()

        def serve(self, reqs):
            with self.lock:
                self.n += 1
                k = self.n
            if k == 3:
                raise RuntimeError("boom")
            return [FakeResult(r.tenant, shed=(k % 4 == 0))
                    for r in reqs]

    arrivals = [Arrival(t=i * 0.01, scenario="s",
                        request=_req(f"interactive-{i}",
                                     tenant_class="interactive"))
                for i in range(8)]
    run = run_open_loop(FakeRouter(), arrivals, time_scale=0.1,
                        join_timeout_s=30.0)
    assert run["hung_threads"] == 0
    assert len(run["errors"]) == 1 and "boom" in run["errors"][0]
    assert len(run["results"]) == 7
    summary = traffic_summary(run["results"], run["wall_s"])
    assert summary["submitted"] == 7
    assert summary["shed"] == summary["shed_by_reason"].get(
        "queue_full", 0)
    assert "interactive" in summary["classes"]


# ---------------------------------------------------------------------------
# soak SLIs + the --soak gate (synthetic ledgers — no jax)
# ---------------------------------------------------------------------------

def _soak_ledger(tmp_path, lost=0, shed=2, served=8):
    recs = []
    seq = 0
    for i in range(served + shed + lost):
        seq += 1
        recs.append({"seq": seq, "kind": "request_admit",
                     "trace_id": f"{i:016x}", "tenant": "t",
                     "tenant_class": "interactive", "t": 0.0})
    for i in range(served):
        seq += 1
        recs.append({"seq": seq, "kind": "request",
                     "trace_id": f"{i:016x}", "tenant": "t",
                     "tenant_class": "interactive", "cold": False,
                     "ok": True, "quarantined": False,
                     "first_step_s": 0.01 * (i + 1),
                     "queue_wait_s": 0.005 * i, "t": 1.0})
    for i in range(served, served + shed):
        seq += 1
        recs.append({"seq": seq, "kind": "request_shed",
                     "trace_id": f"{i:016x}", "tenant": "t",
                     "tenant_class": "interactive",
                     "reason": "queue_full", "queue_wait_s": 0.5,
                     "retries": 0, "t": 1.0})
    lp = tmp_path / "soak_ledger.jsonl"
    with open(lp, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    return str(lp)


def test_soak_slis_from_ledger(tmp_path):
    from tools.slo import soak_slis_from_ledger

    path = _soak_ledger(tmp_path, lost=1, shed=2, served=8)
    slis = soak_slis_from_ledger(list(obs.read_ledger(path)))
    assert slis["soak_lost_requests"] == 1
    assert slis["soak_shed_rate"] == pytest.approx(2 / 10)
    assert slis["soak_warm_p99_s"] == pytest.approx(0.08)
    assert slis["soak_queue_wait_p99_s"] == pytest.approx(0.5)


def test_slo_check_soak_exit_codes(tmp_path, capsys):
    from tools.slo import main as slo_main

    clean = _soak_ledger(tmp_path, lost=0, shed=0, served=10)
    (tmp_path / "b").mkdir()
    lossy = _soak_ledger(tmp_path / "b", lost=2, shed=0, served=10)
    contract = tmp_path / "SLO.json"
    contract.write_text(json.dumps({
        "slo_schema": 1, "slos": {},
        "soak_slos": {
            "soak_lost_requests": {"ceiling": 0},
            "soak_shed_rate": {"ceiling": 0.2},
            "soak_warm_p99_s": {"ceiling": 2.0},
            "soak_queue_wait_p99_s": {"ceiling": 2.0}}}))
    rc = slo_main(["check", "--soak", "--ledger", clean,
                   "--contract", str(contract), "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and not out["violated"]
    # lost requests violate the zero-ceiling -> exit 2
    rc = slo_main(["check", "--soak", "--ledger", lossy,
                   "--contract", str(contract), "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 2
    assert any("soak_lost_requests" in v for v in out["violated"])
    # a contract without soak_slos is unevaluable -> exit 1
    bare = tmp_path / "bare.json"
    bare.write_text(json.dumps({"slo_schema": 1, "slos": {}}))
    rc = slo_main(["check", "--soak", "--ledger", clean,
                   "--contract", str(bare), "--json"])
    assert rc == 1
    capsys.readouterr()


def test_slo_soak_tighten_preserves_existing_slos(tmp_path, capsys):
    from tools.slo import main as slo_main

    clean = _soak_ledger(tmp_path, lost=0, shed=1, served=9)
    contract = tmp_path / "SLO.json"
    contract.write_text(json.dumps({
        "slo_schema": 1,
        "slos": {"warm_first_step_p99_s": {"ceiling": 2.0}}}))
    rc = slo_main(["check", "--soak", "--ledger", clean,
                   "--contract", str(contract), "--tighten"])
    capsys.readouterr()
    assert rc == 0
    doc = json.loads(contract.read_text())
    # the cold/warm section survives the soak merge untouched
    assert doc["slos"] == {"warm_first_step_p99_s": {"ceiling": 2.0}}
    assert doc["soak_slos"]["soak_lost_requests"] == {"ceiling": 0}
    assert doc["soak_slos"]["soak_shed_rate"]["ceiling"] \
        == pytest.approx(0.3)


def test_committed_contract_has_soak_slos():
    with open(os.path.join(REPO, "SLO.json")) as f:
        doc = json.load(f)
    assert doc["soak_slos"]["soak_lost_requests"] == {"ceiling": 0}
    assert set(doc["soak_slos"]) >= {"soak_warm_p99_s",
                                     "soak_queue_wait_p99_s",
                                     "soak_shed_rate",
                                     "soak_lost_requests"}
    assert doc["soak"]["burst_factor"] == 4.0


# ---------------------------------------------------------------------------
# rendering: the traffic block, tail lines, trace hops
# ---------------------------------------------------------------------------

def test_render_traffic_block_and_absence():
    from tools.obs import render_traffic

    # no admission activity -> no block (plain runs keep their shape)
    assert render_traffic({}, []) == []
    recs = [
        {"seq": 1, "kind": "request_admit", "trace_id": "a" * 16,
         "tenant": "t", "tenant_class": "interactive"},
        {"seq": 2, "kind": "request_retry", "trace_id": "a" * 16,
         "tenant": "t", "tenant_class": "interactive", "attempt": 1,
         "reason": "build_failed", "backoff_s": 0.03},
        {"seq": 3, "kind": "request_shed", "trace_id": "a" * 16,
         "tenant": "t", "tenant_class": "interactive",
         "reason": "deadline_exceeded", "queue_wait_s": 0.4,
         "retries": 1},
    ]
    lines = render_traffic({}, recs)
    text = "\n".join(lines)
    assert "deadline_exceeded=1" in text
    assert "retries: 1 (build_failed=1)" in text
    assert "class interactive" in text and "shed=1" in text
    # labeled counters win over record recounts when snapshotted
    snap = {"counters": {
        'serve_shed_total{reason="queue_full"}': 7,
        "serve_slots_reclaimed_total": 3}}
    text = "\n".join(render_traffic(snap, recs))
    assert "queue_full=7" in text
    assert "reclaimed: 3" in text


def test_tail_and_trace_render_shed_and_retry():
    from tools.obs import _one_line, render_trace

    shed = {"seq": 5, "kind": "request_shed", "trace_id": "b" * 16,
            "tenant": "t", "tenant_class": "chaos",
            "reason": "queue_full", "queue_wait_s": 0.2, "retries": 0,
            "t": 1.0}
    retry = {"seq": 4, "kind": "request_retry", "trace_id": "b" * 16,
             "tenant": "t", "tenant_class": "chaos", "attempt": 1,
             "reason": "lane_quarantined", "backoff_s": 0.05, "t": 0.5}
    assert "reason=queue_full" in _one_line(shed)
    assert "attempt=1" in _one_line(retry)
    admit = {"seq": 1, "kind": "request_admit", "trace_id": "b" * 16,
             "tenant": "t", "tenant_class": "chaos", "steps": 2,
             "t": 0.0, "run_id": "r"}
    lines = render_trace([admit, retry, shed], "b" * 16)
    text = "\n".join(lines)
    assert "retry #1" in text and "lane_quarantined" in text
    assert "SHED" in text and "queue_full" in text
    assert "verdict: shed (queue_full)" in text


def test_trace_completed_line_carries_queue_wait_and_retries():
    from tools.obs import render_trace

    done = {"seq": 2, "kind": "request", "trace_id": "c" * 16,
            "tenant": "t", "cold": False, "ok": True,
            "quarantined": False, "lane": 0, "first_step_s": 0.01,
            "total_s": 0.05, "queue_wait_s": 0.3, "retries": 2,
            "t": 1.0, "run_id": "r"}
    text = "\n".join(render_trace([done], "c" * 16))
    assert "queue_wait=" in text and "retries=2" in text
    assert "verdict: ok" in text


def test_watchdog_heartbeat_carries_queue_and_shed_gauges(tmp_path):
    from ibamr_tpu.utils.watchdog import RunWatchdog, read_heartbeat

    obs.reset_metrics()
    hb = str(tmp_path / "heartbeat.json")
    wd = RunWatchdog(heartbeat_path=hb)
    if obs.peek_gauge("serve_requests_queued") is None:
        wd.beat(step=1)
        payload = read_heartbeat(hb)
        # solo schema untouched: no traffic keys without the gauges
        assert "requests_queued" not in payload
        assert "requests_shed" not in payload
    obs.gauge("serve_requests_queued").set(3)
    obs.gauge("serve_requests_shed").set(5)
    wd.beat(step=2)
    payload = read_heartbeat(hb)
    assert payload["requests_queued"] == 3
    assert payload["requests_shed"] == 5
    obs.reset_metrics()


# ---------------------------------------------------------------------------
# genuine thread concurrency + chaos (one shared warm pool)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def traffic_router():
    spec = BucketSpec(n_cells=_N, n_lat=_N_LAT, n_lon=_N_LON, lanes=2)
    router = WarmPoolRouter(
        [spec], cache=ExecutableCache(), allow_dynamic=True,
        policies={
            "interactive": TenantClassPolicy(
                max_inflight=4, queue_depth=16, queue_timeout_s=30.0,
                deadline_s=60.0, retry_budget=1,
                backoff_base_s=0.01, backoff_cap_s=0.05),
            "batch": TenantClassPolicy(
                max_inflight=2, queue_depth=8, queue_timeout_s=30.0,
                deadline_s=60.0, retry_budget=1,
                backoff_base_s=0.01, backoff_cap_s=0.05),
            "chaos": TenantClassPolicy(
                max_inflight=2, queue_depth=2, queue_timeout_s=1.0,
                deadline_s=2.0, retry_budget=1,
                backoff_base_s=0.01, backoff_cap_s=0.05)})
    router.warm(spec)
    return router, spec


def test_no_lost_request_under_concurrent_chaos(traffic_router,
                                                tmp_path):
    """N producer threads, mixed classes, chaos injectors firing: the
    merged ledger must show EXACTLY one terminal record per admitted
    trace_id, and no producer may hang."""
    from tools.fault_injection import (failing_build_injector,
                                       kill_router_thread_injector)

    router, _ = traffic_router
    lp = tmp_path / "ledger.jsonl"
    results = []
    lock = threading.Lock()

    def producer(i):
        # chaos producers land on a NOVEL family (n_lon=10): its
        # builds get killed/failed by the injectors; healthy
        # producers ride the warm pool
        if i % 4 == 3:
            req = ScenarioRequest(tenant=f"chaos-{i}", n_cells=_N,
                                  n_lat=_N_LAT, n_lon=10, steps=1,
                                  tenant_class="chaos")
        else:
            cls = "batch" if i % 4 == 2 else "interactive"
            req = _req(f"{cls}-{i}", steps=1, tenant_class=cls)
        out = router.serve([req])
        with lock:
            results.extend(out)

    with obs.ledger(str(lp)):
        # every novel-family build dies or raises: kill first, then
        # injected failures — no real compile in this test
        with kill_router_thread_injector(n_kills=1), \
                failing_build_injector(n_failures=99):
            threads = [threading.Thread(target=producer, args=(i,))
                       for i in range(12)]
            for th in threads:
                th.start()
            for th in threads:
                th.join(120.0)
        hung = sum(1 for th in threads if th.is_alive())
        assert hung == 0, f"{hung} producers hung under chaos"

    assert len(results) == 12
    recs = list(obs.read_ledger(str(lp)))
    admits = [r["trace_id"] for r in recs
              if r.get("kind") == "request_admit"]
    assert len(admits) == 12
    terminals = {}
    for r in recs:
        if r.get("kind") in ("request", "request_shed"):
            terminals[r["trace_id"]] = \
                terminals.get(r["trace_id"], 0) + 1
    assert all(terminals.get(t, 0) == 1 for t in admits), \
        f"lost/doubled: { {t: terminals.get(t, 0) for t in admits} }"
    # healthy classes completed; chaos requests shed (their builds
    # were all killed or raised) — capacity isolation held
    chaos = [r for r in results if r.tenant.startswith("chaos")]
    healthy = [r for r in results if not r.tenant.startswith("chaos")]
    assert all(r.shed and r.shed_reason == "build_failed"
               for r in chaos)
    assert all(not r.shed and r.ok for r in healthy)


def test_killed_build_thread_fails_over_not_hangs(traffic_router):
    """A build thread that dies without publishing must fail over to
    a retryable error inside the sliced wait — never a hang."""
    from tools.fault_injection import (failing_build_injector,
                                       kill_router_thread_injector)

    router, _ = traffic_router
    req = ScenarioRequest(tenant="chaos-k", n_cells=_N, n_lat=_N_LAT,
                          n_lon=12, steps=1, tenant_class="chaos")
    t0 = time.perf_counter()
    with kill_router_thread_injector(n_kills=1), \
            failing_build_injector(n_failures=99):
        res = router.serve([req])
    assert time.perf_counter() - t0 < 60.0
    assert res[0].shed and res[0].shed_reason == "build_failed"
    assert res[0].retries >= 1                  # the failover retried


def test_warm_traffic_unchanged_by_admission_layer(traffic_router):
    """Default-policy classes on the warm family keep the original
    zero-compile contract: admission is free when capacity exists."""
    router, _ = traffic_router
    before = router.cache.stats()
    res = router.serve([_req(f"t{i}", steps=1,
                             tenant_class="interactive")
                        for i in range(4)])
    after = router.cache.stats()
    assert all(not r.shed and r.ok and not r.cold for r in res)
    assert all(r.queue_wait_s < 30.0 for r in res)
    assert after["misses"] == before["misses"]  # zero compiles warm


# ---------------------------------------------------------------------------
# sustained soaks (slow tier — conftest SLOW_TESTS)
# ---------------------------------------------------------------------------

def test_soak_long_sustained_open_loop():
    """Multi-minute clean soak: sustained arrivals, zero loss, zero
    hung threads, shed rate inside the committed ceiling."""
    from ibamr_tpu.serve.loadgen import soak_drill

    out = soak_drill(seed=1, duration_s=120.0, rate_rps=6.0,
                     time_scale=1.0)
    assert out["hung_threads"] == 0
    assert out["submitted"] == out["completed"] + out["shed"]
    assert (out["shed_rate"] or 0.0) <= 0.2
    assert out["warm_first_step_p99_s"] is not None


def test_soak_long_chaos_smoke():
    """The full chaos drill at a longer horizon (the tier-1 variant
    runs bounded inside `slo.py check --soak` and dryrun path 21)."""
    from tools.fault_injection import run_soak_smoke

    out = run_soak_smoke(duration_s=60.0, rate_rps=8.0,
                         time_scale=1.0)
    assert out["soak_smoke"] == "ok"
    assert out["lost"] == 0 and out["hung_threads"] == 0
