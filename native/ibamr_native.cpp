// Native runtime kernels for ibamr_tpu (host side).
//
// Reference parity: the reference's runtime around the compute path is
// C++ (SURVEY.md §2.5 — IBStandardInitializer file parsing, SILO/VisIt
// writers, Streamable packing). The TPU compute path is JAX/XLA; this
// library is the native equivalent of the reference's HOST runtime:
//
//  * parse_table:   whitespace/comment-tolerant numeric table parser —
//                   the hot loop of .vertex/.spring/.beam/.target
//                   reading (P10). ~30-60x faster than the Python
//                   tokenizer on multi-million-line structure files.
//  * encode_base64: VTK appended-binary payload encoder (T15
//                   replacement's binary mode).
//
// Exposed with a plain C ABI for ctypes (no pybind11 in the image).
// Build: g++ -O3 -march=native -shared -fPIC ibamr_native.cpp -o ...

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

extern "C" {

// Parse up to max_rows rows of whitespace-separated doubles from a
// text buffer. '#' and '//' start comments running to end of line.
// Rows are newline-delimited; columns beyond max_cols are counted in
// ncols_out (true per-row column count) but not stored; short rows are
// padded with NaN. STRICT tokens (matching the Python parser): a token
// must be entirely consumed by strtod and must not be a hex literal —
// otherwise parsing stops and *status reports the offending line
// (1-based). Returns the number of rows parsed; *status == 0 on
// success.
long parse_table(const char* buf, long len, double* out, long max_rows,
                 long max_cols, int* ncols_out, long* status) {
    const char* p = buf;
    const char* end = buf + len;
    long row = 0;
    long line_no = 1;
    *status = 0;
    while (p < end && row < max_rows) {
        long col = 0;
        while (p < end && *p != '\n') {
            while (p < end && (*p == ' ' || *p == '\t' || *p == '\r'))
                ++p;
            if (p >= end || *p == '\n') break;
            if (*p == '#' || (*p == '/' && p + 1 < end && p[1] == '/')) {
                while (p < end && *p != '\n') ++p;
                break;
            }
            // token extent: up to whitespace / comment / EOL
            const char* q = p;
            while (q < end && *q != ' ' && *q != '\t' && *q != '\n'
                   && *q != '\r' && *q != '#')
                ++q;
            bool hex = false;
            for (const char* c = p; c < q; ++c)
                if (*c == 'x' || *c == 'X') hex = true;
            char* next = nullptr;
            double v = strtod(p, &next);
            if (next != q || hex) {     // partial/invalid token: error
                *status = line_no;
                return row;
            }
            if (col < max_cols) out[row * max_cols + col] = v;
            ++col;
            p = next;
        }
        if (p < end && *p == '\n') {
            ++p;
            ++line_no;
        }
        if (col > 0) {
            for (long c = col; c < max_cols; ++c)
                out[row * max_cols + c] = __builtin_nan("");
            ncols_out[row] = (int)col;  // TRUE count (may exceed max)
            ++row;
        }
    }
    return row;
}

// Standard base64 (RFC 4648) of a binary buffer; returns encoded size.
// out must hold 4 * ((n + 2) / 3) bytes.
long encode_base64(const uint8_t* in, long n, char* out) {
    static const char tab[] =
        "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";
    long o = 0;
    long i = 0;
    for (; i + 2 < n; i += 3) {
        uint32_t v = (in[i] << 16) | (in[i + 1] << 8) | in[i + 2];
        out[o++] = tab[(v >> 18) & 63];
        out[o++] = tab[(v >> 12) & 63];
        out[o++] = tab[(v >> 6) & 63];
        out[o++] = tab[v & 63];
    }
    if (i < n) {
        uint32_t v = in[i] << 16;
        int rem = (int)(n - i);
        if (rem == 2) v |= in[i + 1] << 8;
        out[o++] = tab[(v >> 18) & 63];
        out[o++] = tab[(v >> 12) & 63];
        out[o++] = rem == 2 ? tab[(v >> 6) & 63] : '=';
        out[o++] = '=';
    }
    return o;
}

int ibamr_native_abi_version() { return 2; }

}  // extern "C"
