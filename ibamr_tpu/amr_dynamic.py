"""Dynamic AMR: tagging -> box fitting -> regrid, all inside jit.

Reference parity: the regrid pipeline of SURVEY.md §3.4 —
``StandardTagAndInitialize`` tagging callbacks, ``BergerRigoutsos`` box
clustering, and data transfer old->new (T10 refine/coarsen ops,
``CartCellDoubleQuadraticRefine`` / conservative-linear refine /
``CartCellDoubleCubicCoarsen``), specialized to one fine level.

TPU-first redesign (SURVEY.md §7.1 pillar 1 + §7.3 hard-part #3): the
reference reclusters dynamic patch lists with MPI; here the fine level is
a FIXED-SHAPE dense window whose ORIGIN is data. Regrid changes array
*contents*, never shapes:

- tagging produces a boolean coarse-cell array (gradient / value /
  marker-count criteria — the INS vorticity + IBMethod marker tagging
  analogs);
- "box fitting" reduces tags to a clipped window origin (index min/max
  reductions — the Berger-Rigoutsos role for a single box);
- data transfer is `lax.dynamic_slice` / `dynamic_update_slice` +
  `jnp.roll` by the traced origin shift: coarse synchronized by
  conservative restriction under the OLD window, the NEW window filled by
  conservative-linear prolongation, and surviving fine data copied across
  the overlap.

Everything is a pure function of (state, origin) with static shapes, so
the whole tag->fit->regrid->advance cycle compiles ONCE and the window
tracks the solution with no host round-trip and no recompilation — the
property the reference's regrid pipeline fundamentally cannot have.

Conservative-linear prolongation (the reference's
CONSERVATIVE_LINEAR_REFINE): per-axis central-slope subcell
reconstruction at offsets +-1/4 — each 2^dim fine block averages exactly
to its parent value, so regrid preserves the composite integral to
roundoff (enforced by tests).
"""

from __future__ import annotations

import functools
from typing import Callable, NamedTuple, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ibamr_tpu.amr import interp_periodic, restrict_cc
from ibamr_tpu.grid import StaggeredGrid

Vel = Tuple[jnp.ndarray, ...]


# --------------------------------------------------------------------------
# Tagging (StandardTagAndInitialize callbacks analog)
# --------------------------------------------------------------------------

def tag_value(Q: jnp.ndarray, threshold: float) -> jnp.ndarray:
    """Tag cells where |Q| exceeds ``threshold``."""
    return jnp.abs(Q) > threshold


def tag_gradient(Q: jnp.ndarray, grid: StaggeredGrid,
                 threshold: float) -> jnp.ndarray:
    """Tag cells with large undivided gradient (the vorticity-magnitude
    tagging analog of ``INSStaggeredHierarchyIntegrator``)."""
    mag = jnp.zeros_like(Q)
    for d in range(Q.ndim):
        mag = mag + jnp.abs(jnp.roll(Q, -1, d) - jnp.roll(Q, 1, d))
    return mag > threshold


def tag_markers(X: jnp.ndarray, grid: StaggeredGrid,
                mask: Optional[jnp.ndarray] = None,
                buffer: int = 1) -> jnp.ndarray:
    """Tag cells containing Lagrangian markers, dilated by ``buffer``
    cells (the ``IBMethod`` marker-cell tagging analog)."""
    idx = []
    for d in range(grid.dim):
        i = jnp.floor((X[:, d] - grid.x_lo[d]) / grid.dx[d]).astype(jnp.int32)
        idx.append(jnp.mod(i, grid.n[d]))
    lin = idx[0]
    for d in range(1, grid.dim):
        lin = lin * grid.n[d] + idx[d]
    w = jnp.ones(X.shape[0]) if mask is None else mask
    counts = jnp.zeros(int(np.prod(grid.n))).at[lin].add(w)
    tags = counts.reshape(grid.n) > 0
    for _ in range(buffer):
        grown = tags
        for d in range(grid.dim):
            grown = grown | jnp.roll(tags, 1, d) | jnp.roll(tags, -1, d)
        tags = grown
    return tags


def fit_box_origin(tags: jnp.ndarray, box_shape: Tuple[int, ...],
                   clearance: int = 2) -> jnp.ndarray:
    """Window origin (coarse cells, (dim,) int32) centering the tagged
    region, clipped so the fixed-shape window keeps ``clearance`` cells
    from every domain edge. With no tags, centers the domain. The
    single-box Berger-Rigoutsos replacement.

    The per-axis center is the CIRCULAR mean of the tagged indices, so a
    tagged blob straddling the periodic boundary still centers correctly
    (a linear min/max midpoint would jump to the middle of the domain);
    the window itself never wraps — the clearance clip places it flush
    against the edge nearest the feature in that case.
    """
    dim = tags.ndim
    los = []
    for d in range(dim):
        axes = tuple(a for a in range(dim) if a != d)
        line = jnp.any(tags, axis=axes)
        n = line.shape[0]
        any_tag = jnp.any(line)
        th = 2.0 * np.pi * jnp.arange(n, dtype=jnp.float32) / n
        cs = jnp.sum(jnp.where(line, jnp.cos(th), 0.0))
        sn = jnp.sum(jnp.where(line, jnp.sin(th), 0.0))
        center = jnp.mod(jnp.arctan2(sn, cs) / (2.0 * np.pi) * n + 0.5, n)
        center = jnp.where(any_tag, center, n / 2.0)
        lo = jnp.round(center - box_shape[d] / 2.0).astype(jnp.int32)
        lo = jnp.clip(lo, clearance, n - box_shape[d] - clearance)
        los.append(lo)
    return jnp.stack(los)


# --------------------------------------------------------------------------
# Dynamic-origin transfer operators
# --------------------------------------------------------------------------

def prolong_cc_conservative(coarse: jnp.ndarray, lo: jnp.ndarray,
                            box_shape: Tuple[int, ...],
                            ratio: int = 2) -> jnp.ndarray:
    """Conservative-linear prolongation of the window [lo, lo+shape) to
    fine cells: per-axis central slopes, subcell offsets -1/4,+1/4 — each
    fine block block-averages exactly to its parent (conservation)."""
    dim = coarse.ndim
    # slice the window with a 1-cell halo (window clearance >= 1 from the
    # domain edge keeps this in-bounds without wrapping), then refine
    # axis-by-axis, consuming each axis's halo when its turn comes. Each
    # per-axis +-1/4 pair averages to its input value, so conservation
    # holds regardless of the slopes used.
    halo_lo = lo - 1
    arr = lax.dynamic_slice(coarse, tuple(halo_lo),
                            tuple(s + 2 for s in box_shape))
    for d in range(dim):
        nd = arr.ndim
        sl_m = [slice(None)] * nd
        sl_c = [slice(None)] * nd
        sl_p = [slice(None)] * nd
        sl_m[d] = slice(0, -2)
        sl_c[d] = slice(1, -1)
        sl_p[d] = slice(2, None)
        slope = 0.5 * (arr[tuple(sl_p)] - arr[tuple(sl_m)])
        c = arr[tuple(sl_c)]
        arr = jnp.stack([c - 0.25 * slope, c + 0.25 * slope], axis=d + 1)
        arr = arr.reshape(arr.shape[:d] + (2 * c.shape[d],)
                          + arr.shape[d + 2:])
    assert ratio == 2
    return arr


def restrict_into_coarse(Qc: jnp.ndarray, Qf: jnp.ndarray,
                         lo: jnp.ndarray, ratio: int = 2) -> jnp.ndarray:
    """Write the block-mean restriction of the fine window into the
    coarse array at origin ``lo`` (conservative synchronization)."""
    return lax.dynamic_update_slice(Qc, restrict_cc(Qf, ratio), tuple(lo))


def copy_overlap(Qf_new: jnp.ndarray, Qf_old: jnp.ndarray,
                 lo_new: jnp.ndarray, lo_old: jnp.ndarray,
                 ratio: int = 2) -> jnp.ndarray:
    """Replace prolonged values by surviving old fine data wherever the
    old and new windows overlap: roll the old window by the origin shift
    and mask to the overlap region."""
    dim = Qf_new.ndim
    shift = (lo_old - lo_new) * ratio            # (dim,) traced
    rolled = Qf_old
    for d in range(dim):
        rolled = jnp.roll(rolled, shift[d], axis=d)
    mask = jnp.ones_like(Qf_new, dtype=bool)
    for d in range(dim):
        nf = Qf_new.shape[d]
        i = jnp.arange(nf)
        ok = (i >= shift[d]) & (i < nf + shift[d])   # valid old indices
        shape = [1] * dim
        shape[d] = nf
        mask = mask & ok.reshape(shape)
    return jnp.where(mask, rolled, Qf_new)


def regrid(Qc: jnp.ndarray, Qf: jnp.ndarray, lo_old: jnp.ndarray,
           lo_new: jnp.ndarray, ratio: int = 2
           ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Move the fine window: sync coarse under the old window, prolong
    the new window, keep surviving fine data on the overlap. Conserves
    the composite integral to roundoff."""
    Qc = restrict_into_coarse(Qc, Qf, lo_old, ratio)
    box_shape = tuple(s // ratio for s in Qf.shape)
    Qf_new = prolong_cc_conservative(Qc, lo_new, box_shape, ratio)
    Qf_new = copy_overlap(Qf_new, Qf, lo_new, lo_old, ratio)
    return Qc, Qf_new


# --------------------------------------------------------------------------
# Dynamic-origin ghost fill (quadratic CF interpolation, traced origin)
# --------------------------------------------------------------------------

@functools.lru_cache(maxsize=32)
def _rel_ghost_coords(fine_shape: Tuple[int, ...], ghost: int, ratio: int,
                      dtype_name: str):
    """Origin-relative coarse index coordinates of the ghost-padded fine
    cell centers, per onion slab (static; origin added traced)."""
    dim = len(fine_shape)
    g = ghost
    slabs = []
    for d in range(dim):
        for side in (0, 1):
            rng = []
            for a in range(dim):
                if a < d:
                    rng.append((g, g + fine_shape[a]))
                elif a == d:
                    rng.append((0, g) if side == 0
                               else (fine_shape[a] + g, fine_shape[a] + 2 * g))
                else:
                    rng.append((0, fine_shape[a] + 2 * g))
            axes = [np.arange(lo_i - g, hi_i - g,
                              dtype=np.dtype(dtype_name))
                    for (lo_i, hi_i) in rng]
            # fine index i -> origin-relative coarse coord (i+0.5)/r - 0.5
            axes = [(ax + 0.5) / ratio - 0.5 for ax in axes]
            # cache plain NumPy (jnp arrays here would leak tracers
            # across jit traces via the lru_cache)
            pts = np.stack(np.meshgrid(*axes, indexing="ij"), axis=-1)
            sl = tuple(slice(lo_i, hi_i) for lo_i, hi_i in rng)
            slabs.append((sl, pts))
    return tuple(slabs)


def fill_fine_ghosts_dyn(fine: jnp.ndarray, coarse: jnp.ndarray,
                         lo: jnp.ndarray, ghost: int,
                         ratio: int = 2) -> jnp.ndarray:
    """Ghost-padded fine array with quadratic CF interpolation from the
    periodic coarse level; window origin is traced data."""
    g = ghost
    nf = fine.shape
    out = jnp.zeros(tuple(n + 2 * g for n in nf), dtype=fine.dtype)
    inner = tuple(slice(g, g + n) for n in nf)
    out = out.at[inner].set(fine)
    lo_f = lo.astype(coarse.dtype)
    for sl, pts in _rel_ghost_coords(nf, ghost, ratio, coarse.dtype.name):
        out = out.at[sl].set(interp_periodic(
            coarse, jnp.asarray(pts) + lo_f, order=2))
    return out


# --------------------------------------------------------------------------
# Moving-window two-level advection-diffusion integrator
# --------------------------------------------------------------------------

class AMRState(NamedTuple):
    Qc: jnp.ndarray      # coarse level (periodic)
    Qf: jnp.ndarray      # fine window (fixed shape)
    lo: jnp.ndarray      # (dim,) int32 window origin in coarse cells


class DynamicTwoLevelAdvDiff:
    """Two-level advance of dQ/dt + div(uQ) = kappa lap(Q) whose fine
    window follows the solution.

    The reference's dynamic-AMR loop (§3.4) under the static-shape
    discipline: ``advance(state, dt, n, regrid_interval)`` runs the whole
    subcycled composite advance + tag/fit/regrid cycle in ONE lax.scan.
    ``u_fn(coords, d)`` supplies the face-normal velocity at arbitrary
    coordinates (evaluated on the moving window each substep);
    alternatively fixed per-level velocity ARRAYS ``u_c`` (periodic
    layout) / ``u_f`` (box MAC layout) may be given — only valid while
    the window stays put (the static-two-level case, which
    :class:`ibamr_tpu.amr.TwoLevelAdvDiff` delegates here).
    """

    GHOST = 1   # flux stencils read exactly one ghost layer

    def __init__(self, grid: StaggeredGrid,
                 box_shape: Tuple[int, ...],
                 kappa: float = 0.0,
                 scheme: str = "centered",
                 u_fn: Optional[Callable] = None,
                 u_c: Optional[Vel] = None,
                 u_f: Optional[Vel] = None,
                 tag_threshold: float = 0.05,
                 ratio: int = 2,
                 clearance: int = 2,
                 dtype=jnp.float32):
        assert scheme in ("centered", "upwind")
        assert clearance >= 1, \
            "clearance >= 1 required (prolongation reads a 1-cell halo)"
        assert u_fn is None or (u_c is None and u_f is None)
        self.grid = grid
        self.box_shape = tuple(int(s) for s in box_shape)
        self.kappa = float(kappa)
        self.scheme = scheme
        self.u_fn = u_fn
        self.u_c = u_c
        self.u_f = u_f
        self.tag_threshold = float(tag_threshold)
        self.ratio = ratio
        self.clearance = clearance
        self.dtype = dtype
        self.dx_f = tuple(h / ratio for h in grid.dx)
        self.fine_shape = tuple(s * ratio for s in self.box_shape)

    # -- coordinates of the moving window -----------------------------------
    def _fine_face_coords(self, lo, d):
        """Physical coords of fine faces normal to d (box MAC layout)."""
        grid, r = self.grid, self.ratio
        axes = []
        for a in range(grid.dim):
            n = self.fine_shape[a] + (1 if a == d else 0)
            i = jnp.arange(n, dtype=self.dtype)
            off = 0.0 if a == d else 0.5
            x = grid.x_lo[a] + (lo[a].astype(self.dtype)
                                + (i + off) / r) * grid.dx[a]
            axes.append(x)
        return jnp.meshgrid(*axes, indexing="ij")

    def _coarse_face_coords(self, d):
        grid = self.grid
        axes = []
        for a in range(grid.dim):
            i = jnp.arange(grid.n[a], dtype=self.dtype)
            off = 0.0 if a == d else 0.5
            axes.append(grid.x_lo[a] + (i + off) * grid.dx[a])
        return jnp.meshgrid(*axes, indexing="ij")

    # -- fluxes --------------------------------------------------------------
    def _coarse_fluxes(self, Qc):
        from ibamr_tpu.ops.convection import advective_face_value
        dx = self.grid.dx
        out = []
        for d in range(self.grid.dim):
            Qm = jnp.roll(Qc, 1, d)
            F = jnp.zeros_like(Qc)
            u = None
            if self.u_c is not None:
                u = self.u_c[d]
            elif self.u_fn is not None:
                u = self.u_fn(self._coarse_face_coords(d), d)
            if u is not None:
                F = F + u * advective_face_value(Qm, Qc, u, self.scheme)
            if self.kappa != 0.0:
                F = F - self.kappa * (Qc - Qm) / dx[d]
            out.append(F)
        return tuple(out)

    def _fine_fluxes(self, Qg, lo):
        from ibamr_tpu.ops.convection import advective_face_value
        g = self.GHOST
        dim = self.grid.dim
        nf = self.fine_shape
        out = []
        for d in range(dim):
            lo_sl = [slice(g, g + nf[a]) for a in range(dim)]
            hi_sl = [slice(g, g + nf[a]) for a in range(dim)]
            lo_sl[d] = slice(g - 1, g + nf[d])
            hi_sl[d] = slice(g, g + nf[d] + 1)
            Qm = Qg[tuple(lo_sl)]
            Qp = Qg[tuple(hi_sl)]
            F = jnp.zeros_like(Qm)
            u = None
            if self.u_f is not None:
                u = self.u_f[d]
            elif self.u_fn is not None:
                u = self.u_fn(self._fine_face_coords(lo, d), d)
            if u is not None:
                F = F + u * advective_face_value(Qm, Qp, u, self.scheme)
            if self.kappa != 0.0:
                F = F - self.kappa * (Qp - Qm) / self.dx_f[d]
            out.append(F)
        return tuple(out)

    # -- one composite step (traced origin) ----------------------------------
    def _fine_substeps(self, Qc, Qc_new, Qf, lo, dt):
        """Advance one window's fine data r substeps against the coarse
        predictor; returns (Qf_new, acc_lo, acc_hi) boundary-flux sums
        for the reflux."""
        dim = self.grid.dim
        r = self.ratio
        dx_f = self.dx_f
        dt_f = dt / r
        acc_lo = [None] * dim
        acc_hi = [None] * dim
        for m in range(r):
            theta = m / r
            Qc_theta = (1.0 - theta) * Qc + theta * Qc_new
            Qg = fill_fine_ghosts_dyn(Qf, Qc_theta, lo, self.GHOST, r)
            Ff = self._fine_fluxes(Qg, lo)
            divf = None
            for d in range(dim):
                lo_sl = [slice(None)] * dim
                hi_sl = [slice(None)] * dim
                lo_sl[d] = slice(0, -1)
                hi_sl[d] = slice(1, None)
                t = (Ff[d][tuple(hi_sl)] - Ff[d][tuple(lo_sl)]) / dx_f[d]
                divf = t if divf is None else divf + t
                pl = [slice(None)] * dim
                pl[d] = 0
                f_lo = Ff[d][tuple(pl)]
                pl[d] = -1
                f_hi = Ff[d][tuple(pl)]
                acc_lo[d] = f_lo if acc_lo[d] is None else acc_lo[d] + f_lo
                acc_hi[d] = f_hi if acc_hi[d] is None else acc_hi[d] + f_hi
            Qf = Qf - dt_f * divf
        return Qf, acc_lo, acc_hi

    def _restrict_and_reflux(self, Qc_new, Qf, lo, Fc, acc_lo, acc_hi,
                             dt):
        """Restrict one window onto the coarse level and reflux its CF
        interface neighbors (dynamic origin)."""
        grid = self.grid
        dim = grid.dim
        r = self.ratio
        dx = grid.dx

        # restriction onto covered coarse cells (dynamic origin)
        Qc_new = restrict_into_coarse(Qc_new, Qf, lo, r)

        # reflux at the CF interface: dynamic-slice the neighbor slabs,
        # correct, and write back
        for d in range(dim):
            def face_avg(f):
                tr = [a for a in range(dim) if a != d]
                new_shape = []
                for a in tr:
                    new_shape += [self.box_shape[a], r]
                arr = f.reshape(new_shape)
                mean_axes = tuple(2 * i + 1 for i in range(len(tr)))
                return arr.mean(axis=mean_axes)

            favg_lo = face_avg(acc_lo[d]) / r
            favg_hi = face_avg(acc_hi[d]) / r

            slab_shape = tuple(1 if a == d else self.box_shape[a]
                               for a in range(dim))
            exp = tuple(0 if a == d else slice(None) for a in range(dim))

            # coarse flux planes at the CF boundaries
            lo_face = lo
            fc_lo = lax.dynamic_slice(Fc[d], tuple(lo_face), slab_shape)
            hi_face = lo.at[d].add(self.box_shape[d])
            fc_hi = lax.dynamic_slice(Fc[d], tuple(hi_face), slab_shape)

            # lower neighbor cell at lo[d]-1: F[lo] is its upper face
            nb_lo = lo.at[d].add(-1)
            cell = lax.dynamic_slice(Qc_new, tuple(nb_lo), slab_shape)
            cell = cell + (-dt / dx[d]) * (favg_lo - fc_lo[exp]
                                           ).reshape(slab_shape)
            Qc_new = lax.dynamic_update_slice(Qc_new, cell, tuple(nb_lo))
            # upper neighbor cell at lo[d]+shape: F[hi] is its lower face
            nb_hi = lo.at[d].add(self.box_shape[d])
            cell = lax.dynamic_slice(Qc_new, tuple(nb_hi), slab_shape)
            cell = cell + (dt / dx[d]) * (favg_hi - fc_hi[exp]
                                          ).reshape(slab_shape)
            Qc_new = lax.dynamic_update_slice(Qc_new, cell, tuple(nb_hi))
        return Qc_new

    def _coarse_advance(self, Qc, dt):
        """Coarse-level flux divergence advance; returns (Fc, Qc_new)."""
        dx = self.grid.dx
        Fc = self._coarse_fluxes(Qc)
        div = None
        for d in range(self.grid.dim):
            t = (jnp.roll(Fc[d], -1, d) - Fc[d]) / dx[d]
            div = t if div is None else div + t
        return Fc, Qc - dt * div

    def step(self, state: AMRState, dt: float) -> AMRState:
        Qc, Qf, lo = state
        Fc, Qc_new = self._coarse_advance(Qc, dt)
        Qf, acc_lo, acc_hi = self._fine_substeps(Qc, Qc_new, Qf, lo, dt)
        Qc_new = self._restrict_and_reflux(Qc_new, Qf, lo, Fc, acc_lo,
                                           acc_hi, dt)
        return AMRState(Qc=Qc_new, Qf=Qf, lo=lo)

    # -- tag / fit / regrid ---------------------------------------------------
    def regrid_state(self, state: AMRState) -> AMRState:
        Qc, Qf, lo = state
        Qc_sync = restrict_into_coarse(Qc, Qf, lo, self.ratio)
        tags = tag_gradient(Qc_sync, self.grid, self.tag_threshold)
        lo_new = fit_box_origin(tags, self.box_shape, self.clearance)
        Qc2, Qf2 = regrid(Qc, Qf, lo, lo_new, self.ratio)
        return AMRState(Qc=Qc2, Qf=Qf2, lo=lo_new)

    # -- driver ---------------------------------------------------------------
    def advance(self, state: AMRState, dt: float, num_steps: int,
                regrid_interval: int = 4) -> AMRState:
        """num_steps composite steps with a regrid every
        ``regrid_interval`` steps — one jitted lax.scan."""
        def body(s, k):
            s = lax.cond(jnp.mod(k, regrid_interval) == 0,
                         self.regrid_state, lambda x: x, s)
            return self.step(s, dt), None

        out, _ = lax.scan(body, state, jnp.arange(num_steps))
        return out

    # -- setup / diagnostics --------------------------------------------------
    def initialize(self, fn, lo0=None) -> AMRState:
        """Evaluate ``fn(coords)->array`` on the coarse level, fit the
        window to the initial tags (or use ``lo0``), prolong."""
        Qc = jnp.asarray(fn(self.grid.cell_centers(self.dtype)),
                         dtype=self.dtype)
        Qc = jnp.broadcast_to(Qc, self.grid.n)
        if lo0 is None:
            tags = tag_gradient(Qc, self.grid, self.tag_threshold)
            lo = fit_box_origin(tags, self.box_shape, self.clearance)
        else:
            lo = jnp.asarray(lo0, dtype=jnp.int32)
        # exact samples beat prolongation for the IC
        coords = self._fine_cell_coords(lo)
        Qf = jnp.asarray(fn(coords), dtype=self.dtype)
        Qf = jnp.broadcast_to(Qf, self.fine_shape)
        return AMRState(Qc=Qc, Qf=Qf, lo=lo)

    def _fine_cell_coords(self, lo):
        grid, r = self.grid, self.ratio
        axes = []
        for a in range(grid.dim):
            i = jnp.arange(self.fine_shape[a], dtype=self.dtype)
            x = grid.x_lo[a] + (lo[a].astype(self.dtype)
                                + (i + 0.5) / r) * grid.dx[a]
            axes.append(x)
        return jnp.meshgrid(*axes, indexing="ij")

    def total(self, state: AMRState) -> jnp.ndarray:
        """Composite conserved integral (uncovered coarse + fine)."""
        grid, box_shape = self.grid, self.box_shape
        vol_c = grid.cell_volume
        vol_f = vol_c / (self.ratio ** grid.dim)
        covered = jnp.zeros(grid.n, dtype=bool)
        ones = jnp.ones(box_shape, dtype=bool)
        covered = lax.dynamic_update_slice(covered, ones, tuple(state.lo))
        return (jnp.sum(jnp.where(covered, 0.0, state.Qc)) * vol_c
                + jnp.sum(state.Qf) * vol_f)
