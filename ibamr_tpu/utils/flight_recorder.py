"""Flight recorder: bounded ring of chunk-entry states + run
fingerprint, dumped as a bitwise-replayable capsule on any incident
(PR 5 tentpole 1).

At production scale an incident that cannot be reproduced offline is
unfixable: the PR-2/3 incident records say WHAT went wrong (kind,
vitals, attempts) but not enough to re-execute the failing computation.
The recorder closes that gap:

- :meth:`FlightRecorder.snapshot` is called by
  :class:`~ibamr_tpu.utils.hierarchy_driver.HierarchyDriver` once per
  chunk, BEFORE the jitted chunk consumes the state. The snapshot is a
  HOST copy (``device_get`` -> numpy), which makes it donation-safe by
  construction: with ``RunConfig(donate=True)`` the chunk invalidates
  the device buffers it was passed, but the ring holds independent host
  memory. (``ResilientDriver`` forces donate off anyway; the bare
  driver is the hazard this fixes.)
- The ring is bounded (``capacity`` entries, a handful of chunks), so
  recording costs one host copy of the state per chunk and a few
  states of host RAM — the overhead bound (< 2% of chunk wall at the
  CPU smoke size) is pinned in tests/test_replay.py via the recorder's
  own ``overhead_s`` accounting.
- :meth:`FlightRecorder.dump_incident` writes
  ``incidents/<step>/replay.npz`` (the pre-chunk state) plus
  ``manifest.json``: the run fingerprint (config digest, integrator
  spec, engine + fallback chain, ``spectral_dtype``, jax/numpy
  versions, device count/platform, x64 flag, RNG keys, active fault
  injectors, shadow-audit params) and — when the driver is available —
  the POST-chunk digest: per-leaf CRC32s and the fused vitals vector of
  the state the failing chunk produces, computed by re-executing the
  recorded chunk once through the driver's own compiled executable
  (the incident path is cold; one extra chunk is free). ``tools/
  replay.py`` re-executes the capsule in a fresh process and pins
  bitwise against that digest.

Capsule layout::

    incidents/<step>/replay.npz     # pre-chunk state, checkpoint layout
    incidents/<step>/manifest.json  # fingerprint + chunk + post digest

Both files are written with the checkpoint module's atomic-write
discipline (temp + fsync + rename), so a capsule is never torn — the
manifest is written LAST and is the commit marker.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from ibamr_tpu.utils.checkpoint import (_atomic_write, _gather_arrays,
                                        _leaf_crc, _path_str)

CAPSULE_SCHEMA = 1


def _json_safe(obj):
    """Best-effort conversion of config/spec values to JSON types."""
    if isinstance(obj, dict):
        return {str(k): _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    return repr(obj)


def canonicalize(obj):
    """Deterministic JSON-safe form of fingerprint/cache-key material:
    values through :func:`_json_safe` (tuples -> lists, numpy scalars ->
    python), dict keys RECURSIVELY sorted. Two semantically identical
    configs that differ only in dict insertion order canonicalize (and
    therefore digest) identically — the serving cache
    (ibamr_tpu/serve/aot_cache.py) keys whole compiled executables on
    these digests, so key stability is a correctness property, not a
    nicety."""
    def _sort(v):
        if isinstance(v, dict):
            return {k: _sort(v[k]) for k in sorted(v)}
        if isinstance(v, list):
            return [_sort(x) for x in v]
        return v
    return _sort(_json_safe(obj))


def _engine_label(val) -> Optional[str]:
    """Normalize an engine selection value (the ``use_fast_interaction``
    vocabulary) to a stable string label."""
    if val is None:
        return "auto"
    if val is True:
        return "mxu"
    if val is False:
        return "scatter"
    return str(val)


def describe_integrator(integ) -> dict:
    """Reconstructible spec of an integrator: enough for
    ``tools/replay.py`` to rebuild it in a fresh process. The INS
    integrator is described field-by-field; anything else falls back
    to an opaque record (replayable only via an explicit factory
    ``spec`` passed to the recorder)."""
    if integ is None:
        return {"kind": "opaque", "class": None}
    grid = getattr(integ, "grid", None)
    if (grid is not None and hasattr(integ, "rho")
            and hasattr(integ, "convective_op_type")
            and hasattr(integ, "initialize")):
        import jax.numpy as jnp

        from ibamr_tpu.solvers.escalation import precision_level_name

        wall = getattr(integ, "wall_axes", None)
        return {
            "kind": "ins",
            "grid": {"n": [int(v) for v in grid.n],
                     "x_lo": [float(v) for v in grid.x_lo],
                     "x_up": [float(v) for v in grid.x_up]},
            "rho": float(integ.rho), "mu": float(integ.mu),
            "convective_op_type": str(integ.convective_op_type),
            "dtype": str(jnp.dtype(integ.dtype)),
            "wall_axes": None if wall is None else [bool(w) for w in wall],
            "spectral_dtype": precision_level_name(
                getattr(integ, "spectral_dtype", None)),
        }
    return {"kind": "opaque", "class": type(integ).__name__}


def factory_spec(module: str, name: str, **kwargs) -> dict:
    """Spec for an integrator built by a module-level factory (e.g.
    ``ibamr_tpu.models.shell3d.build_shell_example``): replay imports
    ``module``, calls ``name(**kwargs)`` and expects ``(integ, state)``
    (or an integrator with ``initialize()``). Overrides substitute into
    ``kwargs`` by key (``engine`` maps onto ``use_fast_interaction``)."""
    return {"kind": "factory", "module": module, "name": name,
            "kwargs": _json_safe(kwargs)}


@dataclasses.dataclass
class ChunkSnapshot:
    """One ring entry: the host copy of the state ENTERING a chunk.
    Fleet chunks store the per-lane dt VECTOR and lane-alive mask
    (host copies); solo chunks keep the scalar dt and ``alive=None``."""
    step: int
    dt: Any                           # float, or (B,) ndarray in fleet mode
    length: int
    paths: List[str]                  # leaf order for unflatten
    arrays: Dict[str, np.ndarray]     # path -> host copy
    treedef: Any
    wall_time: float
    alive: Optional[np.ndarray] = None

    def covers(self, step: Optional[int]) -> bool:
        return (step is None
                or self.step <= step <= self.step + self.length)


class FlightRecorder:
    """Bounded ring of pre-chunk host snapshots + the run fingerprint.

    Parameters
    ----------
    capacity:
        Ring depth in chunks. The newest entry covering the incident
        step becomes the capsule; a handful suffices (the supervisor
        dumps on the FIRST raise).
    spec:
        Optional explicit integrator spec (see :func:`factory_spec`)
        overriding the derived :func:`describe_integrator` record —
        required for replay of anything but the plain INS integrator.
    extra_fingerprint:
        Extra JSON-safe fields merged into the fingerprint (mesh shape,
        run labels, ...).
    """

    def __init__(self, capacity: int = 4, spec: Optional[dict] = None,
                 extra_fingerprint: Optional[dict] = None):
        if capacity < 1:
            raise ValueError("FlightRecorder.capacity must be >= 1")
        self.capacity = capacity
        self.ring: "deque[ChunkSnapshot]" = deque(maxlen=capacity)
        self.spec = spec
        self.extra = dict(extra_fingerprint or {})
        self.snapshots = 0
        self.overhead_s = 0.0         # cumulative snapshot cost (the
        #                               < 2%-of-chunk-wall observable)
        self.dumps: List[str] = []
        self._integ = None
        self._cfg = None

    # -- recording -----------------------------------------------------------

    def snapshot(self, state, *, step: int, dt, length: int,
                 integ=None, cfg=None, alive=None) -> None:
        """Host-copy the pre-chunk state into the ring. Called by the
        driver BEFORE the (possibly donated) chunk consumes ``state`` —
        the copy is what makes recording donation-safe. Fleet chunks
        pass the (B,) per-lane dt vector and lane-alive mask."""
        import jax

        t0 = time.perf_counter()
        flat, treedef = jax.tree_util.tree_flatten_with_path(state)
        paths, arrays = [], {}
        for path, leaf in flat:
            key = _path_str(path)
            paths.append(key)
            arrays[key] = np.asarray(jax.device_get(leaf))
        dt_val = float(dt) if np.ndim(dt) == 0 \
            else np.array(dt, dtype=np.float64)
        self.ring.append(ChunkSnapshot(
            step=int(step), dt=dt_val, length=int(length),
            paths=paths, arrays=arrays, treedef=treedef,
            wall_time=time.time(),
            alive=None if alive is None else np.array(alive, dtype=bool)))
        if integ is not None:
            self._integ = integ
        if cfg is not None:
            self._cfg = cfg
        self.snapshots += 1
        self.overhead_s += time.perf_counter() - t0

    def entry_for_step(self, step: Optional[int]) -> Optional[ChunkSnapshot]:
        """Newest ring entry whose chunk covers ``step`` (fallback: the
        newest entry — an incident always belongs to the last chunk
        started)."""
        for entry in reversed(self.ring):
            if entry.covers(step):
                return entry
        return self.ring[-1] if self.ring else None

    def restore(self, entry: ChunkSnapshot):
        """Device state rebuilt from a ring entry's host arrays."""
        import jax
        import jax.numpy as jnp

        leaves = [jnp.asarray(entry.arrays[k]) for k in entry.paths]
        return jax.tree_util.tree_unflatten(entry.treedef, leaves)

    # -- fingerprint ---------------------------------------------------------

    def fingerprint(self, driver=None) -> dict:
        """The run identity a replay must reproduce. JSON-safe."""
        import jax

        integ = driver.integ if driver is not None else self._integ
        cfg = driver.cfg if driver is not None else self._cfg
        cfg_dict = (_json_safe(dataclasses.asdict(cfg))
                    if dataclasses.is_dataclass(cfg) else {})
        digest = hashlib.sha256(
            json.dumps(cfg_dict, sort_keys=True).encode()).hexdigest()
        spec = self.spec if self.spec is not None \
            else describe_integrator(integ)
        try:
            from ibamr_tpu.solvers.escalation import precision_level_name
            fluid = getattr(integ, "ins", integ)
            sd = precision_level_name(
                getattr(fluid, "spectral_dtype", None))
        except Exception:
            sd = None
        engine, chain = self._engine_info(integ, spec)
        try:
            from tools.fault_injection import ACTIVE_INJECTORS
            injectors = _json_safe(dict(ACTIVE_INJECTORS))
        except Exception:
            injectors = {}
        audit = None
        sa = getattr(driver, "shadow_audit", None)
        if sa is not None:
            audit = sa.params()
        fp = {
            "config": cfg_dict, "config_digest": digest,
            "integrator": spec,
            "spectral_dtype": sd,
            "engine": engine, "engine_chain": chain,
            "jax_version": jax.__version__,
            "numpy_version": np.__version__,
            "device_count": jax.device_count(),
            "platform": jax.default_backend(),
            "mesh_shape": self.extra.get("mesh_shape"),
            # sharded runs stamp the full mesh spec (shape, axis names,
            # shard count) via ResilientDriver(mesh=...) so replay can
            # rebuild the sharded program — or knowingly degrade when
            # fewer devices are available than the incident ran on
            "mesh": self.extra.get("mesh"),
            "x64": bool(jax.config.jax_enable_x64),
            # the framework threads no RNG through the run loop; the
            # slot exists so stochastic physics can stamp its keys via
            # extra_fingerprint without a schema bump
            "rng_keys": self.extra.get("rng_keys"),
            "injectors": injectors,
            "audit": audit,
        }
        for k, v in self.extra.items():
            fp.setdefault(k, _json_safe(v))
        # canonical form: dict insertion order must never leak into
        # run_id / serving-cache digests
        return canonicalize(fp)

    def run_id(self, driver=None) -> str:
        """The 16-hex run identity the observability ledger stamps on
        every record — a digest of :meth:`fingerprint`, so the ledger,
        the incident capsules and the replay verdicts of one run all
        cross-reference by the same id."""
        from ibamr_tpu.obs import run_id_from_fingerprint
        return run_id_from_fingerprint(self.fingerprint(driver=driver))

    def observe(self, integ=None, cfg=None) -> None:
        """Bind integrator/config context for fingerprinting WITHOUT
        taking a ring snapshot — the serving cache keys entries on the
        fingerprint of an integrator it never runs through a driver."""
        if integ is not None:
            self._integ = integ
        if cfg is not None:
            self._cfg = cfg

    @staticmethod
    def _engine_info(integ, spec):
        """(engine label, fallback chain) actually in use, best-effort.
        The RESOLVED name stamped by the factory (``ib.engine_name``,
        post-auto-resolution and post-fallback) wins over the factory
        spec's alias — the fingerprint must describe what runs, not
        what was asked for."""
        label = None
        ib_resolved = getattr(getattr(integ, "ib", None),
                              "engine_name", None)
        if ib_resolved is not None:
            label = str(ib_resolved)
        if label is None and spec.get("kind") == "factory":
            kwargs = spec.get("kwargs", {})
            if "use_fast_interaction" in kwargs:
                label = _engine_label(kwargs["use_fast_interaction"])
        if label is None:
            ib = getattr(integ, "ib", None)
            fast = getattr(ib, "fast", None)
            if ib is not None:
                label = (type(fast).__name__ if fast is not None
                         else "scatter")
        if label is None:
            return None, None
        try:
            from ibamr_tpu.ops.interaction_packed import fallback_chain
            return label, list(fallback_chain(label))
        except Exception:
            return label, None

    # -- capsule dump --------------------------------------------------------

    def dump_incident(self, *, directory: str, kind: str,
                      step: Optional[int] = None,
                      event: Optional[str] = None,
                      driver=None,
                      lane: Optional[int] = None) -> Optional[str]:
        """Write ``<directory>/<step>/replay.npz`` + ``manifest.json``
        for the newest ring entry covering ``step``. Returns the
        capsule directory (or None when the ring is empty). A second
        incident landing on the same chunk reuses the existing capsule
        (the state is identical; only the first dump pays).

        ``lane`` (fleet runs) slices the lane-stacked snapshot down to
        that lane's rows: the capsule is SINGLE-LANE (``-L<k>`` suffix
        on the directory), carries a ``lane`` manifest record with the
        original ``lane_index``/``fleet_size``, and replays unbatched —
        ``tools/replay.py`` re-executes it as a B=1 fleet chunk, the
        bitwise-equal solo form of the failing lane."""
        entry = self.entry_for_step(step)
        if entry is None:
            return None
        fleet = np.ndim(entry.dt) > 0
        suffix = "" if lane is None else f"-L{lane:03d}"
        cap_dir = os.path.join(directory, f"{entry.step:08d}{suffix}")
        manifest_path = os.path.join(cap_dir, "manifest.json")
        if os.path.exists(manifest_path):
            return cap_dir
        os.makedirs(cap_dir, exist_ok=True)
        npz_path = os.path.join(cap_dir, "replay.npz")
        if lane is not None:
            arrays = {k: np.ascontiguousarray(v[lane])
                      for k, v in entry.arrays.items()}
            chunk_dt = float(entry.dt[lane]) if fleet \
                else float(entry.dt)
        else:
            arrays = entry.arrays
            chunk_dt = [float(v) for v in entry.dt] if fleet \
                else entry.dt
        _atomic_write(npz_path, lambda f: np.savez(f, **arrays))
        post = None
        if driver is not None and kind != "stall":
            # a stalled chunk may hang again on re-execution — replay
            # of a stall capsule is interactive business, not dump-time
            post = self._post_digest(entry, driver, lane=lane)
        manifest = {
            "capsule_schema": CAPSULE_SCHEMA,
            "incident": {"kind": kind, "event": event,
                         "step": step},
            "chunk": {"start_step": entry.step, "length": entry.length,
                      "dt": chunk_dt},
            "state_file": "replay.npz",
            "leaf_order": entry.paths,
            "pre_leaf_crcs": {k: _leaf_crc(arrays[k])
                              for k in entry.paths},
            "post": post,
            "fingerprint": self.fingerprint(driver),
            "time": time.time(),
        }
        try:
            # a capsule dumped while serving names the request(s) whose
            # trace crosses it — the ledger timeline and the capsule
            # then cross-reference by trace_id, not just run_id
            from ibamr_tpu.obs import bus as _bus
            tids = _bus.current_trace()
            if tids:
                if len(tids) == 1:
                    manifest["trace_id"] = tids[0]
                else:
                    manifest["trace_ids"] = list(tids)
        except Exception:
            pass
        if lane is not None:
            fleet_size = (len(entry.dt) if fleet else
                          getattr(driver, "lanes", None))
            manifest["lane"] = {"index": int(lane),
                                "fleet_size": None if fleet_size is None
                                else int(fleet_size)}
        elif fleet:
            manifest["fleet"] = {
                "size": len(entry.dt),
                "alive": None if entry.alive is None
                else [bool(a) for a in entry.alive]}
        _atomic_write(manifest_path,
                      lambda f: f.write(json.dumps(
                          manifest, indent=1).encode()))
        self.dumps.append(cap_dir)
        return cap_dir

    def _post_digest(self, entry: ChunkSnapshot, driver,
                     lane: Optional[int] = None) -> Optional[dict]:
        """Per-leaf CRC32s + vitals of the state the recorded chunk
        produces, via ONE re-execution through the driver's own
        compiled chunk (cold path: incidents are rare by construction).
        For a lane capsule the digest is of the LANE'S slice of the
        fleet re-execution — bitwise what a B=1 replay must reproduce.
        None when re-execution itself fails."""
        try:
            import jax.numpy as jnp

            state = self.restore(entry)
            if np.ndim(entry.dt) > 0:
                alive = entry.alive if entry.alive is not None \
                    else np.ones(len(entry.dt), dtype=bool)
                out, health = driver._chunk(entry.length)(
                    state, jnp.asarray(entry.dt), jnp.asarray(alive))
            else:
                out, health = driver._chunk(entry.length)(state, entry.dt)
            if lane is not None:
                import jax
                out = jax.tree_util.tree_map(lambda l: l[lane], out)
                h = np.asarray(health)
                vit = h[:, lane] if h.ndim == 2 else h[lane:lane + 1]
            else:
                vit = np.asarray(health).reshape(-1)
            arrays = _gather_arrays(out)
            return {
                "leaf_crcs": {k: _leaf_crc(v) for k, v in arrays.items()},
                "vitals": [float(v) for v in vit],
                "finite": bool(np.isfinite(
                    np.concatenate([np.asarray(v, dtype=np.float64).
                                    reshape(-1) for v in arrays.values()
                                    if np.issubdtype(v.dtype,
                                                     np.floating)])).all()),
            }
        except Exception:
            return None
