"""In-flight health vitals: detect degradation BEFORE the NaN (PR 3).

PR 2's recovery machinery only fires on the *loud* failure — a state
leaf that already went non-finite. The expensive silent failure is the
run that is still finite but already lost: velocity growing
exponentially, CFL creeping past stability, divergence error
compounding. By the time ``_finite_flag`` trips, the newest checkpoints
may already hold garbage-but-finite states and the supervisor pays a
full ``max_retries`` cycle to find a good one.

:class:`HealthProbe` closes that gap. The jit side (:meth:`measure`)
reduces the state to a small fixed vector of physics vitals INSIDE the
driver's scan chunk, so the per-chunk host cost stays exactly one small
device->host transfer (the same sync the old single finite bool paid —
pinned by ``HierarchyDriver.trace_counts``). The host side
(:meth:`classify` / :meth:`check`) applies thresholds, classifies the
chunk OK / WARN / FATAL, and raises :class:`HealthDegraded` — a
:class:`SimulationDiverged` *precursor* — on FATAL or on a sustained
WARN streak, while the state is still finite and the rollback is cheap.

Vitals vector schema (fixed order, ``VITALS_FIELDS``):

====  ============  =====================================================
idx   field         meaning
====  ============  =====================================================
0     ``finite``    1.0 iff every floating state leaf is all-finite
1     ``max_u``     max |u| over the velocity components (0 if no vel)
2     ``cfl``       realized advective CFL: max_u * dt / min(dx)
3     ``div_norm``  max |div u| (0 when no divergence functional given)
4     ``func``      caller-supplied energy/volume functional (NaN = none)
5     ``vol``       IB enclosed volume/area (``volume_fn``; NaN = none)
6     ``budget``    momentum/KE budget term (``budget_fn``; NaN = none)
====  ============  =====================================================

Slots 5–6 are the PR-5 physics-invariant sentinels: both are
conserved-to-drift quantities, so their triage is RELATIVE drift over
the run's own first finite value (``vol_drift_warn/fatal``,
``budget_drift_warn/fatal``) — a leaking membrane or a momentum budget
blowing up rolls back while every checkpoint is still healthy. They
ride the SAME fused vitals vector, so the per-chunk cost stays one
small device->host transfer (pinned via ``trace_counts``).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, List, Optional

import numpy as np

from ibamr_tpu.utils.hierarchy_driver import SimulationDiverged, _finite_flag

OK = "ok"
WARN = "warn"
FATAL = "fatal"

VITALS_FIELDS = ("finite", "max_u", "cfl", "div_norm", "func",
                 "vol", "budget")


class HealthDegraded(SimulationDiverged):
    """Precursor divergence: the state is still FINITE but the vitals
    crossed a FATAL threshold or sustained a WARN streak. Subclassing
    :class:`SimulationDiverged` means the whole PR-2 recovery machinery
    (``ResilientDriver`` rollback + dt backoff + incident record) fires
    unchanged — but with a cheap recovery, because every checkpoint on
    disk still predates any non-finite value."""

    kind = "health_degraded"

    def __init__(self, step: int, reasons, vitals: dict):
        self.step = step
        self.reasons = list(reasons)
        self.vitals = dict(vitals)
        self.bad_leaves: list = []      # nothing is non-finite (yet)
        RuntimeError.__init__(
            self,
            f"health degraded by step {step}: {'; '.join(self.reasons)} "
            f"(vitals {self.vitals}) — rolling back while the state is "
            f"still finite")

    def incident_payload(self) -> dict:
        return {"reasons": self.reasons, "vitals": self.vitals}


@dataclasses.dataclass
class HealthProbe:
    """Fused in-flight vitals probe + host-side triage.

    Jit side: :meth:`measure(state, dt)` returns a fixed float32 vector
    (``VITALS_FIELDS`` order) built from optional accessors — all must
    be jit-traceable functions of the state:

    - ``velocity_fn(state) -> tuple/list of arrays`` (default: the
      state's ``u`` attribute when present);
    - ``divergence_fn(state) -> array or scalar`` (max |.| is taken);
    - ``functional_fn(state) -> scalar`` — the caller's conserved-ish
      quantity (kinetic energy, phase volume, ...), the signal the
      growth triage watches.

    Host side: :meth:`check(vitals, step, dt)` classifies the chunk and
    raises :class:`HealthDegraded` on FATAL, or after ``sustain``
    consecutive WARN chunks. ``None`` thresholds are disabled. The
    functional baseline is the first finite functional value observed
    (reset only by :meth:`reset`), so "growth beyond a configured
    factor" means growth over the run's OWN starting value, not an
    absolute scale the caller would have to guess.
    """

    velocity_fn: Optional[Callable[[Any], Any]] = None
    divergence_fn: Optional[Callable[[Any], Any]] = None
    functional_fn: Optional[Callable[[Any], Any]] = None
    min_dx: Optional[float] = None       # needed for the CFL vital
    # thresholds (None = that check disabled)
    max_u_warn: Optional[float] = None
    max_u_fatal: Optional[float] = None
    cfl_warn: Optional[float] = None
    cfl_fatal: Optional[float] = None
    div_warn: Optional[float] = None
    div_fatal: Optional[float] = None
    func_growth_warn: Optional[float] = None    # factor over baseline
    func_growth_fatal: Optional[float] = None
    # PR-5 invariant sentinels (slots 5-6). Both are conserved-to-drift
    # quantities; thresholds are RELATIVE drift |v - v0| / max(|v0|, eps)
    # over the run's own first finite value.
    volume_fn: Optional[Callable[[Any], Any]] = None
    budget_fn: Optional[Callable[[Any], Any]] = None
    vol_drift_warn: Optional[float] = None
    vol_drift_fatal: Optional[float] = None
    budget_drift_warn: Optional[float] = None
    budget_drift_fatal: Optional[float] = None
    sustain: int = 3                     # WARN chunks before escalation

    VITALS_FIELDS = VITALS_FIELDS        # schema, importable off the class

    def __post_init__(self):
        if self.sustain < 1:
            raise ValueError("sustain must be >= 1 (a WARN streak of "
                             "zero chunks would fire immediately)")
        self._warn_streak = 0
        self._baseline_func: Optional[float] = None
        self._baseline_vol: Optional[float] = None
        self._baseline_budget: Optional[float] = None
        self.history: List[dict] = []    # one record per classified chunk
        self.last: Optional[dict] = None

    # -- construction helpers ----------------------------------------------

    @classmethod
    def for_integrator(cls, integ, **kw) -> "HealthProbe":
        """Probe wired to the framework's integrator conventions: MAC
        velocity at ``state.u``, divergence via the shared stencils,
        kinetic energy as the default functional, a momentum-magnitude
        budget sentinel when a grid is available, and (for 2D IB
        integrators) the enclosed marker polygon area as the volume
        sentinel. Any explicit kwarg wins over the derived default."""
        import jax.numpy as jnp

        from ibamr_tpu.ops import stencils

        is_ib = (hasattr(integ, "ins") and hasattr(integ, "ib"))
        ins = getattr(integ, "ins", None) if is_ib else integ
        grid = getattr(ins, "grid", None)

        def uget(s):
            return s.ins.u if is_ib else s.u

        if grid is not None:
            kw.setdefault("min_dx", float(min(grid.dx)))
            dx = grid.dx
            kw.setdefault("velocity_fn", uget)
            kw.setdefault("divergence_fn",
                          lambda s: stencils.divergence(uget(s), dx))
            # momentum/KE budget: cell_vol * rho * |sum_cells u| — an
            # exactly conserved quantity of the periodic projected
            # equations, so its drift is pure scheme/precision error
            rho = float(getattr(ins, "rho", 1.0))
            cv = float(getattr(grid, "cell_volume", 1.0))

            def budget(s):
                comps = uget(s)
                mom = [jnp.sum(c) for c in comps]
                return cv * rho * jnp.sqrt(sum(m * m for m in mom))
            kw.setdefault("budget_fn", budget)
        if hasattr(integ, "kinetic_energy"):
            kw.setdefault("functional_fn", integ.kinetic_energy)
        elif ins is not None and hasattr(ins, "kinetic_energy"):
            kw.setdefault("functional_fn",
                          lambda s: ins.kinetic_energy(s.ins)
                          if is_ib else ins.kinetic_energy(s))
        if is_ib and grid is not None and len(grid.dx) == 2:
            from ibamr_tpu.integrators.ib import polygon_area
            kw.setdefault("volume_fn", lambda s: polygon_area(s.X))
        return cls(**kw)

    # -- jit side ------------------------------------------------------------

    def measure(self, state, dt):
        """Fixed-shape vitals vector (float32, ``len(VITALS_FIELDS)``);
        fully traceable.
        Meant to be called INSIDE the driver's jitted chunk so the whole
        reduction fuses with the step scan."""
        import jax.numpy as jnp

        finite = _finite_flag(state).astype(jnp.float32)

        vel = (self.velocity_fn(state) if self.velocity_fn is not None
               else getattr(state, "u", None))
        if vel is not None:
            comps = vel if isinstance(vel, (tuple, list)) else (vel,)
            max_u = jnp.asarray(0.0, jnp.float32)
            for c in comps:
                max_u = jnp.maximum(max_u,
                                    jnp.max(jnp.abs(c)).astype(jnp.float32))
        else:
            max_u = jnp.asarray(0.0, jnp.float32)

        if self.min_dx is not None:
            cfl = max_u * jnp.asarray(dt, jnp.float32) \
                / jnp.asarray(self.min_dx, jnp.float32)
        else:
            cfl = jnp.asarray(0.0, jnp.float32)

        if self.divergence_fn is not None:
            div = jnp.max(jnp.abs(self.divergence_fn(state)))
            div = div.astype(jnp.float32)
        else:
            div = jnp.asarray(0.0, jnp.float32)

        if self.functional_fn is not None:
            func = jnp.asarray(self.functional_fn(state),
                               jnp.float32).reshape(())
        else:
            func = jnp.asarray(jnp.nan, jnp.float32)

        if self.volume_fn is not None:
            vol = jnp.asarray(self.volume_fn(state),
                              jnp.float32).reshape(())
        else:
            vol = jnp.asarray(jnp.nan, jnp.float32)

        if self.budget_fn is not None:
            budget = jnp.asarray(self.budget_fn(state),
                                 jnp.float32).reshape(())
        else:
            budget = jnp.asarray(jnp.nan, jnp.float32)

        return jnp.stack([finite, max_u, cfl, div, func, vol, budget])

    # -- host side -----------------------------------------------------------

    @staticmethod
    def unpack(vitals) -> dict:
        """Vector -> named dict. Tolerates shorter (older-schema)
        vectors: missing trailing slots read as NaN, so a v2 5-float
        vitals record still unpacks."""
        v = np.asarray(vitals, dtype=np.float64).reshape(-1)
        return {name: (float(v[i]) if i < v.size else float("nan"))
                for i, name in enumerate(VITALS_FIELDS)}

    def classify(self, vitals, step: int, dt: float):
        """Host-side triage of one chunk's vitals vector. Returns
        ``(level, reasons, vit_dict)`` with level in {OK, WARN, FATAL}
        and updates the WARN streak / functional baseline / history.
        A non-finite chunk is the caller's business (the driver raises
        plain :class:`SimulationDiverged` for it) and is reported FATAL
        here for completeness."""
        vit = self.unpack(vitals)
        reasons: List[str] = []
        level = OK

        def _flag(lvl, msg):
            nonlocal level
            reasons.append(msg)
            if lvl == FATAL or level == FATAL:
                level = FATAL
            else:
                level = WARN

        if vit["finite"] < 1.0:
            _flag(FATAL, "non-finite state leaves")

        for name, warn, fatal in (
                ("max_u", self.max_u_warn, self.max_u_fatal),
                ("cfl", self.cfl_warn, self.cfl_fatal),
                ("div_norm", self.div_warn, self.div_fatal)):
            val = vit[name]
            if fatal is not None and val > fatal:
                _flag(FATAL, f"{name}={val:.4g} > fatal {fatal:.4g}")
            elif warn is not None and val > warn:
                _flag(WARN, f"{name}={val:.4g} > warn {warn:.4g}")

        func = vit["func"]
        if math.isfinite(func):
            if self._baseline_func is None:
                self._baseline_func = func
            base = self._baseline_func
            scale = abs(base) if base != 0.0 else 1.0
            growth = abs(func) / scale
            vit["func_growth"] = growth
            if (self.func_growth_fatal is not None
                    and growth > self.func_growth_fatal):
                _flag(FATAL, f"functional grew {growth:.3g}x over "
                             f"baseline (fatal {self.func_growth_fatal:g}x)")
            elif (self.func_growth_warn is not None
                    and growth > self.func_growth_warn):
                _flag(WARN, f"functional grew {growth:.3g}x over "
                            f"baseline (warn {self.func_growth_warn:g}x)")
        elif self.functional_fn is not None and vit["finite"] >= 1.0:
            _flag(FATAL, "functional is non-finite")

        # invariant sentinels: relative drift over the run's own first
        # finite value — a secular leak fires long before any NaN
        for name, fn, base_attr, warn, fatal in (
                ("vol", self.volume_fn, "_baseline_vol",
                 self.vol_drift_warn, self.vol_drift_fatal),
                ("budget", self.budget_fn, "_baseline_budget",
                 self.budget_drift_warn, self.budget_drift_fatal)):
            val = vit[name]
            if math.isfinite(val):
                if getattr(self, base_attr) is None:
                    setattr(self, base_attr, val)
                base = getattr(self, base_attr)
                drift = abs(val - base) / max(abs(base), 1e-30)
                vit[f"{name}_drift"] = drift
                if fatal is not None and drift > fatal:
                    _flag(FATAL, f"{name} drifted {drift:.3g} from "
                                 f"baseline {base:.4g} (fatal {fatal:g})")
                elif warn is not None and drift > warn:
                    _flag(WARN, f"{name} drifted {drift:.3g} from "
                                f"baseline {base:.4g} (warn {warn:g})")
            elif fn is not None and vit["finite"] >= 1.0:
                _flag(FATAL, f"{name} sentinel is non-finite")

        self._warn_streak = self._warn_streak + 1 if level != OK else 0
        rec = dict(vit, step=int(step), dt=float(dt), level=level,
                   warn_streak=self._warn_streak, reasons=list(reasons))
        self.last = rec
        self.history.append(rec)
        return level, reasons, vit

    def check(self, vitals, step: int, dt: float) -> dict:
        """Classify and ESCALATE: raises :class:`HealthDegraded` on a
        FATAL chunk or once ``sustain`` consecutive chunks came back
        WARN. Returns the host-side vitals record otherwise. The WARN
        streak resets on raise, so a supervised retry starts from a
        clean slate (the functional baseline persists — the retry
        resumes the same trajectory)."""
        level, reasons, vit = self.classify(vitals, step, dt)
        fire = level == FATAL or (level == WARN
                                  and self._warn_streak >= self.sustain)
        if fire and vit["finite"] >= 1.0:
            self._warn_streak = 0
            raise HealthDegraded(step, reasons, vit)
        return self.last

    def reset(self):
        """Forget streaks AND every baseline (a new run)."""
        self._warn_streak = 0
        self._baseline_func = None
        self._baseline_vol = None
        self._baseline_budget = None
