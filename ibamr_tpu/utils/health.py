"""In-flight health vitals: detect degradation BEFORE the NaN (PR 3).

PR 2's recovery machinery only fires on the *loud* failure — a state
leaf that already went non-finite. The expensive silent failure is the
run that is still finite but already lost: velocity growing
exponentially, CFL creeping past stability, divergence error
compounding. By the time ``_finite_flag`` trips, the newest checkpoints
may already hold garbage-but-finite states and the supervisor pays a
full ``max_retries`` cycle to find a good one.

:class:`HealthProbe` closes that gap. The jit side (:meth:`measure`)
reduces the state to a small fixed vector of physics vitals INSIDE the
driver's scan chunk, so the per-chunk host cost stays exactly one small
device->host transfer (the same sync the old single finite bool paid —
pinned by ``HierarchyDriver.trace_counts``). The host side
(:meth:`classify` / :meth:`check`) applies thresholds, classifies the
chunk OK / WARN / FATAL, and raises :class:`HealthDegraded` — a
:class:`SimulationDiverged` *precursor* — on FATAL or on a sustained
WARN streak, while the state is still finite and the rollback is cheap.

Vitals vector schema (fixed order, ``VITALS_FIELDS``):

====  ============  =====================================================
idx   field         meaning
====  ============  =====================================================
0     ``finite``    1.0 iff every floating state leaf is all-finite
1     ``max_u``     max |u| over the velocity components (0 if no vel)
2     ``cfl``       realized advective CFL: max_u * dt / min(dx)
3     ``div_norm``  max |div u| (0 when no divergence functional given)
4     ``func``      caller-supplied energy/volume functional (NaN = none)
5     ``vol``       IB enclosed volume/area (``volume_fn``; NaN = none)
6     ``budget``    momentum/KE budget term (``budget_fn``; NaN = none)
====  ============  =====================================================

Slots 5–6 are the PR-5 physics-invariant sentinels: both are
conserved-to-drift quantities, so their triage is RELATIVE drift over
the run's own first finite value (``vol_drift_warn/fatal``,
``budget_drift_warn/fatal``) — a leaking membrane or a momentum budget
blowing up rolls back while every checkpoint is still healthy. They
ride the SAME fused vitals vector, so the per-chunk cost stays one
small device->host transfer (pinned via ``trace_counts``).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, List, Optional

import numpy as np

from ibamr_tpu.utils.hierarchy_driver import SimulationDiverged, _finite_flag

OK = "ok"
WARN = "warn"
FATAL = "fatal"

VITALS_FIELDS = ("finite", "max_u", "cfl", "div_norm", "func",
                 "vol", "budget")
QUARANTINED = "quarantined"


def _new_triage_ctx() -> dict:
    """One triage context: WARN streak + per-run baselines. The solo
    probe owns one; a fleet probe owns one PER LANE so a drifting lane
    cannot poison its neighbours' baselines."""
    return {"warn_streak": 0, "baseline_func": None,
            "baseline_vol": None, "baseline_budget": None}


class HealthDegraded(SimulationDiverged):
    """Precursor divergence: the state is still FINITE but the vitals
    crossed a FATAL threshold or sustained a WARN streak. Subclassing
    :class:`SimulationDiverged` means the whole PR-2 recovery machinery
    (``ResilientDriver`` rollback + dt backoff + incident record) fires
    unchanged — but with a cheap recovery, because every checkpoint on
    disk still predates any non-finite value."""

    kind = "health_degraded"

    def __init__(self, step: int, reasons, vitals: dict):
        self.step = step
        self.reasons = list(reasons)
        self.vitals = dict(vitals)
        self.bad_leaves: list = []      # nothing is non-finite (yet)
        RuntimeError.__init__(
            self,
            f"health degraded by step {step}: {'; '.join(self.reasons)} "
            f"(vitals {self.vitals}) — rolling back while the state is "
            f"still finite")

    def incident_payload(self) -> dict:
        return {"reasons": self.reasons, "vitals": self.vitals}


@dataclasses.dataclass
class HealthProbe:
    """Fused in-flight vitals probe + host-side triage.

    Jit side: :meth:`measure(state, dt)` returns a fixed float32 vector
    (``VITALS_FIELDS`` order) built from optional accessors — all must
    be jit-traceable functions of the state:

    - ``velocity_fn(state) -> tuple/list of arrays`` (default: the
      state's ``u`` attribute when present);
    - ``divergence_fn(state) -> array or scalar`` (max |.| is taken);
    - ``functional_fn(state) -> scalar`` — the caller's conserved-ish
      quantity (kinetic energy, phase volume, ...), the signal the
      growth triage watches.

    Host side: :meth:`check(vitals, step, dt)` classifies the chunk and
    raises :class:`HealthDegraded` on FATAL, or after ``sustain``
    consecutive WARN chunks. ``None`` thresholds are disabled. The
    functional baseline is the first finite functional value observed
    (reset only by :meth:`reset`), so "growth beyond a configured
    factor" means growth over the run's OWN starting value, not an
    absolute scale the caller would have to guess.
    """

    velocity_fn: Optional[Callable[[Any], Any]] = None
    divergence_fn: Optional[Callable[[Any], Any]] = None
    functional_fn: Optional[Callable[[Any], Any]] = None
    min_dx: Optional[float] = None       # needed for the CFL vital
    # thresholds (None = that check disabled)
    max_u_warn: Optional[float] = None
    max_u_fatal: Optional[float] = None
    cfl_warn: Optional[float] = None
    cfl_fatal: Optional[float] = None
    div_warn: Optional[float] = None
    div_fatal: Optional[float] = None
    func_growth_warn: Optional[float] = None    # factor over baseline
    func_growth_fatal: Optional[float] = None
    # PR-5 invariant sentinels (slots 5-6). Both are conserved-to-drift
    # quantities; thresholds are RELATIVE drift |v - v0| / max(|v0|, eps)
    # over the run's own first finite value.
    volume_fn: Optional[Callable[[Any], Any]] = None
    budget_fn: Optional[Callable[[Any], Any]] = None
    vol_drift_warn: Optional[float] = None
    vol_drift_fatal: Optional[float] = None
    budget_drift_warn: Optional[float] = None
    budget_drift_fatal: Optional[float] = None
    sustain: int = 3                     # WARN chunks before escalation

    VITALS_FIELDS = VITALS_FIELDS        # schema, importable off the class

    def __post_init__(self):
        if self.sustain < 1:
            raise ValueError("sustain must be >= 1 (a WARN streak of "
                             "zero chunks would fire immediately)")
        # triage state lives in per-context dicts so the SAME threshold
        # logic serves the solo run (one context, exposed through the
        # legacy attribute names below) and the fleet (one context per
        # lane: independent baselines and WARN streaks)
        self._solo_ctx = _new_triage_ctx()
        self._lane_ctx: Optional[List[dict]] = None
        self.history: List[dict] = []    # one record per classified chunk
        self.last: Optional[dict] = None
        self.last_lanes: Optional[List[dict]] = None

    # legacy attribute views of the solo triage context (tests and
    # callers poke these directly)
    @property
    def _warn_streak(self):
        return self._solo_ctx["warn_streak"]

    @_warn_streak.setter
    def _warn_streak(self, v):
        self._solo_ctx["warn_streak"] = v

    @property
    def _baseline_func(self):
        return self._solo_ctx["baseline_func"]

    @_baseline_func.setter
    def _baseline_func(self, v):
        self._solo_ctx["baseline_func"] = v

    @property
    def _baseline_vol(self):
        return self._solo_ctx["baseline_vol"]

    @_baseline_vol.setter
    def _baseline_vol(self, v):
        self._solo_ctx["baseline_vol"] = v

    @property
    def _baseline_budget(self):
        return self._solo_ctx["baseline_budget"]

    @_baseline_budget.setter
    def _baseline_budget(self, v):
        self._solo_ctx["baseline_budget"] = v

    # -- construction helpers ----------------------------------------------

    @classmethod
    def for_integrator(cls, integ, **kw) -> "HealthProbe":
        """Probe wired to the framework's integrator conventions: MAC
        velocity at ``state.u``, divergence via the shared stencils,
        kinetic energy as the default functional, a momentum-magnitude
        budget sentinel when a grid is available, and (for 2D IB
        integrators) the enclosed marker polygon area as the volume
        sentinel. Any explicit kwarg wins over the derived default."""
        import jax.numpy as jnp

        from ibamr_tpu.ops import stencils

        is_ib = (hasattr(integ, "ins") and hasattr(integ, "ib"))
        ins = getattr(integ, "ins", None) if is_ib else integ
        grid = getattr(ins, "grid", None)

        def uget(s):
            return s.ins.u if is_ib else s.u

        if grid is not None:
            kw.setdefault("min_dx", float(min(grid.dx)))
            dx = grid.dx
            kw.setdefault("velocity_fn", uget)
            kw.setdefault("divergence_fn",
                          lambda s: stencils.divergence(uget(s), dx))
            # momentum/KE budget: cell_vol * rho * |sum_cells u| — an
            # exactly conserved quantity of the periodic projected
            # equations, so its drift is pure scheme/precision error
            rho = float(getattr(ins, "rho", 1.0))
            cv = float(getattr(grid, "cell_volume", 1.0))

            def budget(s):
                comps = uget(s)
                mom = [jnp.sum(c) for c in comps]
                return cv * rho * jnp.sqrt(sum(m * m for m in mom))
            kw.setdefault("budget_fn", budget)
        if hasattr(integ, "kinetic_energy"):
            kw.setdefault("functional_fn", integ.kinetic_energy)
        elif ins is not None and hasattr(ins, "kinetic_energy"):
            kw.setdefault("functional_fn",
                          lambda s: ins.kinetic_energy(s.ins)
                          if is_ib else ins.kinetic_energy(s))
        if is_ib and grid is not None and len(grid.dx) == 2:
            from ibamr_tpu.integrators.ib import polygon_area
            kw.setdefault("volume_fn", lambda s: polygon_area(s.X))
        return cls(**kw)

    # -- jit side ------------------------------------------------------------

    def measure(self, state, dt):
        """Fixed-shape vitals vector (float32, ``len(VITALS_FIELDS)``);
        fully traceable.
        Meant to be called INSIDE the driver's jitted chunk so the whole
        reduction fuses with the step scan."""
        import jax.numpy as jnp

        finite = _finite_flag(state).astype(jnp.float32)

        vel = (self.velocity_fn(state) if self.velocity_fn is not None
               else getattr(state, "u", None))
        if vel is not None:
            comps = vel if isinstance(vel, (tuple, list)) else (vel,)
            max_u = jnp.asarray(0.0, jnp.float32)
            for c in comps:
                max_u = jnp.maximum(max_u,
                                    jnp.max(jnp.abs(c)).astype(jnp.float32))
        else:
            max_u = jnp.asarray(0.0, jnp.float32)

        if self.min_dx is not None:
            cfl = max_u * jnp.asarray(dt, jnp.float32) \
                / jnp.asarray(self.min_dx, jnp.float32)
        else:
            cfl = jnp.asarray(0.0, jnp.float32)

        if self.divergence_fn is not None:
            div = jnp.max(jnp.abs(self.divergence_fn(state)))
            div = div.astype(jnp.float32)
        else:
            div = jnp.asarray(0.0, jnp.float32)

        if self.functional_fn is not None:
            func = jnp.asarray(self.functional_fn(state),
                               jnp.float32).reshape(())
        else:
            func = jnp.asarray(jnp.nan, jnp.float32)

        if self.volume_fn is not None:
            vol = jnp.asarray(self.volume_fn(state),
                              jnp.float32).reshape(())
        else:
            vol = jnp.asarray(jnp.nan, jnp.float32)

        if self.budget_fn is not None:
            budget = jnp.asarray(self.budget_fn(state),
                                 jnp.float32).reshape(())
        else:
            budget = jnp.asarray(jnp.nan, jnp.float32)

        return jnp.stack([finite, max_u, cfl, div, func, vol, budget])

    # -- host side -----------------------------------------------------------

    @staticmethod
    def unpack(vitals) -> dict:
        """Vector -> named dict. Tolerates shorter (older-schema)
        vectors: missing trailing slots read as NaN, so a v2 5-float
        vitals record still unpacks. A lane-batched (7, B) matrix
        unpacks to per-field (B,) arrays — one column per lane —
        without disturbing the rank-1 paths."""
        v = np.asarray(vitals, dtype=np.float64)
        if v.ndim == 2:
            B = v.shape[1]
            return {name: (v[i].copy() if i < v.shape[0]
                           else np.full(B, np.nan))
                    for i, name in enumerate(VITALS_FIELDS)}
        v = v.reshape(-1)
        return {name: (float(v[i]) if i < v.size else float("nan"))
                for i, name in enumerate(VITALS_FIELDS)}

    def classify(self, vitals, step: int, dt: float):
        """Host-side triage of one chunk's vitals vector. Returns
        ``(level, reasons, vit_dict)`` with level in {OK, WARN, FATAL}
        and updates the WARN streak / functional baseline / history.
        A non-finite chunk is the caller's business (the driver raises
        plain :class:`SimulationDiverged` for it) and is reported FATAL
        here for completeness."""
        vit = self.unpack(vitals)
        level, reasons = self._triage(vit, self._solo_ctx)
        self._warn_streak = self._warn_streak + 1 if level != OK else 0
        rec = dict(vit, step=int(step), dt=float(dt), level=level,
                   warn_streak=self._warn_streak, reasons=list(reasons))
        self.last = rec
        self.history.append(rec)
        return level, reasons, vit

    def _triage(self, vit: dict, ctx: dict):
        """Threshold logic over one unpacked vitals dict against one
        triage context (baselines mutate in place). Streak accounting
        belongs to the caller — solo and per-lane policies differ."""
        reasons: List[str] = []
        level = OK

        def _flag(lvl, msg):
            nonlocal level
            reasons.append(msg)
            if lvl == FATAL or level == FATAL:
                level = FATAL
            else:
                level = WARN

        if vit["finite"] < 1.0:
            _flag(FATAL, "non-finite state leaves")

        for name, warn, fatal in (
                ("max_u", self.max_u_warn, self.max_u_fatal),
                ("cfl", self.cfl_warn, self.cfl_fatal),
                ("div_norm", self.div_warn, self.div_fatal)):
            val = vit[name]
            if fatal is not None and val > fatal:
                _flag(FATAL, f"{name}={val:.4g} > fatal {fatal:.4g}")
            elif warn is not None and val > warn:
                _flag(WARN, f"{name}={val:.4g} > warn {warn:.4g}")

        func = vit["func"]
        if math.isfinite(func):
            if ctx["baseline_func"] is None:
                ctx["baseline_func"] = func
            base = ctx["baseline_func"]
            scale = abs(base) if base != 0.0 else 1.0
            growth = abs(func) / scale
            vit["func_growth"] = growth
            if (self.func_growth_fatal is not None
                    and growth > self.func_growth_fatal):
                _flag(FATAL, f"functional grew {growth:.3g}x over "
                             f"baseline (fatal {self.func_growth_fatal:g}x)")
            elif (self.func_growth_warn is not None
                    and growth > self.func_growth_warn):
                _flag(WARN, f"functional grew {growth:.3g}x over "
                            f"baseline (warn {self.func_growth_warn:g}x)")
        elif self.functional_fn is not None and vit["finite"] >= 1.0:
            _flag(FATAL, "functional is non-finite")

        # invariant sentinels: relative drift over the run's own first
        # finite value — a secular leak fires long before any NaN
        for name, fn, base_key, warn, fatal in (
                ("vol", self.volume_fn, "baseline_vol",
                 self.vol_drift_warn, self.vol_drift_fatal),
                ("budget", self.budget_fn, "baseline_budget",
                 self.budget_drift_warn, self.budget_drift_fatal)):
            val = vit[name]
            if math.isfinite(val):
                if ctx[base_key] is None:
                    ctx[base_key] = val
                base = ctx[base_key]
                drift = abs(val - base) / max(abs(base), 1e-30)
                vit[f"{name}_drift"] = drift
                if fatal is not None and drift > fatal:
                    _flag(FATAL, f"{name} drifted {drift:.3g} from "
                                 f"baseline {base:.4g} (fatal {fatal:g})")
                elif warn is not None and drift > warn:
                    _flag(WARN, f"{name} drifted {drift:.3g} from "
                                f"baseline {base:.4g} (warn {warn:g})")
            elif fn is not None and vit["finite"] >= 1.0:
                _flag(FATAL, f"{name} sentinel is non-finite")

        return level, reasons

    def check_lanes(self, vitals, step: int, dt, alive=None) -> List[dict]:
        """Per-lane triage of a fleet chunk's (7, B) vitals matrix.

        Unlike :meth:`check` this NEVER raises — returning lane
        verdicts is the whole point of fleet triage (one bad lane must
        not abort B-1 healthy ones). Each live lane is triaged against
        its OWN context (independent baselines + WARN streaks); the
        record's ``fire`` bool is the per-lane equivalent of
        :meth:`check`'s raise (FATAL, or a sustained WARN streak, while
        the lane is still finite). Dead lanes (``alive[b]`` false) are
        skipped with level ``quarantined`` — their frozen rows are the
        last good state, not a new fault. The driver converts fired
        lanes into a :class:`~ibamr_tpu.utils.hierarchy_driver
        .LaneFault` for the supervisor."""
        v = np.asarray(vitals, dtype=np.float64)
        if v.ndim != 2:
            raise ValueError(
                f"check_lanes expects a (len(VITALS_FIELDS), B) vitals "
                f"matrix, got shape {v.shape}")
        B = v.shape[1]
        if self._lane_ctx is None or len(self._lane_ctx) != B:
            self._lane_ctx = [_new_triage_ctx() for _ in range(B)]
        dtv = np.asarray(dt, dtype=np.float64).reshape(-1)
        if dtv.size == 1 and B > 1:
            dtv = np.full(B, float(dtv[0]))
        out: List[dict] = []
        for b in range(B):
            if alive is not None and not bool(alive[b]):
                out.append({"lane": b, "step": int(step),
                            "level": QUARANTINED, "fire": False,
                            "reasons": [], "warn_streak": 0})
                continue
            vit = self.unpack(v[:, b])
            ctx = self._lane_ctx[b]
            level, reasons = self._triage(vit, ctx)
            ctx["warn_streak"] = (ctx["warn_streak"] + 1
                                  if level != OK else 0)
            fire = (level == FATAL
                    or (level == WARN
                        and ctx["warn_streak"] >= self.sustain))
            fire = bool(fire and vit["finite"] >= 1.0)
            if fire:
                # mirror check(): a fired lane restarts its streak so
                # a supervised retry starts from a clean slate
                ctx["warn_streak"] = 0
            out.append(dict(vit, lane=b, step=int(step),
                            dt=float(dtv[b]), level=level,
                            warn_streak=ctx["warn_streak"],
                            reasons=list(reasons), fire=fire))
        self.last_lanes = out
        self.history.append({"step": int(step), "fleet": True,
                             "lanes": [{"lane": r["lane"],
                                        "level": r["level"],
                                        "fire": r.get("fire", False)}
                                       for r in out]})
        return out

    def reset_lane(self, lane: int):
        """Fresh triage context for one lane (after a per-lane rollback
        or quarantine restore): the restored slice re-baselines."""
        if self._lane_ctx is not None and 0 <= lane < len(self._lane_ctx):
            self._lane_ctx[lane] = _new_triage_ctx()

    def check(self, vitals, step: int, dt: float) -> dict:
        """Classify and ESCALATE: raises :class:`HealthDegraded` on a
        FATAL chunk or once ``sustain`` consecutive chunks came back
        WARN. Returns the host-side vitals record otherwise. The WARN
        streak resets on raise, so a supervised retry starts from a
        clean slate (the functional baseline persists — the retry
        resumes the same trajectory)."""
        level, reasons, vit = self.classify(vitals, step, dt)
        fire = level == FATAL or (level == WARN
                                  and self._warn_streak >= self.sustain)
        if fire and vit["finite"] >= 1.0:
            self._warn_streak = 0
            raise HealthDegraded(step, reasons, vit)
        return self.last

    def reset(self):
        """Forget streaks AND every baseline (a new run)."""
        self._solo_ctx = _new_triage_ctx()
        self._lane_ctx = None

    def rebaseline(self):
        """Drop the drift anchors (functional / volume / budget
        baselines) while KEEPING warn streaks — for a legitimate
        discontinuous state move, e.g. an assimilation analysis that
        updates every lane between chunks. The next vitals sample
        re-anchors each baseline; without this the first post-analysis
        chunk reads the innovation jump as func/vol/budget drift and
        false-positives a WARN. Streaks survive on purpose: a lane
        that was already trending bad must not get its strikes wiped
        by every analysis."""
        ctxs = [self._solo_ctx] + list(self._lane_ctx or [])
        for ctx in ctxs:
            ctx["baseline_func"] = None
            ctx["baseline_vol"] = None
            ctx["baseline_budget"] = None
