"""Analytic grid functions: expression strings evaluated on grid coordinates.

Reference parity: ``muParserCartGridFunction`` / ``CartGridFunction`` (T12,
SURVEY.md §2.1) — runtime-parsed math expressions from input files, with the
grid coordinates ``X_0, X_1[, X_2]`` and time ``t`` as variables, used for
initial conditions, boundary data, and body forces.

TPU-first design: the expression is compiled once into a jax-traceable
callable over ``jnp`` ufuncs, so evaluating it inside a jitted step is free
of Python overhead and fuses with downstream ops.
"""

from __future__ import annotations

import ast
import math
from typing import Callable, Dict, Sequence

import jax.numpy as jnp

_ALLOWED_FUNCS = {
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan, "asin": jnp.arcsin,
    "acos": jnp.arccos, "atan": jnp.arctan, "atan2": jnp.arctan2,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "exp": jnp.exp, "log": jnp.log, "log10": jnp.log10, "sqrt": jnp.sqrt,
    "abs": jnp.abs, "floor": jnp.floor, "ceil": jnp.ceil, "pow": jnp.power,
    "min": jnp.minimum, "max": jnp.maximum, "sign": jnp.sign,
    "heaviside": lambda x: jnp.where(x >= 0, 1.0, 0.0),
    "where": jnp.where, "logical_and": jnp.logical_and,
    "logical_or": jnp.logical_or,
}
_ALLOWED_CONSTS = {"PI": math.pi, "pi": math.pi, "E": math.e}


class _Validator(ast.NodeVisitor):
    """Whitelist validator: names, numeric constants, arithmetic, calls to
    whitelisted functions, comparisons, conditional expressions."""

    def __init__(self, varnames):
        self.varnames = set(varnames)

    def visit_Expression(self, node):
        self.visit(node.body)

    def visit_Constant(self, node):
        if not isinstance(node.value, (int, float)):
            raise ValueError(f"bad constant {node.value!r}")

    def visit_Name(self, node):
        if node.id not in self.varnames and node.id not in _ALLOWED_CONSTS \
                and node.id not in _ALLOWED_FUNCS:
            raise ValueError(f"unknown name {node.id!r} in grid function")

    def visit_Call(self, node):
        if not isinstance(node.func, ast.Name) or node.func.id not in _ALLOWED_FUNCS:
            raise ValueError("only whitelisted function calls allowed")
        if node.keywords:
            raise ValueError("keyword arguments not allowed in grid functions")
        for a in node.args:
            if isinstance(a, ast.Starred):
                raise ValueError("star-args not allowed in grid functions")
            self.visit(a)

    def visit_BinOp(self, node):
        if not isinstance(node.op, (ast.Add, ast.Sub, ast.Mult, ast.Div,
                                    ast.Pow, ast.Mod, ast.FloorDiv)):
            raise ValueError("disallowed operator")
        self.visit(node.left)
        self.visit(node.right)

    def visit_UnaryOp(self, node):
        if not isinstance(node.op, (ast.UAdd, ast.USub)):
            raise ValueError("disallowed unary operator")
        self.visit(node.operand)

    def visit_IfExp(self, node):
        self.visit(node.test)
        self.visit(node.body)
        self.visit(node.orelse)

    def visit_Compare(self, node):
        self.visit(node.left)
        for c in node.comparators:
            self.visit(c)

    def visit_BoolOp(self, node):
        for v in node.values:
            self.visit(v)

    def generic_visit(self, node):
        if isinstance(node, (ast.Expression, ast.Load, ast.cmpop, ast.boolop,
                             ast.operator, ast.unaryop)):
            super().generic_visit(node)
        elif isinstance(node, (ast.Constant, ast.Name, ast.Call, ast.BinOp,
                               ast.UnaryOp, ast.IfExp, ast.Compare, ast.BoolOp)):
            super().generic_visit(node)
        else:
            raise ValueError(f"disallowed syntax: {type(node).__name__}")


def _normalize(expr: str) -> str:
    # muParser uses ^ for power; python uses **.
    return expr.replace("^", "**")


class _ArraySemantics(ast.NodeTransformer):
    """Rewrite scalar-style conditionals to array ops so piecewise
    expressions (the main use of conditionals in reference input files)
    work on grid arrays: ``a if c else b`` -> ``where(c, a, b)``;
    ``and``/``or`` -> ``logical_and``/``logical_or``."""

    def visit_IfExp(self, node):
        self.generic_visit(node)
        return ast.copy_location(
            ast.Call(func=ast.Name(id="where", ctx=ast.Load()),
                     args=[node.test, node.body, node.orelse], keywords=[]),
            node)

    def visit_BoolOp(self, node):
        self.generic_visit(node)
        fname = "logical_and" if isinstance(node.op, ast.And) else "logical_or"
        out = node.values[0]
        for v in node.values[1:]:
            out = ast.Call(func=ast.Name(id=fname, ctx=ast.Load()),
                           args=[out, v], keywords=[])
        return ast.copy_location(out, node)


class CartGridFunction:
    """A compiled analytic function f(X_0,...,X_{d-1}, t) -> array.

    >>> f = CartGridFunction("sin(2*PI*X_0)*cos(2*PI*X_1)", dim=2)
    >>> f((x, y), t=0.0)
    """

    def __init__(self, expr: str, dim: int):
        self.expr = expr
        self.dim = dim
        varnames = [f"X_{i}" for i in range(dim)] + ["t"] + ["X", "Y", "Z"][:dim]
        src = _normalize(expr)
        tree = ast.parse(src, mode="eval")
        _Validator(varnames).visit(tree)
        tree = ast.fix_missing_locations(_ArraySemantics().visit(tree))
        code = compile(tree, f"<gridfunction:{expr}>", "eval")
        env: Dict[str, object] = dict(_ALLOWED_FUNCS)
        env.update(_ALLOWED_CONSTS)
        self._code, self._env = code, env

    def __call__(self, coords: Sequence[jnp.ndarray], t: float = 0.0) -> jnp.ndarray:
        local: Dict[str, object] = {"t": t}
        for i, c in enumerate(coords):
            local[f"X_{i}"] = c
        # convenience aliases
        alias = ["X", "Y", "Z"]
        for i, c in enumerate(coords[: len(alias)]):
            local[alias[i]] = c
        out = eval(self._code, {"__builtins__": {}, **self._env}, local)
        return jnp.asarray(out)


def function_from_db(db, dim: int, key_prefix: str = "function") -> Callable:
    """Build a vector-valued grid function from a sub-database with keys
    ``function_0 .. function_{d-1}`` (the reference's convention) or a single
    ``function`` key for scalars. Returns f(coords, t) -> list of arrays or array."""
    if f"{key_prefix}_0" in db:
        comps = []
        i = 0
        while f"{key_prefix}_{i}" in db:
            comps.append(CartGridFunction(db.get_string(f"{key_prefix}_{i}"), dim))
            i += 1
        return lambda coords, t=0.0: [c(coords, t) for c in comps]
    expr = db.get_string(key_prefix)
    f = CartGridFunction(expr, dim)
    return lambda coords, t=0.0: f(coords, t)
