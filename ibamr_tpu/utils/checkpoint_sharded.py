"""Sharded fault-tolerant checkpoints: per-shard files + a manifest
commit marker, elastic N->M restore (PR 6 tentpole).

Reference parity: SAMRAI's per-processor restart databases — no rank
ever gathers the global state (SURVEY.md §5.4). The single-host format
(``utils/checkpoint.py``) funnels every leaf through a full host gather
before one process writes one npz; at pod scale that gather neither
fits one host nor belongs on the step's critical path. This module
writes each device's slice separately and extends the PR-2 verified-
commit discipline to the distributed layout:

- :func:`save_sharded_checkpoint` writes one
  ``sharded.<step>/shard-<i>.npz`` per shard — each holding only the
  slices that shard OWNS (replica 0 of each distinct chunk; a device's
  transfer is its own slice, never the global array) — then a single
  ``manifest.json``, written atomically LAST, exactly the PR-2
  sidecar-as-commit-marker pattern. The manifest records the mesh
  spec, the per-leaf sharding layout (which shard owns which index
  range), the state schema, per-chunk CRC32s, and every shard file's
  whole-file CRC32 + byte size. A kill at ANY instant leaves either no
  manifest (the step never committed) or a manifest whose digests
  expose any missing/torn/stale shard.
- :func:`verify_sharded_checkpoint` / :func:`latest_sharded_step`
  skip torn, missing-shard, or CRC-mismatched steps — the distributed
  analog of ``verify_checkpoint``/``latest_step``.
- :func:`restore_sharded` reassembles (or re-shards) a checkpoint
  written on N devices onto whatever the template dictates — an
  M-device mesh, a single device, or plain host arrays — via the
  layout recorded in the manifest (N->1, 1->M, N->M). Chunk assembly
  is pure memcpy, so a same-mesh restore is bitwise and an elastic
  restore matches the gather-restore oracle bitwise (pinned by
  tests/test_checkpoint_sharded.py).
- :class:`AsyncShardedWriter` snapshots per-shard device buffers
  synchronously (donation-safe, still no global gather) and writes the
  shard files CONCURRENTLY on worker threads behind a bounded queue
  with backpressure — the gather leaves the step's critical path
  entirely (ROADMAP item 4).

Layout::

    <dir>/sharded.<step:08d>/shard-0000.npz   # shard 0's slices
    <dir>/sharded.<step:08d>/shard-0001.npz
    ...
    <dir>/sharded.<step:08d>/manifest.json    # commit marker, LAST

Failure drills for every mode this module claims to survive
(kill-one-writer-mid-commit, single-shard corruption/drop, torn
manifest, stale-manifest-newer-shards, concurrent-writer collision)
live in ``tools/fault_injection.py`` (``run_sharded_smoke``) and
``tests/test_checkpoint_sharded.py``.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ibamr_tpu.utils.checkpoint import (CheckpointCorruptError,
                                        _atomic_write,
                                        _atomic_write_digest, _file_crc,
                                        _fsync_dir, _leaf_crc, _path_str,
                                        _schema_diff, state_schema)

SHARDED_SCHEMA = 1

# deterministic commit-window widener for the kill-mid-commit drills:
# sleep this many seconds between the last shard write and the manifest
# write, so a SIGKILL lands reliably inside the uncommitted window
_COMMIT_DELAY_ENV = "IBAMR_SHARDED_COMMIT_DELAY_S"


def _step_dir(directory: str, step: int) -> str:
    return os.path.join(directory, f"sharded.{step:08d}")


def _shard_name(i: int) -> str:
    return f"shard-{i:04d}.npz"


def _fetch_shard(data) -> np.ndarray:
    """Host copy of ONE device shard (``jax.Shard.data`` or any
    array-like). Module-level so the no-global-gather test pin can
    intercept every device->host transfer the save path makes and
    assert each one is shard-sized, never the global array."""
    return np.asarray(data)


def _is_jax_array(leaf) -> bool:
    return (hasattr(leaf, "addressable_shards")
            and hasattr(leaf, "sharding"))


def _plan_shards(state):
    """(devices, leaves_meta, per_shard_arrays) for a state pytree.

    ``devices``: the ordered device list defining shard indices (sorted
    by device id — stable across processes of the same mesh).
    ``leaves_meta``: path -> {shape, dtype, chunks:[{shard, index,
    crc32}]}; every distinct index range of a leaf is owned by exactly
    ONE shard (replica 0), so replicated leaves/axes are stored once.
    ``per_shard_arrays``: shard index -> {path: host slice}.
    """
    import jax

    flat = jax.tree_util.tree_flatten_with_path(state)[0]
    dev_ids: List[int] = []
    for _, leaf in flat:
        if _is_jax_array(leaf):
            for d in leaf.sharding.device_set:
                if d.id not in dev_ids:
                    dev_ids.append(d.id)
    dev_ids.sort()
    shard_of_dev = {d: i for i, d in enumerate(dev_ids)}
    n_shards = max(1, len(dev_ids))

    leaves_meta: Dict[str, Any] = {}
    per_shard: Dict[int, Dict[str, np.ndarray]] = {}

    def own(shard_i: int, key: str, arr: np.ndarray, index):
        per_shard.setdefault(shard_i, {})[key] = arr
        leaves_meta[key]["chunks"].append({
            "shard": shard_i,
            "index": index,
            "crc32": _leaf_crc(arr),
        })

    for path, leaf in flat:
        key = _path_str(path)
        arr_like = leaf if _is_jax_array(leaf) else np.asarray(leaf)
        leaves_meta[key] = {
            "shape": [int(s) for s in np.shape(arr_like)],
            "dtype": str(getattr(arr_like, "dtype",
                                 np.asarray(arr_like).dtype)),
            "chunks": [],
        }
        if not _is_jax_array(leaf):
            # host/numpy leaf: replicated by construction, shard 0 owns
            own(0, key, np.asarray(leaf), _full_index(np.shape(leaf)))
            continue
        seen_indices = set()
        for sh in sorted(leaf.addressable_shards,
                         key=lambda s: shard_of_dev[s.device.id]):
            index = _index_to_json(sh.index, leaf.shape)
            ikey = json.dumps(index)
            if ikey in seen_indices:
                continue              # a replica of a chunk we own
            seen_indices.add(ikey)
            own(shard_of_dev[sh.device.id], key,
                _fetch_shard(sh.data), index)
    return dev_ids, n_shards, leaves_meta, per_shard


def _full_index(shape):
    return [[0, int(s)] for s in shape]


def _index_to_json(index, shape):
    """jax ``Shard.index`` (tuple of slices) -> [[lo, hi], ...]."""
    out = []
    for sl, dim in zip(index, shape):
        lo = 0 if sl.start is None else int(sl.start)
        hi = int(dim) if sl.stop is None else int(sl.stop)
        out.append([lo, hi])
    # scalar arrays have an empty index tuple
    return out


def _mesh_spec(mesh=None, dev_ids=None, n_shards=1) -> dict:
    if mesh is not None:
        return {"shape": [int(s) for s in mesh.devices.shape],
                "axis_names": [str(a) for a in mesh.axis_names],
                "n_shards": int(np.prod(mesh.devices.shape))}
    return {"shape": [int(n_shards)], "axis_names": None,
            "n_shards": int(n_shards)}


def save_sharded_checkpoint(directory: str, state: Any, step: int,
                            metadata: Optional[Dict[str, Any]] = None,
                            keep: int = 3, mesh=None) -> str:
    """Write one checkpoint of ``state`` in the sharded layout.

    Each shard file holds only the slices its device owns — the save
    path never materializes the global state on the host (pinned by
    the no-gather test). The manifest is written atomically LAST and
    is the commit marker: a step without a parseable manifest never
    committed. Returns the step directory."""
    dev_ids, n_shards, leaves_meta, per_shard = _plan_shards(state)
    return _write_shards(directory, step, n_shards, leaves_meta,
                         per_shard, state_schema(state), metadata,
                         keep, mesh=mesh, dev_ids=dev_ids)


def _write_shards(directory: str, step: int, n_shards: int,
                  leaves_meta: dict, per_shard: dict, schema: dict,
                  metadata: Optional[dict], keep: int, mesh=None,
                  dev_ids=None) -> str:
    sdir = _step_dir(directory, step)
    os.makedirs(sdir, exist_ok=True)
    shards_meta: Dict[str, Any] = {}
    for i in range(n_shards):
        arrays = per_shard.get(i, {})
        fname = os.path.join(sdir, _shard_name(i))
        # digest comes from the temp file, pre-replace: re-reading the
        # published path would record a concurrent writer's bytes under
        # THIS writer's manifest (whole-file CRC passes verification,
        # per-chunk CRCs then fail on restore)
        crc, size = _atomic_write_digest(
            fname, lambda f, a=arrays: np.savez(f, **a))
        shards_meta[_shard_name(i)] = {"crc32": crc, "size": size}
    delay = float(os.environ.get(_COMMIT_DELAY_ENV, "0") or 0)
    if delay > 0:
        time.sleep(delay)
    manifest = {
        "sharded_schema": SHARDED_SCHEMA,
        "step": int(step),
        "mesh": _mesh_spec(mesh, dev_ids, n_shards),
        "schema": schema,
        "leaves": leaves_meta,
        "shards": shards_meta,
        "metadata": dict(metadata or {}),
        "time": time.time(),
    }
    payload = json.dumps(manifest).encode()
    _atomic_write(os.path.join(sdir, "manifest.json"),
                  lambda f: f.write(payload))
    _fsync_dir(directory)
    _prune_sharded(directory, keep)
    return sdir


def read_manifest(directory: str, step: int) -> Optional[dict]:
    """Parse a step's manifest; None when absent or torn (invalid
    JSON) — exactly what an uncommitted or killed-mid-commit step
    looks like."""
    path = os.path.join(_step_dir(directory, step), "manifest.json")
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def verify_sharded_checkpoint(directory: str, step: int) -> bool:
    """True iff the step committed and every shard matches its
    manifest digest: the manifest parses, names this step, and each
    shard file exists with the recorded byte size and whole-file
    CRC32. Catches torn manifests, missing/truncated shards, bitrot,
    and stale-manifest-newer-shards (a shard rewritten after the
    commit no longer matches its recorded digest)."""
    manifest = read_manifest(directory, step)
    if manifest is None or manifest.get("step") != step:
        return False
    sdir = _step_dir(directory, step)
    shards = manifest.get("shards")
    if not isinstance(shards, dict):
        return False
    for name, rec in shards.items():
        path = os.path.join(sdir, name)
        try:
            if os.path.getsize(path) != rec.get("size"):
                return False
            if _file_crc(path) != rec.get("crc32"):
                return False
        except OSError:
            return False
    return True


def _all_sharded_steps(directory: str) -> list:
    steps = []
    if not os.path.isdir(directory):
        return steps
    for f in os.listdir(directory):
        m = re.fullmatch(r"sharded\.(\d+)", f)
        if m and os.path.isdir(os.path.join(directory, f)):
            steps.append(int(m.group(1)))
    return sorted(steps)


def latest_sharded_step(directory: str,
                        verified_only: bool = True) -> Optional[int]:
    """Newest restorable sharded step; with ``verified_only`` (the
    default) torn/corrupt/uncommitted steps are skipped."""
    steps = _all_sharded_steps(directory)
    if not verified_only:
        return steps[-1] if steps else None
    return next((s for s in reversed(steps)
                 if verify_sharded_checkpoint(directory, s)), None)


def _prune_sharded(directory: str, keep: int) -> None:
    if keep <= 0:
        return
    steps = _all_sharded_steps(directory)
    doomed = steps[:-keep]
    if not doomed:
        return
    # same contract as the single-host pruner: the newest VERIFIED
    # step is sacrosanct — prune must never shorten the recovery
    # chain to zero
    last_verified = next((s for s in reversed(steps)
                          if verify_sharded_checkpoint(directory, s)),
                         None)
    for s in doomed:
        if s == last_verified:
            continue
        shutil.rmtree(_step_dir(directory, s), ignore_errors=True)


def _assemble_leaf(sdir: str, key: str, meta: dict, shard_files: dict):
    """Reassemble one global host array from its manifest chunks,
    CRC-checking every loaded slice (the array file and the manifest
    must agree down to the chunk)."""
    shape = tuple(meta["shape"])
    chunks = meta["chunks"]
    if not chunks:
        raise CheckpointCorruptError(
            f"sharded checkpoint {sdir}: leaf {key!r} has no chunks "
            f"in the manifest")
    out = None
    for ch in chunks:
        name = _shard_name(int(ch["shard"]))
        if name not in shard_files:
            shard_files[name] = np.load(os.path.join(sdir, name))
        z = shard_files[name]
        if key not in z:
            raise CheckpointCorruptError(
                f"sharded checkpoint {sdir}: shard {name} is missing "
                f"leaf {key!r} recorded in the manifest")
        arr = z[key]
        if _leaf_crc(arr) != ch["crc32"]:
            raise CheckpointCorruptError(
                f"sharded checkpoint {sdir}: leaf {key!r} chunk in "
                f"{name} fails its recorded CRC32 — shard file and "
                f"manifest disagree")
        index = ch["index"]
        if [list(map(int, ij)) for ij in index] == _full_index(shape):
            return arr                 # whole-array chunk (replicated)
        if out is None:
            out = np.empty(shape, dtype=arr.dtype)
        out[tuple(slice(lo, hi) for lo, hi in index)] = arr
    if out is None:
        raise CheckpointCorruptError(
            f"sharded checkpoint {sdir}: leaf {key!r} chunks do not "
            f"cover the array")
    return out


def restore_sharded(directory: str, template: Any,
                    step: Optional[int] = None, sharding_fn=None):
    """Restore a state pytree from the sharded layout — elastically.

    ``template`` supplies structure, dtype, and the TARGET placement:
    a leaf carrying a ``.sharding`` (a state built/placed on the
    resuming mesh) is re-sharded onto it via ``jax.device_put``; plain
    numpy template leaves restore to host arrays. The manifest's
    recorded layout says where every index range lives, so a
    checkpoint written on N devices restores onto M devices for any
    N, M >= 1 (N->1, 1->M, N->M) — assembly is memcpy, so a same-mesh
    restore is bitwise. ``sharding_fn(path_str, np_array)`` overrides
    placement per leaf when given.

    ``step=None`` restores the newest VERIFIED step, warning and
    falling back through older steps on corruption; an explicit
    ``step`` raises :class:`CheckpointCorruptError` when that step
    fails verification. Returns (state, step, manifest)."""
    if step is not None:
        if not os.path.isdir(_step_dir(directory, step)):
            raise FileNotFoundError(_step_dir(directory, step))
        if not verify_sharded_checkpoint(directory, step):
            raise CheckpointCorruptError(
                f"sharded checkpoint {_step_dir(directory, step)} "
                f"failed integrity verification (torn manifest, "
                f"missing shard, or digest mismatch)")
        return _load_sharded_step(directory, step, template, sharding_fn)

    steps = _all_sharded_steps(directory)
    if not steps:
        raise FileNotFoundError(f"no sharded checkpoints in {directory}")
    import warnings

    for s in reversed(steps):
        if not verify_sharded_checkpoint(directory, s):
            warnings.warn(
                f"skipping unverified sharded checkpoint step {s} in "
                f"{directory} (torn manifest, missing shard, or digest "
                f"mismatch — a kill mid-commit leaves exactly this)")
            continue
        try:
            return _load_sharded_step(directory, s, template,
                                      sharding_fn)
        except CheckpointCorruptError as e:
            warnings.warn(f"skipping sharded checkpoint step {s}: {e}")
    raise FileNotFoundError(
        f"no verified sharded checkpoints in {directory} "
        f"({len(steps)} candidate(s), all torn or corrupt)")


def _load_sharded_step(directory: str, step: int, template: Any,
                       sharding_fn):
    import jax

    sdir = _step_dir(directory, step)
    manifest = read_manifest(directory, step)
    leaves_meta = manifest["leaves"]

    paths_and_leaves, treedef = \
        jax.tree_util.tree_flatten_with_path(template)
    stored_schema = manifest.get("schema")
    if stored_schema is not None:
        diff = _schema_diff(stored_schema, state_schema(template))
        if diff:
            raise ValueError(
                f"sharded checkpoint {sdir} was written with an "
                f"incompatible state schema (version "
                f"{stored_schema.get('version', '?')}):\n{diff}")

    shard_files: Dict[str, Any] = {}
    try:
        new_leaves = []
        for path, leaf in paths_and_leaves:
            key = _path_str(path)
            if key not in leaves_meta:
                raise KeyError(
                    f"sharded checkpoint {sdir} missing leaf {key!r}")
            arr = _assemble_leaf(sdir, key, leaves_meta[key],
                                 shard_files)
            tgt_dtype = getattr(leaf, "dtype", None)
            if tgt_dtype is not None and arr.dtype != tgt_dtype:
                arr = arr.astype(tgt_dtype)
            if sharding_fn is not None:
                new_leaves.append(sharding_fn(key, arr))
            elif hasattr(leaf, "sharding"):
                new_leaves.append(jax.device_put(arr, leaf.sharding))
            else:
                new_leaves.append(arr)
    finally:
        for z in shard_files.values():
            z.close()
    state = jax.tree_util.tree_unflatten(treedef, new_leaves)
    return state, step, manifest


class AsyncShardedWriter:
    """Asynchronous sharded checkpoint writes, gather-free and off the
    critical path (ROADMAP item 4's distributed-I/O half).

    ``save`` snapshots each device shard to host SYNCHRONOUSLY (the
    per-shard HBM->host DMA — donation-safe, and still never the
    global array), then hands the write job to a worker. Shard files
    are written CONCURRENTLY over ``shard_workers`` threads; the
    manifest lands strictly after every shard of its step (the commit
    marker ordering is preserved per step, and steps commit in save
    order — one committer thread).

    The pending queue is BOUNDED (``max_pending`` snapshots in
    flight): an unbounded burst of ``save`` calls would queue
    arbitrary host memory. ``overflow="block"`` (default) applies
    backpressure — ``save`` waits for the oldest write to land;
    ``overflow="drop"`` sheds the NEW save instead, counting it in
    ``dropped_saves`` (checkpoints are periodic; dropping one costs an
    interval, not correctness). ``queue_depth()`` is surfaced in the
    watchdog heartbeat by :class:`~ibamr_tpu.utils.supervisor
    .ResilientDriver`.
    """

    def __init__(self, directory: str, keep: int = 3,
                 max_pending: int = 2, overflow: str = "block",
                 shard_workers: int = 4, mesh=None):
        from concurrent.futures import ThreadPoolExecutor

        if max_pending < 1:
            raise ValueError("max_pending must be >= 1")
        if overflow not in ("block", "drop"):
            raise ValueError("overflow must be 'block' or 'drop'")
        self.directory = directory
        self.keep = keep
        self.max_pending = max_pending
        self.overflow = overflow
        self.mesh = mesh
        self.dropped_saves = 0
        self._commit = ThreadPoolExecutor(max_workers=1)
        self._shard_pool = ThreadPoolExecutor(
            max_workers=max(1, shard_workers))
        self._pending = []
        self._lock = threading.Lock()

    def queue_depth(self) -> int:
        """Steps enqueued but not yet committed. Completed futures stay
        in ``_pending`` so ``_raise_finished`` still surfaces their
        failures."""
        with self._lock:
            return sum(1 for f in self._pending if not f.done())

    def _raise_finished(self):
        with self._lock:
            done = [f for f in self._pending if f.done()]
            self._pending = [f for f in self._pending if not f.done()]
        for f in done:
            f.result()

    def _write_step(self, step, n_shards, leaves_meta, per_shard,
                    schema, metadata):
        def write_one(i):
            sdir = _step_dir(self.directory, step)
            os.makedirs(sdir, exist_ok=True)
            fname = os.path.join(sdir, _shard_name(i))
            arrays = per_shard.get(i, {})
            crc, size = _atomic_write_digest(
                fname, lambda f: np.savez(f, **arrays))
            return _shard_name(i), {"crc32": crc, "size": size}

        import time as _time

        from ibamr_tpu import obs as _obs
        t0 = _time.perf_counter()
        try:
            try:
                return self._write_step_once(step, n_shards,
                                             leaves_meta, per_shard,
                                             schema, metadata,
                                             write_one)
            except Exception:
                # one retry: the atomic-replace protocol makes it
                # idempotent (same contract as the single-host writer)
                return self._write_step_once(step, n_shards,
                                             leaves_meta, per_shard,
                                             schema, metadata,
                                             write_one)
        finally:
            _obs.histogram("ckpt_commit_seconds",
                           writer="sharded").observe(
                _time.perf_counter() - t0)

    def _write_step_once(self, step, n_shards, leaves_meta, per_shard,
                         schema, metadata, write_one):
        sdir = _step_dir(self.directory, step)
        os.makedirs(sdir, exist_ok=True)
        shards_meta = dict(self._shard_pool.map(write_one,
                                                range(n_shards)))
        delay = float(os.environ.get(_COMMIT_DELAY_ENV, "0") or 0)
        if delay > 0:
            time.sleep(delay)
        manifest = {
            "sharded_schema": SHARDED_SCHEMA,
            "step": int(step),
            "mesh": _mesh_spec(self.mesh, None, n_shards),
            "schema": schema,
            "leaves": leaves_meta,
            "shards": shards_meta,
            "metadata": dict(metadata or {}),
            "time": time.time(),
        }
        payload = json.dumps(manifest).encode()
        _atomic_write(os.path.join(sdir, "manifest.json"),
                      lambda f: f.write(payload))
        _fsync_dir(self.directory)
        _prune_sharded(self.directory, self.keep)
        return sdir

    def save(self, state: Any, step: int,
             metadata: Optional[Dict[str, Any]] = None):
        """Snapshot per-shard buffers and enqueue the write. Returns
        the committer future, or ``None`` when the save was shed under
        ``overflow="drop"`` backlog."""
        from ibamr_tpu import obs as _obs
        self._raise_finished()
        if self.queue_depth() >= self.max_pending:
            if self.overflow == "drop":
                self.dropped_saves += 1
                _obs.counter("ckpt_dropped_saves_total",
                             writer="sharded").inc()
                return None
            # backpressure: wait for the OLDEST pending write; wait
            # without .result() so _raise_finished surfaces a failure
            # exactly once
            import concurrent.futures as _cf
            with self._lock:
                oldest = next((f for f in self._pending
                               if not f.done()), None)
            if oldest is not None:
                _cf.wait([oldest])
            self._raise_finished()
        # per-shard host snapshot (sync: donation-safe; no gather)
        dev_ids, n_shards, leaves_meta, per_shard = _plan_shards(state)
        schema = state_schema(state)
        fut = self._commit.submit(self._write_step, step, n_shards,
                                  leaves_meta, per_shard, schema,
                                  metadata)
        with self._lock:
            self._pending.append(fut)
        _obs.gauge("ckpt_queue_depth",
                   writer="sharded").set(self.queue_depth())
        return fut

    def wait(self) -> None:
        """Block until every enqueued step is committed (re-raises the
        first worker failure; failed futures are dropped)."""
        with self._lock:
            pending, self._pending = self._pending, []
        for f in pending:
            f.result()

    def close(self) -> None:
        try:
            self.wait()
        finally:
            self._commit.shutdown(wait=True)
            self._shard_pool.shutdown(wait=True)
