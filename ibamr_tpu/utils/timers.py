"""Hierarchical wall-clock timers.

Reference parity: ``SAMRAI::tbox::TimerManager`` + ``IBAMR_TIMER_START/STOP``
macros (SURVEY.md §5.1): named timers bracketing significant methods, with a
hierarchical report at shutdown. On TPU the analog must account for async
dispatch, so the context manager optionally blocks on a pytree of arrays
before reading the clock; within jitted code use ``jax.named_scope`` (we wrap
it) so the names also show up in ``jax.profiler`` traces.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict, Optional

import jax


class Timer:
    """Re-entrant named timer: nested start/stop pairs with the same name are
    supported (recursive methods bracketed by one timer, as in the reference's
    TimerManager)."""

    __slots__ = ("name", "total", "count", "_starts")

    def __init__(self, name: str):
        self.name = name
        self.total = 0.0
        self.count = 0
        self._starts: list = []

    def start(self) -> None:
        self._starts.append(time.perf_counter())

    def stop(self, block_on=None) -> float:
        if not self._starts:
            raise RuntimeError(f"Timer {self.name!r}: stop() without start()")
        if block_on is not None:
            jax.block_until_ready(block_on)
        dt = time.perf_counter() - self._starts.pop()
        # only the outermost frame of a re-entrant timer accumulates, so
        # `total` stays wall-clock (matching SAMRAI's exclusive-timer report)
        if not self._starts:
            self.total += dt
            self.count += 1
        return dt


class TimerManager:
    """Process-wide named-timer registry with a report table."""

    _instance: Optional["TimerManager"] = None

    def __init__(self):
        self.timers: Dict[str, Timer] = {}

    @classmethod
    def instance(cls) -> "TimerManager":
        if cls._instance is None:
            cls._instance = TimerManager()
        return cls._instance

    def get(self, name: str) -> Timer:
        if name not in self.timers:
            self.timers[name] = Timer(name)
        return self.timers[name]

    @contextmanager
    def scope(self, name: str, block_on=None):
        # span emission (PR 9): the scope IS a telemetry span — one
        # bookkeeping path, not two. The span enters jax.named_scope,
        # blocks on `block_on` before its clock read, and closes into
        # the attached run ledger; the Timer accumulates immediately
        # after (same wall time to within microseconds), keeping the
        # report() table alive for callers that never attach a ledger.
        from ibamr_tpu.obs import span as _span

        t = self.get(name)
        t.start()
        try:
            with _span(name, block_on=block_on):
                yield t
        finally:
            t.stop()

    def report(self) -> str:
        if not self.timers:
            return "TimerManager: no timers recorded"
        width = max(len(n) for n in self.timers) + 2
        lines = [f"{'Timer':<{width}}{'Calls':>8}{'Total (s)':>12}{'Mean (ms)':>12}"]
        for name in sorted(self.timers, key=lambda n: -self.timers[n].total):
            t = self.timers[name]
            mean_ms = 1e3 * t.total / max(t.count, 1)
            lines.append(f"{name:<{width}}{t.count:>8}{t.total:>12.4f}{mean_ms:>12.3f}")
        return "\n".join(lines)

    def reset(self) -> None:
        self.timers.clear()


@contextmanager
def timer(name: str, block_on=None):
    """Module-level convenience: ``with timer("IB::spreadForce"): ...``"""
    with TimerManager.instance().scope(name, block_on=block_on) as t:
        yield t


@contextmanager
def profile_trace(log_dir: Optional[str], stage: Optional[str] = None):
    """Capture a jax/XLA device profile for the enclosed region
    (SURVEY.md §5.1 — the deep-dive layer under TimerManager's wall
    timers, viewable in TensorBoard / Perfetto). No-op when ``log_dir``
    is falsy, so call sites can thread a ``--profile DIR`` flag through
    unconditionally. The ``named_scope`` annotations that TimerManager
    already emits show up as trace regions.

    PR 10 rides the bus: the capture runs inside an
    ``obs.span("profile_trace", capture_dir=..., stage=...)``, and a
    ``profile`` ledger record lands when the trace closes — so
    ``tools/obs.py tail`` shows a profile landing live, and the ledger
    names the capture dir ``tools/prof.py attribute`` should be
    pointed at. Telemetry-off runs pay only the span's no-op path."""
    if not log_dir:
        yield
        return
    import jax.profiler as _prof

    from ibamr_tpu import obs

    with obs.span("profile_trace", capture_dir=str(log_dir),
                  stage=stage):
        _prof.start_trace(log_dir)
        try:
            yield
        finally:
            _prof.stop_trace()
            obs.emit("profile", capture_dir=str(log_dir), stage=stage)
