"""Checkpoint / restart of simulation state pytrees.

Reference parity: ``SAMRAI::tbox::RestartManager`` + per-object
``putToDatabase`` serialization to per-rank HDF5 (SURVEY.md §5.4). TPU-first
redesign: the ENTIRE simulation state is one functional pytree (grid arrays,
marker arrays, integrator scalars), so checkpointing is a single pytree
serialization — no object graph walking. Restarting on a different device
mesh re-shards on load (the analog of the reference's restart-on-different-
rank-count support).

Format: one ``.npz`` per checkpoint holding every leaf keyed by its pytree
path, plus a small JSON sidecar for metadata. No pickle anywhere.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, Optional

import jax
import numpy as np


def _esc(s: str) -> str:
    # escape the path separator so dict keys containing '/' cannot collide
    # with genuine nesting ({"a/b": x} vs {"a": {"b": y}})
    return s.replace("%", "%25").replace("/", "%2F")


def _path_str(path) -> str:
    parts = []
    for p in path:
        if isinstance(p, jax.tree_util.DictKey):
            parts.append(_esc(str(p.key)))
        elif isinstance(p, jax.tree_util.SequenceKey):
            parts.append(str(p.idx))
        elif isinstance(p, jax.tree_util.GetAttrKey):
            parts.append(_esc(str(p.name)))
        elif isinstance(p, jax.tree_util.FlattenedIndexKey):
            parts.append(_esc(str(p.key)))
        else:
            parts.append(re.sub(r"[^\w]", "", str(p)))
    return "/".join(parts) if parts else "_root"


SCHEMA_VERSION = 1


def state_schema(state: Any) -> Dict[str, Any]:
    """Schema fingerprint of a state pytree: every leaf's path, shape
    and dtype (the putToDatabase registry analog). Stored in the
    metadata sidecar so restore can DIAGNOSE refactored state layouts
    instead of silently orphaning old checkpoints (VERDICT round 1,
    weak #9)."""
    leaves = jax.tree_util.tree_flatten_with_path(state)[0]
    return {
        "version": SCHEMA_VERSION,
        "leaves": {
            _path_str(p): [list(np.shape(l)),
                           str(getattr(l, "dtype", np.asarray(l).dtype))]
            for p, l in leaves},
    }


def _schema_diff(stored: Dict[str, Any], current: Dict[str, Any]) -> str:
    s_leaves = stored.get("leaves", {})
    c_leaves = current["leaves"]
    lines = []
    for k in sorted(set(s_leaves) - set(c_leaves)):
        lines.append(f"  checkpoint-only leaf: {k} {s_leaves[k]}")
    for k in sorted(set(c_leaves) - set(s_leaves)):
        lines.append(f"  template-only leaf:   {k} {c_leaves[k]}")
    for k in sorted(set(c_leaves) & set(s_leaves)):
        if s_leaves[k][0] != c_leaves[k][0]:
            lines.append(f"  shape mismatch at {k}: checkpoint "
                         f"{s_leaves[k][0]} vs template {c_leaves[k][0]}")
        elif _dtype_kind(s_leaves[k][1]) != _dtype_kind(c_leaves[k][1]):
            # width changes (f64 checkpoint -> f32 run) are a supported
            # cast; KIND changes (float -> int) are a refactor
            lines.append(f"  dtype-kind mismatch at {k}: checkpoint "
                         f"{s_leaves[k][1]} vs template {c_leaves[k][1]}")
    return "\n".join(lines)


def _dtype_kind(name: str) -> str:
    """numpy kind, with ml_dtypes extensions (bfloat16 etc., numpy kind
    'V') classified as floating so f32 <-> bf16 restarts stay legal."""
    import jax.numpy as jnp

    try:
        if jnp.issubdtype(jnp.dtype(name), jnp.floating):
            return "f"
    except TypeError:
        pass
    return np.dtype(name).kind


def _gather_arrays(state: Any) -> Dict[str, np.ndarray]:
    leaves = jax.tree_util.tree_flatten_with_path(state)[0]
    return {_path_str(path): np.asarray(jax.device_get(leaf))
            for path, leaf in leaves}


def _write_arrays(directory: str, arrays: Dict[str, np.ndarray],
                  schema: Dict[str, Any], step: int,
                  metadata: Optional[Dict[str, Any]], keep: int) -> str:
    os.makedirs(directory, exist_ok=True)
    fname = os.path.join(directory, f"restore.{step:08d}.npz")
    np.savez(fname, **arrays)
    meta = dict(metadata or {})
    meta["step"] = step
    meta["schema"] = schema
    with open(fname.replace(".npz", ".json"), "w") as f:
        json.dump(meta, f)
    _prune(directory, keep)
    return fname


def save_checkpoint(directory: str, state: Any, step: int,
                    metadata: Optional[Dict[str, Any]] = None,
                    keep: int = 3) -> str:
    """Serialize a state pytree. Returns the checkpoint file path."""
    return _write_arrays(directory, _gather_arrays(state),
                         state_schema(state), step, metadata, keep)


def _prune(directory: str, keep: int) -> None:
    ckpts = sorted(
        f for f in os.listdir(directory)
        if f.startswith("restore.") and f.endswith(".npz"))
    for f in ckpts[:-keep] if keep > 0 else []:
        os.remove(os.path.join(directory, f))
        side = os.path.join(directory, f.replace(".npz", ".json"))
        if os.path.exists(side):
            os.remove(side)


def latest_step(directory: str) -> Optional[int]:
    if not os.path.isdir(directory):
        return None
    steps = []
    for f in os.listdir(directory):
        m = re.fullmatch(r"restore\.(\d+)\.npz", f)
        if m:
            steps.append(int(m.group(1)))
    return max(steps) if steps else None


class AsyncCheckpointWriter:
    """Asynchronous checkpoint writes (S6 parallel-I/O completion):
    the disk write runs on a single worker thread, overlapping with the
    next compute steps — the TPU analog of the reference's parallel
    HDF5 dumps off the critical path.

    The device->host gather happens SYNCHRONOUSLY inside ``save``:
    deferring it to the worker would read buffers that a
    donate_argnums step (bench.py's pattern) has already invalidated.
    The gather is the cheap part (HBM->host DMA); the write is what
    overlaps. One worker keeps writes ordered; a failed write surfaces
    ONCE on the next ``save``/``wait`` and is then dropped (a
    checkpoint failure must not poison the rest of the run).

    Usage::

        w = AsyncCheckpointWriter(rst_dir, keep=3)
        ...
        w.save(state, step)        # returns immediately
        ...
        w.wait()                   # drain before exit / restart
    """

    def __init__(self, directory: str, keep: int = 3):
        from concurrent.futures import ThreadPoolExecutor

        self.directory = directory
        self.keep = keep
        self._exec = ThreadPoolExecutor(max_workers=1)
        self._pending = []

    def _raise_finished(self):
        # drop completed futures FIRST so a raised failure is reported
        # exactly once and never blocks later saves/close
        done = [f for f in self._pending if f.done()]
        self._pending = [f for f in self._pending if not f.done()]
        for f in done:
            f.result()              # re-raise the worker failure here

    def save(self, state: Any, step: int,
             metadata: Optional[Dict[str, Any]] = None):
        self._raise_finished()
        arrays = _gather_arrays(state)      # sync: donation-safe
        schema = state_schema(state)
        fut = self._exec.submit(_write_arrays, self.directory, arrays,
                                schema, step, metadata, self.keep)
        self._pending.append(fut)
        return fut

    def wait(self) -> None:
        """Block until every enqueued checkpoint is on disk (re-raises
        the first worker failure; failed futures are dropped)."""
        pending, self._pending = self._pending, []
        for f in pending:
            f.result()

    def close(self) -> None:
        try:
            self.wait()
        finally:
            self._exec.shutdown(wait=True)


def restore_checkpoint(directory: str, template: Any,
                       step: Optional[int] = None,
                       sharding_fn=None):
    """Restore a state pytree.

    ``template`` is a pytree with the same structure (e.g. a freshly
    initialized state); its leaves supply structure, dtype and (if the
    stored array disagrees in dtype) the cast target. ``sharding_fn``, if
    given, maps (path_str, np_array) -> jax.Array for re-sharding onto a
    possibly different device mesh.

    Returns (state, step, metadata).
    """
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {directory}")
    fname = os.path.join(directory, f"restore.{step:08d}.npz")
    data = np.load(fname)
    meta_path = fname.replace(".npz", ".json")
    metadata: Dict[str, Any] = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            metadata = json.load(f)

    paths_and_leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
    # schema validation: a refactored state NamedTuple produces a clear
    # named diff instead of a bare missing-key error deep in the loop
    stored_schema = metadata.get("schema")
    if stored_schema is not None:
        diff = _schema_diff(stored_schema, state_schema(template))
        if diff:
            raise ValueError(
                f"checkpoint {fname} was written with an incompatible "
                f"state schema (version "
                f"{stored_schema.get('version', '?')}):\n{diff}")

    new_leaves = []
    for path, leaf in paths_and_leaves:
        key = _path_str(path)
        if key not in data:
            raise KeyError(f"checkpoint {fname} missing leaf {key!r}")
        arr = data[key]
        tgt_dtype = getattr(leaf, "dtype", None)
        if tgt_dtype is not None and arr.dtype != tgt_dtype:
            arr = arr.astype(tgt_dtype)
        if sharding_fn is not None:
            new_leaves.append(sharding_fn(key, arr))
        elif hasattr(leaf, "sharding"):
            new_leaves.append(jax.device_put(arr, leaf.sharding))
        else:
            new_leaves.append(arr)
    state = jax.tree_util.tree_unflatten(treedef, new_leaves)
    return state, step, metadata
