"""Checkpoint / restart of simulation state pytrees.

Reference parity: ``SAMRAI::tbox::RestartManager`` + per-object
``putToDatabase`` serialization to per-rank HDF5 (SURVEY.md §5.4). TPU-first
redesign: the ENTIRE simulation state is one functional pytree (grid arrays,
marker arrays, integrator scalars), so checkpointing is a single pytree
serialization — no object graph walking. Restarting on a different device
mesh re-shards on load (the analog of the reference's restart-on-different-
rank-count support).

Format: one ``.npz`` per checkpoint holding every leaf keyed by its pytree
path, plus a small JSON sidecar for metadata. No pickle anywhere.

Crash safety (the RestartManager durability contract, SURVEY.md §5.4):
every file lands via write-to-temp + ``fsync`` + ``os.replace``, so a
kill at ANY instant leaves either the previous complete checkpoint or
the new complete one — never a truncated ``restore.*.npz`` that
``latest_step`` would select and ``restore_checkpoint`` crash on. The
sidecar is written AFTER the array file and carries an integrity record
(per-leaf CRC32 plus a whole-file digest); a checkpoint is *verified*
iff its sidecar parses and the digests match. ``latest_step`` /
``restore_checkpoint`` skip unverified checkpoints and fall back to the
newest verified one, and ``_prune`` never deletes the last verified
checkpoint — so no sequence of crashes loses more than one checkpoint
interval (pinned by tests/test_resilience.py, including a SIGKILL-mid-
write subprocess drill).
"""

from __future__ import annotations

import json
import os
import re
import threading
import zlib
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np


def _esc(s: str) -> str:
    # escape the path separator so dict keys containing '/' cannot collide
    # with genuine nesting ({"a/b": x} vs {"a": {"b": y}})
    return s.replace("%", "%25").replace("/", "%2F")


def _path_str(path) -> str:
    parts = []
    for p in path:
        if isinstance(p, jax.tree_util.DictKey):
            parts.append(_esc(str(p.key)))
        elif isinstance(p, jax.tree_util.SequenceKey):
            parts.append(str(p.idx))
        elif isinstance(p, jax.tree_util.GetAttrKey):
            parts.append(_esc(str(p.name)))
        elif isinstance(p, jax.tree_util.FlattenedIndexKey):
            parts.append(_esc(str(p.key)))
        else:
            parts.append(re.sub(r"[^\w]", "", str(p)))
    return "/".join(parts) if parts else "_root"


SCHEMA_VERSION = 1


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed integrity verification (truncated file,
    flipped bytes, or a tampered/missing sidecar)."""


def state_schema(state: Any) -> Dict[str, Any]:
    """Schema fingerprint of a state pytree: every leaf's path, shape
    and dtype (the putToDatabase registry analog). Stored in the
    metadata sidecar so restore can DIAGNOSE refactored state layouts
    instead of silently orphaning old checkpoints (VERDICT round 1,
    weak #9)."""
    leaves = jax.tree_util.tree_flatten_with_path(state)[0]
    return {
        "version": SCHEMA_VERSION,
        "leaves": {
            _path_str(p): [list(np.shape(l)),
                           str(getattr(l, "dtype", np.asarray(l).dtype))]
            for p, l in leaves},
    }


def _schema_diff(stored: Dict[str, Any], current: Dict[str, Any]) -> str:
    s_leaves = stored.get("leaves", {})
    c_leaves = current["leaves"]
    lines = []
    for k in sorted(set(s_leaves) - set(c_leaves)):
        lines.append(f"  checkpoint-only leaf: {k} {s_leaves[k]}")
    for k in sorted(set(c_leaves) - set(s_leaves)):
        lines.append(f"  template-only leaf:   {k} {c_leaves[k]}")
    for k in sorted(set(c_leaves) & set(s_leaves)):
        if s_leaves[k][0] != c_leaves[k][0]:
            lines.append(f"  shape mismatch at {k}: checkpoint "
                         f"{s_leaves[k][0]} vs template {c_leaves[k][0]}")
        elif _dtype_kind(s_leaves[k][1]) != _dtype_kind(c_leaves[k][1]):
            # width changes (f64 checkpoint -> f32 run) are a supported
            # cast; KIND changes (float -> int) are a refactor
            lines.append(f"  dtype-kind mismatch at {k}: checkpoint "
                         f"{s_leaves[k][1]} vs template {c_leaves[k][1]}")
    return "\n".join(lines)


def _dtype_kind(name: str) -> str:
    """numpy kind, with ml_dtypes extensions (bfloat16 etc., numpy kind
    'V') classified as floating so f32 <-> bf16 restarts stay legal."""
    import jax.numpy as jnp

    try:
        if jnp.issubdtype(jnp.dtype(name), jnp.floating):
            return "f"
    except TypeError:
        pass
    return np.dtype(name).kind


def _gather_arrays(state: Any) -> Dict[str, np.ndarray]:
    leaves = jax.tree_util.tree_flatten_with_path(state)[0]
    return {_path_str(path): np.asarray(jax.device_get(leaf))
            for path, leaf in leaves}


def _leaf_crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def _file_crc(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk)
            if not buf:
                break
            crc = zlib.crc32(buf, crc)
    return crc & 0xFFFFFFFF


def _fsync_dir(directory: str) -> None:
    # durability of the os.replace itself (a crash after replace but
    # before the directory entry hits disk could resurrect the old name)
    try:
        fd = os.open(directory, os.O_RDONLY)
    except OSError:
        return                      # e.g. non-POSIX fs; best effort
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _atomic_write(path: str, write_fn) -> None:
    """Write via temp name + fsync + os.replace: the file at ``path``
    is always either absent, the old complete version, or the new
    complete version — never torn. The temp name is pid- AND
    thread-unique: two concurrent writers of the same path (the
    sharded collision drill; an async writer racing a sync preemption
    save) must each write their own temp, or they interleave into one
    file and the LAST replace publishes torn bytes."""
    _atomic_write_digest(path, write_fn)


def _atomic_write_digest(path: str, write_fn):
    """:func:`_atomic_write` that also returns ``(crc32, size)`` of the
    written bytes — computed from the PRIVATE temp file BEFORE the
    replace. Re-reading the published path after ``os.replace`` races
    concurrent writers of the same path: the digest of whoever
    replaced LAST would land in THIS writer's integrity record, and
    that mixed record can pass whole-file verification while the
    per-leaf digests disagree (caught by the sharded collision
    drill)."""
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    try:
        with open(tmp, "wb") as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        crc = _file_crc(tmp)
        size = os.path.getsize(tmp)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)
    _fsync_dir(os.path.dirname(path) or ".")
    return crc, size


def _write_arrays(directory: str, arrays: Dict[str, np.ndarray],
                  schema: Dict[str, Any], step: int,
                  metadata: Optional[Dict[str, Any]], keep: int,
                  lanes: Optional[int] = None) -> str:
    os.makedirs(directory, exist_ok=True)
    fname = os.path.join(directory, f"restore.{step:08d}.npz")
    npz_crc, npz_size = _atomic_write_digest(
        fname, lambda f: np.savez(f, **arrays))
    meta = dict(metadata or {})
    meta["step"] = step
    meta["schema"] = schema
    # integrity record: per-leaf CRCs catch in-file tampering down to
    # the leaf; the whole-file digest makes verification a single
    # sequential read. The digest comes from the temp file BEFORE the
    # replace (never re-read the published path: a concurrent writer's
    # bytes could land there in between), and the sidecar is written
    # AFTER the npz replace, so a complete sidecar implies a complete
    # array file (the commit marker).
    meta["integrity"] = {
        "leaves": {k: _leaf_crc(v) for k, v in arrays.items()},
        "npz_crc32": npz_crc,
        "npz_size": npz_size,
    }
    if lanes is not None:
        # lane-axis extension (fleet checkpoints): per-lane CRC32 of
        # every lane-stacked leaf's rows, so one corrupt lane's slice
        # is diagnosed (and every OTHER lane stays restorable via
        # restore_lane) instead of condemning the whole step
        meta["integrity"]["lanes"] = {
            "count": int(lanes),
            "leaves": {k: [_leaf_crc(v[i]) for i in range(int(lanes))]
                       for k, v in arrays.items()
                       if v.ndim >= 1 and v.shape[0] == int(lanes)},
        }
    payload = json.dumps(meta).encode()
    _atomic_write(fname.replace(".npz", ".json"),
                  lambda f: f.write(payload))
    _prune(directory, keep)
    return fname


def _read_sidecar(directory: str, step: int) -> Optional[Dict[str, Any]]:
    """Parse the sidecar; None if absent or torn (invalid JSON)."""
    path = os.path.join(directory, f"restore.{step:08d}.json")
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def verify_checkpoint(directory: str, step: int) -> bool:
    """True iff step's checkpoint is complete and intact: the sidecar
    parses and the array file matches its recorded size and whole-file
    CRC32. Legacy sidecars (written before the integrity record
    existed) are accepted — they predate atomic writes but refusing
    them would orphan every pre-upgrade run."""
    fname = os.path.join(directory, f"restore.{step:08d}.npz")
    if not os.path.exists(fname):
        return False
    meta = _read_sidecar(directory, step)
    if meta is None:
        return False
    integ = meta.get("integrity")
    if integ is None:
        return True                 # legacy checkpoint: trusted as-is
    try:
        if os.path.getsize(fname) != integ.get("npz_size"):
            return False
        return _file_crc(fname) == integ.get("npz_crc32")
    except OSError:
        return False


def save_checkpoint(directory: str, state: Any, step: int,
                    metadata: Optional[Dict[str, Any]] = None,
                    keep: int = 3,
                    lanes: Optional[int] = None) -> str:
    """Serialize a state pytree. Returns the checkpoint file path.
    ``lanes`` (fleet runs) records per-lane leaf CRCs in the sidecar so
    :func:`restore_lane` can salvage healthy lanes from a step whose
    file is damaged elsewhere."""
    return _write_arrays(directory, _gather_arrays(state),
                         state_schema(state), step, metadata, keep,
                         lanes=lanes)


def _all_steps(directory: str) -> list:
    steps = []
    for f in os.listdir(directory):
        m = re.fullmatch(r"restore\.(\d+)\.npz", f)
        if m:
            steps.append(int(m.group(1)))
    return sorted(steps)


def _prune(directory: str, keep: int) -> None:
    # stale temp files are debris from a killed writer (a *different*
    # process: our own pid's temps are live in the async worker);
    # names are ``<path>.tmp.<pid>.<tid>`` (legacy debris may lack <tid>)
    for f in os.listdir(directory):
        m = re.search(r"\.tmp\.(\d+)(?:\.\d+)?$", f)
        if m and int(m.group(1)) != os.getpid():
            try:
                os.remove(os.path.join(directory, f))
            except OSError:
                pass
    if keep <= 0:
        return
    steps = _all_steps(directory)
    doomed = steps[:-keep]
    if not doomed:
        return
    # the newest VERIFIED checkpoint is sacrosanct: if every younger
    # checkpoint is corrupt, deleting it would leave nothing to roll
    # back to — prune must never shorten the recovery chain to zero
    last_verified = next((s for s in reversed(steps)
                          if verify_checkpoint(directory, s)), None)
    for s in doomed:
        if s == last_verified:
            continue
        os.remove(os.path.join(directory, f"restore.{s:08d}.npz"))
        side = os.path.join(directory, f"restore.{s:08d}.json")
        if os.path.exists(side):
            os.remove(side)


def latest_step(directory: str,
                verified_only: bool = True) -> Optional[int]:
    """Newest restorable step. With ``verified_only`` (the default)
    corrupt or sidecar-less checkpoints are skipped — the answer is the
    newest checkpoint :func:`verify_checkpoint` vouches for, never a
    truncated file a crash left behind."""
    if not os.path.isdir(directory):
        return None
    steps = _all_steps(directory)
    if not verified_only:
        return steps[-1] if steps else None
    return next((s for s in reversed(steps)
                 if verify_checkpoint(directory, s)), None)


class AsyncCheckpointWriter:
    """Asynchronous checkpoint writes (S6 parallel-I/O completion):
    the disk write runs on a single worker thread, overlapping with the
    next compute steps — the TPU analog of the reference's parallel
    HDF5 dumps off the critical path.

    The device->host gather happens SYNCHRONOUSLY inside ``save``:
    deferring it to the worker would read buffers that a
    donate_argnums step (bench.py's pattern) has already invalidated.
    The gather is the cheap part (HBM->host DMA); the write is what
    overlaps. One worker keeps writes ordered; a failed write surfaces
    ONCE on the next ``save``/``wait`` and is then dropped (a
    checkpoint failure must not poison the rest of the run).

    The pending queue is BOUNDED: each queued save pins a full host
    copy of the state, so an unbounded burst of ``save`` calls against
    a slow disk queues arbitrary host memory. At ``max_pending``
    outstanding writes, ``overflow="block"`` (default) applies
    backpressure — ``save`` waits for the oldest write to land first —
    while ``overflow="drop"`` sheds the NEW save and counts it in
    ``dropped_saves`` (checkpoints are periodic: a dropped one widens
    the recovery interval, it cannot corrupt anything). Current
    backlog is ``queue_depth()``, surfaced in the watchdog heartbeat
    by :class:`~ibamr_tpu.utils.supervisor.ResilientDriver`.

    Usage::

        w = AsyncCheckpointWriter(rst_dir, keep=3)
        ...
        w.save(state, step)        # returns immediately
        ...
        w.wait()                   # drain before exit / restart
    """

    def __init__(self, directory: str, keep: int = 3,
                 max_pending: int = 2, overflow: str = "block",
                 lanes: Optional[int] = None):
        from concurrent.futures import ThreadPoolExecutor

        if max_pending < 1:
            raise ValueError("max_pending must be >= 1")
        if overflow not in ("block", "drop"):
            raise ValueError("overflow must be 'block' or 'drop'")
        self.directory = directory
        self.keep = keep
        self.max_pending = max_pending
        self.overflow = overflow
        self.lanes = lanes
        self.dropped_saves = 0
        self._exec = ThreadPoolExecutor(max_workers=1)
        self._pending = []

    def queue_depth(self) -> int:
        """Writes enqueued but not yet finished (each pins one host
        copy of the state). Completed futures stay in ``_pending`` so
        ``_raise_finished`` still surfaces their failures."""
        return sum(1 for f in self._pending if not f.done())

    def _raise_finished(self):
        # drop completed futures FIRST so a raised failure is reported
        # exactly once and never blocks later saves/close
        done = [f for f in self._pending if f.done()]
        self._pending = [f for f in self._pending if not f.done()]
        for f in done:
            f.result()              # re-raise the worker failure here

    @staticmethod
    def _write_with_retry(directory, arrays, schema, step, metadata,
                          keep, lanes=None):
        # one retry before surfacing: a transient fs hiccup (NFS blip,
        # ENOSPC race with the pruner) must not cost the interval —
        # the atomic-replace protocol makes the retry idempotent.
        # `_write_arrays` is looked up per call so fault injection
        # (tools.fault_injection.failing_checkpoint_writes) sees both
        # attempts.
        import time as _time

        from ibamr_tpu import obs as _obs
        t0 = _time.perf_counter()
        try:
            try:
                return _write_arrays(directory, arrays, schema, step,
                                     metadata, keep, lanes=lanes)
            except Exception:
                return _write_arrays(directory, arrays, schema, step,
                                     metadata, keep, lanes=lanes)
        finally:
            _obs.histogram("ckpt_commit_seconds",
                           writer="single").observe(
                _time.perf_counter() - t0)

    def save(self, state: Any, step: int,
             metadata: Optional[Dict[str, Any]] = None):
        """Gather and enqueue one checkpoint write. Returns the write
        future, or ``None`` when the save was shed under
        ``overflow="drop"`` backlog."""
        from ibamr_tpu import obs as _obs
        self._raise_finished()
        if self.queue_depth() >= self.max_pending:
            if self.overflow == "drop":
                self.dropped_saves += 1
                _obs.counter("ckpt_dropped_saves_total",
                             writer="single").inc()
                return None
            # backpressure: the oldest pending write must land before
            # this save may pin another host copy of the state; wait
            # without .result() so _raise_finished surfaces a failure
            # exactly once
            import concurrent.futures as _cf
            oldest = next((f for f in self._pending if not f.done()),
                          None)
            if oldest is not None:
                _cf.wait([oldest])
            self._raise_finished()
        arrays = _gather_arrays(state)      # sync: donation-safe
        schema = state_schema(state)
        fut = self._exec.submit(self._write_with_retry, self.directory,
                                arrays, schema, step, metadata,
                                self.keep, self.lanes)
        self._pending.append(fut)
        _obs.gauge("ckpt_queue_depth",
                   writer="single").set(self.queue_depth())
        return fut

    def wait(self) -> None:
        """Block until every enqueued checkpoint is on disk (re-raises
        the first worker failure; failed futures are dropped)."""
        pending, self._pending = self._pending, []
        for f in pending:
            f.result()

    def close(self) -> None:
        try:
            self.wait()
        finally:
            self._exec.shutdown(wait=True)


def restore_checkpoint(directory: str, template: Any,
                       step: Optional[int] = None,
                       sharding_fn=None):
    """Restore a state pytree.

    ``template`` is a pytree with the same structure (e.g. a freshly
    initialized state); its leaves supply structure, dtype and (if the
    stored array disagrees in dtype) the cast target. ``sharding_fn``, if
    given, maps (path_str, np_array) -> jax.Array for re-sharding onto a
    possibly different device mesh.

    With ``step=None`` the newest VERIFIED checkpoint is restored:
    corrupt or sidecar-less checkpoints (what a kill mid-write leaves
    behind) are skipped with a warning, falling back through older
    checkpoints until one loads. An explicit ``step`` raises
    :class:`CheckpointCorruptError` if that checkpoint fails
    verification. Schema mismatches (a refactored state layout) raise
    ``ValueError`` in both modes — that is a diagnosis, not corruption.

    Returns (state, step, metadata).
    """
    if step is not None:
        fname = os.path.join(directory, f"restore.{step:08d}.npz")
        if not os.path.exists(fname):
            raise FileNotFoundError(fname)
        if not verify_checkpoint(directory, step):
            raise CheckpointCorruptError(
                f"checkpoint {fname} failed integrity verification "
                f"(truncated/corrupt file or missing sidecar)")
        return _load_step(directory, step, template, sharding_fn)

    steps = _all_steps(directory) if os.path.isdir(directory) else []
    if not steps:
        raise FileNotFoundError(f"no checkpoints in {directory}")
    import warnings

    for s in reversed(steps):
        if not verify_checkpoint(directory, s):
            warnings.warn(
                f"skipping unverified checkpoint step {s} in "
                f"{directory} (corrupt or sidecar-less — a crash "
                f"mid-write leaves exactly this)")
            continue
        try:
            return _load_step(directory, s, template, sharding_fn)
        except CheckpointCorruptError as e:
            warnings.warn(f"skipping checkpoint step {s}: {e}")
    raise FileNotFoundError(
        f"no verified checkpoints in {directory} "
        f"({len(steps)} candidate(s), all corrupt)")


def _load_step(directory: str, step: int, template: Any, sharding_fn):
    fname = os.path.join(directory, f"restore.{step:08d}.npz")
    data = np.load(fname)
    metadata: Dict[str, Any] = _read_sidecar(directory, step) or {}
    leaf_crcs = (metadata.get("integrity") or {}).get("leaves", {})

    paths_and_leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
    # schema validation: a refactored state NamedTuple produces a clear
    # named diff instead of a bare missing-key error deep in the loop
    stored_schema = metadata.get("schema")
    if stored_schema is not None:
        diff = _schema_diff(stored_schema, state_schema(template))
        if diff:
            raise ValueError(
                f"checkpoint {fname} was written with an incompatible "
                f"state schema (version "
                f"{stored_schema.get('version', '?')}):\n{diff}")

    new_leaves = []
    for path, leaf in paths_and_leaves:
        key = _path_str(path)
        if key not in data:
            raise KeyError(f"checkpoint {fname} missing leaf {key!r}")
        arr = data[key]
        if key in leaf_crcs and _leaf_crc(arr) != leaf_crcs[key]:
            raise CheckpointCorruptError(
                f"checkpoint {fname}: leaf {key!r} fails its recorded "
                f"CRC32 — the array file and sidecar disagree")
        tgt_dtype = getattr(leaf, "dtype", None)
        if tgt_dtype is not None and arr.dtype != tgt_dtype:
            arr = arr.astype(tgt_dtype)
        if sharding_fn is not None:
            new_leaves.append(sharding_fn(key, arr))
        elif hasattr(leaf, "sharding"):
            new_leaves.append(jax.device_put(arr, leaf.sharding))
        else:
            new_leaves.append(arr)
    state = jax.tree_util.tree_unflatten(treedef, new_leaves)
    return state, step, metadata


def restore_lane(directory: str, template: Any, lane: int,
                 step: Optional[int] = None):
    """Restore ONE lane's slice from a lane-axis checkpoint into
    ``template`` (the current lane-stacked fleet state).

    Only lane ``lane``'s rows are touched — every other lane's rows of
    ``template`` are returned bitwise-untouched, which is what makes a
    per-lane rollback safe for the healthy lanes. Verification is
    per-lane: the sidecar's ``integrity.lanes`` record (written when
    checkpoints are saved with ``lanes=``) lets a step whose file is
    corrupt in ANOTHER lane's rows still serve this lane, so one bad
    lane's corruption cannot widen its neighbours' recovery interval.
    Pre-lane sidecars (no ``integrity.lanes``) fall back to whole-leaf
    CRCs.

    Walks newest -> oldest (or only ``step`` when given) and returns
    ``(patched_state, checkpoint_step)``; ``None`` when no checkpoint
    can vouch for this lane (caller falls back to the initial state).
    """
    if not os.path.isdir(directory):
        return None
    steps = [step] if step is not None else \
        list(reversed(_all_steps(directory)))
    import warnings

    for s in steps:
        try:
            return _load_lane_step(directory, s, template, lane), s
        except (CheckpointCorruptError, KeyError, ValueError,
                OSError) as e:
            warnings.warn(
                f"restore_lane: skipping step {s} for lane {lane}: {e}")
    return None


def _load_lane_step(directory: str, step: int, template: Any,
                    lane: int):
    fname = os.path.join(directory, f"restore.{step:08d}.npz")
    if not os.path.exists(fname):
        raise FileNotFoundError(fname)
    meta = _read_sidecar(directory, step)
    if meta is None:
        raise CheckpointCorruptError(
            "sidecar missing or unparseable (torn write)")
    integ = meta.get("integrity") or {}
    lane_rec = integ.get("lanes")
    if lane_rec is not None and lane >= int(lane_rec.get("count", 0)):
        raise ValueError(
            f"lane {lane} out of range for fleet of "
            f"{lane_rec.get('count')}")
    lane_crcs = (lane_rec or {}).get("leaves", {})
    leaf_crcs = integ.get("leaves", {})

    data = np.load(fname)
    paths_and_leaves, treedef = \
        jax.tree_util.tree_flatten_with_path(template)
    new_leaves = []
    for path, leaf in paths_and_leaves:
        key = _path_str(path)
        if key not in data:
            raise KeyError(f"checkpoint {fname} missing leaf {key!r}")
        arr = data[key]
        if arr.shape != tuple(np.shape(leaf)):
            raise ValueError(
                f"checkpoint {fname}: leaf {key!r} shape {arr.shape} "
                f"!= fleet state shape {tuple(np.shape(leaf))}")
        if arr.ndim < 1 or lane >= arr.shape[0]:
            raise ValueError(
                f"checkpoint {fname}: leaf {key!r} has no lane {lane}")
        sl = arr[lane]
        if key in lane_crcs:
            rec = lane_crcs[key]
            if lane >= len(rec) or _leaf_crc(np.asarray(sl)) != \
                    int(rec[lane]):
                raise CheckpointCorruptError(
                    f"checkpoint {fname}: lane {lane} of leaf {key!r} "
                    f"fails its recorded per-lane CRC32")
        elif key in leaf_crcs and _leaf_crc(arr) != leaf_crcs[key]:
            # pre-lane sidecar: the whole leaf must verify
            raise CheckpointCorruptError(
                f"checkpoint {fname}: leaf {key!r} fails its recorded "
                f"CRC32 and carries no per-lane record")
        tgt_dtype = getattr(leaf, "dtype", None)
        if tgt_dtype is not None and sl.dtype != tgt_dtype:
            sl = sl.astype(tgt_dtype)
        new_leaves.append(jnp.asarray(leaf).at[lane].set(sl))
    return jax.tree_util.tree_unflatten(treedef, new_leaves)
