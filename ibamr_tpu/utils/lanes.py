"""Lane-stacked state pytrees for fleet (ensemble) execution.

A *fleet* runs B independent scenarios of one scenario family through a
single compiled executable: every leaf of the integrator state gains a
leading lane axis and the step function is ``jax.vmap``-ed over it
(ROADMAP item 2). The helpers here are the only place the lane axis
convention lives:

- lane axis is ALWAYS axis 0 of every leaf;
- every lane shares one treedef (parameter sweeps vary *values*, not
  shapes — heterogeneous shapes belong in separate shape buckets);
- slicing a lane out (``lane_slice``) produces a state bitwise equal to
  that lane's rows, so single-lane incident capsules and per-lane
  checkpoint restores are exact.

Bitwise contract (pinned by tests/test_fleet.py): the lane-batched
chunk is *batch-size invariant* — lane k of a B-lane chunk is bitwise
identical to the same scenario run through a B=1 chunk of the same
length. The B=1 fleet run is therefore the "solo run" reference for
every bitwise claim; the classic unbatched ``lax.scan`` chunk compiles
to a differently-fused program and may differ by ULPs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def stack_lanes(states):
    """Stack per-lane state pytrees into one lane-batched pytree
    (lane axis 0 on every leaf). All states must share one treedef."""
    if not states:
        raise ValueError("stack_lanes needs at least one lane state")
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack([jnp.asarray(l) for l in leaves],
                                  axis=0),
        *states)


def fleet_size(state) -> int:
    """B — the lane count of a lane-stacked state (leading axis of the
    first leaf; every leaf agrees by construction)."""
    leaves = jax.tree_util.tree_leaves(state)
    if not leaves:
        raise ValueError("empty state pytree")
    return int(leaves[0].shape[0])


def lane_slice(state, k: int):
    """Lane ``k``'s unbatched state — bitwise the rows of lane k."""
    return jax.tree_util.tree_map(lambda l: l[k], state)


def set_lane(state, k: int, lane_state):
    """Lane-stacked state with lane ``k``'s rows replaced by
    ``lane_state`` (unbatched). Other lanes' rows are copied bitwise —
    a per-lane rollback must never perturb healthy lanes."""
    return jax.tree_util.tree_map(
        lambda l, v: l.at[k].set(jnp.asarray(v, dtype=l.dtype)),
        state, lane_state)


def broadcast_lane(lane_state, n: int):
    """A B=n fleet of identical copies of one unbatched state."""
    return jax.tree_util.tree_map(
        lambda l: jnp.broadcast_to(
            jnp.asarray(l)[None], (n,) + jnp.asarray(l).shape).copy(),
        lane_state)


def pad_lanes(states, n: int):
    """Lane-stack ``states`` padded to ``n`` lanes with copies of the
    LAST state; returns ``(stacked, alive)`` where ``alive`` is the
    (n,) bool mask marking the real lanes. Padding lanes are
    dead-on-arrival: the fleet chunk's alive mask freezes them
    in-graph, so a short request group rides a bigger warm-pool bucket
    (ibamr_tpu/serve/router.py) at zero semantic cost — the padded
    rows never influence, and are never reported as, results."""
    if not states:
        raise ValueError("pad_lanes needs at least one lane state")
    if len(states) > n:
        raise ValueError(
            f"pad_lanes: {len(states)} states exceed the {n}-lane bucket")
    stacked = stack_lanes(list(states) + [states[-1]] * (n - len(states)))
    alive = jnp.arange(n) < len(states)
    return stacked, alive


def lane_mask_shape(mask: jnp.ndarray, leaf: jnp.ndarray) -> jnp.ndarray:
    """Reshape a (B,) lane mask for broadcasting against a lane-stacked
    leaf: (B, 1, ..., 1) with the leaf's rank."""
    return mask.reshape((mask.shape[0],) + (1,) * (leaf.ndim - 1))
