from ibamr_tpu.utils.input_db import InputDatabase, parse_input_file, parse_input_string
from ibamr_tpu.utils.gridfunctions import CartGridFunction
from ibamr_tpu.utils.timers import TimerManager, timer
from ibamr_tpu.utils.metrics import MetricsLogger

__all__ = [
    "InputDatabase",
    "parse_input_file",
    "parse_input_string",
    "CartGridFunction",
    "TimerManager",
    "timer",
    "MetricsLogger",
]
