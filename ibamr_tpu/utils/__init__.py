from ibamr_tpu.utils.input_db import InputDatabase, parse_input_file, parse_input_string
from ibamr_tpu.utils.gridfunctions import CartGridFunction
from ibamr_tpu.utils.timers import TimerManager, timer
from ibamr_tpu.utils.metrics import MetricsLogger
from ibamr_tpu.utils.health import HealthDegraded, HealthProbe
from ibamr_tpu.utils.flight_recorder import (FlightRecorder, factory_spec)
from ibamr_tpu.utils.watchdog import (RunWatchdog, heartbeat_age,
                                      read_heartbeat)

__all__ = [
    "InputDatabase",
    "parse_input_file",
    "parse_input_string",
    "CartGridFunction",
    "TimerManager",
    "timer",
    "MetricsLogger",
    "HealthDegraded",
    "HealthProbe",
    "FlightRecorder",
    "factory_spec",
    "RunWatchdog",
    "heartbeat_age",
    "read_heartbeat",
]
