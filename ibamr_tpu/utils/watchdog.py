"""Run watchdog: heartbeat + stalled-chunk detection (PR 3 tentpole 3).

The failure mode the vitals and the solver escalation cannot see is the
run that stops PRODUCING chunks at all: a hung XLA compile, a TPU relay
that dropped mid-session (three consecutive rounds of it, STATUS.md), a
deadlocked collective. From the outside that run is indistinguishable
from a slow one — no exception, no NaN, no log line — until someone
notices hours later.

:class:`RunWatchdog` makes the silence observable from two directions:

- **outward**: a daemon thread writes ``<dir>/heartbeat.json``
  (``{step, steps_per_s, last_chunk_wall_s, ckpt_queue_depth, time,
  pid}``, plus ``lanes_ok``/``lanes_quarantined``/``lanes_retrying``
  on fleet runs) atomically at
  a fixed cadence, so any EXTERNAL observer — ``tools/relay_watch.py``,
  an operator's ``watch cat`` — can distinguish "alive and computing"
  from "process gone/hung" by file staleness alone;
- **inward**: the same thread tracks the wall time since the last
  :meth:`beat` against a rolling expectation of chunk wall time (EMA of
  the driver's measured ``last_chunk_wall_s``) and, once the silence
  exceeds ``stall_factor x`` that expectation (floored at
  ``min_stall_s``), records ONE structured ``stall`` incident (schema
  v2, ``kind: stall``) and invokes the configurable stall callback.
  The detector re-arms on the next beat, so an intermittent stall is
  counted every time it happens, not only once per process.

The watchdog never unwinds the run itself — a stalled chunk usually
cannot be interrupted from Python anyway (the thread is blocked in XLA).
The callback decides the policy: log-and-wait (default), kill the relay
subprocess (relay_watch), or abort the process for the scheduler to
restart.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
import threading
import time
from typing import Callable, Optional

HEARTBEAT_NAME = "heartbeat.json"


def write_heartbeat(path: str, payload: dict) -> None:
    """Atomic heartbeat write: temp + ``os.replace`` in the target
    directory, so a reader never sees a torn JSON file (same discipline
    as the PR-2 checkpoint writes)."""
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix=".heartbeat-", suffix=".tmp", dir=d)
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def read_heartbeat(path: str) -> Optional[dict]:
    """The parsed heartbeat, or ``None`` when absent/torn (a torn file
    can only be a writer that predates ``write_heartbeat``)."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def heartbeat_age(path: str, now: Optional[float] = None) -> Optional[float]:
    """Seconds since the producing RUN last made progress (its last
    ``beat``), or ``None`` when there is no readable heartbeat. THE
    staleness primitive for external observers. Note the ``time`` field
    is deliberately the last-beat time, NOT the last file write: the
    daemon keeps rewriting the file while the main thread hangs in XLA,
    and a heartbeat that stayed fresh through a hung chunk would hide
    exactly the stall this exists to expose."""
    hb = read_heartbeat(path)
    if hb is None or "time" not in hb:
        return None
    return (time.time() if now is None else now) - float(hb["time"])


@dataclasses.dataclass
class RunWatchdog:
    """Heartbeat writer + stalled-chunk detector.

    Parameters
    ----------
    heartbeat_path:
        Where ``heartbeat.json`` lives (``None`` = detector only, no
        file). A directory path is accepted and gets ``heartbeat.json``
        appended.
    interval_s:
        Daemon cadence: heartbeat refresh + stall check period.
    stall_factor:
        A chunk is stalled once the silence since the last beat exceeds
        ``stall_factor x`` the rolling chunk-wall-time expectation.
    min_stall_s:
        Floor on the stall threshold — fast chunks must not turn jitter
        (or the first compile) into false stalls.
    ema_alpha:
        Weight of the newest chunk wall time in the rolling expectation.
    on_stall:
        ``on_stall(record: dict)`` invoked once per detected stall (the
        policy hook: log, kill a subprocess, abort).
    on_incident:
        Structured-record sink (``ResilientDriver`` points this at its
        ``incidents.jsonl`` writer when it owns the watchdog).
    """

    heartbeat_path: Optional[str] = None
    interval_s: float = 1.0
    stall_factor: float = 4.0
    min_stall_s: float = 5.0
    ema_alpha: float = 0.3
    on_stall: Optional[Callable[[dict], None]] = None
    on_incident: Optional[Callable[[dict], None]] = None

    def __post_init__(self):
        if self.interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        if self.stall_factor <= 1.0:
            raise ValueError("stall_factor must be > 1 (a threshold at "
                             "or below the expectation flags every chunk)")
        if self.min_stall_s < 0:
            raise ValueError("min_stall_s must be >= 0")
        if not (0.0 < self.ema_alpha <= 1.0):
            raise ValueError("ema_alpha must be in (0, 1]")
        if (self.heartbeat_path is not None
                and not self.heartbeat_path.endswith(".json")):
            # a directory (existing or not): the file gets the
            # canonical name inside it
            self.heartbeat_path = os.path.join(self.heartbeat_path,
                                               HEARTBEAT_NAME)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # wall-clock of the last beat (creation time before any beat,
        # so a run hung in its FIRST chunk still ages externally)
        self._beat_walltime = time.time()
        self._last_beat: Optional[float] = None
        self._prev_beat: Optional[float] = None
        self._step: Optional[int] = None
        self._prev_step: Optional[int] = None
        self._last_chunk_wall_s: Optional[float] = None
        self._ckpt_queue_depth: Optional[int] = None
        # fleet triage counters (PR 7): None until the first fleet beat,
        # so solo heartbeats keep their historical schema
        self._lanes_ok: Optional[int] = None
        self._lanes_quarantined: Optional[int] = None
        self._lanes_retrying: Optional[int] = None
        # run-ledger pointer (PR 9): a stalled run's correlated
        # telemetry stream is one heartbeat read away
        self._ledger_path: Optional[str] = None
        self._ledger_seq: Optional[int] = None
        self._ema_chunk_s: Optional[float] = None
        self._armed = True
        self.stalls: list = []          # one record per detected stall

    # -- producer side ------------------------------------------------------

    def beat(self, step: Optional[int] = None,
             last_chunk_wall_s: Optional[float] = None,
             ckpt_queue_depth: Optional[int] = None,
             lanes_ok: Optional[int] = None,
             lanes_quarantined: Optional[int] = None,
             lanes_retrying: Optional[int] = None,
             ledger_path: Optional[str] = None,
             ledger_seq: Optional[int] = None) -> None:
        """Record liveness (call once per completed chunk). Also
        refreshes the heartbeat file immediately, so the file is never
        staler than the run's real progress; the daemon only keeps it
        warm between long-spaced beats."""
        now = time.monotonic()
        with self._lock:
            self._beat_walltime = time.time()
            self._prev_beat, self._last_beat = self._last_beat, now
            if step is not None:
                self._prev_step, self._step = self._step, int(step)
            if last_chunk_wall_s is not None:
                w = float(last_chunk_wall_s)
                self._last_chunk_wall_s = w
                self._ema_chunk_s = w if self._ema_chunk_s is None else \
                    (1.0 - self.ema_alpha) * self._ema_chunk_s \
                    + self.ema_alpha * w
            if ckpt_queue_depth is not None:
                # async checkpoint backlog: a depth pinned at max means
                # the writer can't keep up with the cadence — an
                # external observer sees I/O pressure building BEFORE
                # saves start dropping or the run starts blocking
                self._ckpt_queue_depth = int(ckpt_queue_depth)
            if lanes_ok is not None:
                self._lanes_ok = int(lanes_ok)
            if lanes_quarantined is not None:
                self._lanes_quarantined = int(lanes_quarantined)
            if lanes_retrying is not None:
                self._lanes_retrying = int(lanes_retrying)
            if ledger_path is not None:
                self._ledger_path = str(ledger_path)
            if ledger_seq is not None:
                self._ledger_seq = int(ledger_seq)
            self._armed = True          # re-arm: the run moved again
            payload = self._payload_locked()
        if self.heartbeat_path is not None:
            write_heartbeat(self.heartbeat_path, payload)

    def _payload_locked(self) -> dict:
        sps = None
        if (self._prev_beat is not None and self._step is not None
                and self._prev_step is not None
                and self._last_beat > self._prev_beat
                and self._step > self._prev_step):
            sps = (self._step - self._prev_step) \
                / (self._last_beat - self._prev_beat)
        payload = {"step": self._step, "steps_per_s": sps,
                   "last_chunk_wall_s": self._last_chunk_wall_s,
                   "ckpt_queue_depth": self._ckpt_queue_depth,
                   "time": self._beat_walltime,
                   "written": time.time(), "pid": os.getpid()}
        if self._lanes_ok is not None:
            # fleet run: the external observer sees lane triage in the
            # same file it already watches for staleness
            payload["lanes_ok"] = self._lanes_ok
            payload["lanes_quarantined"] = self._lanes_quarantined
            payload["lanes_retrying"] = self._lanes_retrying
        if self._ledger_path is not None:
            # a stall incident is one pointer away from the correlated
            # telemetry stream (and the seq to start reading at)
            payload["ledger_path"] = self._ledger_path
            payload["ledger_seq"] = self._ledger_seq
        # serving runs (PR 14): the router's request gauges, present
        # only when the process actually served — peeked, not created,
        # so the solo heartbeat schema is untouched (PR-7 precedent)
        try:
            from ibamr_tpu.obs import bus as _bus
            inflight = _bus.peek_gauge("serve_requests_inflight")
            completed = _bus.peek_gauge("serve_requests_completed")
            queued = _bus.peek_gauge("serve_requests_queued")
            shed = _bus.peek_gauge("serve_requests_shed")
        except Exception:
            inflight = completed = queued = shed = None
        if inflight is not None or completed is not None:
            payload["requests_inflight"] = (
                None if inflight is None else int(inflight))
            payload["requests_completed"] = (
                None if completed is None else int(completed))
        # admission-control gauges (PR 17): queued waiters and the
        # cumulative shed count — a wedged admission queue shows up in
        # the heartbeat an external observer already polls; same
        # peek-only rule, so solo runs never grow these keys
        if queued is not None or shed is not None:
            payload["requests_queued"] = (
                None if queued is None else int(queued))
            payload["requests_shed"] = (
                None if shed is None else int(shed))
        # elastic-pool gauges (PR 18): live families, precompile
        # backlog, and the brownout mode ladder position — a router
        # stuck in shed_batch or leaking pools is visible to the same
        # external poll; peek-only, so solo runs never grow these keys
        try:
            fams = _bus.peek_gauge("serve_families_live")
            building = _bus.peek_gauge("serve_precompiles_inflight")
            mode = _bus.peek_gauge("serve_mode")
        except Exception:
            fams = building = mode = None
        if fams is not None or building is not None \
                or mode is not None:
            payload["families_live"] = (
                None if fams is None else int(fams))
            payload["precompiles_inflight"] = (
                None if building is None else int(building))
            try:
                from ibamr_tpu.serve.autoscale import MODES
            except Exception:
                MODES = ()
            payload["serve_mode"] = (
                None if mode is None
                else MODES[int(mode)] if 0 <= int(mode) < len(MODES)
                else int(mode))
        return payload

    # -- detector -----------------------------------------------------------

    def stall_threshold_s(self) -> float:
        with self._lock:
            ema = self._ema_chunk_s
        if ema is None:
            return max(self.min_stall_s, self.stall_factor
                       * self.interval_s)
        return max(self.min_stall_s, self.stall_factor * ema)

    def check(self, now: Optional[float] = None) -> Optional[dict]:
        """One stall check (the daemon calls this every ``interval_s``;
        tests call it directly). Returns the stall record when one
        fires, else ``None``. Fires at most once per beat gap."""
        now = time.monotonic() if now is None else now
        threshold = self.stall_threshold_s()
        with self._lock:
            if self._last_beat is None or not self._armed:
                return None
            age = now - self._last_beat
            if age <= threshold:
                return None
            self._armed = False          # once per silence
            rec = {"event": "stall", "kind": "stall",
                   "step": self._step, "beat_age_s": age,
                   "threshold_s": threshold,
                   "expected_chunk_wall_s": self._ema_chunk_s,
                   "last_chunk_wall_s": self._last_chunk_wall_s}
            self.stalls.append(rec)
        if self.on_incident is not None:
            try:
                self.on_incident(rec)
            except Exception:
                pass                     # the sink must not kill the dog
        if self.on_stall is not None:
            try:
                self.on_stall(rec)
            except Exception:
                pass
        return rec

    # -- daemon -------------------------------------------------------------

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            if self.heartbeat_path is not None:
                with self._lock:
                    payload = self._payload_locked()
                try:
                    write_heartbeat(self.heartbeat_path, payload)
                except OSError:
                    pass                 # a full disk must not kill it
            self.check()

    def start(self) -> "RunWatchdog":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="ibamr-watchdog",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10.0 * self.interval_s)
        self._thread = None

    def __enter__(self) -> "RunWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
