"""Structured per-step metrics logging.

Reference parity: SAMRAI ``tbox::PIO`` per-step log lines (time, dt, CFL,
Krylov iters) + ``IBInstrumentPanel`` text outputs (SURVEY.md §5.5). Here:
one JSONL stream of per-step dicts, plus a human-readable console echo.
"""

from __future__ import annotations

import json
import math
import os
import sys
from typing import Any, Dict, IO, Optional

import numpy as np


def _jsonable(v: Any) -> Any:
    if hasattr(v, "item") and getattr(v, "ndim", None) == 0:
        return v.item()
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, (np.integer,)):
        return int(v)
    if hasattr(v, "tolist"):
        return v.tolist()
    return v


class MetricsLogger:
    def __init__(self, path: Optional[str] = None, echo: bool = False,
                 stream: Optional[IO[str]] = None):
        self.path = path
        self.echo = echo
        self.stream = stream or sys.stdout
        self._fh: Optional[IO[str]] = None
        if path is not None:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._fh = open(path, "a")

    def log(self, record: Dict[str, Any]) -> None:
        rec = {}
        for k, v in record.items():
            v = _jsonable(v)
            if isinstance(v, float) and not math.isfinite(v):
                # strict-JSON stream: a NaN/Inf vital must neither
                # break downstream json.loads (json.dumps would emit
                # bare NaN) nor vanish silently — null the value and
                # flag it, so the non-finite event stays queryable
                rec[k] = None
                rec[f"{k}_nonfinite"] = repr(v)
            else:
                rec[k] = v
        line = json.dumps(rec, allow_nan=False)
        if self._fh is not None:
            self._fh.write(line + "\n")
            self._fh.flush()
        if self.echo:
            brief = "  ".join(
                f"{k}={v:.5g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in rec.items())
            print(brief, file=self.stream)

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.flush()
                os.fsync(self._fh.fileno())
            except (OSError, ValueError):
                pass            # closed/unsyncable stream: still close
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
