"""Hierarchical input-database config system.

Preserves the reference's input-file *vocabulary* (SAMRAI ``tbox::Database``
files: ``Section { key = value }``, ``//`` comments, comma-separated arrays,
TRUE/FALSE booleans, quoted strings, simple arithmetic in numeric values) so
that reference input files (``input2d`` / ``input3d``) port mechanically.

Reference parity: SAMRAI's yacc-based input parser + ``tbox::Database`` typed
accessors (``getDouble``, ``getBool``, ``getDatabase``) — SURVEY.md §5.6.
This is a clean-room reimplementation of the file format, not a port.
"""

from __future__ import annotations

import ast
import math
import operator
import re
from typing import Any, Dict, Iterator, List, Optional, Sequence, Union

Scalar = Union[int, float, bool, str]
Value = Union[Scalar, List[Scalar]]

# --------------------------------------------------------------------------
# Safe arithmetic evaluation for numeric config expressions, e.g. "2*PI/64".
# --------------------------------------------------------------------------

_ALLOWED_BINOPS = {
    ast.Add: operator.add,
    ast.Sub: operator.sub,
    ast.Mult: operator.mul,
    ast.Div: operator.truediv,
    ast.FloorDiv: operator.floordiv,
    ast.Mod: operator.mod,
    ast.Pow: operator.pow,
}
_ALLOWED_UNARY = {ast.UAdd: operator.pos, ast.USub: operator.neg}
_CONSTS = {"PI": math.pi, "pi": math.pi, "E": math.e}
_FUNCS = {
    "sin": math.sin, "cos": math.cos, "tan": math.tan, "exp": math.exp,
    "log": math.log, "sqrt": math.sqrt, "abs": abs, "floor": math.floor,
    "ceil": math.ceil, "pow": pow, "min": min, "max": max, "int": int,
}


def _eval_node(node: ast.AST, names: Dict[str, float]) -> float:
    if isinstance(node, ast.Expression):
        return _eval_node(node.body, names)
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, float)):
        return node.value
    if isinstance(node, ast.BinOp) and type(node.op) in _ALLOWED_BINOPS:
        return _ALLOWED_BINOPS[type(node.op)](
            _eval_node(node.left, names), _eval_node(node.right, names))
    if isinstance(node, ast.UnaryOp) and type(node.op) in _ALLOWED_UNARY:
        return _ALLOWED_UNARY[type(node.op)](_eval_node(node.operand, names))
    if isinstance(node, ast.Name):
        if node.id in names:
            return names[node.id]
        if node.id in _CONSTS:
            return _CONSTS[node.id]
        raise KeyError(node.id)
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        fn = _FUNCS.get(node.func.id)
        if fn is None:
            raise KeyError(node.func.id)
        return fn(*[_eval_node(a, names) for a in node.args])
    raise ValueError(f"disallowed expression node: {ast.dump(node)}")


def eval_arith(expr: str, names: Optional[Dict[str, float]] = None) -> float:
    """Evaluate a restricted arithmetic expression (no attribute access,
    no subscripts, whitelisted functions/constants only)."""
    tree = ast.parse(expr, mode="eval")
    return _eval_node(tree, names or {})


# --------------------------------------------------------------------------
# Database
# --------------------------------------------------------------------------

class InputDatabase:
    """Typed hierarchical key/value store mirroring tbox::Database accessors."""

    def __init__(self, name: str = "root"):
        self.name = name
        self._entries: Dict[str, Union[Value, "InputDatabase"]] = {}

    # -- structural ---------------------------------------------------------
    def keys(self) -> List[str]:
        return list(self._entries.keys())

    def __contains__(self, key: str) -> bool:
        return key in self._entries

    def __iter__(self) -> Iterator[str]:
        return iter(self._entries)

    def put(self, key: str, value: Union[Value, "InputDatabase"]) -> None:
        self._entries[key] = value

    def is_database(self, key: str) -> bool:
        return isinstance(self._entries.get(key), InputDatabase)

    def get_database(self, key: str) -> "InputDatabase":
        v = self._entries.get(key)
        if not isinstance(v, InputDatabase):
            raise KeyError(f"{self.name}: no sub-database {key!r}")
        return v

    def get_database_with_default(self, key: str) -> "InputDatabase":
        if key in self and self.is_database(key):
            return self.get_database(key)
        return InputDatabase(key)

    # -- typed scalar accessors --------------------------------------------
    def _get(self, key: str) -> Value:
        if key not in self._entries:
            raise KeyError(f"{self.name}: missing key {key!r}")
        v = self._entries[key]
        if isinstance(v, InputDatabase):
            raise KeyError(f"{self.name}: {key!r} is a sub-database, not a value")
        return v

    def _scalar(self, key: str) -> Scalar:
        v = self._get(key)
        if isinstance(v, list):
            if len(v) != 1:
                raise TypeError(f"{self.name}: {key!r} is an array of length {len(v)}")
            return v[0]
        return v

    def get_int(self, key: str, default: Optional[int] = None) -> int:
        if key not in self and default is not None:
            return default
        return int(self._scalar(key))

    def get_float(self, key: str, default: Optional[float] = None) -> float:
        if key not in self and default is not None:
            return default
        return float(self._scalar(key))

    def get_bool(self, key: str, default: Optional[bool] = None) -> bool:
        if key not in self and default is not None:
            return default
        v = self._scalar(key)
        if isinstance(v, str):
            return v.upper() in ("TRUE", "YES", "ON", "1")
        return bool(v)

    def get_string(self, key: str, default: Optional[str] = None) -> str:
        if key not in self and default is not None:
            return default
        return str(self._scalar(key))

    def get_array(self, key: str, default: Optional[Sequence[Scalar]] = None) -> List[Scalar]:
        if key not in self and default is not None:
            return list(default)
        v = self._get(key)
        return list(v) if isinstance(v, list) else [v]

    def get_int_array(self, key: str, default: Optional[Sequence[int]] = None) -> List[int]:
        return [int(x) for x in self.get_array(key, default)]

    def get_float_array(self, key: str, default: Optional[Sequence[float]] = None) -> List[float]:
        return [float(x) for x in self.get_array(key, default)]

    # -- conversion ---------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for k, v in self._entries.items():
            out[k] = v.to_dict() if isinstance(v, InputDatabase) else v
        return out

    @classmethod
    def from_dict(cls, d: Dict[str, Any], name: str = "root") -> "InputDatabase":
        db = cls(name)
        for k, v in d.items():
            if isinstance(v, dict):
                db.put(k, cls.from_dict(v, name=k))
            else:
                db.put(k, v)
        return db

    def __repr__(self) -> str:
        return f"InputDatabase({self.name!r}, keys={self.keys()})"


# --------------------------------------------------------------------------
# Parser
# --------------------------------------------------------------------------

_SECTION_RE = re.compile(r"^\s*([A-Za-z_][\w\-]*)\s*\{\s*$")
_ASSIGN_RE = re.compile(r"^\s*([A-Za-z_][\w\-]*)\s*=\s*(.*)$")
_CLOSE_RE = re.compile(r"^\s*\}\s*$")


def _strip_comments(text: str) -> str:
    # Remove /* */ block comments, then // line comments (outside strings).
    text = re.sub(r"/\*.*?\*/", " ", text, flags=re.S)
    out_lines = []
    for line in text.splitlines():
        result, in_str = [], False
        i = 0
        while i < len(line):
            c = line[i]
            if c == '"':
                in_str = not in_str
                result.append(c)
            elif not in_str and c == "/" and i + 1 < len(line) and line[i + 1] == "/":
                break
            elif not in_str and c == "#":  # also accept shell-style comments
                break
            else:
                result.append(c)
            i += 1
        out_lines.append("".join(result))
    return "\n".join(out_lines)


def _split_commas(s: str) -> List[str]:
    """Split on commas that are outside quotes and parentheses."""
    parts, depth, in_str, cur = [], 0, False, []
    for c in s:
        if c == '"':
            in_str = not in_str
            cur.append(c)
        elif not in_str and c == "(":
            depth += 1
            cur.append(c)
        elif not in_str and c == ")":
            depth -= 1
            cur.append(c)
        elif not in_str and depth == 0 and c == ",":
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(c)
    parts.append("".join(cur))
    return [p.strip() for p in parts if p.strip()]


def _parse_scalar(tok: str) -> Scalar:
    tok = tok.strip()
    if tok.startswith('"') and tok.endswith('"') and len(tok) >= 2:
        return tok[1:-1]
    up = tok.upper()
    if up in ("TRUE", "YES", "ON"):
        return True
    if up in ("FALSE", "NO", "OFF"):
        return False
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        return float(tok)
    except ValueError:
        pass
    try:
        v = eval_arith(tok)
        if isinstance(v, float) and v.is_integer() and ("." not in tok and "e" not in tok.lower() and "/" not in tok):
            return int(v)
        return v
    except Exception:
        return tok  # bare word -> string


def _parse_value(raw: str) -> Value:
    parts = _split_commas(raw)
    vals = [_parse_scalar(p) for p in parts]
    if len(vals) == 1:
        return vals[0]
    return vals


def _normalize_braces(text: str) -> str:
    """Split inline sections (``Main { x = 1 }``) onto separate lines so the
    line-based parser handles them; braces inside quoted strings are kept."""
    out, in_str = [], False
    for c in text:
        if c == '"':
            in_str = not in_str
            out.append(c)
        elif not in_str and c == "{":
            out.append(" {\n")
        elif not in_str and c == "}":
            out.append("\n}\n")
        else:
            out.append(c)
    return "".join(out)


def parse_input_string(text: str, name: str = "root") -> InputDatabase:
    text = _normalize_braces(_strip_comments(text))
    root = InputDatabase(name)
    stack: List[InputDatabase] = [root]
    lines = text.splitlines()
    i = 0
    while i < len(lines):
        line = lines[i].strip()
        i += 1
        if not line:
            continue
        # allow "Name {" possibly with trailing content handled line-wise
        m = _SECTION_RE.match(line)
        if m:
            child = InputDatabase(m.group(1))
            stack[-1].put(m.group(1), child)
            stack.append(child)
            continue
        if _CLOSE_RE.match(line):
            if len(stack) == 1:
                raise ValueError("unbalanced '}' in input file")
            stack.pop()
            continue
        m = _ASSIGN_RE.match(line)
        if m:
            key, raw = m.group(1), m.group(2).strip()
            # multi-line arrays: keep consuming while line ends with ','
            while raw.endswith(",") and i < len(lines):
                raw += " " + lines[i].strip()
                i += 1
            stack[-1].put(key, _parse_value(raw))
            continue
        raise ValueError(f"cannot parse input line: {line!r}")
    if len(stack) != 1:
        raise ValueError("unbalanced '{' in input file")
    return root


def parse_input_file(path: str) -> InputDatabase:
    with open(path, "r") as f:
        return parse_input_string(f.read(), name=path)
