"""Hierarchical input-database config system.

Preserves the reference's input-file *vocabulary* (SAMRAI ``tbox::Database``
files: ``Section { key = value }``, ``//`` comments, comma-separated arrays,
TRUE/FALSE booleans, quoted strings, simple arithmetic in numeric values) so
that reference input files (``input2d`` / ``input3d``) port mechanically.

Reference parity: SAMRAI's yacc-based input parser + ``tbox::Database`` typed
accessors (``getDouble``, ``getBool``, ``getDatabase``) — SURVEY.md §5.6.
This is a clean-room reimplementation of the file format, not a port.
"""

from __future__ import annotations

import ast
import math
import operator
import re
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple, Union

Scalar = Union[int, float, bool, str]
Value = Union[Scalar, List[Scalar]]

# --------------------------------------------------------------------------
# Safe arithmetic evaluation for numeric config expressions, e.g. "2*PI/64".
# --------------------------------------------------------------------------

_ALLOWED_BINOPS = {
    ast.Add: operator.add,
    ast.Sub: operator.sub,
    ast.Mult: operator.mul,
    ast.Div: operator.truediv,
    ast.FloorDiv: operator.floordiv,
    ast.Mod: operator.mod,
    ast.Pow: operator.pow,
}
_ALLOWED_UNARY = {ast.UAdd: operator.pos, ast.USub: operator.neg}
_CONSTS = {"PI": math.pi, "pi": math.pi, "E": math.e}
_FUNCS = {
    "sin": math.sin, "cos": math.cos, "tan": math.tan, "exp": math.exp,
    "log": math.log, "sqrt": math.sqrt, "abs": abs, "floor": math.floor,
    "ceil": math.ceil, "pow": pow, "min": min, "max": max, "int": int,
}


def _eval_node(node: ast.AST, names: Dict[str, float]) -> float:
    if isinstance(node, ast.Expression):
        return _eval_node(node.body, names)
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, float)):
        return node.value
    if isinstance(node, ast.BinOp) and type(node.op) in _ALLOWED_BINOPS:
        return _ALLOWED_BINOPS[type(node.op)](
            _eval_node(node.left, names), _eval_node(node.right, names))
    if isinstance(node, ast.UnaryOp) and type(node.op) in _ALLOWED_UNARY:
        return _ALLOWED_UNARY[type(node.op)](_eval_node(node.operand, names))
    if isinstance(node, ast.Name):
        if node.id in names:
            return names[node.id]
        if node.id in _CONSTS:
            return _CONSTS[node.id]
        raise KeyError(node.id)
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        fn = _FUNCS.get(node.func.id)
        if fn is None:
            raise KeyError(node.func.id)
        return fn(*[_eval_node(a, names) for a in node.args])
    raise ValueError(f"disallowed expression node: {ast.dump(node)}")


def eval_arith(expr: str, names: Optional[Dict[str, float]] = None) -> float:
    """Evaluate a restricted arithmetic expression (no attribute access,
    no subscripts, whitelisted functions/constants only)."""
    tree = ast.parse(expr, mode="eval")
    return _eval_node(tree, names or {})


# --------------------------------------------------------------------------
# Database
# --------------------------------------------------------------------------

class InputDatabase:
    """Typed hierarchical key/value store mirroring tbox::Database accessors."""

    def __init__(self, name: str = "root"):
        self.name = name
        self._entries: Dict[str, Union[Value, "InputDatabase"]] = {}

    # -- structural ---------------------------------------------------------
    def keys(self) -> List[str]:
        return list(self._entries.keys())

    def __contains__(self, key: str) -> bool:
        return key in self._entries

    def __iter__(self) -> Iterator[str]:
        return iter(self._entries)

    def put(self, key: str, value: Union[Value, "InputDatabase"]) -> None:
        self._entries[key] = value

    def is_database(self, key: str) -> bool:
        return isinstance(self._entries.get(key), InputDatabase)

    def get_database(self, key: str) -> "InputDatabase":
        v = self._entries.get(key)
        if not isinstance(v, InputDatabase):
            raise KeyError(f"{self.name}: no sub-database {key!r}")
        return v

    def get_database_with_default(self, key: str) -> "InputDatabase":
        if key in self and self.is_database(key):
            return self.get_database(key)
        return InputDatabase(key)

    # -- typed scalar accessors --------------------------------------------
    def _get(self, key: str) -> Value:
        if key not in self._entries:
            raise KeyError(f"{self.name}: missing key {key!r}")
        v = self._entries[key]
        if isinstance(v, InputDatabase):
            raise KeyError(f"{self.name}: {key!r} is a sub-database, not a value")
        return v

    def _scalar(self, key: str) -> Scalar:
        v = self._get(key)
        if isinstance(v, list):
            if len(v) != 1:
                raise TypeError(f"{self.name}: {key!r} is an array of length {len(v)}")
            return v[0]
        return v

    def get_int(self, key: str, default: Optional[int] = None) -> int:
        if key not in self and default is not None:
            return default
        return int(self._scalar(key))

    def get_float(self, key: str, default: Optional[float] = None) -> float:
        if key not in self and default is not None:
            return default
        return float(self._scalar(key))

    def get_bool(self, key: str, default: Optional[bool] = None) -> bool:
        if key not in self and default is not None:
            return default
        v = self._scalar(key)
        if isinstance(v, str):
            return v.upper() in ("TRUE", "YES", "ON", "1")
        return bool(v)

    def get_string(self, key: str, default: Optional[str] = None) -> str:
        if key not in self and default is not None:
            return default
        return str(self._scalar(key))

    def get_array(self, key: str, default: Optional[Sequence[Scalar]] = None) -> List[Scalar]:
        if key not in self and default is not None:
            return list(default)
        v = self._get(key)
        return list(v) if isinstance(v, list) else [v]

    def get_int_array(self, key: str, default: Optional[Sequence[int]] = None) -> List[int]:
        return [int(x) for x in self.get_array(key, default)]

    def get_float_array(self, key: str, default: Optional[Sequence[float]] = None) -> List[float]:
        return [float(x) for x in self.get_array(key, default)]

    # -- conversion ---------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for k, v in self._entries.items():
            out[k] = v.to_dict() if isinstance(v, InputDatabase) else v
        return out

    @classmethod
    def from_dict(cls, d: Dict[str, Any], name: str = "root") -> "InputDatabase":
        db = cls(name)
        for k, v in d.items():
            if isinstance(v, dict):
                db.put(k, cls.from_dict(v, name=k))
            else:
                db.put(k, v)
        return db

    def __repr__(self) -> str:
        return f"InputDatabase({self.name!r}, keys={self.keys()})"


# --------------------------------------------------------------------------
# Parser
# --------------------------------------------------------------------------

def _strip_comments(text: str) -> str:
    """Remove ``//``, ``#`` line comments and ``/* */`` block comments in a
    single string-aware pass: comment markers inside quoted strings (with
    escape support) are left alone. Newlines are preserved."""
    out: List[str] = []
    i, n = 0, len(text)
    in_str = esc = False
    while i < n:
        c = text[i]
        if in_str:
            out.append(c)
            if esc:
                esc = False
            elif c == "\\":
                esc = True
            elif c == '"':
                in_str = False
            i += 1
        elif c == '"':
            in_str = True
            out.append(c)
            i += 1
        elif c == "/" and i + 1 < n and text[i + 1] == "/":
            while i < n and text[i] != "\n":
                i += 1
        elif c == "#":  # also accept shell-style comments
            while i < n and text[i] != "\n":
                i += 1
        elif c == "/" and i + 1 < n and text[i + 1] == "*":
            j = text.find("*/", i + 2)
            end = n if j == -1 else j + 2
            out.append("\n" * text.count("\n", i, end))
            i = end
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _parse_scalar(tok: str, raw: Optional[str] = None) -> Scalar:
    """Parse one value element. ``tok`` is the (possibly space-rejoined)
    token text used for arithmetic; ``raw`` is the verbatim source span used
    as the fallback string so unquoted values like ``viz2d/data`` survive."""
    tok = tok.strip()
    if tok.startswith('"') and tok.endswith('"') and len(tok) >= 2:
        return tok[1:-1].replace('\\"', '"').replace("\\\\", "\\")
    up = tok.upper()
    if up in ("TRUE", "YES", "ON"):
        return True
    if up in ("FALSE", "NO", "OFF"):
        return False
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        return float(tok)
    except ValueError:
        pass
    try:
        expr = tok.replace("^", "**")  # muParser-style power in config values
        v = eval_arith(expr)
        if isinstance(v, float) and v.is_integer() and not any(
                ch in tok for ch in (".", "e", "E", "/")):
            return int(v)
        return v
    except Exception:
        return (raw if raw is not None else tok).strip()  # bare word -> string


# Tokens: quoted strings; numbers (incl. scientific notation); identifiers;
# punctuation/operators; catch-all atoms (unquoted path/filename fragments
# like ``.txt`` or ``a:b``). Newlines are insignificant, matching the
# reference's yacc-based grammar (`a = 1  b = 2` on one line is valid).
_TOKEN_RE = re.compile(r"""
    "(?:[^"\\]|\\.)*"                   # quoted string
  | \d+\.?\d*(?:[eE][+-]?\d+)?          # number (123, 1.5, 1e-3)
  | \.\d+(?:[eE][+-]?\d+)?              # .5
  | [A-Za-z_]\w*(?:-[A-Za-z_]\w*)*      # identifier (hyphens allowed when
                                        # letter-adjacent: max-levels)
  | \*\*                                # power
  | [{}=,()+\-*/^%]                     # punctuation & operators
  | [^\s{}=,"()+\-*/^%]+                # catch-all atom (paths, etc.)
""", re.X)


class _Tok(str):
    """A token carrying its source span, for verbatim-text fallbacks."""
    start: int
    end: int

    def __new__(cls, s: str, start: int, end: int):
        o = super().__new__(cls, s)
        o.start, o.end = start, end
        return o


def _tokenize(text: str) -> Tuple[List["_Tok"], str]:
    text = _strip_comments(text)
    toks, pos = [], 0
    for m in _TOKEN_RE.finditer(text):
        gap = text[pos:m.start()]
        if gap.strip():
            raise ValueError(f"cannot tokenize input near: {gap.strip()[:40]!r}")
        toks.append(_Tok(m.group(0), m.start(), m.end()))
        pos = m.end()
    if text[pos:].strip():
        raise ValueError(f"cannot tokenize input near: {text[pos:].strip()[:40]!r}")
    return toks, text


_IDENT_RE = re.compile(r"[A-Za-z_]\w*(?:-[A-Za-z_]\w*)*\Z")


class _Parser:
    def __init__(self, toks: List["_Tok"], source: str):
        self.toks = toks
        self.source = source
        self.i = 0

    def peek(self, k: int = 0) -> Optional[str]:
        j = self.i + k
        return self.toks[j] if j < len(self.toks) else None

    def next(self) -> str:
        t = self.peek()
        if t is None:
            raise ValueError("unexpected end of input file")
        self.i += 1
        return t

    def parse_body(self, db: InputDatabase, top: bool) -> None:
        while True:
            t = self.peek()
            if t is None:
                if not top:
                    raise ValueError("unbalanced '{' in input file")
                return
            if t == "}":
                if top:
                    raise ValueError("unbalanced '}' in input file")
                self.next()
                return
            if not _IDENT_RE.match(t):
                raise ValueError(f"expected a key or section name, got {t!r}")
            name = self.next()
            nxt = self.peek()
            if nxt == "{":
                self.next()
                child = InputDatabase(name)
                db.put(name, child)
                self.parse_body(child, top=False)
            elif nxt == "=":
                self.next()
                db.put(name, self.parse_value_list())
            else:
                raise ValueError(f"expected '=' or '{{' after {name!r}")

    def _at_entry_boundary(self) -> bool:
        t = self.peek()
        if t is None or t == "}":
            return True
        return bool(_IDENT_RE.match(t)) and self.peek(1) in ("=", "{")

    def parse_value_list(self) -> Value:
        vals = [self.parse_element()]
        while self.peek() == ",":
            self.next()
            if self._at_entry_boundary():  # tolerate trailing comma
                break
            vals.append(self.parse_element())
        return vals[0] if len(vals) == 1 else vals

    def parse_element(self) -> Scalar:
        parts: List["_Tok"] = []
        depth = 0
        while True:
            t = self.peek()
            if t is None or (t == "," and depth == 0) or t in ("{", "="):
                break
            if t == "}" and depth == 0:
                break
            if depth == 0 and _IDENT_RE.match(t) and self.peek(1) in ("=", "{"):
                break  # next entry starts
            t = self.next()
            if t == "(":
                depth += 1
            elif t == ")":
                depth -= 1
            parts.append(t)
        if not parts:
            raise ValueError("empty value in input file")
        raw = self.source[parts[0].start:parts[-1].end]
        if len(parts) == 1:
            return _parse_scalar(parts[0], raw=raw)
        return _parse_scalar(" ".join(parts), raw=raw)


def parse_input_string(text: str, name: str = "root") -> InputDatabase:
    root = InputDatabase(name)
    toks, source = _tokenize(text)
    _Parser(toks, source).parse_body(root, top=True)
    return root


def parse_input_file(path: str) -> InputDatabase:
    with open(path, "r") as f:
        return parse_input_string(f.read(), name=path)
