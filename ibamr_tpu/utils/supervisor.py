"""Supervised rollback-and-retry run loop (the ResilientDriver).

Reference parity: the ``RestartManager`` + health-loop machinery
(SURVEY.md §5.2-5.4) — the reference answers a mid-run failure with
"restart from the last dump"; this module makes that loop automatic.
:class:`ResilientDriver` wraps :class:`HierarchyDriver.run` so a run
that loses its numerical footing (``SimulationDiverged``), its
checkpoint write (the async writer's one retry + verified-fallback
restore), or its host (SIGTERM/SIGINT preemption) finishes anyway:

- **divergence** -> roll back to the newest VERIFIED checkpoint (or
  the initial state when none exists), shrink dt by ``dt_backoff``,
  and retry, up to ``max_retries`` times;
- **preemption** -> drain the async writer, write a final synchronous
  checkpoint of the last healthy post-chunk state, and return;
- every recovery appends one structured JSONL record to
  ``incidents.jsonl`` (schema in docs/RESILIENCE.md) so operators see
  what the run survived, not just that it finished;
- **precision drift** (the f64 shadow audit tripping on a bf16/mixed
  spectral path) -> roll back and retry at the NEXT
  ``PRECISION_FALLBACKS`` level (bf16 -> f32 -> f64) with dt UNCHANGED
  — the cure is precision, not stability — recorded as a
  ``precision_escalation`` incident;
- with a :class:`~ibamr_tpu.utils.flight_recorder.FlightRecorder`
  wired (``recorder=`` here or on the driver), EVERY incident record
  is **schema v3**: it carries a ``replay`` pointer to a dumped
  ``incidents/<step>/replay.npz`` + manifest capsule that
  ``tools/replay.py`` re-executes bitwise offline.

The supervisor owns the checkpoint cadence: it installs an
:class:`AsyncCheckpointWriter`-backed ``checkpoint_fn`` on the wrapped
driver (chaining to any user callback) and tracks the last healthy
state via the driver's per-chunk ``metrics_fn`` hook. Divergence can
never poison the chain — the driver raises BEFORE the cadence callback
sees a non-finite state.
"""

from __future__ import annotations

import json
import os
import signal
import time
from typing import Any, Callable, Optional

from ibamr_tpu import obs as _obs
from ibamr_tpu.utils.checkpoint import (AsyncCheckpointWriter,
                                        latest_step, restore_checkpoint,
                                        restore_lane, save_checkpoint)
from ibamr_tpu.utils.hierarchy_driver import LaneFault, SimulationDiverged

_RETRIES = _obs.counter("supervisor_retries_total")
_ROLLBACKS = _obs.counter("supervisor_rollbacks_total")
_ESCALATIONS = _obs.counter("supervisor_precision_escalations_total")
_LANE_ROLLBACKS = _obs.counter("supervisor_lane_rollbacks_total")
_LANE_QUARANTINES = _obs.counter("supervisor_lane_quarantines_total")


class PreemptionSignal(BaseException):
    """Raised by the installed SIGTERM/SIGINT handler. BaseException so
    integrator/callback ``except Exception`` blocks cannot swallow the
    shutdown request."""

    def __init__(self, signum: int):
        self.signum = signum
        super().__init__(f"preemption signal {signal.Signals(signum).name}")


class ResilientDriver:
    """Wrap a :class:`HierarchyDriver` with rollback-and-retry.

    Parameters
    ----------
    driver:
        The :class:`HierarchyDriver` to supervise. Its
        ``cfg.restart_interval`` sets the checkpoint cadence (and
        therefore the maximum progress one crash can cost).
    checkpoint_dir:
        Where checkpoints and ``incidents.jsonl`` live.
    max_retries:
        Divergence recoveries allowed before the last
        ``SimulationDiverged`` is re-raised.
    dt_backoff:
        Multiplier applied to ``cfg.dt`` on every divergence recovery
        (0.5 halves the step). With ``cfg.cfl`` set the backed-off dt
        still acts as the cap.
    keep:
        Checkpoints retained on disk (the pruner never deletes the
        last verified one regardless).
    sharding_fn:
        Forwarded to :func:`restore_checkpoint` on rollback — restores
        stay correct when the run is later resumed on a different
        device mesh.
    handle_signals:
        Install SIGTERM/SIGINT handlers for the duration of ``run``
        (main thread only; silently skipped elsewhere).
    sharded:
        Use the per-shard checkpoint format
        (:mod:`ibamr_tpu.utils.checkpoint_sharded`) instead of the
        single-host one: the cadence writer becomes an
        :class:`~ibamr_tpu.utils.checkpoint_sharded.AsyncShardedWriter`
        (no full-state host gather), rollback walks to the newest
        VERIFIED sharded step, and the preemption save is sharded too.
    mesh:
        Recorded into sharded manifests and (via ``recorder.extra``)
        into incident capsules, so ``tools/replay.py`` knows the mesh
        a sharded incident ran on.
    """

    def __init__(self, driver, checkpoint_dir: str, *,
                 max_retries: int = 3, dt_backoff: float = 0.5,
                 keep: int = 3, sharding_fn: Optional[Callable] = None,
                 handle_signals: bool = True,
                 incident_log: Optional[str] = None,
                 watchdog=None, recorder=None,
                 sharded: bool = False, mesh=None,
                 quarantine_threshold: float = 0.5):
        if max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        if not (0.0 < dt_backoff <= 1.0):
            raise ValueError("dt_backoff must be in (0, 1]")
        if not (0.0 < quarantine_threshold <= 1.0):
            raise ValueError("quarantine_threshold must be in (0, 1]")
        self.driver = driver
        # rollback keeps PRE-chunk state references (the initial-state
        # restore template, the preemption save of the last good state)
        # that whole-chunk buffer donation would invalidate — a
        # supervised driver must never donate. Forced off here rather
        # than validated, so cfg presets that enable donation for the
        # bare driver still work supervised; compiled chunks are reset
        # because donation is baked into them at jit time.
        if getattr(driver.cfg, "donate", False):
            driver.cfg.donate = False
            driver._chunks = {}
        self.directory = checkpoint_dir
        self.max_retries = max_retries
        self.dt_backoff = dt_backoff
        self.keep = keep
        self.sharding_fn = sharding_fn
        self.handle_signals = handle_signals
        self.incident_log = incident_log or os.path.join(
            checkpoint_dir, "incidents.jsonl")
        self.incidents = []           # in-memory mirror of the JSONL
        # optional RunWatchdog (utils/watchdog.py): the supervisor
        # feeds it a beat per chunk and points its incident sink here,
        # so a stalled chunk lands in the same incidents.jsonl
        self.watchdog = watchdog
        if watchdog is not None and watchdog.on_incident is None:
            watchdog.on_incident = self._record
        # optional FlightRecorder: installed onto the driver (pre-chunk
        # host snapshots) so every incident record can carry a dumped
        # replay capsule — incident schema v3
        self.recorder = recorder if recorder is not None \
            else getattr(driver, "recorder", None)
        if self.recorder is not None \
                and getattr(driver, "recorder", None) is None:
            driver.recorder = self.recorder
        self.sharded = sharded
        self.mesh = mesh
        if self.recorder is not None and (sharded or mesh is not None):
            # capsule fingerprints carry the mesh spec so replay can
            # rebuild (or knowingly degrade) the sharded program
            from ibamr_tpu.utils.checkpoint_sharded import _mesh_spec
            self.recorder.extra.setdefault(
                "mesh", _mesh_spec(mesh, None, 1))
            if mesh is not None:
                self.recorder.extra.setdefault(
                    "mesh_shape", tuple(int(s)
                                        for s in mesh.devices.shape))
        self._writer = None           # live cadence writer during run()
        self.preempted = False
        self.preempt_signum: Optional[int] = None
        self._last: Optional[tuple] = None   # (state, step) post-chunk
        # ---- fleet (lane-batched) supervision ------------------------
        # per-lane retry budgets: one bad lane burns only its own
        # retries; quarantine_threshold is the give-up knob — when more
        # than this fraction of lanes is quarantined (or every lane is
        # dead) the fleet run is no longer worth the trace and
        # HealthDegraded surfaces
        self.quarantine_threshold = quarantine_threshold
        self._lane_retries: dict = {}

    # -- incident records ---------------------------------------------------

    def _record(self, rec: dict) -> dict:
        rec = dict(rec)
        rec["time"] = time.time()
        rec.setdefault("schema", 3)
        if "replay" not in rec:
            rec["replay"] = self._dump_replay(rec)
        # cross-reference the run ledger (PR 9): the incident's slim
        # twin lands there as kind "incident" and the JSONL record
        # carries its ledger seq — one pointer from incidents.jsonl to
        # the correlated span/counter stream and back
        seq = _obs.emit(
            "incident",
            event=rec.get("event"), incident_kind=rec.get("kind"),
            step=rec.get("step"), lane=rec.get("lane"),
            retry=rec.get("retry"), replay=rec.get("replay"))
        if seq is not None:
            rec["ledger_seq"] = seq
        self.incidents.append(rec)
        os.makedirs(os.path.dirname(self.incident_log) or ".",
                    exist_ok=True)
        with open(self.incident_log, "a") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()
        return rec

    def _dump_replay(self, rec: dict,
                     lane: Optional[int] = None) -> Optional[str]:
        """Dump (or reuse) the replay capsule for one incident record;
        returns the capsule directory or None (no recorder / empty
        ring / dump failure — a failed dump must never mask the
        incident itself). ``lane`` slices a fleet snapshot down to a
        single-lane capsule."""
        if self.recorder is None:
            return None
        try:
            return self.recorder.dump_incident(
                directory=os.path.join(self.directory, "incidents"),
                kind=rec.get("kind", rec.get("event", "incident")),
                step=rec.get("step"), event=rec.get("event"),
                driver=self.driver, lane=lane)
        except Exception as exc:          # pragma: no cover - defensive
            import warnings
            warnings.warn(f"replay capsule dump failed: {exc!r}")
            return None

    # -- precision escalation -----------------------------------------------

    def _escalate_precision(self, e) -> Optional[tuple]:
        """Walk ``spectral_dtype`` one PRECISION_FALLBACKS link up the
        chain on the wrapped integrator (unwrapping one IB layer).
        Returns ``(before, after)`` level names, or None when the chain
        is exhausted / the integrator has no spectral knob — the caller
        then falls through to the plain dt-backoff recovery."""
        from ibamr_tpu.solvers.escalation import (PRECISION_FALLBACKS,
                                                  precision_level_name)
        from ibamr_tpu.solvers.spectral_plan import canonical_spectral_dtype

        integ = self.driver.integ
        fluid = getattr(integ, "ins", integ)
        if not hasattr(fluid, "spectral_dtype"):
            return None
        cur = precision_level_name(fluid.spectral_dtype)
        nxt = PRECISION_FALLBACKS.get(cur)
        if nxt is None:
            return None
        fluid.spectral_dtype = canonical_spectral_dtype(nxt)
        # the spectral_dtype is baked into the compiled chunks at trace
        # time — drop them so the retry traces the escalated path
        self.driver._chunks = {}
        return cur, nxt

    def _escalate_inflation(self, e) -> Optional[tuple]:
        """Climb the assimilation cycle's multiplicative-inflation
        ladder one INFLATION_FALLBACKS rung (the precision-escalation
        shape for ``kind == "filter_degraded"``). The exception carries
        the cycle's bound ``escalate`` callable; inflation is a traced
        argument of the analysis executable, so no chunk or cache
        invalidation is needed. Returns ``(before, after)`` rungs, or
        None when the ladder is exhausted — the caller then falls
        through to the plain dt-backoff recovery."""
        esc = getattr(e, "escalate", None)
        if not callable(esc):
            return None
        return esc()

    # -- rollback -----------------------------------------------------------

    def _latest(self):
        if self.sharded:
            from ibamr_tpu.utils.checkpoint_sharded import \
                latest_sharded_step
            return latest_sharded_step(self.directory)
        return latest_step(self.directory)

    def _restore(self, template: Any):
        if self.sharded:
            from ibamr_tpu.utils.checkpoint_sharded import restore_sharded
            return restore_sharded(self.directory, template,
                                   sharding_fn=self.sharding_fn)
        return restore_checkpoint(self.directory, template,
                                  sharding_fn=self.sharding_fn)

    def _rollback(self, template: Any, initial: tuple):
        """(state, step) to resume from: newest verified checkpoint,
        else the initial state."""
        step = self._latest()
        if step is None:
            return initial[0], initial[1], None
        state, k, _ = self._restore(template)
        return state, k, k

    # -- fleet (lane-batched) recovery --------------------------------------

    def _lane_beat_fields(self) -> dict:
        """Per-lane fields for the watchdog heartbeat (empty dict for a
        solo run, keeping the beat schema unchanged)."""
        driver = self.driver
        if getattr(driver, "lanes", None) is None:
            return {}
        alive = driver.lane_alive
        quarantined = int((~alive).sum())
        retrying = sum(1 for ln, r in self._lane_retries.items()
                       if r > 0 and alive[ln])
        fields = {"lanes_ok": int(driver.lanes) - quarantined - retrying,
                  "lanes_quarantined": quarantined,
                  "lanes_retrying": retrying}
        for k, v in fields.items():
            _obs.gauge(k).set(v)
        return fields

    def _recover_lanes(self, e: LaneFault, initial: tuple):
        """Per-lane rollback / quarantine for a :class:`LaneFault`.

        ``e.state`` is the post-chunk stacked state: healthy lanes'
        progress SURVIVES — only the failing lanes' rows are rewritten,
        each from the newest checkpoint that vouches for that lane
        (falling back to the lane's initial slice). A lane with retry
        budget left gets its own dt backed off and runs again; an
        exhausted lane is quarantined — restored rows, then frozen
        in-graph by the lane-alive mask, so the fleet keeps its one
        compiled trace. Raises :class:`HealthDegraded` only when every
        lane is dead or more than ``quarantine_threshold`` of the fleet
        is quarantined.

        Returns ``(patched_state, resume_step)``.
        """
        from ibamr_tpu.utils.health import HealthDegraded
        from ibamr_tpu.utils.lanes import lane_slice, set_lane

        driver = self.driver
        B = int(driver.lanes)
        state = e.state
        try:
            self._writer.wait()    # pending intervals land before we
        except Exception:          # decide which checkpoint is newest
            pass
        probe = getattr(driver, "health_probe", None)
        for lane in e.lanes:
            retries = self._lane_retries.get(lane, 0)
            reasons = e.lane_reasons.get(lane, [])
            # capsule FIRST, while the failing lane's rows are still
            # the failing bytes (the restore below rewrites them)
            replay = self._dump_replay(
                {"kind": e.kind, "step": e.step}, lane=lane)
            restored = restore_lane(self.directory, state, lane) \
                if not self.sharded else None
            if restored is not None:
                state, ck = restored
                rollback_step, from_ck = int(ck), True
            else:
                state = set_lane(state, lane,
                                 lane_slice(initial[0], lane))
                rollback_step, from_ck = initial[1], False
            base = {"kind": e.kind, "step": e.step, "lane": lane,
                    "fleet_size": B, "reasons": reasons,
                    "bad_leaves": sorted(
                        e.lane_bad_leaves.get(lane, [])),
                    "rollback_step": rollback_step,
                    "from_checkpoint": from_ck, "replay": replay}
            if retries < self.max_retries:
                self._lane_retries[lane] = retries + 1
                _LANE_ROLLBACKS.inc()
                dt_before = float(driver.lane_dt[lane])
                driver.lane_dt[lane] = dt_before * self.dt_backoff
                if probe is not None:
                    probe.reset_lane(lane)
                self._record(dict(base, **{
                    "event": "lane_rollback",
                    "retry": retries + 1,
                    "max_retries": self.max_retries,
                    "dt_before": dt_before,
                    "dt_after": float(driver.lane_dt[lane])}))
            else:
                driver.lane_alive[lane] = False
                _LANE_QUARANTINES.inc()
                self._record(dict(base, **{
                    "event": "lane_quarantine",
                    "retries": retries,
                    "max_retries": self.max_retries}))
        quarantined = int((~driver.lane_alive).sum())
        if quarantined >= B or \
                quarantined / B > self.quarantine_threshold:
            self._record({
                "event": "fleet_give_up", "kind": e.kind,
                "step": e.step, "fleet_size": B,
                "lanes_quarantined": quarantined,
                "quarantine_threshold": self.quarantine_threshold,
                "replay": None})
            raise HealthDegraded(
                e.step,
                [f"{quarantined}/{B} lanes quarantined "
                 f"(threshold {self.quarantine_threshold})"],
                {"fleet_size": B, "lanes_quarantined": quarantined})
        return state, e.step

    # -- main entry ---------------------------------------------------------

    def run(self, state, start_step: int = 0):
        """Advance to ``cfg.num_steps`` surviving divergence and
        preemption; returns the final state (check ``self.preempted``
        to distinguish a completed run from a preempted one)."""
        driver = self.driver
        initial = (state, start_step)
        self._last = initial
        if self.sharded:
            from ibamr_tpu.utils.checkpoint_sharded import \
                AsyncShardedWriter
            writer = AsyncShardedWriter(self.directory, keep=self.keep,
                                        mesh=self.mesh)
        else:
            writer = AsyncCheckpointWriter(
                self.directory, keep=self.keep,
                lanes=getattr(driver, "lanes", None))
        self._writer = writer

        user_ckpt = driver.checkpoint_fn
        user_metrics = driver.metrics_fn

        def ckpt_fn(s, k):
            writer.save(s, k)
            if user_ckpt is not None:
                user_ckpt(s, k)

        def metrics_fn(s, k):
            # per-chunk hook: remember the last HEALTHY state — the
            # driver raises on divergence before this runs
            self._last = (s, k)
            if self.watchdog is not None:
                led = _obs.current()
                self.watchdog.beat(
                    step=k,
                    last_chunk_wall_s=getattr(driver,
                                              "last_chunk_wall_s", None),
                    ckpt_queue_depth=writer.queue_depth(),
                    # one pointer from a stalled run's heartbeat to its
                    # ledger (and the exact record to start reading at)
                    ledger_path=(led.path if led is not None else None),
                    ledger_seq=(led.last_seq if led is not None
                                else None),
                    **self._lane_beat_fields())
            return user_metrics(s, k) if user_metrics is not None else None

        driver.checkpoint_fn = ckpt_fn
        driver.metrics_fn = metrics_fn

        old_handlers = {}
        if self.handle_signals:
            def _handler(signum, frame):
                raise PreemptionSignal(signum)
            for sig in (signal.SIGTERM, signal.SIGINT):
                try:
                    old_handlers[sig] = signal.signal(sig, _handler)
                except ValueError:     # not the main thread
                    break

        retries = 0
        cur_state, cur_step = state, start_step
        if self.watchdog is not None:
            self.watchdog.start()
        try:
            while True:
                try:
                    out = driver.run(cur_state, start_step=cur_step)
                    writer.wait()      # every interval durably on disk
                    return out
                except LaneFault as e:
                    # fleet mode: one bad lane must not sink the fleet
                    # — recovery is PER LANE (rollback + dt backoff,
                    # then quarantine) and the healthy lanes' post-
                    # chunk progress is kept; the run resumes at the
                    # failing chunk's END, never re-running healthy
                    # lanes. _recover_lanes raises HealthDegraded when
                    # the fleet itself is no longer viable.
                    cur_state, cur_step = self._recover_lanes(e, initial)
                except SimulationDiverged as e:
                    # incident schema v3: ``kind`` discriminates the
                    # failure family (divergence | health_degraded |
                    # solver_breakdown | precision_drift), subclass
                    # payloads ride along, ``replay`` points at the
                    # dumped capsule when a recorder is wired
                    kind = getattr(e, "kind", "divergence")
                    payload = e.incident_payload() \
                        if hasattr(e, "incident_payload") else {}
                    dt_before = driver.cfg.dt
                    # dump the capsule NOW, while the driver's compiled
                    # chunk and spectral_dtype still match the failing
                    # execution (escalation below invalidates both)
                    payload["replay"] = self._dump_replay(
                        {"kind": kind, "step": e.step})
                    if retries >= self.max_retries:
                        self._record(dict(payload, **{
                            "event": "give_up", "kind": kind,
                            "step": e.step,
                            "bad_leaves": list(e.bad_leaves),
                            "retries": retries,
                            "dt": dt_before}))
                        raise
                    retries += 1
                    _RETRIES.inc()
                    try:
                        writer.wait()  # pending intervals land first
                    except Exception:
                        pass           # roll back to what's on disk
                    if kind == "precision_drift":
                        esc = self._escalate_precision(e)
                    elif kind == "filter_degraded":
                        esc = self._escalate_inflation(e)
                    else:
                        esc = None
                    cur_state, cur_step, ck = self._rollback(initial[0],
                                                             initial)
                    _ROLLBACKS.inc()
                    if esc is not None:
                        # precision (or filter tuning), not stability,
                        # is the problem: dt stays put; the retry
                        # reruns the rolled-back chunk at the escalated
                        # spectral_dtype / inflation rung
                        _ESCALATIONS.inc()
                        event = ("inflation_escalation"
                                 if kind == "filter_degraded"
                                 else "precision_escalation")
                        before_key, after_key = (
                            ("inflation_before", "inflation_after")
                            if kind == "filter_degraded"
                            else ("spectral_dtype_before",
                                  "spectral_dtype_after"))
                        self._record(dict(payload, **{
                            "event": event,
                            "kind": kind, "step": e.step,
                            "retry": retries,
                            "max_retries": self.max_retries,
                            "rollback_step": cur_step,
                            "from_checkpoint": ck is not None,
                            before_key: esc[0],
                            after_key: esc[1],
                            "dt": dt_before}))
                        continue
                    driver.cfg.dt = dt_before * self.dt_backoff
                    self._record(dict(payload, **{
                        "event": "divergence", "kind": kind,
                        "step": e.step,
                        "bad_leaves": list(e.bad_leaves),
                        "retry": retries,
                        "max_retries": self.max_retries,
                        "rollback_step": cur_step,
                        "from_checkpoint": ck is not None,
                        "dt_before": dt_before,
                        "dt_after": driver.cfg.dt}))
        except PreemptionSignal as e:
            self.preempted = True
            self.preempt_signum = e.signum
            try:
                writer.wait()          # drain enqueued intervals
            except Exception:
                pass
            st, k = self._last
            if self.sharded:
                from ibamr_tpu.utils.checkpoint_sharded import \
                    save_sharded_checkpoint
                save_sharded_checkpoint(self.directory, st, k,
                                        keep=self.keep, mesh=self.mesh,
                                        metadata={"preempted": True})
            else:
                save_checkpoint(self.directory, st, k, keep=self.keep,
                                metadata={"preempted": True})
            self._record({
                "event": "preemption",
                "signal": signal.Signals(e.signum).name,
                "step": k, "checkpoint_step": k})
            return st
        finally:
            if self.watchdog is not None:
                self.watchdog.stop()
            driver.checkpoint_fn = user_ckpt
            driver.metrics_fn = user_metrics
            for sig, h in old_handlers.items():
                signal.signal(sig, h)
            try:
                writer.close()
            except Exception:
                pass
            self._writer = None
