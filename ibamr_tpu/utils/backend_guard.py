"""Defensive JAX backend selection for driver entry points.

The container pins ``JAX_PLATFORMS=axon`` (a single real TPU chip behind a
loopback relay) and installs a sitecustomize hook that re-registers that
backend in every interpreter — even when the caller exports
``JAX_PLATFORMS=cpu``. Round 1 lost both driver artifacts to this:
``dryrun_multichip`` hung in ``jax.devices()`` waiting on the relay, and
``bench.py`` died on a transient ``UNAVAILABLE`` from backend setup
(VERDICT.md round 1, "What's weak" #1). ``tests/conftest.py`` already
carried the working guard; this module makes it available to every entry
point.

Two use cases:

- :func:`force_cpu` — run on the host-CPU backend (optionally as an
  N-virtual-device mesh). For multichip dryruns and tests.
- :func:`init_backend_with_retry` — initialize whatever real accelerator
  the environment provides, retrying transient failures, falling back to
  CPU so a benchmark can still emit a (labelled) number instead of
  nothing. For ``bench.py``.
"""

from __future__ import annotations

import os
import time
from typing import Optional, Tuple

# JAX_PLATFORMS as the container set it, before any force_cpu() mutation —
# needed to probe/restore the accelerator after a CPU fallback.
_ORIG_JAX_PLATFORMS: Optional[str] = os.environ.get("JAX_PLATFORMS")

# factories popped by _drop_axon_factory, kept so restore_accelerator()
# can re-register them (a mid-run relay recovery is otherwise one-way)
_stashed_factories: dict = {}

# per-process probe memo: (platform, error) of the last subprocess probe.
# Healthy example startups pay the probe subprocess exactly once
# (ADVICE round 2); explicit re-probes bypass via probe_backend directly.
_probe_memo: Optional[Tuple[Optional[str], Optional[str]]] = None


def _drop_axon_factory() -> None:
    """Unregister the axon PJRT backend factory so no code path can
    force-initialize the TPU relay. The factory is stashed, not lost —
    restore_accelerator() re-registers it. Private-API access is fully
    guarded: if jax moves the symbol, we degrade to trusting
    JAX_PLATFORMS."""
    try:
        from jax._src import xla_bridge as _xb

        fac = _xb._backend_factories.pop("axon", None)
        if fac is not None:
            _stashed_factories["axon"] = fac
    except Exception:
        pass


def _clear_backend_caches() -> None:
    """Forget any initialized (or failed-to-initialize) backend state so
    the next ``jax.devices()`` re-runs platform selection with the
    current env/config."""
    try:
        from jax._src import xla_bridge as _xb

        _xb._clear_backends()
        return
    except Exception:
        pass
    try:  # public-ish fallback
        import jax.extend.backend as _jeb

        _jeb.clear_backends()
    except Exception:
        pass


def force_cpu(n_devices: Optional[int] = None):
    """Pin jax to the host-CPU backend, defeating the axon hook.

    ``n_devices``: request that many virtual CPU devices via
    ``--xla_force_host_platform_device_count`` (honored only if the flag
    is not already set — the driver may have set its own count).

    Safe to call whether or not jax is already imported; must be called
    before the first jax *compute* in this process. Returns the jax
    module.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n_devices}"
            ).strip()

    import jax

    _drop_axon_factory()
    try:
        from jax._src import xla_bridge as _xb

        if _xb.backends_are_initialized():
            _clear_backend_caches()
    except Exception:
        pass
    jax.config.update("jax_platforms", "cpu")
    return jax


def auto_backend():
    """Example-driver entry guard: honor an explicit
    ``JAX_PLATFORMS=cpu`` request (defeating the axon hook that would
    override it and hang on a downed relay), otherwise initialize the
    accelerator with the probe+retry+fallback path. Returns the jax
    module. Call BEFORE the first jax compute."""
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        return force_cpu()
    jax, _, err = init_backend_with_retry()
    if err:
        print(f"[backend] accelerator unavailable ({err}); running on "
              f"CPU", flush=True)
    return jax


def probe_backend(timeout_s: float, env: Optional[dict] = None,
                  ) -> Tuple[Optional[str], Optional[str]]:
    """Check IN A SUBPROCESS whether the default backend can initialize
    within ``timeout_s``. The TPU relay can HANG ``jax.devices()``
    indefinitely (not just error) — a hang in-process is unrecoverable
    because backend init holds the xla_bridge lock, so the probe must be
    a child process we can kill. ``env`` overrides the child environment
    (default: parent env — the same env an in-process init would see).
    Returns (platform, None) on success or (None, reason) on
    timeout/failure."""
    import subprocess
    import sys

    code = "import jax; print(jax.devices()[0].platform)"
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout_s, env=env)
    except subprocess.TimeoutExpired:
        return None, f"backend init hung > {timeout_s:.0f}s (relay down?)"
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()[-1:]
        return None, f"backend init failed: {' '.join(tail)}"
    return r.stdout.strip().splitlines()[-1], None


def probe_accelerator(timeout_s: float) -> Tuple[Optional[str],
                                                 Optional[str]]:
    """Probe the ACCELERATOR backend specifically, even after a
    force_cpu() fallback mutated this process's JAX_PLATFORMS: the child
    gets the container's original JAX_PLATFORMS back (and no forced CPU
    device-count flag, which is harmless but noisy)."""
    env = dict(os.environ)
    if _ORIG_JAX_PLATFORMS is None:
        env.pop("JAX_PLATFORMS", None)
    else:
        env["JAX_PLATFORMS"] = _ORIG_JAX_PLATFORMS
    return probe_backend(timeout_s, env=env)


def restore_accelerator() -> Tuple[object, Optional[str]]:
    """Undo a force_cpu()/CPU-fallback in this process and re-initialize
    the accelerator backend. Call ONLY after probe_accelerator()
    succeeded (the in-process init below can still hang if the relay
    wedges in between — same residual race as init_backend_with_retry).

    Returns (jax, platform) on success or (jax, None) if the accelerator
    is still unavailable (process stays on CPU)."""
    if _ORIG_JAX_PLATFORMS is None:
        os.environ.pop("JAX_PLATFORMS", None)
    else:
        os.environ["JAX_PLATFORMS"] = _ORIG_JAX_PLATFORMS

    import jax

    try:
        from jax._src import xla_bridge as _xb

        for name, fac in list(_stashed_factories.items()):
            _xb._backend_factories.setdefault(name, fac)
        _stashed_factories.clear()
    except Exception:
        pass
    _clear_backend_caches()
    try:
        jax.config.update("jax_platforms", _ORIG_JAX_PLATFORMS)
    except Exception:
        pass
    try:
        devs = jax.devices()
        plat = devs[0].platform
        if plat == "cpu":
            force_cpu()
            return jax, None
        return jax, plat
    except Exception:
        # relay wedged between probe and init: re-pin CPU so the next
        # in-process compute cannot hang on the half-restored relay
        force_cpu()
        return jax, None


def init_backend_with_retry(retries: int = 3, delay: float = 10.0,
                            probe_timeout: float = 180.0,
                            ) -> Tuple[object, str, Optional[str]]:
    """Initialize the default (accelerator) backend, retrying transient
    failures; fall back to CPU rather than crash, and guard against the
    common hang mode.

    A subprocess probe (``probe_timeout`` seconds, overridable via
    ``IBAMR_BACKEND_PROBE_TIMEOUT``) guards against the relay hanging
    backend init indefinitely; only after the probe succeeds do we
    initialize in-process. Residual race: if the relay wedges in the
    window between a successful probe and the in-process init, that
    init can still block — un-guardable in-process because backend init
    holds the xla_bridge lock; callers needing a hard bound should run
    under an external timeout as the driver does.

    Returns ``(jax, platform, error)`` where ``platform`` is e.g.
    ``"axon"``/``"tpu"``/``"cpu"`` and ``error`` is the last accelerator
    init failure message when we fell back (None on clean init).
    """
    global _probe_memo
    probe_timeout = float(os.environ.get("IBAMR_BACKEND_PROBE_TIMEOUT",
                                         probe_timeout))
    last_err: Optional[str] = None
    platform = None
    if _probe_memo is not None:
        # one probe subprocess per process (ADVICE round 2): healthy
        # startups reuse the verdict; a re-probe after relay recovery
        # goes through probe_accelerator()/restore_accelerator().
        platform, last_err = _probe_memo
    else:
        # escalating timeouts: a healthy relay answers the short probe in
        # seconds; only a hang pays the full timeout, exactly once
        short = min(60.0, probe_timeout)
        for attempt in range(max(retries, 1)):
            platform, err = probe_backend(
                short if attempt == 0 else probe_timeout)
            if platform is not None:
                break
            last_err = err
            if err and "hung" in err and attempt > 0:
                # a hang that survived the escalated probe will not heal
                # in seconds; go straight to the CPU fallback
                break
            if attempt + 1 < retries:
                time.sleep(delay)
        _probe_memo = (platform, last_err)
    if platform is None:
        jax = force_cpu()
        return jax, "cpu", last_err

    import jax

    for attempt in range(max(retries, 1)):
        try:
            devs = jax.devices()
            return jax, devs[0].platform, None
        except RuntimeError as e:  # backend setup failure (UNAVAILABLE...)
            last_err = f"{type(e).__name__}: {e}"
            _clear_backend_caches()
            if attempt + 1 < retries:
                time.sleep(delay * (attempt + 1))
    jax = force_cpu()
    return jax, "cpu", last_err
