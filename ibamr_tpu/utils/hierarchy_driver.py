"""Unified simulation run loop (the HierarchyIntegrator skeleton, T13).

Reference parity: ``IBTK::HierarchyIntegrator::advanceHierarchy`` plus
the driver boilerplate every reference ``main.cpp`` repeats — dt
management, regrid cadence, viz dumps, restart writing, per-step
diagnostics (SURVEY.md §2.1 T13, §3.1). Round 1 hand-rolled this loop
in every example and integrator (VERDICT round 1 item 8); this module
is the one shared skeleton, so examples shrink to config + callbacks.

TPU-first structure: the inner loop is a jitted ``lax.scan`` over
``chunk`` steps with a fused finite-state reduction, so health checking
costs one extra scalar per chunk instead of a host sync per step
(SURVEY.md §5.2's checkify/guard promise). ``dt`` is a traced argument
— CFL-driven dt changes between chunks do NOT retrigger compilation.

On divergence the driver raises :class:`SimulationDiverged` naming the
offending state leaves BEFORE any checkpoint of the broken state is
written — a blown-up run halts with a diagnostic instead of poisoning
the restart chain.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ibamr_tpu import obs as _obs

# module-cached handles: inc()/observe() on the instance is the
# lock-free path
_CHUNKS_TOTAL = _obs.counter("driver_chunks_total")
_STEPS_TOTAL = _obs.counter("driver_steps_total")
_CHUNK_WALL = _obs.histogram("driver_chunk_wall_seconds")
_obs.describe("driver_chunk_wall_seconds",
              "Per-chunk wall time including the post-chunk sync.")


class SimulationDiverged(RuntimeError):
    """Raised when the state stops being finite; carries diagnostics.

    ``kind`` tags the incident-schema-v2 record the supervisor writes
    (subclasses: ``health_degraded`` precursor in utils/health.py,
    ``solver_breakdown`` in solvers/escalation.py);
    ``incident_payload()`` contributes subclass-specific fields."""

    kind = "divergence"

    def __init__(self, step: int, bad_leaves):
        self.step = step
        self.bad_leaves = bad_leaves
        names = ", ".join(bad_leaves) or "<unknown>"
        super().__init__(
            f"simulation diverged by step {step}: non-finite values in "
            f"state leaves [{names}] — no checkpoint written for the "
            f"broken state")

    def incident_payload(self) -> dict:
        return {}


class LaneFault(SimulationDiverged):
    """One or more lanes of a fleet chunk went bad; the REST of the
    fleet advanced normally and that progress must not be thrown away.

    Carries the post-chunk lane-stacked state (healthy lanes' progress)
    so the supervisor can patch only the failing lanes' slices and
    resume from ``step`` — rolling back B-1 healthy lanes for one bad
    lane is exactly the failure mode fleet execution exists to avoid.
    """

    kind = "lane_fault"

    def __init__(self, step: int, lanes, lane_reasons: dict,
                 vitals, fleet_size: int, state=None,
                 bad_leaves: Optional[dict] = None):
        self.lanes = list(lanes)
        self.lane_reasons = dict(lane_reasons)
        self.vitals = vitals
        self.fleet_size = int(fleet_size)
        self.state = state                 # post-chunk stacked state
        self.lane_bad_leaves = dict(bad_leaves or {})
        # SimulationDiverged's bad_leaves carries the union for callers
        # that only know the base class
        union = sorted({leaf for ls in self.lane_bad_leaves.values()
                        for leaf in ls})
        RuntimeError.__init__(
            self,
            f"lane fault at step {step}: lanes {self.lanes} of "
            f"{self.fleet_size} failed "
            f"({ {k: v for k, v in self.lane_reasons.items()} })")
        self.step = step
        self.bad_leaves = union

    def incident_payload(self) -> dict:
        vit = self.vitals
        return {
            "lanes": self.lanes,
            "lane_reasons": self.lane_reasons,
            "fleet_size": self.fleet_size,
            "lane_bad_leaves": self.lane_bad_leaves,
            "vitals": (np.asarray(vit).tolist()
                       if vit is not None else None),
        }


def _finite_flag(state) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(state)
    flags = [jnp.all(jnp.isfinite(l)) for l in leaves
             if hasattr(l, "dtype") and jnp.issubdtype(l.dtype,
                                                       jnp.floating)]
    out = jnp.asarray(True)
    for f in flags:
        out = jnp.logical_and(out, f)
    return out


def _finite_flag_lanes(state) -> jnp.ndarray:
    """Per-lane finite flags for a lane-stacked state: (B,) float
    vector, 1.0 where every floating leaf of that lane is finite."""
    leaves = jax.tree_util.tree_leaves(state)
    out = None
    for l in leaves:
        if hasattr(l, "dtype") and jnp.issubdtype(l.dtype, jnp.floating):
            axes = tuple(range(1, l.ndim))
            f = jnp.all(jnp.isfinite(l), axis=axes)
            out = f if out is None else jnp.logical_and(out, f)
    if out is None:
        raise ValueError("state has no floating leaves")
    return out.astype(jnp.float32)


def _bad_leaf_names(state) -> list:
    flat, _ = jax.tree_util.tree_flatten_with_path(state)
    bad = []
    for path, leaf in flat:
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype,
                                                     jnp.floating):
            if not bool(jnp.all(jnp.isfinite(leaf))):
                bad.append(jax.tree_util.keystr(path))
    return bad


# Rematerialization policies for differentiable chunks (PR 19): what
# reverse-mode may SAVE inside each step of a scan chunk. "full" saves
# nothing (recompute everything from the per-step carry — minimal
# memory, one extra primal pass); "dots" saves matmul/contraction
# results (the MXU transfer einsums — recompute only the cheap
# elementwise chains). Names, not callables, so RunConfig stays a
# plain-data input file.
REMAT_POLICIES = {
    "full": None,
    "dots": "checkpoint_dots",
    "dots_no_batch": "checkpoint_dots_with_no_batch_dims",
}


def checkpointed_step(step, remat: str):
    """Wrap ``step(state, dt)`` in ``jax.checkpoint`` under the named
    policy — the building block for gradient-ready scan chunks."""
    policy_name = REMAT_POLICIES[remat]
    if policy_name is None:
        return jax.checkpoint(step)
    return jax.checkpoint(
        step, policy=getattr(jax.checkpoint_policies, policy_name))


@dataclasses.dataclass
class RunConfig:
    """Cadences mirror the reference input-file vocabulary."""
    dt: float
    num_steps: int
    viz_dump_interval: int = 0        # 0 = off
    restart_interval: int = 0
    regrid_interval: int = 0
    health_interval: int = 10         # steps per jitted chunk (>= 1;
    #                                   the health check is not optional)
    cfl: Optional[float] = None       # recompute dt each chunk if set
    donate: bool = False              # donate the chunk's input state
    #   buffers (whole-step in-place update: no fresh HBM allocation
    #   per chunk). OPT-IN because donation invalidates the caller's
    #   pre-chunk state references — anything retaining the state it
    #   passed to run() (rollback templates, resume copies) must leave
    #   this off; ResilientDriver forces it off for exactly that reason.
    remat: Optional[str] = None       # checkpoint policy for the scan
    #   chunk (PR 19): None = primal-only chunks (unchanged); a policy
    #   name from REMAT_POLICIES wraps the per-step body in
    #   ``jax.checkpoint`` so reverse-mode through a chunk stores ONE
    #   state per step instead of every intermediate field. Setting it
    #   also forces chunk-input donation OFF (a donated input is a
    #   use-after-free for the cotangent replay) — the design loop
    #   differentiates these chunks via ibamr_tpu.design.

    def __post_init__(self):
        if self.remat is not None and self.remat not in REMAT_POLICIES:
            raise ValueError(
                f"RunConfig.remat must be one of "
                f"{sorted(REMAT_POLICIES)} or None, got {self.remat!r}")
        # Fail-fast input validation: a bad input file must die HERE
        # with the offending field named, not produce a zero-length
        # scan or a silent no-op run hours later.
        if not (self.dt > 0):            # also rejects NaN dt
            raise ValueError(
                f"RunConfig.dt must be > 0, got {self.dt!r} (a non-"
                f"positive or NaN timestep silently freezes the run)")
        if self.num_steps < 0:
            raise ValueError(
                f"RunConfig.num_steps must be >= 0, got "
                f"{self.num_steps!r}")
        for name in ("viz_dump_interval", "restart_interval",
                     "regrid_interval"):
            val = getattr(self, name)
            if val < 0:
                raise ValueError(
                    f"RunConfig.{name} must be >= 0 (0 = off), got "
                    f"{val!r} — a negative cadence is a typo'd input "
                    f"file, not a request")
        if self.health_interval < 1:
            raise ValueError(
                "health_interval is the steps-per-chunk granularity and "
                "must be >= 1 (the divergence guard cannot be disabled)")
        if self.cfl is not None and not (self.cfl > 0):
            raise ValueError(
                f"RunConfig.cfl must be > 0 when set, got {self.cfl!r}")


class HierarchyDriver:
    """Shared advance/regrid/viz/restart/health loop.

    ``integ`` needs ``step(state, dt) -> state`` (every integrator in
    the framework); optionally ``cfl_dt(state, cfl)`` when
    ``cfg.cfl`` is set. Callbacks (all optional):

    - ``viz_fn(state, step)`` at the viz cadence;
    - ``metrics_fn(state, step) -> dict`` after every chunk (logged by
      the caller — returned dicts are aggregated into ``self.history``);
    - ``regrid_fn(state, step) -> state`` at the regrid cadence
      (host-side retagging — may rebuild sharded placement);
    - ``checkpoint_fn(state, step)`` at the restart cadence.

    ``health_probe`` (a :class:`ibamr_tpu.utils.health.HealthProbe`)
    upgrades the per-chunk finite bool to the fused vitals vector at
    the SAME one-transfer-per-chunk cost: the probe's ``measure`` runs
    inside the jitted chunk, its ``check`` triages on the host and
    raises ``HealthDegraded`` (a ``SimulationDiverged`` precursor)
    before any cadence callback sees the degraded state.

    ``recorder`` (a :class:`ibamr_tpu.utils.flight_recorder
    .FlightRecorder`) snapshots the pre-chunk state to HOST memory
    before every chunk. The snapshot happens BEFORE the jitted chunk
    consumes the state, which is what makes recording compatible with
    ``cfg.donate=True``: the donated chunk invalidates the device
    buffers, but the ring holds independent host copies.

    ``shadow_audit`` (a :class:`ibamr_tpu.solvers.escalation
    .ShadowAuditor`) re-runs one fluid substep at f64 every N chunks
    and raises ``PrecisionDrift`` when the configured
    ``spectral_dtype`` path drifts past its pinned bound — BEFORE the
    checkpoint cadence can persist a silently-drifted state.
    """

    def __init__(self, integ, cfg: RunConfig,
                 viz_fn: Optional[Callable] = None,
                 metrics_fn: Optional[Callable] = None,
                 regrid_fn: Optional[Callable] = None,
                 checkpoint_fn: Optional[Callable] = None,
                 step_fn: Optional[Callable] = None,
                 timer=None,
                 timer_name: str = "HierarchyIntegrator::advanceHierarchy",
                 health_probe=None,
                 recorder=None,
                 shadow_audit=None,
                 lanes: Optional[int] = None,
                 fleet_step_wrap: Optional[Callable] = None,
                 lane_mesh=None):
        self.integ = integ
        self.cfg = cfg
        self.viz_fn = viz_fn
        self.metrics_fn = metrics_fn
        self.regrid_fn = regrid_fn
        self.checkpoint_fn = checkpoint_fn
        self.timer = timer                 # TimerManager: scopes ONLY the
        self.timer_name = timer_name       # jitted advance, not callbacks
        self.health_probe = health_probe
        self.recorder = recorder
        self.shadow_audit = shadow_audit
        self.last_vitals = None            # host dict of the last chunk
        self.last_chunk_wall_s = None      # wall seconds incl. the sync
        self.history = []
        self._base_step = (step_fn if step_fn is not None
                           else integ.step)
        # one compiled chunk per distinct length (a handful at most:
        # cadence-aligned lengths repeat) — no masked-tail waste
        self._chunks = {}
        # DISTINCT INPUT SIGNATURES observed per chunk length: the
        # retrace observable the no-retrace contract is tested against.
        # jit's _cache_size() cannot serve here — the process-global
        # pjit LRU can evict a live entry in a long session, reading as
        # 0 even though no retrace happened (and a later call would
        # silently recompile). Counting raw trace events is also too
        # coupled: a re-trace after jax.clear_caches() (the per-module
        # conftest fixture) or an AOT .lower() re-enters the closure
        # without any NEW signature (ADVICE r5 item 3) — so the dict
        # counts distinct (shape, dtype) signatures instead, which a
        # benign re-trace of a known signature leaves unchanged.
        self.trace_counts = {}
        self._trace_sigs = {}
        # ---- fleet (lane-batched) mode -------------------------------
        # lanes=B runs B independent scenarios through ONE vmapped
        # chunk: state leaves carry a leading lane axis, dt becomes a
        # (B,) vector and a (B,) lane-alive mask freezes quarantined
        # lanes in-graph. Both are TRACED arguments — per-lane dt
        # backoff and quarantine never retrace.
        if lanes is not None and lanes < 1:
            raise ValueError(f"lanes must be >= 1, got {lanes!r}")
        if lanes is not None and cfg.cfl is not None:
            raise ValueError(
                "cfg.cfl adaptive dt is not supported in fleet mode — "
                "lanes carry independent per-lane dt (driver.lane_dt)")
        self.lanes = lanes
        self.fleet_step_wrap = fleet_step_wrap
        # lane_mesh: shard the LANE axis over devices (GSPMD over whole
        # lanes — parallel.mesh.make_lane_mesh). Orthogonal to the
        # per-lane machinery: quarantine/dt stay (B,) traced vectors.
        if lane_mesh is not None and lanes is None:
            raise ValueError("lane_mesh requires fleet mode (lanes=B)")
        if lane_mesh is not None:
            d = int(lane_mesh.devices.size)
            if lanes % d != 0:
                raise ValueError(
                    f"lanes={lanes} not divisible by the {d}-device "
                    f"lane mesh (each device must own whole lanes)")
        self.lane_mesh = lane_mesh
        if lanes is not None:
            # host mirrors of the traced per-lane knobs; the supervisor
            # mutates these between chunks (rollback backoff,
            # quarantine) without triggering a retrace
            self.lane_dt = np.full(lanes, float(cfg.dt), dtype=float)
            self.lane_alive = np.ones(lanes, dtype=bool)
        else:
            self.lane_dt = None
            self.lane_alive = None

    def _chunk(self, n: int):
        if n not in self._chunks:
            base_step = self._base_step
            if self.cfg.remat is not None:
                # gradient-ready chunk: per-step checkpoint policy; the
                # scan below then exposes the standard scan-of-remat
                # structure reverse-mode differentiates at one saved
                # carry per step
                base_step = checkpointed_step(base_step, self.cfg.remat)
            # local aliases: the closure must not capture self, or the
            # global pjit cache would pin the whole driver (integrator,
            # history, callbacks) for the cache entry's lifetime
            counts = self.trace_counts
            sigs = self._trace_sigs
            probe = self.health_probe
            lanes = self.lanes
            if lanes is not None:
                self._chunks[n] = self._build_fleet_chunk(n)
                return self._chunks[n]

            def chunk(state, dt):
                # runs at TRACE time only: record the input signature;
                # the count is the number of DISTINCT signatures, so a
                # benign re-trace (cache cleared, AOT lower) of a
                # known signature does not read as a retrace
                sig = (
                    tuple((tuple(l.shape), str(l.dtype))
                          for l in jax.tree_util.tree_leaves(state)
                          if hasattr(l, "shape")),
                    (tuple(getattr(dt, "shape", ())),
                     str(getattr(dt, "dtype", type(dt).__name__))))
                sigs.setdefault(n, set()).add(sig)
                counts[n] = len(sigs[n])

                def body(s, _):
                    return base_step(s, dt), None

                out, _ = jax.lax.scan(body, state, None, length=n)
                # the vitals vector replaces the single finite bool at
                # the SAME one-transfer-per-chunk cost: both fuse into
                # the scan's output and cross to the host once
                if probe is not None:
                    return out, probe.measure(out, dt)
                return out, _finite_flag(out)

            # whole-chunk buffer donation: the input state's buffers are
            # reused for the output (velocity/pressure update in place
            # instead of allocating fresh full-field buffers per chunk).
            # Safe inside run(): callbacks only ever see the POST-chunk
            # state, and the loop immediately rebinds ``state``.
            # FORCED OFF under remat: a gradient-bound chunk's input is
            # replayed by the cotangent pass — donating it is a
            # use-after-free (same hazard jitted_step(donate=True)
            # refuses under an active trace).
            if self.cfg.donate and self.cfg.remat is None:
                self._chunks[n] = jax.jit(chunk, donate_argnums=(0,))
            else:
                self._chunks[n] = jax.jit(chunk)
        return self._chunks[n]

    def _build_fleet_chunk(self, n: int):
        """The lane-batched chunk: ``chunk(state, dt_vec, alive)``.

        One ``lax.scan`` over a vmapped step; quarantined lanes are
        frozen in-graph by selecting their PRE-step rows after every
        step (``jnp.where`` on the lane-alive mask — no retrace, no
        host round-trip). The bitwise contract: this chunk is
        batch-size invariant (lane k of B lanes == the same lane run at
        B=1; pinned by tests/test_fleet.py), which is what makes B=1
        runs the solo reference and single-lane capsules replayable."""
        base_step = self._base_step
        counts = self.trace_counts
        sigs = self._trace_sigs
        probe = self.health_probe
        lanes = self.lanes
        wrap = self.fleet_step_wrap
        # lane-mesh shardings built OUTSIDE the closure (no self capture)
        if self.lane_mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            _lane_sh = NamedSharding(
                self.lane_mesh, PartitionSpec(self.lane_mesh.axis_names[0]))

            def _pin_lanes(t):
                # constraint-pin the lane axis at the chunk boundary so
                # GSPMD keeps whole lanes on their devices through the
                # scan; the comm scope labels any resulting resharding
                # for obs/deviceprof comm_s attribution
                with jax.named_scope("comm"):
                    return jax.tree_util.tree_map(
                        lambda a: (jax.lax.with_sharding_constraint(
                            a, _lane_sh)
                            if getattr(a, "ndim", 0) >= 1 else a), t)
        else:
            _pin_lanes = None

        stacked_step = jax.vmap(base_step, in_axes=(0, 0))
        if wrap is not None:
            # lane-targeted fault injection wraps the STACKED step: a
            # per-lane injector needs the lane axis in view
            stacked_step = wrap(stacked_step)
        if probe is not None:
            measure_lanes = jax.vmap(probe.measure, in_axes=(0, 0))

        def chunk(state, dt, alive):
            # trace-time signature record; the lane count is an
            # explicit element so the no-retrace contract is testable
            # per (B, chunk length)
            sig = (
                int(lanes),
                tuple((tuple(l.shape), str(l.dtype))
                      for l in jax.tree_util.tree_leaves(state)
                      if hasattr(l, "shape")),
                (tuple(dt.shape), str(dt.dtype)),
                (tuple(alive.shape), str(alive.dtype)))
            sigs.setdefault(n, set()).add(sig)
            counts[n] = len(sigs[n])

            if _pin_lanes is not None:
                state = _pin_lanes(state)
                (dt, alive) = _pin_lanes((dt, alive))

            def body(s, _):
                new = stacked_step(s, dt)
                # freeze dead lanes at their pre-step rows; healthy
                # lanes pass through bitwise (select, not arithmetic)
                frozen = jax.tree_util.tree_map(
                    lambda nl, ol: jnp.where(
                        alive.reshape((lanes,) + (1,) * (nl.ndim - 1)),
                        nl, ol),
                    new, s)
                return frozen, None

            out, _ = jax.lax.scan(body, state, None, length=n)
            if _pin_lanes is not None:
                out = _pin_lanes(out)
            if probe is not None:
                # (B, 7) per-lane vitals -> (7, B); still ONE host
                # transfer per chunk
                return out, jnp.transpose(measure_lanes(out, dt))
            return out, _finite_flag_lanes(out)

        if self.cfg.donate:
            return jax.jit(chunk, donate_argnums=(0,))
        return jax.jit(chunk)

    def _triage_fleet(self, state, health, step: int):
        """Host-side per-lane triage of a fleet chunk's vitals.

        ``health`` is the (7, B) vitals matrix (probe) or the (B,)
        finite vector. Dead (quarantined) lanes are skipped — their
        frozen rows are the last good state, not a new fault. Any LIVE
        lane that went non-finite or triaged FATAL raises
        :class:`LaneFault` carrying the post-chunk state; the
        supervisor patches only the failing lanes and resumes."""
        probe = self.health_probe
        alive = self.lane_alive
        B = self.lanes
        finite = (health[0] >= 1.0) if probe is not None \
            else (health >= 1.0)
        bad = [i for i in range(B) if alive[i] and not bool(finite[i])]
        reasons = {i: ["non_finite"] for i in bad}
        if probe is not None:
            verdicts = probe.check_lanes(health, step=step,
                                         dt=self.lane_dt, alive=alive)
            self.last_vitals = verdicts
            for i, v in enumerate(verdicts):
                if i in reasons or not alive[i]:
                    continue
                if v.get("fire"):
                    bad.append(i)
                    reasons[i] = list(v.get("reasons") or [])
        if bad:
            from ibamr_tpu.utils.lanes import lane_slice
            bad_leaves = {}
            for i in bad:
                if not bool(finite[i]):
                    bad_leaves[i] = _bad_leaf_names(lane_slice(state, i))
            raise LaneFault(step, sorted(bad), reasons, health, B,
                            state=state, bad_leaves=bad_leaves)

    def run(self, state, start_step: int = 0):
        """Advance to ``cfg.num_steps``; returns the final state."""
        cfg = self.cfg
        step = start_step
        dt = cfg.dt
        if (start_step and cfg.regrid_interval
                and self.regrid_fn is not None
                and start_step % cfg.regrid_interval == 0):
            # resume landing ON a regrid boundary: the checkpoint the
            # caller restored was written BEFORE that step's regrid ran
            # (cadence order below is checkpoint, then regrid), so the
            # pending regrid — or an assimilation analysis riding the
            # regrid hook — must fire exactly once here, else a
            # supervisor rollback silently drops it
            state = self.regrid_fn(state, start_step)
        cadences = [i for i in (cfg.viz_dump_interval,
                                cfg.restart_interval,
                                cfg.regrid_interval) if i]
        while step < cfg.num_steps:
            if cfg.cfl is not None:
                # float() keeps dt a weak-typed Python scalar whichever
                # branch wins (a device-scalar cfl_dt would otherwise
                # flip the aval and retrace)
                dt = float(min(cfg.dt,
                               self.integ.cfl_dt(state, cfg.cfl)))
            n = min(cfg.health_interval, cfg.num_steps - step)
            for i in cadences:               # land exactly on cadences
                n = min(n, i - step % i)
            probe = self.health_probe
            fleet = self.lanes is not None
            if fleet:
                snap_dt = self.lane_dt.copy()
                snap_alive = self.lane_alive.copy()
                chunk_args = (jnp.asarray(self.lane_dt),
                              jnp.asarray(self.lane_alive))
            else:
                snap_dt, snap_alive = dt, None
                chunk_args = (dt,)
            if self.recorder is not None:
                # host copy of the PRE-chunk state, taken before the
                # (possibly donated) chunk invalidates its buffers
                self.recorder.snapshot(state, step=step, dt=snap_dt,
                                       length=n, integ=self.integ,
                                       cfg=cfg, alive=snap_alive)
            t0 = time.perf_counter()
            # the chunk span brackets dispatch AND the one-per-chunk
            # host sync below; with a run ledger attached it closes
            # into the ledger (kind "span"), else it costs two clock
            # reads. Telemetry never reaches inside the jitted chunk —
            # the *_telemetry graph contracts pin zero in-scan host
            # transfers with the bus armed.
            with _obs.span("driver/chunk", step=step, length=n):
                if self.timer is not None:
                    with self.timer.scope(self.timer_name):
                        state, health = self._chunk(n)(state,
                                                       *chunk_args)
                        # one device sync per chunk (inside the scope):
                        # the finite bool or the fused vitals vector
                        health = np.asarray(health)
                else:
                    state, health = self._chunk(n)(state, *chunk_args)
                    health = np.asarray(health)
            self.last_chunk_wall_s = time.perf_counter() - t0
            _CHUNKS_TOTAL.inc()
            _STEPS_TOTAL.inc(n)
            _CHUNK_WALL.observe(self.last_chunk_wall_s)
            # per-chunk counters snapshot + device-memory watermarks,
            # riding the sync that just happened (no-op when no ledger
            # is attached)
            _obs.chunk_boundary(step=step + n,
                                chunk_wall_s=self.last_chunk_wall_s)
            if fleet:
                # per-lane triage; raises LaneFault (carrying the
                # post-chunk state so healthy-lane progress survives)
                # BEFORE any cadence callback sees a poisoned lane
                self._triage_fleet(state, health, step + n)
            else:
                finite = bool(health[0] >= 1.0) if probe is not None \
                    else bool(health)
                if not finite:
                    raise SimulationDiverged(step + n,
                                             _bad_leaf_names(state))
                if probe is not None:
                    # host-side triage; raises HealthDegraded (the
                    # SimulationDiverged precursor) BEFORE any cadence
                    # callback can checkpoint the degraded state
                    self.last_vitals = probe.check(health, step=step + n,
                                                   dt=dt)
            if self.shadow_audit is not None and not fleet:
                # strided f64 shadow audit; raises PrecisionDrift
                # BEFORE the checkpoint cadence can persist a
                # silently-drifted state
                self.shadow_audit.maybe_audit(self.integ, state, dt,
                                              step=step + n)
            step += n

            if self.metrics_fn is not None:
                rec = self.metrics_fn(state, step)
                if rec:
                    self.history.append(rec)
            if (cfg.viz_dump_interval and self.viz_fn is not None
                    and step % cfg.viz_dump_interval == 0):
                self.viz_fn(state, step)
            if (cfg.restart_interval and self.checkpoint_fn is not None
                    and step % cfg.restart_interval == 0):
                self.checkpoint_fn(state, step)
            if (cfg.regrid_interval and self.regrid_fn is not None
                    and step % cfg.regrid_interval == 0):
                state = self.regrid_fn(state, step)
        # always visualize the final configuration, aligned or not
        if (cfg.viz_dump_interval and self.viz_fn is not None
                and step % cfg.viz_dump_interval != 0):
            self.viz_fn(state, step)
        return state
