"""Explicit Godunov advection: slope-limited MUSCL predictor with
corner-transport-upwind (CTU) transverse corrections.

Reference parity: ``AdvectorExplicitPredictorPatchOps`` (P20, SURVEY.md
§2.2 — the m4 Fortran ``*godunov*`` predictor kernels) and the
convective predictor inside
``AdvDiffPredictorCorrectorHierarchyIntegrator`` (P19). The reference's
default face reconstruction is PPM; this module provides the PLM/CTU
member of the same family (2nd order, monotone with the MC limiter) —
the ``INSStaggeredPPMConvectiveOperator`` role for scalars is covered by
:mod:`ibamr_tpu.ops.convection`.

TPU-first: the predictor is whole-array rolls + `jnp.where` upwind
selects — no per-cell Fortran loops; everything fuses into one kernel
per axis under jit.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

Vel = Tuple[jnp.ndarray, ...]


def mc_limited_slope(Q: jnp.ndarray, axis: int,
                     wall: bool = False) -> jnp.ndarray:
    """Monotonized-central limited undivided slope (van Leer MC).

    ``wall`` treats both ends of ``axis`` as domain walls: the
    cross-wall (periodic-wrap) differences are zeroed — the
    even-reflection ghost — which limits the boundary cells' slopes to
    0 instead of polluting them with the opposite wall's values."""
    dp = jnp.roll(Q, -1, axis) - Q
    dm = Q - jnp.roll(Q, 1, axis)
    if wall:
        from ibamr_tpu.ops.stencils import wall_boundary_masks

        is_lo, is_hi = wall_boundary_masks(Q.shape, axis)
        dm = jnp.where(is_lo, 0.0, dm)
        dp = jnp.where(is_hi, 0.0, dp)
    dc = 0.5 * (dp + dm)
    s = jnp.sign(dc)
    mag = jnp.minimum(jnp.abs(dc),
                      2.0 * jnp.minimum(jnp.abs(dp), jnp.abs(dm)))
    return jnp.where(dp * dm > 0.0, s * mag, 0.0)


def _face_states(Q: jnp.ndarray, u: jnp.ndarray, d: int, dx: float,
                 dt: float, wall: bool = False
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Left/right predicted states at the lower d-faces (PLM in space +
    half-dt characteristic tracing along d)."""
    slope = mc_limited_slope(Q, d, wall=wall)
    nu = u * dt / dx           # face CFL number
    # left state: from cell i-1, traced toward the face over dt/2
    qL = jnp.roll(Q, 1, d) + 0.5 * (1.0 - jnp.maximum(nu, 0.0)) \
        * jnp.roll(slope, 1, d)
    # right state: from cell i
    qR = Q - 0.5 * (1.0 + jnp.minimum(nu, 0.0)) * slope
    return qL, qR


def godunov_face_values(Q: jnp.ndarray, u: Vel,
                        dx: Sequence[float], dt: float,
                        ctu: bool = True,
                        wall_axes: Optional[Sequence[bool]] = None) -> Vel:
    """Time-centered face values q^{n+1/2} at the lower faces of each
    axis; ``u`` is the advecting MAC velocity. With ``ctu``, transverse
    derivative corrections (corner transport upwind) lift the stability
    limit to the full multidimensional CFL.

    ``wall_axes[d]`` marks axis d as wall-bounded under the pinned-face
    storage convention (ins_walls): the advecting normal velocity
    carries 0 at both wall faces, so every wall-face flux vanishes and
    the flux-divergence rolls stay EXACT; the only wall correction
    needed is the even-reflection slope limit at boundary cells
    (mc_limited_slope ``wall``)."""
    dim = Q.ndim
    if wall_axes is None:
        wall_axes = (False,) * dim
    faces = []
    for d in range(dim):
        qL, qR = _face_states(Q, u[d], d, dx[d], dt,
                              wall=wall_axes[d])
        if ctu:
            corr = jnp.zeros_like(Q)
            for a in range(dim):
                if a == d:
                    continue
                # transverse donor-cell flux difference (Colella CTU):
                # upwinded, so the predictor stays monotone
                Fa = u[a] * jnp.where(u[a] > 0.0, jnp.roll(Q, 1, a), Q)
                corr = corr + (jnp.roll(Fa, -1, a) - Fa) / dx[a]
            qL = qL - 0.5 * dt * jnp.roll(corr, 1, d)
            qR = qR - 0.5 * dt * corr
        faces.append(jnp.where(u[d] > 0.0, qL,
                     jnp.where(u[d] < 0.0, qR, 0.5 * (qL + qR))))
    return tuple(faces)


def advect(Q: jnp.ndarray, u: Vel, dx: Sequence[float], dt: float,
           ctu: bool = True,
           wall_axes: Optional[Sequence[bool]] = None) -> jnp.ndarray:
    """One conservative Godunov advection step:
    Q - dt div(u q^{n+1/2}) (flux form -> exact mass conservation).
    ``wall_axes`` — see godunov_face_values (wall-face fluxes vanish
    under the pinned-face convention, so conservation holds in the
    walled box too)."""
    qf = godunov_face_values(Q, u, dx, dt, ctu=ctu, wall_axes=wall_axes)
    out = Q
    for d in range(Q.ndim):
        F = u[d] * qf[d]
        out = out - dt * (jnp.roll(F, -1, d) - F) / dx[d]
    return out


def advect_split(Q: jnp.ndarray, u: Vel, dx: Sequence[float],
                 dt: float, parity: int = 0) -> jnp.ndarray:
    """Strang dimensionally-split Godunov step: one 1D PLM sweep per
    axis (alternate ``parity`` between steps for 2nd order). Each sweep
    is TVD, so the split scheme is RIGOROUSLY monotone for constant
    advection — the guarantee the unsplit CTU predictor trades for
    unsplit accuracy (it allows O(0.1%) corner over/undershoots)."""
    dim = Q.ndim
    order = range(dim) if parity % 2 == 0 else reversed(range(dim))
    for d in order:
        qL, qR = _face_states(Q, u[d], d, dx[d], dt)
        qf = jnp.where(u[d] > 0.0, qL,
                       jnp.where(u[d] < 0.0, qR, 0.5 * (qL + qR)))
        F = u[d] * qf
        Q = Q - dt * (jnp.roll(F, -1, d) - F) / dx[d]
    return Q


class AdvDiffPredictorCorrector:
    """Predictor-corrector advection-diffusion integrator.

    Reference parity: ``AdvDiffPredictorCorrectorHierarchyIntegrator``
    (P19) — Godunov predictor supplies the time-centered convective
    flux; diffusion is Crank-Nicolson (FFT Helmholtz solve on the
    periodic grid):
      (1/dt - kappa/2 lap) Q^{n+1} =
          (1/dt + kappa/2 lap) Q^n - div(u q^{n+1/2})
    """

    def __init__(self, grid, kappa: float = 0.0, ctu: bool = True):
        self.grid = grid
        self.kappa = float(kappa)
        self.ctu = ctu

    def step(self, Q: jnp.ndarray, u: Vel, dt: float) -> jnp.ndarray:
        from ibamr_tpu.ops import stencils
        from ibamr_tpu.solvers import fft

        dx = self.grid.dx
        qf = godunov_face_values(Q, u, dx, dt, ctu=self.ctu)
        conv = jnp.zeros_like(Q)
        for d in range(Q.ndim):
            F = u[d] * qf[d]
            conv = conv + (jnp.roll(F, -1, d) - F) / dx[d]
        if self.kappa == 0.0:
            return Q - dt * conv
        rhs = Q / dt + 0.5 * self.kappa * stencils.laplacian(Q, dx) - conv
        return fft.solve_helmholtz_periodic(rhs, dx, alpha=1.0 / dt,
                                            beta=-0.5 * self.kappa)
