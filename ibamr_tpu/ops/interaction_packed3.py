"""Fully-blocked occupancy-packed spread/interpolate: z-blocked chunks
+ spill-folding overlap-add.

Reference parity: the same T2 operations as every other engine
(``LEInteractor::spread/interpolate``,
``ibtk/src/lagrangian/fortran/lagrangian_interaction3d.f.m4`` [U] —
SURVEY.md T2, the north-star hot path); exact adjoint pair; overflow
through the shared scatter fallback.

Why a third layout (round 5, VERDICT item 2 — attack the roofline gap
structurally): the HLO audit (`PERF_HLO.md`) measured the xy-packed
engine's remaining waste —

1. **Full-z contraction.** The packed engine carries the entire last
   axis (n_z = 256 at the flagship) through the contraction while a
   marker's delta support touches only ``s = 4`` z-cells: 14.2 of its
   14.2 GFLOP/component are ~64x against the useful work, and the
   per-tile partials ``T`` materialize at (B, P, n_z) grid scale
   (177 MB/component).
2. **Masked overlap-add.** Accumulating width-13 tiles into the grid
   as 4 core/spill mask combinations costs 4 grid-size materializations
   + rolls (1.6 GB/component — the single largest traffic block of the
   whole coupled step after packing).

This module blocks ALL axes (z tiles of 16 by default): chunks hold
markers of one (x,y,z)-tile, the contraction output is (chunk,
w_z, P) with w_z = 21 instead of (chunk, P, 256) — ~12x less partial
traffic and ~6-10x less MXU work — and the overlap-add is restructured
as **spill folding**: because the spill width (s+1) never exceeds the
tile, each block's spill lands entirely in its successor's core, so
the periodic accumulation happens ON THE SMALL TILE TENSOR (roll by
one block + add, per axis), leaving a pure partition that reshapes to
the grid in ONE pass (plus one multi-axis roll) — no masked grid-size
passes at all. The same measured at 256^3/1e5 markers (HLO audit,
re-run with this engine): spread bytes-accessed 11.25 -> ~3 GB,
transfer dot-FLOPs 38 -> ~3 GFLOP against identical results.

Layout notes (TPU): contraction outputs put the xy-footprint P = 169
on the minor (lane) axis and w_z on the sublane axis — w_z = 21 on
lanes would pad 6x. Chunk capacity defaults to 64 (finer occupancy
granularity than the xy-packed 128: z-blocking multiplies active
tiles, so per-tile counts shrink).
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops.delta import Kernel, get_kernel
from ibamr_tpu.ops.interaction import _centering_offsets
from ibamr_tpu.ops.interaction_fast import (
    _phi_safe, bucketed_channel, contract_compressed,
    spread_overflow_fallbacks, unbucket_with_overflow)

Vel = Tuple[jnp.ndarray, ...]


class BucketGeometry3(NamedTuple):
    """Static fully-blocked configuration (python ints -> one
    compilation). All ``dim`` axes carry a (tile, nblk, width) triple;
    ``width_d = tile_d + support + 1`` (the +-1 margin absorbs the
    per-centering j0 shift, same convention as interaction_fast)."""
    tile: Tuple[int, ...]
    nblk: Tuple[int, ...]
    cap: int                  # marker slots per chunk
    support: int
    width: Tuple[int, ...]


def make_geometry3(grid: StaggeredGrid, kernel: Kernel = "IB_4",
                   tile: int = 8, tile_last: int = 16,
                   cap: int = 64) -> BucketGeometry3:
    support, _ = get_kernel(kernel)
    tiles = tuple([tile] * (grid.dim - 1) + [tile_last])
    for d, (n, t) in enumerate(zip(grid.n, tiles)):
        if t < support + 1:
            raise ValueError(
                f"tile {t} (axis {d}) must be >= support+1 = "
                f"{support + 1} (spill must fit one tile)")
        if n % t != 0:
            raise ValueError(
                f"grid extent {n} not divisible by tile {t} (axis {d})")
        if n < t + support + 1:
            raise ValueError(
                f"grid extent {n} too small for tile {t} + support "
                f"{support} + 1 (axis {d})")
    return BucketGeometry3(
        tile=tiles,
        nblk=tuple(n // t for n, t in zip(grid.n, tiles)),
        cap=int(cap),
        support=int(support),
        width=tuple(t + support + 1 for t in tiles))


class PackedBuckets3(NamedTuple):
    """Chunk-packed marker layout over (x, y, z)-tiles. Duck-types the
    shared-fallback fields of interaction_fast.Buckets."""
    Xb: jnp.ndarray               # (Q, c, dim)
    wb: jnp.ndarray               # (Q, c)
    slot_of_marker: jnp.ndarray   # (N,)
    w_overflow: jnp.ndarray       # (N,)
    o_idx: jnp.ndarray            # (ocap,)
    o_w: jnp.ndarray              # (ocap,)
    any_overflow: jnp.ndarray     # () bool
    exceeded: jnp.ndarray         # () bool
    x0: Tuple[jnp.ndarray, ...]   # per axis: (Q,) tile origin cell
    tile_of_chunk: jnp.ndarray    # (Q,) int32 nondecreasing


def _block_ids3_np(grid, Xn, support, tiles):
    bid = np.zeros(len(Xn), dtype=np.int64)
    for d in range(grid.dim):
        xi = (Xn[:, d] - grid.x_lo[d]) / grid.dx[d] - 0.5
        j0 = np.floor(xi - 0.5 * support).astype(np.int64) + 1
        bid = bid * (grid.n[d] // tiles[d]) \
            + np.mod(j0, grid.n[d]) // tiles[d]
    return bid


def suggest_chunks3(grid: StaggeredGrid, X, kernel: Kernel = "IB_4",
                    tile: int = 8, tile_last: int = 16,
                    chunk: int = 64, slack: float = 1.3) -> int:
    """Host-side chunk-capacity heuristic from a concrete marker
    distribution (slack x the exact demand sum(ceil(count/c)))."""
    Xn = np.asarray(X)
    support, _ = get_kernel(kernel)
    tiles = tuple([tile] * (grid.dim - 1) + [tile_last])
    bids = _block_ids3_np(grid, Xn, support, tiles)
    B = int(np.prod([n // t for n, t in zip(grid.n, tiles)]))
    counts = np.bincount(bids, minlength=B)
    need = int(np.sum(-(-counts // chunk)))
    return max(8, int(math.ceil(need * slack)))


def pack_markers3(geom: BucketGeometry3, grid: StaggeredGrid,
                  X: jnp.ndarray,
                  weights: Optional[jnp.ndarray] = None,
                  nchunks: int = 1024,
                  overflow_cap: Optional[int] = None) -> PackedBuckets3:
    """Bucket markers by (x,y,z)-tile, pack into ``Q`` chunks of
    ``geom.cap`` slots in tile order. The sort/assign/scatter/overflow
    machinery is interaction_packed.chunk_pack_core — shared with the
    xy-packed engine so the two layouts cannot diverge; only the tile
    id (all dim axes here) and the x0 decode differ."""
    from ibamr_tpu.ops.interaction_packed import (chunk_pack_core,
                                                  default_overflow_cap)

    N, dim = X.shape
    if weights is None:
        weights = jnp.ones((N,), dtype=X.dtype)
    if overflow_cap is None:
        overflow_cap = default_overflow_cap(N)
    s = geom.support
    Q = int(nchunks)
    bid = jnp.zeros((N,), dtype=jnp.int32)
    for d in range(dim):
        xi = (X[:, d] - grid.x_lo[d]) / grid.dx[d] - 0.5
        j0 = jnp.floor(xi - 0.5 * s).astype(jnp.int32) + 1
        b = jnp.mod(j0, grid.n[d]) // geom.tile[d]
        bid = bid * geom.nblk[d] + b
    B = int(np.prod(geom.nblk))

    (Xb, wb, slot_of_marker, w_overflow, o_idx, o_w, n_over,
     exceeded, tid) = chunk_pack_core(bid, X, weights, Q, geom.cap, B,
                                      overflow_cap)
    x0 = []
    for d in range(dim):
        ids = tid
        for a in range(dim - 1, d, -1):
            ids = ids // geom.nblk[a]
        x0.append((ids % geom.nblk[d]) * geom.tile[d])
    return PackedBuckets3(Xb=Xb, wb=wb, slot_of_marker=slot_of_marker,
                          w_overflow=w_overflow, o_idx=o_idx, o_w=o_w,
                          any_overflow=n_over > 0, exceeded=exceeded,
                          x0=tuple(x0), tile_of_chunk=tid)


def _axis_weights3(geom, grid, b: PackedBuckets3, d: int, off: float,
                   phi):
    """(Q, c, width_d) delta weights over the footprint of axis d
    (footprint starts one cell below the tile origin)."""
    n = grid.n[d]
    xi = (b.Xb[..., d] - grid.x_lo[d]) / grid.dx[d] - off
    l = jnp.arange(geom.width[d], dtype=xi.dtype)
    base = b.x0[d].astype(xi.dtype)[:, None, None] - 1.0
    t = xi[..., None] - (base + l)
    t = jnp.mod(t + 0.5 * n, float(n)) - 0.5 * n
    return phi(t)


def _tile_weights3(geom, grid, b: PackedBuckets3, centering,
                   kernel: Kernel):
    """A (Q, c, P) over the first dim-1 axes + Wz (Q, c, w_last)."""
    support, phi0 = get_kernel(kernel)
    phi = _phi_safe(phi0, support)
    offs = _centering_offsets(grid, centering)
    dim = grid.dim
    Ws = [_axis_weights3(geom, grid, b, d, offs[d], phi)
          for d in range(dim)]
    A = Ws[0]
    for W in Ws[1:-1]:
        A = A[..., :, None] * W[..., None, :]
        A = A.reshape(A.shape[0], A.shape[1], -1)
    return A, Ws[-1]


def _fold_spills_to_grid(geom, grid, T: jnp.ndarray) -> jnp.ndarray:
    """Spill-folding overlap-add: T in the CONTRACTION-OUTPUT layout
    (nb0[, nb1], nb2, w_last, w0[, w1]) -> grid.

    Per axis, the spill segment [tile, width) of block b lies entirely
    inside block b+1's core [0, s+1) (guaranteed by tile >= s+1), so a
    roll-by-one-block + add on the SMALL tile tensor replaces the
    masked grid-size accumulation of interaction_fast._overlap_add.
    Folding happens IN the contraction layout (largest axis first, so
    every later pass touches a smaller tensor and no pre-transpose of
    the widths tensor is ever materialized); only the folded core —
    exactly grid-sized — pays the interleave transpose. Footprint base
    = tile origin - 1 -> one final multi-axis roll(-1)."""
    dim = grid.dim
    nb, tl, wd = geom.nblk, geom.tile, geom.width
    # width-axis position for block axis d in the contraction layout
    w_ax = {dim - 1: dim}
    for d in range(dim - 1):
        w_ax[d] = dim + 1 + d
    # fold the largest-relative-shrink axes first, so every later
    # pass touches the smallest possible tensor
    for d in sorted(range(dim), key=lambda a: tl[a] / wd[a]):
        ax_b, ax_w = d, w_ax[d]
        core = jax.lax.slice_in_dim(T, 0, tl[d], axis=ax_w)
        spill = jax.lax.slice_in_dim(T, tl[d], wd[d], axis=ax_w)
        spill = jnp.roll(spill, 1, axis=ax_b)    # periodic successor
        pad = [(0, 0)] * core.ndim
        pad[ax_w] = (0, tl[d] - (wd[d] - tl[d]))
        T = core + jnp.pad(spill, pad)
    perm = []
    for d in range(dim):
        perm += [d, w_ax[d]]
    out = T.transpose(perm).reshape(grid.n)
    return jnp.roll(out, (-1,) * dim, tuple(range(dim)))


def _extract_tiles3(geom, grid, f: jnp.ndarray) -> jnp.ndarray:
    """Gather every block's (width...) footprint -> (B, w_last, P)
    with the xy-footprint combined on the MINOR axis (P on lanes)."""
    dim = grid.dim
    arr = f
    for d in range(dim):
        idx = (np.arange(geom.nblk[d])[:, None] * geom.tile[d] - 1
               + np.arange(geom.width[d])[None, :]) % grid.n[d]
        arr = jnp.take(arr, jnp.asarray(idx.reshape(-1)), axis=2 * d)
        arr = arr.reshape(arr.shape[:2 * d]
                          + (geom.nblk[d], geom.width[d])
                          + arr.shape[2 * d + 1:])
    # arr: (nb0, w0[, nb1, w1], nb2, w2) -> (B, w_last, P)
    B = int(np.prod(geom.nblk))
    if dim == 2:
        arr = arr.transpose(0, 2, 3, 1)          # nb0 nb1 w1 w0
        return arr.reshape(B, geom.width[1], geom.width[0])
    arr = arr.transpose(0, 2, 4, 5, 1, 3)        # nb0 nb1 nb2 w2 w0 w1
    return arr.reshape(B, geom.width[dim - 1],
                       int(np.prod(geom.width[:dim - 1])))


def spread_packed3(geom: BucketGeometry3, grid: StaggeredGrid,
                   b: PackedBuckets3, F: jnp.ndarray, X: jnp.ndarray,
                   centering, kernel: Kernel,
                   precision=jax.lax.Precision.HIGHEST,
                   compute_dtype=None) -> jnp.ndarray:
    """Spread marker values F (N,) -> grid field (exact vs the scatter
    oracle up to roundoff; overflow through the shared fallback)."""
    inv_vol = 1.0 / math.prod(grid.dx)
    Ff = bucketed_channel(b, F)
    A, Wz = _tile_weights3(geom, grid, b, centering, kernel)
    A = A * (Ff * b.wb * inv_vol)[..., None]
    # (Q, w_last, P): footprint P on the minor (lane) axis
    Tq = contract_compressed("qmp,qmw->qwp", A, Wz, compute_dtype,
                             precision=precision)
    B = int(np.prod(geom.nblk))
    T = jax.ops.segment_sum(Tq, b.tile_of_chunk, num_segments=B,
                            indices_are_sorted=True)
    dim = grid.dim
    # stay in the contraction layout — the fold shrinks the tensor
    # BEFORE any transpose materializes
    T = T.reshape(tuple(geom.nblk) + (geom.width[dim - 1],)
                  + tuple(geom.width[:dim - 1]))
    out = _fold_spills_to_grid(geom, grid, T)
    return spread_overflow_fallbacks(out, b, F, X, grid, centering,
                                     kernel)


def interpolate_packed3(geom: BucketGeometry3, grid: StaggeredGrid,
                        b: PackedBuckets3, f: jnp.ndarray,
                        X: jnp.ndarray, centering, kernel: Kernel,
                        precision=jax.lax.Precision.HIGHEST,
                        compute_dtype=None) -> jnp.ndarray:
    """Interpolate grid field at markers -> (N,) (adjoint of spread)."""
    T = _extract_tiles3(geom, grid, f)               # (B, w_last, P)
    Tq = jnp.take(T, b.tile_of_chunk, axis=0)        # (Q, w_last, P)
    A, Wz = _tile_weights3(geom, grid, b, centering, kernel)
    D = contract_compressed("qwp,qmw->qmp", Tq, Wz, compute_dtype,
                            precision=precision)
    Ub = jnp.sum(A * D, axis=-1) * b.wb              # (Q, c)
    return unbucket_with_overflow(Ub, b, f, X, grid, centering, kernel)


class PackedInteraction3:
    """Drop-in FastInteraction-shaped engine: fully-blocked
    occupancy-packed chunks + spill-folding overlap-add. Bucket+pack
    once per X (``buckets``), reuse for all components and both
    directions within a step (the ctx protocol)."""

    def __init__(self, grid: StaggeredGrid, kernel: Kernel = "IB_4",
                 tile: int = 8, tile_last: int = 16, chunk: int = 64,
                 nchunks: int = 2048,
                 overflow_cap: Optional[int] = None,
                 compute_dtype=None):
        self.grid = grid
        self.kernel: Kernel = kernel
        self.geom = make_geometry3(grid, kernel, tile=tile,
                                   tile_last=tile_last, cap=chunk)
        self.nchunks = int(nchunks)
        self.overflow_cap = overflow_cap
        self.compute_dtype = compute_dtype

    def buckets(self, X: jnp.ndarray,
                weights: Optional[jnp.ndarray] = None
                ) -> PackedBuckets3:
        return pack_markers3(self.geom, self.grid, X, weights,
                             nchunks=self.nchunks,
                             overflow_cap=self.overflow_cap)

    def interpolate_vel(self, u: Vel, X: jnp.ndarray,
                        weights: Optional[jnp.ndarray] = None,
                        b: Optional[PackedBuckets3] = None
                        ) -> jnp.ndarray:
        if b is None:
            b = self.buckets(X, weights)
        cols = [interpolate_packed3(self.geom, self.grid, b, u[d], X,
                                    d, self.kernel,
                                    compute_dtype=self.compute_dtype)
                for d in range(self.grid.dim)]
        return jnp.stack(cols, axis=-1)

    def spread_vel(self, F: jnp.ndarray, X: jnp.ndarray,
                   weights: Optional[jnp.ndarray] = None,
                   b: Optional[PackedBuckets3] = None) -> Vel:
        if b is None:
            b = self.buckets(X, weights)
        return tuple(spread_packed3(self.geom, self.grid, b, F[:, d],
                                    X, d, self.kernel,
                                    compute_dtype=self.compute_dtype)
                     for d in range(self.grid.dim))
