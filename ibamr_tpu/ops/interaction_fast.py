"""MXU-formulated spread/interpolate: bucketed one-hot matmul kernels.

Reference parity: same operations as :mod:`ibamr_tpu.ops.interaction`
(``LEInteractor::spread/interpolate``, T2 — the north-star hot path) —
bitwise-equivalent math, radically different schedule.

The problem with the direct formulation: XLA lowers the 64-point-per-
marker scatter-add serially on TPU (~230 ms for 1e5 markers at 256^3).
TPU-first redesign (SURVEY.md §7.3 hard-part #1): turn the scatter into
DENSE MATMULS so the MXU does it:

1. **Bucket** markers by the (x, y) tile containing their stencil origin
   (one argsort + one scatter of N elements — cheap); fixed capacity
   ``cap`` per tile (static shapes), overflow handled exactly by a
   masked fallback to the scatter path under ``lax.cond``.
2. **Dense per-axis weights.** For each marker evaluate the delta
   kernel at ALL 13 = T+5 x-offsets of its tile (and 13 y-offsets) —
   compact support makes everything outside the true 4-point stencil
   exactly zero — and at all Nz wrapped z-offsets. No index arithmetic
   survives into the hot loop.
3. **Tensor-product accumulation as matmul.** Per tile b:
       spread:  T[b, xy, z] = sum_m (Wx (x) Wy * F)[b, m, xy] Wz[b, m, z]
       interp:  U[b, m] = sum_xy A[b, m, xy] sum_z T[b, xy, z] Wz[b, m, z]
   — batched (169, cap) x (cap, Nz) contractions that run on the MXU at
   TFLOP rates instead of serialized scatter updates.
4. **Overlap-add** the (13, 13, Nz) tiles into the periodic grid with
   core/spill reshapes + rolls (pure data movement).

The weights are the same ``delta.get_kernel`` functions, so spread and
interp remain exact adjoints of each other and agree with the reference
formulation to floating-point roundoff (enforced by tests).
"""

from __future__ import annotations

import math
from functools import partial
from typing import NamedTuple, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import interaction
from ibamr_tpu.ops.delta import Kernel, get_kernel
from ibamr_tpu.ops.interaction import _centering_offsets

Vel = Tuple[jnp.ndarray, ...]

# Debug-mode enforcement of the compact_overflow pad convention
# (ADVICE r5 item 4): pad slots of the compact overflow list carry the
# REAL marker index order[N-1] with weight 0, so correctness requires
# every consumer to weight contributions by ``o_w`` — a 0 weight makes
# the pad entry inert unless the aliased marker's value is non-finite
# (0 * inf = nan) or a future engine family forgets the weighting.
# With the flag on (env IBAMR_TPU_DEBUG_OVERFLOW=1, or set
# ``debug_overflow_pad(True)``), both consumers re-derive their compact
# contribution with pad entries hard-masked and assert bitwise
# agreement at runtime via jax.debug.callback.
import os as _os

_DEBUG_OVERFLOW_PAD = bool(int(_os.environ.get(
    "IBAMR_TPU_DEBUG_OVERFLOW", "0")))


def debug_overflow_pad(enabled: bool) -> bool:
    """Toggle the pad-inertness debug check; returns the previous
    value. Takes effect at TRACE time — flip it before jitting."""
    global _DEBUG_OVERFLOW_PAD
    prev, _DEBUG_OVERFLOW_PAD = _DEBUG_OVERFLOW_PAD, bool(enabled)
    return prev


def _check_pad_inert(tag: str, with_pads: jnp.ndarray,
                     pads_masked: jnp.ndarray) -> None:
    def _host_check(a, b):
        if not np.array_equal(np.asarray(a), np.asarray(b)):
            raise FloatingPointError(
                f"compact_overflow pad convention violated in {tag}: "
                f"o_w == 0 pad entries contributed to the result "
                f"(non-finite aliased marker value, or a consumer "
                f"not weighting by o_w)")
    jax.debug.callback(_host_check, with_pads, pads_masked)


class BucketGeometry(NamedTuple):
    """Static bucketing configuration (python ints -> one compilation)."""
    tile: Tuple[int, ...]     # tile extent per blocked axis (all but last)
    nblk: Tuple[int, ...]     # number of tiles per blocked axis
    cap: int                  # marker capacity per tile
    support: int              # delta support s
    width: Tuple[int, ...]    # tile + s + 1 per blocked axis


def make_geometry(grid: StaggeredGrid, kernel: Kernel = "IB_4",
                  tile: int = 8, cap: int = 256) -> BucketGeometry:
    support, _ = get_kernel(kernel)
    blocked = grid.n[:-1]
    if tile < support + 1:
        # the spill segment (support+1 wide) must fit inside one tile,
        # or _overlap_add would silently drop it
        raise ValueError(
            f"tile {tile} must be >= support+1 = {support + 1}")
    for n in blocked:
        if n % tile != 0:
            raise ValueError(f"grid extent {n} not divisible by tile {tile}")
        if n < tile + support + 1:
            # footprint wider than the axis: the wrapped footprint would
            # overlap itself and double-count
            raise ValueError(
                f"grid extent {n} too small for tile {tile} + "
                f"support {support} + 1")
    return BucketGeometry(
        tile=tuple(tile for _ in blocked),
        nblk=tuple(n // tile for n in blocked),
        cap=int(cap),
        support=int(support),
        width=tuple(tile + support + 1 for _ in blocked))


def suggest_cap(grid: StaggeredGrid, X, kernel: Kernel = "IB_4",
                tile: int = 8, slack: float = 1.5) -> int:
    """Host-side capacity heuristic from a concrete marker distribution:
    1.5x the max tile occupancy, rounded up to a multiple of 8."""
    Xn = np.asarray(X)
    support, _ = get_kernel(kernel)
    bids = _block_ids_np(grid, Xn, support, tile)
    counts = np.bincount(bids, minlength=int(np.prod(
        [n // tile for n in grid.n[:-1]])))
    cap = int(math.ceil(max(1, counts.max()) * slack / 8.0) * 8)
    return cap


def _block_ids_np(grid, Xn, support, tile):
    dim = grid.dim
    bid = np.zeros(len(Xn), dtype=np.int64)
    for d in range(dim - 1):
        xi = (Xn[:, d] - grid.x_lo[d]) / grid.dx[d] - 0.5
        j0 = np.floor(xi - 0.5 * support).astype(np.int64) + 1
        b = np.mod(j0, grid.n[d]) // tile
        bid = bid * (grid.n[d] // tile) + b
    return bid


class Buckets(NamedTuple):
    """Per-call bucketed marker layout (all shapes static)."""
    Xb: jnp.ndarray         # (B, cap, dim) positions (junk in empty slots)
    wb: jnp.ndarray         # (B, cap) weights incl. 0 padding
    slot_of_marker: jnp.ndarray   # (N,) flat slot index or B*cap (dropped)
    w_overflow: jnp.ndarray       # (N,) weights of dropped markers
    o_idx: jnp.ndarray      # (ocap,) original indices of overflow markers
    o_w: jnp.ndarray        # (ocap,) their weights (0 in pad slots)
    any_overflow: jnp.ndarray     # () bool
    exceeded: jnp.ndarray   # () bool: overflow count > ocap (rare)
    x0: Tuple[jnp.ndarray, ...]   # per blocked axis: (B,) tile origin cell


def compact_overflow(order: jnp.ndarray, keep: jnp.ndarray,
                     slot_sorted: jnp.ndarray, weights: jnp.ndarray,
                     N: int, overflow_cap: int):
    """Shared overflow machinery for every bucketed/packed layout (one
    definition so the pad-slot conventions the downstream fallbacks
    rely on cannot diverge between engine families): the per-ORIGINAL-
    marker slot / overflow-weight write-back (``order`` is a
    permutation -> unique-indices scatters) and the compact overflow
    list via sized nonzero (positions come out in the same increasing
    order a stable argsort produced; pad entries carry weight 0).
    Returns (slot_of_marker, w_overflow, o_idx, o_w, n_over,
    exceeded)."""
    slot_of_marker = jnp.zeros((N,), dtype=jnp.int32).at[order].set(
        slot_sorted.astype(jnp.int32), unique_indices=True)
    w_overflow = jnp.zeros((N,), dtype=weights.dtype).at[order].set(
        jnp.where(keep, 0.0, weights[order]), unique_indices=True)
    o_pos = jnp.nonzero(~keep, size=overflow_cap, fill_value=N)[0]
    o_valid = o_pos < N
    o_pos_c = jnp.minimum(o_pos, N - 1)
    o_idx = order[o_pos_c].astype(jnp.int32)
    o_w = jnp.where(o_valid, weights[order[o_pos_c]], 0.0)
    n_over = N - jnp.sum(keep)
    return (slot_of_marker, w_overflow, o_idx, o_w, n_over,
            n_over > overflow_cap)


def bucket_markers(geom: BucketGeometry, grid: StaggeredGrid,
                   X: jnp.ndarray,
                   weights: Optional[jnp.ndarray] = None,
                   overflow_cap: Optional[int] = None) -> Buckets:
    N, dim = X.shape
    if weights is None:
        weights = jnp.ones((N,), dtype=X.dtype)
    if overflow_cap is None:
        overflow_cap = min(N, max(2048, 1 << int(math.ceil(
            math.log2(max(N // 8, 1))))))
    s = geom.support
    # block id per marker from the cell-centered stencil origin
    bid = jnp.zeros((N,), dtype=jnp.int32)
    for d in range(dim - 1):
        xi = (X[:, d] - grid.x_lo[d]) / grid.dx[d] - 0.5
        j0 = jnp.floor(xi - 0.5 * s).astype(jnp.int32) + 1
        b = jnp.mod(j0, grid.n[d]) // geom.tile[d]
        bid = bid * geom.nblk[d] + b
    B = int(np.prod(geom.nblk))
    cap = geom.cap

    order = jnp.argsort(bid)
    bid_s = bid[order]
    edges = jnp.searchsorted(bid_s,
                             jnp.arange(B + 1, dtype=bid_s.dtype))
    start, counts = edges[:-1], jnp.diff(edges).astype(jnp.int32)
    rank = jnp.arange(N, dtype=jnp.int32) - start[bid_s].astype(jnp.int32)
    keep = rank < cap
    slot_sorted = jnp.where(keep, bid_s * cap + rank, B * cap)

    # slot -> sorted-marker position as pure GATHERS (TPU scatter over
    # 1e5 indices serializes; gather of the same layout does not —
    # bitwise-identical pool to the old scatter construction)
    slot_b = jnp.arange(B * cap, dtype=jnp.int32) // cap
    slot_r = jnp.arange(B * cap, dtype=jnp.int32) % cap
    src = jnp.where(slot_r < counts[slot_b],
                    start[slot_b].astype(jnp.int32) + slot_r, N)
    Xb = jnp.take(X[order], src, axis=0, mode="fill",
                  fill_value=0).reshape(B, cap, dim)
    wb = jnp.take(weights[order], src, mode="fill",
                  fill_value=0).reshape(B, cap)

    (slot_of_marker, w_overflow, o_idx, o_w, n_over,
     exceeded) = compact_overflow(order, keep, slot_sorted, weights, N,
                                  overflow_cap)

    # tile origins per blocked axis, broadcast over the flat block index
    x0 = []
    for d in range(dim - 1):
        ids = jnp.arange(B, dtype=jnp.int32)
        for a in range(dim - 1 - 1, d, -1):
            ids = ids // geom.nblk[a]
        x0.append((ids % geom.nblk[d]) * geom.tile[d])
    return Buckets(Xb=Xb, wb=wb, slot_of_marker=slot_of_marker,
                   w_overflow=w_overflow, o_idx=o_idx, o_w=o_w,
                   any_overflow=n_over > 0, exceeded=exceeded,
                   x0=tuple(x0))


# -- dense per-axis weights --------------------------------------------------

def _phi_safe(phi, support):
    half = 0.5 * support

    def f(t):
        inside = jnp.abs(t) < half
        return jnp.where(inside, phi(jnp.clip(t, -half, half)), 0.0)
    return f


def _blocked_axis_weights(geom, grid, b: Buckets, d: int, off: float, phi):
    """(B, cap, width) weights over the tile footprint of blocked axis d
    (footprint starts one cell below the tile origin)."""
    n = grid.n[d]
    xi = (b.Xb[..., d] - grid.x_lo[d]) / grid.dx[d] - off   # (B, cap)
    l = jnp.arange(geom.width[d], dtype=xi.dtype)
    base = b.x0[d].astype(xi.dtype)[:, None, None] - 1.0
    t = xi[..., None] - (base + l)
    # markers whose wrapped stencil landed them in an edge tile sit a
    # full period away from the footprint coordinates
    t = jnp.mod(t + 0.5 * n, float(n)) - 0.5 * n
    return phi(t)


def _full_axis_weights(grid, b: Buckets, d: int, off: float, phi):
    """(B, cap, n_d) wrapped weights over the full (periodic) last axis."""
    n = grid.n[d]
    xi = (b.Xb[..., d] - grid.x_lo[d]) / grid.dx[d] - off
    k = jnp.arange(n, dtype=xi.dtype)
    t = xi[..., None] - k
    t = jnp.mod(t + 0.5 * n, float(n)) - 0.5 * n
    return phi(t)


def _tile_weights(geom, grid, b: Buckets, centering, kernel):
    support, phi0 = get_kernel(kernel)
    phi = _phi_safe(phi0, support)
    offs = _centering_offsets(grid, centering)
    dim = grid.dim
    Ws = [_blocked_axis_weights(geom, grid, b, d, offs[d], phi)
          for d in range(dim - 1)]
    Wlast = _full_axis_weights(grid, b, dim - 1, offs[dim - 1], phi)
    # combine blocked axes into one footprint axis p
    A = Ws[0]
    for W in Ws[1:]:
        A = A[..., :, None] * W[..., None, :]
        A = A.reshape(A.shape[0], A.shape[1], -1)
    return A, Wlast       # (B, cap, P), (B, cap, n_last)


# -- overlap-add / tile extraction -------------------------------------------

def _overlap_add(geom, grid, T: jnp.ndarray) -> jnp.ndarray:
    """Accumulate tiles T (B, w0[, w1], n_last) into the periodic grid:
    split each blocked axis into core [0, tile) and spill [tile, width)
    segments, reshape each combination onto the grid, roll into place."""
    dim = grid.dim
    nb = geom.nblk
    tl = geom.tile
    wd = geom.width
    n_last = grid.n[dim - 1]
    B = T.shape[0]
    T = T.reshape(tuple(nb) + tuple(wd) + (n_last,))
    nblocked = dim - 1
    out = jnp.zeros(grid.n, dtype=T.dtype)
    for mask in range(2 ** nblocked):
        seg = T
        shift = []
        ok = True
        for d in range(nblocked):
            spill = (mask >> d) & 1
            lo, hi = (0, tl[d]) if not spill else (tl[d], wd[d])
            sl = [slice(None)] * seg.ndim
            sl[nblocked + d] = slice(lo, hi)
            seg = seg[tuple(sl)]
            # pad segment length up to tile (spill is s+1 <= tile)
            pad = tl[d] - (hi - lo)
            if pad < 0:
                ok = False
                break
            if pad:
                pw = [(0, 0)] * seg.ndim
                pw[nblocked + d] = (0, pad)
                seg = jnp.pad(seg, pw)
            # core starts at x0 - 1; spill starts at x0 + tile - 1
            shift.append(-1 if not spill else tl[d] - 1)
        if not ok:
            continue
        # interleave (nb, tile) axis pairs -> grid layout
        perm = []
        for d in range(nblocked):
            perm += [d, nblocked + d]
        perm += [2 * nblocked]
        seg = seg.transpose(perm).reshape(grid.n)
        for d in range(nblocked):
            seg = jnp.roll(seg, shift[d], axis=d)
        out = out + seg
    return out


def _extract_tiles(geom, grid, f: jnp.ndarray) -> jnp.ndarray:
    """Gather the (width..., n_last) tile of every block -> (B, P, n_last)."""
    dim = grid.dim
    nblocked = dim - 1
    arr = f
    # take along each blocked axis: axis d of arr is the grid axis d
    for d in range(nblocked):
        idx = (np.arange(geom.nblk[d])[:, None] * geom.tile[d] - 1
               + np.arange(geom.width[d])[None, :]) % grid.n[d]
        arr = jnp.take(arr, jnp.asarray(idx.reshape(-1)), axis=2 * d)
        arr = arr.reshape(arr.shape[:2 * d]
                          + (geom.nblk[d], geom.width[d])
                          + arr.shape[2 * d + 1:])
    # arr: (nb0, w0[, nb1, w1], n_last) -> (B, P, n_last)
    if nblocked == 1:
        B = geom.nblk[0]
        return arr.reshape(B, geom.width[0], grid.n[dim - 1])
    perm = (0, 2, 1, 3, 4)
    arr = arr.transpose(perm)
    B = geom.nblk[0] * geom.nblk[1]
    return arr.reshape(B, geom.width[0] * geom.width[1], grid.n[dim - 1])


# -- public ops --------------------------------------------------------------

def bucketed_channel(b: Buckets, F: jnp.ndarray) -> jnp.ndarray:
    """Scatter a per-marker channel (N,) into the bucket-slot layout
    (B, cap) of ``b`` (shared by the MXU and Pallas spread engines)."""
    Ff = jnp.zeros((b.Xb.shape[0] * b.Xb.shape[1] + 1,), dtype=F.dtype)
    return Ff.at[b.slot_of_marker].add(F)[:-1].reshape(b.wb.shape)


def spread_overflow_fallbacks(out: jnp.ndarray, b: Buckets,
                              F: jnp.ndarray, X: jnp.ndarray,
                              grid: StaggeredGrid, centering,
                              kernel: Kernel) -> jnp.ndarray:
    """Accumulate the overflow markers' contribution into ``out``:
    compact scatter for the buffered overflow, exact full-scatter when
    the buffer itself overflowed (shared by both bucketed engines)."""
    def compact(o):
        # pad slots rely on o_w == 0 making them inert (the index
        # aliases a real marker — compact_overflow's convention)
        res = interaction.spread(F[b.o_idx], grid, X[b.o_idx],
                                 centering=centering, kernel=kernel,
                                 weights=b.o_w, out=o)
        if _DEBUG_OVERFLOW_PAD:
            live = b.o_w != 0
            masked = interaction.spread(
                jnp.where(live, F[b.o_idx], 0.0), grid, X[b.o_idx],
                centering=centering, kernel=kernel, weights=b.o_w,
                out=o)
            _check_pad_inert("spread_overflow_fallbacks", res, masked)
        return res

    def full(o):
        return interaction.spread(F, grid, X, centering=centering,
                                  kernel=kernel, weights=b.w_overflow,
                                  out=o)

    return jax.lax.cond(
        b.exceeded, full,
        lambda o: jax.lax.cond(b.any_overflow, compact,
                               lambda oo: oo, o), out)


def contract_compressed(spec: str, a, b, compute_dtype,
                        precision=jax.lax.Precision.HIGHEST):
    """The one transfer-engine contraction point: exact f32 einsum, or
    bf16-compressed operands with f32 accumulation when
    ``compute_dtype`` is set (the (B,cap,P)/(B,cap,nz) operands are the
    dominant HBM traffic of the whole IB step — PERF.md round-3
    breakdown; compression costs ~3 decimal digits of delta-weight
    precision, pinned by tests). Shared by the MXU and packed engines
    in both directions so the scheme cannot diverge between them."""
    if compute_dtype is not None:
        return jnp.einsum(spec, a.astype(compute_dtype),
                          b.astype(compute_dtype),
                          preferred_element_type=jnp.float32
                          ).astype(a.dtype)
    return jnp.einsum(spec, a, b, precision=precision)


def spread_bucketed(geom: BucketGeometry, grid: StaggeredGrid,
                    b: Buckets, F: jnp.ndarray, X: jnp.ndarray,
                    centering, kernel: Kernel,
                    compute_dtype=None) -> jnp.ndarray:
    """Spread marker values F (N,) -> grid field; exact up to roundoff
    vs interaction.spread (overflow markers go through that path).

    Marker weights are the ones baked into ``b`` at bucket-build time
    (``b.wb``/``b.o_w``/``b.w_overflow``) — there is deliberately no
    per-call weights argument here, so stale-weights misuse is
    impossible (ADVICE round 1)."""
    inv_vol = 1.0 / math.prod(grid.dx)
    Ff = bucketed_channel(b, F)
    A, Wlast = _tile_weights(geom, grid, b, centering, kernel)
    A = A * (Ff * b.wb * inv_vol)[..., None]
    T = contract_compressed("bmp,bmz->bpz", A, Wlast, compute_dtype)
    out = _overlap_add(geom, grid, T.reshape(
        (T.shape[0],) + tuple(geom.width) + (grid.n[grid.dim - 1],)))
    return spread_overflow_fallbacks(out, b, F, X, grid, centering,
                                     kernel)


def unbucket_with_overflow(Ub: jnp.ndarray, b: Buckets, f: jnp.ndarray,
                           X: jnp.ndarray, grid: StaggeredGrid,
                           centering, kernel: Kernel) -> jnp.ndarray:
    """Scatter per-slot interpolants Ub (B, cap) back to marker order
    and add the overflow markers' contribution (compact gather for the
    buffered overflow, exact full gather when the buffer itself
    overflowed) — the interp twin of spread_overflow_fallbacks, shared
    by the MXU and Pallas engines."""
    U = jnp.take(Ub.reshape(-1), jnp.minimum(
        b.slot_of_marker, Ub.size - 1), axis=0)
    U = jnp.where(b.slot_of_marker < Ub.size, U, 0.0)

    def compact(U):
        # pad slots rely on o_w == 0 making them inert (the index
        # aliases a real marker — compact_overflow's convention)
        Uo = interaction.interpolate(f, grid, X[b.o_idx],
                                     centering=centering, kernel=kernel,
                                     weights=b.o_w)
        if _DEBUG_OVERFLOW_PAD:
            _check_pad_inert(
                "unbucket_with_overflow",
                jnp.where(b.o_w != 0, 0.0, Uo),
                jnp.zeros_like(Uo))
        return U.at[b.o_idx].add(Uo)

    def full(U):
        return U + interaction.interpolate(
            f, grid, X, centering=centering, kernel=kernel,
            weights=b.w_overflow)

    return jax.lax.cond(
        b.exceeded, full,
        lambda u: jax.lax.cond(b.any_overflow, compact,
                               lambda uu: uu, u), U)


def interpolate_bucketed(geom: BucketGeometry, grid: StaggeredGrid,
                         b: Buckets, f: jnp.ndarray, X: jnp.ndarray,
                         centering, kernel: Kernel,
                         compute_dtype=None) -> jnp.ndarray:
    """Interpolate grid field at markers -> (N,) (adjoint of spread).
    Marker weights come from ``b`` only — see spread_bucketed."""
    T = _extract_tiles(geom, grid, f)                 # (B, P, n_last)
    A, Wlast = _tile_weights(geom, grid, b, centering, kernel)
    D = contract_compressed("bpz,bmz->bmp", T, Wlast, compute_dtype)
    # wb already carries the caller's marker weights (bucket_markers)
    Ub = jnp.sum(A * D, axis=-1) * b.wb               # (B, cap)
    return unbucket_with_overflow(Ub, b, f, X, grid, centering, kernel)


class FastInteraction:
    """Drop-in spread/interp engine: bucket once per X, reuse for all
    components and both directions within a timestep.

    Marker ``weights`` are baked into the Buckets at build time; when a
    prebuilt ``b`` is passed to spread/interp, the ``weights`` argument
    is used only as the build input for ``b is None`` and MUST match
    what the buckets were built with.
    """

    def __init__(self, grid: StaggeredGrid, kernel: Kernel = "IB_4",
                 tile: int = 8, cap: int = 256,
                 overflow_cap: Optional[int] = None,
                 compute_dtype=None):
        self.grid = grid
        self.kernel: Kernel = kernel
        self.geom = make_geometry(grid, kernel, tile=tile, cap=cap)
        self.overflow_cap = overflow_cap
        # None = exact f32 contractions; jnp.bfloat16 = compressed
        # operands (see spread_bucketed)
        self.compute_dtype = compute_dtype

    def buckets(self, X: jnp.ndarray,
                weights: Optional[jnp.ndarray] = None) -> Buckets:
        return bucket_markers(self.geom, self.grid, X, weights,
                              overflow_cap=self.overflow_cap)

    def interpolate_vel(self, u: Vel, X: jnp.ndarray,
                        weights: Optional[jnp.ndarray] = None,
                        b: Optional[Buckets] = None) -> jnp.ndarray:
        if b is None:
            b = self.buckets(X, weights)
        cols = [interpolate_bucketed(self.geom, self.grid, b, u[d], X,
                                     d, self.kernel,
                                     compute_dtype=self.compute_dtype)
                for d in range(self.grid.dim)]
        return jnp.stack(cols, axis=-1)

    def spread_vel(self, F: jnp.ndarray, X: jnp.ndarray,
                   weights: Optional[jnp.ndarray] = None,
                   b: Optional[Buckets] = None) -> Vel:
        if b is None:
            b = self.buckets(X, weights)
        return tuple(spread_bucketed(self.geom, self.grid, b, F[:, d], X,
                                     d, self.kernel,
                                     compute_dtype=self.compute_dtype)
                     for d in range(self.grid.dim))
