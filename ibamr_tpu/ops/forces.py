"""Lagrangian force generation: springs, beams, target points.

Reference parity: ``IBStandardForceGen`` + the force-spec classes
(``IBSpringForceSpec``, ``IBBeamForceSpec``, ``IBTargetPointForceSpec``,
P11, SURVEY.md §2.2 and §3.2):

  springs: F_i += k (|X_j - X_i| - L0) * (X_j - X_i)/|X_j - X_i|   (+ reaction)
  beams:   F -= c * D^4 X   via curvature D = X_prev - 2 X_mid + X_next - C0
  targets: F_i += kappa (X0_i - X_i) - eta U_i

TPU-first redesign (SURVEY.md §7.1 pillar 4): the reference's per-node
``Streamable`` spec objects become padded structure-of-arrays index lists;
force evaluation is vectorized gathers + one ``segment_sum`` scatter per
spec family — no serialization layer, no per-node objects. All shapes are
static, so the whole Lagrangian force evaluation fuses into the jitted
timestep.

Inactive pool slots are handled by per-spec ``enabled`` masks (0/1 floats),
the analog of marker-capacity padding.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp


class SpringSpecs(NamedTuple):
    """M springs between marker indices idx0[m] -- idx1[m]."""
    idx0: jnp.ndarray        # (M,) int32
    idx1: jnp.ndarray        # (M,) int32
    stiffness: jnp.ndarray   # (M,)
    rest_length: jnp.ndarray  # (M,)
    enabled: jnp.ndarray     # (M,) 0/1 mask (padding support)


class BeamSpecs(NamedTuple):
    """M bending elements (prev, mid, next) with rigidity and optional
    rest curvature."""
    prev: jnp.ndarray        # (M,) int32
    mid: jnp.ndarray         # (M,) int32
    nxt: jnp.ndarray         # (M,) int32
    rigidity: jnp.ndarray    # (M,)
    rest_curvature: jnp.ndarray  # (M, dim)
    enabled: jnp.ndarray     # (M,)


class TargetSpecs(NamedTuple):
    """M tether points anchoring marker idx[m] to X_target[m]."""
    idx: jnp.ndarray         # (M,) int32
    stiffness: jnp.ndarray   # (M,)
    damping: jnp.ndarray     # (M,)
    X_target: jnp.ndarray    # (M, dim)
    enabled: jnp.ndarray     # (M,)


class ForceSpecs(NamedTuple):
    springs: Optional[SpringSpecs] = None
    beams: Optional[BeamSpecs] = None
    targets: Optional[TargetSpecs] = None


def make_springs(idx0, idx1, stiffness, rest_length,
                 dtype=jnp.float32) -> SpringSpecs:
    idx0 = jnp.asarray(idx0, dtype=jnp.int32)
    return SpringSpecs(
        idx0=idx0,
        idx1=jnp.asarray(idx1, dtype=jnp.int32),
        stiffness=jnp.asarray(stiffness, dtype=dtype),
        rest_length=jnp.asarray(rest_length, dtype=dtype),
        enabled=jnp.ones(idx0.shape, dtype=dtype))


def make_beams(prev, mid, nxt, rigidity, rest_curvature=None, dim=2,
               dtype=jnp.float32) -> BeamSpecs:
    prev = jnp.asarray(prev, dtype=jnp.int32)
    if rest_curvature is None:
        rest_curvature = jnp.zeros((prev.shape[0], dim), dtype=dtype)
    return BeamSpecs(
        prev=prev,
        mid=jnp.asarray(mid, dtype=jnp.int32),
        nxt=jnp.asarray(nxt, dtype=jnp.int32),
        rigidity=jnp.asarray(rigidity, dtype=dtype),
        rest_curvature=jnp.asarray(rest_curvature, dtype=dtype),
        enabled=jnp.ones(prev.shape, dtype=dtype))


def make_targets(idx, stiffness, X_target, damping=None,
                 dtype=jnp.float32) -> TargetSpecs:
    idx = jnp.asarray(idx, dtype=jnp.int32)
    if damping is None:
        damping = jnp.zeros(idx.shape, dtype=dtype)
    return TargetSpecs(
        idx=idx,
        stiffness=jnp.asarray(stiffness, dtype=dtype),
        damping=jnp.asarray(damping, dtype=dtype),
        X_target=jnp.asarray(X_target, dtype=dtype),
        enabled=jnp.ones(idx.shape, dtype=dtype))


def spring_energy(X: jnp.ndarray, s: SpringSpecs) -> jnp.ndarray:
    d = X[s.idx1] - X[s.idx0]
    length = jnp.sqrt(jnp.sum(d * d, axis=-1))
    return 0.5 * jnp.sum(
        s.enabled * s.stiffness * (length - s.rest_length) ** 2)


import collections
import threading

# insertion/access-ordered for single-entry LRU eviction; the lock
# keeps concurrent traces (multi-threaded jit) from interleaving
# get/insert. RLock: the weakref eviction finalizer below can fire
# during a GC triggered INSIDE the locked region.
_SCATTER_PLAN_CACHE: "collections.OrderedDict" = collections.OrderedDict()
_SCATTER_PLAN_LOCK = threading.RLock()
_SCATTER_PLAN_MAX = 64


def _scatter_plan(index_arrays, N: int):
    """Host-side assembly plan: concatenate the (static) per-family
    scatter indices, argsort them once, and build the static (N, K)
    GATHER table (row i = positions in the concatenated value list
    contributing to marker i, padded with the out-of-range sentinel
    M). Spec topology never changes between calls, so the sort runs
    once per spec set (cached) and the runtime assembly becomes pure
    gathers — TPU scatter-add with 1e5+ duplicate indices serializes
    (measured 13.1 ms of the flagship step at 256^3), and even the
    sorted ``segment_sum`` still lowers to an HLO scatter; the gather
    table removes the scatter entirely for bounded-degree topologies
    (the caller keeps the sorted segment_sum for hub topologies where
    K blows the table up). Returns (perm, sorted_ids, gather). Raises
    on traced indices; the caller falls back to scatter-add assembly."""
    key = tuple(id(a) for a in index_arrays) + (N,)
    with _SCATTER_PLAN_LOCK:
        hit = _SCATTER_PLAN_CACHE.get(key)
        if hit is not None:
            _SCATTER_PLAN_CACHE.move_to_end(key)    # LRU freshness
            return hit[0], hit[1], hit[2]
    import numpy as np
    ids = np.concatenate([np.asarray(a).ravel() for a in index_arrays])
    M = ids.shape[0]
    perm = np.argsort(ids, kind="stable").astype(np.int64)
    sorted_ids = ids[perm]
    counts = np.bincount(ids, minlength=N)
    K = int(counts.max()) if M else 0
    starts = np.concatenate([[0], np.cumsum(counts)])
    gather = np.full((N, max(K, 1)), M, dtype=np.int32)
    rank = np.arange(M, dtype=np.int64) - starts[sorted_ids]
    gather[sorted_ids, rank] = perm
    # cache NUMPY arrays: jnp constants minted inside a jit trace are
    # tracers, and caching a tracer across traces is a leak
    plan = (perm.astype(np.int32), sorted_ids.astype(np.int32), gather)
    # anchor the index arrays via weakrefs whose finalizer evicts the
    # entry: a discarded model's device buffers are freed rather than
    # pinned by the cache, and an id() can only be recycled AFTER its
    # entry is gone — no stale-hit hazard either way. Non-weakref-able
    # arrays are pinned strongly (same guarantee, costs their memory).
    import weakref

    def _evict(_ref, _key=key):
        with _SCATTER_PLAN_LOCK:
            _SCATTER_PLAN_CACHE.pop(_key, None)
    try:
        anchors = tuple(weakref.ref(a, _evict) for a in index_arrays)
    except TypeError:
        anchors = index_arrays
    with _SCATTER_PLAN_LOCK:
        while len(_SCATTER_PLAN_CACHE) >= _SCATTER_PLAN_MAX:
            # single-entry LRU eviction: the bound holds without
            # discarding every hot plan (cost of a miss is one re-sort)
            _SCATTER_PLAN_CACHE.popitem(last=False)
        _SCATTER_PLAN_CACHE[key] = (plan[0], plan[1], plan[2], anchors)
    return plan


def compute_lagrangian_force(X: jnp.ndarray, U: jnp.ndarray,
                             specs: ForceSpecs,
                             num_markers: Optional[int] = None) -> jnp.ndarray:
    """Assemble F(X, U) over all marker nodes -> (N, dim).

    ``num_markers`` must equal X.shape[0] (static); it exists only for
    clarity at call sites. When the spec index arrays are concrete
    (the usual case: topology is closed over by the jitted step), all
    family contributions accumulate through a static (N, K) gather
    table + axis sum — ZERO scatter ops in the compiled HLO (pinned
    by tests/test_forces_hlo.py). Hub topologies whose max degree K
    would blow the table up (N*K > 4*(M+N)) keep the sorted
    ``segment_sum``; traced indices fall back to scatter-adds.
    """
    N = X.shape[0] if num_markers is None else num_markers

    idx_arrays = []   # static scatter indices, one per contribution
    val_arrays = []   # matching (M, dim) contribution vectors

    if specs.springs is not None:
        s = specs.springs
        d = X[s.idx1] - X[s.idx0]                       # (M, dim)
        length = jnp.sqrt(jnp.sum(d * d, axis=-1))      # (M,)
        safe = jnp.where(length > 0, length, 1.0)
        tension = s.enabled * s.stiffness * (length - s.rest_length)
        fvec = (tension / safe)[:, None] * d            # force on idx0
        idx_arrays += [s.idx0, s.idx1]
        val_arrays += [fvec, -fvec]

    if specs.beams is not None:
        b = specs.beams
        D = (X[b.prev] - 2.0 * X[b.mid] + X[b.nxt]
             - b.rest_curvature)                        # (M, dim)
        cD = (b.enabled * b.rigidity)[:, None] * D
        idx_arrays += [b.prev, b.mid, b.nxt]
        val_arrays += [-cD, 2.0 * cD, -cD]

    if specs.targets is not None:
        tgt = specs.targets
        disp = tgt.X_target - X[tgt.idx]
        fvec = (tgt.enabled * tgt.stiffness)[:, None] * disp \
            - (tgt.enabled * tgt.damping)[:, None] * U[tgt.idx]
        idx_arrays += [tgt.idx]
        val_arrays += [fvec]

    if not idx_arrays:
        return jnp.zeros_like(X)

    try:
        perm, sorted_ids, gather = _scatter_plan(tuple(idx_arrays), N)
    except jax.errors.TracerArrayConversionError:
        F = jnp.zeros_like(X)
        for idx, val in zip(idx_arrays, val_arrays):
            F = F.at[idx].add(val)
        return F
    vals = jnp.concatenate(val_arrays, axis=0)
    M, K = vals.shape[0], gather.shape[1]
    if N * K <= 4 * (M + N):
        # bounded-degree topology (every real structure: springs/beams
        # touch each node a handful of times): gather rows + axis sum,
        # no scatter anywhere in the lowering
        contrib = jnp.take(vals, jnp.asarray(gather.reshape(-1)),
                           axis=0, mode="fill", fill_value=0)
        return jnp.sum(contrib.reshape(N, K, vals.shape[1]), axis=1)
    return jax.ops.segment_sum(vals[perm], sorted_ids, num_segments=N,
                               indices_are_sorted=True)
