"""Eulerian <-> Lagrangian interaction: spread and interpolate.

Reference parity: ``LEInteractor::spread`` / ``LEInteractor::interpolate``
(T2) + the marker-data side of ``LDataManager::spread/interp`` (T1) — the
signature IB operations and the north-star hot path (SURVEY.md §3.2):

  spread:      f(x_g) += sum_markers F_m prod_d phi((x_g - X_m)/h) / h^dim
  interpolate: U_m     = sum_grid    u(x_g) prod_d phi((x_g - X_m)/h)

TPU-first design (SURVEY.md §7.3 hard-part #1): markers are a fixed-shape
``(N, dim)`` array; for each marker the ``s^dim`` stencil weights are built
by broadcasting per-axis weight vectors (one fused elementwise kernel), and
the grid exchange is ONE flattened gather (interp) or scatter-add (spread)
— XLA lowers scatter-add with duplicate indices correctly, and under
sharding it becomes the irregular-communication step that the reference
implements with PETSc VecScatter ghost accumulation.

Spread and interpolate use the SAME weights, so they are exact adjoints:
  <spread(F), u> * h^dim == sum_m F_m . interp(u)_m
— the free correctness oracle the tests enforce.

An optional ``weights`` (marker mask) supports fixed-capacity marker pools
with inactive slots (SURVEY.md §7.1 pillar 1): masked markers contribute
nothing and interpolate to zero.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops.delta import Kernel, get_kernel_axes

Vel = Tuple[jnp.ndarray, ...]


def _centering_offsets(grid: StaggeredGrid, centering) -> Tuple[float, ...]:
    """Grid-unit coordinate of index j along axis d is j + offset_d.
    centering: "cell" | int component for face-centered | explicit tuple."""
    if centering == "cell":
        return (0.5,) * grid.dim
    if isinstance(centering, int):
        return tuple(0.0 if d == centering else 0.5 for d in range(grid.dim))
    return tuple(centering)


def _axis_weights_indices_raw(xi: jnp.ndarray, support: int, phi):
    """Per-axis stencil indices (UNWRAPPED — may be negative or >= n)
    and delta weights. The single source of the kernel-support index
    math, shared with the sharded engine (parallel.lagrangian), which
    needs contiguous indices for its halo-extended local buffers.

    xi: (N,) continuous grid-unit coordinate of the markers along this
    axis; returns j (N, support) int32, w (N, support).
    """
    j0 = jnp.floor(xi - 0.5 * support).astype(jnp.int32) + 1
    offs = jnp.arange(support, dtype=jnp.int32)
    j = j0[:, None] + offs[None, :]
    w = phi(xi[:, None] - j.astype(xi.dtype))
    return j, w


def _axis_weights_indices(xi: jnp.ndarray, n: int, support: int, phi):
    """Per-axis stencil indices (wrapped periodic) and weights."""
    j, w = _axis_weights_indices_raw(xi, support, phi)
    return jnp.mod(j, n), w


def _stencil(grid: StaggeredGrid, X: jnp.ndarray, centering, kernel: Kernel):
    """Flattened linear indices (N, prod(s_d)) and tensor-product
    weights. Kernels may be anisotropic (composite B-splines pick a
    different order along the face-normal axis, delta.get_kernel_axes)."""
    specs = get_kernel_axes(kernel, centering, grid.dim)
    offsets = _centering_offsets(grid, centering)
    dim = grid.dim
    idxs, ws = [], []
    for d in range(dim):
        support_d, phi_d = specs[d]
        xi = (X[:, d] - grid.x_lo[d]) / grid.dx[d] - offsets[d]
        idx, w = _axis_weights_indices(xi, grid.n[d], support_d, phi_d)
        idxs.append(idx)
        ws.append(w)

    return (_combine_linear(idxs, specs, grid, X.shape[0]),
            _combine_tensor(ws, specs, X.shape[0]))


def _combine_linear(idxs, specs, grid, N):
    """Tensor-product linear grid index per stencil point (N, S) — the
    single source of the index linearization (shared by the value and
    gradient transfers)."""
    lin = idxs[0]
    for d in range(1, len(idxs)):
        s_d = specs[d][0]
        lin = lin[..., :, None] * grid.n[d] + idxs[d].reshape(
            (N,) + (1,) * (lin.ndim - 1) + (s_d,))
    return lin.reshape(N, -1)


def _combine_tensor(factors, specs, N):
    """Tensor-product combine of per-axis (N, s_d) factors -> (N, S)."""
    t = factors[0]
    for d in range(1, len(factors)):
        s_d = specs[d][0]
        t = t[..., :, None] * factors[d].reshape(
            (N,) + (1,) * (t.ndim - 1) + (s_d,))
    return t.reshape(N, -1)


def interpolate(field: jnp.ndarray, grid: StaggeredGrid, X: jnp.ndarray,
                centering="cell", kernel: Kernel = "IB_4",
                weights: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """U_m = sum_g field(x_g) * delta_h(x_g - X_m) * h^dim  -> (N,)"""
    lin, wgt = _stencil(grid, X, centering, kernel)
    vals = jnp.take(field.reshape(-1), lin, axis=0)
    out = jnp.sum(vals * wgt, axis=-1)
    if weights is not None:
        out = out * weights
    return out


def spread(F: jnp.ndarray, grid: StaggeredGrid, X: jnp.ndarray,
           centering="cell", kernel: Kernel = "IB_4",
           weights: Optional[jnp.ndarray] = None,
           out: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """f(x_g) += sum_m F_m * delta_h(x_g - X_m); delta_h carries the
    1/h^dim factor. Accumulates into ``out`` if given."""
    lin, wgt = _stencil(grid, X, centering, kernel)
    inv_vol = 1.0 / math.prod(grid.dx)
    vals = (F * inv_vol)[:, None] * wgt
    if weights is not None:
        vals = vals * weights[:, None]
    if out is None:
        out = jnp.zeros(grid.n, dtype=jnp.result_type(F, wgt))
    flat = out.reshape(-1).at[lin.reshape(-1)].add(vals.reshape(-1))
    return flat.reshape(grid.n)


def interpolate_vel(u: Sequence[jnp.ndarray], grid: StaggeredGrid,
                    X: jnp.ndarray, kernel: Kernel = "IB_4",
                    weights: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Interpolate a MAC velocity to markers -> (N, dim); component d is
    sampled at its own face centering."""
    cols = [interpolate(u[d], grid, X, centering=d, kernel=kernel,
                        weights=weights)
            for d in range(grid.dim)]
    return jnp.stack(cols, axis=-1)


def spread_vel(F: jnp.ndarray, grid: StaggeredGrid, X: jnp.ndarray,
               kernel: Kernel = "IB_4",
               weights: Optional[jnp.ndarray] = None) -> Vel:
    """Spread marker forces (N, dim) onto the MAC grid, one scatter per
    component at its own centering. Includes the 1/h^dim delta factor."""
    return tuple(spread(F[:, d], grid, X, centering=d, kernel=kernel,
                        weights=weights)
                 for d in range(grid.dim))


# --------------------------------------------------------------------------
# Kernel-GRADIENT transfers (P18 IMP material points: velocity-gradient
# interpolation dF/dt = (grad u) F and divergence-form stress spreading
# f = -sum_p V_p P F^T grad(delta) — the reference's IMPMethod kernels)
# --------------------------------------------------------------------------

def _stencil_with_grad(grid: StaggeredGrid, X: jnp.ndarray, centering,
                       kernel: Kernel):
    """Like :func:`_stencil` but additionally returns the spatial
    gradient of each tensor-product weight w.r.t. the marker position:
    lin (N, S), W (N, S), dW (N, S, dim) with
    dW[..., j] = (phi_j'(r)/h_j) * prod_{d != j} phi_d(r_d)."""
    import jax

    from ibamr_tpu.ops.delta import validate_gradient_kernel
    validate_gradient_kernel(kernel)
    specs = get_kernel_axes(kernel, centering, grid.dim)
    offsets = _centering_offsets(grid, centering)
    dim = grid.dim
    idxs, ws, dws = [], [], []
    for d in range(dim):
        support_d, phi_d = specs[d]
        xi = (X[:, d] - grid.x_lo[d]) / grid.dx[d] - offsets[d]
        j_raw, w = _axis_weights_indices_raw(xi, support_d, phi_d)
        # derivative of phi at the same offsets: d/dX = phi'(r)/h
        r = xi[:, None] - j_raw.astype(xi.dtype)
        dphi = jax.vmap(jax.grad(phi_d))(r.reshape(-1)).reshape(r.shape)
        idxs.append(jnp.mod(j_raw, grid.n[d]))
        ws.append(w)
        dws.append(dphi / grid.dx[d])

    N = X.shape[0]
    lin = _combine_linear(idxs, specs, grid, N)
    W = _combine_tensor(ws, specs, N)
    dW = jnp.stack([_combine_tensor([dws[d] if d == j else ws[d]
                                     for d in range(dim)], specs, N)
                    for j in range(dim)], axis=-1)
    return lin, W, dW


def interpolate_vel_and_gradient(u: Sequence[jnp.ndarray],
                                 grid: StaggeredGrid, X: jnp.ndarray,
                                 kernel: Kernel = "BSPLINE_3",
                                 weights: Optional[jnp.ndarray] = None):
    """Fused (U, grad u) at markers: one stencil build + one gather per
    component serves both the value (N, dim) and the gradient
    (N, dim, dim) — the IMP step's hot transfer."""
    dim = grid.dim
    vals_rows, grad_rows = [], []
    for i in range(dim):
        lin, W, dW = _stencil_with_grad(grid, X, i, kernel)
        vals = jnp.take(u[i].reshape(-1), lin, axis=0)
        vals_rows.append(jnp.sum(vals * W, axis=-1))
        grad_rows.append(jnp.sum(vals[..., None] * dW, axis=1))
    U = jnp.stack(vals_rows, axis=-1)
    G = jnp.stack(grad_rows, axis=1)
    if weights is not None:
        U = U * weights[:, None]
        G = G * weights[:, None, None]
    return U, G


def interpolate_gradient_vel(u: Sequence[jnp.ndarray],
                             grid: StaggeredGrid, X: jnp.ndarray,
                             kernel: Kernel = "BSPLINE_3",
                             weights: Optional[jnp.ndarray] = None
                             ) -> jnp.ndarray:
    """Velocity gradient at markers: G[:, i, j] = du_i/dx_j (N, dim,
    dim), each component sampled at its own MAC centering."""
    _, G = interpolate_vel_and_gradient(u, grid, X, kernel=kernel,
                                        weights=weights)
    return G


def spread_stress(PFt: jnp.ndarray, V: jnp.ndarray, grid: StaggeredGrid,
                  X: jnp.ndarray, kernel: Kernel = "BSPLINE_3",
                  weights: Optional[jnp.ndarray] = None) -> Vel:
    """Divergence-form internal-force spreading of the per-point stress
    ``PFt = P(F) F^T`` (N, dim, dim) with reference volumes V (N,):
    f_i(x_g) = -(1/h^dim) sum_p V_p sum_j PFt[p, i, j] dW_g/dx_j.
    The total spread force vanishes identically (sum_g dW = 0), so
    momentum is conserved to roundoff."""
    dim = grid.dim
    inv_vol = 1.0 / math.prod(grid.dx)
    out = []
    for i in range(dim):
        lin, _, dW = _stencil_with_grad(grid, X, i, kernel)
        coeff = PFt[:, i, :] * V[:, None]
        if weights is not None:
            coeff = coeff * weights[:, None]
        vals = -inv_vol * jnp.sum(coeff[:, None, :] * dW, axis=-1)
        f = jnp.zeros(grid.n, dtype=vals.dtype).reshape(-1)
        f = f.at[lin.reshape(-1)].add(vals.reshape(-1))
        out.append(f.reshape(grid.n))
    return tuple(out)
