"""Eulerian <-> Lagrangian interaction: spread and interpolate.

Reference parity: ``LEInteractor::spread`` / ``LEInteractor::interpolate``
(T2) + the marker-data side of ``LDataManager::spread/interp`` (T1) — the
signature IB operations and the north-star hot path (SURVEY.md §3.2):

  spread:      f(x_g) += sum_markers F_m prod_d phi((x_g - X_m)/h) / h^dim
  interpolate: U_m     = sum_grid    u(x_g) prod_d phi((x_g - X_m)/h)

TPU-first design (SURVEY.md §7.3 hard-part #1): markers are a fixed-shape
``(N, dim)`` array; for each marker the ``s^dim`` stencil weights are built
by broadcasting per-axis weight vectors (one fused elementwise kernel), and
the grid exchange is ONE flattened gather (interp) or scatter-add (spread)
— XLA lowers scatter-add with duplicate indices correctly, and under
sharding it becomes the irregular-communication step that the reference
implements with PETSc VecScatter ghost accumulation.

Spread and interpolate use the SAME weights, so they are exact adjoints:
  <spread(F), u> * h^dim == sum_m F_m . interp(u)_m
— the free correctness oracle the tests enforce.

An optional ``weights`` (marker mask) supports fixed-capacity marker pools
with inactive slots (SURVEY.md §7.1 pillar 1): masked markers contribute
nothing and interpolate to zero.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops.delta import Kernel, get_kernel_axes

Vel = Tuple[jnp.ndarray, ...]


def _centering_offsets(grid: StaggeredGrid, centering) -> Tuple[float, ...]:
    """Grid-unit coordinate of index j along axis d is j + offset_d.
    centering: "cell" | int component for face-centered | explicit tuple."""
    if centering == "cell":
        return (0.5,) * grid.dim
    if isinstance(centering, int):
        return tuple(0.0 if d == centering else 0.5 for d in range(grid.dim))
    return tuple(centering)


def _axis_weights_indices_raw(xi: jnp.ndarray, support: int, phi):
    """Per-axis stencil indices (UNWRAPPED — may be negative or >= n)
    and delta weights. The single source of the kernel-support index
    math, shared with the sharded engine (parallel.lagrangian), which
    needs contiguous indices for its halo-extended local buffers.

    xi: (N,) continuous grid-unit coordinate of the markers along this
    axis; returns j (N, support) int32, w (N, support).
    """
    j0 = jnp.floor(xi - 0.5 * support).astype(jnp.int32) + 1
    offs = jnp.arange(support, dtype=jnp.int32)
    j = j0[:, None] + offs[None, :]
    w = phi(xi[:, None] - j.astype(xi.dtype))
    return j, w


def _axis_weights_indices(xi: jnp.ndarray, n: int, support: int, phi):
    """Per-axis stencil indices (wrapped periodic) and weights."""
    j, w = _axis_weights_indices_raw(xi, support, phi)
    return jnp.mod(j, n), w


def _stencil(grid: StaggeredGrid, X: jnp.ndarray, centering, kernel: Kernel):
    """Flattened linear indices (N, prod(s_d)) and tensor-product
    weights. Kernels may be anisotropic (composite B-splines pick a
    different order along the face-normal axis, delta.get_kernel_axes)."""
    specs = get_kernel_axes(kernel, centering, grid.dim)
    offsets = _centering_offsets(grid, centering)
    dim = grid.dim
    idxs, ws = [], []
    for d in range(dim):
        support_d, phi_d = specs[d]
        xi = (X[:, d] - grid.x_lo[d]) / grid.dx[d] - offsets[d]
        idx, w = _axis_weights_indices(xi, grid.n[d], support_d, phi_d)
        idxs.append(idx)
        ws.append(w)

    # tensor-product combine: linear index and weight per stencil point
    N = X.shape[0]
    lin = idxs[0]
    wgt = ws[0]
    for d in range(1, dim):
        s_d = specs[d][0]
        lin = lin[..., :, None] * grid.n[d] + idxs[d].reshape(
            (N,) + (1,) * (lin.ndim - 1) + (s_d,))
        wgt = wgt[..., :, None] * ws[d].reshape(
            (N,) + (1,) * (wgt.ndim - 1) + (s_d,))
    return lin.reshape(N, -1), wgt.reshape(N, -1)


def interpolate(field: jnp.ndarray, grid: StaggeredGrid, X: jnp.ndarray,
                centering="cell", kernel: Kernel = "IB_4",
                weights: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """U_m = sum_g field(x_g) * delta_h(x_g - X_m) * h^dim  -> (N,)"""
    lin, wgt = _stencil(grid, X, centering, kernel)
    vals = jnp.take(field.reshape(-1), lin, axis=0)
    out = jnp.sum(vals * wgt, axis=-1)
    if weights is not None:
        out = out * weights
    return out


def spread(F: jnp.ndarray, grid: StaggeredGrid, X: jnp.ndarray,
           centering="cell", kernel: Kernel = "IB_4",
           weights: Optional[jnp.ndarray] = None,
           out: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """f(x_g) += sum_m F_m * delta_h(x_g - X_m); delta_h carries the
    1/h^dim factor. Accumulates into ``out`` if given."""
    lin, wgt = _stencil(grid, X, centering, kernel)
    inv_vol = 1.0 / math.prod(grid.dx)
    vals = (F * inv_vol)[:, None] * wgt
    if weights is not None:
        vals = vals * weights[:, None]
    if out is None:
        out = jnp.zeros(grid.n, dtype=jnp.result_type(F, wgt))
    flat = out.reshape(-1).at[lin.reshape(-1)].add(vals.reshape(-1))
    return flat.reshape(grid.n)


def interpolate_vel(u: Sequence[jnp.ndarray], grid: StaggeredGrid,
                    X: jnp.ndarray, kernel: Kernel = "IB_4",
                    weights: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Interpolate a MAC velocity to markers -> (N, dim); component d is
    sampled at its own face centering."""
    cols = [interpolate(u[d], grid, X, centering=d, kernel=kernel,
                        weights=weights)
            for d in range(grid.dim)]
    return jnp.stack(cols, axis=-1)


def spread_vel(F: jnp.ndarray, grid: StaggeredGrid, X: jnp.ndarray,
               kernel: Kernel = "IB_4",
               weights: Optional[jnp.ndarray] = None) -> Vel:
    """Spread marker forces (N, dim) onto the MAC grid, one scatter per
    component at its own centering. Includes the 1/h^dim delta factor."""
    return tuple(spread(F[:, d], grid, X, centering=d, kernel=kernel,
                        weights=weights)
                 for d in range(grid.dim))
