"""Stochastic forcing for fluctuating hydrodynamics.

Reference parity: ``INSStaggeredStochasticForcing`` + ``RNG`` +
``AdvDiffStochasticForcing`` (P6, SURVEY.md §2.2) — the
Landau-Lifshitz fluctuating stress: the momentum equation gains
``div W`` with a Gaussian random stress of covariance

    <W_ij W_kl> = 2 kT mu (delta_ik delta_jl + delta_il delta_jk)
                  / (dV dt)

so that, with the dissipative term, the fluid thermalizes to
equipartition (fluctuation-dissipation). Discretely (Balboa-Usabiaga et
al. staggered scheme, the one the reference follows): diagonal stress
components live at cell centers, off-diagonal components at nodes
(2D) / edges (3D), symmetrized, and the MAC force is the conservative
staggered divergence — so the total momentum injected is EXACTLY zero
(telescoping sums), which the tests enforce.

TPU-first: ``jax.random`` (counter-based, reproducible, splittable)
replaces the reference's seeded RNG stream; one ``sample`` call is a
handful of fused normal draws + roll-stencil divergences, jitted into
the step.
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid

Vel = Tuple[jnp.ndarray, ...]


class StochasticStressForcing:
    """Fluctuating-stress MAC force generator (P6).

    scale = sqrt(2 kT mu / (dV dt)); ``sample(key, dt)`` returns the
    MAC body force div W for one step.
    """

    def __init__(self, grid: StaggeredGrid, mu: float, kT: float,
                 dtype=jnp.float32):
        self.grid = grid
        self.mu = float(mu)
        self.kT = float(kT)
        self.dtype = dtype

    def _scale(self, dt: float) -> float:
        dV = self.grid.cell_volume
        return math.sqrt(2.0 * self.kT * self.mu / (dV * dt))

    def sample_stress(self, key, dt: float):
        """Random stress fields: diag (dim arrays at cell centers) and
        symmetrized off-diagonal (dict (i,j)->array at i-j edge/node
        centering), scaled for fluctuation-dissipation."""
        g = self.grid
        dim = g.dim
        s = self._scale(dt)
        n_off = dim * (dim - 1) // 2
        keys = jax.random.split(key, dim + n_off)
        # diagonal: variance 2 s^2  (the (delta_ik delta_jl + ...) doubles
        # the diagonal covariance)
        diag = tuple(
            s * math.sqrt(2.0)
            * jax.random.normal(keys[d], g.n, dtype=self.dtype)
            for d in range(dim))
        off = {}
        k = dim
        for i in range(dim):
            for j in range(i + 1, dim):
                # W_ij = W_ji: one draw of variance s^2 shared by both
                off[(i, j)] = s * jax.random.normal(keys[k], g.n,
                                                    dtype=self.dtype)
                k += 1
        return diag, off

    def sample(self, key, dt: float) -> Vel:
        """MAC force (div W)_d = d_d W_dd + sum_{j!=d} d_j W_dj.

        Centering bookkeeping (component d lives on lower d-faces):
        - W_dd at cell centers: d_d via backward difference -> d-face;
        - W_dj (j != d) at d-j edges (lower in both d and j): d_j via
          forward difference -> d-face.
        """
        g = self.grid
        dim = g.dim
        dx = g.dx
        diag, off = self.sample_stress(key, dt)
        out = []
        for d in range(dim):
            acc = (diag[d] - jnp.roll(diag[d], 1, d)) / dx[d]
            for j in range(dim):
                if j == d:
                    continue
                W = off[(min(d, j), max(d, j))]
                acc = acc + (jnp.roll(W, -1, j) - W) / dx[j]
            out.append(acc)
        return tuple(out)


class StochasticFluxForcing:
    """Scalar fluctuating flux for adv-diff (AdvDiffStochasticForcing):
    dQ/dt += div( sqrt(2 kappa Q_ref / (dV dt)) Z ), Z iid normal on
    faces; conservative by the same telescoping argument."""

    def __init__(self, grid: StaggeredGrid, kappa: float,
                 Q_ref: float = 1.0, dtype=jnp.float32):
        self.grid = grid
        self.kappa = float(kappa)
        self.Q_ref = float(Q_ref)
        self.dtype = dtype

    def sample(self, key, dt: float) -> jnp.ndarray:
        g = self.grid
        s = math.sqrt(2.0 * self.kappa * self.Q_ref
                      / (g.cell_volume * dt))
        keys = jax.random.split(key, g.dim)
        out = jnp.zeros(g.n, dtype=self.dtype)
        for d in range(g.dim):
            Z = s * jax.random.normal(keys[d], g.n, dtype=self.dtype)
            out = out + (jnp.roll(Z, -1, d) - Z) / g.dx[d]
        return out
