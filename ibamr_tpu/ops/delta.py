"""Regularized discrete delta-function kernels.

Reference parity: the kernel menu of ``LEInteractor`` (T2, SURVEY.md §2.1):
PIECEWISE_LINEAR, COSINE, IB_3, IB_4, BSPLINE_2..6, USER_DEFINED. The IB_*
kernels are Peskin's classical immersed-boundary kernels satisfying the
zeroth/first moment and even-odd sum conditions; the B-splines are cardinal
B-splines (partition of unity + symmetry).

TPU-first design: each kernel is a branch-free jnp expression on |r|
(piecewise pieces combined with jnp.where / truncated powers), so the
weight evaluation for all markers x all stencil offsets is one fused
elementwise kernel — no per-marker control flow.

All kernels are 1-D; multi-D weights are tensor products (as in the
reference's Fortran loops).
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Tuple, Union

import jax.numpy as jnp

KernelFn = Callable[[jnp.ndarray], jnp.ndarray]
KernelSpec = Tuple[int, KernelFn]  # (support in grid points, phi(r))


def _phi_piecewise_linear(r: jnp.ndarray) -> jnp.ndarray:
    a = jnp.abs(r)
    return jnp.maximum(1.0 - a, 0.0)


def _phi_cosine(r: jnp.ndarray) -> jnp.ndarray:
    a = jnp.abs(r)
    return jnp.where(a < 2.0, 0.25 * (1.0 + jnp.cos(0.5 * math.pi * a)), 0.0)


def _phi_ib3(r: jnp.ndarray) -> jnp.ndarray:
    a = jnp.abs(r)
    # guard sqrt args so the unused branch never produces nan
    inner = (1.0 + jnp.sqrt(jnp.maximum(1.0 - 3.0 * a * a, 0.0))) / 3.0
    s = jnp.sqrt(jnp.maximum(1.0 - 3.0 * (1.0 - a) ** 2, 0.0))
    outer = (5.0 - 3.0 * a - s) / 6.0
    return jnp.where(a < 0.5, inner, jnp.where(a < 1.5, outer, 0.0))


def _phi_ib4(r: jnp.ndarray) -> jnp.ndarray:
    a = jnp.abs(r)
    s_in = jnp.sqrt(jnp.maximum(1.0 + 4.0 * a - 4.0 * a * a, 0.0))
    inner = 0.125 * (3.0 - 2.0 * a + s_in)
    s_out = jnp.sqrt(jnp.maximum(-7.0 + 12.0 * a - 4.0 * a * a, 0.0))
    outer = 0.125 * (5.0 - 2.0 * a - s_out)
    return jnp.where(a < 1.0, inner, jnp.where(a < 2.0, outer, 0.0))


def _make_bspline(order: int) -> KernelFn:
    """Cardinal B-spline M_k via the truncated-power formula:
    M_k(x) = 1/(k-1)! sum_j (-1)^j C(k,j) (x + k/2 - j)_+^{k-1}.
    Support k grid points; C^{k-2} smooth; partition of unity."""
    k = order
    coef = [((-1) ** j) * math.comb(k, j) / math.factorial(k - 1)
            for j in range(k + 1)]

    def phi(r: jnp.ndarray) -> jnp.ndarray:
        out = jnp.zeros_like(r)
        for j in range(k + 1):
            out = out + coef[j] * jnp.maximum(r + 0.5 * k - j, 0.0) ** (k - 1)
        return jnp.where(jnp.abs(r) < 0.5 * k, out, 0.0)

    return phi


_KERNELS: Dict[str, KernelSpec] = {
    "PIECEWISE_LINEAR": (2, _phi_piecewise_linear),
    "COSINE": (4, _phi_cosine),
    "IB_3": (3, _phi_ib3),
    "IB_4": (4, _phi_ib4),
    "BSPLINE_2": (2, _make_bspline(2)),
    "BSPLINE_3": (3, _make_bspline(3)),
    "BSPLINE_4": (4, _make_bspline(4)),
    "BSPLINE_5": (5, _make_bspline(5)),
    "BSPLINE_6": (6, _make_bspline(6)),
}

Kernel = Union[str, KernelSpec]


def get_kernel(kernel: Kernel) -> KernelSpec:
    """Resolve a kernel name (or a user-defined ``(support, phi)`` pair —
    the USER_DEFINED path of the reference)."""
    if isinstance(kernel, str):
        try:
            return _KERNELS[kernel.upper()]
        except KeyError:
            raise ValueError(
                f"unknown delta kernel {kernel!r}; have {sorted(_KERNELS)}")
    support, fn = kernel
    return int(support), fn


def stencil_size(kernel: Kernel) -> int:
    """Reference parity: LEInteractor::getStencilSize."""
    return get_kernel(kernel)[0]


def available_kernels() -> Tuple[str, ...]:
    return tuple(sorted(_KERNELS))
