"""Regularized discrete delta-function kernels.

Reference parity: the kernel menu of ``LEInteractor`` (T2, SURVEY.md §2.1):
PIECEWISE_LINEAR, COSINE, IB_3, IB_4, BSPLINE_2..6, USER_DEFINED. The IB_*
kernels are Peskin's classical immersed-boundary kernels satisfying the
zeroth/first moment and even-odd sum conditions; the B-splines are cardinal
B-splines (partition of unity + symmetry).

TPU-first design: each kernel is a branch-free jnp expression on |r|
(piecewise pieces combined with jnp.where / truncated powers), so the
weight evaluation for all markers x all stencil offsets is one fused
elementwise kernel — no per-marker control flow.

All kernels are 1-D; multi-D weights are tensor products (as in the
reference's Fortran loops).
"""

from __future__ import annotations

import functools
import math
from typing import Callable, Dict, List, Tuple, Union

import numpy as np
import jax
import jax.numpy as jnp

KernelFn = Callable[[jnp.ndarray], jnp.ndarray]
KernelSpec = Tuple[int, KernelFn]  # (support in grid points, phi(r))


def _phi_piecewise_linear(r: jnp.ndarray) -> jnp.ndarray:
    a = jnp.abs(r)
    return jnp.maximum(1.0 - a, 0.0)


def _phi_cosine(r: jnp.ndarray) -> jnp.ndarray:
    a = jnp.abs(r)
    return jnp.where(a < 2.0, 0.25 * (1.0 + jnp.cos(0.5 * math.pi * a)), 0.0)


@jax.custom_jvp
def _safe_sqrt(x: jnp.ndarray) -> jnp.ndarray:
    """sqrt clamped at 0 with a FINITE derivative at the clamp (PR 19):
    plain ``sqrt(maximum(x, 0))``'s autodiff chain is ``inf * 0 = nan``
    wherever the clamp is active — which poisons every marker-position
    gradient through the IB kernels. A custom JVP keeps the PRIMAL
    graph byte-identical (the kernel appears in every transfer graph;
    its convert/pbroadcast budgets must not pay for differentiability)
    and guards only the derivative: 1/(2*sqrt) where positive, 0 at and
    below the clamp."""
    return jnp.sqrt(jnp.maximum(x, 0.0))


@_safe_sqrt.defjvp
def _safe_sqrt_jvp(primals, tangents):
    (x,), (t,) = primals, tangents
    y = _safe_sqrt(x)
    pos = y > 0.0
    denom = jnp.where(pos, 2.0 * y, jnp.ones((), y.dtype))
    return y, jnp.where(pos, t / denom, jnp.zeros((), y.dtype))


def _phi_ib3(r: jnp.ndarray) -> jnp.ndarray:
    a = jnp.abs(r)
    # guard sqrt args so the unused branch never produces nan (and the
    # gradient stays finite at the clamp — _safe_sqrt)
    inner = (1.0 + _safe_sqrt(1.0 - 3.0 * a * a)) / 3.0
    s = _safe_sqrt(1.0 - 3.0 * (1.0 - a) ** 2)
    outer = (5.0 - 3.0 * a - s) / 6.0
    return jnp.where(a < 0.5, inner, jnp.where(a < 1.5, outer, 0.0))


def _phi_ib4(r: jnp.ndarray) -> jnp.ndarray:
    a = jnp.abs(r)
    s_in = _safe_sqrt(1.0 + 4.0 * a - 4.0 * a * a)
    inner = 0.125 * (3.0 - 2.0 * a + s_in)
    s_out = _safe_sqrt(-7.0 + 12.0 * a - 4.0 * a * a)
    outer = 0.125 * (5.0 - 2.0 * a - s_out)
    return jnp.where(a < 1.0, inner, jnp.where(a < 2.0, outer, 0.0))


def _make_bspline(order: int) -> KernelFn:
    """Cardinal B-spline M_k via the truncated-power formula:
    M_k(x) = 1/(k-1)! sum_j (-1)^j C(k,j) (x + k/2 - j)_+^{k-1}.
    Support k grid points; C^{k-2} smooth; partition of unity."""
    k = order
    coef = [((-1) ** j) * math.comb(k, j) / math.factorial(k - 1)
            for j in range(k + 1)]

    def phi(r: jnp.ndarray) -> jnp.ndarray:
        out = jnp.zeros_like(r)
        for j in range(k + 1):
            out = out + coef[j] * jnp.maximum(r + 0.5 * k - j, 0.0) ** (k - 1)
        return jnp.where(jnp.abs(r) < 0.5 * k, out, 0.0)

    return phi


@functools.lru_cache(maxsize=4)
def _ib6_table(m: int = 4096) -> np.ndarray:
    """Tabulate the 6-point C3 kernel on a uniform r-grid over [-3, 3].

    Construction (the Bao-Kaye-Peskin 2016 family): for each fractional
    position x the six weights are the smooth solution of
      m0 = 1,  m1 = 0,  m2 = K,  m3 = 0,  sum_even = sum_odd = 1/2,
      sum of squares = C,
    with the published second-moment constant K = 59/60 - sqrt(29)/20
    and C pinned by phi(+-3) = 0. Solved numerically at import (host
    numpy) with branch continuity tracked in x; the result is positive,
    C3-smooth, continuous across stencil windows to 1e-15, and
    satisfies the moment conditions to machine precision (validated in
    tests/test_delta_kernels.py). Evaluation then interpolates this
    table linearly (interp error ~ (1/m)^2 ~ 6e-8 at the default m)."""
    Kc = 59.0 / 60.0 - math.sqrt(29.0) / 20.0
    s6 = np.arange(-2, 4)
    even = (s6 % 2 == 0).astype(float)

    def lin(x):
        p = x - s6
        A = np.stack([np.ones(6), p, p * p, p ** 3, even])
        b = np.array([1.0, 0.0, Kc, 0.0, 0.5])
        w0, *_ = np.linalg.lstsq(A, b, rcond=None)
        _, _, Vt = np.linalg.svd(A)
        return w0, Vt[-1]

    w0, v = lin(0.0)
    t0 = -w0[5] / v[5]
    C = (w0 + t0 * v) @ (w0 + t0 * v)

    xs = np.linspace(0.0, 1.0, m, endpoint=False)
    W = np.zeros((m, 6))
    prev = w0 + t0 * v
    for i, x in enumerate(xs):
        w0, v = lin(x)
        t = math.sqrt(max(C - w0 @ w0, 0.0))
        ca, cb = w0 + t * v, w0 - t * v
        W[i] = ca if (np.linalg.norm(ca - prev)
                      <= np.linalg.norm(cb - prev)) else cb
        prev = W[i]
    # segment j of the table covers r in [-3+j, -2+j): the weight of
    # point s = 3-j at fractional position x = r - (-3+j) ... = r + 3 - j
    tab = np.zeros(6 * m + 1)
    for j in range(6):
        tab[j * m:(j + 1) * m] = W[:, 5 - j]
    tab[-1] = 0.0
    return tab


def _phi_ib6(r: jnp.ndarray) -> jnp.ndarray:
    tab = jnp.asarray(_ib6_table())
    m = (tab.shape[0] - 1) // 6
    t = (jnp.clip(r, -3.0, 3.0) + 3.0) * m
    i = jnp.clip(jnp.floor(t).astype(jnp.int32), 0, 6 * m - 1)
    frac = t - i
    lo = jnp.take(tab, i)
    hi = jnp.take(tab, i + 1)
    val = lo + frac * (hi - lo)
    return jnp.where(jnp.abs(r) < 3.0, val, 0.0).astype(r.dtype)


_KERNELS: Dict[str, KernelSpec] = {
    "PIECEWISE_LINEAR": (2, _phi_piecewise_linear),
    "COSINE": (4, _phi_cosine),
    "IB_3": (3, _phi_ib3),
    "IB_4": (4, _phi_ib4),
    "IB_6": (6, _phi_ib6),
    "BSPLINE_2": (2, _make_bspline(2)),
    "BSPLINE_3": (3, _make_bspline(3)),
    "BSPLINE_4": (4, _make_bspline(4)),
    "BSPLINE_5": (5, _make_bspline(5)),
    "BSPLINE_6": (6, _make_bspline(6)),
}

# Composite B-spline kernels (the Lee-Griffith divergence-compatible
# family, LEInteractor [vintage: modern]): a MAC velocity component uses
# order n along its OWN (face-normal) axis and order n-1 along the
# tangential axes; cell-centered fields use order n on every axis.
# (Axis assignment is the [U] interpretation of SURVEY.md's
# COMPOSITE_BSPLINE row — the reference mount was empty.)
_COMPOSITE: Dict[str, Tuple[int, int]] = {
    "COMPOSITE_BSPLINE_32": (3, 2),
    "COMPOSITE_BSPLINE_43": (4, 3),
    "COMPOSITE_BSPLINE_54": (5, 4),
}

Kernel = Union[str, KernelSpec]


def is_composite(kernel: Kernel) -> bool:
    return isinstance(kernel, str) and kernel.upper() in _COMPOSITE


# Kernels whose implementation has a trustworthy AD derivative for the
# kernel-GRADIENT transfers (IMP, P18): C^1 closed forms. Excluded and
# why: PIECEWISE_LINEAR / BSPLINE_2 are C^0 (distributional derivative);
# IB_3 / IB_4 have subgradient kinks at the piece knots so vmap(grad)
# silently returns one-sided values there; IB_6 is table-interpolated,
# so AD yields a piecewise-CONSTANT (staircase) derivative;
# COMPOSITE_BSPLINE_32's tangential axis is the C^0 hat.
_C1_GRADIENT_KERNELS = frozenset({
    "COSINE", "BSPLINE_3", "BSPLINE_4", "BSPLINE_5", "BSPLINE_6",
    "COMPOSITE_BSPLINE_43", "COMPOSITE_BSPLINE_54",
})


def validate_gradient_kernel(kernel: Kernel) -> None:
    """Reject kernels whose AD derivative is unreliable for
    kernel-gradient transfers (ADVICE round 2: a user passing
    kernel="IB_4" to IMPMethod must get an error, not silently degraded
    kink-point gradients). User-defined ``(support, phi)`` pairs pass —
    smoothness is the caller's contract (document C^1 there)."""
    if isinstance(kernel, str) and \
            kernel.upper() not in _C1_GRADIENT_KERNELS:
        raise ValueError(
            f"kernel {kernel!r} is not C^1 (or its implementation has "
            "no trustworthy AD derivative) and cannot be used for "
            "kernel-gradient transfers (IMP); choose one of "
            f"{sorted(_C1_GRADIENT_KERNELS)} or pass a user-defined "
            "(support, phi) pair that is C^1")


def get_kernel_axes(kernel: Kernel, centering, dim: int
                    ) -> List[KernelSpec]:
    """Per-axis (support, phi) specs for a field of the given centering
    ("cell" or the int component of a MAC velocity). Plain kernels are
    isotropic; composite B-splines pick order by normal/tangential."""
    if is_composite(kernel):
        n_norm, n_tang = _COMPOSITE[kernel.upper()]
        if isinstance(centering, int):
            return [get_kernel(f"BSPLINE_{n_norm}") if d == centering
                    else get_kernel(f"BSPLINE_{n_tang}")
                    for d in range(dim)]
        if centering != "cell":
            # an explicit offset tuple carries no normal-axis identity;
            # guessing would silently drop the normal/tangential split
            raise ValueError(
                "composite B-spline kernels need centering='cell' or an "
                "int MAC component (to identify the normal axis); got "
                f"{centering!r}")
        return [get_kernel(f"BSPLINE_{n_norm}")] * dim
    return [get_kernel(kernel)] * dim


def get_kernel(kernel: Kernel) -> KernelSpec:
    """Resolve a kernel name (or a user-defined ``(support, phi)`` pair —
    the USER_DEFINED path of the reference). Composite kernels are
    anisotropic; resolve them per axis with :func:`get_kernel_axes`
    (the MXU-bucketed and sharded engines are isotropic-only and reject
    them here)."""
    if isinstance(kernel, str):
        name = kernel.upper()
        if name in _COMPOSITE:
            raise ValueError(
                f"{kernel!r} is a composite (anisotropic) kernel; use "
                "get_kernel_axes / the scatter interaction path")
        try:
            return _KERNELS[name]
        except KeyError:
            raise ValueError(
                f"unknown delta kernel {kernel!r}; have "
                f"{sorted(_KERNELS) + sorted(_COMPOSITE)}")
    support, fn = kernel
    return int(support), fn


def stencil_size(kernel: Kernel) -> int:
    """Reference parity: LEInteractor::getStencilSize (max over axes
    for composite kernels)."""
    if is_composite(kernel):
        return max(_COMPOSITE[kernel.upper()])
    return get_kernel(kernel)[0]


def available_kernels() -> Tuple[str, ...]:
    return tuple(sorted(_KERNELS) + sorted(_COMPOSITE))
