"""Pallas TPU kernel for the bucketed spread (SURVEY.md §7.3 #1).

Reference parity: the Fortran ``lagrangian_ib4_spread_3d`` inner loop
(T2/P23) — the north-star scatter. The framework already has two
formulations: the XLA scatter-add (ops.interaction) and the MXU
one-hot-matmul (ops.interaction_fast). This module adds the bespoke
TPU schedule SURVEY.md names as hard-part #1: markers bucketed by tile
(reusing interaction_fast's Buckets layout), then ONE Pallas program
per tile accumulating its (W*W, NZ) dense tile in VMEM — per-marker
rank-1 outer-product updates on VPU-friendly (169, NZ) shapes, with no
(B, 169, NZ)-sized HBM intermediate and no scatter at all. The
periodic overlap-add of the finished tiles reuses
interaction_fast._overlap_add (pure data movement).

Weights evaluate the SAME delta.get_kernel functions at ALL W tile
offsets — compact support zeroes everything outside the true stencil,
so no dynamic slicing (and none of its TPU layout constraints) is
needed inside the kernel.

Correctness oracle: bitwise-level agreement with ops.interaction.spread
(tested in interpret mode on CPU).

Wiring status (round 3): BOTH transfers now exist as Pallas programs
(:class:`PallasSpread3D` + the interp twin in
:class:`PallasInteraction`), selectable from the flagship model via
``build_shell_example(use_fast_interaction="pallas")`` and compared
three-way (scatter / MXU / pallas) by ``bench.py`` — with the pallas
leg in a TERMINABLE child process because this container's TPU relay
routes Pallas through a remote-compile service that stalled on this
kernel in round 2 (plain XLA compiles fine). The default production
engine remains the MXU bucketed formulation until a compiled-TPU
timing shows the Pallas schedule winning; its intended advantage is
identical FLOPs with no (B, cap, P) weight intermediates in HBM.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops.delta import Kernel, get_kernel
from ibamr_tpu.ops.interaction import _centering_offsets
from ibamr_tpu.ops.interaction_fast import (BucketGeometry, Buckets,
                                            _overlap_add, _phi_safe)


def _marker_weight_preamble(geom: BucketGeometry, grid: StaggeredGrid,
                            offs, phi):
    """Shared per-tile weight computation for BOTH Pallas programs
    (spread and its interp adjoint must evaluate bit-identical weights):
    markers on the LANE axis, per-axis kernel-weight matrices
    ``wx (W0, cap), wy (W1, cap), wz (nz, cap)`` with periodic wrap.

    Mosaic-friendly by construction (round 3: the per-marker rank-1
    fori_loop form died in infer-vector-layout on a reshape): iota +
    broadcast arithmetic only — no reshape or transpose in-kernel.
    """
    W0, W1 = geom.width
    nz = grid.n[2]
    t0, t1 = geom.tile
    lo = grid.x_lo
    dx = grid.dx

    def weights(Xt, bx, by):
        x0 = bx * t0 - 1          # tile footprint origin (cells)
        y0 = by * t1 - 1
        ox = jax.lax.broadcasted_iota(jnp.int32, (W0, 1), 0).astype(
            Xt.dtype)
        oy = jax.lax.broadcasted_iota(jnp.int32, (W1, 1), 0).astype(
            Xt.dtype)
        kz = jax.lax.broadcasted_iota(jnp.int32, (nz, 1), 0).astype(
            Xt.dtype)

        xi = (Xt[0:1, :] - lo[0]) / dx[0] - offs[0]    # (1, cap)
        yi = (Xt[1:2, :] - lo[1]) / dx[1] - offs[1]
        zi = (Xt[2:3, :] - lo[2]) / dx[2] - offs[2]
        # wrapped distances (periodic) at every tile/axis offset
        tx = xi - (x0 + ox)                            # (W0, cap)
        tx = tx - jnp.round(tx / grid.n[0]) * grid.n[0]
        ty = yi - (y0 + oy)                            # (W1, cap)
        ty = ty - jnp.round(ty / grid.n[1]) * grid.n[1]
        tz = zi - kz                                   # (nz, cap)
        tz = tz - jnp.round(tz / nz) * nz
        return phi(tx), phi(ty), phi(tz)

    return weights


def _spread_kernel_3d(geom: BucketGeometry, grid: StaggeredGrid,
                      offs, phi, interpret: bool):
    """Build the per-tile Pallas program (static closure)."""
    W0, W1 = geom.width
    nz = grid.n[2]
    nb0, nb1 = geom.nblk
    cap = geom.cap
    weights = _marker_weight_preamble(geom, grid, offs, phi)

    def kernel(XbT_ref, coef_ref, out_ref):
        b = pl.program_id(0)
        bx = b // nb1
        by = b % nb1
        Xt = XbT_ref[0]                                # (3, cap)
        c = coef_ref[0]                                # (1, cap)
        wx, wy, wz = weights(Xt, bx, by)
        wzc = wz * c                                   # (nz, cap)

        # out[a*W1 + b, z] = sum_m wx[a,m] wy[b,m] c[m] wz[z,m]
        for a in range(W0):                            # static unroll
            rows = jax.lax.dot_general(
                wy * wx[a:a + 1, :], wzc,
                (((1,), (1,)), ((), ())),
                preferred_element_type=out_ref.dtype,
                precision=jax.lax.Precision.HIGHEST)   # (W1, nz)
            out_ref[0, a * W1:(a + 1) * W1, :] = rows

    def call(Xb, coef):
        B = Xb.shape[0]
        # markers on the lane axis: transpose OUTSIDE the kernel (XLA
        # handles layout changes; Mosaic must not see them)
        XbT = jnp.swapaxes(Xb, 1, 2)                   # (B, 3, cap)
        coefT = coef[:, None, :]                       # (B, 1, cap)
        return pl.pallas_call(
            kernel,
            grid=(B,),
            in_specs=[
                pl.BlockSpec((1, 3, cap), lambda b: (b, 0, 0)),
                pl.BlockSpec((1, 1, cap), lambda b: (b, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, W0 * W1, nz), lambda b: (b, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((B, W0 * W1, nz), Xb.dtype),
            interpret=interpret,
        )(XbT, coefT)

    return call


class PallasSpread3D:
    """Spread engine: interaction_fast bucketing + a Pallas tile kernel.

    3D only (the north-star configuration); falls back is the caller's
    concern. ``interpret=True`` runs the same program in the Pallas
    interpreter (CPU testing).
    """

    def __init__(self, grid: StaggeredGrid, kernel: Kernel = "IB_4",
                 tile: int = 8, cap: int = 256,
                 interpret: Optional[bool] = None):
        from ibamr_tpu.ops.interaction_fast import make_geometry

        if grid.dim != 3:
            raise ValueError("PallasSpread3D is 3D-only")
        self.grid = grid
        self.kernel: Kernel = kernel
        self.geom = make_geometry(grid, kernel, tile=tile, cap=cap)
        if interpret is None:
            interpret = jax.default_backend() == "cpu"
        self.interpret = bool(interpret)
        support, phi0 = get_kernel(kernel)
        self._phi = _phi_safe(phi0, support)

    def spread(self, F: jnp.ndarray, X: jnp.ndarray, centering,
               b: Buckets) -> jnp.ndarray:
        """Spread one scalar channel (N,) -> grid field, exact vs
        ops.interaction.spread for in-capacity markers (overflow flows
        through the caller's fallback exactly as in interaction_fast)."""
        from ibamr_tpu.ops.interaction_fast import (
            bucketed_channel, spread_overflow_fallbacks)

        geom = self.geom
        grid = self.grid
        inv_vol = 1.0 / math.prod(grid.dx)
        offs = _centering_offsets(grid, centering)
        coef = bucketed_channel(b, F) * b.wb * inv_vol
        # accumulate in the caller's dtype (f32 states stay f32; an f64
        # caller keeps full precision end to end)
        call = _spread_kernel_3d(geom, grid, offs, self._phi,
                                 self.interpret)
        T = call(b.Xb.astype(coef.dtype), coef)
        T = T.reshape((T.shape[0],) + tuple(geom.width) + (grid.n[2],))
        out = _overlap_add(geom, grid, T.astype(F.dtype))
        return spread_overflow_fallbacks(out, b, F, X, grid, centering,
                                         self.kernel)

    def spread_vel(self, F: jnp.ndarray, X: jnp.ndarray,
                   b: Buckets) -> tuple:
        return tuple(self.spread(F[:, d], X, d, b)
                     for d in range(self.grid.dim))


def _interp_kernel_3d(geom: BucketGeometry, grid: StaggeredGrid,
                      offs, phi, interpret: bool):
    """Per-tile interp program: contract the extracted tile with ALL
    cap markers' tensor-product weights in one fused VMEM computation —
    the gather twin of _spread_kernel_3d. The (P, cap) contraction is a
    dense dot (MXU); no (B, cap, P) weight intermediate ever reaches
    HBM (the MXU einsum path materializes two of those)."""
    W0, W1 = geom.width
    nz = grid.n[2]
    nb1 = geom.nblk[1]
    cap = geom.cap
    weights = _marker_weight_preamble(geom, grid, offs, phi)

    def kernel(XbT_ref, T_ref, out_ref):
        # the gather twin of _spread_kernel_3d, same shared weight
        # preamble: the z-contraction as ONE dot_general, the (a, b)
        # contraction as a static W0-unroll of sublane reductions.
        b = pl.program_id(0)
        bx = b // nb1
        by = b % nb1
        Xt = XbT_ref[0]                                # (3, cap)
        wx, wy, wz = weights(Xt, bx, by)               # (nz, cap) wz

        T = T_ref[0]                                   # (P, nz)
        # accumulate in the caller's dtype: f64 callers keep full
        # precision end to end, like the spread twin
        tmp = jnp.dot(T, wz.astype(T.dtype),
                      preferred_element_type=T.dtype,
                      precision=jax.lax.Precision.HIGHEST)  # (P, cap)
        out = jnp.zeros((1, cap), dtype=T.dtype)
        for a in range(W0):                            # static unroll
            blk = tmp[a * W1:(a + 1) * W1, :]          # (W1, cap)
            inner = jnp.sum(wy.astype(T.dtype) * blk, axis=0,
                            keepdims=True)             # (1, cap)
            out = out + wx[a:a + 1, :].astype(T.dtype) * inner
        out_ref[0] = out

    def call(Xb, T):
        B = Xb.shape[0]
        XbT = jnp.swapaxes(Xb, 1, 2)                   # (B, 3, cap)
        return pl.pallas_call(
            kernel,
            grid=(B,),
            in_specs=[
                pl.BlockSpec((1, 3, cap), lambda b: (b, 0, 0)),
                pl.BlockSpec((1, W0 * W1, nz), lambda b: (b, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, cap), lambda b: (b, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((B, 1, cap), Xb.dtype),
            interpret=interpret,
        )(XbT, T)

    return call


def _packed_spread_kernel_3d(geom: BucketGeometry, grid: StaggeredGrid,
                             offs, phi, interpret: bool):
    """Packed-chunk spread program: grid over CHUNKS (not tiles), the
    output block chosen by the scalar-prefetched ``tile_of_chunk`` map.
    Chunk ids are assigned in tile order (interaction_packed), so all
    chunks of one tile are consecutive grid steps and Pallas keeps the
    output block resident in VMEM — the revisit-accumulation pattern.
    Blocks no chunk visits are zeroed outside (visited-tile mask)."""
    from jax.experimental.pallas import tpu as pltpu

    W0, W1 = geom.width
    nz = grid.n[2]
    nb1 = geom.nblk[1]
    cap = geom.cap
    weights = _marker_weight_preamble(geom, grid, offs, phi)

    def kernel(tid_ref, XbT_ref, coef_ref, out_ref):
        q = pl.program_id(0)
        tid = tid_ref[q]
        prev = tid_ref[jnp.maximum(q - 1, 0)]
        first = (q == 0) | (tid != prev)
        bx = tid // nb1
        by = tid % nb1
        Xt = XbT_ref[0]                                # (3, cap)
        c = coef_ref[0]                                # (1, cap)
        wx, wy, wz = weights(Xt, bx, by)
        wzc = wz * c                                   # (nz, cap)

        @pl.when(first)
        def _():
            out_ref[...] = jnp.zeros(out_ref.shape, out_ref.dtype)

        for a in range(W0):                            # static unroll
            rows = jax.lax.dot_general(
                wy * wx[a:a + 1, :], wzc,
                (((1,), (1,)), ((), ())),
                preferred_element_type=out_ref.dtype,
                precision=jax.lax.Precision.HIGHEST)   # (W1, nz)
            out_ref[0, a * W1:(a + 1) * W1, :] += rows

    def call(tid, Xb, coef, B):
        Q = Xb.shape[0]
        XbT = jnp.swapaxes(Xb, 1, 2)                   # (Q, 3, cap)
        coefT = coef[:, None, :]                       # (Q, 1, cap)
        gspec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(Q,),
            in_specs=[
                pl.BlockSpec((1, 3, cap), lambda q, t: (q, 0, 0)),
                pl.BlockSpec((1, 1, cap), lambda q, t: (q, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, W0 * W1, nz),
                                   lambda q, t: (t[q], 0, 0)),
        )
        return pl.pallas_call(
            kernel,
            grid_spec=gspec,
            out_shape=jax.ShapeDtypeStruct((B, W0 * W1, nz), Xb.dtype),
            interpret=interpret,
        )(tid, XbT, coefT)

    return call


def _packed_interp_kernel_3d(geom: BucketGeometry, grid: StaggeredGrid,
                             offs, phi, interpret: bool):
    """Packed-chunk interp program: per chunk, DMA the (P, nz) tile of
    ``tile_of_chunk[q]`` and contract against the chunk's marker
    weights (consecutive same-tile reads reuse the resident block)."""
    from jax.experimental.pallas import tpu as pltpu

    W0, W1 = geom.width
    nz = grid.n[2]
    nb1 = geom.nblk[1]
    cap = geom.cap
    weights = _marker_weight_preamble(geom, grid, offs, phi)

    def kernel(tid_ref, XbT_ref, T_ref, out_ref):
        q = pl.program_id(0)
        tid = tid_ref[q]
        bx = tid // nb1
        by = tid % nb1
        Xt = XbT_ref[0]                                # (3, cap)
        wx, wy, wz = weights(Xt, bx, by)               # (nz, cap) wz

        T = T_ref[0]                                   # (P, nz)
        tmp = jnp.dot(T, wz.astype(T.dtype),
                      preferred_element_type=T.dtype,
                      precision=jax.lax.Precision.HIGHEST)  # (P, cap)
        out = jnp.zeros((1, cap), dtype=T.dtype)
        for a in range(W0):                            # static unroll
            blk = tmp[a * W1:(a + 1) * W1, :]          # (W1, cap)
            inner = jnp.sum(wy.astype(T.dtype) * blk, axis=0,
                            keepdims=True)             # (1, cap)
            out = out + wx[a:a + 1, :].astype(T.dtype) * inner
        out_ref[0] = out

    def call(tid, Xb, T):
        Q = Xb.shape[0]
        XbT = jnp.swapaxes(Xb, 1, 2)                   # (Q, 3, cap)
        gspec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(Q,),
            in_specs=[
                pl.BlockSpec((1, 3, cap), lambda q, t: (q, 0, 0)),
                pl.BlockSpec((1, W0 * W1, nz), lambda q, t: (t[q], 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, cap), lambda q, t: (q, 0, 0)),
        )
        return pl.pallas_call(
            kernel,
            grid_spec=gspec,
            out_shape=jax.ShapeDtypeStruct((Q, 1, cap), Xb.dtype),
            interpret=interpret,
        )(tid, XbT, T)

    return call


class PallasPackedInteraction:
    """Occupancy-packed chunks (ops.interaction_packed layout) driven by
    Pallas tile programs: the best of both round-3 engines. Work scales
    with ``Q*c ~ N`` instead of ``B*cap_max`` (packing), and the weight
    tensors never exist in HBM (Pallas) — the only large HBM arrays are
    the per-tile partial grids. Spread accumulates same-tile chunks in
    VMEM via the consecutive-revisit pattern; unvisited tiles are
    zeroed by a visited-tile mask outside the kernel."""

    def __init__(self, grid: StaggeredGrid, kernel: Kernel = "IB_4",
                 tile: int = 8, chunk: int = 128, nchunks: int = 1024,
                 overflow_cap: Optional[int] = None,
                 interpret: Optional[bool] = None):
        from ibamr_tpu.ops.interaction_fast import make_geometry

        if grid.dim != 3:
            raise ValueError("PallasPackedInteraction is 3D-only")
        self.grid = grid
        self.kernel: Kernel = kernel
        self.geom = make_geometry(grid, kernel, tile=tile, cap=chunk)
        self.nchunks = int(nchunks)
        self.overflow_cap = overflow_cap
        if interpret is None:
            interpret = jax.default_backend() == "cpu"
        self.interpret = bool(interpret)
        support, phi0 = get_kernel(kernel)
        self._phi = _phi_safe(phi0, support)

    def buckets(self, X: jnp.ndarray,
                weights: Optional[jnp.ndarray] = None):
        from ibamr_tpu.ops.interaction_packed import pack_markers

        return pack_markers(self.geom, self.grid, X, weights=weights,
                            nchunks=self.nchunks,
                            overflow_cap=self.overflow_cap)

    def refresh(self, b, X: jnp.ndarray,
                weights: Optional[jnp.ndarray] = None):
        """Slot-preserving half-step refresh (pallas twin): the chunk
        layout is the shared interaction_packed one, so the re-gather
        and drift-bound fallback are identical — the Pallas programs
        only ever see the resulting PackedBuckets."""
        from ibamr_tpu.ops.interaction_packed import refresh_packed

        return refresh_packed(self.geom, self.grid, b, X, weights)

    def _visited_mask(self, b) -> jnp.ndarray:
        import numpy as np

        B = int(np.prod(self.geom.nblk))
        occupied = jnp.sum(b.wb != 0, axis=1) > 0          # (Q,)
        return jnp.zeros((B,), dtype=bool).at[b.tile_of_chunk].max(
            occupied)

    def spread(self, F: jnp.ndarray, X: jnp.ndarray, centering,
               b) -> jnp.ndarray:
        import math as _math

        from ibamr_tpu.ops.interaction_fast import (
            _overlap_add, bucketed_channel, spread_overflow_fallbacks)
        import numpy as np

        geom = self.geom
        grid = self.grid
        B = int(np.prod(geom.nblk))
        inv_vol = 1.0 / _math.prod(grid.dx)
        offs = _centering_offsets(grid, centering)
        coef = bucketed_channel(b, F) * b.wb * inv_vol
        call = _packed_spread_kernel_3d(geom, grid, offs, self._phi,
                                        self.interpret)
        T = call(b.tile_of_chunk, b.Xb.astype(coef.dtype), coef, B)
        T = jnp.where(self._visited_mask(b)[:, None, None], T, 0.0)
        T = T.reshape((B,) + tuple(geom.width) + (grid.n[2],))
        out = _overlap_add(geom, grid, T.astype(F.dtype))
        return spread_overflow_fallbacks(out, b, F, X, grid, centering,
                                         self.kernel)

    def spread_vel(self, F: jnp.ndarray, X: jnp.ndarray,
                   weights: Optional[jnp.ndarray] = None,
                   b=None) -> tuple:
        if b is None:
            b = self.buckets(X, weights=weights)
        return tuple(self.spread(F[:, d], X, d, b)
                     for d in range(self.grid.dim))

    def interpolate(self, f: jnp.ndarray, X: jnp.ndarray, centering,
                    b) -> jnp.ndarray:
        from ibamr_tpu.ops.interaction_fast import (
            _extract_tiles, unbucket_with_overflow)

        geom = self.geom
        grid = self.grid
        offs = _centering_offsets(grid, centering)
        T = _extract_tiles(geom, grid, f)             # (B, P, nz)
        call = _packed_interp_kernel_3d(geom, grid, offs, self._phi,
                                        self.interpret)
        Ub = call(b.tile_of_chunk, b.Xb.astype(f.dtype),
                  T.astype(f.dtype))[:, 0, :]
        Ub = Ub * b.wb                                # (Q, cap)
        return unbucket_with_overflow(Ub, b, f, X, grid, centering,
                                      self.kernel)

    def interpolate_vel(self, u, X: jnp.ndarray,
                        weights: Optional[jnp.ndarray] = None,
                        b=None) -> jnp.ndarray:
        if b is None:
            b = self.buckets(X, weights=weights)
        return jnp.stack([self.interpolate(u[d], X, d, b)
                          for d in range(self.grid.dim)], axis=-1)


class HybridPackedInteraction:
    """Pallas-packed SPREAD + XLA packed (bf16-compressible) INTERP
    over ONE shared PackedBuckets context. Motivated by the round-5
    on-chip phases table: within the packed engine spread costs 28.8 ms
    to interp's 13.7 for identical dot work — the spread overlap-add's
    materialized per-tile partials are the waste, and the Pallas spread
    program accumulates them in VMEM instead; interp has no such
    asymmetry, and the XLA interp with bf16-compressed operands is the
    measured-cheapest interp. This engine composes the best measured
    direction of each backend. Same exactness contract as both parents
    (scatter-oracle equality, overflow fallback)."""

    def __init__(self, grid: StaggeredGrid, kernel: Kernel = "IB_4",
                 tile: int = 8, chunk: int = 128, nchunks: int = 1024,
                 overflow_cap: Optional[int] = None,
                 compute_dtype=None, interpret: Optional[bool] = None):
        from ibamr_tpu.ops.interaction_packed import PackedInteraction

        self._pal = PallasPackedInteraction(
            grid, kernel=kernel, tile=tile, chunk=chunk,
            nchunks=nchunks, overflow_cap=overflow_cap,
            interpret=interpret)
        self._xla = PackedInteraction(
            grid, kernel=kernel, tile=tile, chunk=chunk,
            nchunks=nchunks, overflow_cap=overflow_cap,
            compute_dtype=compute_dtype)
        self.grid = grid
        self.kernel: Kernel = kernel
        self.geom = self._xla.geom
        self.nchunks = int(nchunks)
        self.overflow_cap = overflow_cap

    def buckets(self, X: jnp.ndarray,
                weights: Optional[jnp.ndarray] = None):
        return self._xla.buckets(X, weights)

    def refresh(self, b, X: jnp.ndarray,
                weights: Optional[jnp.ndarray] = None):
        """Both backends read the ONE shared PackedBuckets, so one
        slot-preserving refresh serves spread and interp alike."""
        return self._xla.refresh(b, X, weights)

    def spread_vel(self, F: jnp.ndarray, X: jnp.ndarray,
                   weights: Optional[jnp.ndarray] = None,
                   b=None) -> tuple:
        if b is None:
            b = self.buckets(X, weights=weights)
        return self._pal.spread_vel(F, X, weights=weights, b=b)

    def interpolate_vel(self, u, X: jnp.ndarray,
                        weights: Optional[jnp.ndarray] = None,
                        b=None) -> jnp.ndarray:
        if b is None:
            b = self.buckets(X, weights=weights)
        return self._xla.interpolate_vel(u, X, weights=weights, b=b)


class PallasInteraction:
    """Drop-in FastInteraction-shaped engine with BOTH transfers as
    Pallas tile kernels (3D only): spread via :class:`PallasSpread3D`'s
    program, interp via its gather twin. Selectable from the flagship
    model with ``use_fast_interaction="pallas"`` and benchmarked
    three-way (scatter / MXU / Pallas) by bench.py (VERDICT round 2
    item 5)."""

    def __init__(self, grid: StaggeredGrid, kernel: Kernel = "IB_4",
                 tile: int = 8, cap: int = 256,
                 overflow_cap: Optional[int] = None,
                 interpret: Optional[bool] = None):
        from ibamr_tpu.ops.interaction_fast import make_geometry

        if grid.dim != 3:
            raise ValueError("PallasInteraction is 3D-only")
        self.grid = grid
        self.kernel: Kernel = kernel
        self.geom = make_geometry(grid, kernel, tile=tile, cap=cap)
        self.overflow_cap = overflow_cap
        if interpret is None:
            interpret = jax.default_backend() == "cpu"
        self.interpret = bool(interpret)
        support, phi0 = get_kernel(kernel)
        self._phi = _phi_safe(phi0, support)
        self._spread = PallasSpread3D(grid, kernel=kernel, tile=tile,
                                      cap=cap, interpret=interpret)

    def buckets(self, X: jnp.ndarray,
                weights: Optional[jnp.ndarray] = None) -> Buckets:
        from ibamr_tpu.ops.interaction_fast import bucket_markers

        return bucket_markers(self.geom, self.grid, X, weights=weights,
                              overflow_cap=self.overflow_cap)

    def interpolate(self, f: jnp.ndarray, X: jnp.ndarray, centering,
                    b: Buckets) -> jnp.ndarray:
        from ibamr_tpu.ops.interaction_fast import (
            _extract_tiles, unbucket_with_overflow)

        geom = self.geom
        grid = self.grid
        offs = _centering_offsets(grid, centering)
        T = _extract_tiles(geom, grid, f)             # (B, P, nz)
        call = _interp_kernel_3d(geom, grid, offs, self._phi,
                                 self.interpret)
        Ub = call(b.Xb.astype(f.dtype), T.astype(f.dtype))[:, 0, :]
        Ub = Ub * b.wb                                # (B, cap)
        return unbucket_with_overflow(Ub, b, f, X, grid, centering,
                                      self.kernel)

    def interpolate_vel(self, u, X: jnp.ndarray,
                        weights: Optional[jnp.ndarray] = None,
                        b: Optional[Buckets] = None) -> jnp.ndarray:
        if b is None:
            b = self.buckets(X, weights=weights)
        return jnp.stack([self.interpolate(u[d], X, d, b)
                          for d in range(self.grid.dim)], axis=-1)

    def spread_vel(self, F: jnp.ndarray, X: jnp.ndarray,
                   weights: Optional[jnp.ndarray] = None,
                   b: Optional[Buckets] = None):
        if b is None:
            b = self.buckets(X, weights=weights)
        return self._spread.spread_vel(F, X, b)
