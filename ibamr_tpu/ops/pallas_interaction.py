"""Pallas TPU kernel for the bucketed spread (SURVEY.md §7.3 #1).

Reference parity: the Fortran ``lagrangian_ib4_spread_3d`` inner loop
(T2/P23) — the north-star scatter. The framework already has two
formulations: the XLA scatter-add (ops.interaction) and the MXU
one-hot-matmul (ops.interaction_fast). This module adds the bespoke
TPU schedule SURVEY.md names as hard-part #1: markers bucketed by tile
(reusing interaction_fast's Buckets layout), then ONE Pallas program
per tile accumulating its (W*W, NZ) dense tile in VMEM — per-marker
rank-1 outer-product updates on VPU-friendly (169, NZ) shapes, with no
(B, 169, NZ)-sized HBM intermediate and no scatter at all. The
periodic overlap-add of the finished tiles reuses
interaction_fast._overlap_add (pure data movement).

Weights evaluate the SAME delta.get_kernel functions at ALL W tile
offsets — compact support zeroes everything outside the true stencil,
so no dynamic slicing (and none of its TPU layout constraints) is
needed inside the kernel.

Correctness oracle: bitwise-level agreement with ops.interaction.spread
(tested in interpret mode on CPU).

Hardware status (2026-07-30): this container's TPU relay routes Pallas
through a remote-compile service that stalls on this kernel (plain XLA
programs compile fine), so compiled-TPU timings could not be captured
this round; the kernel stays OFF the default paths (scatter and the
MXU bucketed formulation remain the production spread engines) until a
environment with local Pallas compilation can profile it. The intended
schedule advantage over the MXU path: identical FLOPs but no
(B, 169, NZ) HBM intermediate and no overlap-add traffic.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops.delta import Kernel, get_kernel
from ibamr_tpu.ops.interaction import _centering_offsets
from ibamr_tpu.ops.interaction_fast import (BucketGeometry, Buckets,
                                            _overlap_add, _phi_safe)


def _spread_kernel_3d(geom: BucketGeometry, grid: StaggeredGrid,
                      offs, phi, interpret: bool):
    """Build the per-tile Pallas program (static closure)."""
    W0, W1 = geom.width
    nz = grid.n[2]
    nb0, nb1 = geom.nblk
    t0, t1 = geom.tile
    cap = geom.cap
    lo = grid.x_lo
    dx = grid.dx

    def kernel(Xb_ref, coef_ref, out_ref):
        b = pl.program_id(0)
        bx = b // nb1
        by = b % nb1
        x0 = bx * t0 - 1          # tile footprint origin (cells)
        y0 = by * t1 - 1

        ox = jax.lax.broadcasted_iota(jnp.float32, (W0, 1), 0)
        oy = jax.lax.broadcasted_iota(jnp.float32, (W1, 1), 0)
        kz = jax.lax.broadcasted_iota(jnp.float32, (1, nz), 1)

        def body(i, acc):
            x = Xb_ref[0, i, 0]
            y = Xb_ref[0, i, 1]
            z = Xb_ref[0, i, 2]
            c = coef_ref[0, i, 0]
            xi = (x - lo[0]) / dx[0] - offs[0]
            yi = (y - lo[1]) / dx[1] - offs[1]
            zi = (z - lo[2]) / dx[2] - offs[2]
            # wrapped distances (periodic) at every tile/axis offset
            tx = xi - (x0 + ox)
            tx = tx - jnp.round(tx / grid.n[0]) * grid.n[0]
            ty = yi - (y0 + oy)
            ty = ty - jnp.round(ty / grid.n[1]) * grid.n[1]
            tz = zi - kz
            tz = tz - jnp.round(tz / nz) * nz
            wx = phi(tx)                      # (W0, 1)
            wy = phi(ty)                      # (W1, 1)
            wz = phi(tz)                      # (1, nz)
            wxy = (wx * wy.T).reshape(W0 * W1, 1)
            return acc + wxy * (c * wz)       # rank-1 VPU update

        acc = jnp.zeros((W0 * W1, nz), dtype=out_ref.dtype)
        out_ref[0] = jax.lax.fori_loop(0, cap, body, acc)

    def call(Xb, coef):
        B = Xb.shape[0]
        # trailing singleton keeps the TPU block-shape rule happy (last
        # two dims must divide (8, 128) or equal the array dims)
        coef = coef[:, :, None]
        return pl.pallas_call(
            kernel,
            grid=(B,),
            in_specs=[
                pl.BlockSpec((1, cap, 3), lambda b: (b, 0, 0)),
                pl.BlockSpec((1, cap, 1), lambda b: (b, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, W0 * W1, nz), lambda b: (b, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((B, W0 * W1, nz), Xb.dtype),
            interpret=interpret,
        )(Xb, coef)

    return call


class PallasSpread3D:
    """Spread engine: interaction_fast bucketing + a Pallas tile kernel.

    3D only (the north-star configuration); falls back is the caller's
    concern. ``interpret=True`` runs the same program in the Pallas
    interpreter (CPU testing).
    """

    def __init__(self, grid: StaggeredGrid, kernel: Kernel = "IB_4",
                 tile: int = 8, cap: int = 256,
                 interpret: Optional[bool] = None):
        from ibamr_tpu.ops.interaction_fast import make_geometry

        if grid.dim != 3:
            raise ValueError("PallasSpread3D is 3D-only")
        self.grid = grid
        self.kernel: Kernel = kernel
        self.geom = make_geometry(grid, kernel, tile=tile, cap=cap)
        if interpret is None:
            interpret = jax.default_backend() == "cpu"
        self.interpret = bool(interpret)
        support, phi0 = get_kernel(kernel)
        self._phi = _phi_safe(phi0, support)

    def spread(self, F: jnp.ndarray, X: jnp.ndarray, centering,
               b: Buckets) -> jnp.ndarray:
        """Spread one scalar channel (N,) -> grid field, exact vs
        ops.interaction.spread for in-capacity markers (overflow flows
        through the caller's fallback exactly as in interaction_fast)."""
        from ibamr_tpu.ops.interaction_fast import (
            bucketed_channel, spread_overflow_fallbacks)

        geom = self.geom
        grid = self.grid
        inv_vol = 1.0 / math.prod(grid.dx)
        offs = _centering_offsets(grid, centering)
        coef = bucketed_channel(b, F) * b.wb * inv_vol
        # accumulate in the caller's dtype (f32 states stay f32; an f64
        # caller keeps full precision end to end)
        call = _spread_kernel_3d(geom, grid, offs, self._phi,
                                 self.interpret)
        T = call(b.Xb.astype(coef.dtype), coef)
        T = T.reshape((T.shape[0],) + tuple(geom.width) + (grid.n[2],))
        out = _overlap_add(geom, grid, T.astype(F.dtype))
        return spread_overflow_fallbacks(out, b, F, X, grid, centering,
                                         self.kernel)

    def spread_vel(self, F: jnp.ndarray, X: jnp.ndarray,
                   b: Buckets) -> tuple:
        return tuple(self.spread(F[:, d], X, d, b)
                     for d in range(self.grid.dim))
