"""Discrete norms over grid fields.

Reference parity: IBTK ``NormOps`` / SAMRAIVectorReal norms (T17).
Volume-weighted L1/L2/max norms and inner products. These are the global
reductions of the framework (the analog of the reference's MPI-reduced
PETSc VecNorm/VecDot, SURVEY.md §2.4); under sharding XLA lowers them to
``psum`` collectives.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp


def l1_norm(f: jnp.ndarray, cell_volume: float = 1.0) -> jnp.ndarray:
    return jnp.sum(jnp.abs(f)) * cell_volume


def l2_norm(f: jnp.ndarray, cell_volume: float = 1.0) -> jnp.ndarray:
    return jnp.sqrt(jnp.sum(jnp.square(f)) * cell_volume)


def max_norm(f: jnp.ndarray) -> jnp.ndarray:
    return jnp.max(jnp.abs(f))


def vel_l2_norm(u: Sequence[jnp.ndarray], cell_volume: float = 1.0) -> jnp.ndarray:
    s = jnp.sum(jnp.square(u[0]))
    for c in u[1:]:
        s = s + jnp.sum(jnp.square(c))
    return jnp.sqrt(s * cell_volume)


def vel_max_norm(u: Sequence[jnp.ndarray]) -> jnp.ndarray:
    m = jnp.max(jnp.abs(u[0]))
    for c in u[1:]:
        m = jnp.maximum(m, jnp.max(jnp.abs(c)))
    return m


def dot(a, b, cell_volume: float = 1.0) -> jnp.ndarray:
    """Volume-weighted inner product of two fields or two velocity tuples."""
    if isinstance(a, (tuple, list)):
        s = jnp.sum(a[0] * b[0])
        for x, y in zip(a[1:], b[1:]):
            s = s + jnp.sum(x * y)
        return s * cell_volume
    return jnp.sum(a * b) * cell_volume
