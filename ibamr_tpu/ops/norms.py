"""Discrete norms over grid fields.

Reference parity: IBTK ``NormOps`` / SAMRAIVectorReal norms (T17).
Volume-weighted L1/L2/max norms and inner products. These are the global
reductions of the framework (the analog of the reference's MPI-reduced
PETSc VecNorm/VecDot, SURVEY.md §2.4); under sharding XLA lowers them to
``psum`` collectives — every reduction here runs under the ``comm``
named scope so that cross-device time attributes to the comm op-class
(obs/deviceprof ``comm_s``) instead of landing in ``unattributed``.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp


def _reduce(fn, *args):
    """One global reduction under the ``comm`` named scope. The scope
    costs nothing single-device; under sharding it labels the psum the
    partitioner inserts for the cross-shard combine."""
    with jax.named_scope("comm"):
        return fn(*args)


def tree_dot(a: Any, b: Any) -> jnp.ndarray:
    """Unweighted inner product over any matching pytrees (the primitive
    under every norm and Krylov residual in the framework). Mismatched
    structures raise (via tree_map); empty trees give 0.0."""
    sums = jax.tree_util.tree_map(
        lambda x, y: _reduce(jnp.sum, x * y), a, b)
    leaves = jax.tree_util.tree_leaves(sums)
    if not leaves:
        return jnp.asarray(0.0)
    s = leaves[0]
    for x in leaves[1:]:
        s = s + x
    return s


def tree_dots(pairs: Sequence[Any]) -> jnp.ndarray:
    """K inner products as ONE fused reduction -> a (K,) vector.

    ``pairs`` is a sequence of (a, b) pytree pairs with identical
    structure. Per leaf the K elementwise products are stacked and
    reduced over the trailing axes in a single ``jnp.sum`` — under
    sharding the partitioner then inserts ONE psum of a length-K
    vector where K scalar ``tree_dot`` calls would each sync the mesh
    (the per-iteration Krylov reductions in solvers/krylov.py are the
    consumers). Each row reduces over the same elements in the same
    order as its scalar ``tree_dot``, so values are unchanged —
    tests/test_norms_fused.py pins exact equality in f64."""
    pairs = list(pairs)
    if not pairs:
        return jnp.zeros((0,))
    per_leaf = jax.tree_util.tree_map(
        lambda *xs: _reduce(
            lambda s: jnp.sum(s, axis=tuple(range(1, s.ndim))),
            jnp.stack(xs)),
        *[jax.tree_util.tree_map(jnp.multiply, a, b) for a, b in pairs])
    leaves = jax.tree_util.tree_leaves(per_leaf)
    if not leaves:
        return jnp.zeros((len(pairs),))
    s = leaves[0]
    for x in leaves[1:]:
        s = s + x
    return s


def l1_norm(f: jnp.ndarray, cell_volume: float = 1.0) -> jnp.ndarray:
    return _reduce(jnp.sum, jnp.abs(f)) * cell_volume


def l2_norm(f: jnp.ndarray, cell_volume: float = 1.0) -> jnp.ndarray:
    return jnp.sqrt(_reduce(jnp.sum, jnp.square(f)) * cell_volume)


def max_norm(f: jnp.ndarray) -> jnp.ndarray:
    return _reduce(jnp.max, jnp.abs(f))


def vel_l2_norm(u: Sequence[jnp.ndarray], cell_volume: float = 1.0) -> jnp.ndarray:
    s = _reduce(jnp.sum, jnp.square(u[0]))
    for c in u[1:]:
        s = s + _reduce(jnp.sum, jnp.square(c))
    return jnp.sqrt(s * cell_volume)


def vel_max_norm(u: Sequence[jnp.ndarray]) -> jnp.ndarray:
    m = _reduce(jnp.max, jnp.abs(u[0]))
    for c in u[1:]:
        m = jnp.maximum(m, _reduce(jnp.max, jnp.abs(c)))
    return m


def dot(a, b, cell_volume: float = 1.0) -> jnp.ndarray:
    """Volume-weighted inner product of two fields or two velocity tuples."""
    return tree_dot(a, b) * cell_volume
