"""Discrete norms over grid fields.

Reference parity: IBTK ``NormOps`` / SAMRAIVectorReal norms (T17).
Volume-weighted L1/L2/max norms and inner products. These are the global
reductions of the framework (the analog of the reference's MPI-reduced
PETSc VecNorm/VecDot, SURVEY.md §2.4); under sharding XLA lowers them to
``psum`` collectives.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp


def tree_dot(a: Any, b: Any) -> jnp.ndarray:
    """Unweighted inner product over any matching pytrees (the primitive
    under every norm and Krylov residual in the framework). Mismatched
    structures raise (via tree_map); empty trees give 0.0."""
    sums = jax.tree_util.tree_map(lambda x, y: jnp.sum(x * y), a, b)
    leaves = jax.tree_util.tree_leaves(sums)
    if not leaves:
        return jnp.asarray(0.0)
    s = leaves[0]
    for x in leaves[1:]:
        s = s + x
    return s


def l1_norm(f: jnp.ndarray, cell_volume: float = 1.0) -> jnp.ndarray:
    return jnp.sum(jnp.abs(f)) * cell_volume


def l2_norm(f: jnp.ndarray, cell_volume: float = 1.0) -> jnp.ndarray:
    return jnp.sqrt(jnp.sum(jnp.square(f)) * cell_volume)


def max_norm(f: jnp.ndarray) -> jnp.ndarray:
    return jnp.max(jnp.abs(f))


def vel_l2_norm(u: Sequence[jnp.ndarray], cell_volume: float = 1.0) -> jnp.ndarray:
    s = jnp.sum(jnp.square(u[0]))
    for c in u[1:]:
        s = s + jnp.sum(jnp.square(c))
    return jnp.sqrt(s * cell_volume)


def vel_max_norm(u: Sequence[jnp.ndarray]) -> jnp.ndarray:
    m = jnp.max(jnp.abs(u[0]))
    for c in u[1:]:
        m = jnp.maximum(m, jnp.max(jnp.abs(c)))
    return m


def dot(a, b, cell_volume: float = 1.0) -> jnp.ndarray:
    """Volume-weighted inner product of two fields or two velocity tuples."""
    return tree_dot(a, b) * cell_volume
