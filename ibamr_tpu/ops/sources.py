"""Internal fluid sources/sinks carried by Lagrangian points.

Reference parity: ``IBStandardSourceGen`` / ``IBLagrangianSourceStrategy``
(P14, SURVEY.md §2.2) — point sources of fluid mass inside immersed
structures (e.g. the inflow/outflow of a pumping heart chamber). Each
source m has a strength Q_m (volume rate); the Eulerian source field

    q(x) = sum_m Q_m delta_h(x - X_m)

enters the projection as div u = q (see
:meth:`ibamr_tpu.integrators.ins.INSStaggeredIntegrator.step`). In a
periodic (or any closed) domain, total source must balance total sink;
the projection removes any residual mean — the same compatibility
bookkeeping the reference performs across its source set.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import interaction
from ibamr_tpu.ops.delta import Kernel


class SourceSpecs(NamedTuple):
    """M point sources at marker indices ``idx`` with strengths ``Q``."""
    idx: jnp.ndarray        # (M,) int32 indices into the marker array
    Q: jnp.ndarray          # (M,) volume rates (+source / -sink)
    enabled: jnp.ndarray    # (M,) 0/1 mask


def make_sources(idx, Q, enabled=None, dtype=jnp.float32) -> SourceSpecs:
    idx = jnp.asarray(idx, dtype=jnp.int32)
    if enabled is None:
        enabled = jnp.ones(idx.shape, dtype=dtype)
    return SourceSpecs(idx=idx,
                       Q=jnp.asarray(Q, dtype=dtype),
                       enabled=jnp.asarray(enabled, dtype=dtype))


def eulerian_source(specs: SourceSpecs, grid: StaggeredGrid,
                    X: jnp.ndarray, kernel: Kernel = "IB_4",
                    Q: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Cell-centered q(x) = sum_m Q_m delta_h(x - X_m); ``Q`` overrides
    the static strengths (time-varying sources)."""
    strengths = specs.Q if Q is None else Q
    Xs = X[specs.idx]
    return interaction.spread(strengths * specs.enabled, grid, Xs,
                              centering="cell", kernel=kernel)
