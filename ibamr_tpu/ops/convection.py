"""Convective (advection) operators for the staggered INS equations.

Reference parity: the INSStaggered*ConvectiveOperator family (P4, SURVEY.md
§2.2) — PPM/upwind/centered Godunov-type operators with Fortran flux loops.
TPU-first redesign: the fluxes are whole-array fused stencils (jnp.roll or
ghost-padded slices), conservative (divergence) form on the MAC grid, so
XLA fuses the entire N(u) evaluation into a few HBM passes; no per-cell
Riemann logic.

Conventions as in ibamr_tpu.ops.stencils: u_d[i] at the lower d-face of
cell i. The operator returns N(u)_d at u_d's own faces, where
N(u)_d = sum_e d/dx_e (u_e u_d) (conservative form; equals u.grad u for
discretely divergence-free u).

Schemes:
- "centered": 2nd-order centered flux averages (energy-stable at moderate
  CFL with CN diffusion; the default for smooth acceptance configs).
- "upwind": 1st-order donor-cell upwinding of the advected component
  (robust, diffusive; the stabilized fallback).
- "ppm": piecewise-parabolic (Colella–Woodward 1984) limited
  reconstruction, upwinded at faces — the reference's default operator
  (``INSStaggeredPPMConvectiveOperator``), implemented as whole-array
  limited interpolants instead of Fortran predictor loops.

Two code paths:
- :func:`convective_rate` — the original fully-periodic roll formulation
  (centered/upwind only; kept as the minimal-HBM fast path).
- :func:`convective_rate_bc` — ghost-padded formulation supporting all
  schemes AND no-slip walls on any subset of axes (the wall-bounded
  Navier–Stokes path, VERDICT round 1 item 4), including inhomogeneous
  tangential wall velocities (moving lids). Wall storage follows
  ibamr_tpu.integrators.ins_walls: the wall-NORMAL component pins slot 0
  along its own axis to the lo wall face (and the hi wall face is the
  wrap image of slot 0), so its beyond-wall ghosts are odd reflections
  about the wall NODE; tangential components are cell-centered along the
  wall axis, so their ghosts reflect about the wall PLANE
  (ghost = 2*V_wall - interior).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax.numpy as jnp

from ibamr_tpu.ops import stencils

Vel = Tuple[jnp.ndarray, ...]

# ghost depth of the padded path: PPM face states reach 3 cells out
_G = 3


def _avg_m(f: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Backward 2-point average: value at i-1/2 from i-1, i."""
    return 0.5 * (f + jnp.roll(f, 1, axis))


def _avg_p(f: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Forward 2-point average: value at i+1/2 from i, i+1."""
    return 0.5 * (f + jnp.roll(f, -1, axis))


def _upwind_m(f: jnp.ndarray, vel: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Donor-cell value at i-1/2 given advecting velocity there."""
    return jnp.where(vel >= 0, jnp.roll(f, 1, axis), f)


def _upwind_p(f: jnp.ndarray, vel: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Donor-cell value at i+1/2 given advecting velocity there."""
    return jnp.where(vel >= 0, f, jnp.roll(f, -1, axis))


def _cui_face(U: jnp.ndarray, C: jnp.ndarray,
              D: jnp.ndarray) -> jnp.ndarray:
    """CUI face value from (far-upwind, upwind, downwind) cell states:
    cubic upwind interpolation limited by the convective-boundedness
    criterion in normalized-variable form (Waterson & Deconinck, JCP
    224 (2007); the reference's AdvDiffCUIConvectiveOperator /
    INSVCStaggeredConservative CUI menu entry, SURVEY.md P4/P19 [U]).

    NVD: phi_hat = (C-U)/(D-U); the limited face value is
      3*phi_hat           on (0, 1/6]
      3/4*phi_hat + 3/8   on (1/6, 5/6)   (the cubic-upwind segment)
      1                   on [5/6, 1)
      phi_hat (upwind)    outside (0, 1)  (non-smooth: donor cell)
    """
    den = D - U
    # guard the normalized variable where D == U (uniform data: face
    # value reduces to C regardless of the branch taken)
    safe = jnp.where(jnp.abs(den) > 0.0, den, 1.0)
    ph = (C - U) / safe
    f_hat = jnp.where(
        ph < 1.0 / 6.0, 3.0 * ph,
        jnp.where(ph <= 5.0 / 6.0, 0.75 * ph + 0.375,
                  jnp.ones_like(ph)))
    f_hat = jnp.where((ph > 0.0) & (ph < 1.0), f_hat, ph)
    return jnp.where(jnp.abs(den) > 0.0, U + f_hat * den, C)


def advective_face_value(Qm: jnp.ndarray, Qp: jnp.ndarray,
                         vel: jnp.ndarray, scheme: str,
                         Qmm: Optional[jnp.ndarray] = None,
                         Qpp: Optional[jnp.ndarray] = None
                         ) -> jnp.ndarray:
    """Face value of an advected scalar from its two neighbor cells
    (Qm below the face, Qp above) and the face-normal velocity — the one
    shared scheme-selection point for the cell-centered transport paths
    (adv_diff and the two-level AMR fluxes). ``"cui"`` additionally
    needs the far neighbors Qmm (below Qm) and Qpp (above Qp)."""
    if scheme == "centered":
        return 0.5 * (Qm + Qp)
    if scheme == "upwind":
        return jnp.where(vel > 0, Qm, Qp)
    if scheme == "cui":
        if Qmm is None or Qpp is None:
            raise ValueError("cui needs the far-neighbor states "
                             "Qmm/Qpp")
        up = _cui_face(Qmm, Qm, Qp)    # vel >= 0: C = Qm, U = Qmm
        dn = _cui_face(Qpp, Qp, Qm)    # vel <  0: C = Qp, U = Qpp
        return jnp.where(vel > 0, up,
                         jnp.where(vel < 0, dn, 0.5 * (up + dn)))
    raise ValueError(f"unknown convective scheme {scheme!r}")


def convective_rate(u: Vel, dx: Sequence[float], scheme: str = "centered") -> Vel:
    """N(u)_d = sum_e d/dx_e(u_e u_d), each component at its own faces."""
    if scheme not in ("centered", "upwind"):
        raise ValueError(f"unknown convective scheme {scheme!r}")
    dim = len(u)
    out = []
    for d in range(dim):
        acc = jnp.zeros_like(u[d])
        for e in range(dim):
            if e == d:
                # flux at cell centers along d: (avg u_d)^2 or upwind
                adv = _avg_p(u[d], d)           # advecting velocity at centers
                if scheme == "upwind":
                    q = _upwind_p(u[d], adv, d)
                else:
                    q = adv
                flux = adv * q                   # at cell centers
                acc = acc + (flux - jnp.roll(flux, 1, d)) / dx[d]
            else:
                # flux at d-e edges (corner i-1/2 in d, j-1/2 in e):
                # u_e averaged along d, u_d averaged (or upwinded) along e
                adv = _avg_m(u[e], d)            # u_e at the edge
                if scheme == "upwind":
                    q = _upwind_m(u[d], adv, e)
                else:
                    q = _avg_m(u[d], e)
                flux = adv * q                   # at edges (lower in e)
                acc = acc + (jnp.roll(flux, -1, e) - flux) / dx[e]
        out.append(acc)
    return tuple(out)


# ---------------------------------------------------------------------------
# Ghost-padded path: walls + PPM (convective_rate_bc)
# ---------------------------------------------------------------------------

def _take(a: jnp.ndarray, axis: int, lo: int, hi: int) -> jnp.ndarray:
    return stencils.axis_slice(a, axis, lo, hi)


def _pad_wrap(a: jnp.ndarray, axis: int, g: int) -> jnp.ndarray:
    n = a.shape[axis]
    return jnp.concatenate(
        [_take(a, axis, n - g, n), a, _take(a, axis, 0, g)], axis)


def _pad_cell_wall(a: jnp.ndarray, axis: int, g: int,
                   v_lo: float = 0.0, v_hi: float = 0.0) -> jnp.ndarray:
    """Ghosts for data at CELL CENTERS along a wall axis: odd reflection
    about the wall plane through the Dirichlet value
    (ghost[-1-k] = 2 v_lo - a[k]); v != 0 is a moving tangential wall."""
    n = a.shape[axis]
    lo = 2.0 * v_lo - jnp.flip(_take(a, axis, 0, g), axis)
    hi = 2.0 * v_hi - jnp.flip(_take(a, axis, n - g, n), axis)
    return jnp.concatenate([lo, a, hi], axis)


def _pad_face_pinned_wall(a: jnp.ndarray, axis: int, g: int) -> jnp.ndarray:
    """Ghosts for data at FACES along its own wall axis (pinned storage:
    slot 0 == lo wall face == 0; hi wall face == wrap image). Odd
    reflection about the wall nodes: a[-k] = -a[k]; a[n] = 0 (hi wall),
    a[n+k] = -a[n-k]. No-penetration is homogeneous by construction."""
    n = a.shape[axis]
    lo = -jnp.flip(_take(a, axis, 1, g + 1), axis)
    zero = jnp.zeros_like(_take(a, axis, 0, 1))
    hi = jnp.concatenate(
        [zero, -jnp.flip(_take(a, axis, n - (g - 1), n), axis)], axis)
    return jnp.concatenate([lo, a, hi], axis)


def _sh(ap: jnp.ndarray, axis: int, s: int, n: int, g: int) -> jnp.ndarray:
    """Shifted view of a g-padded array: value at index i+s, i in [0, n)."""
    return _take(ap, axis, g + s, g + s + n)


def _ppm_states(ap: jnp.ndarray, axis: int, n: int, g: int):
    """CW84 limited parabola edge states over the EXTENDED cell range
    [-1, n] (length n+2 along ``axis``): returns (aL, aR) with aL/aR the
    monotonized lower/upper-face states of each 1D cell."""
    def ext(s):
        return _take(ap, axis, g - 1 + s, g + 1 + s + n)

    a, am, ap1 = ext(0), ext(-1), ext(1)
    am2, ap2 = ext(-2), ext(2)

    def mc_slope(c, m, p):
        d = 0.5 * (p - m)
        mono = (p - c) * (c - m) > 0.0
        lim = jnp.minimum(jnp.abs(d),
                          2.0 * jnp.minimum(jnp.abs(p - c), jnp.abs(c - m)))
        return jnp.where(mono, jnp.sign(d) * lim, 0.0)

    s0 = mc_slope(a, am, ap1)
    sm = mc_slope(am, am2, a)
    sp = mc_slope(ap1, a, ap2)
    # 4th-order face interpolants with limited-slope correction (CW84 1.6)
    fL = am + 0.5 * (a - am) - (1.0 / 6.0) * (s0 - sm)
    fR = a + 0.5 * (ap1 - a) - (1.0 / 6.0) * (sp - s0)
    # monotonize the parabola (CW84 1.10)
    local_ext = (fR - a) * (a - fL) <= 0.0
    aL = jnp.where(local_ext, a, fL)
    aR = jnp.where(local_ext, a, fR)
    diff = aR - aL
    q6 = diff * (a - 0.5 * (aL + aR))
    d2 = diff * diff / 6.0
    aL = jnp.where(q6 > d2, 3.0 * a - 2.0 * aR, aL)
    aR = jnp.where(q6 < -d2, 3.0 * a - 2.0 * aL, aR)
    return aL, aR


def _face_value_padded(ap: jnp.ndarray, adv: jnp.ndarray, axis: int,
                       n: int, g: int, scheme: str,
                       shift: int) -> jnp.ndarray:
    """Advected value at the 1D faces ``i + shift - 1/2`` (shift=0: lower
    face of cell i; shift=1: upper face) from the g-padded cell data
    ``ap`` and the face-normal advecting velocity ``adv`` there."""
    qm = _sh(ap, axis, shift - 1, n, g)
    qp = _sh(ap, axis, shift, n, g)
    if scheme == "centered":
        return 0.5 * (qm + qp)
    if scheme == "upwind":
        return jnp.where(adv >= 0.0, qm, qp)
    if scheme == "ppm":
        aL, aR = _ppm_states(ap, axis, n, g)
        up = _take(aR, axis, shift, shift + n)        # aR of cell i+shift-1
        dn = _take(aL, axis, shift + 1, shift + 1 + n)  # aL of cell i+shift
        return jnp.where(adv > 0.0, up,
                         jnp.where(adv < 0.0, dn, 0.5 * (up + dn)))
    if scheme == "cui":
        qmm = _sh(ap, axis, shift - 2, n, g)
        qpp = _sh(ap, axis, shift + 1, n, g)
        up = _cui_face(qmm, qm, qp)
        dn = _cui_face(qpp, qp, qm)
        return jnp.where(adv > 0.0, up,
                         jnp.where(adv < 0.0, dn, 0.5 * (up + dn)))
    raise ValueError(f"unknown convective scheme {scheme!r}")


def _pin_wall_faces(a: jnp.ndarray, axis: int) -> jnp.ndarray:
    idx = [slice(None)] * a.ndim
    idx[axis] = slice(0, 1)
    return a.at[tuple(idx)].set(0.0)


def convective_rate_bc(
        u: Vel, dx: Sequence[float], scheme: str = "ppm",
        wall_axes: Optional[Sequence[bool]] = None,
        wall_tangential: Optional[Dict[Tuple[int, int, int], float]] = None,
) -> Vel:
    """N(u)_d = sum_e d/dx_e(u_e u_d) with BC-aware ghost fills.

    ``wall_axes[e]`` puts no-slip walls on both sides of axis e (storage
    convention of ibamr_tpu.integrators.ins_walls); axes without walls
    are periodic. ``wall_tangential[(d, e, side)]`` prescribes the
    tangential velocity of component d on the side(0=lo,1=hi) wall of
    axis e (a moving lid); unset entries are 0 (stationary no-slip).

    Wall-edge momentum fluxes vanish identically (the advecting normal
    velocity is 0 at walls), so the flux-divergence rolls stay exact;
    the wall-normal output faces (pinned slots) are zeroed.
    """
    dim = len(u)
    if wall_axes is None:
        wall_axes = (False,) * dim
    tang = wall_tangential or {}
    g = _G
    out = []
    for d in range(dim):
        acc = jnp.zeros_like(u[d])
        n_d = u[d].shape
        for e in range(dim):
            n_e = n_d[e]
            if e == d:
                # 1D cells = the faces of u_d along d; fluxes at cell
                # centers (the 1D upper faces, shift=1)
                if wall_axes[d]:
                    ud_p = _pad_face_pinned_wall(u[d], d, g)
                else:
                    ud_p = _pad_wrap(u[d], d, g)
                adv = 0.5 * (_sh(ud_p, d, 0, n_e, g)
                             + _sh(ud_p, d, 1, n_e, g))
                q = _face_value_padded(ud_p, adv, d, n_e, g, scheme,
                                       shift=1)
                flux = adv * q
                acc = acc + (flux - jnp.roll(flux, 1, d)) / dx[d]
            else:
                # fluxes at d-e edges (lower d-face x lower e-face).
                # advecting u_e averaged along d (u_e is cell-centered
                # along d; its wall value on axis d is its tangential
                # Dirichlet datum there)
                if wall_axes[d]:
                    ue_p = _pad_cell_wall(
                        u[e], d, 1,
                        v_lo=tang.get((e, d, 0), 0.0),
                        v_hi=tang.get((e, d, 1), 0.0))
                else:
                    ue_p = _pad_wrap(u[e], d, 1)
                adv = 0.5 * (_sh(ue_p, d, -1, n_d[d], 1)
                             + _sh(ue_p, d, 0, n_d[d], 1))
                # advected u_d along e (cell-centered along e)
                if wall_axes[e]:
                    ud_p = _pad_cell_wall(
                        u[d], e, g,
                        v_lo=tang.get((d, e, 0), 0.0),
                        v_hi=tang.get((d, e, 1), 0.0))
                else:
                    ud_p = _pad_wrap(u[d], e, g)
                q = _face_value_padded(ud_p, adv, e, n_e, g, scheme,
                                       shift=0)
                flux = adv * q
                acc = acc + (jnp.roll(flux, -1, e) - flux) / dx[e]
        if wall_axes[d]:
            acc = _pin_wall_faces(acc, d)
        out.append(acc)
    return tuple(out)
