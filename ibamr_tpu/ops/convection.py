"""Convective (advection) operators for the staggered INS equations.

Reference parity: the INSStaggered*ConvectiveOperator family (P4, SURVEY.md
§2.2) — PPM/upwind/centered Godunov-type operators with Fortran flux loops.
TPU-first redesign: the fluxes are whole-array fused stencels (jnp.roll),
conservative (divergence) form on the MAC grid, so XLA fuses the entire
N(u) evaluation into a few HBM passes; no per-cell Riemann logic.

Conventions as in ibamr_tpu.ops.stencils: u_d[i] at the lower d-face of
cell i. The operator returns N(u)_d at u_d's own faces, where
N(u)_d = sum_e d/dx_e (u_e u_d) (conservative form; equals u.grad u for
discretely divergence-free u).

Schemes:
- "centered": 2nd-order centered flux averages (energy-stable at moderate
  CFL with CN diffusion; the default for smooth acceptance configs).
- "upwind": 1st-order donor-cell upwinding of the advected component
  (robust, diffusive; the stabilized fallback).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp

Vel = Tuple[jnp.ndarray, ...]


def _avg_m(f: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Backward 2-point average: value at i-1/2 from i-1, i."""
    return 0.5 * (f + jnp.roll(f, 1, axis))


def _avg_p(f: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Forward 2-point average: value at i+1/2 from i, i+1."""
    return 0.5 * (f + jnp.roll(f, -1, axis))


def _upwind_m(f: jnp.ndarray, vel: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Donor-cell value at i-1/2 given advecting velocity there."""
    return jnp.where(vel >= 0, jnp.roll(f, 1, axis), f)


def _upwind_p(f: jnp.ndarray, vel: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Donor-cell value at i+1/2 given advecting velocity there."""
    return jnp.where(vel >= 0, f, jnp.roll(f, -1, axis))


def advective_face_value(Qm: jnp.ndarray, Qp: jnp.ndarray,
                         vel: jnp.ndarray, scheme: str) -> jnp.ndarray:
    """Face value of an advected scalar from its two neighbor cells
    (Qm below the face, Qp above) and the face-normal velocity — the one
    shared scheme-selection point for the cell-centered transport paths
    (adv_diff and the two-level AMR fluxes)."""
    if scheme == "centered":
        return 0.5 * (Qm + Qp)
    if scheme == "upwind":
        return jnp.where(vel > 0, Qm, Qp)
    raise ValueError(f"unknown convective scheme {scheme!r}")


def convective_rate(u: Vel, dx: Sequence[float], scheme: str = "centered") -> Vel:
    """N(u)_d = sum_e d/dx_e(u_e u_d), each component at its own faces."""
    if scheme not in ("centered", "upwind"):
        raise ValueError(f"unknown convective scheme {scheme!r}")
    dim = len(u)
    out = []
    for d in range(dim):
        acc = jnp.zeros_like(u[d])
        for e in range(dim):
            if e == d:
                # flux at cell centers along d: (avg u_d)^2 or upwind
                adv = _avg_p(u[d], d)           # advecting velocity at centers
                if scheme == "upwind":
                    q = _upwind_p(u[d], adv, d)
                else:
                    q = adv
                flux = adv * q                   # at cell centers
                acc = acc + (flux - jnp.roll(flux, 1, d)) / dx[d]
            else:
                # flux at d-e edges (corner i-1/2 in d, j-1/2 in e):
                # u_e averaged along d, u_d averaged (or upwinded) along e
                adv = _avg_m(u[e], d)            # u_e at the edge
                if scheme == "upwind":
                    q = _upwind_m(u[d], adv, e)
                else:
                    q = _avg_m(u[d], e)
                flux = adv * q                   # at edges (lower in e)
                acc = acc + (jnp.roll(flux, -1, e) - flux) / dx[e]
        out.append(acc)
    return tuple(out)
