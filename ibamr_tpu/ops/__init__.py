from ibamr_tpu.ops import stencils, norms

__all__ = ["stencils", "norms"]
