"""Staggered-grid vector calculus as fused XLA stencils.

Reference parity: ``HierarchyMathOps`` / ``PatchMathOps`` + their Fortran
kernels (T4, SURVEY.md §2.1) — discrete div, grad, Laplacian, curl, and
cell<->face interpolation on the MAC grid.

TPU-first design: every stencil is expressed with ``jnp.roll`` on whole
arrays. Under jit, XLA fuses these into single HBM-bandwidth-bound passes;
under a ``NamedSharding`` the SPMD partitioner lowers the rolls into
neighbor halo exchanges over ICI automatically — this *is* the replacement
for SAMRAI's RefineSchedule halo machinery on the periodic uniform level
(SURVEY.md §2.4). Periodic boundaries are therefore the native case; wall
boundaries are imposed by masking layers on top (see ibamr_tpu.bc).

Index conventions (see ibamr_tpu.grid.StaggeredGrid):
- cc field p[i]: cell centers.  fc field u_d[i]: lower face of cell i.
- d/dx of cc at faces: (p[i] - p[i-1])/dx  -> roll(+1)
- d/dx of fc at centers: (u[i+1] - u[i])/dx -> roll(-1)

With these, div(grad(p)) == laplacian(p) exactly, and gradient is the
negative adjoint of divergence — discrete integration by parts that the
projection method and the tests rely on.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp

Vel = Tuple[jnp.ndarray, ...]


def _dxm(f: jnp.ndarray, axis: int, h: float) -> jnp.ndarray:
    """Backward difference (f[i] - f[i-1]) / h — cc->fc derivative."""
    return (f - jnp.roll(f, 1, axis)) / h


def _dxp(f: jnp.ndarray, axis: int, h: float) -> jnp.ndarray:
    """Forward difference (f[i+1] - f[i]) / h — fc->cc derivative."""
    return (jnp.roll(f, -1, axis) - f) / h


def divergence(u: Sequence[jnp.ndarray], dx: Sequence[float]) -> jnp.ndarray:
    """div u at cell centers from face-centered components."""
    out = _dxp(u[0], 0, dx[0])
    for d in range(1, len(u)):
        out = out + _dxp(u[d], d, dx[d])
    return out


def gradient(p: jnp.ndarray, dx: Sequence[float]) -> Vel:
    """grad p at faces from a cell-centered field."""
    return tuple(_dxm(p, d, dx[d]) for d in range(len(dx)))


def laplacian(f: jnp.ndarray, dx: Sequence[float]) -> jnp.ndarray:
    """Standard 2d+1-point Laplacian on the field's own grid (cc or fc)."""
    out = jnp.zeros_like(f)
    for d in range(f.ndim):
        out = out + (jnp.roll(f, -1, d) - 2.0 * f + jnp.roll(f, 1, d)) / (dx[d] ** 2)
    return out


def laplacian_vel(u: Sequence[jnp.ndarray], dx: Sequence[float]) -> Vel:
    return tuple(laplacian(c, dx) for c in u)


# --------------------------------------------------------------------------
# Interpolations between centerings
# --------------------------------------------------------------------------

def cc_to_fc(p: jnp.ndarray) -> Vel:
    """Cell-centered scalar to each face centering (2-point average):
    value at lower face i of axis d = (p[i-1] + p[i]) / 2."""
    return tuple(0.5 * (p + jnp.roll(p, 1, d)) for d in range(p.ndim))


def fc_to_cc(u: Sequence[jnp.ndarray]) -> Vel:
    """Each face-centered component to cell centers (2-point average)."""
    return tuple(0.5 * (c + jnp.roll(c, -1, d)) for d, c in enumerate(u))


def fc_component_to_fc(u: Sequence[jnp.ndarray], src: int, dst: int) -> jnp.ndarray:
    """Interpolate component ``src`` onto the faces of component ``dst``
    (4-point average in the src/dst plane; identity if src == dst).
    Diagnostic/utility interpolation; the convective operator builds its
    own edge-centered averages instead (ibamr_tpu.ops.convection)."""
    c = u[src]
    if src == dst:
        return c
    # to cell centers along src axis (forward avg), then to dst faces
    # along dst axis (backward avg)
    c = 0.5 * (c + jnp.roll(c, -1, src))
    c = 0.5 * (c + jnp.roll(c, 1, dst))
    return c


# --------------------------------------------------------------------------
# Curl / vorticity
# --------------------------------------------------------------------------

def curl_2d_node(u: Sequence[jnp.ndarray], dx: Sequence[float]) -> jnp.ndarray:
    """2D vorticity w = dv/dx - du/dy at grid nodes (the natural centering:
    node [i,j] at position (i*dx, j*dy) touches u faces above/below and v
    faces left/right)."""
    dvdx = _dxm(u[1], 0, dx[0])
    dudy = _dxm(u[0], 1, dx[1])
    return dvdx - dudy


def curl_2d_cc(u: Sequence[jnp.ndarray], dx: Sequence[float]) -> jnp.ndarray:
    """2D vorticity averaged to cell centers (for tagging/visualization)."""
    w = curl_2d_node(u, dx)
    w = 0.5 * (w + jnp.roll(w, -1, 0))
    w = 0.5 * (w + jnp.roll(w, -1, 1))
    return w


def curl_3d_cc(u: Sequence[jnp.ndarray], dx: Sequence[float]) -> Vel:
    """3D vorticity components averaged to cell centers."""
    ucc = fc_to_cc(u)

    def dcc(f, axis, h):
        return (jnp.roll(f, -1, axis) - jnp.roll(f, 1, axis)) / (2.0 * h)

    wx = dcc(ucc[2], 1, dx[1]) - dcc(ucc[1], 2, dx[2])
    wy = dcc(ucc[0], 2, dx[2]) - dcc(ucc[2], 0, dx[0])
    wz = dcc(ucc[1], 0, dx[0]) - dcc(ucc[0], 1, dx[1])
    return (wx, wy, wz)


def vorticity_magnitude_cc(u: Sequence[jnp.ndarray], dx: Sequence[float]) -> jnp.ndarray:
    if len(u) == 2:
        return jnp.abs(curl_2d_cc(u, dx))
    w = curl_3d_cc(u, dx)
    return jnp.sqrt(w[0] ** 2 + w[1] ** 2 + w[2] ** 2)


# --------------------------------------------------------------------------
# Strain rate (T4 hierarchy-math completion: the reference's
# side-centered->cell strain/deformation diagnostics used by viscosity
# models and data post-processing)
# --------------------------------------------------------------------------

def strain_rate_cc(u: Sequence[jnp.ndarray],
                   dx: Sequence[float],
                   wall_axes: Sequence[bool] | None = None,
                   ) -> Tuple[Tuple[jnp.ndarray, ...], ...]:
    """Symmetric strain-rate tensor E_ij = (du_i/dx_j + du_j/dx_i)/2 at
    cell centers. Diagonal entries use the exact MAC face differences
    (native centering); off-diagonals use centered differences of the
    cell-averaged components via :func:`central_grad`, whose ``wall``
    mode switches the boundary layers to one-sided differences so no
    cross-wall wrapped value enters. Diagonal terms need no wall
    special-case: under the pinned-face no-slip storage convention the
    wrap face IS the wall face and carries the pinned wall value."""
    dim = len(u)
    ucc = fc_to_cc(u)
    wall_axes = (tuple(bool(w) for w in wall_axes)
                 if wall_axes is not None else (False,) * dim)

    E = [[None] * dim for _ in range(dim)]
    for i in range(dim):
        # du_i/dx_i from the two faces bounding the cell: exact MAC
        E[i][i] = (jnp.roll(u[i], -1, i) - u[i]) / dx[i]
        for j in range(i + 1, dim):
            Eij = 0.5 * (central_grad(ucc[i], j, dx[j], wall_axes[j])
                         + central_grad(ucc[j], i, dx[i], wall_axes[i]))
            E[i][j] = Eij
            E[j][i] = Eij
    return tuple(tuple(row) for row in E)


def strain_rate_magnitude_cc(u: Sequence[jnp.ndarray],
                             dx: Sequence[float],
                             wall_axes: Sequence[bool] | None = None,
                             ) -> jnp.ndarray:
    """|E| = sqrt(2 E:E) — the shear-rate scalar of generalized-Newtonian
    viscosity models. ``wall_axes`` forwards to :func:`strain_rate_cc`
    (one-sided boundary-layer differences on wall axes)."""
    E = strain_rate_cc(u, dx, wall_axes)
    acc = None
    for row in E:
        for e in row:
            t = e * e
            acc = t if acc is None else acc + t
    return jnp.sqrt(2.0 * acc)


def wall_boundary_masks(shape, axis: int):
    """(is_lo, is_hi) boolean masks of the first/last cell layer along
    ``axis`` — THE helper for zeroing/replacing cross-wall periodic-wrap
    differences under the even-reflection ghost convention (shared by
    the Godunov slope limiter and the level-set wall machinery so the
    convention is single-sourced)."""
    import jax

    i = jax.lax.broadcasted_iota(jnp.int32, tuple(shape), axis)
    return i == 0, i == shape[axis] - 1


def axis_slice(a: jnp.ndarray, axis: int, lo: int, hi: int) -> jnp.ndarray:
    """``a[..., lo:hi, ...]`` along ``axis`` — THE shared static-slice
    helper (the wall-flux concatenation assemblies and the ghost-padded
    convection path all need it; one definition, not per-module
    copies)."""
    idx = [slice(None)] * a.ndim
    idx[axis] = slice(lo, hi)
    return a[tuple(idx)]


def mac_complete_from_periodic(f):
    """Periodic lower-face MAC layout -> face-complete (+1 on each
    component's own axis), duplicating the wrap face. Exact when the
    physics guarantees the boundary faces carry the wrap value — e.g.
    a spread force whose structure keeps delta-support clearance from
    the boundary (both boundary faces then carry 0). Shared by the
    fine-window composite path and the open-boundary IB coupling."""
    out = []
    for d, c in enumerate(f):
        first = axis_slice(c, d, 0, 1)
        out.append(jnp.concatenate([c, first], axis=d))
    return tuple(out)


def mac_periodic_from_complete(u, n):
    """Face-complete MAC layout -> periodic lower-face layout (drop
    each component's upper boundary face). Inverse of
    :func:`mac_complete_from_periodic` under the clearance contract."""
    return tuple(axis_slice(c, d, 0, n[d]) for d, c in enumerate(u))


def central_grad(phi: jnp.ndarray, d: int, dx_d: float,
                 wall: bool = False) -> jnp.ndarray:
    """Central difference along ``d``; with ``wall``, plain ONE-SIDED
    differences at the boundary cells instead of the periodic wrap.
    THE shared cell-centered wall-gradient helper (level-set geometry,
    viscoelastic velocity gradients/stress divergence)."""
    g = (jnp.roll(phi, -1, d) - jnp.roll(phi, 1, d)) / (2.0 * dx_d)
    if wall:
        is_lo, is_hi = wall_boundary_masks(phi.shape, d)
        one_lo = (jnp.roll(phi, -1, d) - phi) / dx_d
        one_hi = (phi - jnp.roll(phi, 1, d)) / dx_d
        g = jnp.where(is_lo, one_lo, jnp.where(is_hi, one_hi, g))
    return g
