"""Kirchhoff rod mechanics: director frames, discrete strains, forces
and torques.

Reference parity: ``IBKirchhoffRodForceGen`` + the rod part of
``GeneralizedIBMethod`` (P12, SURVEY.md §2.2; Lim, Ferent, Wang, Peskin,
SIAM J. Sci. Comput. 31 (2008) — the generalized IB method with
orthonormal director triads {D1, D2, D3} carried by each rod node).

Discrete model (edge e between nodes i, i+1, rest spacing ds):
  edge frame   D^e = polar-orthonormalized midpoint of D_i, D_{i+1}
  curvature/twist strains (cyclic):
     Omega_1 = (dD2/ds) . D3^e,  Omega_2 = (dD3/ds) . D1^e,
     Omega_3 = (dD1/ds) . D2^e          (d/ds = forward difference)
  stretch/shear strain:  Gamma = (D^e)^T (X_{i+1}-X_i)/ds - e3
  energy: E = sum_e ds [ 1/2 b_k (Omega_k - kappa_k)^2
                         + 1/2 s_k Gamma_k^2 ]

TPU-first redesign: the reference evaluates hand-derived force/couple
formulas in C++ loops; here the discrete energy is a pure jitted
function of (X, D) and
  force   F_i = -dE/dX_i            (jax.grad)
  torque  N_i = -sum_rows d_row x dE/dd_row
(the rotational gradient: for a variation delta D = theta x D row-wise,
dE = theta . sum_rows (d_row x g_row)), so force/torque consistency with
the energy is guaranteed by construction. Batched 3x3 symmetric eigen-
solves (polar decomposition) and the strain algebra all fuse on device.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class RodSpecs(NamedTuple):
    """M rod edges between consecutive node indices idx0[m] -> idx1[m].

    b: (M, 3) bending/twist moduli; kappa: (M, 3) intrinsic curvature +
    twist; s: (M, 3) shear/stretch moduli; ds: (M,) rest spacing;
    enabled: (M,) 0/1 mask.
    """
    idx0: jnp.ndarray
    idx1: jnp.ndarray
    b: jnp.ndarray
    kappa: jnp.ndarray
    s: jnp.ndarray
    ds: jnp.ndarray
    enabled: jnp.ndarray


def make_rods(idx0, idx1, b, kappa, s, ds, dtype=jnp.float32) -> RodSpecs:
    idx0 = jnp.asarray(idx0, dtype=jnp.int32)
    M = idx0.shape[0]

    def arr3(v):
        v = jnp.asarray(v, dtype=dtype)
        return jnp.broadcast_to(v, (M, 3)) if v.ndim <= 1 else v

    return RodSpecs(
        idx0=idx0, idx1=jnp.asarray(idx1, dtype=jnp.int32),
        b=arr3(b), kappa=arr3(kappa), s=arr3(s),
        ds=jnp.broadcast_to(jnp.asarray(ds, dtype=dtype), (M,)),
        enabled=jnp.ones((M,), dtype=dtype))


def _quat_from_rot(R: jnp.ndarray) -> jnp.ndarray:
    """Unit quaternion (w,x,y,z) of rotation matrices with angle < pi
    (always true for adjacent rod frames); smooth at the identity —
    unlike eigen-based polar decomposition, whose gradient blows up on
    the degenerate spectrum the identity produces."""
    tr = R[..., 0, 0] + R[..., 1, 1] + R[..., 2, 2]
    w = 0.5 * jnp.sqrt(jnp.maximum(1.0 + tr, 1e-12))
    s = 1.0 / (4.0 * w)
    return jnp.stack([
        w,
        (R[..., 2, 1] - R[..., 1, 2]) * s,
        (R[..., 0, 2] - R[..., 2, 0]) * s,
        (R[..., 1, 0] - R[..., 0, 1]) * s], axis=-1)


def _rot_from_quat(q: jnp.ndarray) -> jnp.ndarray:
    w, x, y, z = q[..., 0], q[..., 1], q[..., 2], q[..., 3]
    return jnp.stack([
        jnp.stack([1 - 2 * (y * y + z * z), 2 * (x * y - w * z),
                   2 * (x * z + w * y)], axis=-1),
        jnp.stack([2 * (x * y + w * z), 1 - 2 * (x * x + z * z),
                   2 * (y * z - w * x)], axis=-1),
        jnp.stack([2 * (x * z - w * y), 2 * (y * z + w * x),
                   1 - 2 * (x * x + y * y)], axis=-1)], axis=-2)


def edge_frames(D: jnp.ndarray, specs: RodSpecs) -> jnp.ndarray:
    """Sqrt-rotation midpoint frame per edge (Lim et al. 2008):
    D^e = sqrt(D_b D_a^T) D_a -> (M, 3, 3). The quaternion square root
    is q^(1/2) ~ normalize(q + identity)."""
    Da = D[specs.idx0]
    Db = D[specs.idx1]
    # rows are directors: rotation taking frame a to frame b is
    # R = Db^T_cols ... with row-director convention R = Db^T Da ... use
    # R d_a,k = d_b,k  =>  R = sum_k d_b,k d_a,k^T = Db^T Da (rows outer)
    R = jnp.einsum("mki,mkj->mij", Db, Da)
    q = _quat_from_rot(R)
    qh = q + jnp.array([1.0, 0.0, 0.0, 0.0], dtype=q.dtype)
    qh = qh / jnp.linalg.norm(qh, axis=-1, keepdims=True)
    Rh = _rot_from_quat(qh)
    return jnp.einsum("mij,mkj->mki", Rh, Da)


def rod_strains(X: jnp.ndarray, D: jnp.ndarray, specs: RodSpecs
                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(Omega, Gamma) per edge -> ((M, 3), (M, 3))."""
    De = edge_frames(D, specs)
    Da = D[specs.idx0]
    Db = D[specs.idx1]
    dDds = (Db - Da) / specs.ds[:, None, None]
    # cyclic: Omega_k = (dD_{k+1}/ds) . D_{k+2}^e
    Om = jnp.stack([
        jnp.einsum("mi,mi->m", dDds[:, 1], De[:, 2]),
        jnp.einsum("mi,mi->m", dDds[:, 2], De[:, 0]),
        jnp.einsum("mi,mi->m", dDds[:, 0], De[:, 1])], axis=-1)
    t = (X[specs.idx1] - X[specs.idx0]) / specs.ds[:, None]
    Gam = jnp.einsum("mki,mi->mk", De, t)
    Gam = Gam - jnp.array([0.0, 0.0, 1.0], dtype=Gam.dtype)
    return Om, Gam


def rod_energy(X: jnp.ndarray, D: jnp.ndarray, specs: RodSpecs):
    """Total elastic energy of the rod network."""
    Om, Gam = rod_strains(X, D, specs)
    e_bend = 0.5 * jnp.sum(specs.b * (Om - specs.kappa) ** 2, axis=-1)
    e_shear = 0.5 * jnp.sum(specs.s * Gam ** 2, axis=-1)
    return jnp.sum(specs.enabled * specs.ds * (e_bend + e_shear))


def rod_force_torque(X: jnp.ndarray, D: jnp.ndarray, specs: RodSpecs
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(F, N): nodal forces (n, 3) and torques (n, 3) = -(gradients of
    the discrete energy), torque via the rotational gradient."""
    gX, gD = jax.grad(rod_energy, argnums=(0, 1))(X, D, specs)
    F = -gX
    # N_i = - sum_rows d_row x dE/dd_row
    N = -jnp.sum(jnp.cross(D, gD), axis=1)
    return F, N


def rodrigues(w: jnp.ndarray) -> jnp.ndarray:
    """Rotation matrices exp([w]_x) for rotation vectors w (..., 3),
    Taylor-guarded at small angles (safe under autodiff)."""
    theta = jnp.linalg.norm(w, axis=-1, keepdims=True)
    small = theta < 1e-8
    th = jnp.where(small, 1.0, theta)
    a = jnp.where(small, 1.0 - theta ** 2 / 6.0, jnp.sin(th) / th)
    b = jnp.where(small, 0.5 - theta ** 2 / 24.0,
                  (1.0 - jnp.cos(th)) / th ** 2)
    wx, wy, wz = w[..., 0], w[..., 1], w[..., 2]
    zeros = jnp.zeros_like(wx)
    K = jnp.stack([
        jnp.stack([zeros, -wz, wy], axis=-1),
        jnp.stack([wz, zeros, -wx], axis=-1),
        jnp.stack([-wy, wx, zeros], axis=-1)], axis=-2)
    I = jnp.eye(3, dtype=w.dtype)
    return (I + a[..., None] * K
            + b[..., None] * jnp.einsum("...ij,...jk->...ik", K, K))


def rotate_frames(D: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    """Rotate director triads by rotation vectors w: rows d_k ->
    R(w) d_k."""
    R = rodrigues(w)
    return jnp.einsum("...ij,...kj->...ki", R, D)


def straight_rod(n: int, length: float, origin=(0.0, 0.0, 0.0),
                 axis=(0.0, 0.0, 1.0), dtype=jnp.float32
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(X, D) for a straight rod with D3 along the axis (natural frame)."""
    import numpy as np
    a = np.asarray(axis, dtype=np.float64)
    a = a / np.linalg.norm(a)
    t = np.linspace(0.0, length, n)
    X = np.asarray(origin)[None, :] + t[:, None] * a[None, :]
    # any frame with third director = axis
    tmp = np.array([1.0, 0.0, 0.0])
    if abs(np.dot(tmp, a)) > 0.9:
        tmp = np.array([0.0, 1.0, 0.0])
    d1 = np.cross(tmp, a)
    d1 = d1 / np.linalg.norm(d1)
    d2 = np.cross(a, d1)
    D = np.broadcast_to(np.stack([d1, d2, a], axis=0), (n, 3, 3))
    return jnp.asarray(X, dtype=dtype), jnp.asarray(D.copy(), dtype=dtype)
