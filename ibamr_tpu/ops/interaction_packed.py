"""Occupancy-packed MXU spread/interpolate: chunked bucket matmuls.

Reference parity: same operations as :mod:`ibamr_tpu.ops.interaction`
(``LEInteractor::spread/interpolate``, T2 — the north-star hot path);
same math as :mod:`ibamr_tpu.ops.interaction_fast` (the bucketed MXU
formulation), different *layout*.

Why: the fixed ``(B_tiles, cap)`` slot pool of ``interaction_fast``
sizes ``cap`` by the MAXIMUM tile occupancy. For surface structures
(the flagship shell) the marker density is silhouette-clustered, so at
256^3 the pool runs at ~10% utilization — and the dominant HBM arrays
(the ``(B, cap, P)`` / ``(B, cap, nz)`` weight operands) are ~90%
padding. Round-3 on-chip profiling attributes most of the 167 ms of
transfer time per step to exactly that traffic.

TPU-first redesign: keep the tile/footprint geometry, but allocate
**chunks** of ``c`` marker slots per tile in proportion to occupancy:

  chunks_needed(tile) = ceil(count(tile) / c)
  chunk q in [0, Q): holds <= c markers of ONE tile, tile_of_chunk[q]

Total slots become ``Q*c ~ N + c*active_tiles`` instead of
``B*cap_max`` — utilization goes from ~10% to >40% on the flagship,
shrinking every weight/einsum operand by the same factor. The einsum
runs per chunk; per-tile partial tiles are reduced with a sorted
``segment_sum`` (chunk ids are assigned in tile order, so the segment
reduction is contiguous); the overlap-add is unchanged. Markers beyond
the global chunk capacity ``Q`` (not per-tile — a hot tile can take
arbitrarily many chunks) flow through the exact compact-scatter
fallback shared with interaction_fast.

Spread/interp reuse the same ``delta.get_kernel`` weights and remain
exact adjoints; tests pin equality against the scatter oracle.
"""

from __future__ import annotations

import contextlib
import functools
import math
from typing import NamedTuple, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import interaction
from ibamr_tpu.ops.delta import Kernel, get_kernel
from ibamr_tpu.ops.interaction_fast import (
    BucketGeometry, _block_ids_np, _extract_tiles, _overlap_add,
    _tile_weights, bucketed_channel, contract_compressed, make_geometry,
    spread_overflow_fallbacks, unbucket_with_overflow)

Vel = Tuple[jnp.ndarray, ...]

# Reverse-mode policy for the packed transfers (PR 19). The default
# custom VJP reuses spread/interp adjointness: d(spread) wrt F is an
# interp of the grid cotangent through the SAME ``PackedBuckets`` (pure
# gathers — the overflow merge is rewritten scatter-free), d(interp)
# wrt f is a spread through the same buckets, and position cotangents
# flow through the oracle stencil weights (gather-only graphs). The
# bucket layout itself is treated as a non-differentiated constant:
# pack-time integers are piecewise constant in X, and the position
# gradient is returned in full through the explicit ``X`` argument
# (callers always pass the same X the buckets were built from — the
# engine API bakes that in). Set False to fall back to plain autodiff
# through the packed implementation (saves nothing, emits transposed
# scatters, and is NOT covered by the ``grad_spread``/``grad_interp``
# graph budgets).
GRAD_TRANSFERS = True


@contextlib.contextmanager
def plain_autodiff_transfers():
    """Trace-scoped opt-out of the custom-VJP transfer wrappers.

    ``jax.custom_vjp`` functions refuse forward-mode autodiff
    (jvp/linearize), so any graph that takes exact JVPs through a
    spread/interp — the implicit Newton-Krylov coupling linearizes its
    whole spread -> solve -> interp residual — must trace inside this
    context: transfers route through the raw packed implementations,
    which JAX differentiates natively in both modes (reverse mode there
    emits transposed scatters and is NOT covered by the
    ``grad_spread``/``grad_interp`` budgets)."""
    global GRAD_TRANSFERS
    prev = GRAD_TRANSFERS
    GRAD_TRANSFERS = False
    try:
        yield
    finally:
        GRAD_TRANSFERS = prev


class PackedBuckets(NamedTuple):
    """Chunk-packed marker layout (duck-types interaction_fast.Buckets
    for the shared helpers: same field names + ``tile_of_chunk``)."""
    Xb: jnp.ndarray               # (Q, c, dim)
    wb: jnp.ndarray               # (Q, c) marker weights (0 = empty slot)
    slot_of_marker: jnp.ndarray   # (N,) flat slot or Q*c (overflowed)
    w_overflow: jnp.ndarray       # (N,)
    o_idx: jnp.ndarray            # (ocap,)
    o_w: jnp.ndarray              # (ocap,)
    any_overflow: jnp.ndarray     # () bool
    exceeded: jnp.ndarray         # () bool
    x0: Tuple[jnp.ndarray, ...]   # per blocked axis: (Q,) tile origin
    tile_of_chunk: jnp.ndarray    # (Q,) int32, nondecreasing


def suggest_chunks(grid: StaggeredGrid, X, kernel: Kernel = "IB_4",
                   tile: int = 8, chunk: int = 128,
                   slack: float = 1.3) -> int:
    """Host-side chunk-capacity heuristic from a concrete marker
    distribution: slack x the exact chunk demand sum(ceil(count/c))."""
    Xn = np.asarray(X)
    support, _ = get_kernel(kernel)
    bids = _block_ids_np(grid, Xn, support, tile)
    B = int(np.prod([n // tile for n in grid.n[:-1]]))
    counts = np.bincount(bids, minlength=B)
    need = int(np.sum(-(-counts // chunk)))
    return max(8, int(math.ceil(need * slack)))


def chunk_pack_core(bid: jnp.ndarray, X: jnp.ndarray,
                    weights: jnp.ndarray, Q: int, c: int, B: int,
                    overflow_cap: int):
    """THE occupancy-packing core shared by every chunk-packed layout
    (xy-packed here, fully-blocked in interaction_packed3 — one
    definition so the sort/assign/scatter/overflow machinery cannot
    diverge between engines): given per-marker tile ids ``bid`` in
    [0, B), pack markers into ``Q`` chunks of ``c`` slots allocated
    compactly in tile order. Returns
    (Xb, wb, slot_of_marker, w_overflow, o_idx, o_w, n_over,
    exceeded, tile_of_chunk)."""
    N, dim = X.shape
    order = jnp.argsort(bid)
    bid_s = bid[order]
    # per-tile marker ranges from the sorted ids (no scatter: TPU
    # scatter-adds over 1e5 indices serialize — measured 14.6 ms of
    # bucket prep at the flagship shape before this rewrite)
    edges = jnp.searchsorted(bid_s,
                             jnp.arange(B + 1, dtype=bid_s.dtype))
    start, counts = edges[:-1], jnp.diff(edges).astype(jnp.int32)
    nchunk_tile = -((-counts) // c)                     # ceil(counts/c)
    base = jnp.cumsum(nchunk_tile) - nchunk_tile        # exclusive scan
    rank = jnp.arange(N, dtype=jnp.int32) - start[bid_s].astype(jnp.int32)
    chunk_s = base[bid_s] + rank // c                   # global chunk id
    keep = chunk_s < Q
    slot_sorted = jnp.where(keep, chunk_s * c + rank % c, Q * c)

    # tile of every chunk, directly from the chunk allocation (base is
    # nondecreasing): chunk j belongs to the last tile whose first
    # chunk is <= j; trailing never-allocated chunks pin to B-1 so the
    # id sequence stays nondecreasing for the sorted segment_sum
    tid = (jnp.searchsorted(base, jnp.arange(Q, dtype=base.dtype),
                            side="right").astype(jnp.int32) - 1)
    tid = jnp.clip(tid, 0, B - 1)

    # slot -> sorted-marker position (pure gathers; every slot of an
    # allocated chunk maps to start[tile] + offset-in-tile, empty
    # slots gather a zero fill). Bitwise-identical layout to the old
    # scatter construction.
    q_c = jnp.arange(Q * c, dtype=jnp.int32) // c       # chunk of slot
    r = jnp.arange(Q * c, dtype=jnp.int32) % c          # rank in chunk
    t_of_slot = tid[q_c]
    off_in_tile = (q_c - base[t_of_slot]) * c + r
    valid = (off_in_tile >= 0) & (off_in_tile < counts[t_of_slot])
    src = jnp.where(valid, start[t_of_slot] + off_in_tile, N)
    X_s = X[order]
    w_s = weights[order]
    Xb = jnp.take(X_s, src, axis=0, mode="fill",
                  fill_value=0).reshape(Q, c, dim)
    wb = jnp.take(w_s, src, mode="fill", fill_value=0).reshape(Q, c)

    from ibamr_tpu.ops.interaction_fast import compact_overflow
    (slot_of_marker, w_overflow, o_idx, o_w, n_over,
     exceeded) = compact_overflow(order, keep, slot_sorted, weights, N,
                                  overflow_cap)

    return (Xb, wb, slot_of_marker, w_overflow, o_idx, o_w, n_over,
            exceeded, tid)


def default_overflow_cap(N: int) -> int:
    """Shared overflow-buffer sizing heuristic."""
    return min(N, max(2048, 1 << int(math.ceil(
        math.log2(max(N // 8, 1))))))


def pack_markers(geom: BucketGeometry, grid: StaggeredGrid,
                 X: jnp.ndarray, weights: Optional[jnp.ndarray] = None,
                 nchunks: int = 1024,
                 overflow_cap: Optional[int] = None) -> PackedBuckets:
    """Bucket markers by tile, then pack tiles' markers into ``Q``
    chunks of ``geom.cap`` slots, allocated compactly in tile order."""
    N, dim = X.shape
    if weights is None:
        weights = jnp.ones((N,), dtype=X.dtype)
    if overflow_cap is None:
        overflow_cap = default_overflow_cap(N)
    s = geom.support
    Q = int(nchunks)
    bid = jnp.zeros((N,), dtype=jnp.int32)
    for d in range(dim - 1):
        xi = (X[:, d] - grid.x_lo[d]) / grid.dx[d] - 0.5
        j0 = jnp.floor(xi - 0.5 * s).astype(jnp.int32) + 1
        b = jnp.mod(j0, grid.n[d]) // geom.tile[d]
        bid = bid * geom.nblk[d] + b
    B = int(np.prod(geom.nblk))

    (Xb, wb, slot_of_marker, w_overflow, o_idx, o_w, n_over,
     exceeded, tid) = chunk_pack_core(bid, X, weights, Q, geom.cap, B,
                                      overflow_cap)
    x0 = []
    for d in range(dim - 1):
        ids = tid
        for a in range(dim - 1 - 1, d, -1):
            ids = ids // geom.nblk[a]
        x0.append((ids % geom.nblk[d]) * geom.tile[d])
    return PackedBuckets(Xb=Xb, wb=wb, slot_of_marker=slot_of_marker,
                         w_overflow=w_overflow, o_idx=o_idx, o_w=o_w,
                         any_overflow=n_over > 0, exceeded=exceeded,
                         x0=tuple(x0), tile_of_chunk=tid)


def refresh_packed(geom: BucketGeometry, grid: StaggeredGrid,
                   b: PackedBuckets, X: jnp.ndarray,
                   weights: Optional[jnp.ndarray] = None
                   ) -> Tuple[PackedBuckets, jnp.ndarray]:
    """Slot-preserving half-step refresh: re-gather the NEW positions
    ``X`` into the existing pack-time chunk layout of ``b`` instead of
    re-running the full sort/bucket/pack.

    Exactness: a chunk's footprint covers cells ``[x0-1, x0+tile+s-1]``
    (``_blocked_axis_weights`` starts one cell below the tile origin).
    On a staggered grid axis ``d`` sees TWO stencil origins per marker
    — the cell-centered one (offset 0.5; components != d) and the
    face-centered one (offset 0.0; component d, up to one cell higher)
    — and the transfer stays EXACT for any drifted position whose new
    origins BOTH satisfy ``mod(j0 - (x0-1), n) <= tile+1`` on every
    blocked axis (then every stencil cell of every component still
    lands in the footprint, and the mod-centered distances evaluate
    the same periodic weights the scatter oracle uses). In continuous
    terms that gives every marker at least half a cell of forward
    slack and a full cell backward, so CFL-bounded substep drift
    always passes. Overflow markers stay exact regardless: the
    compact-scatter fallbacks evaluate at call-time ``X``.

    The drift bound is checked jittably; when ANY live packed marker
    violates it the whole layout falls back to a full re-pack under
    ``lax.cond`` (identical static shapes), so the result is exact
    either way. Returns ``(buckets, hit)`` with ``hit`` True when the
    cheap re-gather was sufficient."""
    N, dim = X.shape
    if weights is None:
        weights = jnp.ones((N,), dtype=X.dtype)
    # both lax.cond branches must carry identical pytrees: the re-pack
    # branch derives its weight fields from ``weights``, the refresh
    # branch keeps ``b``'s
    weights = jnp.asarray(weights, dtype=b.wb.dtype)
    Q, c = b.Xb.shape[0], b.Xb.shape[1]
    ocap = b.o_idx.shape[0]
    s = geom.support
    slot = b.slot_of_marker
    chunk_of_marker = jnp.minimum(slot // c, Q - 1)

    # drift-bound check per blocked axis, against the ASSIGNED chunk's
    # pack-time tile origin (overflow/inactive markers are exempt:
    # their transfers never read the packed layout)
    ok = jnp.ones((N,), dtype=bool)
    for d in range(dim - 1):
        x0 = b.x0[d][chunk_of_marker]
        for off in (0.5, 0.0):      # cell- and face-centered origins
            xi = (X[:, d] - grid.x_lo[d]) / grid.dx[d] - off
            j0 = jnp.floor(xi - 0.5 * s).astype(jnp.int32) + 1
            r = jnp.mod(j0 - (x0 - 1), grid.n[d])
            ok &= r <= geom.tile[d] + 1
    ok |= (slot >= Q * c) | (weights == 0)
    hit = jnp.all(ok)

    # slot -> marker inverse (one N-sized scatter; duplicates only at
    # the discarded overflow sentinel Q*c), then one gather of the new
    # positions into the pack-time slots. Everything else in the
    # layout (weights, overflow lists, chunk->tile map) is
    # position-independent and carries over.
    inv = jnp.full((Q * c + 1,), N, dtype=jnp.int32).at[slot].set(
        jnp.arange(N, dtype=jnp.int32))
    Xb = jnp.take(X, inv[:-1], axis=0, mode="fill",
                  fill_value=0).reshape(Q, c, dim)

    return jax.lax.cond(
        hit,
        lambda: b._replace(Xb=Xb),
        lambda: pack_markers(geom, grid, X, weights, nchunks=Q,
                             overflow_cap=ocap)), hit


def _spread_raw(geom: BucketGeometry, grid: StaggeredGrid,
                b: PackedBuckets, F: jnp.ndarray, X: jnp.ndarray,
                centering, kernel: Kernel,
                precision=jax.lax.Precision.HIGHEST,
                compute_dtype=None) -> jnp.ndarray:
    inv_vol = 1.0 / math.prod(grid.dx)
    Ff = bucketed_channel(b, F)
    A, Wlast = _tile_weights(geom, grid, b, centering, kernel)
    A = A * (Ff * b.wb * inv_vol)[..., None]
    Tq = contract_compressed("qmp,qmz->qpz", A, Wlast, compute_dtype,
                             precision=precision)
    B = int(np.prod(geom.nblk))
    T = jax.ops.segment_sum(Tq, b.tile_of_chunk, num_segments=B,
                            indices_are_sorted=True)
    out = _overlap_add(geom, grid, T.reshape(
        (B,) + tuple(geom.width) + (grid.n[grid.dim - 1],)))
    return spread_overflow_fallbacks(out, b, F, X, grid, centering,
                                     kernel)


def _interp_raw(geom: BucketGeometry, grid: StaggeredGrid,
                b: PackedBuckets, f: jnp.ndarray, X: jnp.ndarray,
                centering, kernel: Kernel,
                precision=jax.lax.Precision.HIGHEST,
                compute_dtype=None) -> jnp.ndarray:
    T = _extract_tiles(geom, grid, f)                 # (B, P, nz)
    Tq = jnp.take(T, b.tile_of_chunk, axis=0)         # (Q, P, nz)
    A, Wlast = _tile_weights(geom, grid, b, centering, kernel)
    D = contract_compressed("qpz,qmz->qmp", Tq, Wlast, compute_dtype,
                            precision=precision)
    Ub = jnp.sum(A * D, axis=-1) * b.wb               # (Q, c)
    return unbucket_with_overflow(Ub, b, f, X, grid, centering, kernel)


# -- packed-transfer reverse mode (PR 19) ------------------------------------

def _marker_weights(b: PackedBuckets) -> jnp.ndarray:
    """Recover the per-ORIGINAL-marker weight vector from the packed
    layout: the pack-time weight for packed markers (their slot is
    unique) plus ``w_overflow`` for dropped ones — pure gathers."""
    wb_flat = b.wb.reshape(-1)
    packed = jnp.take(wb_flat, jnp.minimum(b.slot_of_marker,
                                           wb_flat.size - 1))
    packed = jnp.where(b.slot_of_marker < wb_flat.size, packed, 0.0)
    return packed + b.w_overflow


def _merge_overflow_gather(U: jnp.ndarray, o_idx: jnp.ndarray,
                           vals: jnp.ndarray) -> jnp.ndarray:
    """``U.at[o_idx].add(vals)`` rewritten scatter-free: sort the
    compact overflow list by marker id, prefix-sum the sorted values,
    and gather each marker's run sum via two searchsorted probes
    (sort + cumsum + gathers only — pad entries alias real markers
    with value 0, and duplicate ids sum exactly as the scatter-add
    would)."""
    perm = jnp.argsort(o_idx)
    so = o_idx[perm]
    cs = jnp.concatenate([jnp.zeros((1,), vals.dtype),
                          jnp.cumsum(vals[perm])])
    ar = jnp.arange(U.shape[0], dtype=so.dtype)
    lo = jnp.searchsorted(so, ar, side="left")
    hi = jnp.searchsorted(so, ar, side="right")
    return U + (cs[hi] - cs[lo])


def _interp_gather_only(geom: BucketGeometry, grid: StaggeredGrid,
                        b: PackedBuckets, g: jnp.ndarray,
                        X: jnp.ndarray, centering, kernel: Kernel,
                        precision, compute_dtype) -> jnp.ndarray:
    """Interp of grid field ``g`` through the SAME buckets, emitting
    ZERO scatter primitives: the packed main path is already pure
    gathers/einsum; the overflow merge goes through
    :func:`_merge_overflow_gather` instead of ``.at[].add``. This is
    the spread VJP's cotangent pass — ``grad_spread`` pins the zero."""
    T = _extract_tiles(geom, grid, g)
    Tq = jnp.take(T, b.tile_of_chunk, axis=0)
    A, Wlast = _tile_weights(geom, grid, b, centering, kernel)
    D = contract_compressed("qpz,qmz->qmp", Tq, Wlast, compute_dtype,
                            precision=precision)
    Ub = jnp.sum(A * D, axis=-1) * b.wb
    U = jnp.take(Ub.reshape(-1), jnp.minimum(
        b.slot_of_marker, Ub.size - 1), axis=0)
    U = jnp.where(b.slot_of_marker < Ub.size, U, 0.0)

    def compact(U):
        Uo = interaction.interpolate(g, grid, X[b.o_idx],
                                     centering=centering, kernel=kernel,
                                     weights=b.o_w)
        return _merge_overflow_gather(U, b.o_idx, Uo)

    def full(U):
        return U + interaction.interpolate(
            g, grid, X, centering=centering, kernel=kernel,
            weights=b.w_overflow)

    return jax.lax.cond(
        b.exceeded, full,
        lambda u: jax.lax.cond(b.any_overflow, compact,
                               lambda uu: uu, u), U)


def _position_cotangent(grid: StaggeredGrid, field: jnp.ndarray,
                        X: jnp.ndarray, centering, kernel: Kernel,
                        scale: jnp.ndarray) -> jnp.ndarray:
    """Marker-position cotangent of a transfer: pull ``scale`` (the
    per-marker chain factor) back through the oracle stencil evaluation
    ``X -> sum_cells field * delta_h(cells - X)``. The stencil indices
    are floor-derived (zero derivative); only the kernel weights
    differentiate, so the pulled-back graph is gathers + elementwise —
    no scatters."""
    y, pull = jax.vjp(
        lambda Xp: interaction.interpolate(field, grid, Xp,
                                           centering=centering,
                                           kernel=kernel), X)
    (X_ct,) = pull(scale.astype(y.dtype))
    return X_ct


def _zeros_ct(x):
    if jnp.issubdtype(jnp.result_type(x), jnp.inexact):
        return jnp.zeros_like(x)
    return np.zeros(jnp.shape(x), dtype=jax.dtypes.float0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4, 5))
def _spread_vjp(geom, grid, centering, kernel, precision, compute_dtype,
                b: PackedBuckets, F: jnp.ndarray,
                X: jnp.ndarray) -> jnp.ndarray:
    return _spread_raw(geom, grid, b, F, X, centering, kernel,
                       precision=precision, compute_dtype=compute_dtype)


def _spread_fwd(geom, grid, centering, kernel, precision, compute_dtype,
                b, F, X):
    out = _spread_raw(geom, grid, b, F, X, centering, kernel,
                      precision=precision, compute_dtype=compute_dtype)
    return out, (b, F, X)


def _spread_bwd(geom, grid, centering, kernel, precision, compute_dtype,
                res, ct):
    b, F, X = res
    inv_vol = 1.0 / math.prod(grid.dx)
    # d/dF: interp of the grid cotangent through the SAME buckets
    # (weights included), scaled by the spread's 1/h^dim — zero
    # scatters, zero bucket preps
    F_ct = inv_vol * _interp_gather_only(geom, grid, b, ct, X,
                                         centering, kernel, precision,
                                         compute_dtype)
    # d/dX: the kernel-weight derivative, pulled back through the
    # oracle stencil evaluation of the SAME cotangent field
    w_full = _marker_weights(b)
    X_ct = _position_cotangent(grid, ct, X, centering, kernel,
                               F * w_full * inv_vol)
    return (jax.tree_util.tree_map(_zeros_ct, b), F_ct, X_ct)


_spread_vjp.defvjp(_spread_fwd, _spread_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4, 5))
def _interp_vjp(geom, grid, centering, kernel, precision, compute_dtype,
                b: PackedBuckets, f: jnp.ndarray,
                X: jnp.ndarray) -> jnp.ndarray:
    return _interp_raw(geom, grid, b, f, X, centering, kernel,
                       precision=precision, compute_dtype=compute_dtype)


def _interp_fwd(geom, grid, centering, kernel, precision, compute_dtype,
                b, f, X):
    out = _interp_raw(geom, grid, b, f, X, centering, kernel,
                      precision=precision, compute_dtype=compute_dtype)
    return out, (b, f, X)


def _interp_bwd(geom, grid, centering, kernel, precision, compute_dtype,
                res, ct):
    b, f, X = res
    vol = math.prod(grid.dx)
    # d/df: spread of the marker cotangents through the SAME buckets;
    # interp carries no 1/h^dim, so undo the spread's factor. The
    # grid-side adjoint of a gather IS a scatter — this path reuses
    # the primal spread's scatter set verbatim (grad_interp budgets
    # it; no NEW scatter shapes are introduced)
    f_ct = vol * _spread_raw(geom, grid, b, ct, X, centering, kernel,
                             precision=precision,
                             compute_dtype=compute_dtype)
    w_full = _marker_weights(b)
    X_ct = _position_cotangent(grid, f, X, centering, kernel,
                               ct * w_full)
    return (jax.tree_util.tree_map(_zeros_ct, b), f_ct, X_ct)


_interp_vjp.defvjp(_interp_fwd, _interp_bwd)


def spread_packed(geom: BucketGeometry, grid: StaggeredGrid,
                  b: PackedBuckets, F: jnp.ndarray, X: jnp.ndarray,
                  centering, kernel: Kernel,
                  precision=jax.lax.Precision.HIGHEST,
                  compute_dtype=None) -> jnp.ndarray:
    """Spread marker values F (N,) -> grid field; exact up to roundoff
    vs interaction.spread (overflow flows through that path).
    ``compute_dtype=jnp.bfloat16`` compresses the chunk operands (the
    dominant HBM traffic; ~3 decimal digits of weight precision).

    Reverse mode: a custom VJP (see ``GRAD_TRANSFERS``) whose cotangent
    pass is an interp through the SAME buckets — zero scatter
    primitives, zero extra bucket preps (the ``grad_spread`` graph
    budget pins both)."""
    if not GRAD_TRANSFERS:
        return _spread_raw(geom, grid, b, F, X, centering, kernel,
                           precision=precision,
                           compute_dtype=compute_dtype)
    return _spread_vjp(geom, grid, centering, kernel, precision,
                       compute_dtype, b, F, X)


def interpolate_packed(geom: BucketGeometry, grid: StaggeredGrid,
                       b: PackedBuckets, f: jnp.ndarray, X: jnp.ndarray,
                       centering, kernel: Kernel,
                       precision=jax.lax.Precision.HIGHEST,
                       compute_dtype=None) -> jnp.ndarray:
    """Interpolate grid field at markers -> (N,) (adjoint of spread).

    Reverse mode: custom VJP — d/df is a spread through the SAME
    buckets (scaled by h^dim), d/dX the oracle weight-derivative
    pullback (``grad_interp`` budgets the pass)."""
    if not GRAD_TRANSFERS:
        return _interp_raw(geom, grid, b, f, X, centering, kernel,
                           precision=precision,
                           compute_dtype=compute_dtype)
    return _interp_vjp(geom, grid, centering, kernel, precision,
                       compute_dtype, b, f, X)


class PackedInteraction:
    """Drop-in FastInteraction-shaped engine with occupancy-packed
    chunks: bucket+pack once per X, reuse for all components and both
    directions within a timestep. ``chunk`` is the per-chunk slot count
    (the MXU contraction depth — keep it a multiple of 128);
    ``nchunks`` the static global chunk capacity — size it from a
    concrete marker distribution with :func:`suggest_chunks` (the
    flagship model does this at build time); markers beyond it flow
    through the exact scatter fallback."""

    def __init__(self, grid: StaggeredGrid, kernel: Kernel = "IB_4",
                 tile: int = 8, chunk: int = 128, nchunks: int = 1024,
                 overflow_cap: Optional[int] = None,
                 compute_dtype=None):
        self.grid = grid
        self.kernel: Kernel = kernel
        self.geom = make_geometry(grid, kernel, tile=tile, cap=chunk)
        self.nchunks = int(nchunks)
        self.overflow_cap = overflow_cap
        self.compute_dtype = compute_dtype

    def buckets(self, X: jnp.ndarray,
                weights: Optional[jnp.ndarray] = None) -> PackedBuckets:
        return pack_markers(self.geom, self.grid, X, weights,
                            nchunks=self.nchunks,
                            overflow_cap=self.overflow_cap)

    def refresh(self, b: PackedBuckets, X: jnp.ndarray,
                weights: Optional[jnp.ndarray] = None
                ) -> Tuple[PackedBuckets, jnp.ndarray]:
        """Slot-preserving re-gather of new positions into ``b``'s
        chunk layout (full re-pack fallback under the drift bound);
        returns ``(buckets, hit)`` — see :func:`refresh_packed`."""
        return refresh_packed(self.geom, self.grid, b, X, weights)

    def interpolate_vel(self, u: Vel, X: jnp.ndarray,
                        weights: Optional[jnp.ndarray] = None,
                        b: Optional[PackedBuckets] = None) -> jnp.ndarray:
        if b is None:
            b = self.buckets(X, weights)
        cols = [interpolate_packed(self.geom, self.grid, b, u[d], X,
                                   d, self.kernel,
                                   compute_dtype=self.compute_dtype)
                for d in range(self.grid.dim)]
        return jnp.stack(cols, axis=-1)

    def spread_vel(self, F: jnp.ndarray, X: jnp.ndarray,
                   weights: Optional[jnp.ndarray] = None,
                   b: Optional[PackedBuckets] = None) -> Vel:
        if b is None:
            b = self.buckets(X, weights)
        return tuple(spread_packed(self.geom, self.grid, b, F[:, d], X,
                                   d, self.kernel,
                                   compute_dtype=self.compute_dtype)
                     for d in range(self.grid.dim))


# -- engine registry: graceful degradation chain -----------------------------
#
# Registry-level fallback order for every named transfer engine. When an
# engine's construction, compile, or probe execution fails (a Pallas
# remote-compile stall, a Mosaic lowering regression, a geometry
# constraint on an unusual grid), the run degrades one link down this
# chain — trading measured speed for availability — instead of dying.
# Every chain terminates at "scatter" (the always-correct XLA
# scatter/gather oracle, engine object None). Consumed by
# models.shell3d.build_engine_with_fallback; pinned by
# tests/test_resilience.py with monkeypatched failures.

ENGINE_FALLBACKS = {
    "pallas_packed": "packed",
    "hybrid_bf16": "packed_bf16",
    "hybrid_packed_bf16": "packed_bf16",   # alias of hybrid_bf16
    "packed_bf16": "packed",
    "packed3_bf16": "packed3",
    "packed3": "packed",
    "packed": "scatter",
    "pallas": "mxu",
    "mxu_bf16": "mxu",
    "mxu": "scatter",
}


def normalize_engine_name(name) -> str:
    """Map the ``use_fast_interaction`` vocabulary (True/False/str) to
    a canonical registry name."""
    if name is True:
        return "mxu"
    if name is False or name is None or name == "scatter":
        return "scatter"
    return str(name).lower()


def fallback_chain(name):
    """The degradation order starting AT ``name`` (inclusive), ending
    at "scatter". Raises KeyError for unknown engine names."""
    cur = normalize_engine_name(name)
    chain = [cur]
    while cur != "scatter":
        cur = ENGINE_FALLBACKS[cur]
        chain.append(cur)
    return chain


def record_engine_fallback(failed: str, to: str) -> None:
    """Publish one ENGINE_FALLBACKS degradation onto the telemetry bus
    (labeled by the failed engine and its replacement). Called by
    ``models.shell3d.build_engine_with_fallback`` next to the warning
    it already emits — the warning tells a human once, the counter
    makes the degradation visible in every later ledger snapshot."""
    from ibamr_tpu import obs

    obs.counter("engine_fallbacks_total",
                engine=normalize_engine_name(failed),
                to=normalize_engine_name(to)).inc()
