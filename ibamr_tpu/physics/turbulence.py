"""Turbulence closures: Smagorinsky LES and Wilcox k-omega URANS.

Reference parity: the turbulence half of P22 (SURVEY.md §2.2 "newer
physics" — the reference's two-equation URANS integrator and wall-model
stack). Two closures:

- :func:`eddy_viscosity_smagorinsky` — the algebraic LES model
  ``nu_t = (Cs Delta)^2 |S|``: one fused elementwise pass over the
  strain-rate magnitude the stencil library already provides. Composes
  with any variable-viscosity integrator (``mu_eff = mu + rho nu_t``).
- :class:`KOmegaModel` — Wilcox (1988) two-equation k-omega transport,
  built ON the existing semi-implicit machinery: advection by the
  resolved velocity (upwind), variable-diffusivity diffusion
  (``nu + sigma nu_t``, explicit), production from the resolved strain
  rate, and POINTWISE-IMPLICIT dissipation (``-beta* k omega`` /
  ``-beta omega^2``), which is what makes the stiff near-wall
  sink terms unconditionally stable without a coupled solve — the
  TPU-first replacement for the reference's PETSc-implicit source
  handling.

Both keep every field cell-centered and fused-elementwise; nothing here
introduces a new solver seam.

Oracles (tests/test_turbulence.py): rigid rotation produces zero eddy
viscosity; nu_t scales as Delta^2; homogeneous decay of (k, omega)
matches the closed-form ODE solution; an under-resolved high-Re
Taylor-Green run is energy-decaying and bounded WITH the LES term.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ibamr_tpu.grid import StaggeredGrid
from ibamr_tpu.ops import stencils

Vel = Tuple[jnp.ndarray, ...]


# ---------------------------------------------------------------------------
# Smagorinsky LES
# ---------------------------------------------------------------------------

def eddy_viscosity_smagorinsky(u: Vel, dx: Sequence[float],
                               cs: float = 0.17) -> jnp.ndarray:
    """Cell-centered LES eddy viscosity ``nu_t = (Cs Delta)^2 |S|``
    with ``Delta = (prod dx)^(1/dim)`` and ``|S| = sqrt(2 E:E)``."""
    dim = len(u)
    delta = math.prod(float(h) for h in dx) ** (1.0 / dim)
    S = stencils.strain_rate_magnitude_cc(u, dx)
    return (cs * delta) ** 2 * S



def _vc_step_with_extra_viscosity(vc, state, dt: float,
                                  mu_extra: jnp.ndarray):
    """Take one VC step with ``viscosity(phi) + mu_extra``.

    Single point of the (non-reentrant) bound-method override both
    closure drivers use: the patch lives only for the duration of this
    call (trace time under jit), and the try/finally restore keeps the
    shared integrator clean even if the step throws. Do not interleave
    two models over one integrator instance from different threads.
    """
    orig = vc.viscosity
    vc.viscosity = lambda phi: orig(phi) + mu_extra
    try:
        return vc.step(state, dt)
    finally:
        vc.viscosity = orig


class SmagorinskyINS:
    """Single-phase LES: the VC momentum machinery with
    ``mu_eff = mu + rho nu_t(u)`` refreshed from the resolved field
    every step. Constant density keeps the projection exact (FFT)."""

    def __init__(self, grid: StaggeredGrid, mu: float, rho: float = 1.0,
                 cs: float = 0.17, convective_op_type: str = "upwind",
                 dtype=jnp.float32):
        from ibamr_tpu.integrators.ins_vc import INSVCStaggeredIntegrator

        self.grid = grid
        self.mu = float(mu)
        self.rho = float(rho)
        self.cs = float(cs)
        self.dtype = dtype
        self._vc = INSVCStaggeredIntegrator(
            grid, rho0=rho, rho1=rho, mu0=mu, mu1=mu,
            convective_op_type=convective_op_type,
            reinit_interval=0, precond="fft", dtype=dtype)

    def initialize(self, u0: Optional[Vel] = None):
        st = self._vc.initialize(jnp.zeros(self.grid.n,
                                           dtype=self.dtype),
                                 u0_arrays=u0)
        return st

    def step(self, state, dt: float):
        """One LES step: freeze ``mu_eff`` from the current resolved
        field, then take the VC step with that viscosity."""
        mu_t = self.rho * eddy_viscosity_smagorinsky(
            state.u, self.grid.dx, self.cs)
        return _vc_step_with_extra_viscosity(self._vc, state, dt, mu_t)


# ---------------------------------------------------------------------------
# Wilcox k-omega
# ---------------------------------------------------------------------------

class KOmegaState(NamedTuple):
    k: jnp.ndarray        # turbulent kinetic energy (cell-centered)
    omega: jnp.ndarray    # specific dissipation rate


class KOmegaModel:
    """Wilcox (1988) k-omega closure on periodic cell-centered fields.

    ``advance`` takes one dt of both transport equations given the
    resolved MAC velocity:

      dk/dt + u.grad k  = P_k - beta* k omega
                          + div((nu + sigma* nu_t) grad k)
      dw/dt + u.grad w  = alpha (w/k) P_k - beta w^2
                          + div((nu + sigma nu_t) grad w)

    with ``nu_t = k/omega`` and ``P_k = nu_t |S|^2`` (production
    limited to ``c_lim beta* k omega`` — the standard realizability
    clip). Advection is upwind via the existing convective machinery;
    the sink terms are pointwise IMPLICIT:

      k^{n+1} = k* / (1 + dt beta* omega^n)
      w^{n+1} = w* / (1 + dt beta w^n)

    so arbitrarily stiff dissipation never bounds dt.
    """

    alpha: float = 5.0 / 9.0
    beta: float = 3.0 / 40.0
    beta_star: float = 9.0 / 100.0
    sigma: float = 0.5
    sigma_star: float = 0.5

    def __init__(self, grid: StaggeredGrid, nu: float,
                 prod_limit: float = 10.0, k_min: float = 1e-12,
                 omega_min: float = 1e-8):
        self.grid = grid
        self.nu = float(nu)
        self.prod_limit = float(prod_limit)
        self.k_min = float(k_min)
        self.omega_min = float(omega_min)

    def nu_t(self, st: KOmegaState) -> jnp.ndarray:
        return st.k / jnp.maximum(st.omega, self.omega_min)

    def _adv(self, q: jnp.ndarray, u: Vel, dx) -> jnp.ndarray:
        """First-order upwind advection of a cell-centered scalar by
        the MAC velocity (flux form, periodic)."""
        flux_div = jnp.zeros_like(q)
        for d in range(len(u)):
            uf = u[d]
            q_up = jnp.where(uf > 0.0, jnp.roll(q, 1, d), q)
            flux = uf * q_up
            flux_div = flux_div + (jnp.roll(flux, -1, d) - flux) / dx[d]
        return flux_div

    def _diff(self, q: jnp.ndarray, D: jnp.ndarray, dx) -> jnp.ndarray:
        """div(D grad q) with arithmetic face diffusivity, periodic."""
        out = jnp.zeros_like(q)
        for d in range(q.ndim):
            Df = 0.5 * (D + jnp.roll(D, 1, d))
            grad = (q - jnp.roll(q, 1, d)) / dx[d]
            flux = Df * grad
            out = out + (jnp.roll(flux, -1, d) - flux) / dx[d]
        return out

    def advance(self, st: KOmegaState, u: Vel, dt: float) -> KOmegaState:
        dx = self.grid.dx
        k = jnp.maximum(st.k, self.k_min)
        w = jnp.maximum(st.omega, self.omega_min)
        nu_t = k / w
        S2 = stencils.strain_rate_magnitude_cc(u, dx) ** 2
        P_k = jnp.minimum(nu_t * S2,
                          self.prod_limit * self.beta_star * k * w)

        k_star = (k + dt * (P_k - self._adv(k, u, dx)
                            + self._diff(k, self.nu
                                         + self.sigma_star * nu_t, dx)))
        w_star = (w + dt * (self.alpha * (w / k) * P_k
                            - self._adv(w, u, dx)
                            + self._diff(w, self.nu
                                         + self.sigma * nu_t, dx)))
        # pointwise-implicit sinks (unconditionally stable)
        k_new = k_star / (1.0 + dt * self.beta_star * w)
        w_new = w_star / (1.0 + dt * self.beta * w)
        return KOmegaState(k=jnp.maximum(k_new, self.k_min),
                           omega=jnp.maximum(w_new, self.omega_min))


class KOmegaINS:
    """URANS driver: resolved INS (VC machinery, constant density) with
    ``mu_eff = mu + rho nu_t`` from a co-advanced k-omega pair — the
    analog of the reference's two-equation turbulence hierarchy
    integrator, as one jittable composite step."""

    def __init__(self, grid: StaggeredGrid, mu: float, rho: float = 1.0,
                 convective_op_type: str = "upwind",
                 dtype=jnp.float32):
        from ibamr_tpu.integrators.ins_vc import INSVCStaggeredIntegrator

        self.grid = grid
        self.mu = float(mu)
        self.rho = float(rho)
        self.dtype = dtype
        self.model = KOmegaModel(grid, nu=mu / rho)
        self._vc = INSVCStaggeredIntegrator(
            grid, rho0=rho, rho1=rho, mu0=mu, mu1=mu,
            convective_op_type=convective_op_type,
            reinit_interval=0, precond="fft", dtype=dtype)

    def initialize(self, u0: Optional[Vel] = None,
                   k0: float = 1e-4, omega0: float = 1.0):
        ins = self._vc.initialize(jnp.zeros(self.grid.n,
                                            dtype=self.dtype),
                                  u0_arrays=u0)
        turb = KOmegaState(
            k=jnp.full(self.grid.n, k0, dtype=self.dtype),
            omega=jnp.full(self.grid.n, omega0, dtype=self.dtype))
        return ins, turb

    def step(self, ins_state, turb: KOmegaState, dt: float):
        mu_t = self.rho * self.model.nu_t(turb)
        ins_new = _vc_step_with_extra_viscosity(self._vc, ins_state,
                                                dt, mu_t)
        turb_new = self.model.advance(turb, ins_new.u, dt)
        return ins_new, turb_new
